#!/usr/bin/env python
"""Validate a training run's ``metrics.jsonl`` against the documented schema.

The jsonl stream (utils/metrics.py + telemetry/) is the machine-readable
contract BENCH tooling and tests consume; this validator keeps it honest:

- every line is a flat JSON object of finite numbers (no strings, nulls,
  NaN/Inf — and no booleans: flags must never leak into the scalar stream);
- training records (identified by ``fps``) carry the required core fields
  plus the telemetry fields the runner flushes every log interval;
- counters/rates/timers are non-negative;
- every field name is known — either an exact name or one of the documented
  prefix/suffix families — so schema drift fails loudly instead of silently
  growing unconsumed keys;
- the supervisor lineage riders (``run_id``: hex string, ``incarnation``:
  non-negative int; stamped onto every record by utils/metrics.py when the
  process runs under scripts/train_supervisor.py) are validated up front and
  excepted from the numbers-only rule on any record shape.

Usage:
    python scripts/check_metrics_schema.py [--strict] <metrics.jsonl | run_dir>

A directory argument validates every ``metrics.jsonl`` under it plus any
rotated ``metrics.jsonl.1`` siblings (utils/metrics.py ``--metrics_max_mb``),
any ``trace.jsonl``/``trace.jsonl.1`` span streams (telemetry/tracing.py),
the rollup plane's ``timeseries.jsonl`` (telemetry/timeseries.py; typed
``{"ts": ...}`` window/hist records) and the correlator's ``incidents.jsonl``
(telemetry/incidents.py; typed ``{"incident": ...}`` lifecycle records) —
typed records are identified by their marker field and validated against
their own closed schema, so the streams may even share a file.

``--strict`` additionally enforces the per-family suffix vocabularies: by
default a key under a known prefix (``serving_``, ``fleet_``, ...) passes with
ANY suffix, which catches a brand-new family but not a typo inside one
(``serving_deadlnie_misses``).  Strict mode matches each family against the
documented vocabulary regex and returns nonzero on anything else — bench legs
run post-run validation in this mode.

Exit 0 when valid; exit 1 with one line per violation otherwise.  Importable:
``validate_record`` / ``validate_file`` are used by tests/test_telemetry.py.
"""

from __future__ import annotations

import json
import math
import re
import sys
from pathlib import Path
from typing import List

# exact field names (README.md "Observability" documents units)
KNOWN_FIELDS = {
    # core training record (base_runner.train_loop)
    "episode", "total_steps", "fps", "average_step_rewards",
    "value_loss", "policy_loss", "dist_entropy", "grad_norm", "param_norm",
    "update_ratio", "ratio",
    "aver_episode_rewards", "aver_episode_delays", "aver_episode_payments",
    # telemetry counters / rates (telemetry/registry.py flush)
    "env_steps", "agent_steps", "env_steps_per_sec", "agent_steps_per_sec",
    "compile_count", "compile_seconds_total", "steady_state_recompiles",
    "nonfinite_grad_steps", "deferred_fetch_errors",
    # anomaly tripwires + flight recorder (telemetry/anomaly.py,
    # telemetry/flight_recorder.py)
    "anomalies_total", "flight_snapshots", "flight_bundles",
    # fused multi-episode dispatch (--iters_per_dispatch K > 1,
    # base_runner._train_loop_fused): core metric fields become means over
    # the stacked (K,) per-iteration values; these ride along
    "iters_per_dispatch", "dispatch_count", "dispatches_per_sec",
    # 1.0 when --iters_per_dispatch > 1 was requested but the fused path
    # silently fell back to the classic loop (host-driven collector or a
    # trainer without train_iteration), 0.0 when the fused path actually ran
    "dispatch_fused_fallback",
    # gauges (telemetry/system.py)
    "device_bytes_in_use", "device_peak_bytes", "host_rss_bytes",
    # one-shot
    "flops_per_step",
    # cost_analysis bytes-accessed per jitted call (base_runner._mark_steady;
    # update/collect in the unfused loop, dispatch when --iters_per_dispatch
    # fuses them)
    "bytes_per_update", "bytes_per_collect", "bytes_per_dispatch",
    # profiling record (base_runner profiling branch)
    "profile_collect_sec", "profile_train_sec", "profile_dispatch_sec",
    # SMAC win rate (smac_runner._extra_metrics)
    "incre_win_rate",
    # speculative decode health (models/decode.py spec_decode, gauged from
    # both training collect — base_runner — and serving — engine.decode):
    # mean block passes per decode call, passes that verified outstanding
    # drafts, and the draft acceptance rate (bounded to [0, 1] below)
    "decode_spec_draft_passes", "decode_spec_verify_passes",
    "decode_spec_accept_rate",
}

# open families: per-objective channels, eval protocol fields, per-function
# compile counters, sampled step timers (with registry _max/_sum suffixes)
KNOWN_PREFIXES = (
    "average_step_objective_",
    "eval_",
    "compile_count_",
    "step_time_",
    "anomalies_",           # per-kind trip counters (anomalies_<kind>)
    # serving records (serving/loadgen.py run_load + the batcher/engine
    # telemetry that rides along): QPS, latency percentiles, shed/deadline
    # rates, queue depth, per-bucket occupancy (serving_bucket_<B>), batch
    # fill, engine timings — all serving_<field>
    "serving_",
    # replicated-fleet records (serving/fleet.py fleet_record): replica
    # counts/health, router retries/sheds, per-replica labeled gauges
    # (fleet_replica_<rid>_<signal>)
    "fleet_",
    # weight-push rollout records (serving/rollout_ctl.py): push/rollback
    # counters, canary comparison/mismatch totals
    "rollout_",
    # sharded-run gauges (base_runner._mark_steady under a --data_shards/
    # --seq_shards/--fsdp_shards/--tp_shards mesh): mesh shape (shard_count/
    # shard_data/shard_seq/shard_fsdp/shard_tp), per-shard cost_analysis
    # bytes (shard_bytes_per_<fn> — per-DEVICE, the SPMD executable's
    # numbers), per-replica HBM high-water (shard_hbm_high_water_bytes,
    # absent on CPU), the compiled psum count (shard_psum_count), and the
    # shard_param_ parameter-sharding sub-family (bytes per axis, max
    # per-device param/opt footprint, per-kind collective census)
    "shard_",
    # preemption-safety gauges (training/resilience.py + base_runner):
    # snapshot/retry/failure/emergency-save/quarantine counters,
    # deadline-overrun count, graceful-stop latency (resilience_stop_latency_s)
    "resilience_",
    # multi-scenario eval matrix (training/multi_scenario.py +
    # SMACScenarioRunner): per-scenario gauges scenario_<name>_<signal>
    # (reward/delay/payment, or win_rate/dead_ratio/episodes for SMAC) plus
    # family aggregates (scenario_count/_reward_min/_reward_max/_spread/
    # _specialist_count/_generalist_gap).  NOT in the blanket non-negative
    # set: DCML per-scenario rewards are negative costs.
    "scenario_",
    # SLO burn-rate gauges (telemetry/slo.py SLOMonitor.gauges): per-objective
    # multi-window error-budget burn rates (slo_<obj>_burn/_burn_fast/
    # _burn_slow for latency/error/goodput) plus the window request count
    "slo_",
    # cached-decode gauges (serving/engine.py, decode_mode="cached"): packed
    # KV footprint per bucket (decode_cache_bytes_b<B> — a static function of
    # bucket × model shape × serve dtype, published at warmup), scan length
    # (decode_cache_steps = n_agent), and the fraction of attended positions
    # served from the cache (decode_cache_hit_fraction = (A-1)/(A+1))
    "decode_cache_",
    # async actor-learner overlap (--async_actors, base_runner.
    # _train_loop_async + training/async_loop.py): queue health (depth,
    # wait-time histogram, the drop counter pinned at 0), actor/learner
    # program counters, the submesh split, the fallback gauge, and the
    # actor program's private telemetry merged under async_actor_<field>
    "async_",
    # param-version staleness of consumed trajectory blocks (1-step-lagged
    # PPO): per-block lag histogram (staleness_learner_steps_*) and the
    # learner's current published version (staleness_param_version)
    "staleness_",
    # multi-producer trajectory store (training/async_loop.TrajectoryStore,
    # --async_actor_workers N): ring occupancy/high-water, outstanding
    # admission tickets, put/get/drop counters, the worker count, and the
    # admission bound itself (store_staleness_budget — the invariant checker
    # reads it so staleness records self-describe their contract)
    "store_",
    # off-policy V-trace correction (training/off_policy.py): application
    # counter, per-block param lag, and truncated-IS ratio summaries
    # (offpolicy_rho_mean/_rho_max and the rho-bar/c-bar clip fractions)
    "offpolicy_",
    # chaos fault injection (mat_dcml_tpu/chaos/): armed/fired/injected event
    # counters, the expected-anomaly suppression counter, and the armed flag
    # gauge — plus the typed {"chaos": ...} event records validated separately
    "chaos_",
    # federated scrape health (telemetry/remote.py RemoteScraper +
    # scripts/obs_collector.py): live/stale source counts, scrape errors,
    # seq-guarded restart detections, poll counter
    "scrape_",
    # observability-plane self-metering: /telemetry.json serve counter
    # (TelemetrySidecar / PolicyServer) and the collector's own counters
    "obs_",
    # tuned-config application (mat_dcml_tpu/tuning/ + scripts/autotune.py):
    # applied/overridden knob counts, the fingerprint-mismatch flag, search
    # accounting, per-knob measured ratios, and the verify-gate re-measure
    "tune_",
    # rollup-store accounting gauges (telemetry/timeseries.py RollupStore.
    # gauges): tracked series, overflow drops, open/closed/expired window
    # counts, tier compactions — the typed {"ts": ...} window records are
    # validated separately
    "ts_",
    # incident-correlator summary gauges (telemetry/incidents.py
    # IncidentCorrelator.summary): totals by lifecycle state, attribution
    # split, criticals, flap suppressions — the typed {"incident": ...}
    # lifecycle records are validated separately
    "incident_",
    # cross-host serving federation (serving/router.py ServiceRouter): the
    # router tier's request/failover/brownout outcome counters, probe + host
    # health accounting, generation-consistent push/rollback totals, the
    # generation-split flag gauge, and the upstream-latency sketch
    "router_",
    # per-host gauges of the same federation record (host_<hid>_state/...)
    # — host_rss_bytes predates the family and is carved out in the strict
    # vocabulary below
    "host_",
)

# registry suffixes a histogram sketch appends on flush (registry.py
# HistogramSketch.snapshot); observations append _max/_sum
_HIST_SUFFIXES = ("_p50", "_p95", "_p99", "_count", "_mean")

# --strict: per-family suffix vocabularies.  A key under one of these
# prefixes must match the family's regex; families without an entry
# (eval_, step_time_, ... — genuinely open) stay prefix-only.
STRICT_FAMILY_PATTERNS = {
    "serving_": re.compile(
        r"^serving_(qps|offered_qps|ok|wall_s|slo_ms|goodput_slo|goodput_qps"
        r"|p50_ms|p95_ms|p99_ms|shed_rate|deadline_miss_rate|error_rate"
        r"|buckets|weight_swaps|shed|requests|queue_depth|deadline_misses"
        r"|degraded_ok|degraded_batches|degraded_failed|engine_failures"
        r"|batches|bucket_\d+|batch_fill|engine_ms|latency_ms|queue_wait_ms"
        r"|decode_ms|dtype_bits"
        # HTTP client-side (serving/server.py HttpPolicyClient): client wall
        # minus the server-reported server_ms, and transport/HTTP failures
        r"|client_overhead_ms|client_errors"
        # multi-target loadgen (serving/loadgen.py MultiTargetClient): the
        # same client-side pair re-emitted per endpoint next to the merged
        # sketch, so federated runs attribute overhead per host/router URL
        r"|target_\d+_client_(overhead_ms|errors)"
        r")(_max|_sum|_p50|_p95|_p99|_count|_mean)?$"),
    "decode_cache_": re.compile(
        r"^decode_cache_(bytes_b\d+|steps|hit_fraction)$"),
    "fleet_": re.compile(
        r"^fleet_(replicas|healthy|requests|retries|retries_exhausted"
        r"|attempt_timeouts|shed|no_healthy|unhealthy_marks|readmissions"
        r"|probe_failures|generation|stress|brownout"
        r"|replica_\d+_(state|outstanding|generation|recompiles|served"
        r"|degraded_ok|degraded_failed))$"),
    "rollout_": re.compile(
        r"^rollout_(pushes|rollbacks|slo_gated|canary_comparisons"
        r"|canary_mismatches"
        r"|(canary|incumbent)_ms(_p50|_p95|_p99|_count|_mean))$"),
    "shard_": re.compile(
        r"^shard_(count|data|seq|fsdp|tp|psum_count|hbm_high_water_bytes"
        r"|bytes_per_[a-z_]+"
        # shard_param_: the fsdp/tp parameter-sharding family
        # (parallel/sharding.py): global param bytes split by sharding axis,
        # max per-device param(+opt) footprint, per-kind collective census
        r"|param_bytes_(total|fsdp|tp|replicated)"
        r"|param_(max_device_bytes|opt_max_device_bytes)"
        r"|param_collectives_(all_reduce|all_gather|reduce_scatter"
        r"|collective_permute|all_to_all))$"),
    "resilience_": re.compile(
        r"^resilience_(snapshots|emergency_saves|quarantined_steps"
        r"|deadline_overruns|dispatch_failures|dispatch_retries"
        r"|stop_latency_s|checkpoint_io_retries|checkpoint_io_failures"
        r"|supervisor_exit_76|supervisor_launches|supervisor_last_exit)$"),
    "slo_": re.compile(
        r"^slo_((latency|error|goodput)_burn(_fast|_slow)?"
        r"|window_requests)$"),
    # async_actor_<field> mirrors the actor program's whole merged telemetry
    # registry (compile counters, step timers, ...) and is deliberately an
    # open sub-namespace
    "async_": re.compile(
        r"^async_(fallback|queue_depth|queue_drops|queue_max_depth"
        r"|learner_steps|learner_devices"
        r"|queue_wait_ms(_p50|_p95|_p99|_count|_mean)"
        r"|actor_[a-z0-9_]+)$"),
    "staleness_": re.compile(
        r"^staleness_(param_version"
        r"|learner_steps(_p50|_p95|_p99|_count|_mean))$"),
    "store_": re.compile(
        r"^store_(depth|max_depth|tickets|puts|gets|drops|admits"
        r"|workers|staleness_budget)$"),
    "offpolicy_": re.compile(
        r"^offpolicy_(applied|lag|rho_mean|rho_max"
        r"|rho_clip_fraction|c_clip_fraction)$"),
    "chaos_": re.compile(
        r"^chaos_(events_armed|events_fired|injected_faults"
        r"|suppressed_anomalies|active)$"),
    "scrape_": re.compile(
        r"^scrape_(sources|stale|errors|restarts|polls"
        # collector self-observability (scripts/obs_collector.py --obs_port):
        # per-poll scrape-duration histogram, per-source staleness gauges
        # (scrape_staleness_s_<label>), per-source restart counts
        r"|duration_ms(_max|_sum|_p50|_p95|_p99|_count|_mean)?"
        r"|staleness_s_max|staleness_s_[A-Za-z0-9_.-]+"
        r"|restarts_[A-Za-z0-9_.-]+)$"),
    "ts_": re.compile(
        r"^ts_(series|series_dropped|windows_open|windows_closed"
        r"|windows_expired|compactions)$"),
    "incident_": re.compile(
        r"^incident_(total|open|mitigated|resolved|attributed|unexplained"
        r"|critical|flaps_suppressed)$"),
    "obs_": re.compile(
        r"^obs_(snapshot_requests|collector_polls"
        r"|collector_merged_records)$"),
    "router_": re.compile(
        r"^router_(hosts|healthy|requests|retries|retries_exhausted"
        r"|failovers|shed|no_healthy|brownout|unhealthy_marks|readmissions"
        r"|probes|probe_failures|pushes|rollbacks|push_failures|slo_gated"
        r"|generation|generation_split"
        r"|upstream_ms(_p50|_p95|_p99|_count|_mean))$"),
    # host_rss_bytes is the long-standing process gauge; everything else
    # under host_ is the federation record's per-host state
    "host_": re.compile(
        r"^host_(rss_bytes"
        r"|\d+_(state|outstanding|generation|requests|failures))$"),
    "tune_": re.compile(
        r"^tune_(applied|overridden|mismatch|search_wall_s|probes"
        r"|probes_pruned|verify_ratio|ratio_[a-z0-9_]+)$"),
}

# fields that must never go negative (counters, rates, timers, gauges)
NON_NEGATIVE = (
    "env_steps", "agent_steps", "env_steps_per_sec", "agent_steps_per_sec",
    "compile_count", "compile_seconds_total", "steady_state_recompiles",
    "nonfinite_grad_steps", "deferred_fetch_errors",
    "anomalies_total", "flight_snapshots", "flight_bundles",
    "device_bytes_in_use", "device_peak_bytes",
    "host_rss_bytes", "flops_per_step", "fps",
    "bytes_per_update", "bytes_per_collect", "bytes_per_dispatch",
    "iters_per_dispatch", "dispatch_count", "dispatches_per_sec",
    "profile_dispatch_sec",
    "decode_spec_draft_passes", "decode_spec_verify_passes",
    "decode_spec_accept_rate",
    "dispatch_fused_fallback",
    # scenario-family aggregates (per-scenario rewards may be negative and
    # are deliberately NOT constrained)
    "scenario_count", "scenario_spread", "scenario_specialist_count",
)

# rates that must stay within [0, 1] (acceptance is accepted/offered; the
# cache hit fraction is cached/attended positions)
UNIT_INTERVAL = ("decode_spec_accept_rate", "dispatch_fused_fallback",
                 "decode_cache_hit_fraction",
                 "offpolicy_rho_clip_fraction", "offpolicy_c_clip_fraction")

# a serving record (identified by serving_qps) must carry the benchmark
# contract BENCHLOG consumes: throughput, latency percentiles, shed rate
REQUIRED_SERVING = (
    "serving_qps", "serving_ok", "serving_wall_s",
    "serving_p50_ms", "serving_p95_ms", "serving_p99_ms",
    "serving_shed_rate", "serving_deadline_miss_rate", "serving_error_rate",
)

# a router record (identified by router_hosts) must carry the federation
# contract: service size/health, request + failover outcomes, honest
# brownout accounting, and the generation gauges that expose a split-brain
# service (two hosts steady-state serving different weight generations)
REQUIRED_ROUTER = (
    "router_hosts", "router_healthy", "router_requests", "router_failovers",
    "router_brownout", "router_generation", "router_generation_split",
)

# a fleet record (identified by fleet_replicas) must carry the replication
# contract: health/size, router outcome counters, and the rollout totals a
# dashboard needs to tell "healthy fleet" from "fleet quietly rolling back"
REQUIRED_FLEET = (
    "fleet_replicas", "fleet_healthy", "fleet_requests", "fleet_retries",
    "fleet_unhealthy_marks", "fleet_readmissions", "fleet_generation",
    "rollout_pushes", "rollout_rollbacks",
)

# a DCML multi-scenario eval-matrix record (identified by scenario_spread —
# the SMAC win-rate matrix emits scenario_count alone) must carry the full
# family-aggregate contract so the generalist checkpoint is comparable
REQUIRED_SCENARIO = (
    "scenario_count", "scenario_reward_min", "scenario_reward_max",
    "scenario_spread", "scenario_specialist_count", "scenario_generalist_gap",
)

# a training record (vs eval/profile records, which are sparse) must have:
REQUIRED_CORE = (
    "episode", "total_steps", "fps", "average_step_rewards",
    "value_loss", "policy_loss", "dist_entropy", "grad_norm", "ratio",
)
REQUIRED_TELEMETRY = (
    "env_steps_per_sec", "step_time_collect", "step_time_train",
    "compile_count", "compile_seconds_total", "device_bytes_in_use",
    "host_rss_bytes",
)
# under --iters_per_dispatch K > 1 the per-phase blocking timers do not exist
# (collect+train fuse into one dispatch); the dispatch-level timers replace
# them.  Records advertise the mode via the iters_per_dispatch gauge.
REQUIRED_TELEMETRY_FUSED = (
    "env_steps_per_sec", "step_time_dispatch", "step_time_host_block",
    "compile_count", "compile_seconds_total", "device_bytes_in_use",
    "host_rss_bytes", "dispatch_count",
)


def _known(name: str) -> bool:
    if name in KNOWN_FIELDS:
        return True
    # prefix families match the FULL name first: scenario_count / shard_count
    # are family members whose tail happens to collide with a hist suffix
    if any(name.startswith(p) for p in KNOWN_PREFIXES):
        return True
    base = name
    for suffix in ("_max", "_sum") + _HIST_SUFFIXES:
        if base.endswith(suffix):
            base = base[: -len(suffix)]
            break
    return base in KNOWN_FIELDS


def _strict_ok(name: str) -> bool:
    """--strict: a key under a vocabulary-bearing family must match the
    family's documented pattern (typos inside a known family fail here)."""
    for prefix, pattern in STRICT_FAMILY_PATTERNS.items():
        if name.startswith(prefix):
            return pattern.match(name) is not None
    return True


# anomaly records (telemetry/anomaly.py Anomaly.to_record) are the one
# sanctioned exception to the numbers-only rule: kind/signal are strings,
# nonfinite values encode as "nan"/"inf"/"-inf" strings (strict JSON has no
# NaN literal), and baseline is null before warmup.  trace_exemplar pins the
# live trace id at trip time (optional: only when a tracer was sampling).
ANOMALY_FIELDS = ("anomaly", "signal", "value", "baseline", "episode",
                  "total_steps", "trace_exemplar")
_ANOMALY_REQUIRED = ("anomaly", "signal", "value", "baseline", "episode",
                     "total_steps")
_NONFINITE_STRINGS = ("nan", "inf", "-inf")
# a trace id as minted by telemetry/tracing.py (16-hex) or carried over W3C
# traceparent (32-hex)
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")


def _validate_anomaly(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in _ANOMALY_REQUIRED:
        if k not in record:
            errs.append(f"{where}: anomaly record missing {k!r}")
    te = record.get("trace_exemplar")
    if te is not None and (
            not isinstance(te, str) or not _TRACE_ID_RE.match(te)):
        errs.append(f"{where}: anomaly field 'trace_exemplar' must be a "
                    f"trace id (8-32 hex chars), got {te!r}")
    for k in ("anomaly", "signal"):
        if k in record and not isinstance(record[k], str):
            errs.append(f"{where}: anomaly field {k!r} must be a string")
    for k in ("value", "baseline"):
        v = record.get(k)
        if v is None or isinstance(v, bool):
            if isinstance(v, bool):
                errs.append(f"{where}: anomaly field {k!r} is a boolean")
            continue  # null baseline = tripped before warmup
        if isinstance(v, str):
            if v not in _NONFINITE_STRINGS:
                errs.append(f"{where}: anomaly field {k!r} string must be one "
                            f"of {_NONFINITE_STRINGS}, got {v!r}")
        elif not isinstance(v, (int, float)):
            errs.append(f"{where}: anomaly field {k!r} is {type(v).__name__}")
        elif not math.isfinite(v):
            errs.append(f"{where}: anomaly field {k!r} must encode nonfinite "
                        f"values as strings, got {v}")
    for k in ("episode", "total_steps"):
        v = record.get(k)
        if v is not None and (isinstance(v, bool) or not isinstance(v, int) or v < 0):
            errs.append(f"{where}: anomaly field {k!r} must be a non-negative "
                        f"integer")
    for k in record:
        if k not in ANOMALY_FIELDS:
            errs.append(f"{where}: unexpected field {k!r} in anomaly record")
    return errs


# span records (telemetry/tracing.py TraceContext): one flat line per span,
# identified by the "trace" id field.  Another sanctioned string-bearing
# record: trace/span/kind/parent are strings, t_ms/dur_ms are the numeric
# payload, and arbitrary attrs (status, replica, bucket, ok, ...) ride along
# as strings, booleans, or finite numbers.
TRACE_REQUIRED = ("trace", "span", "kind", "t_ms", "dur_ms")
_TRACE_SPAN_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _validate_trace(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in TRACE_REQUIRED:
        if k not in record:
            errs.append(f"{where}: trace record missing {k!r}")
    for k in ("trace", "span", "kind"):
        v = record.get(k)
        if v is not None and not isinstance(v, str):
            errs.append(f"{where}: trace field {k!r} must be a string")
    span = record.get("span")
    if isinstance(span, str) and not _TRACE_SPAN_RE.match(span):
        errs.append(f"{where}: trace span name {span!r} is not a "
                    f"lower_snake_case identifier")
    parent = record.get("parent")
    if parent is not None and not isinstance(parent, str):
        errs.append(f"{where}: trace field 'parent' must be a string or null "
                    f"(null = the root span)")
    for k in ("t_ms", "dur_ms"):
        v = record.get(k)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            errs.append(f"{where}: trace field {k!r} is not numeric")
        elif not math.isfinite(v) or v < 0:
            errs.append(f"{where}: trace field {k!r} must be finite and "
                        f"non-negative, got {v}")
    for k, v in record.items():
        if k in TRACE_REQUIRED or k == "parent":
            continue
        if isinstance(v, str) or isinstance(v, bool):
            continue  # span attrs may carry status strings / flags
        if not isinstance(v, (int, float)):
            errs.append(f"{where}: trace attr {k!r} is {type(v).__name__}")
        elif not math.isfinite(v):
            errs.append(f"{where}: trace attr {k!r} is non-finite ({v})")
    return errs


# emergency-checkpoint records (base_runner._graceful_stop_check /
# _emergency_on_failure): like anomaly records, a typed exception to the
# numbers-only rule — the marker field carries the stop reason as a string.
EMERGENCY_FIELDS = ("emergency_checkpoint", "episode", "total_steps",
                    "stop_latency_s")
_EMERGENCY_REQUIRED = ("emergency_checkpoint", "episode", "total_steps")


def _validate_emergency(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in _EMERGENCY_REQUIRED:
        if k not in record:
            errs.append(f"{where}: emergency record missing {k!r}")
    v = record.get("emergency_checkpoint")
    if v is not None and not isinstance(v, str):
        errs.append(f"{where}: emergency field 'emergency_checkpoint' must be "
                    f"a string (the stop reason)")
    for k in ("episode", "total_steps"):
        v = record.get(k)
        if v is not None and (isinstance(v, bool) or not isinstance(v, int) or v < 0):
            errs.append(f"{where}: emergency field {k!r} must be a "
                        f"non-negative integer")
    v = record.get("stop_latency_s")
    if v is not None and (isinstance(v, bool) or not isinstance(v, (int, float))
                         or not math.isfinite(v) or v < 0):
        errs.append(f"{where}: emergency field 'stop_latency_s' must be a "
                    f"non-negative finite number")
    for k in record:
        if k not in EMERGENCY_FIELDS:
            errs.append(f"{where}: unexpected field {k!r} in emergency record")
    return errs


# chaos fault-injection event records (mat_dcml_tpu/chaos/inject.py): the
# "chaos" marker field carries the lifecycle stage (fired / suppressed /
# cleared) as a string; event_id / kind / target / suppressed_kind are
# strings, at_s / t_s / duration_s the numeric payload.
CHAOS_FIELDS = ("chaos", "event_id", "kind", "target", "at_s", "t_s",
                "duration_s", "suppressed_kind")
_CHAOS_REQUIRED = ("chaos", "event_id", "kind")
_CHAOS_STAGES = ("fired", "suppressed", "cleared")


def _validate_chaos(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in _CHAOS_REQUIRED:
        if k not in record:
            errs.append(f"{where}: chaos record missing {k!r}")
    v = record.get("chaos")
    if v is not None and v not in _CHAOS_STAGES:
        errs.append(f"{where}: chaos field 'chaos' must be one of "
                    f"{_CHAOS_STAGES}, got {v!r}")
    for k in ("event_id", "kind", "target", "suppressed_kind"):
        v = record.get(k)
        if v is not None and not isinstance(v, str):
            errs.append(f"{where}: chaos field {k!r} must be a string")
    for k in ("at_s", "t_s", "duration_s"):
        v = record.get(k)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            errs.append(f"{where}: chaos field {k!r} is not numeric")
        elif not math.isfinite(v) or v < 0:
            errs.append(f"{where}: chaos field {k!r} must be finite and "
                        f"non-negative, got {v}")
    for k in record:
        if k not in CHAOS_FIELDS:
            errs.append(f"{where}: unexpected field {k!r} in chaos record")
    return errs


# rollup window records (telemetry/timeseries.py RollupStore._close_raw):
# the "ts" marker carries the record kind — "window" (scalar aggregate:
# count/sum/min/max/last of the increments that landed inside the window) or
# "hist" (the window's exact HistogramSketch delta as a dict).
TS_FIELDS = ("ts", "tier", "width_s", "start_s", "metric",
             "ts_count", "ts_sum", "ts_min", "ts_max", "ts_last", "ts_sketch")
_TS_REQUIRED = ("ts", "tier", "width_s", "start_s", "metric")
_TS_KINDS = ("window", "hist")
_TS_WINDOW_NUMERIC = ("ts_count", "ts_sum", "ts_min", "ts_max", "ts_last")
_SKETCH_FIELDS = ("buckets", "count", "total", "vmin", "vmax")


def _validate_ts(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in _TS_REQUIRED:
        if k not in record:
            errs.append(f"{where}: ts record missing {k!r}")
    kind = record.get("ts")
    if kind is not None and kind not in _TS_KINDS:
        errs.append(f"{where}: ts field 'ts' must be one of {_TS_KINDS}, "
                    f"got {kind!r}")
    tier = record.get("tier")
    if tier is not None and (
            isinstance(tier, bool) or not isinstance(tier, int) or tier < 0):
        errs.append(f"{where}: ts field 'tier' must be a non-negative integer")
    for k in ("width_s", "start_s"):
        v = record.get(k)
        if v is not None and (
                isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v < 0):
            errs.append(f"{where}: ts field {k!r} must be a non-negative "
                        f"finite number")
    metric = record.get("metric")
    if metric is not None and not isinstance(metric, str):
        errs.append(f"{where}: ts field 'metric' must be a string")
    if kind == "window":
        for k in _TS_WINDOW_NUMERIC:
            v = record.get(k)
            if v is None:
                errs.append(f"{where}: ts window record missing {k!r}")
            elif isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or not math.isfinite(v):
                errs.append(f"{where}: ts field {k!r} must be a finite number")
        c = record.get("ts_count")
        if isinstance(c, (int, float)) and not isinstance(c, bool) and c < 0:
            errs.append(f"{where}: ts field 'ts_count' is negative ({c})")
        if "ts_sketch" in record:
            errs.append(f"{where}: ts window record must not carry "
                        f"'ts_sketch'")
    elif kind == "hist":
        sk = record.get("ts_sketch")
        if not isinstance(sk, dict):
            errs.append(f"{where}: ts hist record needs a 'ts_sketch' dict")
        else:
            for k in _SKETCH_FIELDS:
                if k not in sk:
                    errs.append(f"{where}: ts_sketch missing {k!r}")
            b = sk.get("buckets")
            if b is not None and (not isinstance(b, list) or any(
                    isinstance(x, bool) or not isinstance(x, int) or x < 0
                    for x in b)):
                errs.append(f"{where}: ts_sketch 'buckets' must be a list of "
                            f"non-negative integers")
    for k in record:
        if k not in TS_FIELDS:
            errs.append(f"{where}: unexpected field {k!r} in ts record")
    return errs


# incident lifecycle records (telemetry/incidents.py Incident.record): the
# "incident" marker carries the lifecycle stage; attribution is a chaos event
# id causal key; trace_exemplar follows into trace.jsonl's span tree.
INCIDENT_FIELDS = ("incident", "incident_id", "kind", "severity", "t_s",
                   "events", "flaps", "attributed_to", "trace_exemplar",
                   "duration_s")
_INCIDENT_REQUIRED = ("incident", "incident_id", "kind", "severity", "t_s",
                      "events", "flaps")
_INCIDENT_STAGES = ("open", "mitigated", "resolved", "annotated")
_INCIDENT_SEVERITIES = ("warning", "critical")
_INCIDENT_ID_RE = re.compile(r"^inc:[0-9]{3,}$")
# chaos event ids are kind:NNN (chaos/inject.py); soak-delivered synthetic
# faults namespace theirs as soak:kind:NNN
_EVENT_ID_RE = re.compile(r"^[a-z][a-z0-9_]*(:[a-z][a-z0-9_]*)*:[0-9]{3,}$")


def _validate_incident(record, where: str) -> List[str]:
    errs: List[str] = []
    for k in _INCIDENT_REQUIRED:
        if k not in record:
            errs.append(f"{where}: incident record missing {k!r}")
    stage = record.get("incident")
    if stage is not None and stage not in _INCIDENT_STAGES:
        errs.append(f"{where}: incident field 'incident' must be one of "
                    f"{_INCIDENT_STAGES}, got {stage!r}")
    iid = record.get("incident_id")
    if iid is not None and (
            not isinstance(iid, str) or not _INCIDENT_ID_RE.match(iid)):
        errs.append(f"{where}: incident field 'incident_id' must match "
                    f"inc:NNN, got {iid!r}")
    kind = record.get("kind")
    if kind is not None and not isinstance(kind, str):
        errs.append(f"{where}: incident field 'kind' must be a string")
    sev = record.get("severity")
    if sev is not None and sev not in _INCIDENT_SEVERITIES:
        errs.append(f"{where}: incident field 'severity' must be one of "
                    f"{_INCIDENT_SEVERITIES}, got {sev!r}")
    attr = record.get("attributed_to")
    if attr is not None and (
            not isinstance(attr, str) or not _EVENT_ID_RE.match(attr)):
        errs.append(f"{where}: incident field 'attributed_to' must be a "
                    f"chaos event id (kind:NNN), got {attr!r}")
    te = record.get("trace_exemplar")
    if te is not None and (
            not isinstance(te, str) or not _TRACE_ID_RE.match(te)):
        errs.append(f"{where}: incident field 'trace_exemplar' must be a "
                    f"trace id (8-32 hex chars), got {te!r}")
    for k in ("t_s", "duration_s"):
        v = record.get(k)
        if v is not None and (
                isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v < 0):
            errs.append(f"{where}: incident field {k!r} must be a "
                        f"non-negative finite number")
    for k in ("events", "flaps"):
        v = record.get(k)
        if v is not None and (
                isinstance(v, bool) or not isinstance(v, int) or v < 0):
            errs.append(f"{where}: incident field {k!r} must be a "
                        f"non-negative integer")
    for k in record:
        if k not in INCIDENT_FIELDS:
            errs.append(f"{where}: unexpected field {k!r} in incident record")
    return errs


# supervisor lineage riders (utils/metrics.py stamps these onto EVERY record
# written under scripts/train_supervisor.py — training, anomaly, emergency,
# collector records alike): run_id is the stable hex id of the logical run,
# incarnation the 1-based launch count.
_RUN_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")


def _validate_riders(record, where: str) -> List[str]:
    errs: List[str] = []
    rid = record.get("run_id")
    if rid is not None and (
            not isinstance(rid, str) or not _RUN_ID_RE.match(rid)):
        errs.append(f"{where}: rider 'run_id' must be an 8-32 char lowercase "
                    f"hex string, got {rid!r}")
    inc = record.get("incarnation")
    if inc is not None and (
            isinstance(inc, bool) or not isinstance(inc, int) or inc < 0):
        errs.append(f"{where}: rider 'incarnation' must be a non-negative "
                    f"integer, got {inc!r}")
    return errs


def validate_record(record, index: int = 0, strict_names: bool = True,
                    strict: bool = False) -> List[str]:
    """Errors for one parsed jsonl record (empty list = valid)."""
    errs: List[str] = []
    where = f"record {index}"
    if not isinstance(record, dict):
        return [f"{where}: not a JSON object"]
    if "run_id" in record or "incarnation" in record:
        # lineage riders are validated here then stripped, so the typed
        # record schemas and the numbers-only rule below never see them
        errs.extend(_validate_riders(record, where))
        record = {k: v for k, v in record.items()
                  if k not in ("run_id", "incarnation")}
    if "anomaly" in record:
        # typed tripwire record — its own schema, BEFORE the numbers-only rule
        return errs + _validate_anomaly(record, where)
    if "emergency_checkpoint" in record:
        # typed emergency-checkpoint record — ditto
        return errs + _validate_emergency(record, where)
    if "trace" in record:
        # span record (trace.jsonl; may interleave in mixed fixtures) — ditto
        return errs + _validate_trace(record, where)
    if "chaos" in record:
        # chaos fault-injection event record — ditto
        return errs + _validate_chaos(record, where)
    if "ts" in record:
        # rollup window / hist-delta record (timeseries.jsonl) — ditto
        return errs + _validate_ts(record, where)
    if "incident" in record:
        # incident lifecycle record (incidents.jsonl) — ditto
        return errs + _validate_incident(record, where)
    for k, v in record.items():
        if isinstance(v, bool):
            errs.append(f"{where}: field {k!r} is a boolean (flags must not "
                        f"enter the scalar stream)")
            continue
        if not isinstance(v, (int, float)):
            errs.append(f"{where}: field {k!r} is {type(v).__name__}, not numeric")
            continue
        if not math.isfinite(v):
            errs.append(f"{where}: field {k!r} is non-finite ({v})")
            continue
        if (k in NON_NEGATIVE
                or k.startswith(("serving_", "fleet_", "rollout_", "shard_",
                                 "resilience_", "slo_",
                                 "decode_cache_", "async_",
                                 "staleness_", "store_", "offpolicy_",
                                 "chaos_",
                                 "scrape_", "obs_", "tune_",
                                 "ts_", "incident_",
                                 "router_", "host_"))) and v < 0:
            errs.append(f"{where}: field {k!r} is negative ({v})")
        if k in UNIT_INTERVAL and not (0.0 <= v <= 1.0):
            errs.append(f"{where}: field {k!r} must be in [0, 1], got {v}")
        if strict_names and not _known(k):
            errs.append(f"{where}: unknown field {k!r} — document it in "
                        f"README.md and scripts/check_metrics_schema.py")
        elif strict and not _strict_ok(k):
            errs.append(f"{where}: field {k!r} is not in its family's "
                        f"documented vocabulary (--strict)")
    if "scrape_sources" in record:
        # federated merged record (obs_collector): a cross-process union of
        # raw registry states — the per-subsystem flush contracts below are
        # about single-process flush records and do not apply to it
        return errs
    if "serving_qps" in record:  # serving benchmark record
        for k in REQUIRED_SERVING:
            if k not in record:
                errs.append(f"{where}: serving record missing {k!r}")
    if "scenario_spread" in record:  # multi-scenario eval-matrix record
        for k in REQUIRED_SCENARIO:
            if k not in record:
                errs.append(f"{where}: scenario eval record missing {k!r}")
    if "fleet_replicas" in record:  # fleet snapshot record
        for k in REQUIRED_FLEET:
            if k not in record:
                errs.append(f"{where}: fleet record missing {k!r}")
    if "router_hosts" in record:  # federation router record
        for k in REQUIRED_ROUTER:
            if k not in record:
                errs.append(f"{where}: router record missing {k!r}")
    if "fps" in record:  # training record: enforce the full contract
        fused = record.get("iters_per_dispatch", 1) > 1
        for k in REQUIRED_CORE:
            if k not in record:
                errs.append(f"{where}: training record missing {k!r}")
        for k in (REQUIRED_TELEMETRY_FUSED if fused else REQUIRED_TELEMETRY):
            if k not in record:
                errs.append(f"{where}: training record missing telemetry "
                            f"field {k!r}")
    return errs


def validate_file(path, strict_names: bool = True,
                  strict: bool = False) -> List[str]:
    """Errors for a whole metrics.jsonl / trace.jsonl (empty list = valid)."""
    errs: List[str] = []
    n = 0
    with open(path) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            n += 1
            try:
                record = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"record {i}: invalid JSON ({e})")
                continue
            errs.extend(validate_record(record, i, strict_names=strict_names,
                                        strict=strict))
    if n == 0:
        errs.append(f"{path}: no records")
    return errs


def discover(target: Path) -> List[Path]:
    """Every validatable stream under a run directory: metrics.jsonl,
    trace.jsonl, timeseries.jsonl, and incidents.jsonl plus their rotated
    ``.1`` predecessors."""
    hits: List[Path] = []
    for name in ("metrics.jsonl", "trace.jsonl",
                 "timeseries.jsonl", "incidents.jsonl"):
        for p in sorted(target.rglob(name)):
            rotated = p.with_name(p.name + ".1")
            if rotated.exists():
                hits.append(rotated)   # older records first
            hits.append(p)
    return hits


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    strict = "--strict" in argv
    argv = [a for a in argv if a != "--strict"]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    target = Path(argv[0])
    if target.is_dir():
        hits = discover(target)
        if not hits:
            print(f"no metrics.jsonl under {target}", file=sys.stderr)
            return 2
    else:
        hits = [target]
    failed = False
    for path in hits:
        errs = validate_file(path, strict=strict)
        if errs:
            failed = True
            for e in errs:
                print(f"{path}: {e}")
        else:
            n = sum(1 for l in open(path) if l.strip())
            print(f"{path}: OK ({n} records)")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
