#!/usr/bin/env python
"""Serve a policy export behind a replicated fleet.

Boots an ``EngineFleet`` (N decode replicas — one per local device when the
host has several — behind the least-outstanding-requests router), fronts it
with the stdlib HTTP server, and optionally starts a ``WeightPusher``
watching an export root so new training generations roll out through the
canary gate automatically.

Usage:
  python scripts/serve_fleet.py --policy_dir exports/gen1 \
      [--replicas 2] [--port 8420] [--buckets 1,8,32,128] \
      [--watch_root exports] [--poll_interval_s 2.0] \
      [--canary_comparisons 24] [--max_mismatch_frac 0.25] \
      [--run_dir results/fleet --trace_sample 0.01] [--slo_p99_ms 250]

Manual pushes hit the running server:
  curl -X POST localhost:8420/v1/push -d '{"policy_dir": "exports/gen2"}'
  curl -X POST localhost:8420/v1/rollback
  curl localhost:8420/fleet
  curl localhost:8420/metrics        # Prometheus text, fleet-merged

``--run_dir`` + ``--trace_sample`` sample request span trees into
``<run_dir>/trace.jsonl``; ``--slo_p99_ms`` arms the burn-rate monitor whose
``slo_*`` gauges ride the /metrics scrape and gate canary promotion.
"""

import argparse
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

from mat_dcml_tpu.serving.batcher import BatcherConfig  # noqa: E402
from mat_dcml_tpu.serving.engine import EngineConfig  # noqa: E402
from mat_dcml_tpu.serving.fleet import EngineFleet, FleetConfig  # noqa: E402
from mat_dcml_tpu.serving.rollout_ctl import RolloutConfig, WeightPusher  # noqa: E402
from mat_dcml_tpu.serving.server import PolicyServer  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="MAT replicated policy fleet")
    p.add_argument("--policy_dir", required=True,
                   help="export dir from scripts/export_policy.py")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8420)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--decode_mode", default="cached",
                   choices=["cached", "scan", "spec", "stride"])
    p.add_argument("--serve_dtype", default="f32", choices=["f32", "bf16"])
    p.add_argument("--spec_block", type=int, default=8)
    p.add_argument("--tuned_config", default=None,
                   help="tuned_config.json from scripts/autotune.py; fills "
                        "every serving knob not given explicitly above "
                        "(fingerprint mismatch -> warn, serve on defaults)")
    p.add_argument("--max_batch_wait_ms", type=float, default=2.0)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--max_retries", type=int, default=2)
    p.add_argument("--request_timeout_s", type=float, default=0.0,
                   help="per-attempt failover watchdog; 0 disables")
    p.add_argument("--watch_root", default=None,
                   help="export root to poll for new generations")
    p.add_argument("--poll_interval_s", type=float, default=2.0)
    p.add_argument("--canary_comparisons", type=int, default=24)
    p.add_argument("--max_mismatch_frac", type=float, default=0.25)
    p.add_argument("--canary_timeout_s", type=float, default=30.0)
    p.add_argument("--run_dir", default=None,
                   help="where trace.jsonl lands; required for tracing")
    p.add_argument("--trace_sample", type=float, default=0.01,
                   help="fraction of requests traced (0 disables)")
    p.add_argument("--trace_max_mb", type=float, default=64.0)
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="p99 latency SLO in ms; 0 disables the burn monitor")
    args = p.parse_args(argv)

    tracer = None
    if args.run_dir and args.trace_sample > 0:
        from mat_dcml_tpu.telemetry.tracing import Tracer

        tracer = Tracer(args.run_dir, sample=args.trace_sample,
                        max_mb=args.trace_max_mb)
    slo = None
    if args.slo_p99_ms > 0:
        from mat_dcml_tpu.telemetry.slo import SLOConfig, SLOMonitor

        slo = SLOMonitor(SLOConfig(latency_p99_ms=args.slo_p99_ms))
    engine_cfg = EngineConfig(
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        decode_mode=args.decode_mode,
        spec_block=args.spec_block,
        serve_dtype=args.serve_dtype,
    )
    tuned_app = None
    if args.tuned_config:
        from mat_dcml_tpu.tuning import (apply_tuned_engine,
                                         explicit_cli_flags,
                                         last_application)

        # flags the user actually typed beat the artifact, field by field
        engine_cfg = apply_tuned_engine(
            args.tuned_config, engine_cfg,
            explicit=explicit_cli_flags(argv))
        tuned_app = last_application()
    fleet = EngineFleet.from_export(
        args.policy_dir,
        fleet_cfg=FleetConfig(
            n_replicas=args.replicas,
            max_retries=args.max_retries,
            request_timeout_s=args.request_timeout_s or None,
        ),
        engine_cfg=engine_cfg,
        batcher_cfg=BatcherConfig(max_queue=args.max_queue,
                                  max_batch_wait_ms=args.max_batch_wait_ms),
        rollout_cfg=RolloutConfig(
            canary_comparisons=args.canary_comparisons,
            max_mismatch_frac=args.max_mismatch_frac,
            canary_timeout_s=args.canary_timeout_s,
        ),
        tracer=tracer,
        slo_monitor=slo,
    )
    if tuned_app is not None:
        # the tune_ gauge family rides the fleet-merged /metrics scrape,
        # mirroring what the training runner publishes from the same artifact
        for name, value in tuned_app.gauges().items():
            fleet.telemetry.gauge(name, value)
    server = PolicyServer(fleet=fleet, host=args.host, port=args.port)
    server.start()

    pusher = None
    if args.watch_root:
        pusher = WeightPusher(fleet, args.watch_root,
                              poll_interval_s=args.poll_interval_s)
        pusher.start()
        print(f"[fleet] pusher watching {args.watch_root} every "
              f"{args.poll_interval_s}s")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        if pusher is not None:
            pusher.stop()
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
