#!/usr/bin/env python
"""Probe which vector patterns Mosaic's infer-vector-layout accepts, via
chipless AOT compilation against a v5e topology (no TPU needed — the same
TpuAotCompiler path the axon compile helper uses runs locally through
libtpu).  Each probe is a minimal pallas kernel isolating one pattern the
whole-decode kernel (ops/pallas_decode.py) needs; the verdicts drive its
Mosaic-compatibility fixes.

Usage: JAX_PLATFORMS=cpu python scripts/mosaic_probe.py
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")

from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental import topologies  # noqa: E402

TB, L, D = 64, 104, 64


def tpu_compile(f, *specs):
    topo = topologies.get_topology_desc(
        "v5e:1x1x1", platform="tpu", chips_per_host_bounds=[1, 1, 1]
    )
    sh = jax.sharding.SingleDeviceSharding(topo.devices[0])
    args = [jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh) for s in specs]
    jax.jit(f).lower(*args).compile()


def probe(name, f, *specs):
    try:
        tpu_compile(f, *specs)
        print(f"OK    {name}")
        return True
    except Exception as e:
        msg = str(e).split("\n")
        detail = next((l for l in msg if "tpu." in l or "vector" in l), msg[0])
        print(f"FAIL  {name}: {detail.strip()[:110]}")
        return False


def k_store_expand(x_ref, i_ref, o_ref):
    # the current kernel's KV write: (TB, D) -> (TB, 1, D) rank expand
    o_ref[:, pl.ds(i_ref[0], 1), :] = x_ref[:][:, None, :]


def k_store_squeeze(x_ref, i_ref, o_ref):
    # squeezed dynamic store into the middle axis
    o_ref[:, i_ref[0], :] = x_ref[:]


def k_store_leading(x_ref, i_ref, o_ref):
    # cache transposed to (L, TB, D): write via a LEADING unit expand
    o_ref[pl.ds(i_ref[0], 1), :, :] = x_ref[:][None]


def k_store_leading_squeeze(x_ref, i_ref, o_ref):
    o_ref[i_ref[0]] = x_ref[:]


def k_q_expand(q_ref, k_ref, o_ref):
    # scores via (TB, 1, dh) * (TB, L, dh), lane reduce -> (TB, L)
    o_ref[:] = jnp.sum(q_ref[:][:, None, :] * k_ref[:], axis=-1)


def k_q_leading(q_ref, k_ref, o_ref):
    # K laid out (L, TB, dh): scores via (1, TB, dh) * (L, TB, dh) -> (L, TB)
    o_ref[:] = jnp.sum(q_ref[:][None] * k_ref[:], axis=-1)


def k_w_expand(w_ref, v_ref, o_ref):
    # out via (TB, L, 1) * (TB, L, dh), middle reduce -> (TB, dh)
    o_ref[:] = jnp.sum(w_ref[:][:, :, None] * v_ref[:], axis=1)


def k_w_leading(w_ref, v_ref, o_ref):
    # V laid out (L, TB, dh); need w (L, TB) -> (L, TB, 1): trailing expand
    o_ref[:] = jnp.sum(w_ref[:][:, :, None] * v_ref[:], axis=0)


def k_w_bcast(w_ref, v_ref, o_ref):
    # same, via broadcast_in_dim instead of reshape-then-broadcast
    w3 = jax.lax.broadcast_in_dim(w_ref[:], (L, TB, D), (0, 1))
    o_ref[:] = jnp.sum(w3 * v_ref[:], axis=0)


def k_w_bcast_mid(w_ref, v_ref, o_ref):
    # V (TB, L, dh); w (TB, L) broadcast along new trailing lane dim
    w3 = jax.lax.broadcast_in_dim(w_ref[:], (TB, L, D), (0, 1))
    o_ref[:] = jnp.sum(w3 * v_ref[:], axis=1)


def k_sublane_softmax(s_ref, o_ref):
    # softmax over the SUBLANE axis of an (L, TB) score tile
    o_ref[:] = jax.nn.softmax(s_ref[:], axis=0)


def run(name, kernel, ins, out_shape, dtype=jnp.bfloat16):
    f = pl.pallas_call(kernel, out_shape=jax.ShapeDtypeStruct(out_shape, dtype))
    specs = [jax.ShapeDtypeStruct(s, d) for s, d in ins]
    return probe(name, lambda *a: f(*a), *specs)


def main():
    bf = jnp.bfloat16
    i32 = jnp.int32
    f32 = jnp.float32
    oks = [
        run("store (TB,1,D) rank-expand   [current kernel]", k_store_expand,
            [((TB, D), bf), ((1,), i32)], (TB, L, D)),
        run("store squeezed middle index", k_store_squeeze,
            [((TB, D), bf), ((1,), i32)], (TB, L, D)),
        run("store (1,TB,D) leading expand [cache as (L,TB,D)]", k_store_leading,
            [((TB, D), bf), ((1,), i32)], (L, TB, D)),
        run("store squeezed leading index  [cache as (L,TB,D)]", k_store_leading_squeeze,
            [((TB, D), bf), ((1,), i32)], (L, TB, D)),
        run("scores q (TB,1,dh) mid expand [current kernel]", k_q_expand,
            [((TB, D), f32), ((TB, L, D), f32)], (TB, L), f32),
        run("scores q (1,TB,dh) leading    [cache as (L,TB,D)]", k_q_leading,
            [((TB, D), f32), ((L, TB, D), f32)], (L, TB), f32),
        run("out w (TB,L,1) trailing expand [current kernel]", k_w_expand,
            [((TB, L), f32), ((TB, L, D), f32)], (TB, D), f32),
        run("out w (L,TB,1) trailing expand [cache as (L,TB,D)]", k_w_leading,
            [((L, TB), f32), ((L, TB, D), f32)], (TB, D), f32),
        run("out w broadcast_in_dim (L,TB)->(L,TB,D)", k_w_bcast,
            [((L, TB), f32), ((L, TB, D), f32)], (TB, D), f32),
        run("out w broadcast_in_dim (TB,L)->(TB,L,D)", k_w_bcast_mid,
            [((TB, L), f32), ((TB, L, D), f32)], (TB, D), f32),
        run("softmax over sublane axis of (L,TB)", k_sublane_softmax,
            [((L, TB), f32)], (L, TB), f32),
    ]
    # exit code = number of failed probes, so CI and shell callers see FAILs
    # instead of an unconditional 0
    return sum(not ok for ok in oks)


if __name__ == "__main__":
    sys.exit(main())
