#!/bin/bash
# Round-3 combined chip session, run after the mid-sweep tunnel wedge killed
# scripts/tpu_session.sh.  Priority order: the convergence evidence first
# (VERDICT r3 item 3 — the one artifact that needs hours), then the
# fast-env/fixed-kernel measurements (scripts/tpu_session2.sh).
set -x
cd "$(dirname "$0")/.."
mkdir -p artifacts/r3
export BENCH_TPU_PROBE_TIMEOUT=0
export MAT_DCML_TPU_DECODE_IMPL=xla   # measured winner (artifacts/r3/winner.txt)

echo "=== convergence runs (reference recipe, full budget) ==="
timeout 16000 bash scripts/tpu_convergence.sh 1000000 1 \
  > artifacts/r3/convergence.log 2>&1
tail -40 artifacts/r3/convergence.log

bash scripts/tpu_session2.sh

echo "=== session 3 complete ==="
