#!/usr/bin/env python
"""Measured perf-flag search -> tuned-config artifact -> regression gate.

Searches the declared flag space (``mat_dcml_tpu/tuning/space.py``) with
short matched-pair probes — real fused collect+train dispatches and real AOT
decode engines, warmup excluded, zero steady-state recompiles asserted per
probe — and emits a fingerprinted ``tuned_config.json`` that training
(``--tuned_config`` on any ``train_*.py``) and serving
(``scripts/serve_fleet.py --tuned_config``) load at startup.

Usage:
  python scripts/autotune.py [--preset cpu_small] [--out tuned_config.json]
      [--budget_s 600] [--trials 3] [--knobs a,b] [--bytes_cut 2.0]
  python scripts/autotune.py --only dispatch --k_list 1,4,16   # K sweep table
  python scripts/autotune.py --only decode --modes scan,spec,cached
  python scripts/autotune.py verify --tuned tuned_config.json [--margin 0.05]

``verify`` re-measures tuned vs all-defaults on the fingerprinted hardware
(matched-pair median-of-ratios) and exits nonzero unless tuned >= 1.0x
within ``--margin``: 1 = tuned lost, 3 = fingerprint mismatch (wrong
hardware — nothing to verify here).  With ``MAT_DCML_TPU_TUNED_REGEN=1`` a
``cpu_small`` search also refreshes the committed regression fixture
``tests/data/tuned_cpu_small.json`` (the update-bytes-budget pattern).

Progress goes to stderr; tables and the summary/verify json records to
stdout, so the sweep wrappers (``scripts/k_sweep_bench.sh``,
``scripts/decode_sweep.sh``) stay pipeline-friendly.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from mat_dcml_tpu.utils.platform import apply_platform_override  # noqa: E402

apply_platform_override()

EXIT_OK, EXIT_FAIL, EXIT_SKIPPED = 0, 1, 3
FIXTURE_PATH = os.path.join(ROOT, "tests", "data", "tuned_cpu_small.json")
REGEN_ENV = "MAT_DCML_TPU_TUNED_REGEN"

PRESETS = {
    # the full DCML env at tiny E/T with the tiny trunk: same program
    # structure as the recipe, minutes on a CPU dev box
    # decode_requests=128: 32-request probes flip the cached/scan winner
    # between runs on a noisy box; 128 keeps the serve plane inside the
    # verify margin
    "cpu_small": dict(E=8, T=4, n_block=1, n_embd=32, n_head=2,
                      ppo_epoch=2, num_mini_batch=2, iters=2,
                      decode_requests=128),
    # the shipped DCML-AS recipe shapes (chip sessions)
    "recipe": dict(E=256, T=50, n_block=2, n_embd=64, n_head=2,
                   ppo_epoch=15, num_mini_batch=4, iters=2,
                   decode_requests=128),
}


def log(msg: str) -> None:
    print(f"[autotune] {msg}", file=sys.stderr, flush=True)


class ProbeHarness:
    """Real probes for one preset: a fused collect+train dispatch scored in
    env-steps/s (dispatch/update/shards groups) and an AOT decode engine
    scored in decode-requests/s (decode group).  Programs are cached per
    point signature, so matched rounds after the first pay timing only —
    warmup/compile never enters a score, and every probe asserts zero
    steady-state recompiles."""

    def __init__(self, preset: str, overrides=None, log_fn=log):
        import jax

        from mat_dcml_tpu.config import RunConfig
        from mat_dcml_tpu.envs.dcml import DCMLEnv, DCMLEnvConfig
        from mat_dcml_tpu.training.runner import build_mat_policy

        self.jax = jax
        p = dict(PRESETS[preset])
        p.update(overrides or {})
        self.preset_name = preset
        self.p = p
        self.log = log_fn
        self.run = RunConfig(
            n_rollout_threads=p["E"], episode_length=p["T"],
            n_block=p["n_block"], n_embd=p["n_embd"], n_head=p["n_head"],
        )
        self.env = DCMLEnv(DCMLEnvConfig(),
                           data_dir=os.path.join(ROOT, "data"))
        self.policy = build_mat_policy(self.run, self.env)
        self.params = self.policy.init_params(jax.random.key(0))
        self._train_cache = {}
        self._serve_cache = {}
        self._bytes_cache = {}
        self.serve_details = {}

    # ------------------------------------------------------------- identity

    def fingerprint(self):
        from mat_dcml_tpu.tuning.space import Fingerprint

        return Fingerprint.current(
            preset=f"{self.run.env_name}:{self.run.scenario}",
            n_block=self.run.n_block, n_embd=self.run.n_embd,
            n_head=self.run.n_head,
        )

    def context(self) -> dict:
        return {
            "devices": list(self.jax.devices()),
            "n_rollout_threads": self.run.n_rollout_threads,
            "n_embd": self.run.n_embd,
            # fsdp/tp probing needs the sharded-runner harness (bench.py
            # BENCH_FSDP); the space prunes those values with that reason
            "param_shard_probe": False,
        }

    # ------------------------------------------------------------ evaluate

    def evaluate(self, point: dict, knob) -> float:
        if knob.group == "decode":
            return self.serve_score(point)
        return self.train_score(point)

    def bytes_of(self, point: dict, knob):
        """Static bytes-accessed prescreen — update-group knobs only (the
        epoch-buffer streaming knobs are exactly the memory-traffic ones)."""
        if knob.group != "update":
            return None
        return self.update_bytes(point)

    def _ppo(self, point: dict):
        from mat_dcml_tpu.training.ppo import PPOConfig

        kw = dict(ppo_epoch=self.p["ppo_epoch"],
                  num_mini_batch=self.p["num_mini_batch"])
        for k in ("update_stream_chunks", "minibatch_layout"):
            if k in point:
                kw[k] = point[k]
        return PPOConfig(**kw)

    def _train_key(self, point: dict) -> tuple:
        return (int(point.get("iters_per_dispatch", 1)),
                int(point.get("update_stream_chunks", 4)),
                str(point.get("minibatch_layout", "gather")))

    def _fresh_params(self):
        # each dispatch donates its train state, whose buffers would
        # otherwise be the shared self.params — every entry gets a copy
        import jax.numpy as jnp

        return self.jax.tree_util.tree_map(jnp.array, self.params)

    def _train_entry(self, point: dict) -> dict:
        jax = self.jax
        key = self._train_key(point)
        entry = self._train_cache.get(key)
        if entry is not None:
            return entry

        from mat_dcml_tpu.telemetry import Telemetry, instrumented_jit
        from mat_dcml_tpu.training.base_runner import make_dispatch_fn
        from mat_dcml_tpu.training.ppo import MATTrainer
        from mat_dcml_tpu.training.rollout import RolloutCollector

        K = key[0]
        trainer = MATTrainer(self.policy, self._ppo(point))
        collector = RolloutCollector(self.env, self.policy,
                                     self.run.episode_length)
        tel = Telemetry()
        dispatch = instrumented_jit(
            make_dispatch_fn(trainer, collector, K),
            f"probe_dispatch_k{K}", tel, lambda *a: None,
            donate_argnums=(0, 1),
        )
        train_state = trainer.init_state(self._fresh_params())
        rollout_state = collector.init_state(
            jax.random.key(1), self.run.n_rollout_threads)
        rng = jax.random.key(2)
        t0 = time.perf_counter()
        for _ in range(2):  # compile + the weak-type recompile
            train_state, rollout_state, rng, _ = dispatch(
                train_state, rollout_state, rng)
            jax.block_until_ready(train_state)
        dispatch.mark_steady()
        self.log(f"probe {key}: warm in {time.perf_counter() - t0:.1f}s")
        entry = {"dispatch": dispatch, "tel": tel,
                 "carry": (train_state, rollout_state, rng)}
        self._train_cache[key] = entry
        return entry

    def train_score(self, point: dict) -> float:
        """env-steps/s over ``iters`` steady fused dispatches (DeferredFetch
        overlap, warmup excluded, zero steady recompiles asserted)."""
        jax = self.jax
        from mat_dcml_tpu.telemetry import DeferredFetch

        entry = self._train_entry(point)
        dispatch = entry["dispatch"]
        train_state, rollout_state, rng = entry["carry"]
        iters = int(self.p["iters"])
        K = self._train_key(point)[0]
        pending = None
        start = time.perf_counter()
        for _ in range(iters):
            train_state, rollout_state, rng, stacked = dispatch(
                train_state, rollout_state, rng)
            fetch = DeferredFetch(stacked)
            if pending is not None:
                pending.get()
            pending = fetch
        pending.get()
        jax.block_until_ready(train_state)
        elapsed = time.perf_counter() - start
        entry["carry"] = (train_state, rollout_state, rng)
        recompiles = entry["tel"].counters.get("steady_state_recompiles", 0.0)
        if recompiles:
            raise AssertionError(
                f"probe {self._train_key(point)} recompiled in steady state "
                f"({recompiles:.0f}x) — the measurement is invalid")
        steps = iters * K * self.run.n_rollout_threads * self.run.episode_length
        return steps / max(elapsed, 1e-9)

    def _serve_key(self, point: dict) -> tuple:
        return (str(point.get("decode_mode", "cached")),
                int(point.get("spec_block", 8)),
                tuple(int(b) for b in point.get("serve_buckets",
                                                (1, 8, 32, 128))),
                str(point.get("serve_dtype", "f32")))

    def serve_score(self, point: dict) -> float:
        """Decode-requests/s through a warmed AOT engine at the point's
        serving knobs (smallest bucket — the latency-critical program)."""
        import numpy as np

        from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig
        from mat_dcml_tpu.tuning.probe import median as _median

        key = self._serve_key(point)
        eng = self._serve_cache.get(key)
        if eng is None:
            t0 = time.perf_counter()
            eng = DecodeEngine(
                self.params, self.policy.cfg,
                EngineConfig(buckets=key[2], decode_mode=key[0],
                             spec_block=key[1], serve_dtype=key[3]),
                log_fn=lambda *a: None,
            )
            eng.warmup()
            self._serve_cache[key] = eng
            self.log(f"probe {key}: engine warm in "
                     f"{time.perf_counter() - t0:.1f}s")
        cfg = self.policy.cfg
        b = eng.min_bucket
        state = np.zeros((b, cfg.n_agent, cfg.state_dim), np.float32)
        obs = np.zeros((b, cfg.n_agent, cfg.obs_dim), np.float32)
        avail = np.ones((b, cfg.n_agent, cfg.action_dim), np.float32)
        n = int(self.p["decode_requests"])
        times = []
        start = time.perf_counter()
        for _ in range(n):
            t0 = time.perf_counter()
            eng.decode(state, obs, avail)
            times.append((time.perf_counter() - t0) * 1e3)
        elapsed = time.perf_counter() - start
        recompiles = eng.steady_state_recompiles()
        if recompiles:
            raise AssertionError(
                f"serve probe {key} recompiled in steady state "
                f"({recompiles:.0f}x) — the measurement is invalid")
        qps = (n * b) / max(elapsed, 1e-9)
        self.serve_details[key] = {
            "qps": qps, "p50_ms": _median(times), "bucket": b,
            "recompiles": recompiles,
        }
        return qps

    def update_bytes(self, point: dict):
        """Static bytes-accessed of the compiled PPO update at this point
        (cost_analysis; shapes via eval_shape — no rollout compile paid)."""
        jax = self.jax
        from mat_dcml_tpu.training.ppo import MATTrainer
        from mat_dcml_tpu.training.rollout import RolloutCollector
        from mat_dcml_tpu.utils.profiling import compiled_bytes

        key = (int(point.get("update_stream_chunks", 4)),
               str(point.get("minibatch_layout", "gather")))
        if key in self._bytes_cache:
            return self._bytes_cache[key]
        trainer = MATTrainer(self.policy, self._ppo(point))
        collector = RolloutCollector(self.env, self.policy,
                                     self.run.episode_length)
        rs = collector.init_state(jax.random.key(1),
                                  self.run.n_rollout_threads)
        rs2_shape, traj_shape = jax.eval_shape(
            collector.collect, self.params, rs)
        state = trainer.init_state(self.params)
        compiled = jax.jit(trainer.train).lower(
            state, traj_shape, rs2_shape, jax.random.key(2)).compile()
        val = compiled_bytes(compiled)
        self._bytes_cache[key] = val
        return val


# ------------------------------------------------------------------ helpers

def _overrides(args) -> dict:
    ov = {}
    for name in ("E", "T", "iters", "ppo_epoch", "mini_batch",
                 "decode_requests"):
        v = getattr(args, name, None)
        if v is not None:
            ov["num_mini_batch" if name == "mini_batch" else name] = v
    return ov


def _replace_knob(space, name, **changes):
    from mat_dcml_tpu.tuning.space import FlagSpace

    try:
        space.knob(name)
    except KeyError:
        return space
    return FlagSpace(tuple(
        dataclasses.replace(k, **changes) if k.name == name else k
        for k in space.knobs))


def build_space(args):
    from mat_dcml_tpu.tuning.space import default_space

    space = default_space()
    if args.knobs:
        space = space.subset(
            [k.strip() for k in args.knobs.split(",") if k.strip()])
    if args.only:
        space = space.group(args.only)
    if args.k_list:
        ks = tuple(int(x) for x in args.k_list.split(","))
        space = _replace_knob(space, "iters_per_dispatch", domain=ks,
                              default=1 if 1 in ks else ks[0])
    if args.modes:
        modes = tuple(m.strip() for m in args.modes.split(","))
        space = _replace_knob(
            space, "decode_mode", domain=modes,
            default="cached" if "cached" in modes else modes[0])
    if args.buckets:
        ladder = tuple(int(b) for b in args.buckets.split(","))
        space = _replace_knob(space, "serve_buckets", domain=(ladder,),
                              default=ladder)
    if args.spec_block_default:
        sb = int(args.spec_block_default)
        knob = None
        try:
            knob = space.knob("spec_block")
        except KeyError:
            pass
        if knob is not None:
            dom = tuple(sorted(set(knob.domain) | {sb}))
            space = _replace_knob(space, "spec_block", domain=dom, default=sb)
    return space


def print_group_table(group: str, result, harness) -> None:
    dev = harness.jax.devices()[0]
    if group == "dispatch":
        prov = result.provenance.get("iters_per_dispatch") or {}
        cands = prov.get("candidates") or {}
        rows = sorted(((int(v), s) for v, s in cands.items()))
        for K, s in rows:
            print(json.dumps({"K": K, "steps_per_sec": round(s, 2)}),
                  flush=True)
        if rows:
            best_k, best_s = max(rows, key=lambda r: r[1])
            record = {
                "metric": "dcml_mat_fused_dispatch_env_steps_per_sec",
                "value": round(best_s, 2), "unit": "env_steps/s",
                "platform": dev.platform, "device": dev.device_kind,
                "provisional": False, "E": harness.run.n_rollout_threads,
                "best_K": best_k,
            }
            for K, s in rows:
                record[f"k{K}_steps_per_sec"] = round(s, 2)
            print(json.dumps(record), flush=True)
        return
    if group == "decode":
        hdr = ("mode", "spec", "buckets", "dtype", "qps", "p50_ms",
               "recompiles")
        print()
        print("decode mode x serving ladder (autotune probes, "
              f"bucket-1 dispatches, {dev.platform})")
        print("  ".join(f"{h:>12}" for h in hdr))
        for key, d in sorted(harness.serve_details.items()):
            mode, spec, buckets, dtype = key
            print("  ".join(f"{v:>12}" for v in (
                mode, spec, ",".join(str(b) for b in buckets), dtype,
                round(d["qps"], 2), round(d["p50_ms"], 2),
                int(d["recompiles"]))))
        print()
        return
    # generic: one json line per probed knob with its candidate scores
    for name, prov in result.provenance.items():
        print(json.dumps({"knob": name, **prov}), flush=True)


# --------------------------------------------------------------------- modes

def do_search(args) -> int:
    from mat_dcml_tpu.tuning.search import staged_search
    from mat_dcml_tpu.tuning.space import TunedConfig

    harness = ProbeHarness(args.preset, _overrides(args))
    space = build_space(args)
    bytes_of = harness.bytes_of if args.bytes_cut > 0 else None
    result = staged_search(
        space, harness.evaluate, budget_s=args.budget_s, trials=args.trials,
        log=log, bytes_of=bytes_of, bytes_cut=args.bytes_cut,
        switch_margin=args.switch_margin, context=harness.context(),
    )
    tc = TunedConfig(
        fingerprint=harness.fingerprint(),
        knobs=dict(result.point),
        provenance=result.provenance,
        search={"wall_s": round(result.wall_s, 3),
                "probes_run": result.probes_run,
                "probes_pruned": result.probes_pruned,
                "budget_s": args.budget_s,
                "truncated": int(result.truncated),
                "preset": args.preset},
    )
    if args.only:
        print_group_table(args.only, result, harness)
    out = args.out
    if out is None:
        # group sweeps print tables; a partial-space artifact would
        # silently shadow a full one, so writing is opt-in there
        out = "" if args.only else "tuned_config.json"
    if out:
        tc.save(out)
        log(f"wrote {out}")
    if os.environ.get(REGEN_ENV) and args.preset == "cpu_small":
        tc.save(FIXTURE_PATH)
        log(f"regenerated {FIXTURE_PATH}")
    dev = harness.jax.devices()[0]
    record = {
        "metric": "dcml_mat_autotune_search",
        "value": round(result.wall_s, 2), "unit": "s",
        "platform": dev.platform, "device": dev.device_kind,
        "provisional": False, "preset": args.preset,
        "probes_run": result.probes_run,
        "probes_pruned": result.probes_pruned,
        "truncated": int(result.truncated),
        "knobs": {k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in result.point.items()},
    }
    print(json.dumps(record), flush=True)
    return EXIT_OK


_SERVE_KNOBS = ("decode_mode", "spec_block", "serve_buckets", "serve_dtype")


def do_verify(args) -> int:
    from mat_dcml_tpu.tuning.probe import ab_trials, median_of_ratios
    from mat_dcml_tpu.tuning.space import (
        TunedConfig, TunedConfigMismatchError, default_space)

    if not args.tuned:
        log("verify needs --tuned PATH")
        return 2
    tc = TunedConfig.load(args.tuned)
    preset = tc.search.get("preset", args.preset)
    if preset not in PRESETS:
        preset = args.preset
    ov = _overrides(args)
    # rebuild exactly the tuned shape — the artifact's fingerprint, not the
    # preset table, is the source of truth for the model
    ov.update(n_block=tc.fingerprint.n_block, n_embd=tc.fingerprint.n_embd,
              n_head=tc.fingerprint.n_head)
    harness = ProbeHarness(preset, ov)
    try:
        tc.check(harness.fingerprint())
    except TunedConfigMismatchError as e:
        log(f"verify SKIPPED (wrong hardware): {e}")
        return EXIT_SKIPPED

    defaults = default_space().defaults()
    tuned = dict(defaults)
    tuned.update(tc.knobs)
    trials = max(args.trials, 1)
    _, tr = ab_trials(
        {"tuned": lambda: harness.train_score(tuned),
         "default": lambda: harness.train_score(defaults)},
        trials)
    ratios = {"train": median_of_ratios(tr, "tuned", "default")}
    if any(tuple(tuned[k]) != tuple(defaults[k])
           if isinstance(defaults[k], tuple) else tuned[k] != defaults[k]
           for k in _SERVE_KNOBS if k in tuned):
        _, sr = ab_trials(
            {"tuned": lambda: harness.serve_score(tuned),
             "default": lambda: harness.serve_score(defaults)},
            trials)
        ratios["serve"] = median_of_ratios(sr, "tuned", "default")

    ok = all(r >= 1.0 - args.margin for r in ratios.values())
    dev = harness.jax.devices()[0]
    record = {
        "metric": "dcml_mat_autotune_verify",
        "value": round(min(ratios.values()), 4), "unit": "x_default",
        "platform": dev.platform, "device": dev.device_kind,
        "provisional": False, "tuned": str(args.tuned),
        "margin": args.margin, "trials": trials,
        "verify_pass": int(ok),
    }
    for name, r in ratios.items():
        record[f"{name}_ratio"] = round(r, 4)
    print(json.dumps(record), flush=True)
    log(f"verify {'PASS' if ok else 'FAIL'}: " + ", ".join(
        f"{n} {r:.4f}x" for n, r in ratios.items())
        + f" (margin {args.margin:g})")
    return EXIT_OK if ok else EXIT_FAIL


def main(argv=None) -> int:
    from mat_dcml_tpu.tuning.space import GROUP_ORDER

    p = argparse.ArgumentParser(
        description="perf-flag autotuner", allow_abbrev=False)
    p.add_argument("mode", nargs="?", default="search",
                   choices=["search", "verify"])
    p.add_argument("--preset", default="cpu_small", choices=sorted(PRESETS))
    p.add_argument("--out", default=None,
                   help="artifact path (default tuned_config.json; "
                        "no artifact for --only sweeps)")
    p.add_argument("--budget_s", type=float, default=600.0)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--only", default=None, choices=list(GROUP_ORDER),
                   help="sweep one knob group and print its table")
    p.add_argument("--knobs", default=None,
                   help="comma list restricting the space to these knobs")
    p.add_argument("--bytes_cut", type=float, default=2.0,
                   help="bytes-accessed prescreen factor (0 disables)")
    p.add_argument("--switch_margin", type=float, default=0.05,
                   help="median ratio a non-default value must clear "
                        "to win its knob")
    p.add_argument("--tuned", default=None, help="verify: artifact path")
    p.add_argument("--margin", type=float, default=0.05,
                   help="verify: allowed noise below 1.0x")
    # preset overrides (the sweep wrappers map their env knobs here)
    p.add_argument("--E", type=int, default=None)
    p.add_argument("--T", type=int, default=None)
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--ppo_epoch", type=int, default=None)
    p.add_argument("--mini_batch", type=int, default=None)
    p.add_argument("--decode_requests", type=int, default=None)
    # domain overrides
    p.add_argument("--k_list", default=None)
    p.add_argument("--modes", default=None)
    p.add_argument("--buckets", default=None)
    p.add_argument("--spec_block_default", type=int, default=None)
    args = p.parse_args(argv)
    if args.mode == "verify":
        return do_verify(args)
    return do_search(args)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
