#!/bin/bash
# Round-5 chip session (VERDICT r4 "Next round" item 1).
#
# SHORT measurement legs only, highest-information first — the w99
# convergence run lives on CPU this round (resumed across rounds via Orbax,
# results/DCML/AS/momat/conv_r4_w99_cpu), so the chip is purely for the
# numbers that have been plans since round 3: post-restructure combined-step
# bench, the fixed decode-kernel A/B, the attention A/B inside the PPO
# update, per-phase MFU breakdown, and the E-ladder.
# One TPU client at a time; the caller (tpu_retry_session5.sh) verified a
# healthy grant.
set -x
cd "$(dirname "$0")/.."
mkdir -p artifacts/r5
export BENCH_TPU_PROBE_TIMEOUT=0
export MAT_DCML_TPU_DECODE_IMPL=xla   # measured r3 winner; leg 3 re-checks

# Hard wall-clock stop (default 17:30 UTC, ~1 h before the round-5 driver
# window): the driver's own bench.py needs the single-client tunnel
# uncontended at round end — a long leg must never still hold it.
STOP_AT="${TPU_SESSION_STOP_AT:-17:30}"
now=$(date -u +%s)
stop=$(date -u -d "today $STOP_AT" +%s) || { echo "bad TPU_SESSION_STOP_AT=$STOP_AT"; exit 1; }
[ "$stop" -le "$now" ] && stop=$(date -u -d "tomorrow $STOP_AT" +%s)
budget() {  # budget <leg-cap-seconds> -> min(cap, seconds-to-stop); 0 = stop
  local cap=$1 rem=$(( stop - $(date -u +%s) ))
  [ "$rem" -lt 60 ] && { echo 0; return; }
  [ "$rem" -lt "$cap" ] && echo "$rem" || echo "$cap"
}
need() { t=$(budget "$1"); [ "$t" -gt 0 ] && return 0
         echo "=== past hard stop $STOP_AT UTC; ending session ==="; exit 0; }

echo "=== 1. combined-step bench at E=256 + op trace (the round-5 number of record) ==="
need 3000
BENCH_N_ENVS=256 BENCH_ITERS=3 BENCH_BREAKDOWN=1 \
  BENCH_PROFILE_DIR=artifacts/r5/trace_e256 timeout "$t" python bench.py \
  > artifacts/r5/bench_e256_xla.json 2> artifacts/r5/bench_e256_xla.log
cat artifacts/r5/bench_e256_xla.json
JAX_PLATFORMS=cpu python scripts/trace_report.py artifacts/r5/trace_e256 40 \
  > artifacts/r5/trace_e256_report.txt 2>&1 || true
tail -50 artifacts/r5/trace_e256_report.txt

echo "=== 2. attention A/B in the PPO update (E=256) — the roofline's top lever ==="
need 3000
MAT_DCML_TPU_ATTN_IMPL=pallas BENCH_N_ENVS=256 BENCH_ITERS=3 BENCH_BREAKDOWN=1 \
  timeout "$t" python bench.py \
  > artifacts/r5/bench_e256_attnpallas.json 2> artifacts/r5/bench_e256_attnpallas.log
cat artifacts/r5/bench_e256_attnpallas.json

echo "=== 3. decode micro-bench: fixed Pallas whole-decode vs XLA scan ==="
need 3000
timeout "$t" python scripts/tpu_decode_bench.py 256 512 \
  > artifacts/r5/decode_bench.json 2> artifacts/r5/decode_bench.log
cat artifacts/r5/decode_bench.json

echo "=== 4. collect decomposition (on-chip effect of the sampler fix) ==="
need 3000
timeout "$t" python scripts/tpu_collect_bench.py 256 \
  > artifacts/r5/collect_bench.json 2> artifacts/r5/collect_bench.log
cat artifacts/r5/collect_bench.json

echo "=== 5. E-ladder with remat+grad-accum ==="
need 5400
BENCH_SWEEP=1 BENCH_SWEEP_ENVS=256,512,1024,2048,4096,8192 BENCH_BREAKDOWN=1 \
  BENCH_ITERS=3 timeout "$t" python bench.py \
  > artifacts/r5/bench_sweep.json 2> artifacts/r5/bench_sweep.log
cat artifacts/r5/bench_sweep.json

echo "=== 6. f32-trunk baseline (isolates the dtype lever; legs 1/2 are bf16 by default) ==="
need 3000
BENCH_DTYPE=float32 BENCH_N_ENVS=256 BENCH_ITERS=3 BENCH_BREAKDOWN=1 \
  timeout "$t" python bench.py \
  > artifacts/r5/bench_e256_f32.json 2> artifacts/r5/bench_e256_f32.log
cat artifacts/r5/bench_e256_f32.json

echo "=== session 5 complete ==="
