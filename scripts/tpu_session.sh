#!/bin/bash
# The round-3 chip session, run unattended on the first healthy tunnel grant
# (tunnel discipline: ONE client at a time; each python process below is a
# fresh claim, fine while the chip is healthy).
#
#   1. decode micro-bench: XLA scan vs whole-decode Pallas kernel
#   2. combined-step A/B at E=256: pick the faster decode impl
#   3. full E-sweep with per-phase MFU breakdown (headline evidence)
#   4. full-budget convergence: momat (both objectives) then scalar mat
#
# All output accumulates under artifacts/r3/.
set -x
cd "$(dirname "$0")/.."
mkdir -p artifacts/r3
export BENCH_TPU_PROBE_TIMEOUT=0     # the caller already probed; don't re-queue

echo "=== 1. decode micro-bench ==="
timeout 3000 python scripts/tpu_decode_bench.py 256 512 \
  > artifacts/r3/decode_bench.json 2> artifacts/r3/decode_bench.log
cat artifacts/r3/decode_bench.json

echo "=== 2. combined-step A/B at E=256 ==="
for impl in xla pallas; do
  MAT_DCML_TPU_DECODE_IMPL=$impl BENCH_N_ENVS=256 BENCH_ITERS=3 \
    timeout 3000 python bench.py \
    > "artifacts/r3/bench_e256_$impl.json" 2> "artifacts/r3/bench_e256_$impl.log"
  cat "artifacts/r3/bench_e256_$impl.json"
done

# pick the winner for the rest of the session
winner=$(python - <<'EOF'
import json
def v(p):
    try:
        return json.load(open(p))["value"]
    except Exception:
        return -1.0
x, p = v("artifacts/r3/bench_e256_xla.json"), v("artifacts/r3/bench_e256_pallas.json")
print("pallas" if p > x else "xla")
EOF
)
echo "winner impl: $winner" | tee artifacts/r3/winner.txt
export MAT_DCML_TPU_DECODE_IMPL=$winner

echo "=== 3. full E-sweep with breakdown ==="
BENCH_SWEEP=1 BENCH_SWEEP_ENVS=256,512,1024,2048 BENCH_BREAKDOWN=1 \
  BENCH_ITERS=3 timeout 5400 python bench.py \
  > artifacts/r3/bench_sweep.json 2> artifacts/r3/bench_sweep.log
cat artifacts/r3/bench_sweep.json

echo "=== 4. convergence runs (reference recipe, full budget) ==="
timeout 14000 bash scripts/tpu_convergence.sh 1000000 1 \
  > artifacts/r3/convergence.log 2>&1
tail -40 artifacts/r3/convergence.log

echo "=== session complete ==="
