#!/bin/sh
# SUPERSEDED: the decode-mode sweep is now the `decode` knob group of the
# perf-flag autotuner — this wrapper is `scripts/autotune.py --only decode`
# and prints one mode-by-ladder comparison table from the same protocol
# (warm AOT engine per mode, alternating best-of-N batch-1 dispatches,
# recompile detector armed).  The old env knobs still work and map onto
# autotune flags; new callers should invoke autotune.py directly (run
# without --only it also emits the tuned_config.json artifact).  The
# bit-exactness three-way A/B stays where it was: BENCH_CACHED_DECODE=1
# python bench.py.
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python scripts/autotune.py \
  --only decode \
  --modes "${DECODE_SWEEP_MODES:-scan,spec,cached}" \
  --buckets "${BENCH_SERVING_BUCKETS:-1,4,16}" \
  --decode_requests "${BENCH_SERVING_REQUESTS:-256}" \
  --spec_block_default "${BENCH_SERVING_SPEC_BLOCK:-8}" \
  --trials "${BENCH_TRIALS:-2}"
