#!/bin/sh
# Sweep the decode modes (scan | spec | cached) through the serving bucket
# ladder and print one comparison table.  Each mode runs bench.py's
# BENCH_SERVING leg — continuous batcher over the AOT bucket ladder plus the
# unbatched single-dispatch baseline, recompile detector armed — so every
# cell of the table is the same protocol with only the decode program
# swapped.  Finishes with the BENCH_CACHED_DECODE three-way A/B (bit-exact
# assert + alternating best-of-5 serving/collect trials) unless
# DECODE_SWEEP_AB=0.
#
# Knobs (all pass through to bench.py):
#   DECODE_SWEEP_MODES         comma list, default scan,spec,cached
#   BENCH_SERVING_BUCKETS      default 1,4,16
#   BENCH_SERVING_REQUESTS     default 256
#   BENCH_SERVING_CONCURRENCY  default 16
#   BENCH_SERVING_SPEC_BLOCK   default 8
#
# On CPU the numbers are protocol checks, not the TPU speedup of record —
# run on a chip session for the real curve.
cd "$(dirname "$0")/.."
set -e

MODES="${DECODE_SWEEP_MODES:-scan,spec,cached}"
BUCKETS="${BENCH_SERVING_BUCKETS:-1,4,16}"
OUT="$(mktemp)"
trap 'rm -f "$OUT"' EXIT

for mode in $(printf '%s' "$MODES" | tr ',' ' '); do
  echo "== decode_sweep: mode=$mode buckets=$BUCKETS ==" >&2
  env \
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BENCH_SERVING=1 \
    BENCH_SERVING_DECODE_MODE="$mode" \
    BENCH_SERVING_BUCKETS="$BUCKETS" \
    python bench.py | tail -1 >> "$OUT"
done

if [ "${DECODE_SWEEP_AB:-1}" = "1" ]; then
  echo "== decode_sweep: three-way A/B (BENCH_CACHED_DECODE) ==" >&2
  env \
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BENCH_CACHED_DECODE=1 \
    python bench.py | tail -1 >> "$OUT"
fi

python - "$OUT" <<'EOF'
import json, sys

rows, ab = [], None
with open(sys.argv[1]) as f:
    for line in f:
        rec = json.loads(line)
        if rec.get("metric") == "dcml_mat_cached_decode_p50":
            ab = rec
        else:
            rows.append(rec)

hdr = ("mode", "buckets", "qps", "single_qps", "p50_ms", "p99_ms",
       "shed", "recompiles")
print()
print("decode mode x serving bucket ladder")
print("  ".join(f"{h:>11}" for h in hdr))
for r in rows:
    print("  ".join(f"{v:>11}" for v in (
        r["decode_mode"], r["buckets"], r["value"], r["single_qps"],
        r["p50_ms"], r["p99_ms"], r["shed_rate"],
        int(r["steady_state_recompiles"]))))

if ab is not None:
    print()
    print(f"three-way A/B (E={ab['E']}, bucket={ab['bucket']}, "
          f"best-of-{ab['trials']}, bit_exact={ab['bit_exact']})")
    cols = ("mode", "serve_p50_ms", "batch1_qps", "collect_steps_s")
    print("  ".join(f"{c:>15}" for c in cols))
    for m in ("scan", "spec", "cached"):
        print("  ".join(f"{v:>15}" for v in (
            m, ab[f"{m}_p50_ms"], ab[f"{m}_batch1_qps"],
            ab[f"{m}_collect_steps_s"])))
    print(f"beats_scan={ab['beats_scan']} beats_spec={ab['beats_spec']} "
          f"collect_ok={ab['collect_ok']} "
          f"recompiles={int(ab['steady_state_recompiles'])}")
EOF
