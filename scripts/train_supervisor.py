#!/usr/bin/env python
"""Relaunch-with-backoff supervisor for preemptible training.

Retires the ad-hoc ``scripts/tpu_retry_session*.sh`` probe loops: instead of
hand-rolled per-session retry shells, wrap ANY training command line once —

    python scripts/train_supervisor.py -- \
        python train_dcml.py --resume auto --iters_per_dispatch 8 ...

Semantics (driven by the training side's exit codes, training/resilience.py):

- exit 0      -> the run finished; the supervisor exits 0.
- exit 75     -> graceful preemption (SIGTERM honored, emergency checkpoint
                 written).  NOT a crash: the crash counter resets and the
                 child relaunches after ``--preempt-delay`` seconds.  With
                 ``--resume auto`` the relaunch restores the emergency carry
                 and continues bit-exact.
- exit 76     -> the dispatch watchdog exhausted its retries (an emergency
                 checkpoint was written on the way out).  Tracked on its OWN
                 budget (``--max-watchdog-relaunches``) and counter
                 (``resilience_supervisor_exit_76``): watchdog exhaustion
                 usually means a sick device/filer that a relaunch onto fresh
                 state often clears, but it must not silently consume the
                 generic crash budget — the two failure modes page
                 differently.
- anything else -> a crash.  Relaunch with jittered exponential backoff
                 (base * 2^(crashes-1), capped at ``--backoff-max``) up to
                 ``--max-relaunches`` consecutive crashes, then give up and
                 exit with the child's last code.  A clean preemption or a
                 normal exit resets both counters.

SIGTERM/SIGINT to the supervisor forward to the child (which takes its
emergency checkpoint) and the supervisor exits with the child's code — so
killing the supervisor IS the graceful-stop path, one level up.

Relaunch lineage: the supervisor mints one stable ``run_id`` (or inherits
``MAT_DCML_RUN_ID`` from an outer orchestrator) and exports it plus a
per-launch ``MAT_DCML_INCARNATION`` into every child, so every metrics
record, telemetry snapshot, and the supervisor's own exit record carry
queryable ``run_id``/``incarnation`` riders — relaunches of one logical run
federate into one stream (utils/metrics.py, telemetry/remote.py).
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import subprocess
import sys
import time
import uuid
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from mat_dcml_tpu.telemetry.remote import (  # noqa: E402
    INCARNATION_ENV,
    RUN_ID_ENV,
)
from mat_dcml_tpu.training.resilience import (  # noqa: E402
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
    backoff_delay,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("--max-relaunches", type=int, default=10,
                        help="consecutive CRASH relaunches before giving up "
                             "(preemptions don't count)")
    parser.add_argument("--backoff-base", type=float, default=5.0,
                        help="crash backoff base, seconds")
    parser.add_argument("--backoff-max", type=float, default=300.0,
                        help="crash backoff ceiling, seconds")
    parser.add_argument("--max-watchdog-relaunches", type=int, default=3,
                        help="consecutive watchdog-exhaustion (exit 76) "
                             "relaunches before giving up — a separate budget "
                             "from generic crashes")
    parser.add_argument("--preempt-delay", type=float, default=1.0,
                        help="relaunch delay after a clean preemption, seconds")
    parser.add_argument("--metrics-file", default=None,
                        help="append supervisor counters as a jsonl record "
                             "here on exit (schema family resilience_)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="training command line (prefix with --)")
    args = parser.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given; usage: train_supervisor.py [opts] -- cmd ...")

    child: subprocess.Popen | None = None
    forwarded = {"sig": None}

    def forward(signum, frame):
        forwarded["sig"] = signum
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)

    crashes = 0
    watchdog_exits = 0
    watchdog_exits_total = 0
    launches = 0
    # one stable id per logical run, inherited if an outer orchestrator
    # already minted one; each launch below bumps the incarnation
    run_id = os.environ.get(RUN_ID_ENV) or uuid.uuid4().hex[:16]

    def _append(record: dict) -> None:
        if args.metrics_file is None:
            return
        import json

        path = Path(args.metrics_file)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def write_relaunch(last_rc: int) -> None:
        # one record per relaunch, BEFORE the next launch: the incident
        # correlator (telemetry/incidents.py) matches it to the open kill
        # incident by run_id and annotates the lineage instead of opening a
        # duplicate — the relaunch is the mitigation, not a new failure
        _append({
            "resilience_supervisor_relaunch": launches,
            "resilience_supervisor_last_exit":
                last_rc if last_rc >= 0 else 128 - last_rc,
            "run_id": run_id,
            "incarnation": launches + 1,
        })

    def write_metrics(last_rc: int) -> None:
        _append({
            "resilience_supervisor_exit_76": watchdog_exits_total,
            "resilience_supervisor_launches": launches,
            # signal deaths (wait() returns -N) encode shell-style as 128+N
            # so the resilience_ family stays non-negative
            "resilience_supervisor_last_exit":
                last_rc if last_rc >= 0 else 128 - last_rc,
            "run_id": run_id,
            "incarnation": launches,
        })

    while True:
        launches += 1
        print(f"[supervisor] launch {launches} run_id={run_id}: "
              f"{' '.join(cmd)}", flush=True)
        child = subprocess.Popen(
            cmd,
            env={**os.environ,
                 RUN_ID_ENV: run_id,
                 INCARNATION_ENV: str(launches)},
        )
        rc = child.wait()
        if forwarded["sig"] is not None:
            # our own stop was forwarded; the child already checkpointed
            print(f"[supervisor] stop forwarded; child exited {rc}", flush=True)
            write_metrics(rc)
            return rc
        if rc == 0:
            print("[supervisor] run complete", flush=True)
            write_metrics(rc)
            return 0
        if rc == EXIT_PREEMPTED:
            crashes = 0
            watchdog_exits = 0
            print(f"[supervisor] child preempted (exit {rc}); relaunching in "
                  f"{args.preempt_delay:.1f}s", flush=True)
            write_relaunch(rc)
            time.sleep(args.preempt_delay)
            continue
        if rc == EXIT_WATCHDOG:
            # watchdog exhaustion: its own consecutive budget + counter, NOT
            # a generic crash (it already emergency-checkpointed; a relaunch
            # resumes and retries on fresh program state)
            watchdog_exits += 1
            watchdog_exits_total += 1
            print(f"[supervisor] resilience_supervisor_exit_76="
                  f"{watchdog_exits_total}", flush=True)
            if watchdog_exits > args.max_watchdog_relaunches:
                print(f"[supervisor] {watchdog_exits} consecutive watchdog "
                      f"exhaustions (exit {rc}); giving up", flush=True)
                write_metrics(rc)
                return rc
            delay = min(args.backoff_max,
                        backoff_delay(watchdog_exits, args.backoff_base * 1e3))
            print(f"[supervisor] child hit watchdog exhaustion (exit {rc}, "
                  f"{watchdog_exits}/{args.max_watchdog_relaunches}); "
                  f"relaunching in {delay:.1f}s", flush=True)
            write_relaunch(rc)
            time.sleep(delay)
            continue
        crashes += 1
        if crashes > args.max_relaunches:
            print(f"[supervisor] {crashes} consecutive crashes (last exit "
                  f"{rc}); giving up", flush=True)
            write_metrics(rc)
            return rc
        delay = min(args.backoff_max,
                    args.backoff_base * (2 ** (crashes - 1))) * (0.5 + random.random())
        print(f"[supervisor] child crashed (exit {rc}, crash {crashes}/"
              f"{args.max_relaunches}); relaunching in {delay:.1f}s", flush=True)
        write_relaunch(rc)
        time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
