#!/bin/bash
# SUPERSEDED: use scripts/train_supervisor.py (relaunch-with-backoff +
# --resume auto emergency-checkpoint resume, training/resilience.py) instead
# of these ad-hoc per-session probe loops; kept for the session logs they
# reference.
# Wait for any in-flight chip session to end, then probe for a healthy TPU
# grant and run scripts/tpu_session5b.sh (the session-5 recovery legs).
# Single-client discipline: never probe while tpu_session5.sh still runs.
# Stops probing at TPU_RETRY_STOP_AT (default 01:30 UTC) so a late grant
# never collides with the round driver's own bench window.
cd "$(dirname "$0")/.."
mkdir -p artifacts/r5
STOP_AT="${TPU_RETRY_STOP_AT:-01:30}"
stop=$(date -u -d "today $STOP_AT" +%s)
[ "$stop" -le "$(date -u +%s)" ] && stop=$(date -u -d "tomorrow $STOP_AT" +%s)

while pgrep -f "bash scripts/tpu_session5.sh" > /dev/null; do
  echo "[retry5b] session 5 still running at $(date -u +%H:%M:%S); waiting" >> artifacts/r5/retry5b.log
  sleep 300
  [ "$(date -u +%s)" -ge "$stop" ] && { echo "[retry5b] stop reached while waiting" >> artifacts/r5/retry5b.log; exit 0; }
done

n=0
while [ "$(date -u +%s)" -lt "$stop" ]; do
  n=$((n + 1))
  echo "[retry5b] probe $n at $(date -u +%H:%M:%S)" >> artifacts/r5/retry5b.log
  if timeout 2400 python -c "
import jax
d = jax.devices()
assert d and d[0].platform == 'tpu', d
import jax.numpy as jnp
assert float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()) == 512.0
print('healthy:', d)
" >> artifacts/r5/retry5b.log 2>&1; then
    echo "[retry5b] healthy at $(date -u +%H:%M:%S); starting session 5b" >> artifacts/r5/retry5b.log
    bash scripts/tpu_session5b.sh >> artifacts/r5/session5b.log 2>&1
    echo "[retry5b] session 5b finished at $(date -u +%H:%M:%S)" >> artifacts/r5/retry5b.log
    exit 0
  fi
  sleep 120
done
echo "[retry5b] stop time $STOP_AT reached; no healthy grant" >> artifacts/r5/retry5b.log
