#!/bin/sh
# Reference train_mujoco.sh: HalfCheetah 6x1, obsk 0, 40 threads, 40
# minibatches, episode_length 100, lr 5e-5, entropy 0.001, grad clip 0.5,
# ppo_epoch 10, clip 0.05; faulty-node eval list for robustness studies.
scenario="${1:-HalfCheetah-v2}"
conf="${2:-6x1}"
seed="${3:-1}"
exec python train_mujoco.py --scenario "$scenario" --agent_conf "$conf" \
  --agent_obsk 0 --algorithm_name mat --experiment_name single --seed "$seed" \
  --n_rollout_threads 40 --num_mini_batch 40 --episode_length 100 \
  --num_env_steps 10000000 --lr 5e-5 --entropy_coef 0.001 \
  --max_grad_norm 0.5 --ppo_epoch 10 --clip_param 0.05 \
  --eval_faulty_node -1 --eval_episodes 5
