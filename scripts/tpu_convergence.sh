#!/bin/sh
# Full-budget convergence evidence on the chip (VERDICT r3 item 3):
# the reference DCML recipe (DCML_MAT_Train.py:193 — 8 rollout threads,
# 1M env steps, T=50, lr 5e-5, ppo_epoch 15, 4 minibatches) for
#   1) momat  — both objective channels vs the shipped TensorBoard exports
#   2) mat    — scalar episode reward vs the TD3 anchor (data/dcml_td3.txt)
# run SEQUENTIALLY (tunnel discipline: one TPU client at a time), then the
# convergence report for each.
#
# Usage: scripts/tpu_convergence.sh [num_env_steps] [seed]
set -e
steps="${1:-1000000}"
seed="${2:-1}"
cd "$(dirname "$0")/.."

# Three legs: momat under BOTH scalarization weightings (the reference's
# missing trainer makes its weighting unrecoverable — the equal-weights run
# dominates the reference's completion-time channel, the payment-weighted
# "1,9" run chases its payment channel; BENCHLOG "MO-norm fix validation"),
# then scalar mat vs the TD3 anchor.
run_leg() {
  local algo="$1" exp="$2"; shift 2
  echo "=== $algo/$exp: $steps env steps (reference recipe) ==="
  python train_dcml.py --algorithm_name "$algo" --experiment_name "$exp" \
    --seed "$seed" --n_rollout_threads 8 --num_env_steps "$steps" \
    --episode_length 50 --lr 5e-5 --ppo_epoch 15 --num_mini_batch 4 \
    --log_interval 25 "$@"
  python convergence_report.py "results/DCML/AS/$algo/$exp/metrics.jsonl" || true
}
run_leg momat conv_r4
run_leg momat conv_r4_w19 --objective_weights 1,9
run_leg mat conv_r4
