#!/usr/bin/env python
"""Measure the whole-decode fused kernel vs the XLA decode scan on the chip.

One serialized TPU session (tunnel discipline: one client at a time), probed
via bench.py's killable-subprocess pattern: times ``get_actions`` (encode +
full autoregressive decode) under the XLA impl and the Pallas whole-decode
kernel at several batch tiles, at the production shape (E x 101 agents, bf16
trunk), and reports the on-chip draw-match fraction between the two impls
(f32 bit-exactness is pinned separately by tests/test_pallas_decode.py; the
full train-loop effect is measured by bench.py's E-sweep once dispatch
flips).

Writes one JSON line per E to stdout; diagnostics to stderr.
Usage: python scripts/tpu_decode_bench.py [E ...]
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def log(msg):
    print(f"[decode-bench] {msg}", file=sys.stderr, flush=True)


def main():
    Es = [int(a) for a in sys.argv[1:]] or [256]

    from bench import _setup_jax

    jax, fell_back = _setup_jax()
    if fell_back:
        log("TPU unavailable; refusing to measure decode on CPU")
        raise SystemExit(2)
    import jax.numpy as jnp
    import numpy as np

    from mat_dcml_tpu.config import RunConfig
    from mat_dcml_tpu.envs.dcml import DCMLEnv, DCMLEnvConfig
    from mat_dcml_tpu.models import decode as decode_lib
    from mat_dcml_tpu.training.runner import build_mat_policy
    import mat_dcml_tpu.ops.pallas_decode as pd

    data_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "data")
    run = RunConfig(model_dtype=os.environ.get("BENCH_DTYPE", "bfloat16"))
    env = DCMLEnv(DCMLEnvConfig(), data_dir=data_dir)
    policy = build_mat_policy(run, env)
    params = policy.init_params(jax.random.key(0))
    A = policy.cfg.n_agent

    def make_inputs(E, seed=1):
        ks = jax.random.split(jax.random.key(seed), 3)
        obs = jax.random.normal(ks[0], (E, A, env.obs_dim))
        share = jax.random.normal(ks[1], (E, A, env.share_obs_dim))
        ava = jnp.ones((E, A, env.action_dim))
        return share, obs, ava

    def timed(fn, *args, iters=20, vary_key=None):
        """Block after EVERY call and, when ``vary_key`` names a positional
        arg, swap in a fresh PRNG key each call: repeat dispatches of one
        executable with unchanged args measured dispatch-only on the tunneled
        TPU runtime (r5 session leg 3 printed 0.12 ms for a full 101-position
        AR decode)."""
        args = list(args)
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for i in range(iters):
            if vary_key is not None:
                args[vary_key] = jax.random.key(1000 + i)
            out = fn(*args)
            jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters, out

    for E in Es:
        share, obs, ava = make_inputs(E)

        def actions_with(impl, block_b=None):
            os.environ["MAT_DCML_TPU_DECODE_IMPL"] = impl
            orig = pd.fused_ar_decode
            if block_b is not None:
                pd.fused_ar_decode = functools.partial(orig, block_b=block_b)
            try:
                fn = jax.jit(
                    lambda p, k, s, o, a: policy.get_actions(p, k, s, o, a)
                )
                dt, out = timed(fn, params, jax.random.key(7), share, obs, ava,
                                vary_key=1)
            finally:
                pd.fused_ar_decode = orig
                os.environ["MAT_DCML_TPU_DECODE_IMPL"] = "auto"
            return dt, out

        t_xla, out_xla = actions_with("xla")
        log(f"E={E}: xla get_actions {t_xla*1e3:.1f} ms ({t_xla/A*1e6:.0f} us/position)")
        row = {"E": E, "xla_ms": round(t_xla * 1e3, 2)}

        for bb in (32, 64, 128):
            try:
                t_p, out_p = actions_with("pallas", block_b=bb)
            except Exception as e:
                log(f"E={E} pallas block_b={bb} FAILED: {type(e).__name__}: {e}")
                row[f"pallas_bb{bb}_ms"] = None
                continue
            # on-chip parity: under a bf16 trunk the two paths round logits
            # differently in low bits, so near-tie draws may differ on a tiny
            # fraction of (env, agent) pairs — report the match fraction
            # (f32 interpret-mode equality is pinned by test_pallas_decode.py)
            a_x, a_p = np.asarray(out_xla.action), np.asarray(out_p.action)
            nd = A - 1
            match = float((a_x[:, :nd] == a_p[:, :nd]).mean())
            tail_err = float(np.max(np.abs(a_x[:, nd:] - a_p[:, nd:])))
            log(
                f"E={E}: pallas bb={bb} {t_p*1e3:.1f} ms ({t_p/A*1e6:.0f} us/pos) "
                f"draw_match={match:.4f} tail_maxerr={tail_err:.2e} "
                f"speedup={t_xla/t_p:.1f}x"
            )
            row[f"pallas_bb{bb}_ms"] = round(t_p * 1e3, 2)
            row[f"pallas_bb{bb}_draw_match"] = round(match, 4)
        print(json.dumps(row), flush=True)

    log("done")


if __name__ == "__main__":
    main()
