#!/bin/sh
# Reference train_football.sh: academy_counterattack_easy, 4 agents, 20
# threads, 1 minibatch, episode_length 200, lr 5e-4, ppo_epoch 10, clip 0.05.
# Needs the external gfootball package (the entry point explains the gating).
scenario="${1:-academy_counterattack_easy}"
seed="${2:-1}"
exec python train_football.py --scenario "$scenario" --n_agent 4 \
  --algorithm_name mat --experiment_name single --seed "$seed" \
  --n_rollout_threads 20 --num_mini_batch 1 --episode_length 200 \
  --num_env_steps 10000000 --lr 5e-4 --entropy_coef 0.01 \
  --max_grad_norm 0.5 --ppo_epoch 10 --clip_param 0.05 --eval_episodes 32
