#!/usr/bin/env python
"""Render one run's observability streams into a single text report.

Consumes the two jsonl streams a run leaves behind — ``metrics.jsonl``
(utils/metrics.py; training records, serving/fleet snapshots, anomaly and
emergency records) and ``trace.jsonl`` (telemetry/tracing.py; sampled span
trees) — plus their rotated ``.1`` predecessors, and prints four panels:

1. **Latency waterfall by span**: per-span duration statistics (count / mean /
   p50 / p95 / max) across every sampled trace, grouped by trace kind, plus an
   ASCII waterfall of the slowest complete request tree so "where did the p99
   go" is answerable without loading anything into a UI.
2. **Fleet / SLO summary**: the last observed serving percentiles (merged
   sketch snapshots), fleet routing and rollout counters, live SLO burn-rate
   gauges, and every typed anomaly record grouped by kind.  Runs fronted by
   the federation router (serving/router.py) additionally get a **service
   topology** panel: per-host health / request share / weight generation
   (split-brain generations are flagged), the router's failover / brownout /
   push accounting, and the upstream-latency sketch.
3. **Actor/learner overlap** (``--async_actors`` runs): submesh split, queue
   depth / queue-wait p95 / drop counter, actor-vs-learner progress, and the
   param-staleness histogram.
4. **Training health**: fps and step-timer trajectory, compile/recompile and
   nonfinite-grad counters, dispatch mode, and emergency checkpoints.
5. **Incident timeline**: the correlator's typed ``incident`` records
   (telemetry/incidents.py) grouped per incident id — lifecycle chain,
   severity, attribution causal key (UNEXPLAINED incidents are flagged),
   trace exemplars, and the ``incident_`` summary gauges.
6. **Long-run trends**: the rollup plane's ``ts`` window records
   (telemetry/timeseries.py) — first-vs-last window means for step timers,
   tail latencies, and burn rates, so multi-hour drift is visible without
   replaying the raw stream.
7. **Perf-flag tuning provenance**: the ``tune_`` gauge family the autotuner
   stamps (tuning/probe.py) — which tuned config a run actually ran.

**Multi-source (federation) mode** — repeated ``--source label=dir`` renders
one coherent report across a whole service (serving fleet + trainer + loadgen
+ the ``scripts/obs_collector.py`` output dir):

- a federation header with the collector's ``scrape_*`` / ``obs_*`` health
  (stale sources are flagged, never silently dropped),
- a **cross-process trace stitching** panel: span records grouped by trace id
  across sources, counting traces that crossed a process boundary and showing
  the client-root minus server-root overhead plus the slowest stitched
  request (client wall, server wall, failover ``attempt`` hops).  A federated
  service stitches THREE tiers under one id — client root, router root
  (kind ``router``, with its ``route`` host hops), host-fleet root — and the
  panel renders the full chain for the slowest such trace,
- a **chaos-vs-SLO timeline**: every chaos record correlated, in stream
  order, with the nearest SLO burn / latency-tail observation before and
  after it,
- then the four per-source panels for each source in turn.

Usage:
    python scripts/obs_report.py <run_dir>              # finds both streams
    python scripts/obs_report.py --metrics m.jsonl --trace t.jsonl
    python scripts/obs_report.py --source fleet=runs/serve \\
        --source trainer=runs/train --source collector=runs/obs

Everything is stdlib; the report goes to stdout (pipe it into a file to keep
it next to the run).  Exit 2 when no records are found at all.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional

BAR_WIDTH = 40


# --------------------------------------------------------------------- input


def read_jsonl(paths: List[Path]) -> List[dict]:
    records: List[dict] = []
    for path in paths:
        if path is None or not path.exists():
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue   # a torn tail line on a live run is not fatal
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def with_rotated(path: Optional[Path]) -> List[Path]:
    """``[file.1, file]`` so rotated (older) records come first."""
    if path is None:
        return []
    return [path.with_name(path.name + ".1"), path]


def percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


# ------------------------------------------------------------ span waterfall


def span_panel(traces: List[dict]) -> List[str]:
    lines = ["== latency waterfall by span =="]
    if not traces:
        return lines + ["  (no trace records)"]
    # per-(kind, span) duration stats across all sampled trees
    by_key: Dict[tuple, List[float]] = defaultdict(list)
    roots: Dict[str, dict] = {}
    children: Dict[str, List[dict]] = defaultdict(list)
    for rec in traces:
        span, kind = rec.get("span", "?"), rec.get("kind", "?")
        dur = float(rec.get("dur_ms", 0.0))
        by_key[(kind, span)].append(dur)
        tid = rec.get("trace", "")
        if rec.get("parent") is None:
            roots[tid] = rec
        else:
            children[tid].append(rec)
    header = f"  {'kind':<10} {'span':<16} {'count':>6} {'mean_ms':>9} " \
             f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}"
    lines.append(header)
    for (kind, span), durs in sorted(by_key.items()):
        lines.append(
            f"  {kind:<10} {span:<16} {len(durs):>6} "
            f"{sum(durs) / len(durs):>9.2f} {percentile(durs, 0.50):>9.2f} "
            f"{percentile(durs, 0.95):>9.2f} {max(durs):>9.2f}"
        )
    # waterfall of the slowest COMPLETE tree (root + at least one child)
    slow = None
    for tid, root in roots.items():
        if children[tid] and (
                slow is None or root["dur_ms"] > roots[slow]["dur_ms"]):
            slow = tid
    if slow is not None:
        root = roots[slow]
        total = max(float(root["dur_ms"]), 1e-9)
        lines.append(f"  -- slowest sampled tree: trace {slow} "
                     f"({root.get('kind', '?')}/{root.get('span', '?')}, "
                     f"{total:.2f} ms, status={root.get('status', '?')}) --")
        tree = [root] + sorted(children[slow], key=lambda r: r.get("t_ms", 0.0))
        for rec in tree:
            t0 = float(rec.get("t_ms", 0.0))
            dur = float(rec.get("dur_ms", 0.0))
            pad = int(BAR_WIDTH * min(t0 / total, 1.0))
            bar = max(1, int(BAR_WIDTH * min(dur / total, 1.0)))
            indent = "" if rec.get("parent") is None else "  "
            lines.append(
                f"  {indent}{rec.get('span', '?'):<14} "
                f"|{' ' * pad}{'#' * bar:<{BAR_WIDTH - pad + 1}}| "
                f"{dur:>8.2f} ms"
            )
        child_sum = sum(float(r.get("dur_ms", 0.0)) for r in tree[1:]
                        if r.get("span") != "attempt")
        lines.append(f"  span sum (ex attempt hops) {child_sum:.2f} ms "
                     f"vs end-to-end {total:.2f} ms")
    return lines


# ------------------------------------------------------------- fleet + SLO


def _last_with_prefix(metrics: List[dict], prefixes: tuple) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for rec in metrics:
        for k, v in rec.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and k.startswith(prefixes):
                out[k] = float(v)   # later records win
    return out


def fleet_panel(metrics: List[dict]) -> List[str]:
    lines = ["== fleet / SLO summary =="]
    latest = _last_with_prefix(
        metrics, ("serving_", "fleet_", "rollout_", "slo_"))
    if not latest:
        lines.append("  (no serving/fleet records)")
    lat = {k: v for k, v in latest.items()
           if k.endswith(("_p50", "_p95", "_p99", "_ms"))
           or "_ms_" in k or k.endswith("_qps")}
    if lat:
        lines.append("  latency / throughput (last observed):")
        for k in sorted(lat):
            lines.append(f"    {k:<34} {lat[k]:>12.3f}")
    slo = {k: v for k, v in latest.items() if k.startswith("slo_")}
    if slo:
        lines.append("  SLO burn rates (>= 1.0 burns the error budget):")
        for k in sorted(slo):
            flag = "  <-- BUDGET BURNING" if (
                k.endswith("_burn") and slo[k] >= 1.0) else ""
            lines.append(f"    {k:<34} {slo[k]:>12.3f}{flag}")
    ops = {k: v for k, v in latest.items()
           if k.startswith(("fleet_", "rollout_")) and k not in lat}
    if ops:
        lines.append("  fleet / rollout counters (last observed):")
        for k in sorted(ops):
            lines.append(f"    {k:<34} {ops[k]:>12.1f}")
    anomalies = [r for r in metrics if "anomaly" in r]
    if anomalies:
        by_kind: Dict[str, int] = defaultdict(int)
        for a in anomalies:
            by_kind[str(a.get("anomaly"))] += 1
        lines.append("  anomalies:")
        for kind, n in sorted(by_kind.items()):
            lines.append(f"    {kind:<34} {n:>12}")
    return lines


_HOST_STATES = {0.0: "UNHEALTHY", 1.0: "healthy"}


def service_panel(metrics: List[dict]) -> List[str]:
    """Federation topology from the router's ``router_``/``host_`` record
    (serving/router.py ``service_record``): one row per host with health
    state, request share, and weight generation; a split-brain service (two
    hosts steady-state on different generations) is flagged loudly, as is a
    generation-split gauge left high by a partial roll."""
    lines = ["== service topology (federation router) =="]
    latest = _last_with_prefix(metrics, ("router_", "host_"))
    latest.pop("host_rss_bytes", None)   # the process gauge, not a host row
    if not any(k.startswith("router_") for k in latest):
        return lines + ["  (no service router records)"]
    n_hosts = latest.get("router_hosts", 0.0)
    lines.append(f"  hosts {n_hosts:.0f}  healthy {latest.get('router_healthy', 0):.0f}"
                 f"  service generation {latest.get('router_generation', 0):.0f}")
    host_ids = sorted(
        int(m.group(1)) for k in latest
        for m in [re.match(r"^host_(\d+)_state$", k)] if m)
    total_req = sum(latest.get(f"host_{h}_requests", 0.0) for h in host_ids)
    gens = {latest.get(f"host_{h}_generation", 0.0) for h in host_ids}
    if host_ids:
        lines.append(f"  {'host':<6} {'state':<11} {'gen':>4} {'requests':>9} "
                     f"{'share':>7} {'outstanding':>12} {'failures':>9}")
    for h in host_ids:
        state = _HOST_STATES.get(
            latest.get(f"host_{h}_state", -1.0), "?")
        req = latest.get(f"host_{h}_requests", 0.0)
        gen = latest.get(f"host_{h}_generation", 0.0)
        flag = "  <-- GENERATION SPLIT" if len(gens) > 1 else ""
        lines.append(
            f"  h{h:<5} {state:<11} {gen:>4.0f} {req:>9.0f} "
            f"{(req / total_req if total_req else 0.0):>6.1%} "
            f"{latest.get(f'host_{h}_outstanding', 0.0):>12.0f} "
            f"{latest.get(f'host_{h}_failures', 0.0):>9.0f}{flag}")
    if latest.get("router_generation_split", 0.0):
        lines.append("  router_generation_split=1  <-- SPLIT-BRAIN SERVICE")
    ups = {k: v for k, v in latest.items()
           if k.startswith("router_upstream_ms")}
    if ups:
        lines.append(
            f"  upstream latency p50 {ups.get('router_upstream_ms_p50', 0):.2f} ms"
            f"  p95 {ups.get('router_upstream_ms_p95', 0):.2f} ms"
            f"  p99 {ups.get('router_upstream_ms_p99', 0):.2f} ms"
            f"  (n={ups.get('router_upstream_ms_count', 0):.0f})")
    lines.append("  router counters (last observed):")
    for k in sorted(k for k in latest
                    if k.startswith("router_")
                    and not k.startswith("router_upstream_ms")
                    and k not in ("router_hosts", "router_healthy",
                                  "router_generation")):
        flag = ""
        if k == "router_retries_exhausted" and latest[k] > 0:
            flag = "  <-- DROPPED REQUESTS"
        elif k == "router_generation_split" and latest[k] > 0:
            flag = "  <-- SPLIT-BRAIN SERVICE"
        lines.append(f"    {k:<34} {latest[k]:>12.1f}{flag}")
    return lines


# ---------------------------------------------------------- training health


def training_panel(metrics: List[dict]) -> List[str]:
    lines = ["== training health =="]
    train = [r for r in metrics if "fps" in r]
    if not train:
        return lines + ["  (no training records)"]
    last = train[-1]
    fps = [float(r["fps"]) for r in train]
    lines.append(f"  records {len(train)}  episodes {last.get('episode', '?')}"
                 f"  total_steps {last.get('total_steps', '?')}")
    lines.append(f"  fps last {fps[-1]:.0f}  mean {sum(fps) / len(fps):.0f}"
                 f"  min {min(fps):.0f}")
    fused = last.get("iters_per_dispatch", 1) > 1
    timers = ("step_time_dispatch", "step_time_host_block") if fused else \
             ("step_time_collect", "step_time_train")
    for t in timers:
        vals = [float(r[t]) for r in train if t in r]
        if vals:
            lines.append(f"  {t:<22} last {vals[-1]:.4f}s  "
                         f"p95 {percentile(vals, 0.95):.4f}s")
    for k in ("compile_count", "compile_seconds_total",
              "steady_state_recompiles", "nonfinite_grad_steps",
              "dispatch_fused_fallback"):
        if k in last:
            lines.append(f"  {k:<28} {float(last[k]):.2f}")
    emergencies = [r for r in metrics if "emergency_checkpoint" in r]
    for e in emergencies:
        lines.append(f"  emergency checkpoint at episode {e.get('episode')}: "
                     f"{e.get('emergency_checkpoint')}")
    return lines


# ------------------------------------------------------ actor/learner overlap


def async_panel(metrics: List[dict]) -> List[str]:
    """Overlap health for ``--async_actors`` runs: submesh split, queue
    depth/wait, the drop counter (contractually 0 — backpressure, not loss),
    actor-vs-learner progress, and the param-staleness histogram."""
    lines = ["== actor/learner overlap =="]
    train = [r for r in metrics if "async_learner_steps" in r]
    if not train:
        return lines + ["  (no async actor-learner records)"]
    last = train[-1]
    lines.append(
        f"  submesh split: {last.get('async_actor_devices', '?'):.0f} actor / "
        f"{last.get('async_learner_devices', '?'):.0f} learner devices"
        if "async_actor_devices" in last else "  submesh split: ?")
    lines.append(f"  learner steps {last.get('async_learner_steps', 0):.0f}  "
                 f"actor iters {last.get('async_actor_iters', 0):.0f}")
    depths = [float(r["async_queue_depth"]) for r in train
              if "async_queue_depth" in r]
    if depths:
        lines.append(f"  queue depth last {depths[-1]:.0f}  "
                     f"p95 {percentile(depths, 0.95):.0f}  "
                     f"max {last.get('async_queue_max_depth', 0):.0f}  "
                     f"drops {last.get('async_queue_drops', 0):.0f}")
    if "async_queue_wait_ms_p95" in last:
        lines.append(f"  queue wait p50 {last.get('async_queue_wait_ms_p50', 0):.2f} ms  "
                     f"p95 {last['async_queue_wait_ms_p95']:.2f} ms  "
                     f"(n={last.get('async_queue_wait_ms_count', 0):.0f})")
    if "staleness_learner_steps_p95" in last:
        lines.append(f"  staleness (learner steps) p50 "
                     f"{last.get('staleness_learner_steps_p50', 0):.1f}  "
                     f"p95 {last['staleness_learner_steps_p95']:.1f}  "
                     f"mean {last.get('staleness_learner_steps_mean', 0):.2f}  "
                     f"(n={last.get('staleness_learner_steps_count', 0):.0f})")
    if "staleness_param_version" in last:
        lines.append(f"  published param version "
                     f"{last['staleness_param_version']:.0f}")
    if "store_staleness_budget" in last:
        lines.append(f"  store: budget {last['store_staleness_budget']:.0f}  "
                     f"depth {last.get('store_depth', 0):.0f}"
                     f"/{last.get('store_max_depth', 0):.0f} max  "
                     f"tickets {last.get('store_tickets', 0):.0f}  "
                     f"puts {last.get('store_puts', 0):.0f}  "
                     f"gets {last.get('store_gets', 0):.0f}  "
                     f"drops {last.get('store_drops', 0):.0f}")
    if "offpolicy_applied" in last:
        lines.append(f"  off-policy correction: applied "
                     f"{last['offpolicy_applied']:.0f}  "
                     f"lag {last.get('offpolicy_lag', 0):.0f}  "
                     f"rho mean {last.get('offpolicy_rho_mean', 0):.3f}  "
                     f"max {last.get('offpolicy_rho_max', 0):.3f}  "
                     f"clipped {last.get('offpolicy_rho_clip_fraction', 0):.1%}")
    # one row per collector worker (--async_actor_workers N): its private
    # iteration counter and actor-side throughput, so a straggling or
    # restarted worker is visible at a glance
    worker_ids = sorted(
        int(m.group(1)) for k in last
        for m in [re.match(r"^async_actor_w(\d+)_iters$", k)] if m)
    for wid in worker_ids:
        iters = last.get(f"async_actor_w{wid}_iters", 0)
        rate = last.get(f"async_actor_w{wid}_env_steps_per_sec")
        line = f"  worker w{wid}: iters {iters:.0f}"
        if rate is not None:
            line += f"  env steps/s {rate:.1f}"
        lines.append(line)
    if worker_ids and last.get("async_actor_restarts"):
        lines.append(f"  worker restarts "
                     f"{last['async_actor_restarts']:.0f}")
    for k in ("async_actor_steady_state_recompiles", "steady_state_recompiles"):
        if k in last:
            side = "actor" if k.startswith("async_actor_") else "learner"
            lines.append(f"  {side} steady-state recompiles "
                         f"{float(last[k]):.0f}")
    return lines


# -------------------------------------------------- incidents + long-run


def incident_panel(metrics: List[dict]) -> List[str]:
    """Timeline of the correlator's typed ``incident`` records: one block per
    incident id with its lifecycle chain and attribution causal key.  An
    incident without ``attributed_to`` is UNEXPLAINED — the condition that
    fails an armed soak."""
    lines = ["== incident timeline =="]
    incs = [r for r in metrics if "incident" in r]
    if not incs:
        return lines + ["  (no incident records)"]
    by_id: Dict[str, List[dict]] = defaultdict(list)
    for r in incs:
        by_id[str(r.get("incident_id", "?"))].append(r)
    for iid in sorted(by_id):
        recs = by_id[iid]
        last = recs[-1]
        chain = " -> ".join(str(r.get("incident", "?")) for r in recs)
        attr = last.get("attributed_to")
        flag = "" if attr else "  <-- UNEXPLAINED"
        lines.append(f"  {iid} {str(last.get('kind', '?')):<26} "
                     f"[{last.get('severity', '?')}] {chain}{flag}")
        detail = [f"cause={attr}" if attr else "cause=?"]
        detail.append(f"events={last.get('events', 1)}")
        if last.get("flaps"):
            detail.append(f"flaps={last['flaps']}")
        if isinstance(last.get("duration_s"), (int, float)):
            detail.append(f"duration={float(last['duration_s']):.2f}s")
        if last.get("trace_exemplar"):
            detail.append(f"exemplar={last['trace_exemplar']}")
        lines.append("      " + "  ".join(detail))
    summary = _last_with_prefix(metrics, ("incident_",))
    # incident_id is a string field on every record, not a gauge
    summary = {k: v for k, v in summary.items() if k != "incident_id"}
    if summary:
        lines.append("  summary:")
        for k in sorted(summary):
            flag = "  <-- FAILS SOAK" if (
                k in ("incident_unexplained", "incident_open")
                and summary[k] > 0) else ""
            lines.append(f"    {k:<34} {summary[k]:>12.1f}{flag}")
    return lines


# window metrics worth trending across a long run
_TREND_SUFFIXES = ("_p95", "_p99", "_burn")
_TREND_PREFIXES = ("step_time", "fps")


def timeseries_panel(metrics: List[dict]) -> List[str]:
    """First-vs-last rollup window means for the drift-prone families: the
    multi-hour trend view the bounded ``RollupStore`` retains after the raw
    stream has rotated away."""
    lines = ["== long-run trends (rollup windows) =="]
    wins = [r for r in metrics if r.get("ts") == "window"]
    if not wins:
        return lines + ["  (no rollup window records)"]
    by_metric: Dict[str, List[dict]] = defaultdict(list)
    for r in wins:
        name = str(r.get("metric", "?"))
        if name.endswith(_TREND_SUFFIXES) or name.startswith(_TREND_PREFIXES):
            by_metric[name].append(r)
    tiers = sorted({int(r.get("tier", 0)) for r in wins})
    lines.append(f"  window records {len(wins)}  tiers {tiers}  "
                 f"metrics trended {len(by_metric)}")
    if not by_metric:
        return lines + ["  (no drift-prone metric families in the windows)"]
    header = f"  {'metric':<34} {'windows':>7} {'first_mean':>11} " \
             f"{'last_mean':>11} {'drift':>8}"
    lines.append(header)
    for name in sorted(by_metric):
        recs = sorted(by_metric[name],
                      key=lambda r: float(r.get("start_s", 0.0)))

        def mean(r: dict) -> float:
            c = float(r.get("ts_count", 0.0))
            return float(r.get("ts_sum", 0.0)) / c if c else 0.0

        first, last = mean(recs[0]), mean(recs[-1])
        drift = ((last - first) / abs(first) * 100.0) if first else 0.0
        lines.append(f"  {name:<34} {len(recs):>7} {first:>11.4f} "
                     f"{last:>11.4f} {drift:>+7.1f}%")
    return lines


def tuning_panel(metrics: List[dict]) -> List[str]:
    """Which tuned perf-flag config a run actually ran: the ``tune_`` gauge
    family stamped from the tuned-config artifact (tuning/probe.py)."""
    lines = ["== perf-flag tuning provenance =="]
    latest = _last_with_prefix(metrics, ("tune_",))
    if not latest:
        return lines + ["  (no tune_ records — run used defaults)"]
    for k in sorted(latest):
        lines.append(f"  {k:<34} {latest[k]:>12.3f}")
    return lines


# ------------------------------------------------------- federation panels


def federation_panel(metrics: List[dict]) -> List[str]:
    """Scrape-plane health from the collector's merged stream: source and
    staleness counts, scrape errors, seq-guarded restarts."""
    lines = ["== federation / scrape health =="]
    latest = _last_with_prefix(metrics, ("scrape_", "obs_"))
    if not latest:
        return lines + ["  (no collector records)"]
    for k in sorted(latest):
        flag = "  <-- STALE SOURCES" if (
            k == "scrape_stale" and latest[k] > 0) else ""
        lines.append(f"  {k:<34} {latest[k]:>12.1f}{flag}")
    riders = [r for r in metrics if "run_id" in r]
    if riders:
        last = riders[-1]
        lines.append(f"  run lineage: run_id={last['run_id']} "
                     f"incarnation={last.get('incarnation', '?')}")
    return lines


# stitched-trace tiers, outermost first; a federated request carries all
# three kinds under one trace id (client -> router -> host fleet), a direct
# fleet request only client + serving
_TIER_ORDER = ("client", "router", "serving")


def stitch_panel(source_traces: Dict[str, List[dict]]) -> List[str]:
    """Group span records by trace id ACROSS sources.  A trace id seen in
    more than one source crossed a process boundary (W3C traceparent over
    ``POST /v1/act``); for those, the client root minus the innermost server
    root is the network + client-stack overhead.  A federated service chains
    THREE roots under one id — client, router (kind ``router``), host fleet —
    and the slowest such trace is rendered tier by tier with the router's
    ``route`` host hops and the fleet's ``attempt`` replica hops."""
    lines = ["== cross-process trace stitching =="]
    by_trace: Dict[str, List[tuple]] = defaultdict(list)
    for src, traces in source_traces.items():
        for rec in traces:
            tid = rec.get("trace")
            if tid:
                by_trace[str(tid)].append((src, rec))
    multi = {tid: recs for tid, recs in by_trace.items()
             if len({src for src, _ in recs}) > 1}
    three_tier = 0
    for recs in multi.values():
        kinds = {str(r.get("kind", "?")) for _, r in recs
                 if r.get("parent") is None}
        if len(kinds & set(_TIER_ORDER)) >= 3:
            three_tier += 1
    lines.append(f"  trace ids {len(by_trace)}  "
                 f"stitched across processes {len(multi)}")
    lines.append(f"  three-tier (client->router->host) {three_tier}")
    if not multi:
        return lines + ["  (no trace id observed in more than one process)"]
    overheads: List[float] = []
    worst = None
    for tid, recs in multi.items():
        # slowest root per kind: a router retry can land the same trace id
        # on more than one host, and the slow hop is the informative one
        roots: Dict[str, tuple] = {}
        for src, r in recs:
            if r.get("parent") is not None:
                continue
            kind = str(r.get("kind", "?"))
            if kind not in roots or float(r.get("dur_ms", 0.0)) > \
                    float(roots[kind][1].get("dur_ms", 0.0)):
                roots[kind] = (src, r)
        client = roots.get("client")
        server = roots.get("serving") or next(
            ((s, r) for k, (s, r) in roots.items() if k != "client"), None)
        if client is None or server is None:
            continue
        overheads.append(max(0.0, float(client[1].get("dur_ms", 0.0))
                             - float(server[1].get("dur_ms", 0.0))))
        if worst is None or float(client[1].get("dur_ms", 0.0)) > \
                float(worst[1][1].get("dur_ms", 0.0)):
            worst = (tid, client, roots, recs)
    if overheads:
        lines.append(
            f"  client-minus-server overhead: n={len(overheads)}  "
            f"mean {sum(overheads) / len(overheads):.2f} ms  "
            f"p95 {percentile(overheads, 0.95):.2f} ms  "
            f"max {max(overheads):.2f} ms")
    if worst is not None:
        tid, _, roots, recs = worst
        lines.append(f"  -- slowest stitched trace {tid} --")
        ordered = [k for k in _TIER_ORDER if k in roots] \
            + sorted(k for k in roots if k not in _TIER_ORDER)
        for depth, kind in enumerate(ordered):
            src, root = roots[kind]
            label = "  " * depth + f"{src}/{root.get('span', '?')}"
            lines.append(f"    {label:<36} "
                         f"{float(root.get('dur_ms', 0.0)):>9.2f} ms  "
                         f"status={root.get('status', '?')}")
        hops = sorted((r for _, r in recs
                       if r.get("span") in ("attempt", "route")),
                      key=lambda r: float(r.get("t_ms", 0.0)))
        for hop in hops:
            if hop.get("span") == "route":
                lines.append(f"      route host={hop.get('host', '?')} "
                             f"retry={hop.get('retry', '?')} "
                             f"ok={hop.get('ok', '?')} "
                             f"{float(hop.get('dur_ms', 0.0)):.2f} ms")
            else:
                lines.append(f"      attempt replica={hop.get('replica', '?')} "
                             f"ok={hop.get('ok', '?')} "
                             f"{float(hop.get('dur_ms', 0.0)):.2f} ms")
    return lines


# keys worth correlating a chaos event against (tail latency + SLO burn)
_CHAOS_WATCH_SUFFIXES = ("_ms_p99", "_ms_p95", "_burn")


def _nearest_watch(metrics: List[dict], idx: int, step: int) -> Dict[str, float]:
    """Walk the stream from ``idx`` in ``step`` direction to the first record
    carrying any watched key; stream order is the honest alignment — these
    files have no shared wall clock."""
    i = idx + step
    while 0 <= i < len(metrics):
        found = {k: float(v) for k, v in metrics[i].items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)
                 and k.endswith(_CHAOS_WATCH_SUFFIXES)}
        if found:
            return found
        i += step
    return {}


def chaos_timeline_panel(source_metrics: Dict[str, List[dict]]) -> List[str]:
    lines = ["== chaos vs SLO / latency timeline =="]
    any_event = False
    for src in sorted(source_metrics):
        metrics = source_metrics[src]
        for idx, rec in enumerate(metrics):
            if "chaos" not in rec:
                continue
            any_event = True
            lines.append(f"  [{src}] {rec.get('chaos', '?')} "
                         f"{rec.get('event_id', '?')}"
                         + (f" t={float(rec['t_s']):.2f}s"
                            if isinstance(rec.get("t_s"), (int, float)) else ""))
            before = _nearest_watch(metrics, idx, -1)
            after = _nearest_watch(metrics, idx, +1)
            for k in sorted(set(before) & set(after)):
                delta = after[k] - before[k]
                lines.append(f"      {k:<32} {before[k]:>10.3f} -> "
                             f"{after[k]:>10.3f}  ({delta:+.3f})")
            for k in sorted(set(after) - set(before)):
                lines.append(f"      {k:<32} {'-':>10} -> {after[k]:>10.3f}")
    if not any_event:
        lines.append("  (no chaos records)")
    return lines


# ----------------------------------------------------------------- assembly


def build_multi_report(sources: "Dict[str, tuple]") -> str:
    """``sources`` maps label -> (metrics, traces).  Federation panels first
    (computed across the union), then the per-source panels."""
    out: List[str] = [
        f"==== federation report: {len(sources)} source(s): "
        f"{', '.join(sorted(sources))} ===="
    ]
    all_metrics = [r for _, (m, _) in sorted(sources.items()) for r in m]
    out += federation_panel(all_metrics)
    out += stitch_panel({s: t for s, (_, t) in sources.items()})
    out += chaos_timeline_panel({s: m for s, (m, _) in sources.items()})
    for src in sorted(sources):
        metrics, traces = sources[src]
        out.append(f"\n==== source: {src} ====")
        out.append(build_report(metrics, traces).rstrip("\n"))
    return "\n".join(out) + "\n"


def build_report(metrics: List[dict], traces: List[dict]) -> str:
    sections = [
        span_panel(traces),
        fleet_panel(metrics),
        service_panel(metrics),
        incident_panel(metrics),
        timeseries_panel(metrics),
        async_panel(metrics),
        training_panel(metrics),
        tuning_panel(metrics),
    ]
    return "\n".join("\n".join(s) for s in sections) + "\n"


def load_streams(root: Optional[Path], metrics_path: Optional[Path] = None,
                 trace_path: Optional[Path] = None):
    """(metrics, traces) for one run dir, rotated files included and
    trace-shaped records split out of mixed streams."""
    extra: List[dict] = []
    trace_files: List[Optional[Path]] = [trace_path]
    if root is not None:
        if metrics_path is None:
            found = sorted(root.rglob("metrics.jsonl"))
            metrics_path = found[0] if found else None
        if trace_path is None:
            # a service run dir nests one trace stream per tier (router/,
            # host0/, host1/, ...) — the stitching panel needs all of them
            trace_files = sorted(root.rglob("trace.jsonl")) or [None]
        # rollup + incident streams ride into the metrics view: their typed
        # records feed the incident/trend panels
        for name in ("timeseries.jsonl", "incidents.jsonl"):
            for path in sorted(root.rglob(name)):
                extra += read_jsonl(with_rotated(path))
    metrics = read_jsonl(with_rotated(metrics_path)) + extra
    traces: List[dict] = []
    for path in trace_files:
        traces += read_jsonl(with_rotated(path))
    # trace records may interleave into metrics.jsonl-shaped fixtures; split
    # them by shape rather than by file so mixed streams still report
    traces += [r for r in metrics if "trace" in r]
    metrics = [r for r in metrics if "trace" not in r]
    return metrics, traces


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="observability run report")
    p.add_argument("run_dir", nargs="?", default=None,
                   help="directory holding metrics.jsonl / trace.jsonl")
    p.add_argument("--metrics", default=None)
    p.add_argument("--trace", default=None)
    p.add_argument("--source", action="append", default=None,
                   metavar="LABEL=DIR",
                   help="federation mode (repeatable): render one report "
                        "across several run dirs — fleet, trainer, loadgen, "
                        "obs_collector output")
    args = p.parse_args(argv)

    if args.source:
        sources: Dict[str, tuple] = {}
        for spec in args.source:
            label, sep, d = spec.partition("=")
            if not sep or not label or not d:
                p.error(f"--source wants label=dir, got {spec!r}")
            sources[label] = load_streams(Path(d))
        if not any(m or t for m, t in sources.values()):
            print("no records found", file=sys.stderr)
            return 2
        sys.stdout.write(build_multi_report(sources))
        return 0

    metrics, traces = load_streams(
        Path(args.run_dir) if args.run_dir else None,
        Path(args.metrics) if args.metrics else None,
        Path(args.trace) if args.trace else None)
    if not metrics and not traces:
        print("no records found", file=sys.stderr)
        return 2
    sys.stdout.write(build_report(metrics, traces))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
