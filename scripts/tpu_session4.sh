#!/bin/bash
# Round-4 chip session (VERDICT r3 "Next round" items 2-5).
# Priority: convergence evidence first (item 3 — the artifact that needs
# hours), then the measurement legs (items 2, 4, 5).  One TPU client at a
# time; this script assumes the caller (tpu_retry_session4.sh) verified a
# healthy grant.
set -x
cd "$(dirname "$0")/.."
mkdir -p artifacts/r4
export BENCH_TPU_PROBE_TIMEOUT=0
export MAT_DCML_TPU_DECODE_IMPL=xla   # measured r3 winner; leg 4 re-checks

echo "=== 1. convergence runs (reference recipe, full budget) ==="
timeout 16000 bash scripts/tpu_convergence.sh 1000000 1 \
  > artifacts/r4/convergence.log 2>&1
tail -40 artifacts/r4/convergence.log

echo "=== 2. collect decomposition (on-chip effect of the sampler fix) ==="
timeout 3000 python scripts/tpu_collect_bench.py 256 \
  > artifacts/r4/collect_bench.json 2> artifacts/r4/collect_bench.log
cat artifacts/r4/collect_bench.json

echo "=== 3. decode micro-bench: fixed Pallas whole-decode vs XLA scan ==="
timeout 3000 python scripts/tpu_decode_bench.py 256 512 \
  > artifacts/r4/decode_bench.json 2> artifacts/r4/decode_bench.log
cat artifacts/r4/decode_bench.json

echo "=== 4. combined-step A/B at E=256 + op trace ==="
for impl in xla pallas; do
  prof=""
  [ "$impl" = xla ] && prof="artifacts/r4/trace_e256"
  MAT_DCML_TPU_DECODE_IMPL=$impl BENCH_N_ENVS=256 BENCH_ITERS=3 \
    BENCH_PROFILE_DIR=$prof timeout 3000 python bench.py \
    > "artifacts/r4/bench_e256_${impl}.json" 2> "artifacts/r4/bench_e256_${impl}.log"
  cat "artifacts/r4/bench_e256_${impl}.json"
done
JAX_PLATFORMS=cpu python scripts/trace_report.py artifacts/r4/trace_e256 40 \
  > artifacts/r4/trace_e256_report.txt 2>&1 || true
tail -50 artifacts/r4/trace_e256_report.txt

echo "=== 5. E-ladder with remat+grad-accum (the unmeasured r3 lever) ==="
BENCH_SWEEP=1 BENCH_SWEEP_ENVS=256,512,1024,2048,4096,8192 BENCH_BREAKDOWN=1 \
  BENCH_ITERS=3 timeout 5400 python bench.py \
  > artifacts/r4/bench_sweep.json 2> artifacts/r4/bench_sweep.log
cat artifacts/r4/bench_sweep.json

echo "=== 6. attention A/B in the PPO update (E=256) ==="
MAT_DCML_TPU_ATTN_IMPL=pallas BENCH_N_ENVS=256 BENCH_ITERS=3 BENCH_BREAKDOWN=1 \
  timeout 3000 python bench.py \
  > artifacts/r4/bench_e256_attnpallas.json 2> artifacts/r4/bench_e256_attnpallas.log
cat artifacts/r4/bench_e256_attnpallas.json

echo "=== session 4 complete ==="
