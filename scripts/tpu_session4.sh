#!/bin/bash
# Round-4 chip session (VERDICT r3 "Next round" items 2-5).
#
# Ordering rationale (differs from the r3 plan): the r3 outage granted ONE
# ~25-minute window, which the session burned before reaching its
# measurement legs.  The short legs (collect decomposition, decode A/B,
# combined A/B + trace, attention A/B — ~30 min total) close VERDICT items
# 2/4/5 and run FIRST; the E-ladder follows; the convergence legs (hours,
# and already covered by the round-4 CPU insurance run in
# artifacts/r4/conv_cpu_w19.log) run LAST so a short grant still produces
# the numbers that have been plans for two rounds.
# One TPU client at a time; the caller (tpu_retry_session4.sh) verified a
# healthy grant.
set -x
cd "$(dirname "$0")/.."
mkdir -p artifacts/r4
export BENCH_TPU_PROBE_TIMEOUT=0
export MAT_DCML_TPU_DECODE_IMPL=xla   # measured r3 winner; leg 2 re-checks

# Hard wall-clock stop (default 04:45 UTC, ~45 min before the round-4
# driver window): the driver's own bench.py needs the single-client tunnel
# uncontended at round end — a convergence leg must never still hold it.
STOP_AT="${TPU_SESSION_STOP_AT:-04:45}"
now=$(date -u +%s)
stop=$(date -u -d "today $STOP_AT" +%s) || { echo "bad TPU_SESSION_STOP_AT=$STOP_AT"; exit 1; }
[ "$stop" -le "$now" ] && stop=$(date -u -d "tomorrow $STOP_AT" +%s)
budget() {  # budget <leg-cap-seconds> -> min(cap, seconds-to-stop); 0 = stop
  local cap=$1 rem=$(( stop - $(date -u +%s) ))
  [ "$rem" -lt 60 ] && { echo 0; return; }
  [ "$rem" -lt "$cap" ] && echo "$rem" || echo "$cap"
}
# computing a budget inside $(...) cannot exit the script (subshell), so
# every leg fetches its budget FIRST and bails past the wall
need() { t=$(budget "$1"); [ "$t" -gt 0 ] && return 0
         echo "=== past hard stop $STOP_AT UTC; ending session ==="; exit 0; }

echo "=== 1. collect decomposition (on-chip effect of the sampler fix) ==="
need 3000
timeout "$t" python scripts/tpu_collect_bench.py 256 \
  > artifacts/r4/collect_bench.json 2> artifacts/r4/collect_bench.log
cat artifacts/r4/collect_bench.json

echo "=== 2. decode micro-bench: fixed Pallas whole-decode vs XLA scan ==="
need 3000
timeout "$t" python scripts/tpu_decode_bench.py 256 512 \
  > artifacts/r4/decode_bench.json 2> artifacts/r4/decode_bench.log
cat artifacts/r4/decode_bench.json

echo "=== 3. combined-step A/B at E=256 + op trace ==="
for impl in xla pallas; do
  prof=""
  [ "$impl" = xla ] && prof="artifacts/r4/trace_e256"
  need 3000
  MAT_DCML_TPU_DECODE_IMPL=$impl BENCH_N_ENVS=256 BENCH_ITERS=3 \
    BENCH_BREAKDOWN=1 BENCH_PROFILE_DIR=$prof timeout "$t" python bench.py \
    > "artifacts/r4/bench_e256_${impl}.json" 2> "artifacts/r4/bench_e256_${impl}.log"
  cat "artifacts/r4/bench_e256_${impl}.json"
done
JAX_PLATFORMS=cpu python scripts/trace_report.py artifacts/r4/trace_e256 40 \
  > artifacts/r4/trace_e256_report.txt 2>&1 || true
tail -50 artifacts/r4/trace_e256_report.txt

echo "=== 4. attention A/B in the PPO update (E=256) ==="
need 3000
MAT_DCML_TPU_ATTN_IMPL=pallas BENCH_N_ENVS=256 BENCH_ITERS=3 BENCH_BREAKDOWN=1 \
  timeout "$t" python bench.py \
  > artifacts/r4/bench_e256_attnpallas.json 2> artifacts/r4/bench_e256_attnpallas.log
cat artifacts/r4/bench_e256_attnpallas.json

echo "=== 5. E-ladder with remat+grad-accum (the unmeasured r3 lever) ==="
need 5400
BENCH_SWEEP=1 BENCH_SWEEP_ENVS=256,512,1024,2048,4096,8192 BENCH_BREAKDOWN=1 \
  BENCH_ITERS=3 timeout "$t" python bench.py \
  > artifacts/r4/bench_sweep.json 2> artifacts/r4/bench_sweep.log
cat artifacts/r4/bench_sweep.json

echo "=== 6. convergence runs (reference recipe, full budget) ==="
need 14000
timeout "$t" bash scripts/tpu_convergence.sh 1000000 1 \
  > artifacts/r4/convergence.log 2>&1
tail -40 artifacts/r4/convergence.log

echo "=== session 4 complete ==="
