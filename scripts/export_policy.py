#!/usr/bin/env python
"""Export a training checkpoint as a weights-only serving artifact.

Restores the latest (or ``--step``) full TrainState from a run's checkpoint
directory and writes just the policy params + MATConfig + space metadata via
``training/checkpoint.export_policy`` — the input ``serving/server.py`` and
``serving/loadgen.py`` consume.  A server restoring this artifact never
deserializes optimizer moments or ValueNorm state.

Usage:
  python scripts/export_policy.py --model_dir results/DCML/AS/mat/check/models \
      --out exports/dcml_as_mat [--step N] [model flags matching the run, e.g.
      --n_block 2 --n_embd 64 --n_head 2 --algorithm_name mat]

Model flags must match the training run (they size the params template); a
mismatch fails loudly at restore time with a tree-structure error.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

import jax  # noqa: E402

from mat_dcml_tpu.config import parse_cli_with_extras  # noqa: E402
from mat_dcml_tpu.envs.dcml import DCMLEnv, DCMLEnvConfig  # noqa: E402
from mat_dcml_tpu.training.checkpoint import CheckpointManager, export_policy  # noqa: E402
from mat_dcml_tpu.training.ppo import MATTrainer  # noqa: E402
from mat_dcml_tpu.training.runner import build_mat_policy  # noqa: E402


def main(argv=None) -> int:
    extras = argparse.ArgumentParser(add_help=False)
    extras.add_argument("--out", required=True, help="export directory")
    extras.add_argument("--step", type=int, default=None,
                        help="checkpoint step (default: latest)")
    extras.add_argument("--data_dir", default="data")
    run, ppo, ns = parse_cli_with_extras(argv, extras=extras)
    if not run.model_dir:
        print("--model_dir is required (the run's models/ directory)",
              file=sys.stderr)
        return 2

    env = DCMLEnv(DCMLEnvConfig(), data_dir=ns.data_dir)
    policy = build_mat_policy(run, env)
    trainer = MATTrainer(policy, ppo, total_updates=run.episodes)
    template = jax.eval_shape(
        lambda: trainer.init_state(policy.init_params(jax.random.key(0)))
    )
    mgr = CheckpointManager(run.model_dir)
    step = ns.step if ns.step is not None else mgr.latest_step()
    if step is None:
        print(f"no checkpoint under {run.model_dir}", file=sys.stderr)
        return 1
    state = mgr.restore(step, template=template)
    space_meta = {
        "env_name": run.env_name,
        "scenario": run.scenario,
        "algorithm_name": run.algorithm_name,
        "n_agents": env.n_agents,
        "obs_dim": env.obs_dim,
        "share_obs_dim": env.share_obs_dim,
        "action_dim": env.action_dim,
        "checkpoint_step": int(step),
    }
    out = export_policy(ns.out, state.params, policy.cfg, space_meta)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    print(f"exported step {step} ({n_params} params) -> {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
