#!/usr/bin/env python
"""Push a policy export into a running fleet (canary-gated hot weight swap).

Stdlib HTTP client against ``scripts/serve_fleet.py``'s control endpoints.
The push blocks until the fleet's canary gate resolves and prints the full
report (status promoted | rolled_back | rejected, comparison/mismatch counts,
warm-pass recompiles, requests dropped during the push — expected 0).

Usage:
  python scripts/push_policy.py --policy_dir exports/gen2 [--host 127.0.0.1]
      [--port 8420] [--rollback]   # --rollback ignores --policy_dir
"""

import argparse
import json
import sys
import urllib.error
import urllib.request


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="push weights into a MAT fleet")
    p.add_argument("--policy_dir", default=None,
                   help="export dir to push (required unless --rollback)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8420)
    p.add_argument("--timeout_s", type=float, default=300.0,
                   help="HTTP timeout; covers warm passes + the canary gate")
    p.add_argument("--rollback", action="store_true",
                   help="roll the fleet back to its prior manifest instead")
    args = p.parse_args(argv)

    if args.rollback:
        url = f"http://{args.host}:{args.port}/v1/rollback"
        body = b"{}"
    else:
        if not args.policy_dir:
            print("--policy_dir is required (or pass --rollback)",
                  file=sys.stderr)
            return 2
        url = f"http://{args.host}:{args.port}/v1/push"
        body = json.dumps({"policy_dir": args.policy_dir}).encode()

    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=args.timeout_s) as resp:
            report = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        print(json.dumps({"http_status": e.code,
                          **json.loads(e.read() or b"{}")}, indent=2),
              file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2))
    status = report.get("status", "rolled_back" if args.rollback else "")
    return 0 if status in ("promoted", "rolled_back") else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
