#!/usr/bin/env python
"""Push a policy export into a running fleet or federated service.

Stdlib HTTP client against the control endpoints of ``serve_fleet.py``
(default) or ``serve_service.py`` (``--service``).  The push blocks until
the canary gate(s) resolve and prints the full report (status promoted |
rolled_back | rejected, comparison/mismatch counts, warm-pass recompiles,
requests dropped during the push — expected 0).

With ``--service`` the target is the router tier and the push is
generation-consistent across hosts: every host's canary gate must pass and
the federated SLO burn must be clean, or every already-promoted host rolls
back — the report carries the per-host sub-reports.

Usage:
  python scripts/push_policy.py --policy_dir exports/gen2 [--host 127.0.0.1]
      [--port 8420] [--rollback]   # --rollback ignores --policy_dir
  python scripts/push_policy.py --service --port 8520 --policy_dir exports/gen2
"""

import argparse
import json
import sys
import urllib.error
import urllib.request


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="push weights into a MAT fleet")
    p.add_argument("--policy_dir", default=None,
                   help="export dir to push (required unless --rollback)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=None,
                   help="default 8420 (fleet) / 8520 (--service)")
    p.add_argument("--service", action="store_true",
                   help="target a serve_service.py router instead of a "
                        "single fleet: the push rolls every host through "
                        "its canary gate, generation-consistently")
    p.add_argument("--timeout_s", type=float, default=None,
                   help="HTTP timeout; covers warm passes + the canary "
                        "gate(s); default 300 (fleet) / 900 (--service)")
    p.add_argument("--rollback", action="store_true",
                   help="roll the fleet back to its prior manifest instead")
    args = p.parse_args(argv)
    if args.port is None:
        args.port = 8520 if args.service else 8420
    if args.timeout_s is None:
        # a service push serializes N host canary gates
        args.timeout_s = 900.0 if args.service else 300.0

    if args.rollback:
        url = f"http://{args.host}:{args.port}/v1/rollback"
        body = b"{}"
    else:
        if not args.policy_dir:
            print("--policy_dir is required (or pass --rollback)",
                  file=sys.stderr)
            return 2
        url = f"http://{args.host}:{args.port}/v1/push"
        body = json.dumps({"policy_dir": args.policy_dir}).encode()

    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=args.timeout_s) as resp:
            report = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        print(json.dumps({"http_status": e.code,
                          **json.loads(e.read() or b"{}")}, indent=2),
              file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2))
    status = report.get("status", "rolled_back" if args.rollback else "")
    return 0 if status in ("promoted", "rolled_back") else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
