#!/bin/bash
# SUPERSEDED: use scripts/train_supervisor.py (relaunch-with-backoff +
# --resume auto emergency-checkpoint resume, training/resilience.py) instead
# of these ad-hoc per-session probe loops; kept for the session logs they
# reference.
# Wait for the first healthy TPU grant, then run scripts/tpu_session5.sh.
# Each probe is itself a claim attempt that can queue ~25 min before the
# tunnel reports UNAVAILABLE (round-2/3/4 outage signature), so probe with a
# generous timeout and loop.  Designed to run detached (nohup).
# Stops probing at TPU_RETRY_STOP_AT (default 17:00 UTC) so a late grant
# never collides with the round driver's own bench window.
cd "$(dirname "$0")/.."
mkdir -p artifacts/r5
STOP_AT="${TPU_RETRY_STOP_AT:-17:00}"
stop=$(date -u -d "today $STOP_AT" +%s)
[ "$stop" -le "$(date -u +%s)" ] && stop=$(date -u -d "tomorrow $STOP_AT" +%s)
n=0
while [ "$(date -u +%s)" -lt "$stop" ]; do
  n=$((n + 1))
  echo "[retry] probe $n at $(date -u +%H:%M:%S)" >> artifacts/r5/retry.log
  if timeout 2400 python -c "
import jax
d = jax.devices()
assert d and d[0].platform == 'tpu', d
import jax.numpy as jnp
assert float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()) == 512.0
print('healthy:', d)
" >> artifacts/r5/retry.log 2>&1; then
    echo "[retry] healthy at $(date -u +%H:%M:%S); starting session 5" >> artifacts/r5/retry.log
    bash scripts/tpu_session5.sh >> artifacts/r5/session5.log 2>&1
    echo "[retry] session 5 finished at $(date -u +%H:%M:%S)" >> artifacts/r5/retry.log
    exit 0
  fi
  sleep 120
done
echo "[retry] stop time $STOP_AT reached; no healthy grant" >> artifacts/r5/retry.log
