#!/bin/sh
# A/B the fused multi-episode dispatch (--iters_per_dispatch) against the
# classic two-dispatch loop: BENCH_K_SWEEP drives bench.py's fused leg
# (base_runner.make_dispatch_fn with donated buffers + DeferredFetch metric
# transfer) at several K values and reports env-steps/s per K.  Small E/T by
# default so the sweep finishes on CPU in minutes; on a chip session export
# BENCH_N_ENVS/BENCH_EPISODE_LENGTH back up to production sizes.
cd "$(dirname "$0")/.."
exec env \
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  BENCH_DIRECT=1 \
  BENCH_K_SWEEP="${BENCH_K_SWEEP:-1,4,16}" \
  BENCH_N_ENVS="${BENCH_N_ENVS:-8}" \
  BENCH_EPISODE_LENGTH="${BENCH_EPISODE_LENGTH:-4}" \
  BENCH_ITERS="${BENCH_ITERS:-4}" \
  BENCH_PPO_EPOCH="${BENCH_PPO_EPOCH:-2}" \
  BENCH_MINI_BATCH="${BENCH_MINI_BATCH:-2}" \
  python bench.py
