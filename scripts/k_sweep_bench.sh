#!/bin/sh
# SUPERSEDED: the K sweep is now a knob group of the perf-flag autotuner —
# this wrapper is `scripts/autotune.py --only dispatch` and prints the same
# per-K json lines + best-K record the old BENCH_K_SWEEP bench leg did
# (best-of-N alternating trials instead of one pass, so the numbers are the
# autotuner's).  The old env knobs still work and map onto autotune flags;
# new callers should invoke autotune.py directly (run without --only it also
# emits the tuned_config.json artifact).
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python scripts/autotune.py \
  --only dispatch \
  --k_list "${BENCH_K_SWEEP:-1,4,16}" \
  --E "${BENCH_N_ENVS:-8}" \
  --T "${BENCH_EPISODE_LENGTH:-4}" \
  --iters "${BENCH_ITERS:-4}" \
  --ppo_epoch "${BENCH_PPO_EPOCH:-2}" \
  --mini_batch "${BENCH_MINI_BATCH:-2}" \
  --trials "${BENCH_TRIALS:-2}"
