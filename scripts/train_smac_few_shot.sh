#!/bin/sh
# Reference train_smac_few_shot.sh: fine-tune the multi-task policy per
# held-out map (loop over maps, restore with --model_dir).
model_dir="${1:?usage: train_smac_few_shot.sh <model_dir of multi-task run>}"
seed="${2:-1}"
# genuinely held-out maps (disjoint from train_smac_multi.sh's roster of
# 3m,8m,2s3z,3s5z,MMM), like the reference's from-scratch/few-shot lists
for map in 2m 5m_vs_6m 8m_vs_9m; do
  python train_smac_multi.py --train_maps "$map" --eval_maps "$map" \
    --algorithm_name mat --experiment_name "few_shot_$map" --seed "$seed" \
    --model_dir "$model_dir" --n_rollout_threads 36 --num_mini_batch 1 \
    --episode_length 100 --num_env_steps 100000 --lr 5e-4 --ppo_epoch 10 \
    --clip_param 0.05 || exit 1
done
