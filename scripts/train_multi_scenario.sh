#!/bin/sh
# Generalist checkpoint over the DCML fault-scenario family (ROADMAP
# multi-scenario item): the faithful DCML recipe, trained across four
# scenarios (incl. the PR 9 fleet_stress preset) under the fused K-step
# dispatch.  Per-scenario eval matrix lands in <run_dir>/metrics.jsonl as
# the scenario_ gauge family; the checkpoint under models/ is the
# generalist artifact.
seed="${1:-1}"
scenarios="${2:-nominal,fleet_stress,heavy_stragglers,busy_fleet}"
exec python train_multi_scenario.py --algorithm_name mat \
  --experiment_name generalist --seed "$seed" --scenarios "$scenarios" \
  --n_rollout_threads 8 --num_env_steps 1000000 --episode_length 50 \
  --lr 5e-5 --ppo_epoch 15 --num_mini_batch 4 --iters_per_dispatch 4 \
  --use_eval true
