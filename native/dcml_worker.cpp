// C++ oracle for the DCML worker timeslot simulation.
//
// An INDEPENDENT scalar implementation of the worker math the reference
// runs in Python (DCML_Worker_TIMESLOT_MultiProcess.py:46-112) and this
// framework vectorizes in JAX (mat_dcml_tpu/envs/dcml/env.py
// _process_workers/_capacity/_cost_at).  Written as the reference wrote
// it — a literal loop draining timeslots one by one — NOT as the JAX
// cumsum/argmax rewrite, so agreement between the three implementations
// is evidence of correctness rather than shared structure
// (tests/test_native_oracle.py runs the differential comparison).
//
// Randomness is externalized: the geometric retry-failure counts are
// inputs (download_fails; upload_fails = the summed extra failures for
// however many upload draws the mode prescribes), making the function a
// pure scalar map that can be compared exactly.
//
// Build: g++ -O2 -shared -fPIC -o libdcml_worker.so dcml_worker.cpp
// (loaded via ctypes; no pybind11 needed).

#include <cmath>
#include <cstdint>

namespace {

// cumulative free capacity over the first j drained slots, period = trace
// starting at slot ctp0 (env.py _capacity; reference price bookkeeping
// DCML_Worker...py:84-108)
double capacity_first_j(const double* trace, int period, int ctp0, long j) {
    long q2 = j / period;       // full periods
    int r2 = (int)(j - q2 * period);
    double cap_period = 0.0;
    for (int s = 0; s < period; ++s) {
        cap_period += 1.0 - trace[(ctp0 + s) % period];
    }
    double partial = 0.0;
    for (int s = 0; s < r2; ++s) {
        partial += 1.0 - trace[(ctp0 + s) % period];
    }
    return (double)q2 * cap_period + partial;
}

}  // namespace

extern "C" {

// Outputs (out[6]): delay, p0, cost, m_slots, drained, cap_period
void dcml_worker_process(
    double r_wl, double c_wl,
    const double* trace, int period,
    double arrive_time, double download_rate,
    double download_fails, double upload_fails,
    int max_drain_slots,
    double second_to_centsec, double bit_to_byte, double worker_frequency,
    double* out) {
    // compute cost in free-capacity units (:49-50)
    double compute_workload = (9.0 * r_wl - 3.0) * c_wl;
    double cost0 = second_to_centsec * std::ceil(compute_workload) / worker_frequency;

    // download with retries (:53-60)
    double n_retry = 1.0 + download_fails;
    double transmit_delay =
        second_to_centsec *
        (std::ceil((r_wl + 1.0) * c_wl) * bit_to_byte / download_rate + 0.001) *
        n_retry;

    double p0 = std::floor(transmit_delay) * 0.1;            // (:65)
    double arrive_ts = std::floor(transmit_delay + arrive_time);  // (:66)
    int ctp0 = (int)std::fmod(arrive_ts, (double)period);    // (:67-69)

    double wl0 = trace[ctp0];
    double frac = transmit_delay - std::floor(transmit_delay);
    double cost = cost0 + ((frac - wl0 > 0.0) ? (frac - wl0) : 0.0);  // (:85-86)

    // drain timeslots one by one until the accumulated free capacity covers
    // the cost (:87-95) — the literal reference loop, epsilon-matched to the
    // vectorized rewrite's tie tolerance
    double cum = 0.0;
    long m = 0;
    while (cum < cost - 1e-9 && m < (long)max_drain_slots) {
        cum += 1.0 - trace[(ctp0 + (int)(m % period)) % period];
        ++m;
    }
    if (m == 0) m = 1;  // smallest m >= 1 (env.py t_part starts at 1)
    double drained = capacity_first_j(trace, period, ctp0, m);

    // upload with retries (:99-106; divides by the DOWNLOAD rate — the
    // reference quirk replicated by both implementations)
    double n_retry_final = n_retry + upload_fails;
    double upload_delay =
        second_to_centsec * (std::ceil(r_wl) * bit_to_byte / download_rate + 0.001) *
            n_retry_final +
        0.02;

    // (:108)
    double delay = (arrive_ts + (double)m) - arrive_time - (drained - cost) + upload_delay;

    double cap_period = capacity_first_j(trace, period, ctp0, period);
    out[0] = delay;
    out[1] = p0;
    out[2] = cost;
    out[3] = (double)m;
    out[4] = drained;
    out[5] = cap_period;
}

// accumulated price at end_timeslot (env.py _cost_at; reference
// DCML_..._SingleProcess.py:131-137)
double dcml_worker_cost_at(
    const double* trace, int period, int ctp0,
    double p0, double m_slots, double end_timeslot) {
    double j = end_timeslot < 1.0 ? 1.0 : end_timeslot;
    if (j > m_slots) j = m_slots;
    return p0 + capacity_first_j(trace, period, ctp0, (long)j);
}

}  // extern "C"
