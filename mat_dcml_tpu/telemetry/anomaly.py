"""Tripwire engine: EMA-baselined anomaly detection over registry signals.

The telemetry layer streams *that* training is healthy; this module decides
*when it stopped being healthy*, cheaply enough to run at every metric
observation point.  :class:`AnomalyDetector` keeps an exponential-moving
baseline per signal and trips on:

- ``nonfinite_grads`` — any minibatch update with a NaN/Inf global grad norm
  (immediate; no baseline needed);
- a nonfinite *value* of any observed signal (a NaN loss is an anomaly even
  before it poisons a gradient);
- spike signals (``grad_norm``, ``param_norm``, ``update_ratio``) exceeding
  ``spike_factor`` x their EMA baseline after ``warmup`` observations;
- step-time signals (``step_time_dispatch`` / ``step_time_train`` /
  ``step_time_collect``) exceeding ``time_factor`` x baseline — a steady-state
  perf regression, e.g. a device falling off its fast path;
- the ``steady_state_recompiles`` counter increasing — the recompile detector
  (jit_instrument.py) already logs the signature diff; the tripwire turns it
  into a typed record and a captured repro bundle;
- ``dispatch_fused_fallback`` reaching 1.0 — the fused runner silently
  degrading to the classic loop is a one-way event and trips exactly once;
- an SLO error-budget burn gauge (telemetry/slo.py) crossing
  ``slo_burn_threshold`` — budget exhaustion becomes a typed
  ``slo_<latency|error|goodput>_budget`` record the rollout controller can
  gate promotion on.

Trips become :class:`Anomaly` records written into the same metrics.jsonl
stream (``scripts/check_metrics_schema.py`` has a dedicated ``anomaly``
branch), and the runner reacts by dumping a flight-recorder bundle and
opening a bounded profiler window (:class:`ProfilerWindow`).

Nothing here touches jax except ``ProfilerWindow`` (host-side profiler
start/stop); detection is plain Python arithmetic on host floats.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

SPIKE_SIGNALS = ("grad_norm", "param_norm", "update_ratio")
TIME_SIGNALS = ("step_time_dispatch", "step_time_train", "step_time_collect")

# combined (multi-window) SLO burn gauges from telemetry/slo.py: thresholded,
# never EMA-baselined — the budget IS the baseline.  A burn >= slo_burn_threshold
# trips the matching "slo_<x>_budget" kind.
SLO_SIGNALS = ("slo_latency_burn", "slo_error_burn", "slo_goodput_burn")

# typed rollout anomaly kinds (serving/rollout_ctl.py): a canary or rollback
# event becomes an Anomaly record in the same metrics.jsonl stream, with the
# rollout GENERATION in the ``episode`` slot (a serving fleet has no episode
# counter) and ``total_steps`` pinned to 0.
ROLLOUT_KINDS = (
    "rollout_canary_parity",     # canary greedy action != incumbent
    "rollout_canary_value",      # canary value head outside tolerance
    "rollout_canary_latency",    # canary latency > factor x incumbent EMA
    "rollout_canary_error",      # canary request errored (budget exceeded)
    "rollout_warm_recompile",    # weight-swap warm pass re-entered XLA
    "rollout_rollback",          # the fleet rolled back to the prior manifest
)


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    spike_factor: float = 4.0   # trip when signal > factor * EMA baseline
    time_factor: float = 2.0    # step-time regression threshold
    warmup: int = 8             # observations before a baseline is trusted
    cooldown: int = 16          # units (episodes/dispatches) between repeat
                                # trips of the same kind — one bad regime must
                                # not flood the stream with identical records
    beta: float = 0.9           # EMA decay per observation
    slo_burn_threshold: float = 1.0  # combined burn >= this exhausts budget


@dataclasses.dataclass(frozen=True)
class Anomaly:
    kind: str                   # e.g. "nonfinite_grads", "grad_norm_spike"
    signal: str                 # the registry signal that tripped
    value: float
    baseline: Optional[float]
    episode: int
    total_steps: int
    # most recent sampled trace id at trip time (tracing.Tracer.last_trace_id)
    # — the exemplar that links an incident to one concrete span tree
    trace_exemplar: Optional[str] = None

    def to_record(self) -> dict:
        """Jsonl-safe record: the ``anomaly`` key routes validators to the
        anomaly branch; nonfinite values encode as strings because strict
        JSON has no NaN/Inf literal."""

        def enc(v):
            if v is None or math.isfinite(v):
                return v
            if math.isnan(v):
                return "nan"
            return "inf" if v > 0 else "-inf"

        rec = {
            "anomaly": self.kind,
            "signal": self.signal,
            "value": enc(self.value),
            "baseline": enc(self.baseline),
            "episode": self.episode,
            "total_steps": self.total_steps,
        }
        if self.trace_exemplar is not None:
            rec["trace_exemplar"] = self.trace_exemplar
        return rec


class AnomalyDetector:
    """Feed ``observe`` a flat ``{signal: float}`` dict once per unit
    (episode or fused dispatch); it returns the anomalies that tripped."""

    def __init__(self, cfg: AnomalyConfig = AnomalyConfig(), telemetry=None,
                 exemplar_fn=None):
        self.cfg = cfg
        self.telemetry = telemetry
        # zero-arg callable returning the most recent sampled trace id (or
        # None) — typically ``lambda: tracer.last_trace_id``; every trip
        # carries it so incidents link to a concrete trace tree
        self.exemplar_fn = exemplar_fn
        self._ema: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._last_trip: Dict[str, int] = {}
        self._unit = 0
        self._recompiles_seen = 0.0
        self._fallback_tripped = False

    # ------------------------------------------------------------- internals

    def _cooled(self, kind: str) -> bool:
        last = self._last_trip.get(kind)
        return last is None or self._unit - last >= self.cfg.cooldown

    def _trip(self, out: List[Anomaly], kind: str, signal: str, value: float,
              baseline: Optional[float], episode: int, total_steps: int) -> None:
        if not self._cooled(kind):
            return
        self._last_trip[kind] = self._unit
        exemplar = None
        if self.exemplar_fn is not None:
            try:
                exemplar = self.exemplar_fn()
            except Exception:
                exemplar = None
        out.append(Anomaly(kind, signal, float(value), baseline, episode,
                           total_steps,
                           trace_exemplar=str(exemplar) if exemplar else None))
        if self.telemetry is not None:
            self.telemetry.count("anomalies_total")
            self.telemetry.count(f"anomalies_{kind}")

    def _baseline(self, name: str, value: float) -> Optional[float]:
        """Current trusted baseline for ``name`` (None during warmup); call
        ``_absorb`` separately so tripped values never dilute the baseline."""
        if self._n.get(name, 0) < self.cfg.warmup:
            return None
        return self._ema[name]

    def _absorb(self, name: str, value: float) -> None:
        if name in self._ema:
            b = self.cfg.beta
            self._ema[name] = b * self._ema[name] + (1.0 - b) * value
        else:
            self._ema[name] = value
        self._n[name] = self._n.get(name, 0) + 1

    # -------------------------------------------------------------- observe

    def observe(self, signals: Dict[str, float], episode: int,
                total_steps: int) -> List[Anomaly]:
        """One detection pass.  ``signals`` maps registry names to host
        floats; unknown names are baselined but only the documented families
        can trip.  Nonfinite signal values trip regardless of family."""
        out: List[Anomaly] = []
        self._unit += 1

        nf = signals.get("nonfinite_grads", 0.0)
        if nf is not None and (not math.isfinite(nf) or nf > 0):
            self._trip(out, "nonfinite_grads", "nonfinite_grads", nf, None,
                       episode, total_steps)

        recompiles = signals.get("steady_state_recompiles", 0.0) or 0.0
        if recompiles > self._recompiles_seen:
            self._trip(out, "steady_state_recompile", "steady_state_recompiles",
                       recompiles, self._recompiles_seen, episode, total_steps)
            self._recompiles_seen = recompiles

        # silent-degradation tripwire: the fused runner falling back to the
        # classic loop is a one-way event per run, so it trips exactly once
        # (no cooldown-paced repeats for a gauge that stays pinned at 1.0).
        fallback = signals.get("dispatch_fused_fallback", 0.0) or 0.0
        if fallback >= 1.0 and not self._fallback_tripped:
            self._fallback_tripped = True
            self._trip(out, "dispatch_fused_fallback", "dispatch_fused_fallback",
                       fallback, None, episode, total_steps)

        for name, value in signals.items():
            if value is None or name in ("nonfinite_grads",
                                         "steady_state_recompiles",
                                         "dispatch_fused_fallback"):
                continue
            value = float(value)
            if not math.isfinite(value):
                self._trip(out, "nonfinite_value", name, value, None,
                           episode, total_steps)
                continue
            if name in SLO_SIGNALS:
                if value >= self.cfg.slo_burn_threshold:
                    self._trip(out, name.replace("_burn", "_budget"), name,
                               value, self.cfg.slo_burn_threshold,
                               episode, total_steps)
                continue  # burn gauges are thresholded, never baselined
            factor = None
            if name in SPIKE_SIGNALS:
                factor = self.cfg.spike_factor
            elif name in TIME_SIGNALS:
                factor = self.cfg.time_factor
            if factor is not None:
                base = self._baseline(name, value)
                if base is not None and value > factor * max(base, 1e-12):
                    self._trip(out, f"{name}_spike", name, value, base,
                               episode, total_steps)
                    continue  # spikes stay out of their own baseline
            self._absorb(name, value)
        return out


def rollout_anomaly(kind: str, signal: str, value: float,
                    baseline: Optional[float], generation: int,
                    telemetry=None) -> Anomaly:
    """Typed rollout anomaly: same record shape as training tripwires, with
    the rollout generation riding in the ``episode`` slot.  ``kind`` must be
    one of :data:`ROLLOUT_KINDS` so downstream dashboards can rely on the
    vocabulary."""
    if kind not in ROLLOUT_KINDS:
        raise ValueError(f"unknown rollout anomaly kind {kind!r}")
    if telemetry is not None:
        telemetry.count("anomalies_total")
        telemetry.count(f"anomalies_{kind}")
    return Anomaly(kind=kind, signal=signal, value=float(value),
                   baseline=baseline, episode=int(generation), total_steps=0)


class CanaryTripwire:
    """Latency + error tripwires over a canary replica during a rollout.

    The baseline is an EMA of *incumbent* request latency (fed from live
    traffic and synthetic shadow probes alike); the canary trips when its
    latency exceeds ``latency_factor`` x that baseline after ``warmup``
    incumbent observations, or when its error count exceeds ``error_budget``.
    Detection is plain host arithmetic — safe to call from any serving
    thread under the controller's lock.
    """

    def __init__(self, latency_factor: float = 4.0, warmup: int = 8,
                 error_budget: int = 0, beta: float = 0.9,
                 generation: int = 0, telemetry=None):
        self.latency_factor = latency_factor
        self.warmup = warmup
        self.error_budget = error_budget
        self.beta = beta
        self.generation = generation
        self.telemetry = telemetry
        self._ema_ms: Optional[float] = None
        self._n = 0
        self._errors = 0

    def observe_incumbent(self, latency_ms: float) -> None:
        latency_ms = float(latency_ms)
        if not math.isfinite(latency_ms):
            return
        if self._ema_ms is None:
            self._ema_ms = latency_ms
        else:
            self._ema_ms = self.beta * self._ema_ms + (1 - self.beta) * latency_ms
        self._n += 1

    def observe_canary(self, latency_ms: float) -> Optional[Anomaly]:
        if self._n < self.warmup or self._ema_ms is None:
            return None
        if float(latency_ms) > self.latency_factor * max(self._ema_ms, 1e-9):
            return rollout_anomaly(
                "rollout_canary_latency", "canary_latency_ms",
                float(latency_ms), self._ema_ms, self.generation,
                self.telemetry,
            )
        return None

    def record_error(self) -> Optional[Anomaly]:
        self._errors += 1
        if self._errors > self.error_budget:
            return rollout_anomaly(
                "rollout_canary_error", "canary_errors",
                float(self._errors), float(self.error_budget),
                self.generation, self.telemetry,
            )
        return None


class ProfilerWindow:
    """Bounded tripwire-triggered ``jax.profiler`` trace window.

    ``trigger`` starts a trace into ``<dir>/anomaly_<tag>``; ``tick`` (called
    once per episode/dispatch, AFTER the unit's work) counts it down and stops
    after ``n_units``.  Fires at most once per run so a persistent anomaly
    cannot re-trace forever, and ``close`` (runner's try/finally) guarantees a
    crash mid-window still terminates the trace instead of leaving a corrupt
    xplane.pb.
    """

    def __init__(self, directory: Optional[str], n_units: int, log=print):
        self.directory = directory
        self.n_units = int(n_units)
        self.log = log
        self.active = False
        self._remaining = 0
        self._fired = False

    @property
    def enabled(self) -> bool:
        return self.directory is not None and self.n_units > 0

    def trigger(self, tag: str) -> bool:
        if not self.enabled or self.active or self._fired:
            return False
        import jax

        target = f"{self.directory}/anomaly_{tag}"
        try:
            jax.profiler.start_trace(target)
        except Exception as e:  # another trace active (scheduled --profile_dir)
            self.log(f"[anomaly] profiler window skipped: {e}")
            return False
        self._fired = True
        self.active = True
        self._remaining = self.n_units
        self.log(f"[anomaly] profiler window open -> {target} "
                 f"({self.n_units} dispatches)")
        return True

    def tick(self) -> None:
        if not self.active:
            return
        self._remaining -= 1
        if self._remaining <= 0:
            self._stop()

    def close(self) -> None:
        if self.active:
            self._stop()

    def _stop(self) -> None:
        import jax

        self.active = False
        try:
            jax.profiler.stop_trace()
            self.log("[anomaly] profiler window closed")
        except Exception as e:
            self.log(f"[anomaly] profiler stop failed: {e}")
