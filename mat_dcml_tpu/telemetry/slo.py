"""Declared SLOs tracked as multi-window error-budget burn rates.

An SLO declares a budget: "≤1% of requests slower than 250 ms", "≤0.1%
errors", "≥98% goodput" (ok *and* within the latency target).  The burn rate
over a window is ``observed_violation_fraction / budget`` — burn 1.0 spends
the budget exactly as fast as allowed, burn 10 exhausts a 30-day budget in
3 days.  Following the classic multi-window alerting recipe, each SLO is
tracked over a *fast* and a *slow* window and the alertable burn is
``min(fast, slow)``: the slow window proves the regression is sustained, the
fast window proves it is still happening — so a long-resolved incident or a
single slow request cannot page.

:class:`SLOMonitor` is fed per-request outcomes from the serving hot path
(``EngineFleet._on_done`` / ``PolicyServer``), emits ``slo_*`` gauges into the
metrics stream, and its burn gauges are wired into the existing
:class:`~mat_dcml_tpu.telemetry.anomaly.AnomalyDetector`, which trips a typed
``slo_*_budget`` anomaly when a combined burn crosses threshold — the same
record shape and cooldown discipline as training tripwires, and the signal
`RolloutController` promotion gates on.

Plain host Python; the injectable ``clock`` keeps burn math deterministic in
tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    latency_p99_ms: float = 250.0   # latency SLO: target for the p99
    latency_budget: float = 0.01    # allowed fraction above target (=> p99)
    error_budget: float = 0.001     # allowed fraction of failed requests
    goodput_floor: float = 0.98     # required fraction ok AND within target
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    min_requests: int = 20          # below this a window cannot burn


class SLOMonitor:
    """Sliding-window burn-rate accounting over per-request outcomes."""

    def __init__(self, cfg: SLOConfig = SLOConfig(),
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.clock = clock
        # (t, latency_violation, error, not_goodput) per request
        self._events: Deque[Tuple[float, bool, bool, bool]] = deque()
        self._lock = threading.Lock()
        self.total_requests = 0

    # ------------------------------------------------------------- recording

    def observe_request(self, latency_ms: float, ok: bool = True) -> None:
        now = self.clock()
        slow = float(latency_ms) > self.cfg.latency_p99_ms
        err = not ok
        with self._lock:
            self._events.append((now, slow and ok, err, err or slow))
            self.total_requests += 1
            horizon = now - self.cfg.slow_window_s
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()

    # --------------------------------------------------------------- reading

    def _window(self, window_s: float, now: float) -> Tuple[int, int, int, int]:
        lo = now - window_s
        n = slow = err = bad = 0
        for t, s, e, b in self._events:
            if t >= lo:
                n += 1
                slow += s
                err += e
                bad += b
        return n, slow, err, bad

    @staticmethod
    def _burn(violations: int, n: int, budget: float, min_n: int) -> float:
        if n < max(min_n, 1) or budget <= 0:
            return 0.0
        return (violations / n) / budget

    def gauges(self, now: Optional[float] = None) -> Dict[str, float]:
        """Flat ``slo_*`` gauge fragment for the metrics stream.  The bare
        ``slo_<x>_burn`` is ``min(fast, slow)`` — the alertable value."""
        if now is None:
            now = self.clock()
        with self._lock:
            events = list(self._events)
        cfg = self.cfg
        out: Dict[str, float] = {}
        per_window = {}
        for tag, win in (("fast", cfg.fast_window_s), ("slow", cfg.slow_window_s)):
            lo = now - win
            n = slow = err = bad = 0
            for t, s, e, b in events:
                if t >= lo:
                    n += 1
                    slow += s
                    err += e
                    bad += b
            per_window[tag] = dict(
                latency=self._burn(slow, n, cfg.latency_budget, cfg.min_requests),
                error=self._burn(err, n, cfg.error_budget, cfg.min_requests),
                goodput=self._burn(bad, n, 1.0 - cfg.goodput_floor, cfg.min_requests),
                n=n,
            )
        for slo in ("latency", "error", "goodput"):
            fast = per_window["fast"][slo]
            slow_ = per_window["slow"][slo]
            out[f"slo_{slo}_burn_fast"] = fast
            out[f"slo_{slo}_burn_slow"] = slow_
            out[f"slo_{slo}_burn"] = min(fast, slow_)
        out["slo_window_requests"] = float(per_window["slow"]["n"])
        return out

    def burn_signals(self, now: Optional[float] = None) -> Dict[str, float]:
        """The combined-burn subset, shaped for ``AnomalyDetector.observe``."""
        g = self.gauges(now)
        return {k: v for k, v in g.items() if k.endswith("_burn")}

    def export_into(self, telemetry, now: Optional[float] = None) -> Dict[str, float]:
        """Push the current gauges into a ``Telemetry`` registry (so they ride
        the next flush) and return them."""
        g = self.gauges(now)
        if telemetry is not None:
            for name, v in g.items():
                telemetry.gauge(name, v)
        return g
