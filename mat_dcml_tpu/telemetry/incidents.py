"""Incident correlation: typed record streams → attributed incident objects.

The soak verdict layer.  :class:`IncidentCorrelator` consumes the repo's
typed observability records — anomaly trips (incl. ``slo_*`` burn budgets),
``chaos`` fired/suppressed/cleared, emergency checkpoints, supervisor relaunch
lineage, scrape-health transitions, fleet replica and service host health —
and groups them
into incidents via time proximity plus causal keys: chaos event ids (PR 15's
suppression keys), trace exemplars, ``run_id``/``incarnation``.

Lifecycle: ``open`` → ``mitigated`` (the attributed fault cleared) →
``resolved`` (quiet after mitigation / at finalize).  An incident **cannot
resolve without attribution** — an unexplained incident stays open by design,
which is exactly what lets ``chaos_soak.py``'s invariant fail a soak on a
symptom nobody injected.  Dedup folds repeat symptoms of the same kind into
one incident; flap suppression stops a bouncing signal from minting an
open/mitigate storm.

State transitions emit typed ``{"incident": <stage>}`` records with a closed
field set (validated by ``check_metrics_schema.py``); :meth:`summary` exports
the ``incident_`` gauge family.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence


def suppression_map() -> Dict[str, tuple]:
    """PR 15's chaos-kind → anomaly-kind-prefix suppression keys, reused as
    the attribution table (lazy import: chaos ↛ telemetry layering)."""
    try:
        from mat_dcml_tpu.chaos.inject import _SUPPRESSES
        return dict(_SUPPRESSES)
    except Exception:
        return {}


# symptom kinds the correlator derives itself (not anomaly-detector kinds)
KILL_KINDS = ("trainer_kill",)
CRITICAL_KINDS = ("nonfinite", "supervisor_kill", "supervisor_relaunch",
                  "fleet_no_healthy", "service_no_healthy")

# causal keys for correlator-derived symptoms: which injected fault kinds
# explain them (the anomaly-kind prefixes come from the chaos suppression
# table; this covers the health transitions the correlator itself derives)
SYMPTOM_FAULTS: Dict[str, tuple] = {
    "fleet_unhealthy": ("replica_crash", "replica_hang", "trainer_kill"),
    "fleet_no_healthy": ("replica_crash", "replica_hang"),
    "scrape_degraded": ("trainer_kill", "replica_crash", "replica_hang"),
    "supervisor_kill": KILL_KINDS,
    "supervisor_relaunch": KILL_KINDS,
    # service tier (router over N host fleets): a killed host shows up as a
    # router_healthy drop in the federation leg's records
    "service_host_down": ("host_loss",),
    "service_no_healthy": ("host_loss",),
}

LIFECYCLE = ("open", "mitigated", "resolved", "annotated")
SEVERITIES = ("warning", "critical")


@dataclasses.dataclass
class IncidentConfig:
    # a symptom within this many seconds of a fault's active window (fired →
    # cleared + grace) attributes to it by time proximity
    proximity_s: float = 45.0
    # same-kind symptom within this window folds into the existing incident
    flap_window_s: float = 120.0
    # reopen storms beyond this many flaps stop emitting records
    max_flaps: int = 8


@dataclasses.dataclass
class Incident:
    incident_id: str
    kind: str
    severity: str
    state: str                      # open | mitigated | resolved
    opened_t: float
    last_symptom_t: float
    attributed_to: Optional[str] = None   # chaos event id (causal key)
    trace_exemplar: Optional[str] = None
    run_id: Optional[str] = None
    incarnation: Optional[int] = None
    events: int = 1
    flaps: int = 0
    mitigated_t: Optional[float] = None
    resolved_t: Optional[float] = None

    def record(self, stage: str, t: float) -> Dict:
        rec: Dict = {
            "incident": stage,
            "incident_id": self.incident_id,
            "kind": self.kind,
            "severity": self.severity,
            "t_s": round(float(t), 6),
            "events": self.events,
            "flaps": self.flaps,
        }
        if self.attributed_to is not None:
            rec["attributed_to"] = self.attributed_to
        if self.trace_exemplar is not None:
            rec["trace_exemplar"] = self.trace_exemplar
        if stage == "resolved":
            rec["duration_s"] = round(float(t) - self.opened_t, 6)
        return rec


class _Fault:
    __slots__ = ("event_id", "kind", "fired_t", "cleared_t")

    def __init__(self, event_id: str, kind: str, fired_t: float):
        self.event_id = event_id
        self.kind = kind
        self.fired_t = fired_t
        self.cleared_t: Optional[float] = None

    def active_at(self, t: float, grace: float) -> bool:
        if t < self.fired_t - 1e-9:
            return False
        end = self.cleared_t if self.cleared_t is not None else t
        return t <= end + grace


class IncidentCorrelator:
    """Feed records in stream order via :meth:`ingest`; call :meth:`finalize`
    at end-of-run.  Emitted transition records accumulate in
    :meth:`records`; live objects in :meth:`incidents`."""

    def __init__(self, cfg: IncidentConfig = IncidentConfig()):
        self.cfg = cfg
        self._suppresses = suppression_map()
        self._faults: Dict[str, _Fault] = {}
        self._incidents: List[Incident] = []
        self._by_kind: Dict[str, Incident] = {}
        self._records: List[Dict] = []
        self._t = 0.0
        self.flaps_suppressed = 0
        # scrape / fleet / service transition state
        self._last_scrape: Dict[str, float] = {}
        self._last_fleet_healthy: Optional[float] = None
        self._last_router_healthy: Optional[float] = None

    # ------------------------------------------------------------ fault plane

    def register_fault(self, event_id: str, kind: str, t: float,
                       cleared_t: Optional[float] = None) -> None:
        """Register an injected fault as an attribution target.  The soak uses
        this for faults it delivers itself (e.g. the SIGTERM kill)."""
        f = self._faults.get(event_id)
        if f is None:
            f = self._faults[event_id] = _Fault(event_id, kind, float(t))
        if cleared_t is not None:
            f.cleared_t = float(cleared_t)

    def _clear_fault(self, event_id: str, t: float) -> None:
        f = self._faults.get(event_id)
        if f is not None and f.cleared_t is None:
            f.cleared_t = t
        for inc in self._incidents:
            if inc.attributed_to == event_id and inc.state == "open":
                self._transition(inc, "mitigated", t)

    def _kind_match(self, symptom_kind: str, fault_kind: str) -> bool:
        prefixes = self._suppresses.get(fault_kind, ())
        if any(symptom_kind.startswith(p) for p in prefixes):
            return True
        if fault_kind in SYMPTOM_FAULTS.get(symptom_kind, ()):
            return True
        return (symptom_kind in CRITICAL_KINDS or
                symptom_kind.startswith("supervisor")) and \
            fault_kind in KILL_KINDS

    def _attribute(self, symptom_kind: str, t: float) -> Optional[str]:
        """Causal-key attribution.  A fault whose kind explains the symptom
        (suppression prefixes, the SYMPTOM_FAULTS table, or kill-family
        matching) and whose active window covers ``t`` wins outright.  A
        kind-matching fault *outside* the window still attributes — soak
        streams concatenate sources whose monotonic clocks are incomparable,
        so the causal key outranks time proximity; nearest ``fired_t`` breaks
        ties.  With no kind match at all, the single active fault attributes
        only when the injection plan leaves no ambiguity."""
        matched: List[_Fault] = []
        active_only: List[_Fault] = []
        for f in self._faults.values():
            match = self._kind_match(symptom_kind, f.kind)
            active = f.active_at(t, self.cfg.proximity_s)
            if match and active:
                return f.event_id
            if match:
                matched.append(f)
            elif active:
                active_only.append(f)
        if matched:
            return min(matched, key=lambda f: abs(f.fired_t - t)).event_id
        if len(active_only) == 1:
            return active_only[0].event_id
        return None

    # -------------------------------------------------------------- ingestion

    def ingest(self, record: Dict, t: Optional[float] = None) -> None:
        """Dispatch one typed record.  Chaos records advance the stream clock
        from their ``t_s``; other records ride the latest clock (or an
        explicit ``t``)."""
        if t is not None:
            self._t = max(self._t, float(t))
        if "chaos" in record:
            self._ingest_chaos(record)
        elif "anomaly" in record:
            self._symptom(
                str(record["anomaly"]), self._t,
                trace=record.get("trace_exemplar"),
                run_id=record.get("run_id"),
                incarnation=record.get("incarnation"),
            )
        elif "emergency_checkpoint" in record:
            self._symptom(
                "supervisor_kill", self._t, severity="critical",
                run_id=record.get("run_id"),
                incarnation=record.get("incarnation"),
            )
        elif "resilience_supervisor_relaunch" in record:
            self._ingest_relaunch(record)
        elif "incident" in record or "ts" in record or "trace" in record:
            pass
        else:
            self._ingest_metrics(record)

    def _ingest_chaos(self, record: Dict) -> None:
        t = float(record.get("t_s", self._t))
        self._t = max(self._t, t)
        stage = record["chaos"]
        event_id = str(record.get("event_id", ""))
        kind = str(record.get("kind", ""))
        if stage == "fired":
            self.register_fault(event_id, kind, t)
        elif stage == "cleared":
            self._clear_fault(event_id, t)
        elif stage == "suppressed":
            # explicit causal key: the injector already matched this anomaly
            # kind to the fault that explains it
            self._symptom(str(record.get("suppressed_kind", kind)), t,
                          attributed=event_id)

    def _ingest_relaunch(self, record: Dict) -> None:
        t = self._t
        run_id = record.get("run_id")
        incarnation = record.get("incarnation")
        # annotate the matching kill incident (same run lineage) rather than
        # opening a second one — the relaunch is the mitigation, not a new
        # failure
        for inc in reversed(self._incidents):
            if inc.kind in ("supervisor_kill", "supervisor_relaunch") and \
                    inc.state != "resolved" and \
                    (run_id is None or inc.run_id in (None, run_id)):
                inc.events += 1
                inc.last_symptom_t = t
                if incarnation is not None:
                    inc.incarnation = int(incarnation)
                if run_id is not None:
                    inc.run_id = str(run_id)
                rec = inc.record("annotated", t)
                if inc.incarnation is not None:
                    rec["incarnation"] = inc.incarnation
                self._records.append(rec)
                if inc.state == "open" and inc.attributed_to is not None:
                    self._transition(inc, "mitigated", t)
                return
        self._symptom("supervisor_relaunch", t, severity="critical",
                      run_id=run_id, incarnation=incarnation)

    def _ingest_metrics(self, record: Dict) -> None:
        t = self._t
        # scrape-health transitions: errors/stale/restarts increasing
        for name in ("scrape_stale", "scrape_errors", "scrape_restarts"):
            v = record.get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                prev = self._last_scrape.get(name, 0.0)
                if float(v) > prev:
                    self._symptom("scrape_degraded", t)
                self._last_scrape[name] = float(v)
        # fleet replica health drops
        healthy = record.get("fleet_healthy")
        replicas = record.get("fleet_replicas")
        if isinstance(healthy, (int, float)) and \
                isinstance(replicas, (int, float)):
            prev = self._last_fleet_healthy
            if healthy < replicas and (prev is None or healthy < prev):
                kind = ("fleet_no_healthy" if healthy == 0
                        else "fleet_unhealthy")
                self._symptom(kind, t)
            self._last_fleet_healthy = float(healthy)
        # service host health drops (router tier, one level above the fleet)
        healthy = record.get("router_healthy")
        hosts = record.get("router_hosts")
        if isinstance(healthy, (int, float)) and \
                isinstance(hosts, (int, float)):
            prev = self._last_router_healthy
            if healthy < hosts and (prev is None or healthy < prev):
                kind = ("service_no_healthy" if healthy == 0
                        else "service_host_down")
                self._symptom(kind, t)
            self._last_router_healthy = float(healthy)

    # ---------------------------------------------------------- incident core

    def _symptom(self, kind: str, t: float, attributed: Optional[str] = None,
                 severity: Optional[str] = None,
                 trace: Optional[str] = None,
                 run_id: Optional[str] = None,
                 incarnation=None) -> None:
        self._t = max(self._t, t)
        if severity is None:
            severity = ("critical"
                        if any(kind.startswith(c) for c in CRITICAL_KINDS)
                        else "warning")
        inc = self._by_kind.get(kind)
        if inc is not None and inc.state != "resolved" and \
                (t - inc.last_symptom_t) <= self.cfg.flap_window_s:
            inc.events += 1
            inc.last_symptom_t = t
            if severity == "critical":
                inc.severity = "critical"
            if inc.trace_exemplar is None and trace:
                inc.trace_exemplar = str(trace)
            if run_id is not None:
                inc.run_id = str(run_id)
            if incarnation is not None:
                inc.incarnation = int(incarnation)
            newly = attributed or self._attribute(kind, t)
            if inc.attributed_to is None and newly is not None:
                inc.attributed_to = newly
                self._records.append(inc.record("annotated", t))
            if inc.state == "mitigated":
                inc.flaps += 1
                inc.state = "open"
                inc.mitigated_t = None
                if inc.flaps <= self.cfg.max_flaps:
                    self._records.append(inc.record("open", t))
                else:
                    self.flaps_suppressed += 1
            return
        inc = Incident(
            incident_id=f"inc:{len(self._incidents):03d}",
            kind=kind,
            severity=severity,
            state="open",
            opened_t=t,
            last_symptom_t=t,
            attributed_to=attributed or self._attribute(kind, t),
            trace_exemplar=str(trace) if trace else None,
            run_id=str(run_id) if run_id is not None else None,
            incarnation=int(incarnation) if incarnation is not None else None,
        )
        self._incidents.append(inc)
        self._by_kind[kind] = inc
        self._records.append(inc.record("open", t))

    def _transition(self, inc: Incident, state: str, t: float) -> None:
        inc.state = state
        if state == "mitigated":
            inc.mitigated_t = t
        elif state == "resolved":
            inc.resolved_t = t
        self._records.append(inc.record(state, t))

    def finalize(self, t: Optional[float] = None) -> None:
        """End-of-run sweep: attributed incidents whose fault cleared resolve
        (via mitigated); unattributed incidents STAY OPEN — they are the
        unexplained residue the soak invariant exists to catch."""
        t = self._t if t is None else max(self._t, float(t))
        for inc in self._incidents:
            if inc.attributed_to is None:
                continue
            fault = self._faults.get(inc.attributed_to)
            cleared = fault is None or fault.cleared_t is not None
            if not cleared:
                continue
            if inc.state == "open":
                self._transition(inc, "mitigated", t)
            if inc.state == "mitigated":
                self._transition(inc, "resolved", t)

    # -------------------------------------------------------------- reporting

    def incidents(self) -> List[Incident]:
        return list(self._incidents)

    def records(self) -> List[Dict]:
        return list(self._records)

    def summary(self) -> Dict[str, float]:
        incs = self._incidents
        return {
            "incident_total": float(len(incs)),
            "incident_open": float(sum(1 for i in incs if i.state == "open")),
            "incident_mitigated": float(
                sum(1 for i in incs if i.state == "mitigated")),
            "incident_resolved": float(
                sum(1 for i in incs if i.state == "resolved")),
            "incident_attributed": float(
                sum(1 for i in incs if i.attributed_to is not None)),
            "incident_unexplained": float(
                sum(1 for i in incs if i.attributed_to is None)),
            "incident_critical": float(
                sum(1 for i in incs if i.severity == "critical")),
            "incident_flaps_suppressed": float(self.flaps_suppressed),
        }


def correlate(records: Sequence[Dict],
              cfg: IncidentConfig = IncidentConfig(),
              synthetic_faults: Sequence[Dict] = ()) -> IncidentCorrelator:
    """Offline convenience: ingest a full record stream in order, register
    any soak-delivered synthetic faults (``{"event_id","kind","t","cleared_t"}``),
    finalize, return the correlator."""
    corr = IncidentCorrelator(cfg)
    for f in synthetic_faults:
        corr.register_fault(f["event_id"], f["kind"], f.get("t", 0.0),
                            cleared_t=f.get("cleared_t"))
    for rec in records:
        corr.ingest(rec)
    corr.finalize()
    return corr
