"""Semantic ``jax.named_scope`` annotations, globally toggleable.

Models and trainers wrap their phases in :func:`named_scope` so xplane traces
(and ``scripts/trace_report.py``) group op time by meaning — encoder forward,
autoregressive decode, GAE, PPO update — instead of a flat HLO op soup.
Scopes are applied at *trace* time only (zero steady-state cost); the
``--trace_named_scopes`` flag flips the module-level switch before anything
compiles, and disabling yields a no-op context manager.

The same scope sites double as value :func:`probe` points for nonfinite
bisection (``scripts/replay_bundle.py``): with no :class:`ProbeSink`
installed — the always case in training — ``probe`` returns before touching
jax, so compiled programs contain no callbacks.  Replay installs a sink and
re-runs the offending dispatch under ``jax.disable_jit()``, where
``jax.debug.callback`` fires eagerly and in program order, so the first
recorded nonfinite value names the first offending scope.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, List, Optional, Tuple

import jax

_ENABLED = True
_PROBE_SINK: Optional["ProbeSink"] = None


def set_named_scopes(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


def named_scopes_enabled() -> bool:
    return _ENABLED


def named_scope(name: str):
    """``jax.named_scope(name)`` when enabled, else a null context."""
    if _ENABLED:
        return jax.named_scope(name)
    return contextlib.nullcontext()


class ProbeSink:
    """Ordered collection of ``(scope_name, host_value)`` probe events."""

    def __init__(self):
        self.events: List[Tuple[str, Any]] = []

    def _record(self, name: str, value) -> None:
        import numpy as np

        self.events.append((name, jax.tree.map(np.asarray, value)))

    def mark(self, label: str) -> None:
        """Host-side phase marker (value ``None``; never nonfinite)."""
        self.events.append((label, None))

    def first_nonfinite(self) -> Optional[Tuple[str, Any]]:
        """First probe event containing a NaN/Inf leaf, or ``None``."""
        import numpy as np

        for name, value in self.events:
            if value is None:
                continue
            for leaf in jax.tree.leaves(value):
                arr = np.asarray(leaf)
                if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                    return name, arr
        return None


def set_probe_sink(sink: Optional[ProbeSink]) -> Optional[ProbeSink]:
    """Install (or clear, with ``None``) the global probe sink; returns the
    previous sink so callers can restore it."""
    global _PROBE_SINK
    prev = _PROBE_SINK
    _PROBE_SINK = sink
    return prev


def probe(name: str, value) -> None:
    """Record ``value`` under ``name`` when a sink is installed; no-op (and
    absent from compiled programs) otherwise.  Call at named-scope sites with
    the scope's name so bisection verdicts match trace_report.py rollups."""
    sink = _PROBE_SINK
    if sink is None:
        return
    jax.debug.callback(functools.partial(sink._record, name), value)
