"""Semantic ``jax.named_scope`` annotations, globally toggleable.

Models and trainers wrap their phases in :func:`named_scope` so xplane traces
(and ``scripts/trace_report.py``) group op time by meaning — encoder forward,
autoregressive decode, GAE, PPO update — instead of a flat HLO op soup.
Scopes are applied at *trace* time only (zero steady-state cost); the
``--trace_named_scopes`` flag flips the module-level switch before anything
compiles, and disabling yields a no-op context manager.
"""

from __future__ import annotations

import contextlib

import jax

_ENABLED = True


def set_named_scopes(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


def named_scopes_enabled() -> bool:
    return _ENABLED


def named_scope(name: str):
    """``jax.named_scope(name)`` when enabled, else a null context."""
    if _ENABLED:
        return jax.named_scope(name)
    return contextlib.nullcontext()
