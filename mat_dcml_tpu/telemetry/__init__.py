"""Unified observability layer: metric registry, jit instrumentation, scopes.

Podracer-style (arXiv:2104.06272) visible accounting for the collect/train
loop: a :class:`Telemetry` registry of counters/gauges/timers flushed into the
jsonl metrics stream, a recompile-detecting ``jax.jit`` wrapper, semantic
``jax.named_scope`` annotations for xplane traces, and device/host gauges.
Everything is dependency-free and jit-safe — host-side observation happens
only at call boundaries and flush time, never inside a trace.
"""

from mat_dcml_tpu.telemetry.async_fetch import DeferredFetch
from mat_dcml_tpu.telemetry.jit_instrument import InstrumentedJit, instrumented_jit
from mat_dcml_tpu.telemetry.registry import Telemetry
from mat_dcml_tpu.telemetry.scopes import named_scope, named_scopes_enabled, set_named_scopes
from mat_dcml_tpu.telemetry.system import device_memory_gauges, host_rss_bytes

__all__ = [
    "DeferredFetch",
    "InstrumentedJit",
    "Telemetry",
    "device_memory_gauges",
    "host_rss_bytes",
    "instrumented_jit",
    "named_scope",
    "named_scopes_enabled",
    "set_named_scopes",
]
