"""Unified observability layer: metric registry, jit instrumentation, scopes.

Podracer-style (arXiv:2104.06272) visible accounting for the collect/train
loop: a :class:`Telemetry` registry of counters/gauges/timers flushed into the
jsonl metrics stream, a recompile-detecting ``jax.jit`` wrapper, semantic
``jax.named_scope`` annotations for xplane traces, and device/host gauges.
Everything is dependency-free and jit-safe — host-side observation happens
only at call boundaries and flush time, never inside a trace.
"""

from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
from mat_dcml_tpu.telemetry.anomaly import (
    Anomaly,
    AnomalyConfig,
    AnomalyDetector,
    ProfilerWindow,
)
from mat_dcml_tpu.telemetry.async_fetch import DeferredFetch
from mat_dcml_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    load_bundle,
    pack_tree,
    unpack_tree,
)
from mat_dcml_tpu.telemetry.jit_instrument import InstrumentedJit, instrumented_jit
from mat_dcml_tpu.telemetry.propagate import (
    TRACEPARENT_HEADER,
    extract as extract_traceparent,
    format_traceparent,
    inject as inject_traceparent,
    parse_traceparent,
)
from mat_dcml_tpu.telemetry.incidents import (
    Incident,
    IncidentConfig,
    IncidentCorrelator,
    correlate,
)
from mat_dcml_tpu.telemetry.registry import HistogramSketch, Telemetry
from mat_dcml_tpu.telemetry.timeseries import (
    TIMESERIES_PATH,
    RollupConfig,
    RollupStore,
    merge_wires,
)
from mat_dcml_tpu.telemetry.remote import (
    RemoteScraper,
    TelemetrySidecar,
    build_snapshot,
    deserialize_telemetry,
    serialize_telemetry,
    snapshot_aggregator,
)
from mat_dcml_tpu.telemetry.scopes import (
    ProbeSink,
    named_scope,
    named_scopes_enabled,
    probe,
    set_named_scopes,
    set_probe_sink,
)
from mat_dcml_tpu.telemetry.slo import SLOConfig, SLOMonitor
from mat_dcml_tpu.telemetry.system import (
    device_memory_gauges,
    host_rss_bytes,
    replica_hbm_high_water_bytes,
)
from mat_dcml_tpu.telemetry.tracing import TraceContext, Tracer

__all__ = [
    "Anomaly",
    "AnomalyConfig",
    "AnomalyDetector",
    "DeferredFetch",
    "FlightRecorder",
    "HistogramSketch",
    "Incident",
    "IncidentConfig",
    "IncidentCorrelator",
    "InstrumentedJit",
    "ProbeSink",
    "ProfilerWindow",
    "RemoteScraper",
    "RollupConfig",
    "RollupStore",
    "SLOConfig",
    "SLOMonitor",
    "TIMESERIES_PATH",
    "TRACEPARENT_HEADER",
    "Telemetry",
    "TelemetryAggregator",
    "TelemetrySidecar",
    "TraceContext",
    "Tracer",
    "build_snapshot",
    "correlate",
    "deserialize_telemetry",
    "device_memory_gauges",
    "extract_traceparent",
    "format_traceparent",
    "host_rss_bytes",
    "inject_traceparent",
    "instrumented_jit",
    "load_bundle",
    "merge_wires",
    "named_scope",
    "named_scopes_enabled",
    "pack_tree",
    "parse_traceparent",
    "probe",
    "replica_hbm_high_water_bytes",
    "serialize_telemetry",
    "set_named_scopes",
    "set_probe_sink",
    "snapshot_aggregator",
    "unpack_tree",
]
