"""Future-style non-blocking device->host metric transfer.

The fused dispatch loop (``base_runner._train_loop_fused``) gets its per-
dispatch metrics back as a small pytree of stacked ``(K,)`` scalars.  Calling
``jax.device_get`` on it directly would block the host until the dispatch
finishes — exactly the per-iteration sync the fused path exists to remove.
:class:`DeferredFetch` instead starts the device->host copy asynchronously at
construction (right after the dispatch is enqueued) and defers the blocking
read to :meth:`get`, which the runner calls one dispatch later — so the host
formats and logs dispatch N-1's metrics while dispatch N runs on device, and
the only host-blocking time left is whatever compute is still in flight when
``get`` is finally called.
"""

from __future__ import annotations

from typing import Any

import jax


class DeferredFetch:
    """Starts an async device->host copy of ``tree``; ``get()`` blocks only
    on whatever is still outstanding and returns the numpy pytree."""

    def __init__(self, tree: Any):
        self._tree = tree
        self._start_error: Exception | None = None
        try:
            for leaf in jax.tree.leaves(tree):
                # jax.Array exposes copy_to_host_async; anything else (python
                # scalars in hand-built trees) is already on the host
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        except Exception as e:  # deleted/donated buffers, runtime errors: the
            # launch site must stay non-blocking, so surface it at get()
            self._start_error = e

    def get(self) -> Any:
        if self._start_error is not None:
            raise self._start_error
        return jax.device_get(self._tree)
