"""Host-side dispatch flight recorder: snapshot-before-donate repro bundles.

The fused dispatch donates its carried train/rollout state, so by the time a
tripwire fires (one dispatch *after* launch — metrics arrive via
:class:`~mat_dcml_tpu.telemetry.async_fetch.DeferredFetch`), the offending
device buffers are gone.  :class:`FlightRecorder` keeps a ring of the last
``depth`` *host* copies of the dispatch inputs — params, optimizer state,
rollout carry, the RNG key chain position — taken at a configurable cadence
BEFORE each dispatch launch (the only point where the buffers are still
valid), and on a trip dumps the newest snapshot at-or-before the offending
episode as a self-contained bundle under ``artifacts/``:

    bundle_ep<episode>_<kind>/
      manifest.json   # run/ppo config, algorithm, iters_per_dispatch,
                      # snapshot + target episodes, anomaly record, git hash,
                      # jax/python versions
      state.pkl       # packed (numpy) train_state / rollout_state / key
      reference.pkl   # metrics fetched at detection time (bit-exact target)
      env.pkl         # the env object, when picklable (self-contained replay)

``scripts/replay_bundle.py`` re-executes the captured dispatch from the
bundle alone and bisects the first nonfinite value by named scope.

Typed PRNG keys cannot round-trip through numpy directly
(``jax.device_get`` returns a ``PRNGKeyArray``); :func:`pack_tree` stores
them as :class:`PRNGKeyLeaf` (impl name + raw ``key_data``) and
:func:`unpack_tree` rebuilds them with ``jax.random.wrap_key_data`` —
bit-exact round trip.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import pickle
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np


@dataclasses.dataclass
class PRNGKeyLeaf:
    """Host-serializable typed PRNG key: impl name + raw key data."""

    impl: str
    data: np.ndarray


@dataclasses.dataclass
class WeakLeaf:
    """Host copy of a weak-typed array.  Weak-typedness is part of the aval
    jit caches on, so losing it across a pack/unpack round trip (numpy has no
    such notion) makes a resumed carry recompile the steady-state dispatch
    once — :func:`unpack_tree` rebuilds the weak aval instead."""

    data: np.ndarray


def _with_weak_type(arr):
    """Re-weaken an array's aval; best-effort (the hook is private jax)."""
    try:
        from jax._src.lax.lax import _convert_element_type

        return _convert_element_type(arr, arr.dtype, weak_type=True)
    except Exception:
        return arr  # aval stays strong: still correct, worst case one recompile


def pack_tree(tree: Any) -> Any:
    """Blocking device->host copy of a pytree, numpy leaves; typed PRNG keys
    become :class:`PRNGKeyLeaf`.  Safe to pickle."""
    import jax
    import jax.numpy as jnp

    def pack_leaf(x):
        # np.array(copy=True), not np.asarray: on the CPU backend device_get
        # can return a zero-copy VIEW of the XLA buffer, and the dispatch
        # about to launch donates that buffer — XLA then reuses the memory in
        # place and a view-based "snapshot" is silently clobbered before the
        # bundle is pickled.
        if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            return PRNGKeyLeaf(str(jax.random.key_impl(x)),
                               np.array(jax.random.key_data(x), copy=True))
        if isinstance(x, jax.Array) and getattr(x.aval, "weak_type", False):
            return WeakLeaf(np.array(jax.device_get(x), copy=True))
        if hasattr(x, "__array__") or isinstance(x, (bool, int, float, complex)):
            return np.array(jax.device_get(x), copy=True)
        return x

    return jax.tree.map(pack_leaf, tree)


def unpack_tree(tree: Any) -> Any:
    """Inverse of :func:`pack_tree`: numpy -> device arrays, key leaves ->
    typed PRNG keys."""
    import jax
    import jax.numpy as jnp

    def unpack_leaf(x):
        # copy=True: the rebuilt arrays feed donating dispatches (replay,
        # watchdog retries, emergency resume).  jnp.asarray can alias the
        # numpy buffer on the CPU backend, and donation would then write
        # into — and corrupt — the retained snapshot itself.
        if isinstance(x, PRNGKeyLeaf):
            return jax.random.wrap_key_data(jnp.array(x.data, copy=True),
                                            impl=x.impl)
        if isinstance(x, WeakLeaf):
            return _with_weak_type(jnp.array(x.data, copy=True))
        if isinstance(x, np.ndarray):
            return jnp.array(x, copy=True)
        if isinstance(x, (bool, int, float, complex)):
            return jnp.asarray(x)
        return x

    return jax.tree.map(unpack_leaf, tree,
                        is_leaf=lambda x: isinstance(x, (PRNGKeyLeaf, WeakLeaf)))


def git_hash(repo_root: Optional[Path] = None) -> str:
    root = repo_root or Path(__file__).resolve().parents[2]
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10,
        )
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


@dataclasses.dataclass
class Bundle:
    path: Path
    manifest: Dict[str, Any]
    state: Dict[str, Any]          # packed: episode / train_state / rollout_state / key
    reference: Optional[Dict[str, Any]]
    env: Any


def load_bundle(path) -> Bundle:
    path = Path(path)
    manifest = json.loads((path / "manifest.json").read_text())
    with open(path / "state.pkl", "rb") as f:
        state = pickle.load(f)
    reference = None
    if (path / "reference.pkl").exists():
        with open(path / "reference.pkl", "rb") as f:
            reference = pickle.load(f)
    env = None
    if (path / "env.pkl").exists():
        with open(path / "env.pkl", "rb") as f:
            env = pickle.load(f)
    return Bundle(path, manifest, state, reference, env)


class FlightRecorder:
    """Ring buffer of packed dispatch inputs + bundle dumping.

    ``depth=0`` disables everything (the default: zero steady-state cost).
    ``interval`` amortizes the blocking pack over that many snapshot calls —
    the runner calls :meth:`snapshot` once per episode/dispatch, immediately
    before launch, while the input buffers are still un-donated.
    """

    def __init__(self, depth: int, interval: int, directory,
                 run_config=None, ppo_config=None, env=None,
                 iters_per_dispatch: int = 1, telemetry=None, log=print):
        self.depth = int(depth)
        self.interval = max(1, int(interval))
        self.directory = Path(directory)
        self.run_config = run_config
        self.ppo_config = ppo_config
        self.env = env
        self.iters_per_dispatch = int(iters_per_dispatch)
        self.telemetry = telemetry
        self.log = log
        self._ring = collections.deque(maxlen=max(self.depth, 1))
        self._calls = 0
        self._dumped_kinds = set()

    @property
    def enabled(self) -> bool:
        return self.depth > 0

    # ------------------------------------------------------------- snapshot

    def snapshot(self, episode: int, train_state, rollout_state, key) -> bool:
        """Pack the dispatch inputs onto the ring (blocking device->host) at
        the configured cadence.  Returns True when a snapshot was taken."""
        if not self.enabled:
            return False
        take = self._calls % self.interval == 0
        self._calls += 1
        if not take:
            return False
        self._ring.append({
            "episode": int(episode),
            "train_state": pack_tree(train_state),
            "rollout_state": pack_tree(rollout_state),
            "key": pack_tree(key),
        })
        if self.telemetry is not None:
            self.telemetry.count("flight_snapshots")
        return True

    # ----------------------------------------------------------------- dump

    def dump(self, anomaly, target_episode: int,
             reference: Optional[Dict[str, Any]] = None) -> Optional[Path]:
        """Write the repro bundle for ``anomaly``: the newest snapshot whose
        episode is at or before ``target_episode`` (the first episode of the
        offending dispatch), once per anomaly kind per run."""
        if not self.enabled or not self._ring:
            return None
        if anomaly.kind in self._dumped_kinds:
            return None
        self._dumped_kinds.add(anomaly.kind)
        snap = None
        for cand in self._ring:
            if cand["episode"] <= target_episode:
                snap = cand  # ring is oldest->newest; keep the newest match
        if snap is None:
            snap = self._ring[0]
        out = self.directory / f"bundle_ep{target_episode}_{anomaly.kind}"
        out.mkdir(parents=True, exist_ok=True)
        manifest = {
            "run_config": dataclasses.asdict(self.run_config) if self.run_config else None,
            "ppo_config": dataclasses.asdict(self.ppo_config) if self.ppo_config else None,
            "algorithm_name": getattr(self.run_config, "algorithm_name", None),
            "iters_per_dispatch": self.iters_per_dispatch,
            "snapshot_episode": snap["episode"],
            "target_episode": int(target_episode),
            "anomaly": anomaly.to_record(),
            "git_hash": git_hash(),
            "jax_version": __import__("jax").__version__,
            "python_version": sys.version.split()[0],
        }
        (out / "manifest.json").write_text(json.dumps(manifest, indent=1, default=str))
        with open(out / "state.pkl", "wb") as f:
            pickle.dump(snap, f)
        if reference is not None:
            with open(out / "reference.pkl", "wb") as f:
                pickle.dump(reference, f)
        if self.env is not None:
            try:
                with open(out / "env.pkl", "wb") as f:
                    pickle.dump(self.env, f)
            except Exception as e:   # env holds unpicklable handles: still
                self.log(f"[flight] env not picklable ({e}); bundle replays "
                         f"only with a caller-built env")
        if self.telemetry is not None:
            self.telemetry.count("flight_bundles")
        self.log(f"[flight] repro bundle -> {out}")
        return out
