"""Fleet-wide telemetry aggregation + Prometheus text exposition.

Per-replica :class:`~mat_dcml_tpu.telemetry.registry.Telemetry` registries are
deliberately isolated (a replica's counters must survive its neighbour's
crash).  :class:`TelemetryAggregator` is the read-side merge: counters and
gauges sum across sources (fleet totals), histogram sketches merge exactly —
so the exported ``serving_decode_ms_p99`` is the honest fleet-wide tail, not
an average of per-replica p99s.

:meth:`TelemetryAggregator.prometheus_text` renders the merged view in the
Prometheus text exposition format (version 0.0.4): counters as ``counter``
with per-replica ``{replica="<label>"}`` breakdowns, gauges as ``gauge``,
histograms as ``summary`` with ``quantile`` labels.  ``PolicyServer`` serves
it at ``GET /metrics`` so a live soak run is scrapeable.

Read-only and lock-free: sources are sampled via dict copies, which is safe
against the recording side's plain assignments.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from .registry import HistogramSketch, Telemetry


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class TelemetryAggregator:
    """Merges N labelled ``Telemetry`` registries into one fleet view."""

    def __init__(self, sources: Optional[Iterable[Tuple[str, Telemetry]]] = None):
        self._sources: List[Tuple[str, Telemetry]] = list(sources or [])

    def add_source(self, label: str, tel: Telemetry) -> None:
        self._sources = [(l, t) for l, t in self._sources if l != label]
        self._sources.append((str(label), tel))

    @property
    def sources(self) -> List[Tuple[str, Telemetry]]:
        return list(self._sources)

    # --------------------------------------------------------------- merging

    def merged_counters(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for _, tel in self._sources:
            for name, v in dict(tel.counters).items():
                out[name] = out.get(name, 0.0) + v
        return out

    def merged_gauges(self) -> Dict[str, float]:
        """Gauges sum across replicas — fleet totals (queue depths,
        outstanding counts).  Non-additive gauges remain readable per-replica
        in the labelled Prometheus lines."""
        out: Dict[str, float] = {}
        for _, tel in self._sources:
            for name, v in dict(tel._gauges).items():
                out[name] = out.get(name, 0.0) + v
        return out

    def merged_hists(self) -> Dict[str, HistogramSketch]:
        out: Dict[str, HistogramSketch] = {}
        for _, tel in self._sources:
            for name, sk in dict(tel.hists).items():
                agg = out.get(name)
                if agg is None:
                    agg = out[name] = HistogramSketch()
                agg.merge(sk)
        return out

    def snapshot(self) -> Dict[str, float]:
        """Flat fleet-wide record fragment: summed counters and gauges plus
        ``_p50/_p95/_p99/_count/_mean`` for every merged histogram."""
        rec = self.merged_counters()
        rec.update(self.merged_gauges())
        for name, sk in self.merged_hists().items():
            if sk.count:
                rec.update(sk.snapshot(name))
        return rec

    # ------------------------------------------------------------ prometheus

    def prometheus_text(self, extra_gauges: Optional[Dict[str, float]] = None) -> str:
        lines: List[str] = []
        counters = self.merged_counters()
        for name in sorted(counters):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {counters[name]:.6g}")
            for label, tel in self._sources:
                v = tel.counters.get(name)
                if v is not None and len(self._sources) > 1:
                    lines.append(
                        f'{name}{{replica="{_prom_escape(label)}"}} {v:.6g}')
        gauges = self.merged_gauges()
        for name in sorted(gauges):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {gauges[name]:.6g}")
            for label, tel in self._sources:
                v = tel._gauges.get(name)
                if v is not None and len(self._sources) > 1:
                    lines.append(
                        f'{name}{{replica="{_prom_escape(label)}"}} {v:.6g}')
        for name, sk in sorted(self.merged_hists().items()):
            if not sk.count:
                continue
            lines.append(f"# TYPE {name} summary")
            for q in (0.5, 0.95, 0.99):
                lines.append(f'{name}{{quantile="{q}"}} {sk.quantile(q):.6g}')
            lines.append(f"{name}_sum {sk.total:.6g}")
            lines.append(f"{name}_count {sk.count}")
        for name in sorted(extra_gauges or {}):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(extra_gauges[name]):.6g}")
        return "\n".join(lines) + "\n"
