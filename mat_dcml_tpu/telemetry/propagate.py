"""Cross-process trace propagation: a W3C-traceparent-style header codec.

A trace minted client-side (``HttpPolicyClient`` in ``serving/server.py`` or
a loadgen dispatcher) crosses the HTTP boundary as one request header::

    traceparent: 00-<trace-id: 32 hex>-<parent-id: 16 hex>-<flags: 2 hex>

mirroring the W3C Trace Context wire format so any off-the-shelf proxy or
collector that understands ``traceparent`` interoperates.  The server side
(``PolicyServer._Handler.do_POST``) extracts the trace id and continues the
SAME trace through routing → queueing → decode via
``Tracer.continue_trace`` — the client's root span and the server's
``request`` span then share one trace id across two ``trace.jsonl`` files,
and ``scripts/obs_report.py`` stitches them back into one tree.

Internal trace ids are 16 lowercase hex chars (``uuid4().hex[:16]``); on the
wire they are left-padded to the 32-hex W3C width and stripped back on
extraction, so locally-minted and externally-minted (full-width) ids both
round-trip losslessly.

Sampling semantics: only sampled requests carry the header (an unsampled
request has no client trace to continue), so the ``sampled`` flag is ``01``
on everything we emit; extraction honors an explicit ``00`` by reporting no
trace — the upstream decided not to record.

Stdlib-only, no I/O: pure string codec plus dict/Message header helpers.
"""

from __future__ import annotations

import re
import uuid
from typing import Mapping, NamedTuple, Optional

TRACEPARENT_HEADER = "traceparent"

_VERSION = "00"
_HEX = re.compile(r"^[0-9a-f]+$")
_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


class TraceParent(NamedTuple):
    """Decoded header: ``trace_id`` in the repo's internal width (16 hex when
    the padded upper half is zero, the full 32 otherwise)."""

    trace_id: str
    parent_id: str
    sampled: bool


def format_traceparent(trace_id: str, parent_id: Optional[str] = None,
                       sampled: bool = True) -> str:
    """Render the header value for ``trace_id``.  ``parent_id`` identifies the
    client-side root span (minted fresh when omitted)."""
    tid = str(trace_id).lower()
    if not _HEX.match(tid) or len(tid) > 32:
        raise ValueError(f"trace id must be <=32 hex chars, got {trace_id!r}")
    pid = (parent_id or uuid.uuid4().hex[:16]).lower()
    if not _HEX.match(pid) or len(pid) > 16:
        raise ValueError(f"parent id must be <=16 hex chars, got {parent_id!r}")
    return (f"{_VERSION}-{tid.rjust(32, '0')}-{pid.rjust(16, '0')}-"
            f"{'01' if sampled else '00'}")


def parse_traceparent(value: Optional[str]) -> Optional[TraceParent]:
    """Decode a header value; ``None`` on anything malformed (a bad header
    must degrade to 'no trace', never to a 4xx/5xx)."""
    if not value:
        return None
    m = _TRACEPARENT.match(value.strip().lower())
    if m is None:
        return None
    version, tid32, pid, flags = m.groups()
    if version == "ff" or tid32 == "0" * 32 or pid == "0" * 16:
        return None
    # strip the pad back to the internal 16-hex width when the upper half is
    # zero; a genuinely 32-hex external id passes through whole
    tid = tid32[16:] if tid32[:16] == "0" * 16 else tid32
    return TraceParent(tid, pid, flags != "00")


def inject(headers: dict, trace) -> dict:
    """Add the traceparent header for ``trace`` (a ``TraceContext`` or a bare
    trace-id string) to a mutable header dict; no-op on ``None`` (unsampled
    request).  Returns ``headers`` for chaining."""
    trace_id = getattr(trace, "trace_id", trace)
    if trace_id:
        headers[TRACEPARENT_HEADER] = format_traceparent(str(trace_id))
    return headers


def extract(headers: Mapping[str, str]) -> Optional[str]:
    """Trace id from a request's headers (``http.server`` Message objects and
    plain dicts both expose ``.get``), or ``None`` when absent, malformed, or
    explicitly unsampled."""
    parsed = parse_traceparent(headers.get(TRACEPARENT_HEADER))
    if parsed is None or not parsed.sampled:
        return None
    return parsed.trace_id
