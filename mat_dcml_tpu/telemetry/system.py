"""Device and host resource gauges, dependency-free.

Sampled at flush boundaries only (host-side; never inside a trace).  Device
memory comes from the PJRT client's ``memory_stats()`` — populated on TPU/GPU,
``None`` on CPU, where the gauges degrade to 0 so the jsonl schema stays
stable across backends.  Host RSS reads ``/proc/self/statm`` (Linux) with a
``resource.getrusage`` peak-RSS fallback elsewhere.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax


def device_memory_gauges(device=None) -> Dict[str, int]:
    """``bytes_in_use`` / ``peak_bytes_in_use`` of one local device (0 when
    the backend exposes no allocator stats, e.g. CPU)."""
    try:
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats() or {}
    except Exception:
        stats = {}
    return {
        "device_bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "device_peak_bytes": int(stats.get("peak_bytes_in_use", 0)),
    }


def replica_hbm_high_water_bytes() -> Optional[int]:
    """Max ``peak_bytes_in_use`` over the LOCAL devices — the per-replica HBM
    high-water mark of a sharded run (each mesh position holds one replica of
    the params plus its data shard, so the max local peak is the number that
    decides whether a config fits the chip).  ``None`` when no local device
    exposes allocator stats (CPU)."""
    peaks = []
    try:
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            if "peak_bytes_in_use" in stats:
                peaks.append(int(stats["peak_bytes_in_use"]))
    except Exception:
        return None
    return max(peaks) if peaks else None


def host_rss_bytes() -> int:
    """Current resident set size of this process in bytes (0 if unknown)."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        pass
    try:
        import resource

        # ru_maxrss is *peak* RSS in KiB on Linux (bytes on macOS); close
        # enough for a fallback gauge.
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak if peak > 1 << 32 else peak * 1024)
    except Exception:
        return 0


def host_gauges() -> Dict[str, int]:
    return {"host_rss_bytes": host_rss_bytes()}
