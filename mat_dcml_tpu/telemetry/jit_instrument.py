"""Recompile-detecting ``jax.jit`` wrapper.

The trainers' two hot functions (``collect`` / ``train``) compile once at
warmup and must never recompile in steady state — a silent steady-state
recompile (shape drift, weak-type flip, python-scalar leak) is the classic
"why did steps/sec fall off a cliff" failure in JAX RL stacks.  This wrapper
makes every compile *visible*:

- explicit AOT compile cache keyed by the abstract signature of the call
  (treedef + per-leaf shape/dtype/weak-type), so compiles are counted and
  timed exactly — no heuristics;
- per-function and aggregate counters into a :class:`Telemetry` registry:
  ``compile_count``, ``compile_seconds_total``, ``compile_count_<name>``;
- after :meth:`InstrumentedJit.mark_steady` (the runner calls it once warmup
  is done), further compiles also bump ``steady_state_recompiles`` and log a
  loud warning naming the function;
- the compiler's analytic FLOP count for the compiled executable is kept on
  ``flops_per_call`` (the THOP hook of ``utils/profiling.py``, now free at
  compile time).

Any failure in the AOT path falls back to a plain ``jax.jit`` call — the
wrapper may under-count in that case but can never break training.

Buffer donation: extra ``jit_kwargs`` (notably ``donate_argnums``) pass
through to both the plain ``jax.jit`` and the AOT ``lower().compile()`` path,
so the fused dispatch can donate its carried train/rollout state without
losing recompile detection.  With donation configured the retry-with-same-args
fallback is disabled for the *executing* call — a donated input may already be
invalidated by the time an executable raises, and retrying would turn a loud
error into a confusing use-after-donation one.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import jax

from mat_dcml_tpu.telemetry.registry import Telemetry
from mat_dcml_tpu.utils.profiling import compiled_bytes, compiled_flops


def _collective_count(compiled) -> Optional[int]:
    """Number of cross-device reduction ops (all-reduce, i.e. ``psum``) in a
    compiled executable.  Prefers the compiler's cost_analysis keys; falls
    back to counting ``all-reduce`` ops in the optimized HLO text.  Best
    effort — returns None rather than raise."""
    try:
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):
            costs = costs[0] if costs else {}
        hits = [v for k, v in (costs or {}).items() if "all-reduce" in k.lower()]
        if hits:
            return int(sum(float(v) for v in hits))
    except Exception:
        pass
    try:
        text = compiled.as_text()
        return sum(
            line.count("all-reduce(") + line.count("all-reduce-start(")
            for line in text.splitlines()
        )
    except Exception:
        return None


#: HLO op names per collective kind, as they appear in optimized HLO text.
#: fsdp/tp param sharding turns matmuls into all-gather / reduce-scatter and
#: seq rings into collective-permute, so the all-reduce-only census above
#: under-describes a 4-axis program; this per-kind census feeds the
#: ``shard_param_collectives_<kind>`` gauges and the BENCH_FSDP expectation
#: table.
_COLLECTIVE_KINDS = {
    "all_reduce": ("all-reduce(", "all-reduce-start("),
    "all_gather": ("all-gather(", "all-gather-start("),
    "reduce_scatter": ("reduce-scatter(",),
    "collective_permute": ("collective-permute(", "collective-permute-start("),
    "all_to_all": ("all-to-all(",),
}


def _collective_kind_counts(compiled) -> Optional[dict]:
    """Per-kind census of collective ops in a compiled executable's optimized
    HLO text (``{kind: count}``, zero-count kinds omitted).  Best effort —
    returns None rather than raise."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    counts = {}
    for kind, needles in _COLLECTIVE_KINDS.items():
        n = sum(text.count(needle) for needle in needles)
        if n:
            counts[kind] = n
    return counts


def _abstract_signature(args, kwargs):
    """Hashable key matching jit's cache granularity for array-only calls:
    pytree structure + (shape, dtype, weak_type) per array leaf; python
    scalars key by type only (jit treats them as weak-typed values)."""
    leaves, treedef = jax.tree.flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((
                tuple(leaf.shape),
                str(leaf.dtype),
                bool(getattr(leaf, "weak_type", False)),
            ))
        else:
            sig.append(("py", type(leaf).__name__))
    return treedef, tuple(sig)


class InstrumentedJit:
    def __init__(
        self,
        fn: Callable,
        name: str,
        telemetry: Optional[Telemetry] = None,
        log_fn: Callable[[str], Any] = print,
        count_collectives: bool = False,
        **jit_kwargs,
    ):
        self._jit = jax.jit(fn, **jit_kwargs)
        self._donating = bool(jit_kwargs.get("donate_argnums") or
                              jit_kwargs.get("donate_argnames"))
        self.name = name
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.log = log_fn
        self._compiled = {}            # signature -> compiled executable | None
        self._steady = False
        self.compile_count = 0
        self.compile_seconds = 0.0
        self.flops_per_call: Optional[float] = None
        self.bytes_per_call: Optional[float] = None
        # sharded runs: number of cross-device reduction ops (all-reduce /
        # psum) in the compiled executable, counted at compile time (None
        # until a compile lands or when counting is off)
        self._count_collectives = bool(count_collectives)
        self.collectives_per_call: Optional[int] = None
        # per-kind collective census ({kind: count}, e.g. "all_gather") of
        # the same executable; None until a counted compile lands
        self.collective_kinds_per_call: Optional[dict] = None

    def mark_steady(self) -> None:
        """Warmup is over: any compile from now on is unexpected."""
        self._steady = True

    def _compile(self, key, args, kwargs):
        t0 = time.perf_counter()
        try:
            compiled = self._jit.lower(*args, **kwargs).compile()
        except Exception:
            compiled = None            # plain-jit fallback (still compiles there)
        dt = time.perf_counter() - t0
        self.compile_count += 1
        self.compile_seconds += dt
        tel = self.telemetry
        tel.count("compile_count")
        tel.count("compile_seconds_total", dt)
        tel.count(f"compile_count_{self.name}")
        if self._steady:
            tel.count("steady_state_recompiles")
            self.log(
                f"[telemetry] WARNING: steady-state recompile of '{self.name}' "
                f"(compile #{self.compile_count}, {dt:.2f}s) — check for shape/"
                f"dtype drift in its inputs"
            )
        if compiled is not None:
            flops = compiled_flops(compiled)
            if flops is not None:
                self.flops_per_call = flops
            nbytes = compiled_bytes(compiled)
            if nbytes is not None:
                self.bytes_per_call = nbytes
            if self._count_collectives:
                n = _collective_count(compiled)
                if n is not None:
                    self.collectives_per_call = n
                kinds = _collective_kind_counts(compiled)
                if kinds is not None:
                    self.collective_kinds_per_call = kinds
            self._maybe_dump_hlo(compiled)
        self._compiled[key] = compiled
        return compiled

    def _maybe_dump_hlo(self, compiled) -> None:
        """Write the optimized HLO text to ``$MAT_DCML_TPU_HLO_DIR/<name>.hlo.txt``
        when that env var is set — the input ``scripts/trace_report.py bytes``
        parses into a bytes-by-scope table.  Best-effort; never breaks a
        compile."""
        import os

        out_dir = os.environ.get("MAT_DCML_TPU_HLO_DIR")
        if not out_dir:
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"{self.name}_{self.compile_count}.hlo.txt"
            )
            with open(path, "w") as f:
                f.write(compiled.as_text())
            self.log(f"[telemetry] dumped optimized HLO to {path}")
        except Exception:
            pass

    def __call__(self, *args, **kwargs):
        try:
            key = _abstract_signature(args, kwargs)
        except Exception:
            return self._jit(*args, **kwargs)
        if key not in self._compiled:
            self._compile(key, args, kwargs)
        compiled = self._compiled[key]
        if compiled is None:
            return self._jit(*args, **kwargs)
        try:
            return compiled(*args, **kwargs)
        except Exception:
            # AOT executables are stricter than jit (committed devices,
            # layouts); never let instrumentation break the call.  Unless the
            # call donates buffers: the failed attempt may already have
            # invalidated its inputs, so retrying with the same args would
            # mask the real error behind a use-after-donation one.
            if self._donating:
                raise
            self._compiled[key] = None
            return self._jit(*args, **kwargs)


def instrumented_jit(
    fn: Callable,
    name: str,
    telemetry: Optional[Telemetry] = None,
    log_fn: Callable[[str], Any] = print,
    **jit_kwargs,
) -> InstrumentedJit:
    """Drop-in for ``jax.jit(fn)`` that counts and times every compile."""
    return InstrumentedJit(fn, name, telemetry, log_fn, **jit_kwargs)
