"""Request-scoped tracing: sampled span trees to a bounded ``trace.jsonl``.

A :class:`Tracer` mints a :class:`TraceContext` at ingress (the HTTP handler
in ``serving/server.py`` or the dispatch boundary in ``training/base_runner``)
and the context object is threaded through routing → queueing → decode.  Each
component records *contiguous* child spans against the context — for serving:
``queue_wait`` ``pad`` ``device_decode`` ``demux`` — so the children exactly
tile the root ``request`` span and their durations sum to the server-side
end-to-end latency (the tier-1 invariant pinned in ``tests/test_tracing.py``).
Retry/failover hops in the fleet record extra ``attempt`` spans under the same
trace id, so a failed-over request reads as one tree.

Sampling is deterministic counter-based: with ``sample=s`` every
``round(1/s)``-th started trace is kept, starting with the first, so tests
and short runs always capture at least one tree and the overhead of a
non-sampled request is one integer increment.  Records are flat jsonl lines::

    {"trace": "ab12..", "span": "device_decode", "parent": "request",
     "t_ms": 3.1, "dur_ms": 12.4, "kind": "serving", ...attrs}

``t_ms`` is the offset from trace start.  The file is bounded: when it grows
past ``max_mb`` it rotates once to ``trace.jsonl.1`` (same policy as
``MetricsWriter`` rotation).  Encoding reuses the numpy-safe default from
``utils.metrics`` so device scalars can ride along as span attributes.

Nothing here touches jax; recording is plain host Python, safe from any
thread, never from inside a traced function.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ..utils.metrics import _json_default


class TraceContext:
    """One sampled request/dispatch.  Thread-safe; spans may be recorded from
    the ingress thread, the batcher dispatch thread, and fleet callbacks."""

    def __init__(self, tracer: "Tracer", trace_id: str, kind: str,
                 root: str = "request"):
        self._tracer = tracer
        self.trace_id = trace_id
        self.kind = kind
        self.root = root
        self.t0 = time.perf_counter()
        self._spans: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._finished = False

    def add_span(self, name: str, start: float, end: float,
                 parent: Optional[str] = None, **attrs: Any) -> None:
        """Record a span with explicit ``time.perf_counter()`` boundaries.
        ``parent`` defaults to the root span."""
        rec = {
            "trace": self.trace_id,
            "span": name,
            "parent": self.root if parent is None else parent,
            "kind": self.kind,
            "t_ms": max(0.0, (start - self.t0) * 1e3),
            "dur_ms": max(0.0, (end - start) * 1e3),
        }
        rec.update(attrs)
        with self._lock:
            if not self._finished:
                self._spans.append(rec)

    def span(self, name: str, **attrs: Any):
        """Context manager measuring a child span around a ``with`` block."""
        return _SpanTimer(self, name, attrs)

    def finish(self, end: Optional[float] = None, **attrs: Any) -> None:
        """Close the root span and flush the tree.  Idempotent — error paths
        and done-callbacks may race; the first finish wins."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            t1 = time.perf_counter() if end is None else end
            root = {
                "trace": self.trace_id,
                "span": self.root,
                "parent": None,
                "kind": self.kind,
                "t_ms": 0.0,
                "dur_ms": max(0.0, (t1 - self.t0) * 1e3),
            }
            root.update(attrs)
            records = [root] + self._spans
            self._spans = []
        self._tracer._write(records)


class _SpanTimer:
    def __init__(self, ctx: TraceContext, name: str, attrs: Dict[str, Any]):
        self._ctx, self._name, self._attrs = ctx, name, attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ctx.add_span(self._name, self._t0, time.perf_counter(),
                           **self._attrs)
        return False


class Tracer:
    """Mints sampled trace contexts and owns the bounded ``trace.jsonl``.

    ``sample=0`` disables tracing entirely (``start_trace`` returns ``None``
    after one integer increment — the fast path the bench A/B measures).
    """

    def __init__(self, run_dir: Optional[str], sample: float = 0.0,
                 max_mb: float = 64.0, filename: str = "trace.jsonl"):
        self.sample = float(sample)
        self.period = int(round(1.0 / self.sample)) if self.sample > 0 else 0
        self.max_bytes = int(max_mb * 1024 * 1024) if max_mb else 0
        self.path = os.path.join(run_dir, filename) if run_dir else None
        self._n = 0
        self._bytes = 0
        self._fh = None
        self._lock = threading.Lock()
        self.traces_started = 0
        self.traces_continued = 0
        self.spans_written = 0
        # most recent SAMPLED trace id — the exemplar an anomaly/SLO-burn
        # record pins at trip time so incidents link to one concrete tree
        self.last_trace_id: Optional[str] = None

    # ------------------------------------------------------------- sampling

    def start_trace(self, kind: str = "serving", root: str = "request",
                    trace_id: Optional[str] = None) -> Optional[TraceContext]:
        """Return a context for every ``period``-th call (first included),
        ``None`` otherwise."""
        if self.period <= 0 or self.path is None:
            return None
        with self._lock:
            n = self._n
            self._n += 1
        if n % self.period != 0:
            return None
        self.traces_started += 1
        tid = trace_id or uuid.uuid4().hex[:16]
        self.last_trace_id = tid
        return TraceContext(self, tid, kind, root=root)

    def continue_trace(self, trace_id: str, kind: str = "serving",
                       root: str = "request") -> Optional[TraceContext]:
        """Continue a trace minted in ANOTHER process (telemetry/propagate.py
        header extraction at HTTP ingress).  The remote client already made
        the sampling decision — only propagated (= sampled) requests carry the
        header — so the local counter is bypassed: dropping the continuation
        here would orphan the client's root span.  Returns ``None`` only when
        this tracer has nowhere to write."""
        if self.path is None or not trace_id:
            return None
        self.traces_started += 1
        self.traces_continued += 1
        self.last_trace_id = trace_id
        return TraceContext(self, trace_id, kind, root=root)

    # -------------------------------------------------------------- writing

    def _write(self, records: List[Dict[str, Any]]) -> None:
        if self.path is None:
            return
        lines = "".join(
            json.dumps(r, default=_json_default) + "\n" for r in records
        )
        data = lines.encode("utf-8")
        with self._lock:
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._fh = open(self.path, "a")
                try:
                    self._bytes = os.path.getsize(self.path)
                except OSError:
                    self._bytes = 0
            if self.max_bytes and self._bytes + len(data) > self.max_bytes:
                self._rotate_locked()
            self._fh.write(lines)
            self._fh.flush()
            self._bytes += len(data)
            self.spans_written += len(records)

    def _rotate_locked(self) -> None:
        self._fh.close()
        rotated = self.path + ".1"
        if os.path.exists(rotated):
            os.remove(rotated)
        os.replace(self.path, rotated)
        self._fh = open(self.path, "a")
        self._bytes = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
