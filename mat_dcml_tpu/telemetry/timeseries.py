"""Bounded streaming time-series rollups: tiered rings of fixed-width windows.

A 24h unattended soak cannot ship its whole metrics.jsonl to a human — the
rollup store keeps a *bounded* trend view no matter how long the run lives:
fixed-width time windows in tiered rings (10s raw → 5min → 1h by default),
per-metric ``count/sum/min/max/last`` plus an **exact** per-window
:class:`~mat_dcml_tpu.telemetry.registry.HistogramSketch` delta for histogram
families.  Memory is capped by construction — ``slots`` windows per tier times
``max_series`` metrics — independent of run length.

Exactness contract (the property the federation tests pin):

- Cumulative counters and sketches are **diffed** against the last-seen state,
  so each window holds the *increment* that landed inside it.  Window delta
  sketches carry the cumulative ``vmin``/``vmax`` at window close; since those
  are monotone, merging every window of the run reproduces the cumulative
  sketch **bit-for-bit** (buckets/count/total add exactly; min/max of the
  monotone series equals the final value).
- Compaction *moves* data between tiers (a raw window evicted from its ring is
  merged into the covering coarse window and dropped from the fine tier), so
  any whole-store merge counts every observation exactly once.
- The wire form (:meth:`RollupStore.to_wire`) is canonical — sorted window
  starts, sorted metric names, sketches via ``HistogramSketch.to_dict`` — so
  a scrape → JSON → :func:`merge_wires` round trip is bit-identical to merging
  the live stores in process.

Closed raw windows drain as schema-typed ``ts_`` records (markers
``{"ts": "window"}`` / ``{"ts": "hist"}``) into a rotating
``timeseries.jsonl`` via the existing ``MetricsWriter``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mat_dcml_tpu.telemetry.registry import HistogramSketch, Telemetry

# GET path served by TelemetrySidecar / PolicyServer, federated by
# obs_collector.py with the same stale-never-zero / seq-guard semantics as
# /telemetry.json.
TIMESERIES_PATH = "/timeseries.json"

# (window width seconds, ring slots): 10s raw for 5 min, 5 min for 2 h,
# 1 h for a day — the whole store covers a 24h soak in ~72 windows.
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = (
    (10.0, 30),
    (300.0, 24),
    (3600.0, 24),
)


class _Agg:
    """Per-metric per-window aggregate; wire form is the 5-list
    ``[count, sum, min, max, last]``."""

    __slots__ = ("count", "sum", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0

    def update(self, value: float, last: Optional[float] = None) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.last = v if last is None else float(last)

    def merge(self, other: "_Agg", cross_source: bool = False) -> None:
        if other.count == 0:
            return
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        # time-ordered merges (tier compaction, oldest-first) keep the newer
        # window's last; cross-source merges sum, mirroring the aggregator's
        # gauge semantics
        self.last = self.last + other.last if cross_source else other.last

    def to_list(self) -> List[float]:
        return [self.count, self.sum, self.min, self.max, self.last]

    @classmethod
    def from_list(cls, vals: Sequence[float]) -> "_Agg":
        a = cls()
        a.count = int(vals[0])
        a.sum = float(vals[1])
        a.min = float(vals[2])
        a.max = float(vals[3])
        a.last = float(vals[4])
        return a


class _Window:
    __slots__ = ("start", "metrics", "hists")

    def __init__(self, start: float):
        self.start = start
        self.metrics: Dict[str, _Agg] = {}
        self.hists: Dict[str, HistogramSketch] = {}

    def merge(self, other: "_Window", cross_source: bool = False) -> None:
        for name, agg in other.metrics.items():
            mine = self.metrics.get(name)
            if mine is None:
                mine = self.metrics[name] = _Agg.from_list(agg.to_list())
            else:
                mine.merge(agg, cross_source=cross_source)
        for name, sk in other.hists.items():
            mine_sk = self.hists.get(name)
            if mine_sk is None:
                self.hists[name] = HistogramSketch.from_dict(sk.to_dict())
            else:
                mine_sk.merge(sk)


@dataclasses.dataclass(frozen=True)
class RollupConfig:
    tiers: Tuple[Tuple[float, int], ...] = DEFAULT_TIERS
    max_series: int = 192        # distinct scalar metric names tracked
    max_hist_series: int = 32    # distinct histogram families tracked

    def cap_bytes(self) -> int:
        """Analytic hard memory cap the store promises to stay under,
        independent of run length: every tier ring full, every window dense."""
        slots = sum(n for _, n in self.tiers)
        agg_bytes = 640                                   # dict entry + _Agg
        sketch_bytes = HistogramSketch.NBUCKETS * 40 + 1024
        per_window = (self.max_series * agg_bytes
                      + self.max_hist_series * sketch_bytes)
        # diff state: one float per scalar series + one bucket list per hist
        diff = self.max_series * 256 + self.max_hist_series * sketch_bytes
        return slots * per_window + diff + 65536


class RollupStore:
    """Tiered-ring rollup store with a hard memory cap.

    ``observe_telemetry`` diffs a cumulative :class:`Telemetry` registry into
    the current raw window; ``observe_record`` folds an already-flat metrics
    record in gauge-style.  Pass a fake ``time_fn`` (or explicit ``t``) to
    drive multi-hour streams deterministically in tests.
    """

    def __init__(self, cfg: RollupConfig = RollupConfig(),
                 time_fn: Callable[[], float] = time.time):
        self.cfg = cfg
        self._time_fn = time_fn
        # the training loop flushes while the sidecar's HTTP thread serves
        # scrape-driven samples of the same store
        self._lock = threading.RLock()
        # per tier: insertion-ordered {aligned_start: _Window}, oldest first
        self._tiers: List[Dict[float, _Window]] = [
            {} for _ in cfg.tiers
        ]
        self._last_counters: Dict[Tuple[str, str], float] = {}
        self._last_hists: Dict[Tuple[str, str], Dict] = {}
        self._pending: List[Dict] = []
        self.series_dropped = 0
        self.windows_closed = 0
        self.windows_expired = 0
        self.compactions = 0
        self._series: set = set()
        self._hist_series: set = set()

    # ------------------------------------------------------------- ingestion

    def observe_telemetry(self, tel: Telemetry, t: Optional[float] = None,
                          source: str = "") -> None:
        """Diff a cumulative registry into the window covering ``t``:
        counters/hists contribute their increment since the previous call for
        the same ``source``; gauges contribute their current value."""
        t = self._time_fn() if t is None else float(t)
        with self._lock:
            w = self._window_for(t)
            for name, v in dict(tel.counters).items():
                key = (source, name)
                delta = float(v) - self._last_counters.get(key, 0.0)
                self._last_counters[key] = float(v)
                self._update(w, name, delta, last=float(v))
            for name, v in dict(tel._gauges).items():
                self._update(w, name, float(v))
            for name, sk in dict(tel.hists).items():
                if not self._admit_hist(name):
                    continue
                key = (source, name)
                prev = self._last_hists.get(key)
                dsk = HistogramSketch()
                if prev is None:
                    dsk.buckets = list(sk.buckets)
                    dsk.count = sk.count
                    dsk.total = sk.total
                else:
                    dsk.buckets = [c - p
                                   for c, p in zip(sk.buckets, prev["buckets"])]
                    dsk.count = sk.count - prev["count"]
                    dsk.total = sk.total - prev["total"]
                # cumulative min/max at window close: monotone, so whole-run
                # merge of window deltas reproduces the cumulative sketch
                # exactly
                dsk.vmin = sk.vmin
                dsk.vmax = sk.vmax
                self._last_hists[key] = {
                    "buckets": list(sk.buckets), "count": sk.count,
                    "total": sk.total,
                }
                if dsk.count > 0:
                    mine = w.hists.get(name)
                    if mine is None:
                        w.hists[name] = dsk
                    else:
                        mine.merge(dsk)

    def observe_record(self, record: Dict, t: Optional[float] = None) -> None:
        """Fold a flat metrics record in gauge-style (no diffing): each finite
        numeric field updates the covering raw window."""
        t = self._time_fn() if t is None else float(t)
        with self._lock:
            w = self._window_for(t)
            for name, v in record.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                self._update(w, name, float(v))

    def _admit(self, name: str) -> bool:
        if name in self._series:
            return True
        if len(self._series) >= self.cfg.max_series:
            self.series_dropped += 1
            return False
        self._series.add(name)
        return True

    def _admit_hist(self, name: str) -> bool:
        if name in self._hist_series:
            return True
        if len(self._hist_series) >= self.cfg.max_hist_series:
            self.series_dropped += 1
            return False
        self._hist_series.add(name)
        return True

    def _update(self, w: _Window, name: str, value: float,
                last: Optional[float] = None) -> None:
        if not self._admit(name):
            return
        agg = w.metrics.get(name)
        if agg is None:
            agg = w.metrics[name] = _Agg()
        agg.update(value, last=last)

    # ----------------------------------------------------- windows and tiers

    def _align(self, t: float, tier: int) -> float:
        width = self.cfg.tiers[tier][0]
        return float(int(t // width) * width)

    def _window_for(self, t: float) -> _Window:
        ring = self._tiers[0]
        start = self._align(t, 0)
        w = ring.get(start)
        if w is not None:
            return w
        if ring:
            newest = next(reversed(ring))
            if start < newest:
                # late record: fold into the oldest retained window — never
                # reopen a closed one (its ts_ records already drained)
                return ring[next(iter(ring))]
            self._close_raw(ring[newest])
        w = ring[start] = _Window(start)
        self._evict()
        return w

    def _close_raw(self, w: _Window) -> None:
        """Queue schema-typed ``ts_`` records for a finished raw window."""
        self.windows_closed += 1
        width = self.cfg.tiers[0][0]
        for name in sorted(w.metrics):
            a = w.metrics[name]
            self._pending.append({
                "ts": "window", "tier": 0, "width_s": width,
                "start_s": w.start, "metric": name,
                "ts_count": a.count, "ts_sum": a.sum, "ts_min": a.min,
                "ts_max": a.max, "ts_last": a.last,
            })
        for name in sorted(w.hists):
            self._pending.append({
                "ts": "hist", "tier": 0, "width_s": width,
                "start_s": w.start, "metric": name,
                "ts_sketch": w.hists[name].to_dict(),
            })

    def _evict(self) -> None:
        for i, (_, slots) in enumerate(self.cfg.tiers):
            ring = self._tiers[i]
            while len(ring) > slots:
                oldest_start = next(iter(ring))
                w = ring.pop(oldest_start)
                if i + 1 < len(self.cfg.tiers):
                    # MOVE into the covering coarse window — never copy, so
                    # a whole-store merge counts each observation once
                    cstart = self._align(oldest_start, i + 1)
                    coarse = self._tiers[i + 1].get(cstart)
                    if coarse is None:
                        coarse = self._tiers[i + 1][cstart] = _Window(cstart)
                    coarse.merge(w)
                    self.compactions += 1
                else:
                    self.windows_expired += 1

    def drain_records(self) -> List[Dict]:
        """Typed ``ts_`` records for raw windows closed since the last drain."""
        with self._lock:
            out, self._pending = self._pending, []
        return out

    # ------------------------------------------------------------ accounting

    def gauges(self) -> Dict[str, float]:
        return {
            "ts_series": float(len(self._series) + len(self._hist_series)),
            "ts_series_dropped": float(self.series_dropped),
            "ts_windows_open": float(sum(len(r) for r in self._tiers)),
            "ts_windows_closed": float(self.windows_closed),
            "ts_windows_expired": float(self.windows_expired),
            "ts_compactions": float(self.compactions),
        }

    def estimate_bytes(self) -> int:
        """Actual retained-state footprint (recursive getsizeof over windows,
        aggregates, sketches, and diff state)."""
        import sys
        n = 0
        for ring in self._tiers:
            n += sys.getsizeof(ring)
            for w in ring.values():
                n += sys.getsizeof(w) + sys.getsizeof(w.metrics)
                for name, a in w.metrics.items():
                    n += sys.getsizeof(name) + sys.getsizeof(a) + 5 * 32
                n += sys.getsizeof(w.hists)
                for name, sk in w.hists.items():
                    n += sys.getsizeof(name) + sys.getsizeof(sk)
                    n += sys.getsizeof(sk.buckets) + len(sk.buckets) * 32
        for key, v in self._last_counters.items():
            n += sys.getsizeof(key) + sys.getsizeof(v)
        for key, st in self._last_hists.items():
            n += sys.getsizeof(key) + len(st["buckets"]) * 32 + 256
        return n

    # ------------------------------------------------------------- wire form

    def to_wire(self) -> Dict:
        """Canonical JSON-safe snapshot: sorted starts, sorted metric names,
        exact sketch dicts.  ``from_wire``/``merge_wires`` round-trip this
        bit-for-bit (floats survive JSON by repr round-trip)."""
        with self._lock:
            return self._to_wire_locked()

    def _to_wire_locked(self) -> Dict:
        tiers = []
        for i, (width, slots) in enumerate(self.cfg.tiers):
            windows = []
            for start in sorted(self._tiers[i]):
                w = self._tiers[i][start]
                windows.append({
                    "start_s": start,
                    "metrics": {name: w.metrics[name].to_list()
                                for name in sorted(w.metrics)},
                    "hists": {name: w.hists[name].to_dict()
                              for name in sorted(w.hists)},
                })
            tiers.append({"width_s": width, "slots": slots,
                          "windows": windows})
        return {"tiers": tiers, "series_dropped": self.series_dropped}

    @classmethod
    def from_wire(cls, wire: Dict,
                  time_fn: Callable[[], float] = time.time) -> "RollupStore":
        tiers = tuple((float(t["width_s"]), int(t["slots"]))
                      for t in wire.get("tiers", ())) or DEFAULT_TIERS
        store = cls(RollupConfig(tiers=tiers), time_fn=time_fn)
        store.series_dropped = int(wire.get("series_dropped", 0))
        for i, t in enumerate(wire.get("tiers", ())):
            for wd in t.get("windows", ()):
                w = _Window(float(wd["start_s"]))
                for name, vals in wd.get("metrics", {}).items():
                    w.metrics[name] = _Agg.from_list(vals)
                    store._series.add(name)
                for name, d in wd.get("hists", {}).items():
                    w.hists[name] = HistogramSketch.from_dict(d)
                    store._hist_series.add(name)
                store._tiers[i][w.start] = w
        return store

    def merged_window(self) -> _Window:
        """Every retained observation merged into one window (whole-run view;
        exact because compaction moves rather than copies).  Coarse tiers
        hold strictly older windows than fine ones, so merging coarse-first
        keeps the time-ordered ``last`` semantics of :meth:`_Agg.merge`."""
        total = _Window(0.0)
        for ring in reversed(self._tiers):
            for w in ring.values():
                total.merge(w)
        return total


def merge_wires(wires: Sequence[Dict]) -> Dict:
    """Merge rollup wire snapshots from several sources into one canonical
    wire, aligning windows by (tier width, start).  Deterministic in the
    given order; applying it to scraped JSON is bit-identical to merging the
    live stores in process (the federation contract)."""
    wires = [w for w in wires if w]
    if not wires:
        return {"tiers": [], "series_dropped": 0}
    base = RollupStore.from_wire(wires[0])
    for other_wire in wires[1:]:
        other = RollupStore.from_wire(other_wire)
        for i, ring in enumerate(other._tiers):
            if i >= len(base._tiers):
                break
            for start, w in ring.items():
                mine = base._tiers[i].get(start)
                if mine is None:
                    base._tiers[i][start] = w
                else:
                    mine.merge(w, cross_source=True)
            # keep ring ordering canonical after out-of-order inserts
            base._tiers[i] = {
                s: base._tiers[i][s] for s in sorted(base._tiers[i])
            }
        base.series_dropped += other.series_dropped
    return base.to_wire()
