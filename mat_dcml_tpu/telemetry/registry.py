"""Counter / gauge / timer registry with per-interval aggregation.

One :class:`Telemetry` instance rides along a training run.  Host code records
observations between metric flushes; :meth:`Telemetry.flush` collapses them
into a flat ``{name: scalar}`` dict that merges into the jsonl record the
existing :class:`~mat_dcml_tpu.utils.metrics.MetricsWriter` already streams,
so BENCH tooling consumes telemetry unchanged.

Semantics:

- **counters** are cumulative for the life of the run (``compile_count``,
  ``nonfinite_grad_steps``, ...) and emitted as-is on every flush.  Counters
  registered with :meth:`rate` additionally emit a ``*_per_sec`` rate over the
  flush interval (used for env/agent-step throughput).
- **gauges** are last-value-wins samples (device memory, host RSS).
- **observations** (incl. :meth:`timer`) aggregate per flush interval: the
  mean is emitted under the bare name plus ``<name>_max`` and ``<name>_sum``,
  then the series resets.
- **once** values appear in exactly one flush (``flops_per_step``).
- **histograms** (:meth:`hist`) are cumulative log-spaced sketches emitting
  ``<name>_p50/_p95/_p99/_count/_mean`` on every flush; sketches from
  different replicas merge exactly, which is what makes fleet-wide
  percentiles honest (see :class:`HistogramSketch`).

Per-dispatch rate accounting (``--iters_per_dispatch K > 1``): the fused
runner counts ``env_steps`` in bursts of ``K * T * E`` when a dispatch's
results *arrive* (not when it is enqueued — launches are async and would
front-run the device), and re-anchors the rate clock via
:meth:`start_interval` once warmup compilation is done, so the first flushed
``*_per_sec`` rates measure steady-state throughput instead of averaging over
the one large fused compile.  Counters therefore arrive in bursts at dispatch
cadence; rates stay exact because both the delta and the interval are taken
at the same flush boundary.

Nothing here touches jax: recording is plain Python and safe to call from
anywhere on the host, but never from inside a traced function.
"""

from __future__ import annotations

import contextlib
import math
import time
from typing import Dict, List, Optional


class HistogramSketch:
    """Mergeable log-spaced histogram for latency quantiles.

    Buckets are geometric: bucket ``i`` covers ``[lo * base**i, lo * base**(i+1))``
    with ``base ≈ 1.2`` (≤ ~10% relative quantile error), which is what makes
    per-replica sketches *mergeable* into honest fleet-wide percentiles —
    unlike averaging per-replica p99s.  Values are clamped into the tracked
    range; exact observed min/max are kept so tail quantiles never report a
    value outside what was actually seen.  Cumulative for the life of the run.
    """

    LO = 1e-3      # 1 microsecond, in ms units
    BASE = 1.2
    NBUCKETS = 126  # covers ~1e-3 .. ~8.8e6 ms

    def __init__(self):
        self.buckets: List[int] = [0] * self.NBUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def _index(self, value: float) -> int:
        if value <= self.LO:
            return 0
        i = int(math.log(value / self.LO) / math.log(self.BASE))
        return min(max(i, 0), self.NBUCKETS - 1)

    def add(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):
            return
        self.buckets[self._index(v)] += 1
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)

    def merge(self, other: "HistogramSketch") -> None:
        for i, n in enumerate(other.buckets):
            self.buckets[i] += n
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                # geometric midpoint of the bucket, clamped to observed range
                mid = self.LO * (self.BASE ** (i + 0.5))
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    def snapshot(self, name: str) -> Dict[str, float]:
        """Flat record fragment: ``<name>_p50/_p95/_p99/_count/_mean``."""
        return {
            name + "_p50": self.quantile(0.50),
            name + "_p95": self.quantile(0.95),
            name + "_p99": self.quantile(0.99),
            name + "_count": float(self.count),
            name + "_mean": self.mean,
        }

    # ------------------------------------------------------- wire round-trip

    def to_dict(self) -> Dict:
        """Exact JSON-safe state: the five fields ``merge``/``quantile`` read.
        Python floats survive a JSON round-trip bit-for-bit (repr round-trip),
        so a sketch merged after ``to_dict``/``from_dict`` yields the SAME
        quantiles as merging the live objects — the property the remote scrape
        plane depends on.  The empty-sketch sentinels (``vmin=inf``,
        ``vmax=-inf``) encode as ``null`` since strict JSON has no Inf."""
        return {
            "buckets": list(self.buckets),
            "count": self.count,
            "total": self.total,
            "vmin": None if self.count == 0 else self.vmin,
            "vmax": None if self.count == 0 else self.vmax,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "HistogramSketch":
        sk = cls()
        buckets = [int(n) for n in d.get("buckets", [])]
        # pad/clip so sketches from a build with a different NBUCKETS merge
        # instead of raising; extra tail buckets collapse into the last one
        if len(buckets) > cls.NBUCKETS:
            head, tail = buckets[: cls.NBUCKETS], buckets[cls.NBUCKETS:]
            head[-1] += sum(tail)
            buckets = head
        sk.buckets = buckets + [0] * (cls.NBUCKETS - len(buckets))
        sk.count = int(d.get("count", 0))
        sk.total = float(d.get("total", 0.0))
        vmin, vmax = d.get("vmin"), d.get("vmax")
        sk.vmin = math.inf if vmin is None else float(vmin)
        sk.vmax = -math.inf if vmax is None else float(vmax)
        return sk


class Telemetry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._obs: Dict[str, List[float]] = {}
        self._once: Dict[str, float] = {}
        self._rates: Dict[str, str] = {}            # counter name -> rate name
        self.hists: Dict[str, HistogramSketch] = {}
        self._last_flush: Optional[float] = None
        self._counters_at_flush: Dict[str, float] = {}

    # ------------------------------------------------------------- recording

    def count(self, name: str, n: float = 1.0) -> None:
        if self.enabled:
            self.counters[name] = self.counters.get(name, 0.0) + n

    def gauge(self, name: str, value: float) -> None:
        if self.enabled:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        if self.enabled:
            self._obs.setdefault(name, []).append(float(value))

    def hist(self, name: str, value: float) -> None:
        """Record into a mergeable log-spaced histogram (cumulative for the
        run; flush emits ``<name>_p50/_p95/_p99/_count/_mean``)."""
        if self.enabled:
            sk = self.hists.get(name)
            if sk is None:
                sk = self.hists[name] = HistogramSketch()
            sk.add(value)

    def once(self, name: str, value: float) -> None:
        """Record a value emitted in the next flush only."""
        if self.enabled:
            self._once[name] = float(value)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def rate(self, counter_name: str, rate_name: str) -> None:
        """Emit ``rate_name`` = delta(counter) / flush-interval seconds."""
        self._rates[counter_name] = rate_name

    # --------------------------------------------------------------- flushing

    def start_interval(self) -> None:
        """(Re)anchor the rate clock — call once right before the loop starts
        so the first flush's rates exclude setup/compile time spent earlier."""
        self._last_flush = time.perf_counter()
        self._counters_at_flush = dict(self.counters)

    def flush(self) -> Dict[str, float]:
        """Aggregate the interval and return a flat record fragment.

        Counters persist (cumulative); gauges persist (last value); observed
        series and once-values reset.
        """
        if not self.enabled:
            return {}
        now = time.perf_counter()
        rec: Dict[str, float] = {}
        for name, v in self.counters.items():
            rec[name] = v
        dt = (now - self._last_flush) if self._last_flush is not None else None
        for cname, rname in self._rates.items():
            delta = self.counters.get(cname, 0.0) - self._counters_at_flush.get(cname, 0.0)
            rec[rname] = (delta / dt) if dt and dt > 0 else 0.0
        rec.update(self._gauges)
        for name, series in self._obs.items():
            rec[name] = sum(series) / len(series)
            rec[name + "_max"] = max(series)
            rec[name + "_sum"] = sum(series)
        for name, sk in self.hists.items():
            if sk.count:
                rec.update(sk.snapshot(name))
        rec.update(self._once)
        self._obs.clear()
        self._once.clear()
        self._last_flush = now
        self._counters_at_flush = dict(self.counters)
        return rec
