"""Remote telemetry federation: exact snapshot wire format, sidecar, scraper.

The in-process :class:`~mat_dcml_tpu.telemetry.aggregate.TelemetryAggregator`
merges live ``Telemetry`` references; this module extends the same exact-merge
semantics across process boundaries:

- :func:`serialize_telemetry` / :func:`deserialize_telemetry` round-trip a
  registry's counters, gauges, and :class:`HistogramSketch` state through
  JSON **losslessly** (the sketch's five merge-relevant fields travel as-is,
  so a remotely merged p50/p95/p99 is bit-for-bit identical to merging the
  live objects — NOT a re-parse of Prometheus text, which rounds to 6
  significant digits).
- :func:`build_snapshot` shapes the ``GET /telemetry.json`` payload: labelled
  per-source registries, a **monotonic** per-process ``seq``, a wall-clock
  stamp, and the supervisor's ``run_id``/``incarnation`` lineage when the
  process runs under ``scripts/train_supervisor.py``.
- :class:`TelemetrySidecar` is the opt-in stdlib HTTP thread
  (``--obs_port`` in training, built into ``PolicyServer`` for serving) that
  exposes that payload, so every process in a soak joins one scrape plane.
- :class:`RemoteScraper` polls N endpoints, keeps the **latest snapshot per
  source label** (a restart replaces the entry — seq going backwards is the
  restart signal — so cumulative counters are never double-counted), marks
  dead sources stale instead of zeroing them, and exposes the merged view
  through a plain ``TelemetryAggregator``.

Everything is stdlib (urllib + http.server); nothing touches jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .aggregate import TelemetryAggregator
from .registry import HistogramSketch, Telemetry
from .timeseries import TIMESERIES_PATH, RollupStore, merge_wires

SNAPSHOT_PATH = "/telemetry.json"

# supervisor-minted lineage (scripts/train_supervisor.py exports these into
# every child so relaunches of one logical run are queryable as one run)
RUN_ID_ENV = "MAT_DCML_RUN_ID"
INCARNATION_ENV = "MAT_DCML_INCARNATION"


def run_identity() -> Dict[str, object]:
    """``{"run_id": ..., "incarnation": ...}`` from the supervisor env vars,
    empty when not running under the supervisor."""
    out: Dict[str, object] = {}
    rid = os.environ.get(RUN_ID_ENV)
    if rid:
        out["run_id"] = rid
    inc = os.environ.get(INCARNATION_ENV)
    if inc is not None and inc.isdigit():
        out["incarnation"] = int(inc)
    return out


# ------------------------------------------------------------ wire round-trip


def serialize_telemetry(tel: Telemetry) -> Dict:
    """One registry as exact JSON: counters/gauges verbatim, sketches via
    :meth:`HistogramSketch.to_dict`.  Dict copies make this safe against the
    recording side's plain assignments (same policy as the aggregator)."""
    return {
        "counters": dict(tel.counters),
        "gauges": dict(tel._gauges),
        "hists": {name: sk.to_dict() for name, sk in dict(tel.hists).items()},
    }


def deserialize_telemetry(data: Dict) -> Telemetry:
    """Rebuild a ``Telemetry`` holder an aggregator can consume as a source.
    The holder is read-side only — flushing it would restart interval state —
    but counters/gauges/hists carry the exact remote values."""
    tel = Telemetry()
    tel.counters = {str(k): float(v)
                    for k, v in (data.get("counters") or {}).items()}
    tel._gauges = {str(k): float(v)
                   for k, v in (data.get("gauges") or {}).items()}
    tel.hists = {str(k): HistogramSketch.from_dict(v)
                 for k, v in (data.get("hists") or {}).items()}
    return tel


def build_snapshot(source: str, sources: Iterable[Tuple[str, Telemetry]],
                   seq: int, extra_gauges: Optional[Dict[str, float]] = None,
                   ) -> Dict:
    """The ``GET /telemetry.json`` payload: every labelled registry of this
    process serialized exactly, under a monotonic ``seq`` (scrape-side restart
    detection) and the supervisor lineage."""
    snap: Dict = {
        "source": str(source),
        "seq": int(seq),
        "time_s": time.time(),
        "sources": {label: serialize_telemetry(tel)
                    for label, tel in sources},
    }
    if extra_gauges:
        snap["extra_gauges"] = {k: float(v) for k, v in extra_gauges.items()}
    snap.update(run_identity())
    return snap


def snapshot_aggregator(snapshots: Iterable[Dict]) -> TelemetryAggregator:
    """Aggregator over deserialized snapshots, each sub-source labelled
    ``<snapshot source>/<sub label>`` so two processes' batcher registries
    stay distinct.  This is the in-process reference merge the collector's
    remote merge is tested bit-for-bit against."""
    agg = TelemetryAggregator()
    for snap in snapshots:
        src = str(snap.get("source", "?"))
        for label, data in (snap.get("sources") or {}).items():
            agg.add_source(f"{src}/{label}", deserialize_telemetry(data))
    return agg


# ------------------------------------------------------------------- sidecar


class _SidecarHandler(BaseHTTPRequestHandler):
    server_version = "mat-dcml-obs/1"

    def log_message(self, fmt, *args):
        self.server.log_fn("[obs] " + fmt % args)

    def do_GET(self):
        sidecar: "TelemetrySidecar" = self.server.sidecar
        if self.path == SNAPSHOT_PATH:
            body = json.dumps(sidecar.snapshot()).encode()
        elif self.path == TIMESERIES_PATH and sidecar.rollup is not None:
            body = json.dumps(sidecar.timeseries_snapshot()).encode()
        elif self.path == "/healthz":
            body = json.dumps({"ok": True, "source": sidecar.label}).encode()
        else:
            body = json.dumps({"error": f"no route {self.path}"}).encode()
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TelemetrySidecar:
    """Opt-in stdlib HTTP thread exposing a process's registries at
    ``/telemetry.json`` so training/loadgen processes join the scrape plane
    (``PolicyServer`` serves the same payload natively).

    ``sources`` may be a single ``Telemetry``, a ``{label: Telemetry}`` dict,
    or a zero-arg callable returning ``[(label, tel), ...]`` for processes
    whose source set changes (a fleet gaining replicas).  Each served
    snapshot bumps ``obs_snapshot_requests`` on the first registry and a
    process-monotonic ``seq``."""

    def __init__(self, sources, port: int = 0, host: str = "127.0.0.1",
                 label: str = "trainer",
                 extra_gauges_fn: Optional[Callable[[], Dict]] = None,
                 rollup: Optional[RollupStore] = None,
                 log_fn=print):
        if isinstance(sources, Telemetry):
            sources = {label: sources}
        if isinstance(sources, dict):
            fixed = [(str(k), v) for k, v in sources.items()]
            self._sources_fn = lambda: fixed
        else:
            self._sources_fn = sources
        self.label = label
        self.extra_gauges_fn = extra_gauges_fn
        self.rollup = rollup
        self._seq = 0
        self._ts_seq = 0
        self._seq_lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), _SidecarHandler)
        self._httpd.sidecar = self
        self._httpd.log_fn = log_fn
        self._thread: Optional[threading.Thread] = None
        self.log_fn = log_fn

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def snapshot(self) -> Dict:
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        sources = list(self._sources_fn())
        if sources:
            sources[0][1].count("obs_snapshot_requests")
        extra = self.extra_gauges_fn() if self.extra_gauges_fn else None
        return build_snapshot(self.label, sources, seq, extra_gauges=extra)

    def timeseries_snapshot(self) -> Dict:
        """The ``GET /timeseries.json`` payload: scrape-driven sampling —
        each request diffs the live registries into the rollup store, then
        serves its canonical wire under a monotonic ``seq`` (same restart
        detection as the telemetry snapshot)."""
        with self._seq_lock:
            self._ts_seq += 1
            seq = self._ts_seq
            for label, tel in self._sources_fn():
                self.rollup.observe_telemetry(tel, source=label)
            wire = self.rollup.to_wire()
        snap: Dict = {
            "source": self.label,
            "seq": seq,
            "time_s": time.time(),
            "rollup": wire,
        }
        snap.update(run_identity())
        return snap

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-sidecar", daemon=True)
        self._thread.start()
        self.log_fn(f"[obs] telemetry sidecar on "
                    f"http://{self._httpd.server_address[0]}:{self.port}"
                    f"{SNAPSHOT_PATH} (source={self.label})")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ------------------------------------------------------------------- scraper


class _Source:
    """Scrape-side state for one endpoint: the latest accepted snapshot plus
    liveness bookkeeping."""

    def __init__(self, label: str, url: str):
        self.label = label
        self.url = url
        self.snapshot: Optional[Dict] = None
        self.seq: Optional[int] = None
        self.last_ok_s: Optional[float] = None
        self.stale = True            # never scraped = stale, not zero
        self.errors = 0
        self.restarts = 0
        # /timeseries.json federation rides the same stale-never-zero /
        # seq-guard state, with its own last-accepted wire + seq
        self.ts_snapshot: Optional[Dict] = None
        self.ts_seq: Optional[int] = None
        self.last_duration_ms: Optional[float] = None


class RemoteScraper:
    """Polls N ``/telemetry.json`` endpoints and maintains the merged view.

    Degradation contract: a dead source keeps its **last accepted snapshot**
    and is marked stale (``mark stale, never zero`` — its cumulative counters
    are still the truest known value), so the merged report keeps serving
    from the remaining sources.  Recovery is seq-guarded: a snapshot whose
    ``seq`` went backwards means the process restarted (fresh counters); the
    stored entry is REPLACED, never summed with its predecessor, so restarts
    cannot double-count counters.
    """

    def __init__(self, endpoints: Iterable[Tuple[str, str]],
                 timeout_s: float = 2.0, stale_after_s: float = 10.0,
                 fetch_timeseries: bool = False, log_fn=print):
        self.sources: Dict[str, _Source] = {}
        for label, url in endpoints:
            url = url.rstrip("/")
            if not url.endswith(SNAPSHOT_PATH):
                url += SNAPSHOT_PATH
            self.sources[str(label)] = _Source(str(label), url)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = float(stale_after_s)
        self.fetch_timeseries = bool(fetch_timeseries)
        self.log_fn = log_fn
        self.polls = 0

    # ------------------------------------------------------------- polling

    def _fetch(self, src: _Source) -> Optional[Dict]:
        with urllib.request.urlopen(src.url, timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def poll(self) -> Dict[str, float]:
        """Scrape every endpoint once; returns the ``scrape_*`` health
        fragment.  Network/parse failures count and mark stale but never
        raise — the collector must outlive its sources."""
        self.polls += 1
        now = time.monotonic()
        for src in self.sources.values():
            t0 = time.perf_counter()
            try:
                snap = self._fetch(src)
                seq = int(snap.get("seq", 0))
            except (urllib.error.URLError, OSError, ValueError,
                    json.JSONDecodeError) as e:
                src.errors += 1
                if src.last_ok_s is None or \
                        now - src.last_ok_s > self.stale_after_s:
                    if not src.stale and src.snapshot is not None:
                        self.log_fn(f"[scrape] source {src.label} stale "
                                    f"({e.__class__.__name__}); keeping last "
                                    f"snapshot seq={src.seq}")
                    src.stale = True
                continue
            if src.seq is not None and seq < src.seq:
                # seq went backwards: the process restarted with fresh
                # counters — replace the entry (never sum old + new)
                src.restarts += 1
                self.log_fn(f"[scrape] source {src.label} restarted "
                            f"(seq {src.seq} -> {seq}); replacing snapshot")
            src.snapshot = snap
            src.seq = seq
            src.last_ok_s = now
            src.stale = False
            if self.fetch_timeseries:
                self._poll_timeseries(src)
            src.last_duration_ms = (time.perf_counter() - t0) * 1e3
        return self.scrape_record()

    def _poll_timeseries(self, src: _Source) -> None:
        """Fetch the source's rollup wire under the same degradation
        contract: failure keeps the last accepted wire (stale, never zero);
        a backwards seq REPLACES the entry."""
        url = src.url[: -len(SNAPSHOT_PATH)] + TIMESERIES_PATH
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                snap = json.loads(resp.read())
            seq = int(snap.get("seq", 0))
        except (urllib.error.URLError, OSError, ValueError,
                json.JSONDecodeError):
            src.errors += 1
            return
        if src.ts_seq is not None and seq < src.ts_seq:
            src.restarts += 1
            self.log_fn(f"[scrape] source {src.label} timeseries restarted "
                        f"(seq {src.ts_seq} -> {seq}); replacing rollup")
        src.ts_snapshot = snap
        src.ts_seq = seq

    # ------------------------------------------------------------- reading

    def snapshots(self) -> List[Dict]:
        """Latest accepted snapshot per source (stale ones included — their
        counters remain the best known value)."""
        return [s.snapshot for s in self.sources.values()
                if s.snapshot is not None]

    def aggregator(self) -> TelemetryAggregator:
        return snapshot_aggregator(self.snapshots())

    def timeseries_snapshots(self) -> List[Dict]:
        """Latest accepted ``/timeseries.json`` payload per source (stale
        included), in endpoint order — the deterministic merge order."""
        return [s.ts_snapshot for s in self.sources.values()
                if s.ts_snapshot is not None]

    def merged_timeseries(self) -> Dict:
        """Canonical merged rollup wire across sources — bit-identical to
        :func:`mat_dcml_tpu.telemetry.timeseries.merge_wires` over the same
        wires in process."""
        return merge_wires(
            [s.get("rollup") for s in self.timeseries_snapshots()])

    def durations_ms(self) -> List[float]:
        """Per-source last scrape duration (collector self-observability)."""
        return [s.last_duration_ms for s in self.sources.values()
                if s.last_duration_ms is not None]

    def staleness_s(self, now: Optional[float] = None) -> List[float]:
        """Per-source seconds since last successful scrape."""
        now = time.monotonic() if now is None else now
        return [now - s.last_ok_s for s in self.sources.values()
                if s.last_ok_s is not None]

    def scrape_record(self) -> Dict[str, float]:
        return {
            "scrape_sources": float(sum(
                1 for s in self.sources.values() if s.snapshot is not None)),
            "scrape_stale": float(sum(
                1 for s in self.sources.values() if s.stale)),
            "scrape_errors": float(sum(
                s.errors for s in self.sources.values())),
            "scrape_restarts": float(sum(
                s.restarts for s in self.sources.values())),
            "scrape_polls": float(self.polls),
        }

    def merged_record(self) -> Dict[str, float]:
        """One flat record: the exact-merged fleet view plus scrape health."""
        rec = self.aggregator().snapshot()
        rec.update(self.scrape_record())
        return rec
