"""JAX platform selection that survives site-level backend shims.

Some managed environments register a tunneled TPU backend from
``sitecustomize`` and call ``jax.config.update("jax_platforms", ...)`` at
interpreter startup — which silently overrides the user's ``JAX_PLATFORMS``
env var (config updates outrank env reading).  Entry points call
:func:`apply_platform_override` so an explicit ``JAX_PLATFORMS=cpu`` (e.g.
running the trainer on a machine whose accelerator tunnel is down) wins again.
No-op when the env var is unset or already in effect.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        current = jax.config.jax_platforms or ""
        # "axon,cpu" with JAX_PLATFORMS=axon is the shim's own doing — leave
        # its fallback list alone; only intervene when the *leading* platform
        # disagrees with what the user asked for.
        if current.split(",")[0] != want.split(",")[0]:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass
