"""Model-statistics hooks: parameter counts and analytic FLOP estimates.

The reference carries both as ad-hoc instrumentation — a ``THOP_FLAG`` that
reroutes ``MultiAgentTransformer.forward`` so the thop profiler can count
MACs (``ma_transformer.py:257-280``) and a commented parameter-count block
(``transformer_policy.py:89-102``).  The XLA-native equivalents need no
third-party profiler: parameters are pytree leaves, and every jitted
computation exposes the compiler's own analytic cost model through
``lower(...).cost_analysis()``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np


def param_count(params: Any) -> int:
    """Total trainable scalars in a parameter pytree
    (``transformer_policy.py:89-102``)."""
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))


def param_bytes(params: Any) -> int:
    """On-device parameter footprint in bytes."""
    return int(sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params)))


def compiled_flops(compiled: Any) -> Optional[float]:
    """FLOPs from a compiled executable's cost analysis, or None.

    ``cost_analysis()`` returns a dict on newer jax and a one-per-program
    list of dicts on older backends; both are handled.  Used by
    ``flop_estimate`` and by the telemetry jit wrapper, which gets the count
    for free at compile time (``flops_per_step`` in the metrics stream).
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not cost:  # some backends return {} / None
            return None
        flops = cost.get("flops")
        return float(flops) if flops is not None else None
    except Exception:
        return None


def compiled_bytes(compiled: Any) -> Optional[float]:
    """"bytes accessed" from a compiled executable's cost analysis, or None.

    XLA's static per-call count: every ``lax.scan``/``while`` BODY is counted
    ONCE regardless of trip count (callers that want per-run traffic multiply
    by trips themselves, as ``bench._roofline`` does).  This is the number the
    ``bytes_per_update`` / ``bytes_per_collect`` gauges report and that
    ``tests/test_update_bytes.py`` budgets against regression.
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not cost:
            return None
        nbytes = cost.get("bytes accessed")
        return float(nbytes) if nbytes is not None else None
    except Exception:
        return None


def flop_estimate(fn: Callable, *args, **kwargs) -> Optional[float]:
    """XLA's analytic FLOP count for one call of ``fn(*args)``.

    The ``THOP_FLAG`` equivalent (``ma_transformer.py:277-280``): returns
    compiler-counted FLOPs for the optimized HLO, or None when the backend
    does not expose a cost model.  Traces + compiles but does not execute.
    """
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        return compiled_flops(lowered.compile())
    except Exception:
        return None


def model_stats_line(params: Any) -> str:
    """One-line summary for runner startup logs."""
    n = param_count(params)
    return f"params {n:,} ({param_bytes(params) / 2**20:.2f} MiB)"
