"""Cross-cutting utilities: platform selection, logging."""

from mat_dcml_tpu.utils.platform import apply_platform_override
