"""Metrics fan-out: jsonl (always) + TensorBoard / wandb (optional).

The reference logs through wandb or tensorboardX chosen by ``--use_wandb``
(``base_runner.py:54-66,472-505``, ``DCML_MAT_Train.py:121-132``).  Here the
machine-readable jsonl stream is primary (it is what the tests and benchmark
tooling consume), with scalar mirrors to TensorBoard
(``<run_dir>/logs``, via torch's bundled SummaryWriter) and/or wandb when
requested — both degrade to a one-line warning if the backend is missing.

``max_mb > 0`` bounds the jsonl: when the file would grow past the cap it
rotates once to ``metrics.jsonl.1`` (replacing any previous rotation) and a
fresh file is started, so a 24h soak keeps at most ~2x ``max_mb`` on disk.
``scripts/check_metrics_schema.py`` validates rotated files alongside the
live one.

Run lineage: when ``scripts/train_supervisor.py`` launched this process it
exports ``MAT_DCML_RUN_ID`` (stable across relaunches) and
``MAT_DCML_INCARNATION`` (bumped per launch); every record written here gets
both stamped in, so relaunches of one logical run federate into one
queryable stream (the ``run_id``/``incarnation`` riders the schema CLI
knows).
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional

import numpy as np


def _json_default(o):
    """Coerce numpy/jax leaves that ``json.dumps`` rejects: scalars via
    ``.item()``, arrays via ``.tolist()`` (0-d arrays become scalars)."""
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    if hasattr(o, "__array__"):            # jax.Array and friends
        arr = np.asarray(o)
        return arr.item() if arr.ndim == 0 else arr.tolist()
    raise TypeError(f"{type(o).__name__} is not JSON serializable")


def scalar_metrics(record: dict) -> dict:
    """Numeric fields suitable for TB/wandb scalar mirrors.

    Excludes booleans explicitly — ``isinstance(True, int)`` holds, so a bare
    numeric check would mirror flags as 0/1 scalar charts — and casts numpy
    scalar types (``np.floating``/``np.integer``) to plain floats.
    """
    return {
        k: float(v)
        for k, v in record.items()
        if isinstance(v, (int, float, np.floating, np.integer))
        and not isinstance(v, (bool, np.bool_))
        and k not in ("episode", "total_steps")
    }


class MetricsWriter:
    def __init__(
        self,
        run_dir: str | Path,
        jsonl_name: str = "metrics.jsonl",
        use_tensorboard: bool = False,
        use_wandb: bool = False,
        wandb_project: str = "mat_dcml_tpu",
        run_name: Optional[str] = None,
        enabled: bool = True,
        max_mb: float = 0.0,
    ):
        """``enabled=False`` turns every sink off (non-primary hosts).
        ``max_mb > 0`` enables size-based rotation to ``<jsonl_name>.1``."""
        self.run_dir = Path(run_dir)
        self.jsonl_path = self.run_dir / jsonl_name
        self.enabled = enabled
        from mat_dcml_tpu.telemetry.remote import run_identity

        self._stamp = run_identity()   # supervisor lineage riders (if any)
        self.max_bytes = int(max_mb * 1024 * 1024) if max_mb else 0
        self._bytes = 0
        self._tb = None
        self._wandb = None
        self._file = None          # lazy persistent jsonl handle (one open)
        if not enabled:
            return
        if use_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.run_dir / "logs"))
            except Exception as e:                     # missing backend ≠ fatal
                print(f"[metrics] tensorboard unavailable ({e}); jsonl only")
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb.init(
                    project=wandb_project, name=run_name, dir=str(self.run_dir)
                )
            except Exception as e:
                print(f"[metrics] wandb unavailable ({e}); jsonl only")

    def write(self, record: dict, step: Optional[int] = None) -> None:
        if not self.enabled:
            return
        if self._file is None or self._file.closed:
            self.jsonl_path.parent.mkdir(parents=True, exist_ok=True)
            self._file = open(self.jsonl_path, "a")
            try:
                self._bytes = os.path.getsize(self.jsonl_path)
            except OSError:
                self._bytes = 0
        if self._stamp:
            record = {**record, **self._stamp}
        line = json.dumps(record, default=_json_default) + "\n"
        if self.max_bytes and self._bytes + len(line) > self.max_bytes:
            self._rotate()
        self._file.write(line)
        self._file.flush()
        self._bytes += len(line)
        step = step if step is not None else record.get("total_steps", record.get("episode"))
        if step is not None and not isinstance(step, int):
            step = int(step)
        scalars = scalar_metrics(record)
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, global_step=step)
        if self._wandb is not None:
            self._wandb.log(scalars, step=step)

    def _rotate(self) -> None:
        """Close, move the full file to ``<name>.1`` (replacing any earlier
        rotation), and reopen fresh — the stream keeps appending unchanged."""
        self._file.close()
        rotated = str(self.jsonl_path) + ".1"
        if os.path.exists(rotated):
            os.remove(rotated)
        os.replace(self.jsonl_path, rotated)
        self._file = open(self.jsonl_path, "a")
        self._bytes = 0

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
