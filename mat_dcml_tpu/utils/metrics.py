"""Metrics fan-out: jsonl (always) + TensorBoard / wandb (optional).

The reference logs through wandb or tensorboardX chosen by ``--use_wandb``
(``base_runner.py:54-66,472-505``, ``DCML_MAT_Train.py:121-132``).  Here the
machine-readable jsonl stream is primary (it is what the tests and benchmark
tooling consume), with scalar mirrors to TensorBoard
(``<run_dir>/logs``, via torch's bundled SummaryWriter) and/or wandb when
requested — both degrade to a one-line warning if the backend is missing.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional


class MetricsWriter:
    def __init__(
        self,
        run_dir: str | Path,
        jsonl_name: str = "metrics.jsonl",
        use_tensorboard: bool = False,
        use_wandb: bool = False,
        wandb_project: str = "mat_dcml_tpu",
        run_name: Optional[str] = None,
        enabled: bool = True,
    ):
        """``enabled=False`` turns every sink off (non-primary hosts)."""
        self.run_dir = Path(run_dir)
        self.jsonl_path = self.run_dir / jsonl_name
        self.enabled = enabled
        self._tb = None
        self._wandb = None
        if not enabled:
            return
        if use_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.run_dir / "logs"))
            except Exception as e:                     # missing backend ≠ fatal
                print(f"[metrics] tensorboard unavailable ({e}); jsonl only")
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb.init(
                    project=wandb_project, name=run_name, dir=str(self.run_dir)
                )
            except Exception as e:
                print(f"[metrics] wandb unavailable ({e}); jsonl only")

    def write(self, record: dict, step: Optional[int] = None) -> None:
        if not self.enabled:
            return
        self.jsonl_path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        step = step if step is not None else record.get("total_steps", record.get("episode"))
        scalars = {
            k: v for k, v in record.items()
            if isinstance(v, (int, float)) and k not in ("episode", "total_steps")
        }
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, global_step=step)
        if self._wandb is not None:
            self._wandb.log(scalars, step=step)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
