"""Rule-based parameter PartitionSpecs: the fsdp x tp layer.

The (data, seq) mesh shards envs and sequence, but every parameter pytree was
replicated (``P()``), capping the MAT trunk at one device's HBM.  This module
is the single place parameter shardings are decided and applied:

- :class:`SpecLayout` names the per-layer specs (embedding / qkv / proj /
  ffn / head) in terms of the ``fsdp`` and ``tp`` mesh axes — a Megatron-ish
  layout where qkv and ffn-up are column-parallel ``P(fsdp, tp)`` and proj
  and ffn-down are row-parallel ``P(tp, fsdp)``;
- :func:`match_partition_rules` maps ordered ``(regex, spec)`` rules over
  "/"-joined flattened tree paths, first match wins.  An unmatched model
  parameter raises :class:`UnmatchedParamError` — rules can NEVER silently
  fall back to replication, because a silently-replicated tensor is exactly
  the HBM leak this layer exists to prevent;
- :func:`resolve_state_specs` applies the rules to a whole TrainState probe.
  Optimizer moments inherit the param specs for free: optax's ``mu``/``nu``
  hold the same ``params/...`` dict subtree, so the same regexes match
  through ``opt_state/1/0/mu/params/...``;
- :func:`place_params` is THE placement seam for params at rest: every
  restore / emergency / elastic / publish path places through it (specs=None
  means replicated, the pre-fsdp behavior);
- :func:`gather_replicated` undoes the sharding for consumers that need full
  values (the serving engine's AOT bucket programs) — through the spec
  layer, not ad-hoc ``put_replicated``.

Scalars and size-1 leaves always replicate; leaves outside a ``params/``
subtree (ValueNorm moments, step counters) always replicate.  Rules engage
only when the mesh actually has fsdp or tp extent — at fsdp=tp=1 the resolved
specs are all ``P()`` and the program is bit-identical to the replicated
path (pinned in tests/test_param_sharding.py).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Optional, Sequence, Tuple

import jax
import jax.tree_util as jtu
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PARAM_MARKER = "params/"

#: axis-name tuple the run mesh exposes for parameter sharding
PARAM_AXES = ("fsdp", "tp")


class UnmatchedParamError(ValueError):
    """A model parameter matched no partition rule.

    Raised instead of silently replicating: an unmatched tensor is a silent
    per-device HBM regression, the exact failure mode this layer prevents.
    """


class ShardMismatchError(ValueError):
    """A resolved spec does not divide its parameter's shape (or names more
    dims than the parameter has)."""


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Named per-layer PartitionSpecs over the (fsdp, tp) axes.

    One frozen instance describes the whole layout; :func:`default_mat_rules`
    binds it to MAT's encoder-decoder param names.
    """

    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"

    def embedding(self) -> P:
        # (env_dim, n_embd): the input dim is env-determined (obs/state/
        # action width, e.g. DCML's 7) and not generally divisible —
        # shard the n_embd columns over both param axes.
        return P(None, (self.fsdp_axis, self.tp_axis))

    def qkv_projection(self) -> P:
        # (n_embd, n_embd) column-parallel
        return P(self.fsdp_axis, self.tp_axis)

    def attn_output(self) -> P:
        # (n_embd, n_embd) row-parallel: consumes the tp-split activations
        return P(self.tp_axis, self.fsdp_axis)

    def ffn_up(self) -> P:
        return P(self.fsdp_axis, self.tp_axis)

    def ffn_down(self) -> P:
        return P(self.tp_axis, self.fsdp_axis)

    def head_hidden(self) -> P:
        # head Dense_0 (n_embd, n_embd)
        return P(self.fsdp_axis, self.tp_axis)

    def head_out(self) -> P:
        # head Dense_1 (n_embd, out_dim) with tiny out_dim (1 or action_dim):
        # shard only the input rows
        return P(self.fsdp_axis, None)

    def replicated(self) -> P:
        return P()


def default_mat_rules(layout: Optional[SpecLayout] = None) -> Tuple[Tuple[str, P], ...]:
    """The default rule set for the MAT encoder-decoder trunk (all
    ``mat_variants`` included).  First match wins; order goes from the
    cheap always-replicated tails to layer kernels by specificity."""
    L = layout or SpecLayout()
    return (
        # norms, biases, gains: 1-D tails, replicated (sharding them saves
        # ~n_embd bytes per tensor and costs a collective per use)
        (r"(bias|scale)$", P()),
        (r"log_std$", P()),
        # env-facing encoders: input dim arbitrary, shard n_embd columns
        (r"(obs_encoder|state_encoder)/Dense_\d+/kernel$", L.embedding()),
        (r"action_encoder\w*/kernel$", L.embedding()),
        # attention (encoder attn, decoder attn1/attn2): qkv column-parallel,
        # proj row-parallel
        (r"attn\d*/(query_p|key_p|value_p)/kernel$", L.qkv_projection()),
        (r"attn\d*/proj/kernel$", L.attn_output()),
        # dec_actor per-agent MLP head: kernels carry a leading n_agent axis
        # (share_actor drops it); explicitly replicated, NOT an omission —
        # the per-agent stack is tiny and agent-indexed
        (r"decoder/mlp/Dense_\d+/kernel$", P()),
        # transformer block MLP
        (r"mlp/Dense_0/kernel$", L.ffn_up()),
        (r"mlp/Dense_1/kernel$", L.ffn_down()),
        # GRU variant recurrence cells: square (n_embd, n_embd) kernels
        (r"cells_\d+/\w+/kernel$", L.qkv_projection()),
        # value / action heads
        (r"(head|act_head)/Dense_0/kernel$", L.head_hidden()),
        (r"(head|act_head)/Dense_\d+/kernel$", L.head_out()),
    )


def _path_str(path) -> str:
    parts = []
    for k in path:
        if isinstance(k, jtu.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jtu.GetAttrKey):
            parts.append(k.name)
        elif isinstance(k, jtu.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jtu.FlattenedIndexKey):
            parts.append(str(k.key))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


def _leaf_size(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    return int(np.prod(shape)) if shape else 1


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree, *, param_marker: str = PARAM_MARKER):
    """Resolve a PartitionSpec per leaf of ``tree`` (params or a whole
    TrainState probe) by first-match-wins regex over the "/"-joined path.

    Scalars/size-1 leaves and leaves outside a ``params/`` subtree replicate
    unconditionally.  A model parameter that matches no rule raises
    :class:`UnmatchedParamError`.
    """
    leaves, treedef = jtu.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in leaves:
        name = _path_str(path)
        if _leaf_size(leaf) <= 1 or param_marker not in name + "/":
            specs.append(P())
            continue
        for pattern, spec in rules:
            if re.search(pattern, name):
                specs.append(spec)
                break
        else:
            raise UnmatchedParamError(
                f"partition rule not found for param {name!r} "
                f"(shape {tuple(getattr(leaf, 'shape', ()))}); add a rule — "
                f"unmatched params never silently replicate"
            )
    return jtu.tree_unflatten(treedef, specs)


def _axis_shards(mesh: Mesh, entry) -> int:
    axes = entry if isinstance(entry, tuple) else (entry,)
    shape = dict(mesh.shape)
    n = 1
    for a in axes:
        if a is not None:
            n *= int(shape.get(a, 1))
    return n


def validate_specs(specs, tree, mesh: Mesh) -> None:
    """Typed shape/divisibility check of resolved specs against a tree.

    Raises :class:`ShardMismatchError` naming the first offending param, its
    dim, and the shard count — catching e.g. ``n_embd % tp != 0`` at config
    time instead of as an opaque XLA error mid-init.
    """
    spec_leaves = jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    tree_leaves = jtu.tree_flatten_with_path(tree)[0]
    for (path, leaf), spec in zip(tree_leaves, spec_leaves):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(spec) > len(shape):
            raise ShardMismatchError(
                f"spec {spec} names {len(spec)} dims but param "
                f"{_path_str(path)!r} has shape {shape}"
            )
        for dim, entry in enumerate(spec):
            n = _axis_shards(mesh, entry)
            if n > 1 and shape[dim] % n:
                raise ShardMismatchError(
                    f"param {_path_str(path)!r} dim {dim} ({shape[dim]}) is "
                    f"not divisible by {entry!r} ({n} shards); pick n_embd a "
                    f"multiple of fsdp_shards*tp_shards or adjust the rules"
                )


def has_param_axes(mesh: Optional[Mesh]) -> bool:
    """True iff ``mesh`` carries real fsdp/tp extent (>1 on either axis)."""
    if mesh is None:
        return False
    shape = dict(mesh.shape)
    return any(int(shape.get(a, 1)) > 1 for a in PARAM_AXES)


def resolve_state_specs(tree, mesh: Optional[Mesh], rules: Optional[Sequence[Tuple[str, P]]] = None):
    """Specs for a params-or-TrainState tree under ``mesh``.

    Fast path: without real fsdp/tp extent every leaf replicates (``P()``)
    WITHOUT consulting rules, so non-MAT policies keep working under pure
    data/seq sharding and the fsdp=tp=1 program stays bit-identical to the
    replicated path.  With extent, rules are mandatory and validated.
    """
    if not has_param_axes(mesh):
        return jax.tree.map(lambda _: P(), tree)
    rules = rules if rules is not None else default_mat_rules()
    specs = match_partition_rules(rules, tree)
    validate_specs(specs, tree, mesh)
    return specs


def named_shardings(specs, mesh: Mesh):
    """Tree of NamedShardings from a tree of PartitionSpecs."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def place_params(tree, mesh: Optional[Mesh], specs=None):
    """THE placement seam for params at rest.

    Every path that moves a host-local (or differently-placed) param tree
    onto a mesh — checkpoint restore, emergency resume, elastic re-placement
    across mesh shapes, async publish — goes through here so resumed state
    can't silently drop its shardings.  ``specs=None`` (or no mesh) means
    replicated, the pre-fsdp behavior.  Re-placement across param-axis
    changes (fsdp=2 -> 4 and back) is just this function with the new mesh's
    resolved specs: ``device_put`` against a NamedSharding reshards.
    """
    if mesh is None:
        return tree
    if specs is None:
        repl = NamedSharding(mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, repl), tree)
    shardings = named_shardings(specs, mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)


def gather_replicated(tree):
    """Gather cross-device-sharded leaves back to fully-replicated arrays on
    their own mesh (host-local and already-replicated leaves pass through).

    The spec-layer inverse of :func:`place_params` — serving's AOT bucket
    programs install through this instead of assuming inbound weights are
    already full."""

    def gather(x):
        if not isinstance(x, jax.Array):
            return x
        sharding = getattr(x, "sharding", None)
        if sharding is None or x.is_fully_replicated or len(x.sharding.device_set) == 1:
            return x
        mesh = getattr(sharding, "mesh", None)
        if mesh is None:  # positional sharding: fall back via host
            return np.asarray(x)
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree.map(gather, tree)


def param_byte_stats(tree, specs, mesh: Optional[Mesh]) -> dict:
    """Byte accounting for the ``shard_param_`` gauge family.

    Returns global param bytes split by which axis shards them, plus the
    max-per-device footprint (global bytes / shard count per leaf) — the
    number that proves the HBM win.  Works on eval_shape probes or concrete
    trees."""
    stats = {
        "bytes_total": 0,
        "bytes_fsdp": 0,
        "bytes_tp": 0,
        "bytes_replicated": 0,
        "max_device_bytes": 0,
    }
    spec_leaves = jtu.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    tree_leaves = jtu.tree_leaves(tree)
    for leaf, spec in zip(tree_leaves, spec_leaves):
        nbytes = _leaf_size(leaf) * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        axes = set()
        shards = 1
        for entry in spec:
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if a is not None:
                    axes.add(a)
            if mesh is not None:
                shards *= _axis_shards(mesh, entry)
        stats["bytes_total"] += nbytes
        if "fsdp" in axes:
            stats["bytes_fsdp"] += nbytes
        if "tp" in axes:
            stats["bytes_tp"] += nbytes
        if not axes:
            stats["bytes_replicated"] += nbytes
        stats["max_device_bytes"] += nbytes // max(1, shards)
    return stats


def load_rules(path: str, layout: Optional[SpecLayout] = None) -> Tuple[Tuple[str, P], ...]:
    """Load a rules file: a JSON list of ``[regex, spec]`` pairs, where spec
    is a list of entries — ``null`` (dim unsharded), an axis name, or a list
    of axis names (a dim sharded over multiple axes).  Example::

        [["attn\\\\d*/(query_p|key_p|value_p)/kernel$", ["fsdp", "tp"]],
         ["(bias|scale)$", []]]

    The README "Scaling" section documents the format with the full default
    MAT rule set.  ``layout`` is accepted for symmetry with
    :func:`default_mat_rules` but JSON rules name axes directly."""
    del layout
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"rules file {path}: expected a JSON list, got {type(raw).__name__}")
    rules = []
    for i, item in enumerate(raw):
        if not (isinstance(item, list) and len(item) == 2 and isinstance(item[0], str)):
            raise ValueError(f"rules file {path}: entry {i} must be [regex, spec-list]")
        pattern, spec = item
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(f"rules file {path}: entry {i} bad regex: {e}") from e
        if not isinstance(spec, list):
            raise ValueError(f"rules file {path}: entry {i} spec must be a list")
        entries = []
        for entry in spec:
            if entry is None or isinstance(entry, str):
                entries.append(entry)
            elif isinstance(entry, list) and all(isinstance(a, str) for a in entry):
                entries.append(tuple(entry))
            else:
                raise ValueError(
                    f"rules file {path}: entry {i} spec entries must be "
                    f"null, an axis name, or a list of axis names"
                )
        rules.append((pattern, P(*entries)))
    return tuple(rules)
