"""Multi-host initialization + global-array helpers.

The reference has no distributed backend (its "cluster" is one process per
env over pipes, SURVEY.md §2.8).  The TPU-native replacement is SPMD over a
global mesh: every host runs the SAME jitted program; XLA inserts the
collectives (grad ``psum``, batch-statistic reductions) over ICI, with DCN
touched only at init/checkpoint/logging.  Because statistics like ValueNorm
moments and advantage mean/std are computed on globally-sharded arrays
INSIDE one jit, they are globally exact by construction — the multi-process
parity test (tests/test_multihost.py) asserts the sharded step matches the
single-device step bit-for-bit-close, which is the property the reference
could never state.

``init_distributed`` wraps ``jax.distributed.initialize``:

- on TPU pods, call with no arguments (the TPU runtime supplies topology);
- on CPU "fake clusters" (tests, CI) pass coordinator/num_processes/
  process_id and gloo collectives are enabled automatically;
- env vars ``MAT_DCML_COORDINATOR`` / ``MAT_DCML_NUM_PROCESSES`` /
  ``MAT_DCML_PROCESS_ID`` drive the same path for launcher scripts.
"""

from __future__ import annotations

import os
from typing import Optional


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-process runtime (idempotent, single-process no-op).

    With no arguments: reads the ``MAT_DCML_*`` env vars; if those are unset
    and the platform is a TPU pod, defers to JAX's automatic cluster
    detection; otherwise stays single-process.
    """
    import jax

    if coordinator_address is None:
        coordinator_address = os.environ.get("MAT_DCML_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("MAT_DCML_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("MAT_DCML_PROCESS_ID")
        process_id = int(pid) if pid is not None else None

    if coordinator_address is None and num_processes is None:
        # TPU pods self-describe; nothing to do elsewhere.  Tunneled or
        # partially-populated pod env vars (single-host slices) make the
        # autodetect raise — that simply means single-process.
        if _running_on_tpu_pod():
            try:
                jax.distributed.initialize()
            except (ValueError, RuntimeError):
                pass
        return

    platforms = (os.environ.get("JAX_PLATFORMS") or "").lower()
    if "cpu" in platforms:
        # CPU cross-process collectives need an explicit backend
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _running_on_tpu_pod() -> bool:
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def process_index() -> int:
    import jax

    return jax.process_index()


def is_primary() -> bool:
    """True on the process that should own logging/checkpoint writes."""
    return process_index() == 0


def global_init_state(collector, key, n_envs: int, mesh, data_axis: str = "data"):
    """Build a rollout state as GLOBAL arrays sharded over ``data_axis``.

    Every process calls this with the same key; the init runs inside jit with
    ``out_shardings``, so each host materializes only its addressable shards
    — the multi-host-safe way to construct sharded program state (no
    host-side full-size array is assumed to exist anywhere).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_data = dict(mesh.shape).get(data_axis, 1)
    if n_envs % n_data:
        raise ValueError(
            f"env batch n_envs={n_envs} must be divisible by the mesh's "
            f"{data_axis!r} axis ({n_data} shards); pick --n_rollout_threads "
            f"a multiple of --data_shards"
        )
    shard = NamedSharding(mesh, P(data_axis))
    repl = NamedSharding(mesh, P())

    def out_sharding(x):
        return shard if getattr(x, "ndim", 0) >= 1 else repl

    def init(k):
        return collector.init_state(k, n_envs)

    probe = jax.eval_shape(init, key)
    shardings = jax.tree.map(out_sharding, probe)
    return jax.jit(init, out_shardings=shardings)(key)


def put_replicated(tree, mesh):
    """Place a host-local pytree (e.g. a restored checkpoint) as replicated
    global arrays on ``mesh``.  Fully-replicated shardings are the one
    multi-host-safe ``device_put`` — every process holds the complete value,
    so no cross-host data movement is implied.  Delegates to the one
    spec-aware placement seam (``parallel.sharding.place_params``) with no
    specs — spec-carrying callers pass their specs to ``place_params``
    directly."""
    from mat_dcml_tpu.parallel.sharding import place_params

    return place_params(tree, mesh, specs=None)


def put_time_major(tree, mesh, data_axis: str = "data"):
    """Place a time-major trajectory pytree (leaves ``(T, E, ...)``) on
    ``mesh``: every ndim>=2 leaf shards its env axis (axis 1) over
    ``data_axis``; scalars (chunk_stats) replicate.

    This is the device-to-device half of the async actor->learner handoff
    (training/async_loop.py): the actor submesh produced the block, the
    learner submesh consumes it, and ``device_put`` with a target
    NamedSharding moves the buffers without staging a full host copy.  The
    same env-batch divisibility contract as :func:`global_init_state`
    applies, just one axis over.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_data = dict(mesh.shape).get(data_axis, 1)
    shard = NamedSharding(mesh, P(None, data_axis))
    repl = NamedSharding(mesh, P())

    def place(x):
        if getattr(x, "ndim", 0) >= 2:
            if x.shape[1] % n_data:
                raise ValueError(
                    f"trajectory env axis ({x.shape[1]}) must be divisible by "
                    f"the mesh's {data_axis!r} axis ({n_data} shards)"
                )
            return jax.device_put(x, shard)
        return jax.device_put(x, repl)

    return jax.tree.map(place, tree)


def put_sharded_state(tree, mesh, data_axis: str = "data"):
    """Place a host-local rollout-state pytree on ``mesh`` under the same
    contract :func:`global_init_state` builds with: every ndim>=1 leaf
    carries a leading env-batch axis and shards over ``data_axis``, scalars
    (the rng key) replicate.  This is the elastic-resume half of that
    contract — a carry packed on one mesh re-places onto another, as long as
    the env batch still divides the new shard count.

    Single-process only (the packed carry is a full host-local copy, which a
    multi-host relaunch does not have); multi-host elastic resume goes
    through the orbax path instead.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_data = dict(mesh.shape).get(data_axis, 1)
    shard = NamedSharding(mesh, P(data_axis))
    repl = NamedSharding(mesh, P())

    def place(x):
        ndim = getattr(x, "ndim", 0)
        if ndim >= 1:
            if x.shape[0] % n_data:
                raise ValueError(
                    f"env batch axis ({x.shape[0]}) must be divisible by the "
                    f"mesh's {data_axis!r} axis ({n_data} shards); pick "
                    f"--n_rollout_threads a multiple of --data_shards"
                )
            return jax.device_put(x, shard)
        return jax.device_put(x, repl)

    return jax.tree.map(place, tree)
