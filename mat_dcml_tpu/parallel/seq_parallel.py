"""Sequence/context parallelism for the MAT training forward.

MAT treats AGENTS as the sequence axis, so "long context" here means many
agents.  The reference's only length device is stride-batched decoding
(SURVEY.md §5); this module context-shards the teacher-forced training
forward — the per-step hot path of PPO — over a ``seq`` mesh axis: every
per-position op (embeds, LayerNorms, MLPs, value/logit heads) runs on its
own shard untouched, and the two attention flavors (encoder full, decoder
causal self/cross) rotate K/V shards around the ring with ``ppermute``
(:mod:`~mat_dcml_tpu.ops.ring_attention`), compute overlapping
communication.  Exact — pinned to the replicated forward by
``tests/test_seq_parallel.py`` on a virtual CPU mesh.

The autoregressive DECODE path is deliberately not context-sharded: it is
sequential over positions with O(1) new work per step, so its shard would
idle n-1 devices; collection scales over the ``data`` axis instead.
"""

from __future__ import annotations

import jax

try:                                        # top-level API (jax >= 0.6)
    from jax import shard_map
except ImportError:                         # jax 0.4.x
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mat_dcml_tpu.ops import attention as _attn


_attn_impl = _attn.impl_override  # trace-time, module-scoped pin


def _check(model) -> None:
    if model.cfg.dec_actor:
        raise NotImplementedError(
            "MAT-Dec replaces the decoder with per-agent MLPs indexed by "
            "global agent id; context-sharding applies to the transformer path"
        )


def seq_sharded_call(model, params, mesh: Mesh, method, n_out: int, *args,
                     axis: str = "seq"):
    """Run any per-position model method with the agent axis ring-sharded.

    ``args`` are ``(B, L, ·)`` arrays; outputs are ``n_out`` ``(B, L, ·)``
    arrays.  When L does not divide the ring (DCML's prime 101 agents), the
    inputs are zero-padded to the next multiple, padded KEY positions are
    masked inside the ring attention, and the padded output rows are sliced
    away — numerics identical to the unpadded forward.  Composable: callable
    eagerly or inside an enclosing jit (the trainer's single jitted update),
    since the attention-impl pin applies at trace time.
    """
    _check(model)
    n = mesh.shape[axis]
    L = args[0].shape[1]
    pad = (-L) % n
    if pad:
        args = tuple(
            jax.numpy.pad(a, ((0, 0), (0, pad), (0, 0))) for a in args
        )
    # Composition with data parallelism: on a 2D (data, seq) mesh the batch
    # axis shards over "data" while agents ring over "seq" — one mesh, one
    # shard_map, so the enclosing jit's data-sharded inputs never fight a
    # second device placement (the ADVICE r2 conflict this used to forbid).
    batch_axis = None
    if "data" in mesh.axis_names and mesh.shape["data"] > 1:
        batch_axis = "data"
        B = args[0].shape[0]
        if B % mesh.shape["data"]:
            raise ValueError(
                f"batch {B} not divisible by the mesh data axis "
                f"({mesh.shape['data']}); choose n_rollout_threads / "
                f"num_mini_batch so minibatch rows divide the data shards"
            )
    row = P(batch_axis, axis, None)
    replicated = jax.tree.map(lambda _: P(), params)
    out_specs = row if n_out == 1 else tuple([row] * n_out)

    with _attn_impl("ring", axis, valid_len=L if pad else 0):

        def fn(p, *a):
            return model.apply(p, *a, method=method)

        out = shard_map(
            fn, mesh=mesh,
            in_specs=(replicated, *([row] * len(args))),
            out_specs=out_specs,
        )(params, *args)
    if pad:
        trim = lambda x: x[:, :L]  # noqa: E731
        out = trim(out) if n_out == 1 else tuple(trim(o) for o in out)
    return out


def seq_sharded_forward(model, params, state, obs, shifted_action,
                        mesh: Mesh, axis: str = "seq"):
    """Teacher-forced MAT forward with the agent axis sharded over ``axis``.

    Args:
      model: a ``MultiAgentTransformer`` (``models/mat.py``).
      state / obs / shifted_action: ``(B, L, ·)`` replicated inputs; the L
        (agent) axis must divide the mesh's ``axis`` size.
      mesh: mesh containing ``axis``.

    Returns:
      ``(v_loc, obs_rep, logits)`` exactly as ``model.__call__`` — computed
      with O(L/n) per-device attention memory and ring communication.
    """
    return seq_sharded_call(
        model, params, mesh, None, 3, state, obs, shifted_action, axis=axis
    )
