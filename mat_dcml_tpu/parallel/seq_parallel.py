"""Sequence/context parallelism for the MAT training forward.

MAT treats AGENTS as the sequence axis, so "long context" here means many
agents.  The reference's only length device is stride-batched decoding
(SURVEY.md §5); this module context-shards the teacher-forced training
forward — the per-step hot path of PPO — over a ``seq`` mesh axis: every
per-position op (embeds, LayerNorms, MLPs, value/logit heads) runs on its
own shard untouched, and the two attention flavors (encoder full, decoder
causal self/cross) rotate K/V shards around the ring with ``ppermute``
(:mod:`~mat_dcml_tpu.ops.ring_attention`), compute overlapping
communication.  Exact — pinned to the replicated forward by
``tests/test_seq_parallel.py`` on a virtual CPU mesh.

The autoregressive DECODE path is deliberately not context-sharded: it is
sequential over positions with O(1) new work per step, so its shard would
idle n-1 devices; collection scales over the ``data`` axis instead.
"""

from __future__ import annotations

import contextlib
import os

import jax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mat_dcml_tpu.ops import attention as _attn


@contextlib.contextmanager
def _attn_impl(impl: str, axis: str):
    """Pin the attention dispatch to ``impl`` while tracing."""
    old_impl = os.environ.get(_attn._IMPL_ENV)
    old_axis = os.environ.get(_attn._RING_AXIS_ENV)
    os.environ[_attn._IMPL_ENV] = impl
    os.environ[_attn._RING_AXIS_ENV] = axis
    try:
        yield
    finally:
        for k, v in ((_attn._IMPL_ENV, old_impl), (_attn._RING_AXIS_ENV, old_axis)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def seq_sharded_forward(model, params, state, obs, shifted_action,
                        mesh: Mesh, axis: str = "seq"):
    """Teacher-forced MAT forward with the agent axis sharded over ``axis``.

    Args:
      model: a ``MultiAgentTransformer`` (``models/mat.py``).
      state / obs / shifted_action: ``(B, L, ·)`` replicated inputs; the L
        (agent) axis must divide the mesh's ``axis`` size.
      mesh: mesh containing ``axis``.

    Returns:
      ``(v_loc, obs_rep, logits)`` exactly as ``model.__call__`` — computed
      with O(L/n) per-device attention memory and ring communication.
    """
    if model.cfg.dec_actor:
        raise NotImplementedError(
            "MAT-Dec replaces the decoder with per-agent MLPs indexed by "
            "global agent id; context-sharding applies to the transformer path"
        )
    n = mesh.shape[axis]
    L = obs.shape[1]
    if L % n != 0:
        raise ValueError(
            f"agent axis ({L}) must divide the '{axis}' mesh axis ({n}); "
            "pad the agent dimension to a multiple"
        )

    row = P(None, axis, None)
    replicated = jax.tree.map(lambda _: P(), params)

    with _attn_impl("ring", axis):

        @jax.jit
        def run(params, state, obs, shifted_action):
            def fwd(params, state_s, obs_s, act_s):
                return model.apply(params, state_s, obs_s, act_s)

            return shard_map(
                fwd, mesh=mesh,
                in_specs=(replicated, row, row, row),
                out_specs=(row, row, row),
            )(params, state, obs, shifted_action)

        return run(params, state, obs, shifted_action)
