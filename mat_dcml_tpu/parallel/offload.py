"""Host-memory offload annotations for the streamed PPO update.

``--update_offload`` moves the streamed update's per-minibatch chunk stack to
host memory and brings each chunk back on-device inside the accumulation scan
(training/ppo.py apply_minibatch) — the XLA host-offloading streaming pattern:
``device_put`` with a memory-kind annotation inside jit compiles to an async
copy the scheduler overlaps with compute, and the device-resident working set
of the fwd/bwd drops from a full minibatch to one chunk.  Composes with
``--update_stream_chunks`` (defines the chunk grain) and ``remat`` (shrinks
the activations that share the freed HBM).

Backend honesty: a chip exposes a distinct ``pinned_host`` space, so the
annotation is a real HBM<->host transfer there.  CPU has a single
``unpinned_host`` space — the annotations trace and compile (pinned by
tests/test_stream_equivalence.py: bit-exact, flag on vs off) but move nothing,
so CPU runs prove compile/numerics only; the HBM relief claim needs the chip
session recorded in ROADMAP.md.

``TransferToMemoryKind`` is not in ``jax.sharding``'s public namespace until
jax 0.5; import falls back to the private home it has in 0.4.x.
"""

from __future__ import annotations

from functools import lru_cache

import jax

try:  # public from jax 0.5
    from jax.sharding import TransferToMemoryKind
except ImportError:  # 0.4.x
    from jax._src.sharding_impls import TransferToMemoryKind


@lru_cache(maxsize=1)
def memory_kinds() -> tuple:
    """(host_kind, device_kind) for the local backend.  Equal kinds mean the
    backend has no separate host space (CPU) and offload is a traced no-op."""
    d = jax.local_devices()[0]
    try:
        kinds = {m.kind for m in d.addressable_memories()}
        dev = d.default_memory().kind
    except Exception:  # backends predating the memories API
        return "device", "device"
    host = "pinned_host" if "pinned_host" in kinds else dev
    return host, dev


def offload_is_real() -> bool:
    """True when the backend has a host space distinct from device memory."""
    host, dev = memory_kinds()
    return host != dev


def to_host(tree):
    """Annotate a pytree for host memory (inside or outside jit)."""
    host, _ = memory_kinds()
    return jax.tree.map(lambda x: jax.device_put(x, TransferToMemoryKind(host)), tree)


def to_device(tree):
    """Annotate a pytree for device memory (inside or outside jit)."""
    _, dev = memory_kinds()
    return jax.tree.map(lambda x: jax.device_put(x, TransferToMemoryKind(dev)), tree)
