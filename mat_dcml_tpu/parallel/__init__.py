"""Mesh construction and sharding helpers (ICI/DCN-aware scaling)."""

from mat_dcml_tpu.parallel.mesh import make_mesh, replicated, data_sharded
from mat_dcml_tpu.parallel.seq_parallel import seq_sharded_forward
