"""Device mesh + sharding utilities.

The reference has no distributed backend at all — its "distributed" layer is
one OS process per env over pipes (SURVEY.md §2.8).  The TPU-native design:

- envs are pure JAX, so rollout parallelism = sharding the env-batch axis of
  the same jitted program over the mesh ``data`` axis;
- gradient data-parallelism falls out of ``jit`` with sharded batch inputs —
  XLA inserts the ``psum`` all-reduces for grads and for the batch statistics
  (advantage mean/std, ValueNorm moments) that the reference computed in
  single-device numpy;
- multi-host: ``jax.distributed.initialize()`` then the same code — ICI for
  collectives, DCN only for init/checkpoint/logging.

``model`` and ``seq`` axes are declared for tensor/sequence parallelism
headroom (the MAT agent axis could be context-sharded for 100x agent counts);
DCML-scale models need only ``data``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_seq: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, model, seq)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_model * n_seq)
    n_total = n_data * n_model * n_seq
    if n_total <= 0 or n_total > len(devices):
        # a typed error, not an assert: asserts vanish under ``python -O``
        # and a silently-oversized mesh dies later with an opaque XLA error
        raise ValueError(
            f"mesh ({n_data}, {n_model}, {n_seq}) needs {n_total} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:n_total]).reshape(n_data, n_model, n_seq)
    return Mesh(arr, axis_names=("data", "model", "seq"))


def make_data_seq_mesh(n_seq: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(data, seq) mesh with seq MINOR: consecutive devices form each ring.

    ``jax.devices()`` orders by process, so with ``n_seq`` dividing the
    per-process device count every ring stays inside one process — ring
    collectives ride ICI, never DCN.  This ordering invariant lives here
    and nowhere else; all data x seq composition sites must build through
    this helper.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_seq <= 0 or len(devices) % n_seq:
        raise ValueError(f"n_seq {n_seq} must divide the device count {len(devices)}")
    # enforce the placement invariant itself, not a proxy: every ring
    # (consecutive n_seq block) must sit inside one process, or its
    # collectives silently ride DCN instead of ICI
    for ring_start in range(0, len(devices), n_seq):
        ring = devices[ring_start:ring_start + n_seq]
        procs = {d.process_index for d in ring}
        if len(procs) > 1:
            raise ValueError(
                f"seq ring {ring_start // n_seq} spans processes {sorted(procs)} "
                f"(ICI -> DCN); pick n_seq dividing the per-process device "
                f"count or reorder the device list"
            )
    return Mesh(np.array(devices).reshape(-1, n_seq), ("data", "seq"))


def make_run_mesh(
    n_seq: int,
    n_fsdp: int = 1,
    n_tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(data, seq, fsdp, tp) mesh, data MAJOR and tp MOST-MINOR.

    Generalizes :func:`make_data_seq_mesh`'s placement invariant: the
    collective-heavy axes (tp every layer, fsdp every param touch, seq every
    ring step) sit innermost so each ``seq x fsdp x tp`` block is a run of
    consecutive devices — ``jax.devices()`` orders by process, so requiring
    each block inside one process keeps those collectives on ICI, never DCN.
    Only the ``data`` axis (grad psum once per step) may span processes.
    """
    devices = list(devices if devices is not None else jax.devices())
    for name, n in (("seq", n_seq), ("fsdp", n_fsdp), ("tp", n_tp)):
        if n <= 0:
            raise ValueError(f"n_{name} must be >= 1, got {n}")
    block = n_seq * n_fsdp * n_tp
    if len(devices) % block:
        raise ValueError(
            f"seq x fsdp x tp block ({n_seq}x{n_fsdp}x{n_tp}={block}) must "
            f"divide the device count {len(devices)}"
        )
    for start in range(0, len(devices), block):
        procs = {d.process_index for d in devices[start:start + block]}
        if len(procs) > 1:
            raise ValueError(
                f"seq/fsdp/tp block {start // block} spans processes "
                f"{sorted(procs)} (ICI -> DCN); pick shard counts whose "
                f"product divides the per-process device count"
            )
    arr = np.array(devices).reshape(-1, n_seq, n_fsdp, n_tp)
    return Mesh(arr, ("data", "seq", "fsdp", "tp"))


def build_run_mesh(
    data_shards: int,
    seq_shards: int = 1,
    fsdp_shards: int = 1,
    tp_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Optional[Mesh]:
    """The runner-facing ``(data, seq, fsdp, tp)`` mesh for ``--data_shards``
    x ``--seq_shards`` x ``--fsdp_shards`` x ``--tp_shards``.

    ``data_shards=0`` means auto: every available device not consumed by the
    other axes becomes a data shard (global device count // (seq*fsdp*tp) —
    under multi-process this counts GLOBAL devices, so every process runs the
    same SPMD program over one global mesh).  Returns ``None`` when no mesh
    is needed (1x1x1x1 single-process) — the runner then keeps host-local
    state.

    Always built through :func:`make_run_mesh` so the minor-axis ICI-block
    placement invariant holds at every composition site.
    """
    devices = list(devices if devices is not None else jax.devices())
    if seq_shards <= 0:
        raise ValueError(f"seq_shards must be >= 1, got {seq_shards}")
    if fsdp_shards <= 0:
        raise ValueError(f"fsdp_shards must be >= 1, got {fsdp_shards}")
    if tp_shards <= 0:
        raise ValueError(f"tp_shards must be >= 1, got {tp_shards}")
    if data_shards < 0:
        raise ValueError(f"data_shards must be >= 0 (0 = auto), got {data_shards}")
    block = seq_shards * fsdp_shards * tp_shards
    n_data = data_shards if data_shards else max(1, len(devices) // block)
    n_total = n_data * block
    if n_total > len(devices):
        raise ValueError(
            f"--data_shards {n_data} x --seq_shards {seq_shards} x "
            f"--fsdp_shards {fsdp_shards} x --tp_shards {tp_shards} needs "
            f"{n_total} devices, have {len(devices)}"
        )
    import jax as _jax

    if _jax.process_count() > 1 and n_total != len(devices):
        # a partial mesh under multi-process would leave some processes with
        # no addressable shard of the program state — every jitted call dies
        # on non-addressable inputs.  Require full coverage (or auto).
        raise ValueError(
            f"multi-process meshes must cover all {len(devices)} global "
            f"devices; --data_shards {n_data} x --seq_shards {seq_shards} x "
            f"--fsdp_shards {fsdp_shards} x --tp_shards {tp_shards} covers "
            f"{n_total} (use --data_shards 0 for auto)"
        )
    if n_total == 1 and _jax.process_count() == 1:
        return None
    return make_run_mesh(seq_shards, fsdp_shards, tp_shards, devices[:n_total])


def build_actor_learner_meshes(
    actor_devices: int = 0,
    learner_devices: int = 0,
    devices: Optional[Sequence[jax.Device]] = None,
) -> tuple[Mesh, Mesh]:
    """Disjoint ``(data, seq=1)`` submeshes for ``--async_actors``
    (Podracer/sebulba): actors own a leading device slice and run the rollout
    collector continuously; the learner owns the rest and consumes trajectory
    blocks.  Both submeshes expose the same ``data`` axis the rest of the
    sharding machinery (``global_init_state``, ``put_sharded_state``) already
    speaks, so state placement code is shared with the synchronous path.

    ``actor_devices`` / ``learner_devices`` of 0 mean auto: the unspecified
    side takes every device the other did not claim; with both auto the split
    is half/half (actors get the extra device on odd counts — collect is the
    wider program).  Single-process only: the two programs overlap as host
    threads, which a multi-process SPMD launch cannot express.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if jax.process_count() > 1:
        raise ValueError(
            "--async_actors overlaps actor/learner as host threads and is "
            "single-process only; multi-process runs use the fused dispatch"
        )
    if n < 2:
        raise ValueError(
            f"--async_actors needs at least 2 devices (one per submesh), "
            f"have {n}"
        )
    if actor_devices < 0 or learner_devices < 0:
        raise ValueError(
            f"--actor_devices/--learner_devices must be >= 0 (0 = auto), got "
            f"{actor_devices}/{learner_devices}"
        )
    if actor_devices == 0 and learner_devices == 0:
        n_learner = max(1, n // 2)
        n_actor = n - n_learner
    elif actor_devices == 0:
        n_learner = learner_devices
        n_actor = n - n_learner
    elif learner_devices == 0:
        n_actor = actor_devices
        n_learner = n - n_actor
    else:
        n_actor, n_learner = actor_devices, learner_devices
    if n_actor < 1 or n_learner < 1 or n_actor + n_learner > n:
        raise ValueError(
            f"--actor_devices {n_actor} + --learner_devices {n_learner} must "
            f"both be >= 1 and fit the {n} available devices"
        )
    actor_mesh = make_data_seq_mesh(1, devices[:n_actor])
    learner_mesh = make_data_seq_mesh(1, devices[n_actor:n_actor + n_learner])
    return actor_mesh, learner_mesh


def carve_actor_worker_meshes(actor_mesh: Mesh, n_workers: int) -> list[Mesh]:
    """Split the actor submesh into ``n_workers`` disjoint per-worker
    ``(data, seq=1)`` slices for ``--async_actor_workers``: each
    :class:`~mat_dcml_tpu.training.async_loop.ActorWorker` runs its own
    collect program on its own contiguous device slice, so N collects
    genuinely overlap instead of time-slicing one submesh.  ``n_workers=1``
    hands back the actor mesh unchanged (PR 13 parity — same devices, same
    compiled program).  The actor device count must divide evenly: a ragged
    split would give workers different data-axis widths and therefore
    different compiled collect programs for the same batch.
    """
    if n_workers < 1:
        raise ValueError(
            f"--async_actor_workers must be >= 1, got {n_workers}"
        )
    if n_workers == 1:
        return [actor_mesh]
    devices = list(actor_mesh.devices.flat)
    n = len(devices)
    if n % n_workers != 0:
        raise ValueError(
            f"--async_actor_workers {n_workers} must divide the actor "
            f"submesh's {n} devices evenly (one equal contiguous slice per "
            f"worker; pick --actor_devices as a multiple of the worker "
            f"count)"
        )
    per = n // n_workers
    return [
        make_data_seq_mesh(1, devices[i * per:(i + 1) * per])
        for i in range(n_workers)
    ]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    """Shard a tree's leaves along ``axis`` over the ``data`` mesh axis."""
    spec = [None] * axis + ["data"]
    return NamedSharding(mesh, P(*spec))


def shard_tree(tree, sharding: NamedSharding):
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
