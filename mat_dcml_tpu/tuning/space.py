"""Declarative perf-flag space + fingerprinted tuned-config artifacts.

The tuning surface (``--iters_per_dispatch``, update streaming/layout, decode
mode/spec-K, the serving bucket ladder, serve dtype, shard axes) is declared
here as :class:`Knob` entries with per-knob domains and validity predicates.
Validity reuses the stack's existing typed errors — a shard point is pruned
by the very ``ValueError`` ``parallel.mesh.build_run_mesh`` would raise at
startup, an engine point by ``EngineConfig.__post_init__`` — so invalid
points are rejected *before* any compile is paid, with the same message a
user would have seen.

A tuned-config artifact (:class:`TunedConfig`, ``tuned_config.json``) carries
a :class:`Fingerprint` — backend + device count/kind + model shape + env
preset — so an artifact never silently applies to the wrong hardware:
loading checks the fingerprint and a mismatch is the typed
:class:`TunedConfigMismatchError` (the config seam catches it, warns, and
continues on defaults).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

ARTIFACT_VERSION = 1

# staged coordinate-descent order: dispatch overhead first (it scales every
# later timing), then update-phase streaming/layout, then decode/serving
# programs, then shard axes (which need the most devices to matter)
GROUP_ORDER = ("dispatch", "update", "decode", "shards")


class TunedConfigMismatchError(ValueError):
    """Artifact fingerprint does not match the current hardware/shape."""

    def __init__(self, mismatches: List[str]):
        self.mismatches = list(mismatches)
        super().__init__(
            "tuned-config fingerprint mismatch: " + "; ".join(self.mismatches)
        )


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """What a tuned artifact was measured on.  ``preset`` is the env preset
    (``"<env_name>:<scenario>"``); model shape is the transformer trunk the
    probes compiled.  Serving-side loads may not know the env preset, so
    :meth:`mismatches` takes an ``ignore`` list."""

    backend: str
    device_count: int
    device_kind: str
    n_block: int
    n_embd: int
    n_head: int
    preset: str

    @classmethod
    def current(cls, preset: str, n_block: int, n_embd: int,
                n_head: int) -> "Fingerprint":
        import jax

        dev = jax.devices()[0]
        return cls(
            backend=jax.default_backend(),
            device_count=len(jax.devices()),
            device_kind=dev.device_kind,
            n_block=int(n_block), n_embd=int(n_embd), n_head=int(n_head),
            preset=preset,
        )

    def mismatches(self, other: "Fingerprint",
                   ignore: Tuple[str, ...] = ()) -> List[str]:
        out = []
        for f in dataclasses.fields(self):
            if f.name in ignore:
                continue
            mine, theirs = getattr(self, f.name), getattr(other, f.name)
            if mine != theirs:
                out.append(f"{f.name}: artifact {theirs!r} vs here {mine!r}")
        return out

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Fingerprint":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable flag: candidate ``domain`` (must contain ``default``), its
    coordinate-descent ``group``, which plane it targets (``train`` /
    ``serve`` / ``both`` — load seams skip knobs for the other plane), and an
    optional validity predicate ``(candidate_point, context) -> reason|None``
    that prunes a candidate before any compile is paid."""

    name: str
    domain: Tuple[Any, ...]
    default: Any
    group: str
    target: str = "train"
    validity: Optional[Callable[[dict, dict], Optional[str]]] = None

    def __post_init__(self):
        if self.group not in GROUP_ORDER:
            raise ValueError(f"unknown knob group {self.group!r} "
                             f"(expected one of {GROUP_ORDER})")
        if self.target not in ("train", "serve", "both"):
            raise ValueError(f"knob target must be train/serve/both, "
                             f"got {self.target!r}")
        if self.default not in self.domain:
            raise ValueError(
                f"knob {self.name!r}: default {self.default!r} "
                f"not in domain {self.domain!r}")

    def prune_reason(self, candidate_point: dict,
                     context: dict) -> Optional[str]:
        if self.validity is None:
            return None
        return self.validity(candidate_point, context)


@dataclasses.dataclass(frozen=True)
class FlagSpace:
    knobs: Tuple[Knob, ...]

    def __post_init__(self):
        names = [k.name for k in self.knobs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate knob names in space: {names}")

    def defaults(self) -> Dict[str, Any]:
        return {k.name: k.default for k in self.knobs}

    def knob(self, name: str) -> Knob:
        for k in self.knobs:
            if k.name == name:
                return k
        raise KeyError(name)

    def by_group(self) -> List[Tuple[str, List[Knob]]]:
        """Knobs grouped in staged-descent order (empty groups omitted)."""
        out = []
        for g in GROUP_ORDER:
            members = [k for k in self.knobs if k.group == g]
            if members:
                out.append((g, members))
        return out

    def subset(self, names) -> "FlagSpace":
        names = list(names)
        missing = [n for n in names if n not in {k.name for k in self.knobs}]
        if missing:
            raise KeyError(f"unknown knobs {missing}")
        return FlagSpace(tuple(k for k in self.knobs if k.name in names))

    def group(self, group: str) -> "FlagSpace":
        if group not in GROUP_ORDER:
            raise KeyError(f"unknown group {group!r} (one of {GROUP_ORDER})")
        return FlagSpace(tuple(k for k in self.knobs if k.group == group))


# ------------------------------------------------------------------ validity
#
# Predicates receive the FULL candidate point (the knob's value already
# merged) plus a context dict: devices (or device_count), n_rollout_threads,
# n_embd, and harness capability flags.  They return a human-readable prune
# reason or None — and they get that reason from the stack's own typed
# errors wherever one exists.

def mesh_validity(point: dict, context: dict) -> Optional[str]:
    """Prune shard points exactly the way the runner would reject them:
    ``parallel.mesh.build_run_mesh`` raises the typed ValueError, and its
    message IS the prune reason.  Divisibility of the env batch and the
    embedding dim ride along (base_runner's own startup checks)."""
    data = int(point.get("data_shards", 1))
    seq = int(point.get("seq_shards", 1))
    fsdp = int(point.get("fsdp_shards", 1))
    tp = int(point.get("tp_shards", 1))
    try:
        from mat_dcml_tpu.parallel.mesh import build_run_mesh

        build_run_mesh(data, seq, fsdp, tp, devices=context.get("devices"))
    except ValueError as e:
        return str(e)
    E = context.get("n_rollout_threads")
    if E and data > 1 and E % data:
        return (f"n_rollout_threads {E} must be divisible by "
                f"data_shards {data}")
    n_embd = context.get("n_embd")
    if n_embd and n_embd % (fsdp * tp):
        return (f"n_embd {n_embd} must be divisible by "
                f"fsdp_shards*tp_shards = {fsdp * tp}")
    if (fsdp > 1 or tp > 1) and not context.get("param_shard_probe", False):
        # honest scope note, not a hardware error: the probe harness times the
        # plain fused dispatch; fsdp/tp probes need the sharded-runner harness
        # of bench.py's BENCH_FSDP leg (a chip-session item)
        return "fsdp/tp probes need the sharded-runner harness (chip session)"
    return None


def engine_validity(point: dict, context: dict) -> Optional[str]:
    """Prune serving points with ``EngineConfig.__post_init__``'s own typed
    errors (non-ascending bucket ladders, unknown modes/dtypes)."""
    try:
        from mat_dcml_tpu.serving.engine import EngineConfig

        EngineConfig(
            buckets=tuple(point.get("serve_buckets", (1, 8, 32, 128))),
            decode_mode=point.get("decode_mode", "cached"),
            spec_block=int(point.get("spec_block", 8)),
            serve_dtype=point.get("serve_dtype", "f32"),
        )
    except ValueError as e:
        return str(e)
    return None


def spec_block_validity(point: dict, context: dict) -> Optional[str]:
    """spec_block is inert unless the (already decided) decode_mode is
    ``spec`` — probing other values would time identical programs."""
    if (point.get("decode_mode", "cached") != "spec"
            and point.get("spec_block", 8) != 8):
        return "spec_block is inert unless decode_mode=spec"
    return engine_validity(point, context)


def bf16_validity(point: dict, context: dict) -> Optional[str]:
    if point.get("serve_dtype") == "bf16" and not context.get(
            "allow_bf16", True):
        return "bf16 serving disabled by context (value-tolerance plane)"
    return engine_validity(point, context)


def default_space() -> FlagSpace:
    """The shipped tuning surface.  Training-side knob names are RunConfig /
    PPOConfig field names (the load seam applies them by name); serving-only
    knobs are ``serve_``-prefixed and map onto ``EngineConfig``."""
    return FlagSpace((
        # --- dispatch: host re-entry amortization (fused K-episode scan)
        Knob("iters_per_dispatch", (1, 2, 4, 8), 1, "dispatch"),
        # --- update: PPO epoch-buffer streaming + minibatch gather layout
        Knob("update_stream_chunks", (0, 2, 4, 8), 4, "update"),
        Knob("minibatch_layout", ("gather", "contiguous"), "gather", "update"),
        # --- decode: rollout/serving decode program + serving ladder/dtype
        Knob("decode_mode", ("cached", "scan", "spec"), "cached", "decode",
             target="both", validity=engine_validity),
        Knob("spec_block", (4, 8, 16), 8, "decode",
             target="both", validity=spec_block_validity),
        Knob("serve_buckets", ((1,), (1, 4, 16), (1, 8, 32, 128)),
             (1, 8, 32, 128), "decode", target="serve",
             validity=engine_validity),
        Knob("serve_dtype", ("f32", "bf16"), "f32", "decode", target="serve",
             validity=bf16_validity),
        # --- shards: mesh axes (typed mesh errors prune what can't build)
        Knob("data_shards", (1, 2, 4, 8), 1, "shards",
             validity=mesh_validity),
        Knob("fsdp_shards", (1, 2), 1, "shards", validity=mesh_validity),
        Knob("tp_shards", (1, 2), 1, "shards", validity=mesh_validity),
    ))


# ------------------------------------------------------------------ artifact

@dataclasses.dataclass
class TunedConfig:
    """Versioned tuned-config artifact: the winning point plus per-knob
    provenance (measured ratio vs default, trials, noise) and search
    accounting (wall time, probes run/pruned, budget, probe preset)."""

    fingerprint: Fingerprint
    knobs: Dict[str, Any]
    provenance: Dict[str, dict] = dataclasses.field(default_factory=dict)
    search: Dict[str, Any] = dataclasses.field(default_factory=dict)
    version: int = ARTIFACT_VERSION

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "fingerprint": self.fingerprint.to_dict(),
            "knobs": dict(self.knobs),
            "provenance": dict(self.provenance),
            "search": dict(self.search),
        }

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path) -> "TunedConfig":
        with open(path) as f:
            d = json.load(f)
        version = int(d.get("version", -1))
        if version != ARTIFACT_VERSION:
            raise ValueError(
                f"{path}: tuned-config version {version} != "
                f"{ARTIFACT_VERSION} (regenerate with scripts/autotune.py)")
        return cls(
            fingerprint=Fingerprint.from_dict(d["fingerprint"]),
            knobs=dict(d.get("knobs", {})),
            provenance=dict(d.get("provenance", {})),
            search=dict(d.get("search", {})),
            version=version,
        )

    def check(self, current: Fingerprint,
              ignore: Tuple[str, ...] = ()) -> None:
        """Raise :class:`TunedConfigMismatchError` unless this artifact was
        measured on hardware/shape matching ``current``."""
        bad = current.mismatches(self.fingerprint, ignore=ignore)
        if bad:
            raise TunedConfigMismatchError(bad)
