"""Perf-flag autotuning: probes, flag space, staged search, tuned artifacts.

- :mod:`~mat_dcml_tpu.tuning.probe` — matched-pair A/B machinery
  (``ab_trials`` + paired-ratio medians), shared with ``bench.py``.
- :mod:`~mat_dcml_tpu.tuning.space` — declarative knob domains with typed
  validity pruning, hardware fingerprints, the ``tuned_config.json``
  artifact, and :class:`TunedConfigMismatchError`.
- :mod:`~mat_dcml_tpu.tuning.search` — staged coordinate descent under a
  wall-clock budget.
- this module — the *load seams*: :func:`apply_tuned_cli` (training,
  called from ``config.parse_cli_with_extras``; explicit CLI flags always
  win) and :func:`apply_tuned_engine` (serving, ``scripts/serve_fleet.py``),
  both recording a :class:`TunedApplication` whose :meth:`gauges` feed the
  ``tune_`` telemetry family.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Dict, Optional

from mat_dcml_tpu.tuning.probe import (  # noqa: F401
    ProbeResult, ab_trials, median, median_of_ratios, paired_ratios,
    probe_candidates,
)
from mat_dcml_tpu.tuning.search import SearchResult, staged_search  # noqa: F401
from mat_dcml_tpu.tuning.space import (  # noqa: F401
    ARTIFACT_VERSION, GROUP_ORDER, Fingerprint, FlagSpace, Knob, TunedConfig,
    TunedConfigMismatchError, default_space,
)


@dataclasses.dataclass
class TunedApplication:
    """What happened when a tuned-config artifact met a run: which knobs
    applied, which were beaten by explicit CLI flags, which target the other
    plane, and whether the fingerprint matched at all."""

    path: str
    applied: Dict[str, Any] = dataclasses.field(default_factory=dict)
    overridden: Dict[str, Any] = dataclasses.field(default_factory=dict)
    skipped: Dict[str, Any] = dataclasses.field(default_factory=dict)
    provenance: Dict[str, dict] = dataclasses.field(default_factory=dict)
    search: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mismatch: bool = False

    def gauges(self) -> Dict[str, float]:
        """The ``tune_`` gauge family (schema:
        ``scripts/check_metrics_schema.py``): applied/overridden knob counts,
        the mismatch flag, search accounting, and per-knob measured ratios."""
        g = {
            "tune_applied": float(len(self.applied)),
            "tune_overridden": float(len(self.overridden)),
            "tune_mismatch": 1.0 if self.mismatch else 0.0,
        }
        for src, dst in (("wall_s", "tune_search_wall_s"),
                         ("probes_run", "tune_probes"),
                         ("probes_pruned", "tune_probes_pruned")):
            v = self.search.get(src)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                g[dst] = float(v)
        for name in self.applied:
            ratio = (self.provenance.get(name) or {}).get("ratio_vs_default")
            if isinstance(ratio, (int, float)) and not isinstance(ratio, bool):
                g[f"tune_ratio_{name}"] = float(ratio)
        return g


# the most recent application in this process; the training runner reads it
# in finalize() to publish tune_ gauges into its telemetry registry
_LAST: Optional[TunedApplication] = None


def record_application(app: TunedApplication) -> None:
    global _LAST
    _LAST = app


def last_application() -> Optional[TunedApplication]:
    return _LAST


def explicit_cli_flags(argv=None) -> set:
    """Flag names the user spelled out (``--name`` / ``--name=value``) —
    these always beat tuned values."""
    if argv is None:
        argv = sys.argv[1:]
    names = set()
    for a in argv:
        if isinstance(a, str) and a.startswith("--"):
            names.add(a[2:].split("=", 1)[0])
    return names


def apply_tuned_cli(path: str, run, ppo, argv=None, log=print):
    """Training load seam (``config.parse_cli_with_extras``): fill every
    RunConfig/PPOConfig knob the command line left at its default from the
    artifact.  Fingerprint mismatch -> warn, record ``tune_mismatch``, and
    return the configs unchanged (the run continues on defaults).
    Serving-only knobs (``serve_``-prefixed) ride the artifact untouched."""
    tc = TunedConfig.load(path)
    app = TunedApplication(path=str(path), provenance=tc.provenance,
                           search=tc.search)
    current = Fingerprint.current(
        preset=f"{run.env_name}:{run.scenario}",
        n_block=run.n_block, n_embd=run.n_embd, n_head=run.n_head,
    )
    try:
        tc.check(current)
    except TunedConfigMismatchError as e:
        app.mismatch = True
        record_application(app)
        log(f"[tune] IGNORING {path} ({e}); continuing on defaults")
        return run, ppo

    explicit = explicit_cli_flags(argv)
    run_fields = {f.name for f in dataclasses.fields(run)}
    ppo_fields = {f.name for f in dataclasses.fields(ppo)}
    run_up: Dict[str, Any] = {}
    ppo_up: Dict[str, Any] = {}
    for name, value in tc.knobs.items():
        if name in explicit:
            app.overridden[name] = value
        elif name in run_fields:
            run_up[name] = value
            app.applied[name] = value
        elif name in ppo_fields:
            ppo_up[name] = value
            app.applied[name] = value
        else:
            app.skipped[name] = value
    record_application(app)
    if run_up:
        run = dataclasses.replace(run, **run_up)
    if ppo_up:
        ppo = dataclasses.replace(ppo, **ppo_up)
    if app.applied or app.overridden:
        msg = f"[tune] applied {sorted(app.applied)} from {path}"
        if app.overridden:
            msg += f"; explicit CLI kept {sorted(app.overridden)}"
        log(msg)
    return run, ppo


def apply_tuned_engine(path: str, engine_cfg, model_cfg=None,
                       explicit=(), log=print):
    """Serving load seam (``scripts/serve_fleet.py``): fill EngineConfig
    fields the caller left unset from the artifact's ``serve_``/decode knobs.
    ``model_cfg`` (a MATConfig, when available) tightens the fingerprint to
    the model shape; the env preset is unknown at serve time and ignored.
    Returns the (possibly replaced) EngineConfig; the application record is
    available via :func:`last_application`."""
    tc = TunedConfig.load(path)
    app = TunedApplication(path=str(path), provenance=tc.provenance,
                           search=tc.search)
    ignore = ["preset"]
    shape = dict(n_block=tc.fingerprint.n_block, n_embd=tc.fingerprint.n_embd,
                 n_head=tc.fingerprint.n_head)
    if model_cfg is not None:
        shape = dict(n_block=model_cfg.n_block, n_embd=model_cfg.n_embd,
                     n_head=model_cfg.n_head)
    else:
        ignore += ["n_block", "n_embd", "n_head"]
    current = Fingerprint.current(preset=tc.fingerprint.preset, **shape)
    try:
        tc.check(current, ignore=tuple(ignore))
    except TunedConfigMismatchError as e:
        app.mismatch = True
        record_application(app)
        log(f"[tune] IGNORING {path} ({e}); serving on defaults")
        return engine_cfg

    # artifact knob name -> EngineConfig field (JSON lists become tuples)
    mapping = {
        "serve_buckets": ("buckets", lambda v: tuple(int(b) for b in v)),
        "serve_dtype": ("serve_dtype", str),
        "decode_mode": ("decode_mode", str),
        "spec_block": ("spec_block", int),
    }
    updates: Dict[str, Any] = {}
    for name, value in tc.knobs.items():
        if name not in mapping:
            app.skipped[name] = value
            continue
        field, conv = mapping[name]
        if name in explicit or field in explicit:
            app.overridden[name] = value
        else:
            updates[field] = conv(value)
            app.applied[name] = value
    record_application(app)
    if updates:
        engine_cfg = dataclasses.replace(engine_cfg, **updates)
        log(f"[tune] serving applied {sorted(app.applied)} from {path}")
    return engine_cfg
