"""Staged coordinate descent over knob groups under a wall-clock budget.

One knob at a time, in :data:`~mat_dcml_tpu.tuning.space.GROUP_ORDER`
(dispatch K -> update streaming/layout -> decode mode/bucket ladder -> shard
axes): the knob's candidates run as *alternating matched rounds* through
:func:`~mat_dcml_tpu.tuning.probe.ab_trials` — every candidate once per
round, order reversed on odd rounds — and the winner is decided by the
*median of per-round ratios vs the default* (the same estimator the
matched-pair bench legs use), not best-of-N: under shared transient load a
lucky single round must not pick a value that a later verify re-measure
rejects.  A non-default value only wins if its median ratio clears
``1 + switch_margin``; otherwise the default is kept.  The winning value is
frozen into the point before the next knob is probed.

Pruning happens before any probe is paid: validity predicates (typed
mesh/divisibility/engine errors) first, then an optional static-bytes
prescreen (``bytes_of``) that cuts candidates whose compiled bytes-accessed
exceed ``bytes_cut``x the cheapest candidate — a bytes-dominated point loses
on memory traffic before it is worth timing.

Everything nondeterministic is injected (``evaluate``, ``bytes_of``,
``clock``), so the search is exactly reproducible under a fake timer in
tests.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional

from mat_dcml_tpu.tuning.probe import ab_trials, median_of_ratios
from mat_dcml_tpu.tuning.space import FlagSpace, Knob


@dataclasses.dataclass
class SearchResult:
    point: Dict[str, Any]            # winning value per knob (defaults where
                                     # pruned/budget-truncated)
    provenance: Dict[str, dict]      # per-knob ratio/trials/noise/candidates
    wall_s: float
    probes_run: int                  # timed evaluations actually paid
    probes_pruned: int               # candidates cut before any timing
    truncated: bool                  # budget ran out before the space did


def staged_search(
    space: FlagSpace,
    evaluate: Callable[[dict, Knob], float],
    *,
    budget_s: float = 600.0,
    trials: int = 3,
    clock: Callable[[], float] = time.monotonic,
    log: Callable[[str], None] = lambda m: None,
    bytes_of: Optional[Callable[[dict, Knob], Optional[float]]] = None,
    bytes_cut: float = 2.0,
    switch_margin: float = 0.05,
    context: Optional[dict] = None,
) -> SearchResult:
    """Coordinate-descend ``space`` and return the winning point.

    ``evaluate(point, knob) -> score`` (higher = better) times one candidate
    point; ``bytes_of(point, knob)`` optionally returns a static
    bytes-accessed figure for the prescreen (None = no opinion).  The
    default value is exempt from the bytes cut — it anchors every ratio.
    """
    context = dict(context or {})
    point = space.defaults()
    provenance: Dict[str, dict] = {}
    probes_run = 0
    probes_pruned = 0
    truncated = False
    t0 = clock()

    for group, knobs in space.by_group():
        for knob in knobs:
            if clock() - t0 >= budget_s:
                truncated = True
                log(f"[search] budget {budget_s:.0f}s exhausted before "
                    f"{knob.name}; keeping defaults for the rest")
                break

            # 1) validity pruning — typed errors, before any compile
            candidates = []
            for v in knob.domain:
                cand = dict(point)
                cand[knob.name] = v
                reason = knob.prune_reason(cand, context)
                if reason is not None:
                    probes_pruned += 1
                    log(f"[search] prune {knob.name}={v!r}: {reason}")
                else:
                    candidates.append(v)

            # 2) bytes prescreen — cut bytes-dominated points without timing
            if bytes_of is not None and len(candidates) > 1:
                sizes = {}
                for v in candidates:
                    b = bytes_of({**point, knob.name: v}, knob)
                    if b is not None:
                        sizes[v] = float(b)
                if sizes:
                    floor = min(sizes.values())
                    for v, b in list(sizes.items()):
                        if v != knob.default and b > bytes_cut * floor:
                            candidates.remove(v)
                            probes_pruned += 1
                            log(f"[search] bytes-cut {knob.name}={v!r}: "
                                f"{b:.3g}B > {bytes_cut:g}x {floor:.3g}B")

            if len(candidates) <= 1:
                provenance[knob.name] = {
                    "value": point[knob.name], "default": knob.default,
                    "ratio_vs_default": 1.0, "trials": 0, "noise": 0.0,
                    "note": "all alternatives pruned",
                }
                continue

            # 3) matched alternating rounds over the surviving candidates
            legs = {
                repr(v): (lambda v=v: float(
                    evaluate({**point, knob.name: v}, knob)))
                for v in candidates
            }
            rounds = max(trials, 1)
            _, results = ab_trials(legs, rounds)
            probes_run += rounds * len(candidates)
            scores = {v: max(results[repr(v)]) for v in candidates}
            if knob.default in candidates:
                # Matched-pair median ratio vs the default from the same
                # rounds: robust to the lucky round that best-of-N rewards.
                ratios = {
                    v: median_of_ratios(results, repr(v), repr(knob.default))
                    for v in candidates
                }
                winner = max(candidates, key=lambda v: ratios[v])
                if ratios[winner] < 1.0 + switch_margin:
                    winner = knob.default
                ratio = ratios[winner]
            else:
                winner = max(candidates, key=lambda v: scores[v])
                ratio = 1.0
            win_rounds = results[repr(winner)]
            noise = ((max(win_rounds) - min(win_rounds))
                     / max(abs(max(win_rounds)), 1e-12))
            point[knob.name] = winner
            provenance[knob.name] = {
                "value": winner, "default": knob.default,
                "ratio_vs_default": round(ratio, 4),
                "trials": rounds, "noise": round(noise, 4),
                "candidates": {repr(v): round(scores[v], 4)
                               for v in candidates},
            }
            log(f"[search] {knob.name} -> {winner!r} "
                f"({ratio:.3f}x default, noise {noise:.1%})")
        if truncated:
            break

    return SearchResult(
        point=point, provenance=provenance, wall_s=clock() - t0,
        probes_run=probes_run, probes_pruned=probes_pruned,
        truncated=truncated,
    )
