"""Matched-pair probe machinery: alternating A/B trials + paired-ratio stats.

One implementation of the measurement discipline every bench leg and the
autotuner share, extracted from ``bench.py`` (the OBS / CHAOS / OBS_FED /
cached-decode legs each re-derived pieces of it):

- :func:`ab_trials` — best-of-N *alternating* trials: every leg runs once per
  round, order reversed on odd rounds, so neither side systematically
  inherits a cold cache or a neighbour's transient load.
- :func:`paired_ratios` / :func:`median_of_ratios` — the matched-pair
  estimator: round *i*'s legs ran back-to-back under the same transient
  container load, so the per-round ratio cancels the drift and the median
  sheds one-sided outlier rounds.  On a noisy shared-CPU box this is the
  honest overhead/speedup estimate (the OBS_FED leg's contract metric).
- :class:`ProbeResult` — per-candidate score series with best-of-N and a
  relative-noise figure the tuned-config artifact records as provenance.

No jax import here: probes receive callables; the timing/compile discipline
(warmup excluded, zero steady-state recompiles asserted) lives with the
caller that builds the leg — ``bench.py`` legs and
``scripts/autotune.py``'s :class:`ProbeHarness`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def ab_trials(legs: dict, trials: int, score=None) -> tuple:
    """Best-of-N alternating-trial A/B runner — the pattern the OBS,
    CACHED_DECODE, and ASYNC legs share.  Runs every leg callable once per
    trial round, REVERSING the leg order on odd rounds so neither side
    systematically inherits a cold cache or a neighbour's transient load.
    On a shared-CPU container contention only ever *slows* a leg, so
    best-of-N per side is the honest estimate of each configuration's
    capability.  Returns ``(best, results)``: ``results[name]`` is the list
    of per-round returns in run order; ``best[name]`` is the score-maximal
    one (``None`` when no ``score`` is given — callers reducing per-metric,
    like the decode leg's min-p50/max-QPS, use ``results`` directly)."""
    results = {name: [] for name in legs}
    names = list(legs)
    for trial in range(max(trials, 1)):
        order = names if trial % 2 == 0 else list(reversed(names))
        for name in order:
            results[name].append(legs[name]())
    best = (None if score is None
            else {name: max(recs, key=score) for name, recs in results.items()})
    return best, results


def median(values: Sequence[float]) -> float:
    """Median of a non-empty sequence (mean-of-two on even lengths)."""
    vals = sorted(values)
    if not vals:
        raise ValueError("median of an empty sequence")
    mid = len(vals) // 2
    return vals[mid] if len(vals) % 2 else (vals[mid - 1] + vals[mid]) / 2.0


def paired_ratios(
    results: Dict[str, list],
    num: str,
    den: str,
    value: Callable = lambda r: r,
) -> List[float]:
    """Sorted per-round ``num/den`` ratios from an :func:`ab_trials` result.

    Round *i*'s legs ran back-to-back under the same transient load, so each
    ratio is a matched pair that cancels the drift; ``value`` extracts the
    scalar from a per-round record (identity for plain-float legs)."""
    return sorted(
        value(a) / max(value(b), 1e-9)
        for a, b in zip(results[num], results[den])
    )


def median_of_ratios(
    results: Dict[str, list],
    num: str,
    den: str,
    value: Callable = lambda r: r,
) -> float:
    """Matched-pair median ratio — the contract estimator on noisy boxes."""
    return median(paired_ratios(results, num, den, value))


@dataclasses.dataclass
class ProbeResult:
    """One candidate's score series across alternating rounds (higher =
    better).  ``noise`` is the relative spread the artifact records so a
    downstream verify gate knows how much margin a ratio deserves."""

    name: str
    scores: List[float]

    @property
    def best(self) -> float:
        return max(self.scores)

    @property
    def noise(self) -> float:
        if not self.scores:
            return 0.0
        top = max(self.scores)
        return (top - min(self.scores)) / max(abs(top), 1e-12)


def probe_candidates(
    legs: Dict[str, Callable[[], float]], trials: int
) -> Dict[str, ProbeResult]:
    """Run scalar-scored candidate legs through :func:`ab_trials` and wrap
    each side's rounds as a :class:`ProbeResult`."""
    _, results = ab_trials(legs, trials)
    return {
        name: ProbeResult(name, [float(s) for s in scores])
        for name, scores in results.items()
    }
