"""mat_dcml_tpu: a TPU-native (JAX/XLA/Pallas) Multi-Agent Transformer framework.

A from-scratch reimplementation of the capabilities of the reference
MAT-DCML project (Multi-Agent Transformer applied to Distributed Coded
Machine Learning worker selection), redesigned TPU-first:

- Agents-as-sequence MAT models as pure Flax modules (``models/``).
- Fused attention and scan-based autoregressive decoding (``ops/``, ``models/decode.py``).
- Pure-JAX vectorized environments (``envs/``) replacing subprocess vec-envs.
- Single-jit PPO training with mesh sharding (``training/``, ``parallel/``).

Reference parity citations use the form ``<file>:<line>`` into the upstream
tree (e.g. ``ma_transformer.py:233``); see SURVEY.md for the layer map.
"""

__version__ = "0.1.0"
