"""Single-agent joint view of the DCML env for centralized PPO.

The reference's ``ppo`` algorithm flattens all DCML agents into ONE decision:
a 201-wide actor feature vector sliced into 100 select-bit categorical heads +
a Gaussian coding-ratio tail (``ppo_policy.py`` + the mixed ``Action_Space``
branch of ``act.py:83-105``), stored in the joint ``SingleReplayBuffer``.
This adapter exposes that view over the vectorized JAX env: one "agent" whose
obs is the centralized state and whose action is the joint
``(100 bits + ratio)`` vector, translated to the per-agent layout the core
``DCMLEnv.step`` consumes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.dcml.env import DCMLEnv, TimeStep
from mat_dcml_tpu.envs.spaces import DCMLActionSpace


class JointDCMLEnv:
    """Wraps ``DCMLEnv`` with (A,) -> (1,) agent collapsing."""

    def __init__(self, env: DCMLEnv):
        self.env = env
        w = env.n_agents - 1  # worker count
        self.n_agents = 1
        self.obs_dim = env.share_obs_dim
        self.share_obs_dim = env.share_obs_dim
        self.action_space = DCMLActionSpace(
            n=env.action_dim, n_sub=w, semi_index=-1, mixed=True,
            multi_discrete=True, continuous=True,
        )
        self.action_dim = self.action_space.sample_dim  # w + 1

    def _wrap_ts(self, ts: TimeStep) -> TimeStep:
        w = self.env.n_agents - 1
        share = ts.share_obs[:1]                       # (1, sob)
        avail = ts.available_actions[None, :w, :]      # (1, w, 2)
        return TimeStep(
            obs=share,
            share_obs=share,
            available_actions=avail,
            reward=ts.reward[:1],
            done=ts.done[:1],
            delay=ts.delay,
            payment=ts.payment,
            objectives=ts.objectives[:1],
        )

    def reset(self, key: jax.Array, episode_idx=0):
        state, ts = self.env.reset(key, episode_idx)
        return state, self._wrap_ts(ts)

    def step(self, state, action: jax.Array):
        # action: (1, w + 1) joint -> per-agent (A, 1)
        joint = action[0]
        per_agent = joint[:, None]                     # (A, 1): bits then ratio
        state, ts = self.env.step(state, per_agent)
        return state, self._wrap_ts(ts)
