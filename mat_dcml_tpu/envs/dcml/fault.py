"""Fleet-stress fault injection for the DCML env.

The `envs/mamujoco/fault.py` pattern (fault masking INSIDE the jitted step,
one compiled program per fault preset, no host-side surgery) extended to the
worker-selection env — the first rung of the ROADMAP fleet-stress item: a
served scheduler should be trained against the traffic it will actually see,
which includes dead nodes and stragglers, not just the uniform random
disable draw the reference env makes.

Two fault channels, both pure ``jnp`` transforms of :class:`DCMLState`:

- **dead nodes**: permanently unavailable workers, ORed into the episode's
  random ``unavailable`` draw.  ``disable_rate`` is recomputed from the
  merged mask so the rank features in ``_observe`` (which divide by
  ``W - disable_rate``) stay consistent with what the policy can select.
- **stragglers**: workers whose failure probability is floored at
  ``straggler_pr_floor`` (chronically lossy links -> more retries) and whose
  local workload trace is shifted up by ``straggler_load`` (busy machines ->
  slower queue drain).  They stay selectable — the policy has to *learn* to
  route around them.

Injection happens at every reset, including the auto-reset inside ``step``,
so the faults persist across the episode stream; observations are rebuilt
from the injected state so the policy sees the world it acts in.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from mat_dcml_tpu.envs.dcml.env import DCMLEnv, DCMLState, TimeStep


@dataclasses.dataclass(frozen=True)
class DCMLFaultConfig:
    """Static fault preset (hashable -> safe to close over in jit)."""

    dead_nodes: Tuple[int, ...] = ()
    straggler_nodes: Tuple[int, ...] = ()
    # minimum failure probability for stragglers (0 = leave their draw alone)
    straggler_pr_floor: float = 0.0
    # additive local-workload shift for stragglers, clipped into [0, 1]
    straggler_load: float = 0.0


def fleet_stress_preset(n_dead: int = 1, n_stragglers: int = 2,
                        pr_floor: float = 0.7,
                        load: float = 0.5) -> DCMLFaultConfig:
    """Minimal fleet-stress variant: the first ``n_dead`` workers are down,
    the next ``n_stragglers`` are chronically slow.  Deterministic worker
    indices (not a random draw) so train and eval stress the same nodes."""
    return DCMLFaultConfig(
        dead_nodes=tuple(range(n_dead)),
        straggler_nodes=tuple(range(n_dead, n_dead + n_stragglers)),
        straggler_pr_floor=pr_floor,
        straggler_load=load,
    )


class FaultyDCMLEnv:
    """DCMLEnv wrapper injecting a :class:`DCMLFaultConfig` into every state.

    Mirrors ``mamujoco.fault.FaultyAgentWrapper``: forwards the attribute
    surface runners/policies read (``cfg`` included — ``build_mat_policy``
    reads ``env.cfg.consts``), keeps every method jit/vmap-safe.
    """

    def __init__(self, env: DCMLEnv, fault: DCMLFaultConfig = DCMLFaultConfig()):
        self.env = env
        self.fault = fault
        self.cfg = env.cfg
        for attr in ("n_agents", "obs_dim", "share_obs_dim", "action_dim",
                     "base_workloads"):
            if hasattr(env, attr):
                setattr(self, attr, getattr(env, attr))
        W = env.cfg.consts.worker_number_max
        bad = [i for i in (*fault.dead_nodes, *fault.straggler_nodes)
               if not 0 <= i < W]
        if bad:
            raise ValueError(f"fault node ids {bad} out of range [0, {W})")

    def _inject(self, state: DCMLState) -> DCMLState:
        W = self.env.cfg.consts.worker_number_max
        iw = jnp.arange(W)
        f = self.fault
        unavailable = state.unavailable
        worker_prs = state.worker_prs
        trace = state.trace
        if f.dead_nodes:
            dead = jnp.isin(iw, jnp.asarray(f.dead_nodes))
            unavailable = unavailable | dead
        if f.straggler_nodes:
            strag = jnp.isin(iw, jnp.asarray(f.straggler_nodes))
            if f.straggler_pr_floor > 0.0:
                worker_prs = jnp.where(
                    strag, jnp.maximum(worker_prs, f.straggler_pr_floor),
                    worker_prs)
            if f.straggler_load > 0.0:
                trace = jnp.where(strag[:, None],
                                  jnp.clip(trace + f.straggler_load, 0.0, 1.0),
                                  trace)
        # keep the rank denominator (W - disable_rate) consistent with the
        # merged availability mask
        disable_rate = unavailable.sum().astype(jnp.int32)
        return state._replace(unavailable=unavailable, worker_prs=worker_prs,
                              trace=trace, disable_rate=disable_rate)

    def _reobserve(self, state: DCMLState, ts: TimeStep) -> TimeStep:
        obs, share_obs, ava = self.env._observe(state)
        return ts._replace(obs=obs, share_obs=share_obs, available_actions=ava)

    def reset(self, key, episode_idx=0):
        state, ts = self.env.reset(key, episode_idx)
        state = self._inject(state)
        return state, self._reobserve(state, ts)

    def step(self, state: DCMLState, action):
        # the incoming state was already injected (reset/previous step), so
        # the wrapped step's reward/delay math runs against the faulty fleet;
        # only the auto-reset NEXT state (and its observations, which this
        # timestep carries) needs injection here
        new_state, ts = self.env.step(state, action)
        new_state = self._inject(new_state)
        return new_state, self._reobserve(new_state, ts)

    def encode_single_agent_state(self, state: DCMLState, binary: bool = True):
        return self.env.encode_single_agent_state(state, binary)
