"""Per-agent (separated-policy) view of the DCML env.

The reference's heterogeneous-agent DCML modes (happo and the per-agent branch
of ``DCML_..._SingleProcess.py:51-52``) give each worker agent
``Action_Space(2)`` and the master a continuous ``Action_Space(1, extra=True)``.
Here all agents expose one :class:`~mat_dcml_tpu.envs.spaces.MixedRole` space;
the role flag rides as a third ``available_actions`` column so stacked /
shared-parameter policies stay structurally homogeneous (see spaces.py).

Actions come back as ``(A, 1)`` float — worker select bits then the master's
ratio — which is exactly the layout ``DCMLEnv.step`` consumes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.dcml.env import DCMLEnv, TimeStep
from mat_dcml_tpu.envs.spaces import MixedRole


class PerAgentDCMLEnv:
    """Wraps ``DCMLEnv`` with role-augmented availability masks."""

    def __init__(self, env: DCMLEnv):
        self.env = env
        self.n_agents = env.n_agents
        self.obs_dim = env.obs_dim
        self.share_obs_dim = env.share_obs_dim
        self.action_space = MixedRole(n=env.action_dim, cont_dim=1)
        self.action_dim = env.action_dim
        w = env.n_agents - env.cfg.consts.extra_agent
        self._role = jnp.concatenate(
            [jnp.zeros((w, 1)), jnp.ones((env.n_agents - w, 1))]
        ).astype(jnp.float32)

    def _wrap_ts(self, ts: TimeStep) -> TimeStep:
        avail = jnp.concatenate([ts.available_actions.astype(jnp.float32), self._role], axis=-1)
        return ts._replace(available_actions=avail)

    def reset(self, key: jax.Array, episode_idx=0):
        state, ts = self.env.reset(key, episode_idx)
        return state, self._wrap_ts(ts)

    def step(self, state, action: jax.Array):
        state, ts = self.env.step(state, action)
        return state, self._wrap_ts(ts)
