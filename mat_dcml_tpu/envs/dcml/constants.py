"""DCML environment constants.

Mirrors ``DCML_ENVs/DCML_utils/DCML_Config.py`` plus the module-level constants
of ``DCML_Master.py:6-16`` and ``DCML_Worker_TIMESLOT_MultiProcess.py:5-12``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DCMLConsts:
    # DCML_Config.py
    worker_number_max: int = 100
    extra_agent: int = 1
    action_dim: int = 2
    local_obs_dim: int = 7            # DYNAMIC_PRICE = False branch
    sob_dim: int = 102
    local_workload_period: int = 20
    time_slot: int = 100
    state_ratio: float = 1.0
    pr_min: float = 0.0
    pr_max: float = 0.95
    continue_probability: float = 0.8
    heterogeneous: bool = True
    non_shannon_data_rate: float = 150.0 * (2**10) * (2**10)
    unavailable_price: float = 10.0
    master_price: float = 0.0

    # DCML_Master.py:6-16
    r_min: int = 2**10
    r_max: int = 2**20
    c_min: int = 2**5
    c_max: int = 2**10

    # DCML_Worker_TIMESLOT_MultiProcess.py:5-12
    worker_frequency: float = 2e9
    bit_to_byte: float = 4.0
    second_to_centsec: float = 1.0
    lambda_of_poisson: float = 3.0

    # DCML_ENV_Functions.py:15-17
    reward_alpha: float = 99.0
    reward_beta: float = 1.0

    @property
    def n_agents(self) -> int:
        return self.worker_number_max + self.extra_agent
