"""DCML environment constants.

Mirrors ``DCML_ENVs/DCML_utils/DCML_Config.py`` plus the module-level constants
of ``DCML_Master.py:6-16`` and ``DCML_Worker_TIMESLOT_MultiProcess.py:5-12``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DCMLConsts:
    # DCML_Config.py
    worker_number_max: int = 100
    extra_agent: int = 1
    action_dim: int = 2
    local_obs_dim: int = 7            # DYNAMIC_PRICE = False branch
    sob_dim: int = 102
    local_workload_period: int = 20
    time_slot: int = 100
    state_ratio: float = 1.0
    pr_min: float = 0.0
    pr_max: float = 0.95
    continue_probability: float = 0.8
    heterogeneous: bool = True
    non_shannon_data_rate: float = 150.0 * (2**10) * (2**10)
    unavailable_price: float = 10.0
    master_price: float = 0.0

    # DCML_Master.py:6-16
    r_min: int = 2**10
    r_max: int = 2**20
    c_min: int = 2**5
    c_max: int = 2**10

    # Shannon channel mode (Shannon.py + DCML_Master.py:10-13,29-31,41-45,
    # DCML_Config.py:10-11): rates B*log2(1 + P*d^-4 / noise)
    min_worker_power: float = 10.0        # Watt
    max_worker_power: float = 20.0
    tx_power_min: float = 50.0            # master transmit power ~ U(50, 60)
    tx_power_max: float = 60.0
    distance_min: float = 10.0            # meters
    distance_max: float = 100.0
    b_total: float = 100e9                # split evenly across workers (:29-31)
    noise_mw: float = 10.0 ** (-50.0 / 10.0)   # -50 dBm -> mW (Shannon.py:9)
    path_loss_exponent: float = -4.0

    # DYNAMIC_PRICE branch (DCML_Config.py:13-17): per-worker unit price in
    # obs; local_obs_dim must be 8 when enabled
    dynamic_price: bool = False

    # DCML_Worker_TIMESLOT_MultiProcess.py:5-12
    worker_frequency: float = 2e9
    bit_to_byte: float = 4.0
    second_to_centsec: float = 1.0
    lambda_of_poisson: float = 3.0

    # DCML_ENV_Functions.py:15-17
    reward_alpha: float = 99.0
    reward_beta: float = 1.0

    @property
    def n_agents(self) -> int:
        return self.worker_number_max + self.extra_agent
