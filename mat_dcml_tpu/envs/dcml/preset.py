"""Preset (deterministic-eval) fixture tooling for the DCML env.

The reference's closest thing to a test harness (SURVEY.md §4): the env can
snapshot its stochastic inputs to ``.npy`` fixtures and replay them, and
``modify_preset`` pins single factors for controlled sweeps
(``DCML_BID_FIRST_MA_ENV_SingleProcess.py:316-353``).  File format matches the
shipped ``data/dcml_benchmark/Sample_*`` fixtures exactly:

- ``<prefix>master_states.npy``: one save, ``(N, 3)`` float = (R, C, Pr)
- ``<prefix>worker_states.npy``: two stacked saves — worker failure probs
  ``(N, W)`` then disable rates ``(N,)``
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from mat_dcml_tpu.envs.dcml.constants import DCMLConsts


@dataclasses.dataclass
class PresetData:
    """In-memory preset fixture: the three arrays ``DCMLEnv`` replays."""

    master: np.ndarray          # (N, 3) = (R, C, Pr)
    worker_prs: np.ndarray      # (N, W)
    disable_rates: np.ndarray   # (N,)

    @property
    def n_episodes(self) -> int:
        return self.master.shape[0]


def generate_preset_data(
    rng: np.random.Generator,
    n_episodes: int,
    consts: DCMLConsts = DCMLConsts(),
    *,
    row: Optional[float] = None,
    col: Optional[float] = None,
    probability: Optional[float] = None,
    disable_rate: Optional[int] = None,
) -> PresetData:
    """Draw ``n_episodes`` of env randomness, optionally pinning factors
    (``generate_preset_data``, ``DCML_..._SingleProcess.py:316-343``).

    Distributions match ``Master.reset`` (R ~ randint[R_MIN, round(1.1*R_MAX)],
    C likewise, Pr ~ U[PR_MIN, PR_MAX]) and ``random.randint(1, 80)`` for the
    disable rate.
    """
    c = consts
    r = rng.integers(c.r_min, round(c.r_max * 1.1) + 1, n_episodes).astype(np.float64)
    cc = rng.integers(c.c_min, round(c.c_max * 1.1) + 1, n_episodes).astype(np.float64)
    pr = rng.uniform(c.pr_min, c.pr_max, n_episodes)
    if row is not None:
        r[:] = row
    if col is not None:
        cc[:] = col
    if probability is not None:
        pr[:] = probability
    if disable_rate is None:
        drs = rng.integers(1, 81, n_episodes)
    else:
        drs = np.full(n_episodes, disable_rate, np.int64)
    worker_prs = rng.uniform(c.pr_min, c.pr_max, (n_episodes, c.worker_number_max))
    return PresetData(
        master=np.stack([r, cc, pr], axis=1),
        worker_prs=worker_prs,
        disable_rates=drs,
    )


def modify_preset(
    data: PresetData,
    *,
    r: Optional[float] = None,
    c: Optional[float] = None,
    pr: Optional[float] = None,
    disable_rate: Optional[int] = None,
) -> PresetData:
    """Pin single factors across all episodes for a controlled sweep
    (``modify_preset``, ``DCML_..._SingleProcess.py:344-353``).  Returns a new
    ``PresetData``; the input is not mutated."""
    master = data.master.copy()
    worker_prs = data.worker_prs.copy()
    drs = data.disable_rates.copy()
    if r is not None:
        master[:, 0] = r
    if c is not None:
        master[:, 1] = c
    if pr is not None:
        worker_prs[:] = pr
    if disable_rate is not None:
        drs[:] = disable_rate
    return PresetData(master, worker_prs, drs)


def save_preset(data: PresetData, dir_name: str | Path, prefix: str = "") -> None:
    """Write the two-file fixture format the reference ships."""
    d = Path(dir_name)
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"{prefix}master_states.npy", "wb") as f:
        np.save(f, data.master)
    with open(d / f"{prefix}worker_states.npy", "wb") as f:
        np.save(f, data.worker_prs)
        np.save(f, data.disable_rates)


def load_preset_data(dir_name: str | Path, prefix: str = "") -> PresetData:
    d = Path(dir_name)
    with open(d / f"{prefix}master_states.npy", "rb") as f:
        master = np.load(f, allow_pickle=False)
    with open(d / f"{prefix}worker_states.npy", "rb") as f:
        worker_prs = np.load(f, allow_pickle=False)
        disable_rates = np.load(f, allow_pickle=False)
    return PresetData(np.asarray(master, np.float64), worker_prs, disable_rates)


def load_sample(bench_dir: str | Path, sample: int = 1) -> PresetData:
    """Load one of the 10 shipped ``Sample_<k>`` fixtures (1001 episodes)."""
    return load_preset_data(bench_dir, prefix=f"Sample_{sample}")
