"""Pure-JAX DCML worker-selection environment.

A stateless, fully-vectorized rewrite of the reference env stack
(``DCML_BID_FIRST_MA_ENV_SingleProcess.py`` + ``DCML_Master.py`` +
``DCML_Worker_TIMESLOT_MultiProcess.py``).  Where the reference runs 100
pure-Python worker simulations per step inside subprocess vec-envs
(SURVEY.md §3.5), this env is a ``step(state, action) -> (state, timestep)``
array program: ``vmap`` it over thousands of env instances and ``lax.scan`` it
inside the rollout jit.

Key closed-form rewrites (all proven equivalent in distribution — see
tests/test_dcml_env.py):

- Geometric retry loops (``DCML_Worker...py:54-59,100-105``): the loop
  ``n=1; while U()<Pr: n+=1`` adds ``F ~ floor(log U / log Pr)`` failures;
  sampled directly.
- The queue-drain loop (``DCML_Worker...py:87-95``): the local workload trace
  is 20-periodic, so the first ``m`` with cumulative free capacity >= cost is
  computed from one period's cumulative sum (q full periods + partial index).
- The reference's upload-retry block is indented *inside* the drain loop
  (``DCML_Worker...py:99-106``) so retry counts inflate once per drained
  timeslot; replicated faithfully via a negative-binomial draw (sum of m
  geometric draws, sampled as Poisson(Gamma(m, Pr/(1-Pr)))).  Set
  ``fixed_upload_retry=True`` for the evidently-intended single draw
  (documented divergence, SURVEY.md §7 "known defects").
- The K-th-smallest selected delay (``DCML_..._SingleProcess.py:128-130``):
  unselected delays set to +inf, one sort, take ``[K-1]``.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.envs.dcml.constants import DCMLConsts

_INF = jnp.inf


class DCMLState(NamedTuple):
    """Per-env state; the fields the *next* ``step`` consumes (set by the last
    auto-reset, mirroring how the reference's ``reset`` primes ``step``)."""

    rng: jax.Array               # PRNG key
    r_rows: jax.Array            # master R (float32, integral value)
    c_cols: jax.Array            # master C
    master_pr: jax.Array         # master failure prob (homogeneous mode)
    worker_prs: jax.Array        # (W,) per-worker failure probs
    trace: jax.Array             # (W, P) local workload in [0, 1]
    unavailable: jax.Array       # (W,) bool
    arrive_time: jax.Array       # int32 in [0, P)
    disable_rate: jax.Array      # int32
    episode_idx: jax.Array       # int32, preset replay cursor
    # per-worker channel rates; NON_SHANNON_DATA_RATE unless shannon_enable
    # (DCML_Basic_Env.py:18-33).  The worker sim divides by download for both
    # directions — the reference's upload formula reads self.download
    # (DCML_Worker...py:106), a quirk replicated faithfully.
    upload_trans: Optional[jax.Array] = None     # (W,)
    download_trans: Optional[jax.Array] = None   # (W,)
    # per-worker unit price (Poisson-derived, DCML_Worker...py:114-118);
    # observed only under dynamic_price
    prices: Optional[jax.Array] = None           # (W,)


class TimeStep(NamedTuple):
    obs: jax.Array               # (A, local_obs_dim)
    share_obs: jax.Array         # (A, sob_dim)
    available_actions: jax.Array  # (A, action_dim)
    reward: jax.Array            # (A, 1)
    done: jax.Array              # (A,) bool
    delay: jax.Array             # scalar info
    payment: jax.Array           # scalar info
    # MO-MAT objective vector (A, 2): (-delay*alpha, -payment*beta) — the
    # per-channel decomposition of the scalar reward
    # (``DCML_ENV_Functions.py:15-17``); the shipped training curves
    # ``momat_ct.csv`` / ``momat_payment.csv`` track exactly these two
    # channels (SURVEY.md §6).  objectives.sum(-1) == reward.
    objectives: jax.Array


@dataclasses.dataclass(frozen=True)
class DCMLEnvConfig:
    consts: DCMLConsts = DCMLConsts()
    fixed: bool = False              # "select all available, K=0.7N" baseline (:58-62)
    preset: bool = False             # deterministic eval replay (:25-32,174-194)
    fixed_upload_retry: bool = False  # fix the reference's in-loop retry defect
    max_drain_slots: float = 2**30   # numerical guard on the drain-loop bound
    # Shannon-rate transmission mode (Shannon.py:14-21, DCML_Basic_Env.py:
    # 18-33): per-worker channel rates from the path-loss formula replace the
    # fixed NON_SHANNON_DATA_RATE; master Pr pinned to 0 (DCML_Master.py:
    # 47-56); share_obs carries the scaled rate vectors instead of worker Prs
    # (DCML_..._SingleProcess.py:248-253)
    shannon_enable: bool = False


class DCMLEnv:
    """Functional env bundle.  All methods are jit/vmap-safe."""

    def __init__(
        self,
        config: DCMLEnvConfig = DCMLEnvConfig(),
        base_workloads: Optional[np.ndarray] = None,
        preset_master: Optional[np.ndarray] = None,
        preset_worker_prs: Optional[np.ndarray] = None,
        preset_disable_rates: Optional[np.ndarray] = None,
        data_dir: str | Path = "data",
    ):
        self.cfg = config
        c = config.consts
        if base_workloads is None:
            base_workloads = load_base_workloads(Path(data_dir) / "workloads.txt", c)
        self.base_workloads = jnp.asarray(base_workloads, jnp.float32)
        assert self.base_workloads.shape == (c.worker_number_max, c.local_workload_period)
        if config.preset:
            if preset_master is None:
                preset_master, preset_worker_prs, preset_disable_rates = load_preset(
                    Path(data_dir) / "dcml_benchmark", sample=1
                )
            self.preset_master = jnp.asarray(preset_master, jnp.float32)
            self.preset_worker_prs = jnp.asarray(preset_worker_prs, jnp.float32)
            self.preset_disable_rates = jnp.asarray(preset_disable_rates, jnp.int32)
        else:
            self.preset_master = None
            self.preset_worker_prs = None
            self.preset_disable_rates = None

        if c.dynamic_price and c.local_obs_dim != 8:
            raise ValueError(
                "dynamic_price=True needs local_obs_dim=8 (DCML_Config.py:13-17)"
            )
        self.n_agents = c.n_agents
        self.obs_dim = c.local_obs_dim
        # Shannon share_obs: [R, C] + upload/1e7 + download/1e7 (:248-251)
        self.share_obs_dim = 2 + 2 * c.worker_number_max if config.shannon_enable else c.sob_dim
        self.action_dim = c.action_dim

    # ------------------------------------------------------------------ reset

    def reset(self, key: jax.Array, episode_idx: jax.Array | int = 0) -> Tuple[DCMLState, TimeStep]:
        """Fresh episode; mirrors ``Env.reset`` (``DCML_..._SingleProcess.py:157-274``)."""
        c = self.cfg.consts
        key, k_dr, k_at, k_master, k_prs, k_trace, k_ava, k_chan, k_price = jax.random.split(key, 9)

        episode_idx = jnp.asarray(episode_idx, jnp.int32)
        # random.randint(1, 80) — inclusive (:158)
        disable_rate = jax.random.randint(k_dr, (), 1, 81, jnp.int32)
        arrive_time = jax.random.randint(k_at, (), 0, c.local_workload_period, jnp.int32)

        # Master.reset (:46-56): R ~ randint(R_MIN, round(R_MAX*1.1)),
        # C ~ randint(C_MIN, round(C_MAX*1.1)), Pr ~ U(0, 0.95), inclusive ends.
        k_r, k_c, k_pr = jax.random.split(k_master, 3)
        r_rows = jax.random.randint(k_r, (), c.r_min, round(c.r_max * 1.1) + 1).astype(jnp.float32)
        c_cols = jax.random.randint(k_c, (), c.c_min, round(c.c_max * 1.1) + 1).astype(jnp.float32)
        master_pr = jax.random.uniform(k_pr, (), minval=c.pr_min, maxval=c.pr_max)

        worker_prs = jax.random.uniform(k_prs, (c.worker_number_max,), minval=c.pr_min, maxval=c.pr_max)

        if self.cfg.preset:
            # Wrap past the end of the fixture instead of JAX's silent
            # clamp-at-last-row (the reference would IndexError there; its
            # benchmark protocol never exceeds the 1001 episodes).
            idx = jnp.mod(episode_idx, self.preset_master.shape[0])
            row = self.preset_master[idx]
            r_rows, c_cols, master_pr = row[0], row[1], row[2]
            worker_prs = self.preset_worker_prs[idx]
            disable_rate = self.preset_disable_rates[idx]

        # all_workload = clip(base * U(0.8, 1.2), 0, 1)  (DCML_Worker...py:39,111)
        noise = jax.random.uniform(k_trace, self.base_workloads.shape, minval=0.8, maxval=1.2)
        trace = jnp.clip(self.base_workloads * noise, 0.0, 1.0)

        # np.random.choice(W, disable_rate, replace=False) (:199): mark the
        # first `disable_rate` slots of a random permutation unavailable.
        perm_rank = jnp.argsort(jax.random.uniform(k_ava, (c.worker_number_max,)))
        unavailable = perm_rank < disable_rate

        W = c.worker_number_max
        if self.cfg.shannon_enable:
            # update_workers_transmission(True) (DCML_Basic_Env.py:19-29) +
            # Master.get_transmission_rate (:41-45): fresh channel draws
            master_pr = jnp.float32(0.0)             # Master.reset (:50-53)
            k_tx, k_d, k_wp = jax.random.split(k_chan, 3)
            bandwidth = c.b_total / W
            tx_power = jax.random.uniform(k_tx, (), minval=c.tx_power_min, maxval=c.tx_power_max)
            dist = jax.random.uniform(k_d, (W,), minval=c.distance_min, maxval=c.distance_max)
            worker_power = jax.random.uniform(
                k_wp, (W,), minval=c.min_worker_power, maxval=c.max_worker_power
            )
            gain = dist ** c.path_loss_exponent / c.noise_mw
            upload_trans = bandwidth * jnp.log2(1.0 + worker_power * gain)
            download_trans = bandwidth * jnp.log2(1.0 + tx_power * gain)
        else:
            # dtype pinned: a bare python-float fill is weak-typed, and a
            # checkpoint round trip strengthens it — the aval drift forces a
            # one-time dispatch recompile on emergency resume
            upload_trans = jnp.full((W,), c.non_shannon_data_rate, dtype=jnp.float32)
            download_trans = jnp.full((W,), c.non_shannon_data_rate, dtype=jnp.float32)

        # per-worker unit price: mean of a period of Poisson(λ) arrivals / λ
        # (DCML_Worker...py:114-118); only observed under dynamic_price, and
        # reset runs EVERY step (auto-reset), so gate it — jax.random.poisson
        # is a rejection sampler whose while_loop serializes inside the
        # collect scan on TPU, and the scan carry keeps XLA from dead-code
        # eliminating an unread (W, P) draw per env per step
        prices = None
        if c.dynamic_price:
            prices = (
                jax.random.poisson(k_price, c.lambda_of_poisson, (W, c.local_workload_period))
                .astype(jnp.float32).mean(axis=1) / c.lambda_of_poisson
            )

        state = DCMLState(
            rng=key,
            r_rows=r_rows,
            c_cols=c_cols,
            master_pr=master_pr,
            worker_prs=worker_prs,
            trace=trace,
            unavailable=unavailable,
            arrive_time=arrive_time,
            disable_rate=disable_rate,
            episode_idx=episode_idx + 1,
            upload_trans=upload_trans,
            download_trans=download_trans,
            prices=prices,
        )
        obs, share_obs, ava = self._observe(state)
        ts = TimeStep(
            obs=obs,
            share_obs=share_obs,
            available_actions=ava,
            reward=jnp.zeros((c.n_agents, 1), jnp.float32),
            done=jnp.zeros((c.n_agents,), bool),
            delay=jnp.float32(0.0),
            payment=jnp.float32(0.0),
            objectives=jnp.zeros((c.n_agents, 2), jnp.float32),
        )
        return state, ts

    # ------------------------------------------------------------------- step

    def step(self, state: DCMLState, action: jax.Array) -> Tuple[DCMLState, TimeStep]:
        """One task round; mirrors ``Env.step`` (``DCML_..._SingleProcess.py:57-144``).

        ``action``: ``(n_agents,)`` or ``(n_agents, 1)`` — 100 select bits then
        the coding ratio (the extra agent's continuous action).
        """
        c = self.cfg.consts
        W = c.worker_number_max
        action = action.reshape(-1)

        key = state.rng
        key, k_proc, k_done, k_reset = jax.random.split(key, 4)

        if self.cfg.fixed:
            select = (~state.unavailable).astype(jnp.float32)
            n_raw = select.sum()
            n_sel = n_raw
            k_code = jnp.floor(n_sel * 0.7)
        else:
            select = action[:W]
            ratio = action[-1]
            n_raw = select.sum()
            n_sel = n_raw
            k_code = jnp.ceil(n_sel * ratio)

        standalone = n_raw < 0.5
        # clamp N in [1, W], K in [1, N]  (:96-103)
        n_sel = jnp.clip(n_sel, 1.0, float(W))
        k_code = jnp.clip(k_code, 1.0, n_sel)
        # standalone path uses K = N = 1 (:81-83); the clamps above already
        # produce K = 1, N = 1 when no worker is selected.

        # Master.get_workload (:39-40): (ceil(R/K), C)
        r_wl = jnp.ceil(state.r_rows / k_code)
        c_wl = state.c_cols

        download = (
            state.download_trans
            if state.download_trans is not None
            else jnp.full((W,), c.non_shannon_data_rate)
        )
        delays, p0, c20, cap_period, m_slots = self._process_workers(
            k_proc, r_wl, c_wl, state.worker_prs, state.trace, state.arrive_time,
            download,
        )

        sel_mask = select > 0.5
        masked_delays = jnp.where(sel_mask, delays, _INF)
        sorted_delays = jnp.sort(masked_delays)
        k_idx = k_code.astype(jnp.int32) - 1
        final_delay = sorted_delays[k_idx]

        end_timeslot = jnp.ceil(final_delay)
        final_costs = self._cost_at(p0, c20, cap_period, m_slots, end_timeslot)
        payment = jnp.sum(jnp.where(sel_mask, final_costs, 0.0))

        reward_main = -(final_delay * c.reward_alpha) - payment * c.reward_beta

        # standalone (:81-92): only worker 0 counts, reward scaled 1.5x, cost
        # is the worker's full drained price (prices[-1]).
        cost0_full = p0[0] + self._capacity(c20[0], cap_period[0], m_slots[0])
        reward_alone = 1.5 * (-(delays[0] * c.reward_alpha) - cost0_full * c.reward_beta)

        reward = jnp.where(standalone, reward_alone, reward_main)
        delay_info = jnp.where(standalone, delays[0], final_delay)
        payment_info = jnp.where(standalone, cost0_full, payment)
        # per-objective channels; the standalone path keeps its 1.5x scaling
        obj_scale = jnp.where(standalone, 1.5, 1.0)
        objectives = obj_scale * jnp.stack(
            [-delay_info * c.reward_alpha, -payment_info * c.reward_beta]
        )

        # done fires with CONTINUE_PROBABILITY (:141-142) — the reference uses
        # it as a "next task unrelated" continuation flag; see ops/gae.py.
        done = jax.random.uniform(k_done, ()) < c.continue_probability

        new_state, reset_ts = self.reset(k_reset, state.episode_idx)
        ts = TimeStep(
            obs=reset_ts.obs,
            share_obs=reset_ts.share_obs,
            available_actions=reset_ts.available_actions,
            reward=jnp.full((c.n_agents, 1), reward, jnp.float32),
            done=jnp.full((c.n_agents,), done),
            delay=delay_info,
            payment=payment_info,
            objectives=jnp.broadcast_to(objectives, (c.n_agents, 2)).astype(jnp.float32),
        )
        return new_state, ts

    # ---------------------------------------------------------------- workers

    def _process_workers(self, key, r_wl, c_wl, prs, trace, arrive_time, download):
        """Vectorized ``Worker.process`` (``DCML_Worker...py:46-112``).

        ``download``: (W,) per-worker data rate — NON_SHANNON_DATA_RATE or the
        Shannon draw; BOTH directions divide by it, replicating the
        reference's upload formula reading ``self.download`` (:106).

        Returns per-worker ``(delay, p0, c20, cap_period, m_slots)`` where
        ``p0`` is the transmit-time price floor, ``c20`` the cumulative free
        capacity over one period starting at the arrival timepoint,
        ``cap_period`` its total, and ``m_slots`` the drained timeslot count.
        """
        c = self.cfg.consts
        W, P = trace.shape
        k_dl, k_ul = jax.random.split(key)

        compute_workload = (9.0 * r_wl - 3.0) * c_wl
        cost0 = c.second_to_centsec * jnp.ceil(compute_workload) / c.worker_frequency

        # download retry count: 1 + Geometric failures (:53-59)
        fails0 = _geometric_failures(k_dl, prs)
        n_retry = 1.0 + fails0
        transmit_delay = (
            c.second_to_centsec
            * (jnp.ceil((r_wl + 1.0) * c_wl) * 1.0 * c.bit_to_byte / download + 0.001)
            * n_retry
        )  # (:60)

        p0 = jnp.floor(transmit_delay) * 0.1  # (:65)
        arrive_ts = jnp.floor(transmit_delay + arrive_time)  # (:66)
        ctp0 = jnp.mod(arrive_ts, P).astype(jnp.int32)  # (:67-69), timepoint = 0

        wl0 = jnp.take_along_axis(trace, ctp0[:, None], axis=1)[:, 0]
        frac = transmit_delay - jnp.floor(transmit_delay)
        cost = cost0 + jnp.maximum(frac - wl0, 0.0)  # (:85-86)

        # free capacity per slot, rolled to start at ctp0, one full period
        idx = jnp.mod(ctp0[:, None] + jnp.arange(P)[None, :], P)
        free = 1.0 - jnp.take_along_axis(trace, idx, axis=1)  # (W, P)
        c20 = jnp.cumsum(free, axis=1)
        cap_period = c20[:, -1]

        # smallest m >= 1 with cumulative capacity >= cost (:87-95)
        cap_safe = jnp.maximum(cap_period, 1e-6)
        q_full = jnp.maximum(jnp.ceil(cost / cap_safe) - 1.0, 0.0)
        rem = cost - q_full * cap_period
        t_part = 1 + jnp.argmax(c20 >= rem[:, None] - 1e-9, axis=1)
        m_slots = jnp.minimum(q_full * P + t_part, self.cfg.max_drain_slots)
        drained = q_full * cap_period + jnp.take_along_axis(c20, (t_part - 1)[:, None], axis=1)[:, 0]

        # upload retries: faithful mode adds one geometric draw per drained
        # timeslot (the reference's in-loop indentation, :99-106); fixed mode
        # draws once.
        if self.cfg.fixed_upload_retry:
            extra_fails = _geometric_failures(k_ul, prs)   # one draw == NB(1, p)
        else:
            extra_fails = _negative_binomial(k_ul, m_slots, prs)
        n_retry_final = n_retry + extra_fails
        upload_delay = (
            c.second_to_centsec
            * (jnp.ceil(r_wl) * 1.0 * c.bit_to_byte / download + 0.001)
            * n_retry_final
            + 0.02
        )  # (:106; divides by download — the reference quirk, see docstring)

        # (:108): finish_timeslot - arrive_time - overshoot + upload_delay
        delay = (arrive_ts + m_slots) - arrive_time - (drained - cost) + upload_delay
        return delay, p0, c20, cap_period, m_slots

    def _capacity(self, c20_row, cap_period_row, j):
        """Cumulative free capacity over the first ``j`` drained slots."""
        P = c20_row.shape[0]
        j = jnp.clip(j, 0, self.cfg.max_drain_slots)
        q2 = jnp.floor(j / P)
        r2 = (j - q2 * P).astype(jnp.int32)
        partial = jnp.where(r2 > 0, c20_row[jnp.maximum(r2 - 1, 0)], 0.0)
        return q2 * cap_period_row + partial

    def _cost_at(self, p0, c20, cap_period, m_slots, end_timeslot):
        """Per-worker accumulated price at ``end_timeslot``
        (``DCML_..._SingleProcess.py:131-137``): ``prices[e-1]`` if the worker
        was still draining, else its final price."""
        j = jnp.minimum(jnp.maximum(end_timeslot, 1.0), m_slots)
        cap = jax.vmap(self._capacity)(c20, cap_period, j)
        return p0 + cap

    # ------------------------------------------------------------------- obs

    def _observe(self, state: DCMLState):
        """Build (obs, share_obs, available_actions); mirrors
        ``DCML_..._SingleProcess.py:162-274`` (OBSERVER_WORKLOAD branch,
        HETEROGENEOUS, DYNAMIC_PRICE=False)."""
        c = self.cfg.consts
        W, P = c.worker_number_max, c.local_workload_period
        avail = ~state.unavailable

        r_norm = (state.r_rows - c.r_min) / (c.r_max - c.r_min)
        c_norm = (state.c_cols - c.c_min) / (c.c_max - c.c_min)

        at = state.arrive_time
        slots = jnp.mod(at + jnp.arange(3), P)
        wl3 = state.trace[:, slots]  # (W, 3)

        n_avail = (W - state.disable_rate).astype(jnp.float32)
        unavail_f = state.unavailable.astype(jnp.float32)
        disabled_before = jnp.cumsum(unavail_f) - unavail_f
        rank = (jnp.arange(W, dtype=jnp.float32) - disabled_before) / n_avail

        # feature 7: own rank if available, else the previous block's feature 7
        # (the obs[-7] back-reference at :210-213), forward-filled from 0.
        # Log-depth cummax + gather instead of a 100-step lax.scan: identical
        # values (the fill picks rank[last available index <= i]), but no
        # sequential inner loop inside the per-step env (TPU collect scan).
        iw = jnp.arange(W)
        last_avail = jax.lax.associative_scan(jnp.maximum, jnp.where(avail, iw, -1))
        feat7 = jnp.where(last_avail >= 0, rank[jnp.maximum(last_avail, 0)], 0.0)

        shared_head = jnp.stack([r_norm * c.state_ratio, c_norm * c.state_ratio])
        worker_obs_avail = jnp.concatenate(
            [jnp.broadcast_to(shared_head, (W, 2)), wl3, state.worker_prs[:, None], rank[:, None]],
            axis=1,
        )
        worker_obs_unavail = jnp.concatenate(
            [jnp.broadcast_to(shared_head, (W, 2)), jnp.ones((W, 4)), feat7[:, None]], axis=1
        )
        worker_obs = jnp.where(avail[:, None], worker_obs_avail, worker_obs_unavail)

        # master ("extra") agent obs (:235-241): availability-masked means
        af = avail.astype(jnp.float32)
        denom = jnp.maximum(af.sum(), 1.0)
        mean_wl3 = (wl3 * af[:, None]).sum(axis=0) / denom
        mean_pr = (state.worker_prs * af).sum() / denom
        master_obs = jnp.concatenate([shared_head, mean_wl3, jnp.array([mean_pr, 1.1])])

        if c.dynamic_price:
            # 8th obs feature (:214-215,228-229,240-241): worker unit price,
            # UNAVAILABLE_PRICE when disabled, MASTER_PRICE for the master
            prices = (
                state.prices if state.prices is not None else jnp.ones((W,))
            )
            price_col = jnp.where(avail, prices, c.unavailable_price)
            worker_obs = jnp.concatenate([worker_obs, price_col[:, None]], axis=1)
            master_obs = jnp.append(master_obs, c.master_price)

        obs = jnp.concatenate([worker_obs, master_obs[None, :]], axis=0)

        if self.cfg.shannon_enable:
            # share_obs = [R, C] ++ upload/1e7 ++ download/1e7 (:248-251)
            share_obs_row = jnp.concatenate(
                [shared_head, state.upload_trans / 1e7, state.download_trans / 1e7]
            )
        else:
            share_obs_row = jnp.concatenate([shared_head, state.worker_prs])  # (:181-182,252-253)
        share_obs = jnp.broadcast_to(share_obs_row, (c.n_agents, self.share_obs_dim))

        # availability mask (:266-268): [1,1] available / [1,0] disabled; master [1,1]
        ava_workers = jnp.stack([jnp.ones(W), af], axis=1)
        ava = jnp.concatenate([ava_workers, jnp.ones((1, 2))], axis=0)
        return obs, share_obs, ava

    # ------------------------------------------------- single-agent encoding

    def encode_single_agent_state(self, state: DCMLState, binary: bool = True) -> jax.Array:
        """``fake_reset`` state encoding (``DCML_..._SingleProcess.py:275-315``):
        the flat single-agent view consumed by non-MARL baselines (TD3 etc.).

        ``binary=True``: 32-bit big-endian binary expansions of R and C
        (:279-286); else their normalized values.  Then, Shannon mode appends
        the scaled rate vectors (:291-295); otherwise Pr plus each worker's
        workload at the arrival timeslot (:296-309, OBSERVER_WORKLOAD).
        """
        c = self.cfg.consts
        W = c.worker_number_max
        if binary:
            shifts = jnp.arange(31, -1, -1)
            r_bits = (state.r_rows.astype(jnp.int32) >> shifts) & 1
            c_bits = (state.c_cols.astype(jnp.int32) >> shifts) & 1
            head = jnp.concatenate([r_bits, c_bits]).astype(jnp.float32)
        else:
            head = jnp.stack([
                (state.r_rows - c.r_min) / (c.r_max - c.r_min) * c.state_ratio,
                (state.c_cols - c.c_min) / (c.c_max - c.c_min) * c.state_ratio,
            ])
        if self.cfg.shannon_enable:
            return jnp.concatenate(
                [head, state.upload_trans / 1e7, state.download_trans / 1e7]
            )
        wl_now = jnp.take_along_axis(
            state.trace, jnp.full((W, 1), state.arrive_time, jnp.int32), axis=1
        )[:, 0]
        return jnp.concatenate([head, state.master_pr[None], wl_now])


# ---------------------------------------------------------------- sampling


def _geom_inverse_cdf(u: jax.Array, p_fail: jax.Array) -> jax.Array:
    """Geometric failure count from a uniform: F = floor(log u / log p)."""
    safe_p = jnp.clip(p_fail, 1e-12, 1.0 - 1e-7)
    return jnp.floor(jnp.log(u) / jnp.log(safe_p))


def _uniform_open(key: jax.Array, shape) -> jax.Array:
    return jax.random.uniform(
        key, shape, minval=jnp.finfo(jnp.float32).tiny, maxval=1.0
    )


def _geometric_failures(key: jax.Array, p_fail: jax.Array) -> jax.Array:
    """Number of consecutive U() < p draws; F=0 at p=0."""
    f = _geom_inverse_cdf(_uniform_open(key, p_fail.shape), p_fail)
    return jnp.where(p_fail <= 0.0, 0.0, f)


_NB_DRAW_CAP = 64


def _negative_binomial(key: jax.Array, n_draws: jax.Array, p_fail: jax.Array) -> jax.Array:
    """Sum of ``n_draws`` iid geometric-failure counts.

    Exact masked sum of up to ``_NB_DRAW_CAP`` closed-form geometric draws —
    the reference itself draws one geometric per drained timeslot in a loop
    (``DCML_Worker...py:99-106``), and the drained-slot counts this receives
    are tiny in practice (p99 ≈ 5 over random-policy rollouts).  The previous
    Gamma-Poisson mixture was distribution-equivalent but ``jax.random.gamma``
    / ``poisson`` are rejection samplers whose data-dependent while_loops
    serialize inside the TPU collect scan.  Lanes with ``n_draws`` beyond the
    cap (never observed) get the remainder from a moment-matched normal, so
    no lane is truncated and no control flow is data-dependent.
    """
    k_g, k_t = jax.random.split(key)
    u = _uniform_open(k_g, (*n_draws.shape, _NB_DRAW_CAP))
    f = _geom_inverse_cdf(u, p_fail[..., None])
    live = jnp.arange(_NB_DRAW_CAP) < jnp.minimum(n_draws, _NB_DRAW_CAP)[..., None]
    total = jnp.where(live, f, 0.0).sum(axis=-1)

    safe_p = jnp.clip(p_fail, 1e-12, 1.0 - 1e-7)
    rem = jnp.maximum(n_draws - _NB_DRAW_CAP, 0.0)
    mean = safe_p / (1.0 - safe_p)
    var = safe_p / jnp.square(1.0 - safe_p)
    z = jax.random.normal(k_t, n_draws.shape)
    tail = jnp.maximum(jnp.round(rem * mean + z * jnp.sqrt(rem * var)), 0.0)
    total = total + jnp.where(rem > 0, tail, 0.0)
    return jnp.where(p_fail <= 0.0, 0.0, total)


# ------------------------------------------------------------------ loaders


def load_base_workloads(path: Path, consts: DCMLConsts) -> np.ndarray:
    """Read the 100 stacked (20,) workload traces
    (``DCML_..._SingleProcess.py:33-37`` reads them sequentially)."""
    traces = []
    with open(path, "rb") as reader:
        for _ in range(consts.worker_number_max):
            traces.append(np.load(reader, allow_pickle=False))
    return np.stack(traces).astype(np.float32)


def load_preset(bench_dir: Path, sample: int = 1):
    """Load one of the 10 shipped eval fixtures (1001 episodes each)."""
    with open(bench_dir / f"Sample_{sample}master_states.npy", "rb") as f:
        master = np.load(f, allow_pickle=False)
    with open(bench_dir / f"Sample_{sample}worker_states.npy", "rb") as f:
        worker_prs = np.load(f, allow_pickle=False)
        disable_rates = np.load(f, allow_pickle=False)
    return master, worker_prs, disable_rates
