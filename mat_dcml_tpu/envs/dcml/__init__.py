"""The DCML worker-selection / workload-allocation environment, pure JAX."""

from mat_dcml_tpu.envs.dcml.constants import DCMLConsts
from mat_dcml_tpu.envs.dcml.env import DCMLEnv, DCMLEnvConfig, DCMLState, TimeStep
from mat_dcml_tpu.envs.dcml.fault import (
    DCMLFaultConfig,
    FaultyDCMLEnv,
    fleet_stress_preset,
)
