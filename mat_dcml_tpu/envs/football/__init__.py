"""Google Research Football family: feature/reward encoders and the gated
gfootball host env (drive through the vec-env bridge + FootballRunner)."""

from mat_dcml_tpu.envs.football.encoders import (
    N_ACTIONS,
    FeatureEncoder,
    Rewarder,
    availability,
    ball_zone_onehot,
)
from mat_dcml_tpu.envs.football.env import FootballHostEnv

__all__ = [
    "N_ACTIONS",
    "FeatureEncoder",
    "Rewarder",
    "availability",
    "ball_zone_onehot",
    "FootballHostEnv",
]
