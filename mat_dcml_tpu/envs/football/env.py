"""Google Research Football behind the host-process bridge (gated).

Wraps gfootball's raw representation with the feature/reward encoders
(``football/football_env.py:13-97``): per-agent encoded obs, share_obs = a
copy of obs (``:56``), shaped rewards, 19-action availability.  Exposes the
host shared-obs contract for :mod:`~mat_dcml_tpu.envs.vec_env`.

Gated on the external ``gfootball`` package (not bundled).  The backend is
injectable for tests: anything yielding gfootball-style raw obs-dict lists
from ``reset()``/``step()`` works.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from mat_dcml_tpu.envs.football.encoders import N_ACTIONS, FeatureEncoder, Rewarder


class FootballHostEnv:
    self_resetting = False

    def __init__(self, scenario: str = "academy_3_vs_1_with_keeper",
                 n_agents: int = 3, rewards: str = "scoring",
                 backend_env=None):
        if backend_env is None:
            try:
                import gfootball.env as football_env  # type: ignore
            except ImportError as err:
                raise ImportError(
                    "FootballHostEnv needs the external gfootball package "
                    "(https://github.com/google-research/football); not "
                    "bundled. Tests inject a fake backend via backend_env."
                ) from err
            backend_env = football_env.create_environment(
                env_name=scenario,
                number_of_left_players_agent_controls=n_agents,
                representation="raw",
                rewards=rewards,
            )
        self._env = backend_env
        self.n_agents = n_agents
        self.action_dim = N_ACTIONS
        self._encoder = FeatureEncoder()
        self._rewarder = Rewarder()
        self._prev_raw: Optional[Sequence[dict]] = None

        probe = self._encode(self._env.reset())
        self.obs_dim = probe[0].shape[1]
        self.share_obs_dim = self.obs_dim              # share_obs = obs copy

    def _encode(self, raw_list):
        rows = [self._encoder.encode(raw) for raw in raw_list]
        obs = np.stack([r[0] for r in rows]).astype(np.float32)
        avail = np.stack([r[1] for r in rows]).astype(np.float32)
        return obs, avail

    def reset(self):
        raw = self._env.reset()
        self._prev_raw = raw
        obs, avail = self._encode(raw)
        return obs, obs.copy(), avail

    def step(self, actions):
        acts = [int(a) for a in np.asarray(actions).reshape(-1)]
        raw, rews, done, info = self._env.step(acts)
        obs, avail = self._encode(raw)
        shaped = np.array(
            [
                self._rewarder.calc_reward(float(r), prev, cur)
                for r, prev, cur in zip(np.atleast_1d(rews), self._prev_raw, raw)
            ],
            np.float32,
        )[:, None]
        self._prev_raw = raw
        dones = np.full((self.n_agents,), bool(np.all(done)))
        info = dict(info or {})
        # goal difference rides the generic episode-info channel: sums of
        # per-step score deltas equal the final goal difference the football
        # runner reports as "scores" (football_runner.py)
        info["delay"] = float(np.atleast_1d(rews)[0])
        info["payment"] = 0.0
        return obs, obs.copy(), shaped, dones, info, avail

    def close(self):
        if hasattr(self._env, "close"):
            self._env.close()
