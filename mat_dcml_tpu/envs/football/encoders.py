"""Google Research Football feature/reward encoders.

Re-design of the reference's hand-rolled encoders over gfootball's "raw"
representation (``football/encode/obs_encode.py:1-346``,
``rew_encode.py:1-104``): per-player features, ball features, teammate and
opponent relative features with closest-unit summaries, a 19-action
availability mask, and the shaped reward (win + score + ball-position +
yellow-card + ball-distance terms).  Pure numpy over the raw obs dict —
fully testable without the game binary.

Action ids follow gfootball's default 19-action set
(``obs_encode.py:_get_avail_new``): 0 no-op, 1-8 directions, 9 long pass,
10 high pass, 11 short pass, 12 shot, 13 sprint, 14 release-move,
15 release-sprint, 16 slide, 17 dribble, 18 release-dribble.
"""

from __future__ import annotations

import numpy as np

N_ACTIONS = 19
(NO_OP, LEFT, TOP_LEFT, TOP, TOP_RIGHT, RIGHT, BOTTOM_RIGHT, BOTTOM,
 BOTTOM_LEFT, LONG_PASS, HIGH_PASS, SHORT_PASS, SHOT, SPRINT, RELEASE_MOVE,
 RELEASE_SPRINT, SLIDE, DRIBBLE, RELEASE_DRIBBLE) = range(N_ACTIONS)

N_ROLES = 10
STICKY_SPRINT = 8
STICKY_DRIBBLE = 9

# pitch landmarks (gfootball coordinates)
MIDDLE_X, PENALTY_X, END_X = 0.2, 0.64, 1.0
PENALTY_Y, END_Y = 0.27, 0.42
BALL_CLOSE = 0.03


def ball_zone_onehot(ball_x: float, ball_y: float) -> np.ndarray:
    """Six-zone pitch partition (own penalty box / own half / midfield /
    their half / their penalty box / out wide)."""
    zone = np.zeros(6, np.float32)
    in_y = -END_Y < ball_y < END_Y
    if (-END_X <= ball_x < -PENALTY_X) and (-PENALTY_Y < ball_y < PENALTY_Y):
        zone[0] = 1.0
    elif in_y and -END_X <= ball_x < -MIDDLE_X:
        zone[1] = 1.0
    elif in_y and -MIDDLE_X <= ball_x <= MIDDLE_X:
        zone[2] = 1.0
    elif (PENALTY_X < ball_x <= END_X) and (-PENALTY_Y < ball_y < PENALTY_Y):
        zone[3] = 1.0
    elif in_y and MIDDLE_X < ball_x <= END_X:
        zone[4] = 1.0
    else:
        zone[5] = 1.0
    return zone


def availability(obs: dict, ball_distance: float) -> np.ndarray:
    """19-action availability mask (``_get_avail_new`` semantics)."""
    avail = np.ones(N_ACTIONS, np.float32)
    sticky = np.asarray(obs["sticky_actions"])
    ball_x, ball_y, _ = obs["ball"]

    ball_kickable = not (
        obs["ball_owned_team"] == 1
        or (obs["ball_owned_team"] == -1 and ball_distance > BALL_CLOSE
            and obs["game_mode"] == 0)
    )
    if not ball_kickable:
        avail[[LONG_PASS, HIGH_PASS, SHORT_PASS, SHOT, DRIBBLE]] = 0
        if obs["ball_owned_team"] == 1 and ball_distance > BALL_CLOSE:
            avail[SLIDE] = 0
    else:
        avail[SLIDE] = 0

    if sticky[STICKY_SPRINT] == 0:
        avail[RELEASE_SPRINT] = 0
    if sticky[STICKY_DRIBBLE] == 1:
        avail[SLIDE] = 0
    else:
        avail[RELEASE_DRIBBLE] = 0
    if sticky[:8].sum() == 0:
        avail[RELEASE_MOVE] = 0

    # shots only near their goal; long/high passes pointless inside the box
    if ball_x < PENALTY_X or not (-PENALTY_Y <= ball_y <= PENALTY_Y):
        avail[SHOT] = 0
    elif ball_x <= END_X:
        avail[[HIGH_PASS, LONG_PASS]] = 0

    # set pieces collapse the choice set (goal kick / corner / penalty)
    if obs["game_mode"] == 2 and ball_x < -0.7:
        avail = np.zeros(N_ACTIONS, np.float32)
        avail[[NO_OP, LONG_PASS, HIGH_PASS, SHORT_PASS]] = 1
    elif obs["game_mode"] == 4 and ball_x > 0.9:
        avail = np.zeros(N_ACTIONS, np.float32)
        avail[[NO_OP, LONG_PASS, HIGH_PASS, SHORT_PASS]] = 1
    elif obs["game_mode"] == 6 and ball_x > 0.6:
        avail = np.zeros(N_ACTIONS, np.float32)
        avail[[NO_OP, SHOT]] = 1
    return avail


class FeatureEncoder:
    """raw obs dict -> flat per-player feature vector + availability."""

    def encode(self, obs: dict) -> tuple[np.ndarray, np.ndarray]:
        me = obs["active"]
        my_pos = np.asarray(obs["left_team"][me], np.float32)
        my_dir = np.asarray(obs["left_team_direction"][me], np.float32)
        my_speed = float(np.linalg.norm(my_dir))
        role = np.zeros(N_ROLES, np.float32)
        role[int(obs["left_team_roles"][me]) % N_ROLES] = 1.0
        sticky = np.asarray(obs["sticky_actions"], np.float32)

        ball = np.asarray(obs["ball"], np.float32)
        ball_dir = np.asarray(obs["ball_direction"], np.float32)
        ball_rel = ball[:2] - my_pos
        ball_distance = float(np.linalg.norm(ball_rel))
        ball_speed = float(np.linalg.norm(ball_dir[:2]))
        owned = float(obs["ball_owned_team"] != -1)
        owned_by_us = float(obs["ball_owned_team"] == 0)
        ball_far = float(ball_distance > BALL_CLOSE)

        avail = availability(obs, ball_distance)

        player = np.concatenate([
            my_pos, my_dir * 100.0, [my_speed * 100.0], role,
            [ball_far, float(obs["left_team_tired_factor"][me]),
             sticky[STICKY_DRIBBLE], sticky[STICKY_SPRINT]],
        ]).astype(np.float32)

        ball_feats = np.concatenate([
            ball, ball_zone_onehot(float(ball[0]), float(ball[1])), ball_rel,
            ball_dir * 20.0,
            [ball_speed * 20.0, ball_distance, owned, owned_by_us],
        ]).astype(np.float32)

        def team_block(pos, direction, tired, drop_me: bool):
            pos = np.asarray(pos, np.float32)
            direction = np.asarray(direction, np.float32)
            tired = np.asarray(tired, np.float32).reshape(-1, 1)
            if drop_me:
                keep = np.arange(len(pos)) != me
                pos, direction, tired = pos[keep], direction[keep], tired[keep]
            dist = np.linalg.norm(pos - my_pos, axis=1, keepdims=True)
            speed = np.linalg.norm(direction, axis=1, keepdims=True)
            block = np.concatenate(
                [pos * 2.0, direction * 100.0, speed * 100.0, dist * 2.0, tired],
                axis=1,
            ).astype(np.float32)
            closest = block[int(np.argmin(dist))]
            return block, closest

        left, left_closest = team_block(
            obs["left_team"], obs["left_team_direction"],
            obs["left_team_tired_factor"], drop_me=True,
        )
        right, right_closest = team_block(
            obs["right_team"], obs["right_team_direction"],
            obs["right_team_tired_factor"], drop_me=False,
        )

        feats = np.concatenate([
            player, ball_feats,
            left.ravel(), left_closest, right.ravel(), right_closest,
        ])
        return feats, avail


class Rewarder:
    """Shaped reward (``rew_encode.py`` term structure):
    ``5*win + 5*score + 0.003*ball_position + yellow - 0.003*min_dist``."""

    def calc_reward(self, rew: float, prev_obs: dict, obs: dict) -> float:
        return float(
            5.0 * self._win(obs)
            + 5.0 * rew
            + 0.003 * self._ball_position(obs)
            + self._yellow(prev_obs, obs)
            - 0.003 * self._min_dist(obs)
        )

    @staticmethod
    def _win(obs) -> float:
        if obs["steps_left"] == 0:
            mine, theirs = obs["score"]
            if mine > theirs:
                return float(mine - theirs)
        return 0.0

    @staticmethod
    def _ball_position(obs) -> float:
        x, y, _ = obs["ball"]
        in_y = -END_Y < y < END_Y
        if (-END_X <= x < -PENALTY_X) and (-PENALTY_Y < y < PENALTY_Y):
            return -2.0
        if in_y and -END_X <= x < -MIDDLE_X:
            return -1.0
        if (PENALTY_X < x <= END_X) and (-PENALTY_Y < y < PENALTY_Y):
            return 2.0
        if in_y and MIDDLE_X < x <= END_X:
            return 1.0
        return 0.0

    @staticmethod
    def _yellow(prev_obs, obs) -> float:
        left = np.sum(obs["left_team_yellow_card"]) - np.sum(prev_obs["left_team_yellow_card"])
        right = np.sum(obs["right_team_yellow_card"]) - np.sum(prev_obs["right_team_yellow_card"])
        return float(right - left)

    @staticmethod
    def _min_dist(obs) -> float:
        if obs["ball_owned_team"] == 0:
            return 0.0
        ball = np.asarray(obs["ball"][:2])
        outfield = np.asarray(obs["left_team"][1:])      # skip the keeper
        return float(np.min(np.linalg.norm(outfield - ball, axis=1)))
