"""Pure-JAX MPE ``simple_tag`` (predator–prey).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_tag.py`` on the
``core.py`` physics.  ``n_adversaries`` slow red predators chase ``n_good``
faster green prey around ``n_landmarks`` large immovable obstacles.

Faithful semantics (scenario file:line cites into the reference):

- Agent order: adversaries first (``simple_tag.py:17-23``); adversary
  size/accel/max_speed 0.075/3.0/1.0, prey 0.05/4.0/1.3; landmarks size 0.2,
  collidable, spawned at ``0.8·U(-1,1)²`` (``:24-51``).
- Per-agent (non-shared) rewards (``World.collaborative`` unset): prey take
  −10 per predator contact and the piecewise screen-exit ``bound`` penalty
  (``:88-112``); every predator receives +10 per (prey, predator) contact
  pair — the sum over ALL predators, identical for each (``:114-127``).
- Obs per agent: ``[vel, pos, landmark_rel, other_pos, other_vel]`` where
  ``other_vel`` covers only *prey* among the others (``:129-145``), so
  predator rows are 2·n_good wider minus...: prey rows are 2 entries
  narrower (they exclude themselves) and zero-pad to the predator width;
  a one-hot agent id is appended by the env driver
  (``environment.py:140-142``).
- Episode ends after ``episode_length`` steps with auto-reset inside
  ``step`` (``environment.py:205-210``, ``env_wrappers.py:305-313``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle


class TagState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2), adversaries first
    agent_vel: jax.Array      # (N, 2)
    landmark_pos: jax.Array   # (L, 2)
    t: jax.Array


class TagTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array          # protocol compat (zeros)
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleTagConfig:
    n_good: int = 1           # simple_tag.py:10-13 comment defaults (1/3/2)
    n_adversaries: int = 3
    n_landmarks: int = 2
    episode_length: int = 25
    adv_size: float = 0.075
    good_size: float = 0.05
    adv_accel: float = 3.0
    good_accel: float = 4.0
    adv_max_speed: float = 1.0
    good_max_speed: float = 1.3
    landmark_size: float = 0.2

    @property
    def n_agents(self) -> int:
        return self.n_adversaries + self.n_good


class SimpleTagEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    def __init__(self, cfg: SimpleTagConfig = SimpleTagConfig()):
        self.cfg = cfg
        N, L, G = cfg.n_agents, cfg.n_landmarks, cfg.n_good
        self.n_agents = N
        # widest role is the adversary: vel2 + pos2 + 2L + 2(N-1) + 2G
        self._core_dim = 4 + 2 * L + 2 * (N - 1) + 2 * G
        self.obs_dim = self._core_dim + N
        self.share_obs_dim = self.obs_dim * N
        self.action_dim = 5
        A = cfg.n_adversaries
        self._sizes = jnp.asarray(
            [cfg.adv_size] * A + [cfg.good_size] * G + [cfg.landmark_size] * L
        )
        self._collide = jnp.ones((N + L,), bool)
        self._movable = jnp.asarray([True] * N + [False] * L)
        self._max_speed = jnp.asarray(
            [cfg.adv_max_speed] * A + [cfg.good_max_speed] * G
        )
        self._gain = jnp.asarray(
            [particle.force_gain(cfg.adv_accel)] * A
            + [particle.force_gain(cfg.good_accel)] * G
        )

    # ----------------------------------------------------------------- reset

    def _spawn(self, key: jax.Array) -> TagState:
        c = self.cfg
        key, k_a, k_l = jax.random.split(key, 3)
        return TagState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=0.8 * jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[TagState, TagTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        return st, TagTimeStep(
            obs, share, avail, jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero
        )

    # ------------------------------------------------------------------ step

    def step(self, st: TagState, action: jax.Array) -> Tuple[TagState, TagTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1)
        onehot = (
            jax.nn.one_hot(act[:, 0].astype(jnp.int32), 5)
            if act.shape[-1] == 1 else act.astype(jnp.float32)
        )
        u = particle.decode_move(onehot) * self._gain[:, None]

        entity_pos = jnp.concatenate([st.agent_pos, st.landmark_pos])
        coll = particle.collision_forces(
            entity_pos, self._sizes, self._collide, self._movable
        )[:N]
        vel = particle.integrate(st.agent_vel, u + coll, self._max_speed)
        pos = st.agent_pos + vel * particle.DT

        stepped = TagState(st.rng, pos, vel, st.landmark_pos, st.t + 1)
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, TagTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (N,)), zero, zero,
        )

    def _reward(self, st: TagState) -> jax.Array:
        c = self.cfg
        A, G = c.n_adversaries, c.n_good
        adv_pos = st.agent_pos[:A]
        good_pos = st.agent_pos[A:]
        d = jnp.linalg.norm(good_pos[:, None, :] - adv_pos[None, :, :], axis=-1)  # (G, A)
        contact = d < (c.good_size + c.adv_size)
        # prey: -10 per touching predator, minus the screen-exit penalty
        good_rew = -10.0 * contact.sum(axis=1) - particle.bound_penalty(good_pos)
        # predators: +10 per (prey, predator) contact pair, shared total
        adv_rew = jnp.full((A,), 10.0 * contact.sum())
        return jnp.concatenate([adv_rew, good_rew])

    # ------------------------------------------------------------------- obs

    def _observe(self, st: TagState):
        c = self.cfg
        N, A = c.n_agents, c.n_adversaries
        idx = jnp.arange(N)
        landmark_rel = (
            st.landmark_pos[None, :, :] - st.agent_pos[:, None, :]
        ).reshape(N, -1)
        rel = st.agent_pos[None, :, :] - st.agent_pos[:, None, :]  # (N, N, 2)

        def row(i):
            others = jnp.where(idx != i, size=N - 1)[0]
            other_pos = rel[i][others].reshape(-1)
            # velocities of *prey* among the others, in agent order
            good_others = jnp.where((idx != i) & (idx >= A), size=c.n_good, fill_value=N)[0]
            pad_vel = jnp.concatenate([st.agent_vel, jnp.zeros((1, 2))])
            other_vel = pad_vel[good_others].reshape(-1)
            return jnp.concatenate(
                [st.agent_vel[i], st.agent_pos[i], landmark_rel[i], other_pos, other_vel]
            )

        core = jax.vmap(row)(idx)  # (N, core_dim) — prey rows end in a 0 pad
        # prey gathered n_good slots but only n_good-1 are real; the fill
        # row (index N) contributed zeros, matching the zero-pad convention
        obs = jnp.concatenate([core, jnp.eye(N)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        avail = jnp.ones((N, self.action_dim))
        return obs, share, avail
