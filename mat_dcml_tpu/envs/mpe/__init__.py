from mat_dcml_tpu.envs.mpe.simple_speaker_listener import (
    SimpleSpeakerListenerEnv,
    SpeakerListenerConfig,
)
from mat_dcml_tpu.envs.mpe.simple_spread import (
    SimpleSpreadConfig,
    SimpleSpreadEnv,
    SpreadState,
    SpreadTimeStep,
)

# scenario registry (reference: mat/envs/mpe/scenarios/__init__.py load());
# simple_spread is the one used by the shipped MPE training recipe
SCENARIOS = {
    "simple_spread": (SimpleSpreadEnv, SimpleSpreadConfig),
    "simple_speaker_listener": (SimpleSpeakerListenerEnv, SpeakerListenerConfig),
}

__all__ = [
    "SimpleSpeakerListenerEnv",
    "SpeakerListenerConfig",
    "SimpleSpreadConfig",
    "SimpleSpreadEnv",
    "SpreadState",
    "SpreadTimeStep",
    "SCENARIOS",
]
