from mat_dcml_tpu.envs.mpe.simple_adversary import (
    SimpleAdversaryConfig,
    SimpleAdversaryEnv,
)
from mat_dcml_tpu.envs.mpe.simple_attack import SimpleAttackConfig, SimpleAttackEnv
from mat_dcml_tpu.envs.mpe.simple_crypto import (
    SimpleCryptoConfig,
    SimpleCryptoDisplayEnv,
    SimpleCryptoEnv,
)
from mat_dcml_tpu.envs.mpe.simple_push import SimplePushConfig, SimplePushEnv
from mat_dcml_tpu.envs.mpe.simple_reference import (
    SimpleReferenceConfig,
    SimpleReferenceEnv,
)
from mat_dcml_tpu.envs.mpe.simple_speaker_listener import (
    SimpleSpeakerListenerEnv,
    SpeakerListenerConfig,
)
from mat_dcml_tpu.envs.mpe.simple_spread import (
    SimpleSpreadConfig,
    SimpleSpreadEnv,
    SpreadState,
    SpreadTimeStep,
)
from mat_dcml_tpu.envs.mpe.simple_tag import SimpleTagConfig, SimpleTagEnv
from mat_dcml_tpu.envs.mpe.simple_world_comm import (
    SimpleWorldCommConfig,
    SimpleWorldCommEnv,
)

# scenario registry (reference: mat/envs/mpe/scenarios/__init__.py load());
# simple_spread is the one used by the shipped MPE training recipe
SCENARIOS = {
    "simple_spread": (SimpleSpreadEnv, SimpleSpreadConfig),
    "simple_speaker_listener": (SimpleSpeakerListenerEnv, SpeakerListenerConfig),
    "simple_tag": (SimpleTagEnv, SimpleTagConfig),
    "simple_adversary": (SimpleAdversaryEnv, SimpleAdversaryConfig),
    "simple_push": (SimplePushEnv, SimplePushConfig),
    "simple_reference": (SimpleReferenceEnv, SimpleReferenceConfig),
    "simple_crypto": (SimpleCryptoEnv, SimpleCryptoConfig),
    "simple_crypto_display": (SimpleCryptoDisplayEnv, SimpleCryptoConfig),
    "simple_attack": (SimpleAttackEnv, SimpleAttackConfig),
    "simple_world_comm": (SimpleWorldCommEnv, SimpleWorldCommConfig),
}

__all__ = [
    "SimpleAdversaryConfig",
    "SimpleAdversaryEnv",
    "SimpleAttackConfig",
    "SimpleAttackEnv",
    "SimpleCryptoConfig",
    "SimpleCryptoDisplayEnv",
    "SimpleCryptoEnv",
    "SimplePushConfig",
    "SimplePushEnv",
    "SimpleReferenceConfig",
    "SimpleReferenceEnv",
    "SimpleSpeakerListenerEnv",
    "SpeakerListenerConfig",
    "SimpleSpreadConfig",
    "SimpleSpreadEnv",
    "SimpleTagConfig",
    "SimpleTagEnv",
    "SimpleWorldCommConfig",
    "SimpleWorldCommEnv",
    "SpreadState",
    "SpreadTimeStep",
    "SCENARIOS",
]
