"""Pure-JAX MPE ``simple_speaker_listener`` (cooperative communication).

Reference: ``mpe/scenarios/simple_speaker_listener.py`` + ``mpe/core.py``
physics.  Two heterogeneous agents: a stationary SPEAKER that observes the
goal landmark's color and can only emit a 3-symbol message, and a mobile
LISTENER that observes its velocity, the three landmark offsets, and the
speaker's message — but not the goal.  Shared reward is the negative squared
listener↔goal distance, so score requires the speaker to name the goal and
the listener to decode it.

Heterogeneity under one homogeneous policy interface (the TimeStep protocol
assumes equal per-agent dims) is handled exactly like multi-map SMAC padding:
obs rows are zero-padded to the wider (listener) layout, and one
``Discrete(5)`` action space serves both roles with availability masks —
speaker actions 0-2 are the comm symbols (3-4 masked off), listener actions
are the standard MPE no-op/±x/±y move set (``environment.py:64`` Discrete
move space; speaker's space is Discrete(dim_c)).

The message the listener observes at step t is the symbol the speaker chose
at step t (MPE updates comm state before observations in the same
``world.step``, ``core.py:186-196``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class SpeakerListenerState(NamedTuple):
    rng: jax.Array
    listener_pos: jax.Array   # (2,)
    listener_vel: jax.Array   # (2,)
    landmark_pos: jax.Array   # (3, 2)
    goal: jax.Array           # () int32 landmark index
    comm: jax.Array           # (3,) speaker's last message one-hot
    t: jax.Array


class SLTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SpeakerListenerConfig:
    n_landmarks: int = 3
    dim_c: int = 3
    episode_length: int = 25
    dt: float = 0.1
    damping: float = 0.25
    sensitivity: float = 5.0
    # kept for train_mpe.py's shared flags; the scenario is fixed-size
    n_agents: int = 2

    def __post_init__(self):
        if self.n_agents != 2:
            raise ValueError("simple_speaker_listener is a 2-agent scenario")


class SimpleSpeakerListenerEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    SPEAKER, LISTENER = 0, 1

    def __init__(self, cfg: SpeakerListenerConfig = SpeakerListenerConfig()):
        self.cfg = cfg
        self.n_agents = 2
        # listener obs: vel(2) + landmark rel (2M) + comm (dim_c); the
        # speaker's goal-color obs (M one-hot) zero-pads into the same width
        self.obs_dim = 2 + 2 * cfg.n_landmarks + cfg.dim_c
        self.share_obs_dim = self.obs_dim * 2
        self.action_dim = 5

    def _spawn(self, key: jax.Array) -> SpeakerListenerState:
        c = self.cfg
        key, k_p, k_l, k_g = jax.random.split(key, 4)
        return SpeakerListenerState(
            rng=key,
            listener_pos=jax.random.uniform(k_p, (2,), minval=-1.0, maxval=1.0),
            listener_vel=jnp.zeros((2,)),
            landmark_pos=jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            goal=jax.random.randint(k_g, (), 0, c.n_landmarks),
            comm=jnp.zeros((c.dim_c,)),
            t=jnp.zeros((), jnp.int32),
        )

    def _observe(self, st: SpeakerListenerState):
        c = self.cfg
        # speaker: goal "color" one-hot, zero-padded to the listener width
        speaker = jnp.zeros((self.obs_dim,)).at[: c.n_landmarks].set(
            jax.nn.one_hot(st.goal, c.n_landmarks)
        )
        listener = jnp.concatenate([
            st.listener_vel,
            (st.landmark_pos - st.listener_pos[None, :]).reshape(-1),
            st.comm,
        ])
        obs = jnp.stack([speaker, listener])
        share = jnp.broadcast_to(obs.reshape(-1), (2, self.share_obs_dim))
        avail = jnp.asarray(
            [[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32
        )  # speaker: 3 comm symbols; listener: no-op/±x/±y
        return obs, share, avail

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[SpeakerListenerState, SLTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        zero = jnp.zeros(())
        return st, SLTimeStep(
            obs, share, avail, jnp.zeros((2, 1)), jnp.zeros((2,), bool), zero, zero
        )

    def step(self, st: SpeakerListenerState, action: jax.Array) -> Tuple[SpeakerListenerState, SLTimeStep]:
        c = self.cfg
        act = action.reshape(2, -1)[:, 0].astype(jnp.int32)
        comm = jax.nn.one_hot(jnp.clip(act[self.SPEAKER], 0, c.dim_c - 1), c.dim_c)
        onehot = jax.nn.one_hot(act[self.LISTENER], 5)
        u = jnp.stack([onehot[1] - onehot[2], onehot[3] - onehot[4]]) * c.sensitivity
        vel = st.listener_vel * (1.0 - c.damping) + u * c.dt
        pos = st.listener_pos + vel * c.dt

        stepped = SpeakerListenerState(
            st.rng, pos, vel, st.landmark_pos, st.goal, comm, st.t + 1
        )
        goal_pos = st.landmark_pos[st.goal]
        reward = -jnp.sum((pos - goal_pos) ** 2)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, SLTimeStep(
            obs, share, avail,
            jnp.broadcast_to(reward, (2, 1)),
            jnp.broadcast_to(done_now, (2,)),
            zero, zero,
        )
