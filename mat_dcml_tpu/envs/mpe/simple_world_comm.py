"""Pure-JAX MPE ``simple_world_comm`` (leader-directed predator-prey world).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_world_comm.py``.  Four
adversaries — one of whom is a speaking LEADER — chase two faster prey
around one obstacle, two food sites, and two forests that hide whoever
stands in them.  The leader sees through forests and broadcasts a 4-symbol
message to coordinate the pack.

Faithful semantics:

- Defaults 4 adversaries (leader = agent 0) + 2 good (``:11-14``); sizes
  0.075/0.045, accel 3.0/4.0, max_speed 1.0/1.3 (``:25-28``); obstacle
  collide size 0.2, food 0.03, forests 0.3, all spawned ``0.8·U(-1,1)²``
  (``:30-56,100-113``); ``dim_c = 4``.
- Actions: the leader is the only non-silent agent, so the reference gives
  it ``MultiDiscrete([move(5), comm(4)])`` and everyone else plain move.
  Here every agent gets the MultiDiscrete space with the comm head masked
  to symbol 0 for silent agents (flat per-head availability segments) —
  their messages are discarded exactly as ``core.py`` zeroes silent
  agents' comm state.
- Rewards (``:154-200``): prey lose 5 per touching adversary, pay
  ``2·bound`` per dimension on screen exit, gain +2 per touched food and
  ``+0.05·min_dist_to_food`` (the reference's sign quirk — it rewards
  DISTANCE from food — replicated); each adversary gets the shaped
  ``-0.1·min_good_dist`` to itself plus a shared +5 per (prey, adversary)
  contact pair.
- Obs (``:225-287``): ``[vel, pos, entity_rel(2·5: obstacle+food+forests),
  other_pos(2·5), (other_vel of prey), in_forest(±1,±1), leader_comm(4)]``
  with forest concealment: another agent's pos/vel read zero unless the
  viewer shares its forest, both are in the open, or the viewer is the
  leader.  Prey rows omit the comm block and put ``in_forest`` before
  ``other_vel`` (``:287``), zero-padding to the adversary width; the
  computed-but-unused ``food_pos``/``prey_forest`` blocks (``:241-246,
  265-277``) are dead code in the reference and not replicated.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle
from mat_dcml_tpu.envs.spaces import MultiDiscrete


class WorldCommState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2): [leader, adversaries..., good...]
    agent_vel: jax.Array
    landmark_pos: jax.Array   # (1, 2) obstacle
    food_pos: jax.Array       # (2, 2)
    forest_pos: jax.Array     # (2, 2)
    leader_comm: jax.Array    # (dim_c,)
    t: jax.Array


class WorldCommTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleWorldCommConfig:
    n_good: int = 2
    n_adversaries: int = 4    # leader included (agent 0)
    n_landmarks: int = 1
    n_food: int = 2
    n_forests: int = 2
    dim_c: int = 4
    episode_length: int = 25
    adv_size: float = 0.075
    good_size: float = 0.045
    adv_accel: float = 3.0
    good_accel: float = 4.0
    adv_max_speed: float = 1.0
    good_max_speed: float = 1.3
    landmark_size: float = 0.2
    food_size: float = 0.03
    forest_size: float = 0.3

    @property
    def n_agents(self) -> int:
        return self.n_adversaries + self.n_good


class SimpleWorldCommEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    def __init__(self, cfg: SimpleWorldCommConfig = SimpleWorldCommConfig()):
        self.cfg = cfg
        N, A, G = cfg.n_agents, cfg.n_adversaries, cfg.n_good
        self.n_agents = N
        n_entities = cfg.n_landmarks + cfg.n_food + cfg.n_forests
        # adversary row is the widest: vel2+pos2+2*entities+2(N-1)+2G+2+dim_c
        self._core_dim = 4 + 2 * n_entities + 2 * (N - 1) + 2 * G + 2 + cfg.dim_c
        self.obs_dim = self._core_dim + N
        self.share_obs_dim = self.obs_dim * N
        self.action_space = MultiDiscrete((5, cfg.dim_c))
        self.action_dim = self.action_space.sample_dim
        self.avail_dim = 5 + cfg.dim_c
        L = cfg.n_landmarks
        self._sizes = jnp.asarray(
            [cfg.adv_size] * A + [cfg.good_size] * G + [cfg.landmark_size] * L
        )
        self._collide = jnp.ones((N + L,), bool)
        self._movable = jnp.asarray([True] * N + [False] * L)
        self._max_speed = jnp.asarray(
            [cfg.adv_max_speed] * A + [cfg.good_max_speed] * G
        )
        self._gain = jnp.asarray(
            [particle.force_gain(cfg.adv_accel)] * A
            + [particle.force_gain(cfg.good_accel)] * G
        )
        self._agent_sizes = self._sizes[:N]

    def _spawn(self, key: jax.Array) -> WorldCommState:
        c = self.cfg
        key, k_a, k_l, k_fo, k_fr = jax.random.split(key, 5)
        u = lambda k, n: 0.8 * jax.random.uniform(k, (n, 2), minval=-1.0, maxval=1.0)
        return WorldCommState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=u(k_l, c.n_landmarks),
            food_pos=u(k_fo, c.n_food),
            forest_pos=u(k_fr, c.n_forests),
            leader_comm=jnp.zeros((c.dim_c,)),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[WorldCommState, WorldCommTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        return st, WorldCommTimeStep(
            obs, share, avail, jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero
        )

    def step(self, st: WorldCommState, action: jax.Array) -> Tuple[WorldCommState, WorldCommTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1).astype(jnp.int32)
        if act.shape[-1] != 2:
            # Fail loudly at trace time: with a wrong-width action array,
            # JAX's static out-of-bounds clamping would silently reuse the
            # move index as the leader's comm symbol (ADVICE r2).
            raise ValueError(
                f"simple_world_comm expects (N, 2) MultiDiscrete actions "
                f"(move, comm); got width {act.shape[-1]}"
            )
        onehot = jax.nn.one_hot(act[:, 0], 5)
        u = particle.decode_move(onehot) * self._gain[:, None]
        comm = jax.nn.one_hot(jnp.clip(act[0, 1], 0, c.dim_c - 1), c.dim_c)

        entity_pos = jnp.concatenate([st.agent_pos, st.landmark_pos])
        coll = particle.collision_forces(
            entity_pos, self._sizes, self._collide, self._movable
        )[:N]
        vel = particle.integrate(st.agent_vel, u + coll, self._max_speed)
        pos = st.agent_pos + vel * particle.DT

        stepped = WorldCommState(
            st.rng, pos, vel, st.landmark_pos, st.food_pos, st.forest_pos,
            comm, st.t + 1,
        )
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, WorldCommTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (N,)), zero, zero,
        )

    def _reward(self, st: WorldCommState) -> jax.Array:
        c = self.cfg
        A, G = c.n_adversaries, c.n_good
        adv_pos, good_pos = st.agent_pos[:A], st.agent_pos[A:]
        d = jnp.linalg.norm(good_pos[:, None, :] - adv_pos[None, :, :], axis=-1)  # (G, A)
        contact = d < (c.good_size + c.adv_size)

        food_d = jnp.linalg.norm(
            good_pos[:, None, :] - st.food_pos[None, :, :], axis=-1
        )  # (G, n_food)
        food_touch = food_d < (c.good_size + c.food_size)
        good_rew = (
            -5.0 * contact.sum(axis=1)
            - 2.0 * particle.bound_penalty(good_pos)
            + 2.0 * food_touch.sum(axis=1)
            + 0.05 * food_d.min(axis=1)   # reference sign quirk (see module doc)
        )
        adv_rew = -0.1 * d.min(axis=0) + 5.0 * contact.sum()
        return jnp.concatenate([adv_rew, good_rew])

    def _observe(self, st: WorldCommState):
        c = self.cfg
        N, A, G = c.n_agents, c.n_adversaries, c.n_good
        idx = jnp.arange(N)
        entities = jnp.concatenate([st.landmark_pos, st.food_pos, st.forest_pos])
        entity_rel = (entities[None, :, :] - st.agent_pos[:, None, :]).reshape(N, -1)
        rel = st.agent_pos[None, :, :] - st.agent_pos[:, None, :]

        fd = jnp.linalg.norm(
            st.agent_pos[:, None, :] - st.forest_pos[None, :, :], axis=-1
        )  # (N, n_forests)
        inf = fd < (self._agent_sizes[:, None] + c.forest_size)  # (N, 2)

        def row(i):
            others = jnp.where(idx != i, size=N - 1)[0]
            # visibility: shared forest, both fully outside, or leader viewer
            share_f = (inf[i][None, :] & inf[others]).any(axis=1)
            both_out = ~inf[i].any() & ~inf[others].any(axis=1)
            visible = share_f | both_out | (i == 0)
            other_pos = jnp.where(visible[:, None], rel[i][others], 0.0).reshape(-1)
            # visibility re-indexed by agent id (padded id N stays invisible)
            vis_by_id = jnp.zeros((N + 1,), bool).at[others].set(visible)
            good_others = jnp.where((idx != i) & (idx >= A), size=G, fill_value=N)[0]
            pad_vel = jnp.concatenate([st.agent_vel, jnp.zeros((1, 2))])
            other_vel = jnp.where(
                vis_by_id[good_others][:, None], pad_vel[good_others], 0.0
            ).reshape(-1)
            in_forest = jnp.where(inf[i], 1.0, -1.0)
            adv_row = jnp.concatenate([
                st.agent_vel[i], st.agent_pos[i], entity_rel[i], other_pos,
                other_vel, in_forest, st.leader_comm,
            ])
            pad = self._core_dim - (4 + entity_rel.shape[1] + other_pos.shape[0]
                                    + 2 * (G - 1) + 2)
            good_row = jnp.concatenate([
                st.agent_vel[i], st.agent_pos[i], entity_rel[i], other_pos,
                in_forest, other_vel[: 2 * (G - 1)], jnp.zeros((pad,)),
            ])
            return jnp.where(i < A, adv_row, good_row)

        core = jax.vmap(row)(idx)
        obs = jnp.concatenate([core, jnp.eye(N)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        # comm head masked to symbol 0 for every silent agent (leader free)
        move_avail = jnp.ones((N, 5))
        comm_avail = jnp.zeros((N, c.dim_c)).at[:, 0].set(1.0).at[0].set(1.0)
        avail = jnp.concatenate([move_avail, comm_avail], axis=1)
        return obs, share, avail
