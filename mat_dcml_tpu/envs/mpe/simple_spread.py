"""Pure-JAX MPE ``simple_spread`` (cooperative navigation).

A vectorizable rewrite of the reference's vendored multi-agent particle env
(``mat_src/mat/envs/mpe/core.py`` physics + ``environment.py`` step protocol +
``scenarios/simple_spread.py`` scenario): N agents move in a 2-D plane to
cover M landmarks while avoiding collisions.  The reference runs one Python
object graph per env inside subprocess workers; here the whole world is a
small pytree and ``step`` is an array program — ``vmap`` it over thousands of
envs.

Faithful semantics:

- Discrete(5) actions decoded as force ``u = (a1-a2, a3-a4) * sensitivity(5)``
  (``environment.py:249-264``, one-hot branch; agents accept integer indices
  and one-hot internally like the MPE runner's conversion,
  ``mpe_runner.py:165-177``).
- Physics: damped velocity integration ``v = v(1-damping) + F/m·dt``;
  softmax-penetration collision forces between agent pairs
  (``core.py:265-279,310-322``): ``F = k_c·Δ/|Δ|·margin·log(1+e^(-(|Δ|-d_min)/margin))``.
- Reward (``scenarios/simple_spread.py:71-82``): shared team reward
  ``N·(-Σ_l min_a |a-l|) - Σ_a collisions(a)``; NOTE the reference counts each
  agent's self-collision (``is_collision(a, a)`` is True), a constant ``-N``
  offset, replicated for parity.
- Obs per agent (``scenarios/simple_spread.py:84-116`` + id feats appended by
  ``environment.py:140-142``): ``[vel(2), pos(2), landmark_rel(2M),
  other_pos(2(N-1)), comm(2(N-1))≡0, one_hot_id(N)]``.
- Episode ends after ``episode_length`` steps (``environment.py:205-210``);
  auto-reset inside ``step`` returns the new episode's obs with the final
  step's reward (``env_wrappers.py:305-313`` worker semantics).
- Reset draws: agent pos ~ U(-1,1)², landmark pos ~ 0.8·U(-1,1)², zero
  velocities (``scenarios/simple_spread.py:37-45``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class SpreadState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2)
    agent_vel: jax.Array      # (N, 2)
    landmark_pos: jax.Array   # (M, 2)
    t: jax.Array              # int32 step counter


class SpreadTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array          # protocol compat (unused; zeros)
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleSpreadConfig:
    n_agents: int = 3
    n_landmarks: int = 3
    episode_length: int = 25   # world.world_length default (core.py:136)
    agent_size: float = 0.15   # scenarios/simple_spread.py:21
    landmark_size: float = 0.05  # Entity default (core.py:53)
    dt: float = 0.1
    damping: float = 0.25
    contact_force: float = 1e2
    contact_margin: float = 1e-3
    sensitivity: float = 5.0   # environment.py:261
    dim_c: int = 2             # communication dim (silent agents -> zeros)


class SimpleSpreadEnv:
    """Functional env bundle; same TimeStep protocol as the DCML env."""

    def __init__(self, cfg: SimpleSpreadConfig = SimpleSpreadConfig()):
        self.cfg = cfg
        N, M = cfg.n_agents, cfg.n_landmarks
        self.n_agents = N
        # vel2 + pos2 + 2M + 2(N-1) + comm 2(N-1) + id N
        self.obs_dim = 4 + 2 * M + (2 + cfg.dim_c) * (N - 1) + N
        self.share_obs_dim = self.obs_dim * N
        self.action_dim = 5  # Discrete(world.dim_p * 2 + 1) (environment.py:64)

    # ----------------------------------------------------------------- reset

    def _spawn(self, key: jax.Array) -> SpreadState:
        c = self.cfg
        key, k_a, k_l = jax.random.split(key, 3)
        agent_pos = jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0)
        landmark_pos = 0.8 * jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0)
        return SpreadState(
            rng=key,
            agent_pos=agent_pos,
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=landmark_pos,
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[SpreadState, SpreadTimeStep]:
        del episode_idx
        state = self._spawn(key)
        obs, share, avail = self._observe(state)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        ts = SpreadTimeStep(
            obs, share, avail,
            jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero,
        )
        return state, ts

    # ------------------------------------------------------------------ step

    def step(self, state: SpreadState, action: jax.Array) -> Tuple[SpreadState, SpreadTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1)
        # integer index -> one-hot (the MPE runner's conversion,
        # mpe_runner.py:165-177); one-hot vectors pass through
        if act.shape[-1] == 1:
            onehot = jax.nn.one_hot(act[:, 0].astype(jnp.int32), 5)
        else:
            onehot = act.astype(jnp.float32)
        u = jnp.stack(
            [onehot[:, 1] - onehot[:, 2], onehot[:, 3] - onehot[:, 4]], axis=1
        ) * c.sensitivity  # (environment.py:249-264)

        # pairwise agent collision forces (core.py:310-322)
        delta = state.agent_pos[:, None, :] - state.agent_pos[None, :, :]  # (N, N, 2)
        dist = jnp.sqrt(jnp.sum(delta**2, axis=-1) + 1e-12)
        dist_min = 2.0 * c.agent_size
        k = c.contact_margin
        penetration = jnp.logaddexp(0.0, -(dist - dist_min) / k) * k
        force_mag = c.contact_force * penetration / dist  # (N, N)
        off_diag = 1.0 - jnp.eye(N)
        pair_force = delta * (force_mag * off_diag)[..., None]  # force on i from j
        coll_force = pair_force.sum(axis=1)

        # integrate (core.py:265-279); mass=1, accel=None, no max_speed
        vel = state.agent_vel * (1.0 - c.damping) + (u + coll_force) * c.dt
        pos = state.agent_pos + vel * c.dt

        stepped = SpreadState(state.rng, pos, vel, state.landmark_pos, state.t + 1)
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        # auto-reset on episode end (env_wrappers.py:305-313)
        fresh = self._spawn(state.rng)
        new_state = jax.tree.map(
            lambda a, b: jnp.where(done_now, a, b), fresh, stepped
        )
        obs, share, avail = self._observe(new_state)
        zero = jnp.zeros(())
        ts = SpreadTimeStep(
            obs, share, avail,
            jnp.broadcast_to(reward, (N, 1)),
            jnp.broadcast_to(done_now, (N,)),
            zero, zero,
        )
        return new_state, ts

    def _reward(self, state: SpreadState) -> jax.Array:
        """Shared team reward (``scenarios/simple_spread.py:71-82`` summed over
        agents by ``environment.py:154-157``)."""
        c = self.cfg
        N = c.n_agents
        d = jnp.linalg.norm(
            state.agent_pos[:, None, :] - state.landmark_pos[None, :, :], axis=-1
        )  # (N, M)
        min_dists = d.min(axis=0).sum()
        # collisions: every pair within 2*size, self-pairs included (the
        # reference's is_collision(a, a) == True quirk)
        ad = jnp.linalg.norm(
            state.agent_pos[:, None, :] - state.agent_pos[None, :, :], axis=-1
        )
        n_coll = (ad < 2.0 * c.agent_size).sum()
        return -N * min_dists - n_coll.astype(jnp.float32)

    # ------------------------------------------------------------------- obs

    def _observe(self, state: SpreadState):
        c = self.cfg
        N, M = c.n_agents, c.n_landmarks
        landmark_rel = (state.landmark_pos[None, :, :] - state.agent_pos[:, None, :]).reshape(N, 2 * M)
        # other agents' relative positions, in agent order with self removed
        rel = state.agent_pos[None, :, :] - state.agent_pos[:, None, :]  # (N, N, 2)
        idx = jnp.arange(N)
        # gather the N-1 "others" rows per agent: for agent i take j != i in order
        others = jax.vmap(
            lambda i: rel[i][jnp.where(idx != i, size=N - 1)[0]].reshape(-1)
        )(idx)  # (N, 2(N-1))
        comm = jnp.zeros((N, c.dim_c * (N - 1)))  # silent agents
        agent_id = jnp.eye(N)
        obs = jnp.concatenate(
            [state.agent_vel, state.agent_pos, landmark_rel, others, comm, agent_id], axis=1
        )
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        avail = jnp.ones((N, self.action_dim))
        return obs, share, avail
