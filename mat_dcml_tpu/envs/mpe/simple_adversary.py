"""Pure-JAX MPE ``simple_adversary`` (physical deception).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_adversary.py``.  One
adversary (agent 0) and ``n_agents-1`` good agents move among
``n_agents-1`` landmarks, one of which is the secret goal.  Good agents
know the goal and try to cover it while the adversary — who cannot see
which landmark is the goal — infers it from their behavior.

Faithful semantics:

- No collisions, no accel/max_speed; all agents size 0.15, landmarks 0.08
  (``simple_adversary.py:17-31``); agents AND landmarks spawn at
  ``U(-1,1)²`` (``:45-52`` — landmarks are NOT shrunk by 0.8 here, unlike
  spread/tag); goal is a uniformly chosen landmark (``:41-44``).
- Per-agent rewards (non-collaborative): good agents all receive
  ``-min_a |a_good - goal| + Σ_adv |adv - goal|`` (shaped variant,
  ``:86-107``); the adversary receives ``-|adv - goal|²`` (squared
  distance, ``:109-117``).
- Obs: good ``[goal_rel(2), landmark_rel(2L), other_pos(2(N-1))]``;
  adversary ``[landmark_rel(2L), other_pos(2(N-1))]`` zero-padded to the
  good width (``:119-137``); one-hot id appended (``environment.py:140-142``).
  Note no velocity features in this scenario.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle


class AdversaryState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2), adversary first
    agent_vel: jax.Array      # (N, 2)
    landmark_pos: jax.Array   # (L, 2)
    goal: jax.Array           # () int32 landmark index
    t: jax.Array


class AdversaryTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleAdversaryConfig:
    n_agents: int = 3         # 1 adversary + 2 good (train_mpe num_agents)
    episode_length: int = 25
    agent_size: float = 0.15
    landmark_size: float = 0.08

    @property
    def n_landmarks(self) -> int:
        return self.n_agents - 1  # simple_adversary.py:16

    def __post_init__(self):
        if self.n_agents < 2:
            raise ValueError("simple_adversary needs >= 2 agents")


class SimpleAdversaryEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    N_ADVERSARIES = 1

    def __init__(self, cfg: SimpleAdversaryConfig = SimpleAdversaryConfig()):
        self.cfg = cfg
        N, L = cfg.n_agents, cfg.n_landmarks
        self.n_agents = N
        self._core_dim = 2 + 2 * L + 2 * (N - 1)  # good row is the widest
        self.obs_dim = self._core_dim + N
        self.share_obs_dim = self.obs_dim * N
        self.action_dim = 5

    def _spawn(self, key: jax.Array) -> AdversaryState:
        c = self.cfg
        key, k_a, k_l, k_g = jax.random.split(key, 4)
        return AdversaryState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            goal=jax.random.randint(k_g, (), 0, c.n_landmarks),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[AdversaryState, AdversaryTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        return st, AdversaryTimeStep(
            obs, share, avail, jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero
        )

    def step(self, st: AdversaryState, action: jax.Array) -> Tuple[AdversaryState, AdversaryTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1)
        onehot = (
            jax.nn.one_hot(act[:, 0].astype(jnp.int32), 5)
            if act.shape[-1] == 1 else act.astype(jnp.float32)
        )
        u = particle.decode_move(onehot) * particle.force_gain(None)
        vel = particle.integrate(st.agent_vel, u, jnp.full((N,), jnp.inf))
        pos = st.agent_pos + vel * particle.DT

        stepped = AdversaryState(st.rng, pos, vel, st.landmark_pos, st.goal, st.t + 1)
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, AdversaryTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (N,)), zero, zero,
        )

    def _reward(self, st: AdversaryState) -> jax.Array:
        goal_pos = st.landmark_pos[st.goal]
        adv_pos = st.agent_pos[: self.N_ADVERSARIES]
        good_pos = st.agent_pos[self.N_ADVERSARIES:]
        good_d = jnp.linalg.norm(good_pos - goal_pos, axis=-1)
        adv_d = jnp.linalg.norm(adv_pos - goal_pos, axis=-1)
        good_rew = -good_d.min() + adv_d.sum()
        adv_rew = -jnp.sum((adv_pos - goal_pos) ** 2, axis=-1)  # squared
        return jnp.concatenate(
            [adv_rew, jnp.full((self.cfg.n_agents - 1,), good_rew)]
        )

    def _observe(self, st: AdversaryState):
        c = self.cfg
        N = c.n_agents
        idx = jnp.arange(N)
        landmark_rel = (
            st.landmark_pos[None, :, :] - st.agent_pos[:, None, :]
        ).reshape(N, -1)
        rel = st.agent_pos[None, :, :] - st.agent_pos[:, None, :]
        goal_rel = st.landmark_pos[st.goal][None, :] - st.agent_pos  # (N, 2)

        def row(i):
            others = jnp.where(idx != i, size=N - 1)[0]
            other_pos = rel[i][others].reshape(-1)
            good = jnp.concatenate([goal_rel[i], landmark_rel[i], other_pos])
            adv = jnp.concatenate(
                [landmark_rel[i], other_pos, jnp.zeros((2,))]
            )
            return jnp.where(i < self.N_ADVERSARIES, adv, good)

        core = jax.vmap(row)(idx)
        obs = jnp.concatenate([core, jnp.eye(N)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        avail = jnp.ones((N, self.action_dim))
        return obs, share, avail
