"""Pure-JAX MPE ``simple_reference`` (cooperative communication, symmetric).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_reference.py``.  Two
agents, three fixed-color landmarks.  Each agent has a private goal landmark
the OTHER agent must reach (``goal_a`` = the other agent, ``goal_b`` = the
target landmark, ``:39-43``), and can see only its partner's goal color —
so both must simultaneously move (decoding the partner's messages) and
speak (describing the partner's target).

Faithful semantics:

- Actions: agents are movable and NOT silent with ``dim_c=10``, so the
  reference exposes ``MultiDiscrete([move(5), comm(10)])``
  (``environment.py:75-87``); the comm sub-action becomes the one-hot
  message visible to the partner on the SAME step (``core.py`` world.step
  updates comm before observations; ``environment.py:240-276`` decode).
- Shared reward (``world.collaborative = True``, ``:12``): the sum over
  agents of ``-|goal_a.pos - goal_b.pos|²`` (``:62-68``) — i.e.
  ``-(|agent1 - goal_of_0|² + |agent0 - goal_of_1|²)`` given to both.
- Obs: ``[vel(2), landmark_rel(6), partner_goal_color(3), partner_comm(10)]``
  (``:69-97``; the goal-position and own-color terms are commented out in
  the reference) + one-hot id (``environment.py:140-142``) -> 23 dims.
  Landmark colors are the fixed R/G/B rows (``:47-49``).
- Spawns: agents ``U(-1,1)²``, landmarks ``0.8·U(-1,1)²``, each agent's
  goal landmark uniform (``:40-43,55-60``); no collisions.

The MAT family is not available here — the reference's transformer act
machinery has no MultiDiscrete family either (``transformer_act.py``);
train with mappo / rmappo / ippo.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle
from mat_dcml_tpu.envs.spaces import MultiDiscrete

LANDMARK_COLORS = jnp.asarray(
    [[0.75, 0.25, 0.25], [0.25, 0.75, 0.25], [0.25, 0.25, 0.75]]
)  # simple_reference.py:47-49


class ReferenceState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (2, 2)
    agent_vel: jax.Array      # (2, 2)
    landmark_pos: jax.Array   # (3, 2)
    goal_b: jax.Array         # (2,) int32 — agent i's target for its PARTNER
    comm: jax.Array           # (2, dim_c) last messages
    t: jax.Array


class ReferenceTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleReferenceConfig:
    n_landmarks: int = 3
    dim_c: int = 10           # simple_reference.py:11
    episode_length: int = 25
    n_agents: int = 2

    def __post_init__(self):
        if self.n_agents != 2:
            raise ValueError("simple_reference is a 2-agent scenario (:15-16)")
        if self.n_landmarks != 3:
            raise ValueError("simple_reference has 3 fixed-color landmarks")


class SimpleReferenceEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    def __init__(self, cfg: SimpleReferenceConfig = SimpleReferenceConfig()):
        self.cfg = cfg
        self.n_agents = 2
        # vel2 + 2L + color3 + partner comm + id2
        self.obs_dim = 2 + 2 * cfg.n_landmarks + 3 + cfg.dim_c + 2
        self.share_obs_dim = self.obs_dim * 2
        self.action_space = MultiDiscrete((5, cfg.dim_c))
        self.action_dim = self.action_space.sample_dim  # stored width: 2 ints
        self.avail_dim = 5 + cfg.dim_c                  # flat per-head segments

    def _spawn(self, key: jax.Array) -> ReferenceState:
        c = self.cfg
        key, k_a, k_l, k_g = jax.random.split(key, 4)
        return ReferenceState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (2, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((2, 2)),
            landmark_pos=0.8 * jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            goal_b=jax.random.randint(k_g, (2,), 0, c.n_landmarks),
            comm=jnp.zeros((2, c.dim_c)),
            t=jnp.zeros((), jnp.int32),
        )

    def _observe(self, st: ReferenceState):
        landmark_rel = (
            st.landmark_pos[None, :, :] - st.agent_pos[:, None, :]
        ).reshape(2, -1)
        # agent i sees its PARTNER's goal color (goal_b of the partner is the
        # landmark *i* must reach; i sees the color of the one it must
        # describe — its own goal_b): observation() reads agent.goal_b
        goal_color = LANDMARK_COLORS[st.goal_b]          # (2, 3)
        partner_comm = st.comm[::-1]                     # other agent's message
        obs = jnp.concatenate(
            [st.agent_vel, landmark_rel, goal_color, partner_comm, jnp.eye(2)],
            axis=1,
        )
        share = jnp.broadcast_to(obs.reshape(-1), (2, self.share_obs_dim))
        avail = jnp.ones((2, self.avail_dim))
        return obs, share, avail

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[ReferenceState, ReferenceTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        zero = jnp.zeros(())
        return st, ReferenceTimeStep(
            obs, share, avail, jnp.zeros((2, 1)), jnp.zeros((2,), bool), zero, zero
        )

    def step(self, st: ReferenceState, action: jax.Array) -> Tuple[ReferenceState, ReferenceTimeStep]:
        c = self.cfg
        act = action.reshape(2, -1).astype(jnp.int32)   # (2, [move, comm])
        if act.shape[-1] != 2:
            # See simple_world_comm.step: a wrong-width array would silently
            # alias move/comm indices under static index clamping (ADVICE r2).
            raise ValueError(
                f"simple_reference expects (2, 2) MultiDiscrete actions "
                f"(move, comm); got width {act.shape[-1]}"
            )
        onehot = jax.nn.one_hot(act[:, 0], 5)
        u = particle.decode_move(onehot) * particle.force_gain(None)
        comm = jax.nn.one_hot(jnp.clip(act[:, 1], 0, c.dim_c - 1), c.dim_c)
        vel = particle.integrate(st.agent_vel, u, jnp.full((2,), jnp.inf))
        pos = st.agent_pos + vel * particle.DT

        stepped = ReferenceState(
            st.rng, pos, vel, st.landmark_pos, st.goal_b, comm, st.t + 1
        )
        # shared reward: agent i's term is -|partner_pos - goal_b_i|²
        goal_pos = stepped.landmark_pos[stepped.goal_b]  # (2, 2)
        partner_pos = pos[::-1]
        reward = -jnp.sum((partner_pos - goal_pos) ** 2)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, ReferenceTimeStep(
            obs, share, avail,
            jnp.broadcast_to(reward, (2, 1)),
            jnp.broadcast_to(done_now, (2,)),
            zero, zero,
        )
