"""Shared pure-JAX MPE particle physics.

Vectorized rewrite of the reference's per-object physics loop
(``mat_src/mat/envs/mpe/core.py:224-279`` force gathering + integration and
``environment.py:240-265`` action decode) used by every scenario env in this
package.  Entities are rows of flat arrays (positions ``(E, 2)``, static
per-entity parameters as ``(E,)`` constants baked into the jitted program),
so the O(E²) collision response becomes one broadcasted pairwise expression
instead of the reference's nested Python loop.

Faithful quirks preserved:

- ``accel`` is applied TWICE in the reference — once as the action
  "sensitivity" (``environment.py:261-263``) and once as the force gain
  ``mass * accel`` (``core.py:237``) — so an agent with ``accel=a`` feels
  force ``a²·u`` while an accel-less agent feels ``5·u`` (mass 1).
- Collision force uses softmax penetration
  ``k·logaddexp(0, -(dist - dist_min)/k)`` (``core.py:315-317``) between
  every pair where both entities collide and the receiver is movable.
- Velocity is damped before the force is applied, then speed-clamped to
  ``max_speed`` (``core.py:265-279``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DT = 0.1
DAMPING = 0.25
CONTACT_FORCE = 1e2
CONTACT_MARGIN = 1e-3


def decode_move(onehot5: jax.Array) -> jax.Array:
    """Discrete(5) one-hot rows -> raw 2-D force direction (pre-gain).

    Action layout no-op/+x/-x/+y/-y per ``environment.py:249-264``
    (discrete_action_space branch): ``u = (a1-a2, a3-a4)``.
    """
    return jnp.stack(
        [onehot5[..., 1] - onehot5[..., 2], onehot5[..., 3] - onehot5[..., 4]],
        axis=-1,
    )


def force_gain(accel: float | None) -> float:
    """Effective scalar multiplying the raw move direction (see module doc)."""
    return accel * accel if accel is not None else 5.0


def collision_forces(
    pos: jax.Array,
    sizes: jax.Array,
    collide: jax.Array,
    movable: jax.Array,
    contact_force: float = CONTACT_FORCE,
    contact_margin: float = CONTACT_MARGIN,
) -> jax.Array:
    """Pairwise contact forces on every entity (``core.py:241-263,310-322``).

    pos: (E, 2); sizes/collide/movable: (E,) static entity parameters.
    Returns (E, 2) summed force on each entity.  All reference scenarios use
    unit masses, so the movable/movable mass ratio (``core.py:318-321``) is 1.
    """
    delta = pos[:, None, :] - pos[None, :, :]                 # (E, E, 2)
    dist = jnp.sqrt(jnp.sum(delta**2, axis=-1) + 1e-12)
    dist_min = sizes[:, None] + sizes[None, :]
    k = contact_margin
    penetration = jnp.logaddexp(0.0, -(dist - dist_min) / k) * k
    mag = contact_force * penetration / dist                   # (E, E)
    pair = collide[:, None] & collide[None, :] & ~jnp.eye(pos.shape[0], dtype=bool)
    mag = jnp.where(pair, mag, 0.0)
    # receiver must be movable; non-movable entities absorb without moving
    return (delta * mag[..., None]).sum(axis=1) * movable[:, None]


def integrate(
    vel: jax.Array,
    force: jax.Array,
    max_speed: jax.Array,
    dt: float = DT,
    damping: float = DAMPING,
) -> jax.Array:
    """Damped Euler velocity update + per-entity speed clamp (``core.py:265-279``).

    max_speed: (E,) with ``inf`` for unclamped entities.
    """
    vel = vel * (1.0 - damping) + force * dt
    speed = jnp.sqrt(jnp.sum(vel**2, axis=-1) + 1e-12)
    scale = jnp.minimum(1.0, max_speed / speed)
    return vel * scale[:, None]


def bound_penalty(pos: jax.Array) -> jax.Array:
    """Per-agent screen-exit penalty (``scenarios/simple_tag.py:100-108``).

    pos: (..., 2).  Sums the per-dimension piecewise bound() term:
    0 below 0.9, linear ramp to 1.0, then exp(2x-2) capped at 10.
    """
    x = jnp.abs(pos)
    ramp = (x - 0.9) * 10.0
    expo = jnp.minimum(jnp.exp(2.0 * x - 2.0), 10.0)
    per_dim = jnp.where(x < 0.9, 0.0, jnp.where(x < 1.0, ramp, expo))
    return per_dim.sum(axis=-1)
