"""Headless MPE episode rendering to GIF.

The reference renders MPE through a pyglet OpenGL viewer and the MPE runner
saves eval episodes as GIFs (``mpe_runner.py:193-255``,
``mpe/rendering.py``) — unusable on a display-less TPU VM.  This module is
the software equivalent: a tiny numpy circle rasterizer over the same
world box and entity color scheme, written with PIL (no GL, no pyglet).

Works with any scenario env in this package whose state exposes
``agent_pos`` plus optional ``landmark_pos`` / ``food_pos`` / ``forest_pos``
rows; role split and radii are read off the env config
(``adv_size``/``good_size``/``agent_size``...).  The pure-comm
``simple_crypto`` has no positions and is not renderable (as in the
reference, whose crypto agents are immovable dots).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# reference entity colors (scenario reset_world conventions)
GOOD = (115, 242, 115)
ADVERSARY = (242, 115, 115)
LEADER = (166, 166, 64)
LANDMARK = (64, 64, 64)
FOOD = (38, 38, 166)
FOREST = (153, 230, 153)
BG = (255, 255, 255)

CAM_RANGE = 1.4  # world box drawn; MPE viewer uses a similar fixed zoom


def is_renderable(env) -> bool:
    """True when the env's state carries positions, or the env declares a
    static display layout (``simple_crypto_display``).  Costs one eager
    reset of a tiny env."""
    import jax

    if hasattr(env, "display_layout"):
        return True
    state, _ = env.reset(jax.random.key(0))
    return hasattr(state, "agent_pos")


GOAL_LANDMARK = (38, 38, 191)   # simple_crypto_display.py:87 [0.15,0.15,0.75]
SPEAKER = (64, 191, 64)         # simple_crypto_display.py:52 [0.25,0.75,0.25]


def _display_entities(env, state):
    """Entities for a static-layout scenario (``simple_crypto_display``):
    fixed spawns, goal landmark highlighted, agents tinted by their latest
    comm symbol (the headless stand-in for the reference's debug prints)."""
    agents, landmarks = env.display_layout()
    goal = int(np.asarray(state.goal))
    out = [
        (p, 0.08, GOAL_LANDMARK if i == goal else LANDMARK)
        for i, p in enumerate(landmarks)
    ]
    comm = np.asarray(state.comm)
    for i, p in enumerate(agents):
        if getattr(env, "ALICE", None) == i:
            base = SPEAKER
        elif i == 0:                       # Eve, the adversary
            base = ADVERSARY
        else:
            base = GOOD
        # tint toward white by comm-symbol index so utterances animate
        sym = int(comm[i].argmax()) if comm[i].any() else -1
        tint = 0.0 if sym < 0 else min(0.15 * (sym + 1), 1.0)   # dim_c can be >6
        color = tuple(int(c + (255 - c) * tint) for c in base)
        out.append((p, 0.05, color))
    return out


def _entities(env, state) -> List[Tuple[np.ndarray, float, Tuple[int, int, int]]]:
    """(pos(2,), radius, color) per entity, back-to-front draw order."""
    cfg = env.cfg
    if hasattr(env, "display_layout"):
        return _display_entities(env, state)
    if not hasattr(state, "agent_pos"):
        raise TypeError(
            f"{type(state).__name__} has no positions to render "
            "(pure-comm scenarios like simple_crypto are not renderable)"
        )
    out: List[Tuple[np.ndarray, float, Tuple[int, int, int]]] = []

    def rows(name, radius, color):
        arr = getattr(state, name, None)
        if arr is None:
            return
        for p in np.asarray(arr).reshape(-1, 2):
            out.append((p, radius, color))

    rows("forest_pos", getattr(cfg, "forest_size", 0.3), FOREST)
    rows("landmark_pos", getattr(cfg, "landmark_size", 0.08), LANDMARK)
    rows("food_pos", getattr(cfg, "food_size", 0.03), FOOD)

    agent_pos = np.asarray(state.agent_pos).reshape(-1, 2)
    # role count lives on the config (tag/attack/world_comm) or as an env
    # class constant (adversary/push: N_ADVERSARIES)
    n_adv = getattr(cfg, "n_adversaries", getattr(env, "N_ADVERSARIES", 0))
    adv_size = getattr(cfg, "adv_size", getattr(cfg, "agent_size", 0.05))
    good_size = getattr(cfg, "good_size", getattr(cfg, "agent_size", 0.05))
    for i, p in enumerate(agent_pos):
        if i < n_adv:
            color = LEADER if (i == 0 and hasattr(cfg, "n_forests")) else ADVERSARY
            out.append((p, adv_size, color))
        else:
            out.append((p, good_size, GOOD))
    return out


def render_frame(env, state, size: int = 350) -> np.ndarray:
    """One (size, size, 3) uint8 frame of the current world state."""
    img = np.empty((size, size, 3), np.uint8)
    img[:] = BG
    # pixel-center world coordinates
    axis = (np.arange(size) + 0.5) / size * (2 * CAM_RANGE) - CAM_RANGE
    xs = axis[None, :]
    ys = -axis[:, None]  # screen y grows downward
    for pos, radius, color in _entities(env, state):
        mask = (xs - pos[0]) ** 2 + (ys - pos[1]) ** 2 <= radius**2
        img[mask] = color
    return img


def save_gif(frames: Sequence[np.ndarray], path: str, fps: int = 12) -> None:
    """Write frames as an animated GIF (PIL; no display required)."""
    from PIL import Image

    ims = [Image.fromarray(f) for f in frames]
    ims[0].save(
        path, save_all=True, append_images=ims[1:],
        duration=int(1000 / fps), loop=0,
    )


def render_episode(env, policy, params, key, n_steps: int = 0,
                   size: int = 350) -> List[np.ndarray]:
    """Roll one deterministic episode and rasterize every step.

    ``policy`` must expose ``get_actions(params, key, share_obs, obs,
    available_actions, deterministic=...)`` over (1, A, ·) batches — the
    MAT/actor-critic policy surface used by the runners' eval loops.
    """
    import jax
    import jax.numpy as jnp

    n_steps = n_steps or getattr(env.cfg, "episode_length", 25)
    state, ts = env.reset(key)
    frames = [render_frame(env, state, size)]
    step = jax.jit(env.step)
    for _ in range(n_steps):
        out = policy.get_actions(
            params, jax.random.key(0),
            ts.share_obs[None], ts.obs[None],
            ts.available_actions[None], deterministic=True,
        )
        act = jnp.asarray(out.action)[0]
        state, ts = step(state, act)
        frames.append(render_frame(env, state, size))
    return frames
