"""Pure-JAX MPE ``simple_push`` (keep-away).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_push.py``.  One good
agent tries to reach the goal landmark; one adversary is rewarded for
keeping it away (by shoving — agents collide).  Landmark colors encode
the goal identity in the good agent's observation.

Faithful semantics:

- Agent 0 is the adversary (``simple_push.py:20-29``); agents collide
  (default size 0.05, unit mass), landmarks don't (``:30-35``); agents at
  ``U(-1,1)²``, landmarks at ``0.8·U(-1,1)²``, goal uniform (``:41-64``).
- Per-agent rewards: good ``-|pos - goal|``; adversary
  ``min_good |good - goal| - |adv - goal|`` (``:66-81``).
- Obs: good ``[vel(2), goal_rel(2), agent_color(3), landmark_rel(2L),
  landmark_colors(3L), other_pos(2(N-1))]``; adversary
  ``[vel(2), landmark_rel(2L), other_pos(2(N-1))]`` zero-padded
  (``:83-105``).  Landmark i's color is ``[0.1,0.1,0.1]`` with channel
  ``i+1`` += 0.8 (``:42-46``); the good agent's color marks the goal index
  with channel ``goal+1`` += 0.5 on ``[0.25]*3`` (``:48-56``) — both are
  computed, not stored.  One-hot id appended (``environment.py:140-142``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle


class PushState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2), adversary first
    agent_vel: jax.Array
    landmark_pos: jax.Array   # (L, 2)
    goal: jax.Array           # () int32
    t: jax.Array


class PushTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimplePushConfig:
    n_agents: int = 2         # 1 adversary + 1 good (simple_push.py:16-17)
    n_landmarks: int = 2
    episode_length: int = 25
    agent_size: float = 0.05  # Entity default (core.py:49-53)
    landmark_size: float = 0.05

    def __post_init__(self):
        if self.n_agents < 2:
            raise ValueError("simple_push needs >= 2 agents")


class SimplePushEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    N_ADVERSARIES = 1

    def __init__(self, cfg: SimplePushConfig = SimplePushConfig()):
        self.cfg = cfg
        N, L = cfg.n_agents, cfg.n_landmarks
        self.n_agents = N
        # good row: vel2 + goal_rel2 + color3 + 2L + 3L + 2(N-1)
        self._core_dim = 7 + 5 * L + 2 * (N - 1)
        self.obs_dim = self._core_dim + N
        self.share_obs_dim = self.obs_dim * N
        self.action_dim = 5
        self._sizes = jnp.asarray([cfg.agent_size] * N + [cfg.landmark_size] * L)
        self._collide = jnp.asarray([True] * N + [False] * L)
        self._movable = jnp.asarray([True] * N + [False] * L)

    def _spawn(self, key: jax.Array) -> PushState:
        c = self.cfg
        key, k_a, k_l, k_g = jax.random.split(key, 4)
        return PushState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=0.8 * jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            goal=jax.random.randint(k_g, (), 0, c.n_landmarks),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[PushState, PushTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        return st, PushTimeStep(
            obs, share, avail, jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero
        )

    def step(self, st: PushState, action: jax.Array) -> Tuple[PushState, PushTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1)
        onehot = (
            jax.nn.one_hot(act[:, 0].astype(jnp.int32), 5)
            if act.shape[-1] == 1 else act.astype(jnp.float32)
        )
        u = particle.decode_move(onehot) * particle.force_gain(None)
        entity_pos = jnp.concatenate([st.agent_pos, st.landmark_pos])
        coll = particle.collision_forces(
            entity_pos, self._sizes, self._collide, self._movable
        )[:N]
        vel = particle.integrate(st.agent_vel, u + coll, jnp.full((N,), jnp.inf))
        pos = st.agent_pos + vel * particle.DT

        stepped = PushState(st.rng, pos, vel, st.landmark_pos, st.goal, st.t + 1)
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, PushTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (N,)), zero, zero,
        )

    def _reward(self, st: PushState) -> jax.Array:
        A = self.N_ADVERSARIES
        goal_pos = st.landmark_pos[st.goal]
        adv_pos = st.agent_pos[:A]
        good_pos = st.agent_pos[A:]
        good_d = jnp.linalg.norm(good_pos - goal_pos, axis=-1)
        adv_d = jnp.linalg.norm(adv_pos - goal_pos, axis=-1)
        return jnp.concatenate([good_d.min() - adv_d, -good_d])

    def _observe(self, st: PushState):
        c = self.cfg
        N, L = c.n_agents, c.n_landmarks
        idx = jnp.arange(N)
        landmark_rel = (
            st.landmark_pos[None, :, :] - st.agent_pos[:, None, :]
        ).reshape(N, -1)
        rel = st.agent_pos[None, :, :] - st.agent_pos[:, None, :]
        goal_rel = st.landmark_pos[st.goal][None, :] - st.agent_pos
        # landmark colors: [0.1,0.1,0.1] + 0.8 on channel i+1 (simple_push.py:42-46)
        lm_colors = (
            jnp.full((L, 3), 0.1)
            .at[jnp.arange(L), jnp.minimum(jnp.arange(L) + 1, 2)]
            .add(0.8)
            .reshape(-1)
        )
        # good agent color marks the goal: [0.25]*3 + 0.5 on channel goal+1
        agent_color = jnp.full((3,), 0.25).at[jnp.minimum(st.goal + 1, 2)].add(0.5)

        def row(i):
            others = jnp.where(idx != i, size=N - 1)[0]
            other_pos = rel[i][others].reshape(-1)
            good = jnp.concatenate(
                [st.agent_vel[i], goal_rel[i], agent_color, landmark_rel[i],
                 lm_colors, other_pos]
            )
            adv_pad = self._core_dim - (2 + 2 * L + 2 * (N - 1))
            adv = jnp.concatenate(
                [st.agent_vel[i], landmark_rel[i], other_pos, jnp.zeros((adv_pad,))]
            )
            return jnp.where(i < self.N_ADVERSARIES, adv, good)

        core = jax.vmap(row)(idx)
        obs = jnp.concatenate([core, jnp.eye(N)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        avail = jnp.ones((N, self.action_dim))
        return obs, share, avail
