"""Pure-JAX MPE ``simple_crypto`` (covert communication).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_crypto.py``.  Three
immovable agents: Eve (agent 0, adversary), Bob (agent 1, good listener),
Alice (agent 2, speaker).  Alice sees the goal landmark's color and a
private key shared only with Bob; both Bob and Eve hear her message; each
"speaks" a reconstruction through its own comm channel.  The good team is
rewarded when Bob's utterance matches the goal color and Eve's does not;
Eve is rewarded for matching it.

Faithful semantics:

- ``dim_c = 4``; every agent is ``movable=False`` and not silent
  (``simple_crypto.py:27-35``), so each agent's action is ONE categorical
  comm symbol (``environment.py`` exposes the comm-only Discrete(dim_c)
  space for immovable speakers) — positions never change and never enter
  any observation; the scenario is a pure signalling game.
- Landmark i's "color" is the one-hot ``e_i`` in dim_c channels
  (``:54-59``); the goal and the key are independent uniformly-chosen
  landmarks (``:61-64``) — the key is the landmark COLOR, not an index.
- Rewards after comm update (per-agent, non-collaborative):
  Eve: ``-|c_Eve - goal_color|²``; Alice and Bob share
  ``-|c_Bob - goal_color|² + |c_Eve - goal_color|²`` (``:98-122``;
  the all-zero-comm skip only fires before any message exists, which the
  one-hot comm alphabet makes unreachable after the first step).
- Obs: Alice ``[goal_color(4), key(4)]``; Bob ``[key(4), alice_comm(4)]``;
  Eve ``[alice_comm(4)]`` zero-padded (``:124-171`` — only the SPEAKER's
  comm is audible, and positions are absent) + one-hot id.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class CryptoState(NamedTuple):
    rng: jax.Array
    goal: jax.Array           # () int32 landmark index
    key: jax.Array            # () int32 landmark index (Alice+Bob's secret)
    comm: jax.Array           # (3, dim_c) last utterances [Eve, Bob, Alice]
    t: jax.Array


class CryptoTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleCryptoConfig:
    n_landmarks: int = 2      # simple_crypto.py:26 (args.num_landmarks # 2)
    dim_c: int = 4
    episode_length: int = 25
    n_agents: int = 3

    def __post_init__(self):
        if self.n_agents != 3:
            raise ValueError("simple_crypto is a 3-agent scenario (Eve/Bob/Alice)")
        if self.n_landmarks > self.dim_c:
            raise ValueError("landmark one-hot colors need n_landmarks <= dim_c")


class SimpleCryptoEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    EVE, BOB, ALICE = 0, 1, 2

    def __init__(self, cfg: SimpleCryptoConfig = SimpleCryptoConfig()):
        self.cfg = cfg
        self.n_agents = 3
        self._core_dim = 2 * cfg.dim_c    # widest rows: Alice/Bob
        self.obs_dim = self._core_dim + 3
        self.share_obs_dim = self.obs_dim * 3
        self.action_dim = cfg.dim_c       # comm symbol (Discrete(dim_c))

    def _spawn(self, key: jax.Array) -> CryptoState:
        c = self.cfg
        key, k_g, k_k = jax.random.split(key, 3)
        return CryptoState(
            rng=key,
            goal=jax.random.randint(k_g, (), 0, c.n_landmarks),
            key=jax.random.randint(k_k, (), 0, c.n_landmarks),
            comm=jnp.zeros((3, c.dim_c)),
            t=jnp.zeros((), jnp.int32),
        )

    def _observe(self, st: CryptoState):
        c = self.cfg
        goal_color = jax.nn.one_hot(st.goal, c.dim_c)
        key_color = jax.nn.one_hot(st.key, c.dim_c)
        alice_comm = st.comm[self.ALICE]
        pad = jnp.zeros((c.dim_c,))
        rows = jnp.stack([
            jnp.concatenate([alice_comm, pad]),          # Eve
            jnp.concatenate([key_color, alice_comm]),    # Bob
            jnp.concatenate([goal_color, key_color]),    # Alice
        ])
        obs = jnp.concatenate([rows, jnp.eye(3)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (3, self.share_obs_dim))
        avail = jnp.ones((3, self.action_dim))
        return obs, share, avail

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[CryptoState, CryptoTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        zero = jnp.zeros(())
        return st, CryptoTimeStep(
            obs, share, avail, jnp.zeros((3, 1)), jnp.zeros((3,), bool), zero, zero
        )

    def step(self, st: CryptoState, action: jax.Array) -> Tuple[CryptoState, CryptoTimeStep]:
        c = self.cfg
        act = action.reshape(3, -1)[:, 0].astype(jnp.int32)
        comm = jax.nn.one_hot(jnp.clip(act, 0, c.dim_c - 1), c.dim_c)
        stepped = CryptoState(st.rng, st.goal, st.key, comm, st.t + 1)

        goal_color = jax.nn.one_hot(stepped.goal, c.dim_c)
        eve_err = jnp.sum((comm[self.EVE] - goal_color) ** 2)
        bob_err = jnp.sum((comm[self.BOB] - goal_color) ** 2)
        good_rew = -bob_err + eve_err
        reward = jnp.stack([-eve_err, good_rew, good_rew])
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, CryptoTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (3,)), zero, zero,
        )


class SimpleCryptoDisplayEnv(SimpleCryptoEnv):
    """``simple_crypto_display`` — the demo/visualization variant.

    Reference: ``mat_src/mat/envs/mpe/scenarios/simple_crypto_display.py``.
    Its diff vs ``simple_crypto`` is entirely presentational: agents spawn on
    a fixed vertical line at x=0, landmarks on a fixed column at x=0.5, the
    goal landmark is highlighted blue, the speaker green, and debug prints
    are enabled — the signalling game itself (rewards, observations, comm)
    is IDENTICAL math (positions never enter either scenario's observations;
    the ``channel``/``color`` attribute rename carries the same one-hot).
    Here the fixed layout feeds the headless renderer (``render.py``)
    instead of stdout prints: agents are drawn at the reference's
    deterministic positions, tinted by their latest comm symbol."""

    def display_layout(self):
        """Static (agent_pos (3, 2), landmark_pos (n_landmarks, 2)) — the
        reference's fixed spawns (``simple_crypto_display.py:71-81``)."""
        import numpy as np

        n, nl = self.n_agents, self.cfg.n_landmarks
        agents = np.stack([
            np.array([0.0, -0.5 + 1.0 / (n - 1) * i]) for i in range(n)
        ])
        landmarks = np.stack([
            np.array([0.5, 0.5 - 0.5 / max(nl - 1, 1) * i]) for i in range(nl)
        ])
        return agents, landmarks
