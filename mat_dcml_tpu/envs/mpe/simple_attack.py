"""Pure-JAX MPE ``simple_attack`` (goal-seeking with interception).

Reference: ``mat_src/mat/envs/mpe/scenarios/simple_attack.py`` (an
author-added scenario, not in upstream MPE).  Every agent — adversaries
first — has its own index-matched goal landmark (``reset_world``
``:54``: ``world.agents[i].goal = landmark_i``, hence the
``num_landmarks == num_agents`` assert ``:14``); all agents share one
body type (size 0.075, accel 3.0, max_speed 1.0, ``:22-25``) and landmarks
are large collidable obstacles (``:29-33``).

Rewards (per-agent, ``:97-146``): both roles get ``-|pos - goal|`` plus a
+0.5 bonus inside the goal radius and the screen-exit ``bound`` penalty;
good agents additionally lose 0.1 per adversary within 0.15 and 0.5 per
touching adversary; adversaries lose 0.5 per (good, adversary) contact
pair anywhere on the field.

Obs (``:148-163``): ``[vel(2), pos(2), landmark_rel(2L), other_pos(2(N-1)),
other_vel(2(N-1))]`` — ALL others' velocities, so rows are homogeneous
(no padding needed) + one-hot id appended by the driver.

Reference defects documented, not replicated:
- ``bound`` is defined as a class-level function and called as a bare
  name inside both reward methods (``:89-95,118,143``) — a ``NameError``
  at first reward call; the scenario cannot actually run upstream.  The
  evident intent (simple_tag's piecewise bound penalty) is implemented.
- ``self.agent_failed`` is set unconditionally under ``if agent.collide``
  (``:115``), making ``info['fail']`` always true after one step; not
  carried.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.mpe import particle


class AttackState(NamedTuple):
    rng: jax.Array
    agent_pos: jax.Array      # (N, 2), adversaries first
    agent_vel: jax.Array
    landmark_pos: jax.Array   # (N, 2) — one goal landmark per agent
    t: jax.Array


class AttackTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class SimpleAttackConfig:
    # the reference's annotated defaults (3 adversaries + 1 good,
    # 3 landmarks, ``:10-13``) violate its own num_landmarks == num_agents
    # assert (``:14``); resolved here by keeping 3 landmarks and dropping to
    # 2 adversaries so the constraint holds
    n_good: int = 1
    n_adversaries: int = 2
    episode_length: int = 25
    agent_size: float = 0.075
    accel: float = 3.0
    max_speed: float = 1.0
    landmark_size: float = 0.2

    @property
    def n_agents(self) -> int:
        return self.n_adversaries + self.n_good

    @property
    def n_landmarks(self) -> int:
        return self.n_agents  # simple_attack.py:14 assert


class SimpleAttackEnv:
    """Functional env bundle; same TimeStep protocol as simple_spread."""

    def __init__(self, cfg: SimpleAttackConfig = SimpleAttackConfig()):
        self.cfg = cfg
        N = cfg.n_agents
        self.n_agents = N
        self.obs_dim = 4 + 2 * cfg.n_landmarks + 4 * (N - 1) + N
        self.share_obs_dim = self.obs_dim * N
        self.action_dim = 5
        self._sizes = jnp.asarray(
            [cfg.agent_size] * N + [cfg.landmark_size] * cfg.n_landmarks
        )
        self._collide = jnp.ones((N + cfg.n_landmarks,), bool)
        self._movable = jnp.asarray([True] * N + [False] * cfg.n_landmarks)
        self._max_speed = jnp.full((N,), cfg.max_speed)
        self._gain = jnp.full((N,), particle.force_gain(cfg.accel))

    def _spawn(self, key: jax.Array) -> AttackState:
        c = self.cfg
        key, k_a, k_l = jax.random.split(key, 3)
        return AttackState(
            rng=key,
            agent_pos=jax.random.uniform(k_a, (c.n_agents, 2), minval=-1.0, maxval=1.0),
            agent_vel=jnp.zeros((c.n_agents, 2)),
            landmark_pos=0.8 * jax.random.uniform(k_l, (c.n_landmarks, 2), minval=-1.0, maxval=1.0),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[AttackState, AttackTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        N = self.cfg.n_agents
        zero = jnp.zeros(())
        return st, AttackTimeStep(
            obs, share, avail, jnp.zeros((N, 1)), jnp.zeros((N,), bool), zero, zero
        )

    def step(self, st: AttackState, action: jax.Array) -> Tuple[AttackState, AttackTimeStep]:
        c = self.cfg
        N = c.n_agents
        act = action.reshape(N, -1)
        onehot = (
            jax.nn.one_hot(act[:, 0].astype(jnp.int32), 5)
            if act.shape[-1] == 1 else act.astype(jnp.float32)
        )
        u = particle.decode_move(onehot) * self._gain[:, None]
        entity_pos = jnp.concatenate([st.agent_pos, st.landmark_pos])
        coll = particle.collision_forces(
            entity_pos, self._sizes, self._collide, self._movable
        )[:N]
        vel = particle.integrate(st.agent_vel, u + coll, self._max_speed)
        pos = st.agent_pos + vel * particle.DT

        stepped = AttackState(st.rng, pos, vel, st.landmark_pos, st.t + 1)
        reward = self._reward(stepped)
        done_now = stepped.t >= c.episode_length

        fresh = self._spawn(st.rng)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh, stepped)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, AttackTimeStep(
            obs, share, avail, reward[:, None],
            jnp.broadcast_to(done_now, (N,)), zero, zero,
        )

    def _reward(self, st: AttackState) -> jax.Array:
        c = self.cfg
        A = c.n_adversaries
        # shared terms: own-goal shaping + screen-exit penalty
        goal_d = jnp.linalg.norm(st.agent_pos - st.landmark_pos, axis=-1)  # (N,)
        base = -goal_d + 0.5 * (goal_d < c.landmark_size) - particle.bound_penalty(st.agent_pos)

        adv_pos, good_pos = st.agent_pos[:A], st.agent_pos[A:]
        d = jnp.linalg.norm(good_pos[:, None, :] - adv_pos[None, :, :], axis=-1)  # (G, A)
        contact = d < 2.0 * c.agent_size
        # good: -0.1 per nearby adversary, -0.5 per touching adversary
        good_pen = 0.1 * (d < 0.15).sum(axis=1) + 0.5 * contact.sum(axis=1)
        # adversaries: -0.5 per (good, adversary) contact pair, shared
        adv_pen = jnp.full((A,), 0.5 * contact.sum())
        return base - jnp.concatenate([adv_pen, good_pen])

    def _observe(self, st: AttackState):
        c = self.cfg
        N = c.n_agents
        idx = jnp.arange(N)
        landmark_rel = (
            st.landmark_pos[None, :, :] - st.agent_pos[:, None, :]
        ).reshape(N, -1)
        rel = st.agent_pos[None, :, :] - st.agent_pos[:, None, :]

        def row(i):
            others = jnp.where(idx != i, size=N - 1)[0]
            return jnp.concatenate([
                st.agent_vel[i], st.agent_pos[i], landmark_rel[i],
                rel[i][others].reshape(-1), st.agent_vel[others].reshape(-1),
            ])

        core = jax.vmap(row)(idx)
        obs = jnp.concatenate([core, jnp.eye(N)], axis=1)
        share = jnp.broadcast_to(obs.reshape(-1), (N, self.share_obs_dim))
        avail = jnp.ones((N, self.action_dim))
        return obs, share, avail
