"""Scenario-as-data: train one policy across a *distribution* of scenarios.

The JaxMARL / Podracer-Anakin idiom (arXiv:2311.10090, arXiv:2104.06272):
instead of one compiled program per scenario (or a host-side map cycle with
one jitted collect per map, as ``SMACMultiRunner`` does), scenario
parameterizations become ARRAYS.  A :class:`ScenarioSet` stacks N same-shape
parameterizations along a leading axis; each env slot carries an ``int32``
scenario id in its per-env state and gathers its own parameter row with
``jax.tree.map(lambda leaf: leaf[sid], stacked)`` inside the jitted step.
No ``lax.switch``, no static branching on the scenario, therefore ONE
compiled program for the whole family — the fused ``--iters_per_dispatch``
dispatch, ``--data_shards`` mesh sharding, and emergency-checkpoint resume
all work unchanged because the scenario id and its PRNG key are ordinary
leading-``E``-axis leaves of the rollout carry.

Scenario switches happen on episode boundaries only: the id is resampled
from the set's (optionally weighted) distribution inside ``step`` exactly
when the wrapped env auto-resets, so mid-episode dynamics never change under
an agent's feet.  Observations (and the centralized state) get the scenario
one-hot appended — the ``dmomat`` preference-conditioning precedent — so a
single MAT policy can learn per-scenario behavior.  With N == 1 the wrapper
adds no key splits and no conditioning columns, which is what makes the
single-scenario path bit-exact against the unwrapped env (pinned by
tests/test_multi_scenario.py).

Per-env-family adapters translate "a parameter row" into the wrapped env's
terms through three hooks:

- ``param_env(env, params)``: an ephemeral per-trace view of the env with
  traced parameter arrays grafted over its roster/config attributes
  (``copy.copy`` + setattr — never hashed, safe under jit/vmap), consumed by
  ``step``/``reset``/``_observe``.
- ``commit(env, params, state, done)``: repair the freshly auto-reset state
  so it is consistent with the (possibly just-resampled) scenario — fault
  injection for DCML (mirroring ``envs/dcml/fault.py``), roster hp/shield
  re-seeding for SMACLite, target rescaling for MuJoCoLite.
- ``observe(env, params, state)``: rebuild (obs, share_obs, avail) from the
  committed state so the policy sees the world it will act in.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.dcml.env import DCMLEnv, DCMLState
from mat_dcml_tpu.envs.dcml.fault import DCMLFaultConfig
from mat_dcml_tpu.envs.mamujoco.lite import MJLiteEnv, MJLiteState
from mat_dcml_tpu.envs.smac.maps import UNIT_STATS, MapParams, get_map_params
from mat_dcml_tpu.envs.smac.smaclite import (
    MELEE_RANGE,
    REWARD_DEATH_VALUE,
    REWARD_SCALE_RATE,
    REWARD_WIN,
    SHOOT_RANGE,
    SMACLiteConfig,
    SMACLiteEnv,
    SMACLiteState,
    _roster_arrays,
)

# shield lookup for union-layout decisions (UNIT_STATS row 1 > 0)
UNIT_HAS_SHIELD = {t: s[1] > 0 for t, s in UNIT_STATS.items()}


class ScenarioSet:
    """N same-shape scenario parameterizations stacked as one array pytree.

    ``params``: a pytree whose every leaf has leading axis N (one row per
    scenario).  ``weights``: optional sampling weights (normalized here);
    None = uniform.
    """

    def __init__(self, names: Tuple[str, ...], params,
                 weights: Optional[Sequence[float]] = None):
        self.names = tuple(names)
        self.params = params
        n = len(self.names)
        if n < 1:
            raise ValueError("a ScenarioSet needs at least one scenario")
        for leaf in jax.tree.leaves(params):
            if leaf.shape[0] != n:
                raise ValueError(
                    f"scenario param leaf has leading axis {leaf.shape[0]}, "
                    f"expected {n} (one row per scenario)"
                )
        if weights is not None:
            w = jnp.asarray(weights, jnp.float32)
            if w.shape != (n,):
                raise ValueError(f"weights shape {w.shape} != ({n},)")
            self.weights = w / w.sum()
        else:
            self.weights = None

    @classmethod
    def stack(cls, names: Sequence[str], param_list: Sequence,
              weights: Optional[Sequence[float]] = None) -> "ScenarioSet":
        """Stack per-scenario param pytrees (all the same structure/shapes)
        along a new leading axis."""
        if len(names) != len(param_list):
            raise ValueError(f"{len(names)} names for {len(param_list)} params")
        params = jax.tree.map(
            lambda *leaves: jnp.stack([jnp.asarray(x) for x in leaves]),
            *param_list,
        )
        return cls(tuple(names), params, weights)

    def gather(self, sid: jax.Array):
        """The parameter row for scenario ``sid`` (traced int32 gather — the
        whole point: data, not program structure)."""
        return jax.tree.map(lambda leaf: leaf[sid], self.params)

    def __len__(self) -> int:
        return len(self.names)


class ScenarioState(NamedTuple):
    """Per-env carry: the wrapped env's state plus this slot's scenario id
    and the id-resampling PRNG chain.  Both extra leaves vmap to leading-E
    arrays, so the sharding contract (rollout.py) and ``pack_carry`` apply
    unchanged."""

    base: object                 # wrapped env's state pytree
    sid: jax.Array               # () int32 scenario id
    rng: jax.Array               # typed PRNG key driving episode resampling


class ScenarioEnv:
    """TimeStep-protocol env over a :class:`ScenarioSet`; jit/vmap-safe.

    ``frozen=True`` pins every slot to its current scenario (no resampling
    on episode reset) — the deterministic per-scenario eval-matrix mode; use
    :meth:`reset_pinned` to start slots in a chosen scenario.
    """

    jittable = True

    _FORWARD = ("cfg", "n_agents", "action_dim", "episode_limit",
                "base_workloads", "action_space", "n_actions")

    def __init__(self, env, scenarios: ScenarioSet, family, frozen: bool = False):
        self.env = env
        self.scenarios = scenarios
        self.family = family
        self.frozen = frozen
        self.n_scenarios = len(scenarios)
        # N == 1 keeps the base obs layout: the conditioning block would be a
        # constant column, and dropping it is what keeps the single-scenario
        # wrapper bit-exact vs the plain env
        self.cond_dim = self.n_scenarios if self.n_scenarios > 1 else 0
        for attr in self._FORWARD:
            if hasattr(env, attr):
                setattr(self, attr, getattr(env, attr))
        self.obs_dim = env.obs_dim + self.cond_dim
        self.share_obs_dim = env.share_obs_dim + self.cond_dim

    # ------------------------------------------------------------- sampling

    def _sample(self, key: jax.Array) -> jax.Array:
        if self.scenarios.weights is None:
            return jax.random.randint(key, (), 0, self.n_scenarios, jnp.int32)
        return jax.random.categorical(
            key, jnp.log(self.scenarios.weights)
        ).astype(jnp.int32)

    # ---------------------------------------------------------- conditioning

    def _condition(self, sid, obs, share_obs):
        if self.cond_dim == 0:
            return obs, share_obs
        row = jax.nn.one_hot(sid, self.n_scenarios, dtype=obs.dtype)
        block = jnp.broadcast_to(row, (obs.shape[0], self.n_scenarios))
        return (jnp.concatenate([obs, block], axis=-1),
                jnp.concatenate([share_obs, block], axis=-1))

    def _finish(self, sid, params, base, ts):
        obs, share_obs, avail = self.family.observe(self.env, params, base)
        obs, share_obs = self._condition(sid, obs, share_obs)
        return ts._replace(obs=obs, share_obs=share_obs,
                           available_actions=avail)

    # -------------------------------------------------------------- control

    def reset(self, key: jax.Array, episode_idx=0):
        if self.n_scenarios == 1:
            # no extra splits: the base env consumes the caller's key exactly
            # as it would unwrapped (bit-exactness of the N=1 path)
            sid = jnp.zeros((), jnp.int32)
            rng, k_base = key, key
        else:
            rng, k_sid, k_base = jax.random.split(key, 3)
            sid = self._sample(k_sid)
        return self._reset_in(k_base, sid, rng, episode_idx)

    def reset_pinned(self, key: jax.Array, sid, episode_idx=0):
        """Start in scenario ``sid`` (traced data — one compiled program
        covers the whole eval matrix)."""
        sid = jnp.asarray(sid, jnp.int32)
        return self._reset_in(key, sid, key, episode_idx)

    def _reset_in(self, k_base, sid, rng, episode_idx):
        params = self.scenarios.gather(sid)
        env_p = self.family.param_env(self.env, params)
        base, ts = env_p.reset(k_base, episode_idx)
        base = self.family.commit(self.env, params, base,
                                  jnp.asarray(True))
        ts = self._finish(sid, params, base, ts)
        return ScenarioState(base=base, sid=sid, rng=rng), ts

    def step(self, state: ScenarioState, action):
        params = self.scenarios.gather(state.sid)
        env_p = self.family.param_env(self.env, params)
        base, ts = env_p.step(state.base, action)
        done = ts.done.any()
        if self.n_scenarios == 1 or self.frozen:
            sid_next, rng = state.sid, state.rng
        else:
            rng, k_sid = jax.random.split(state.rng)
            sid_next = jnp.where(done, self._sample(k_sid), state.sid)
        # the step just played ran under state.sid's params (correct: it
        # belonged to the old episode); the auto-reset state this timestep
        # carries belongs to the NEXT episode, so it is committed — and
        # observed — under the resampled scenario
        params_next = self.scenarios.gather(sid_next)
        base = self.family.commit(self.env, params_next, base, done)
        ts = self._finish(sid_next, params_next, base, ts)
        return ScenarioState(base=base, sid=sid_next, rng=rng), ts

    def frozen_view(self) -> "ScenarioEnv":
        """A no-resampling view sharing this env's set (eval matrix)."""
        view = copy.copy(self)
        view.frozen = True
        return view

    def encode_single_agent_state(self, state: ScenarioState, binary: bool = True):
        return self.env.encode_single_agent_state(state.base, binary)


# ======================================================================= DCML


class DCMLScenarioParams(NamedTuple):
    """Array-ized :class:`~mat_dcml_tpu.envs.dcml.fault.DCMLFaultConfig`:
    per-worker channels instead of static index tuples, so N presets stack
    into one ``(N, W)`` pytree."""

    dead: jax.Array        # (W,) bool — permanently unavailable
    pr_floor: jax.Array    # (W,) f32 — failure-probability floor (0 = none)
    load: jax.Array        # (W,) f32 — additive workload shift (0 = none)


class DCMLScenarioFamily:
    """DCML adapter: parameters act by fault injection on the freshly reset
    state (``envs/dcml/fault.py`` semantics) — DCML auto-resets every step,
    so ``commit`` runs unconditionally and ignores ``done``."""

    @staticmethod
    def identity(env: DCMLEnv) -> DCMLScenarioParams:
        W = env.cfg.consts.worker_number_max
        return DCMLScenarioParams(
            dead=jnp.zeros((W,), bool),
            pr_floor=jnp.zeros((W,), jnp.float32),
            load=jnp.zeros((W,), jnp.float32),
        )

    @staticmethod
    def from_fault(fault: DCMLFaultConfig, W: int) -> DCMLScenarioParams:
        bad = [i for i in (*fault.dead_nodes, *fault.straggler_nodes)
               if not 0 <= i < W]
        if bad:
            raise ValueError(f"fault node ids {bad} out of range [0, {W})")
        iw = jnp.arange(W)
        dead = jnp.isin(iw, jnp.asarray(fault.dead_nodes, jnp.int32)) \
            if fault.dead_nodes else jnp.zeros((W,), bool)
        strag = jnp.isin(iw, jnp.asarray(fault.straggler_nodes, jnp.int32)) \
            if fault.straggler_nodes else jnp.zeros((W,), bool)
        return DCMLScenarioParams(
            dead=dead,
            pr_floor=jnp.where(strag, jnp.float32(fault.straggler_pr_floor),
                               0.0).astype(jnp.float32),
            load=jnp.where(strag, jnp.float32(fault.straggler_load),
                           0.0).astype(jnp.float32),
        )

    @staticmethod
    def param_env(env: DCMLEnv, params: DCMLScenarioParams) -> DCMLEnv:
        return env          # faults act on state, not env attributes

    @staticmethod
    def commit(env: DCMLEnv, params: DCMLScenarioParams,
               state: DCMLState, done) -> DCMLState:
        del done
        unavailable = state.unavailable | params.dead
        # identity rows are exact no-ops: max(pr, 0) == pr, trace already in
        # [0, 1] so clip(trace + 0) == trace
        worker_prs = jnp.maximum(state.worker_prs, params.pr_floor)
        trace = jnp.clip(state.trace + params.load[:, None], 0.0, 1.0)
        # keep the rank denominator (W - disable_rate) consistent with the
        # merged mask — but ONLY when this scenario kills nodes: the env
        # draws disable_rate in [1, 80] independent of W, so recomputing it
        # from the mask on an identity row would CHANGE state at W < 81
        disable_rate = jnp.where(
            params.dead.any(),
            unavailable.sum().astype(jnp.int32),
            state.disable_rate,
        )
        return state._replace(unavailable=unavailable, worker_prs=worker_prs,
                              trace=trace, disable_rate=disable_rate)

    @staticmethod
    def observe(env: DCMLEnv, params: DCMLScenarioParams, state: DCMLState):
        return env._observe(state)


# =================================================================== SMACLite


class SMACScenarioParams(NamedTuple):
    """One map's roster arrays in the shared (union) obs layout, plus its
    reward normalizer and episode limit — everything ``SMACLiteEnv`` reads
    per-map inside its traced methods."""

    a_hp0: jax.Array       # (A,)
    a_sh0: jax.Array
    a_dmg: jax.Array
    a_cd0: jax.Array
    a_range: jax.Array
    a_type: jax.Array      # (A,) int32 into the union one-hot layout
    e_hp0: jax.Array       # (Ne,)
    e_sh0: jax.Array
    e_dmg: jax.Array
    e_cd0: jax.Array
    e_range: jax.Array
    e_type: jax.Array
    reward_norm: jax.Array  # () f32
    limit: jax.Array        # () int32


_SMAC_ROSTER_ATTRS = ("a_hp0", "a_sh0", "a_dmg", "a_cd0", "a_range", "a_type",
                      "e_hp0", "e_sh0", "e_dmg", "e_cd0", "e_range", "e_type")


class SMACScenarioFamily:
    """SMACLite adapter: the roster IS the scenario.  ``param_env`` grafts
    the row's traced roster arrays over a shallow env copy (the copy is
    ephemeral per trace and never hashed, so traced attributes are safe);
    ``commit`` re-seeds hp/shield on episode boundaries because the env's
    internal auto-reset spawned with the OLD scenario's roster.  Spawn
    positions, cooldowns, and timers are roster-independent (asserted same
    ``map_size`` at set construction), so hp/shield are the whole repair."""

    @staticmethod
    def identity(env: SMACLiteEnv) -> SMACScenarioParams:
        return SMACScenarioParams(
            **{a: getattr(env, a) for a in _SMAC_ROSTER_ATTRS},
            reward_norm=jnp.float32(env._reward_norm),
            limit=jnp.int32(env.episode_limit),
        )

    @staticmethod
    def param_env(env: SMACLiteEnv, params: SMACScenarioParams) -> SMACLiteEnv:
        env_p = copy.copy(env)
        for attr in _SMAC_ROSTER_ATTRS:
            setattr(env_p, attr, getattr(params, attr))
        env_p._reward_norm = params.reward_norm
        env_p.episode_limit = params.limit
        return env_p

    @staticmethod
    def commit(env: SMACLiteEnv, params: SMACScenarioParams,
               state: SMACLiteState, done) -> SMACLiteState:
        reseed = lambda fresh, cur: jnp.where(done, fresh, cur)
        return state._replace(
            ally_hp=reseed(params.a_hp0, state.ally_hp),
            ally_shield=reseed(params.a_sh0, state.ally_shield),
            enemy_hp=reseed(params.e_hp0, state.enemy_hp),
            enemy_shield=reseed(params.e_sh0, state.enemy_shield),
        )

    @staticmethod
    def observe(env: SMACLiteEnv, params: SMACScenarioParams,
                state: SMACLiteState):
        return SMACScenarioFamily.param_env(env, params)._observe(state)


def smac_map_scenario_params(mp: MapParams,
                             layout_types: Tuple[str, ...]) -> SMACScenarioParams:
    """One map's roster in the union one-hot ``layout_types`` layout."""
    a = _roster_arrays(mp.agents, layout_types)
    e = _roster_arrays(mp.enemies, layout_types)
    max_reward = (float(e[0].sum() + e[1].sum())
                  + mp.n_enemies * REWARD_DEATH_VALUE + REWARD_WIN)
    return SMACScenarioParams(
        a_hp0=jnp.asarray(a[0]), a_sh0=jnp.asarray(a[1]),
        a_dmg=jnp.asarray(a[2]), a_cd0=jnp.asarray(a[3]),
        a_range=jnp.where(jnp.asarray(a[4]), MELEE_RANGE, SHOOT_RANGE),
        a_type=jnp.asarray(a[5]),
        e_hp0=jnp.asarray(e[0]), e_sh0=jnp.asarray(e[1]),
        e_dmg=jnp.asarray(e[2]), e_cd0=jnp.asarray(e[3]),
        e_range=jnp.where(jnp.asarray(e[4]), MELEE_RANGE, SHOOT_RANGE),
        e_type=jnp.asarray(e[5]),
        reward_norm=jnp.float32(max_reward / REWARD_SCALE_RATE),
        limit=jnp.int32(mp.limit),
    )


def smac_stat_variant(env: SMACLiteEnv, name_suffix: str = "",
                      enemy_hp_scale: float = 1.0,
                      enemy_dmg_scale: float = 1.0,
                      ally_dmg_scale: float = 1.0) -> SMACScenarioParams:
    """A same-roster stat variant (harder/easier fight on the same map) —
    the SMAC analogue of a DCML fault preset.  The reward normalizer tracks
    the scaled enemy pool so max episode return stays ``reward_scale_rate``.
    """
    del name_suffix
    base = SMACScenarioFamily.identity(env)
    e_hp0 = base.e_hp0 * enemy_hp_scale
    e_sh0 = base.e_sh0 * enemy_hp_scale
    max_reward = (float(e_hp0.sum() + e_sh0.sum())
                  + env.n_enemies * REWARD_DEATH_VALUE + REWARD_WIN)
    return base._replace(
        e_hp0=e_hp0, e_sh0=e_sh0,
        e_dmg=base.e_dmg * enemy_dmg_scale,
        a_dmg=base.a_dmg * ally_dmg_scale,
        reward_norm=jnp.float32(max_reward / REWARD_SCALE_RATE),
    )


def build_smac_scenario_set(map_names: Sequence[str],
                            weights: Optional[Sequence[float]] = None):
    """(env, ScenarioSet) for a same-shape SMAC map roster.

    All maps must agree on (n_agents, n_enemies) — the action space is
    ``6 + n_enemies`` and the obs layout is per-agent/per-enemy — and on
    ``map_size`` (spawn geometry is not a scenario parameter).  Unit one-hot
    columns use the UNION of the rosters' types (``layout_types`` on the
    env config) so every map observes through the same feature layout.
    """
    if len(map_names) < 1:
        raise ValueError("need at least one map")
    mps = [get_map_params(m) for m in map_names]
    shapes = {(mp.n_agents, mp.n_enemies) for mp in mps}
    if len(shapes) > 1:
        raise ValueError(
            f"maps {list(map_names)} disagree on (n_agents, n_enemies): "
            f"{sorted(shapes)} — heterogeneous rosters need the host-cycled "
            f"SMACMultiRunner fallback"
        )
    sizes = {mp.map_size for mp in mps}
    if len(sizes) > 1:
        raise ValueError(f"maps disagree on map_size: {sorted(sizes)}")
    union = tuple(sorted({t for mp in mps for t in (*mp.agents, *mp.enemies)}))
    shield = any(
        UNIT_HAS_SHIELD[t] for mp in mps for t in (*mp.agents, *mp.enemies)
    )
    env = SMACLiteEnv(SMACLiteConfig(
        map_name=mps[0].name, layout_types=union, layout_shield=shield,
    ))
    params = [smac_map_scenario_params(mp, union) for mp in mps]
    return env, ScenarioSet.stack(tuple(map_names), params, weights)


# ================================================================= MuJoCoLite


class MJLiteScenarioParams(NamedTuple):
    """Dynamics/target variant of the jointed-chain env: actuator gain,
    damping, stiffness (the ω' update's coefficients) and a target-posture
    scale applied on episode reset."""

    gain: jax.Array          # () f32
    damping: jax.Array
    stiffness: jax.Array
    target_scale: jax.Array


class MJLiteScenarioFamily:
    """MuJoCoLite adapter: dynamics coefficients ride a config replace on a
    shallow env copy (frozen dataclass holding traced scalars — never
    hashed); ``commit`` rescales the freshly drawn target on done so each
    scenario reaches for a different posture envelope."""

    @staticmethod
    def identity(env: MJLiteEnv) -> MJLiteScenarioParams:
        c = env.cfg
        return MJLiteScenarioParams(
            gain=jnp.float32(c.gain), damping=jnp.float32(c.damping),
            stiffness=jnp.float32(c.stiffness),
            target_scale=jnp.float32(1.0),
        )

    @staticmethod
    def variant(env: MJLiteEnv, gain: Optional[float] = None,
                damping: Optional[float] = None,
                stiffness: Optional[float] = None,
                target_scale: float = 1.0) -> MJLiteScenarioParams:
        c = env.cfg
        return MJLiteScenarioParams(
            gain=jnp.float32(c.gain if gain is None else gain),
            damping=jnp.float32(c.damping if damping is None else damping),
            stiffness=jnp.float32(c.stiffness if stiffness is None else stiffness),
            target_scale=jnp.float32(target_scale),
        )

    @staticmethod
    def param_env(env: MJLiteEnv, params: MJLiteScenarioParams) -> MJLiteEnv:
        env_p = copy.copy(env)
        env_p.cfg = dataclasses.replace(
            env.cfg, gain=params.gain, damping=params.damping,
            stiffness=params.stiffness,
        )
        return env_p

    @staticmethod
    def commit(env: MJLiteEnv, params: MJLiteScenarioParams,
               state: MJLiteState, done) -> MJLiteState:
        # identity rows are exact: target * 1.0 == target
        return state._replace(
            target=jnp.where(done, state.target * params.target_scale,
                             state.target)
        )

    @staticmethod
    def observe(env: MJLiteEnv, params: MJLiteScenarioParams,
                state: MJLiteState):
        return env._observe(state)
