"""Action/observation space descriptors.

The reference dispatches on gym space *class names* plus a duck-typed custom
``Action_Space`` (``DCML_ENVs/DCML_utils/DCML_ActionSpace.py``) throughout
(``act.py:18-68``, ``mat/utils/util.py:41-62``, ``transformer_policy.py:28-39``).
Here spaces are frozen dataclasses carrying the same semantic fields; dispatch
is on type, not string matching.

``DCMLActionSpace`` reproduces the reference's mixed layout
(``DCML_ActionSpace.py``): ``n_sub = high - low`` categorical sub-actions with
``n`` choices each (the 100 worker-selection bits, 2 choices), plus
``-semi_index`` Gaussian tail dims (the coding-ratio agent).  ``extra`` marks
the single-continuous-dim variant used for the DCML master agent in separated
(per-agent) policies.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class Discrete:
    """Categorical space with ``n`` choices (gym.spaces.Discrete)."""

    n: int

    @property
    def sample_dim(self) -> int:
        return 1


@dataclasses.dataclass(frozen=True)
class Box:
    """Continuous space; ``dim`` flat dims with uniform bounds (gym.spaces.Box)."""

    dim: int
    low: float = -1.0
    high: float = 1.0

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dim,)

    @property
    def sample_dim(self) -> int:
        return self.dim


@dataclasses.dataclass(frozen=True)
class MultiDiscrete:
    """Tuple of categorical sub-spaces (gym.spaces.MultiDiscrete; the
    reference computes per-head sizes as ``high - low + 1``, ``act.py:56-58``)."""

    nvec: Tuple[int, ...]

    @property
    def sample_dim(self) -> int:
        return len(self.nvec)


@dataclasses.dataclass(frozen=True)
class MultiBinary:
    """``n`` independent Bernoulli bits (gym.spaces.MultiBinary)."""

    n: int

    @property
    def sample_dim(self) -> int:
        return self.n


@dataclasses.dataclass(frozen=True)
class DCMLActionSpace:
    """The reference's duck-typed ``Action_Space`` (``DCML_ActionSpace.py``).

    Modes, matching ``act.py:21-48`` dispatch:
      - ``mixed=True``: ``n_sub`` categorical heads of ``n`` choices sliced
        from one wide feature vector + ``cont_dim`` Gaussian tail — the
        centralized-PPO joint action over all DCML agents.
      - ``extra=True``: 1-dim Gaussian (the master/ratio agent standalone).
      - neither: plain categorical with ``n`` choices (a worker agent).
    """

    n: int = 2
    n_sub: int = 100              # high - low in the reference
    semi_index: int = -1          # negated count of Gaussian tail dims
    mixed: bool = False
    extra: bool = False
    continuous: bool = False
    multi_discrete: bool = False

    @property
    def cont_dim(self) -> int:
        return -self.semi_index

    @property
    def mixed_feature_dim(self) -> int:
        """Width of the actor feature vector the mixed ACT head slices
        (``mlp.py:51-56``): all sub-action logits + tail means."""
        return self.n_sub * self.n + self.cont_dim

    @property
    def sample_dim(self) -> int:
        if self.mixed:
            return self.n_sub + self.cont_dim
        if self.extra:
            return self.cont_dim
        return 1


@dataclasses.dataclass(frozen=True)
class MixedRole:
    """Per-agent space for heterogeneous-agent algorithms on DCML.

    The reference's separated-policy DCML modes give each worker agent
    ``Action_Space(2, continuous=False)`` and the master agent
    ``Action_Space(1, extra=True, continuous=True)``
    (``DCML_..._SingleProcess.py:51-52``) — structurally different heads, which
    would force heterogeneous parameter pytrees.  ``MixedRole`` instead builds
    BOTH heads in one module and selects per row by a role flag, so stacked /
    shared-parameter trainers (HAPPO/MAPPO/IPPO) stay pytree-homogeneous — the
    TPU-native answer to the reference's per-agent ``nn.Module`` lists.

    The role flag rides as an extra trailing column of ``available_actions``
    (width ``n + 1``): ``[avail_0..avail_{n-1}, role]`` with role 1.0 for the
    continuous (master) agent.  Sampled actions are always ``(B, 1)`` float:
    the categorical index for workers, the Gaussian draw for the master.
    """

    n: int = 2                    # categorical choices for the discrete role
    cont_dim: int = 1             # Gaussian dims for the continuous role

    @property
    def sample_dim(self) -> int:
        return max(1, self.cont_dim)


def space_sample_dim(space) -> int:
    """Width of a stored action sample for ``space``."""
    return space.sample_dim
