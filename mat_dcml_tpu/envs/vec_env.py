"""Host-process vectorized env layer — the escape hatch for non-JAX envs.

Pure-JAX envs vectorize with ``vmap`` inside the rollout scan; external
simulators (StarCraft II, Google Research Football, MuJoCo) cannot be traced
and need the reference's architecture: worker processes stepping real envs,
synchronized lock-step over pipes, auto-reset inside the worker
(``env_wrappers.py:27-137`` ``ShareVecEnv`` ABC, ``:300-340`` shareworker,
``:343-403`` ``ShareSubprocVecEnv``, ``:713`` ``ShareDummyVecEnv``).

Differences from the reference, deliberate:

- **k envs per worker process** (``envs_per_worker``) instead of one process
  per env — a TPU host feeding thousands of env slots cannot afford thousands
  of processes; the reference's 1:1 mapping is the degenerate case.
- **spawn start method** + cloudpickled factories (the reference's
  ``CloudpickleWrapper``, ``env_wrappers.py:10-24``): forking a process that
  has initialized XLA deadlocks in the child, so children must start clean.
- Stacked numpy outputs ready for one ``jax.device_put`` per step — the
  host↔device boundary is one transfer per phase, not per env.

Host env contract (the reference's gym-ish shared-obs API,
``DCML_..._SingleProcess.py:57,157``):

    reset() -> (obs (A, d_o), share_obs (A, d_s), available_actions (A, d_a))
    step(a) -> (obs, share_obs, rewards (A, 1), dones (A,), infos,
                available_actions)

Envs whose ``step`` already returns the next episode's obs after a terminal
step (the DCML/pure-JAX convention) set ``self_resetting = True`` and the
worker skips its reset-on-done.
"""

from __future__ import annotations

import multiprocessing as mp
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


def _step_one(env, action):
    """Step + auto-reset-inside-worker (``env_wrappers.py:305-313``): on a
    terminal step the NEW episode's obs/avail are returned with the OLD
    step's reward, matching what the collectors store."""
    obs, share, rew, done, info, avail = env.step(action)
    if not getattr(env, "self_resetting", False) and np.all(done):
        obs, share, avail = env.reset()
    return obs, share, rew, done, info, avail


class CloudpickleWrapper:
    """Carry closures across a spawn boundary (``env_wrappers.py:10-24``).

    Unpickling is LAZY (``.load()``): multiprocessing deserializes process
    args during child bootstrap, before any user code runs — if the payload
    contains device arrays, eager unpickling would initialize the child's JAX
    backend before the platform override below, wedging the child on an
    unavailable accelerator tunnel."""

    def __init__(self, x):
        self.x = x

    def __getstate__(self):
        import cloudpickle

        return cloudpickle.dumps(self.x)

    def __setstate__(self, blob):
        self._blob = blob
        self.x = None

    def load(self):
        if self.x is None:
            import pickle

            self.x = pickle.loads(self._blob)
        return self.x


def _worker_loop(remote, parent_remote, wrapped_fns: CloudpickleWrapper):
    parent_remote.close()
    # spawned children re-run sitecustomize; make an explicit JAX_PLATFORMS
    # win again before any env factory touches jax (utils/platform.py)
    from mat_dcml_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    envs = [fn() for fn in wrapped_fns.load()]
    try:
        while True:
            cmd, data = remote.recv()
            if cmd == "step":
                remote.send([_step_one(env, a) for env, a in zip(envs, data)])
            elif cmd == "reset":
                # data: per-env reset arguments, or None — the reference's
                # "Choose" family (reset-with-argument for preset/turn-based
                # envs, env_wrappers.py:437-667) folded into one command
                if data is None:
                    remote.send([env.reset() for env in envs])
                else:
                    remote.send([
                        env.reset() if arg is None else env.reset(arg)
                        for env, arg in zip(envs, data)
                    ])
            elif cmd == "spaces":
                e = envs[0]
                # action_space rides along so continuous host envs (hands,
                # real MuJoCo) build continuous policies through the bridge
                remote.send((e.n_agents, e.obs_dim, e.share_obs_dim,
                             e.action_dim, getattr(e, "action_space", None)))
            elif cmd == "close":
                for env in envs:
                    if hasattr(env, "close"):
                        env.close()
                remote.close()
                break
            else:
                raise NotImplementedError(cmd)
    except (KeyboardInterrupt, EOFError):
        pass


def _stack_reset(results) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    obs, share, avail = zip(*results)
    return np.stack(obs), np.stack(share), np.stack(avail)


def _stack_step(results):
    obs, share, rew, done, infos, avail = zip(*results)
    return (
        np.stack(obs), np.stack(share), np.stack(rew),
        np.stack(done), list(infos), np.stack(avail),
    )


class ShareVecEnv:
    """Common interface: ``reset(reset_args=None) -> (E, A, ·) numpy``,
    ``step(actions)``.  ``reset_args`` is an optional per-env argument list —
    the reference's "Choose" variants (``env_wrappers.py:437-667``) as a
    parameter instead of four more classes."""

    n_envs: int
    n_agents: int
    obs_dim: int
    share_obs_dim: int
    action_dim: int
    action_space = None    # Box/MultiDiscrete when the host env declares one

    def reset(self, reset_args=None):
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        raise NotImplementedError

    def close(self):
        pass


class ShareDummyVecEnv(ShareVecEnv):
    """In-process variant (``env_wrappers.py:713-763``) — the debugging and
    single-thread configuration, and the reference for bridge tests."""

    def __init__(self, env_fns: Sequence[Callable]):
        self.envs = [fn() for fn in env_fns]
        self.n_envs = len(self.envs)
        e = self.envs[0]
        self.n_agents, self.obs_dim = e.n_agents, e.obs_dim
        self.share_obs_dim, self.action_dim = e.share_obs_dim, e.action_dim
        self.action_space = getattr(e, "action_space", None)

    def reset(self, reset_args=None):
        if reset_args is None:
            return _stack_reset([env.reset() for env in self.envs])
        return _stack_reset([
            env.reset() if arg is None else env.reset(arg)
            for env, arg in zip(self.envs, reset_args)
        ])

    def step(self, actions: np.ndarray):
        return _stack_step([_step_one(env, a) for env, a in zip(self.envs, actions)])

    def close(self):
        for env in self.envs:
            if hasattr(env, "close"):
                env.close()


class ShareSubprocVecEnv(ShareVecEnv):
    """Worker-process variant (``env_wrappers.py:343-403``), k envs/worker."""

    def __init__(self, env_fns: Sequence[Callable], envs_per_worker: int = 1):
        self.n_envs = len(env_fns)
        ctx = mp.get_context("spawn")
        chunks = [
            env_fns[i : i + envs_per_worker]
            for i in range(0, len(env_fns), envs_per_worker)
        ]
        self._chunk_sizes = [len(c) for c in chunks]
        # set before any worker start so __del__ -> close() is safe even if
        # construction fails mid-way (e.g. the env factory raises in-worker)
        self._closed = False
        self.remotes, self.processes = [], []
        for chunk in chunks:
            remote, child = ctx.Pipe()
            p = ctx.Process(
                target=_worker_loop,
                args=(child, remote, CloudpickleWrapper(chunk)),
                daemon=True,
            )
            p.start()
            child.close()
            self.remotes.append(remote)
            self.processes.append(p)
        try:
            self.remotes[0].send(("spaces", None))
            (self.n_agents, self.obs_dim, self.share_obs_dim,
             self.action_dim, self.action_space) = self.remotes[0].recv()
        except (EOFError, ConnectionResetError, BrokenPipeError, OSError) as e:
            self.close()
            raise RuntimeError(
                "vec-env worker died during env construction (its stderr "
                "shows the original error — commonly a missing simulator "
                "package)"
            ) from e

    def reset(self, reset_args=None):
        start = 0
        for remote, k in zip(self.remotes, self._chunk_sizes):
            chunk = None if reset_args is None else list(reset_args[start : start + k])
            remote.send(("reset", chunk))
            start += k
        results: List = []
        for remote in self.remotes:
            results.extend(remote.recv())
        return _stack_reset(results)

    def step(self, actions: np.ndarray):
        """Synchronous lock-step: scatter action slices, gather transitions
        (the reference's step_async/step_wait pair collapsed)."""
        start = 0
        for remote, k in zip(self.remotes, self._chunk_sizes):
            remote.send(("step", actions[start : start + k]))
            start += k
        results = []
        for remote in self.remotes:
            results.extend(remote.recv())
        return _stack_step(results)

    def close(self):
        if self._closed:
            return
        for remote in self.remotes:
            try:
                remote.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        for p in self.processes:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._closed = True

    def __del__(self):
        self.close()


class JaxEnvHostAdapter:
    """Run a TimeStep-protocol JAX env behind the host-env contract.

    Exists so the bridge can be validated against the vmapped path with
    bit-identical PRNG discipline (the env carries its rng in its state), and
    so pure-JAX envs can be mixed into host-side fleets if ever useful.
    ``self_resetting = True``: these envs return the next episode's obs from
    ``step`` themselves (DCML resets every step, ``DCML_..._SingleProcess.py:139``).
    """

    self_resetting = True

    def __init__(self, env, key, episode_idx: int = 0):
        import jax

        self._env = env
        self._key = key
        self._episode_idx = episode_idx
        self._jit_reset = jax.jit(env.reset)
        self._jit_step = jax.jit(env.step)
        self._state = None
        self.n_agents, self.obs_dim = env.n_agents, env.obs_dim
        self.share_obs_dim, self.action_dim = env.share_obs_dim, env.action_dim

    def reset(self):
        self._state, ts = self._jit_reset(self._key, self._episode_idx)
        return np.asarray(ts.obs), np.asarray(ts.share_obs), np.asarray(ts.available_actions)

    def step(self, action):
        self._state, ts = self._jit_step(self._state, action)
        info = {"delay": float(getattr(ts, "delay", 0.0)),
                "payment": float(getattr(ts, "payment", 0.0))}
        return (
            np.asarray(ts.obs), np.asarray(ts.share_obs), np.asarray(ts.reward),
            np.asarray(ts.done), info, np.asarray(ts.available_actions),
        )
