"""Tiny pure-JAX multi-agent env for tests and examples.

``MatchingEnv``: each agent sees a one-hot target in its obs; the team reward
(broadcast to every agent, like the DCML env's shared reward) is the fraction
of agents that picked their matching discrete action.  Episodes end every
``horizon`` steps.  Implements the same TimeStep protocol as the DCML env
(``envs/dcml/env.py``) so every collector/trainer runs on it unchanged — the
role the reference's MPE simple_spread plays as "smallest second env"
(SURVEY.md §7.8), but closed-form learnable so trainer tests can assert
reward improvement in seconds.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class ToyState(NamedTuple):
    rng: jax.Array
    targets: jax.Array       # (A,) int32
    t: jax.Array             # int32 step counter


class ToyTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class MatchingEnvConfig:
    n_agents: int = 3
    n_actions: int = 4
    horizon: int = 10


class MatchingEnv:
    def __init__(self, cfg: MatchingEnvConfig = MatchingEnvConfig()):
        self.cfg = cfg
        self.n_agents = cfg.n_agents
        self.obs_dim = cfg.n_actions
        self.share_obs_dim = cfg.n_actions * cfg.n_agents
        self.action_dim = cfg.n_actions

    def _observe(self, state: ToyState) -> Tuple[jax.Array, jax.Array, jax.Array]:
        c = self.cfg
        obs = jax.nn.one_hot(state.targets, c.n_actions)
        share = jnp.broadcast_to(obs.reshape(-1), (c.n_agents, self.share_obs_dim))
        avail = jnp.ones((c.n_agents, c.n_actions))
        return obs, share, avail

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[ToyState, ToyTimeStep]:
        del episode_idx
        key, k = jax.random.split(key)
        targets = jax.random.randint(k, (self.cfg.n_agents,), 0, self.cfg.n_actions)
        state = ToyState(key, targets, jnp.zeros((), jnp.int32))
        obs, share, avail = self._observe(state)
        zero = jnp.zeros(())
        ts = ToyTimeStep(
            obs, share, avail,
            jnp.zeros((self.cfg.n_agents, 1)),
            jnp.zeros((self.cfg.n_agents,), bool),
            zero, zero,
        )
        return state, ts

    def step(self, state: ToyState, action: jax.Array) -> Tuple[ToyState, ToyTimeStep]:
        c = self.cfg
        act = action[..., 0].astype(jnp.int32)
        hit = (act == state.targets).astype(jnp.float32)
        reward = jnp.broadcast_to(hit.mean(), (c.n_agents, 1))
        t = state.t + 1
        done_now = t >= c.horizon
        key, k_targets = jax.random.split(state.rng)
        new_targets = jax.random.randint(k_targets, (c.n_agents,), 0, c.n_actions)
        state = ToyState(
            rng=key,
            targets=new_targets,
            t=jnp.where(done_now, 0, t),
        )
        obs, share, avail = self._observe(state)
        done = jnp.broadcast_to(done_now, (c.n_agents,))
        zero = jnp.zeros(())
        return state, ToyTimeStep(obs, share, avail, reward, done, zero, zero)
