"""Environment suite: pure-JAX vectorized envs + host escape hatch."""
