"""Per-episode agent-order permutation wrapper.

The reference ships two copy-variant envs whose only addition is shuffling
the agent order each episode so policies cannot overfit to slot identity —
``starcraft2/Random_StarCraft2_Env.py:387-390,404,451-453,484`` (the diff vs
the base SMAC env is exactly ``permutate_idx``) and
``ma_mujoco/multiagent_mujoco/random_mujoco_multi.py:128-131,138,167-172``.
Instead of forking every env, the TPU build factors the idea into one
generic wrapper over the TimeStep protocol: outward row ``i`` is inner agent
``perm[i]`` for obs/share_obs/availability/reward/done, and incoming actions
are gathered back with the inverse permutation before the inner ``step``
(the reference's ``agent_recovery``).

A fresh permutation is drawn whenever the inner env auto-resets (the
reference redraws in ``reset``; with reset-inside-step semantics the
returned obs already belong to the new episode, so they are permuted with
the NEW order while that step's reward/done keep the old one).

Reference defect not replicated: ``random_mujoco_multi.py:138`` applies
``agent_recovery`` to the *flattened* joint action vector, which scrambles
torques whenever agents have more than one action dim; this wrapper permutes
whole per-agent action rows.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class PermutedState(NamedTuple):
    inner: Any
    perm: jax.Array   # (N,) int32 — outward row i shows inner agent perm[i]
    inv: jax.Array    # argsort(perm): inner agent j reads outward row inv[j]
    rng: jax.Array


class AgentPermutationWrapper:
    """Wrap any TimeStep-protocol env with per-episode agent shuffling."""

    def __init__(self, env):
        self.env = env

    def __getattr__(self, name):
        # forward static descriptors (n_agents, obs_dim, action_dim, cfg, ...)
        return getattr(self.env, name)

    def _permute_ts(self, ts, perm):
        return ts._replace(
            obs=ts.obs[perm],
            share_obs=ts.share_obs[perm],
            available_actions=ts.available_actions[perm],
            reward=ts.reward[perm],
            done=ts.done[perm],
        )

    def _draw(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        perm = jax.random.permutation(key, self.env.n_agents)
        return perm, jnp.argsort(perm)

    def reset(self, key: jax.Array, episode_idx=0):
        k_in, k_perm, k_next = jax.random.split(key, 3)
        inner, ts = self.env.reset(k_in, episode_idx)
        perm, inv = self._draw(k_perm)
        return PermutedState(inner, perm, inv, k_next), self._permute_ts(ts, perm)

    def step(self, st: PermutedState, action: jax.Array):
        N = self.env.n_agents
        inner_action = (
            action.reshape(N, -1)[st.inv].reshape(action.shape)
        )
        inner, ts = self.env.step(st.inner, inner_action)

        # reward/done describe the episode just played -> old order
        out = ts._replace(reward=ts.reward[st.perm], done=ts.done[st.perm])
        # obs/avail may already belong to the auto-reset next episode -> draw
        # the next episode's order on done (Random_StarCraft2_Env.py:404)
        k_perm, rng = jax.random.split(st.rng)  # advance unconditionally —
        # selecting between typed PRNG keys needs extended-dtype select
        fresh_perm, fresh_inv = self._draw(k_perm)
        done_now = ts.done.any()
        perm = jnp.where(done_now, fresh_perm, st.perm)
        inv = jnp.where(done_now, fresh_inv, st.inv)
        out = out._replace(
            obs=ts.obs[perm],
            share_obs=ts.share_obs[perm],
            available_actions=ts.available_actions[perm],
        )
        return PermutedState(inner, perm, inv, rng), out
