"""SMAC map registry (``starcraft2/smac_maps.py`` ``map_param_registry``).

Each entry gives team compositions and the episode limit; ``unit_type_bits``
and per-map unit rosters drive obs/state layout exactly as the reference's
``get_map_params`` consumers expect.  Two backends read this table: the
pure-JAX combat stand-in (:mod:`~mat_dcml_tpu.envs.smac.smaclite`) and the
gated real-SC2 host adapter (:mod:`~mat_dcml_tpu.envs.smac.host`).

Unit stat rows are simplified SC2 values (health / shield / damage / cooldown
ticks / melee?) for the stand-in simulator; the real game supplies its own.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

# unit type id -> (health, shield, damage, cooldown_steps, melee)
UNIT_STATS: Dict[str, Tuple[float, float, float, int, bool]] = {
    "marine": (45.0, 0.0, 6.0, 1, False),
    "marauder": (125.0, 0.0, 10.0, 2, False),
    "medivac": (150.0, 0.0, 0.0, 1, False),
    "stalker": (80.0, 80.0, 13.0, 2, False),
    "zealot": (100.0, 50.0, 16.0, 2, True),
    "colossus": (200.0, 150.0, 24.0, 3, False),
    "zergling": (35.0, 0.0, 5.0, 1, True),
    "baneling": (30.0, 0.0, 16.0, 1, True),
    "hydralisk": (80.0, 0.0, 12.0, 1, False),
}


@dataclasses.dataclass(frozen=True)
class MapParams:
    name: str
    agents: Tuple[str, ...]          # ally unit types, one per agent
    enemies: Tuple[str, ...]
    limit: int                       # episode step limit
    map_size: Tuple[float, float] = (32.0, 32.0)

    @property
    def n_agents(self) -> int:
        return len(self.agents)

    @property
    def n_enemies(self) -> int:
        return len(self.enemies)

    @property
    def unit_types(self) -> Tuple[str, ...]:
        """Distinct types on the map, sorted — defines the one-hot layout."""
        return tuple(sorted(set(self.agents) | set(self.enemies)))

    @property
    def unit_type_bits(self) -> int:
        """0 when homogeneous, else one-hot width (``smac_maps.py`` field)."""
        n = len(self.unit_types)
        return 0 if n == 1 else n


def _m(n: int) -> Tuple[str, ...]:
    return ("marine",) * n


map_param_registry: Dict[str, MapParams] = {
    "2m": MapParams("2m", _m(2), _m(2), limit=40),
    "3m": MapParams("3m", _m(3), _m(3), limit=60),
    "8m": MapParams("8m", _m(8), _m(8), limit=120),
    "25m": MapParams("25m", _m(25), _m(25), limit=150),
    "5m_vs_6m": MapParams("5m_vs_6m", _m(5), _m(6), limit=70),
    "8m_vs_9m": MapParams("8m_vs_9m", _m(8), _m(9), limit=120),
    "10m_vs_11m": MapParams("10m_vs_11m", _m(10), _m(11), limit=150),
    "27m_vs_30m": MapParams("27m_vs_30m", _m(27), _m(30), limit=180),
    "2s3z": MapParams(
        "2s3z", ("stalker",) * 2 + ("zealot",) * 3,
        ("stalker",) * 2 + ("zealot",) * 3, limit=120,
    ),
    "3s5z": MapParams(
        "3s5z", ("stalker",) * 3 + ("zealot",) * 5,
        ("stalker",) * 3 + ("zealot",) * 5, limit=150,
    ),
    "MMM": MapParams(
        "MMM", ("medivac",) + ("marauder",) * 2 + ("marine",) * 7,
        ("medivac",) + ("marauder",) * 2 + ("marine",) * 7, limit=150,
    ),
}


def get_map_params(name: str) -> MapParams:
    try:
        return map_param_registry[name]
    except KeyError:
        raise KeyError(
            f"unknown SMAC map {name!r}; known: {sorted(map_param_registry)}"
        ) from None
