"""SMAC environment family: map registry, pure-JAX combat stand-in,
multi-map feature translation, and the gated real-SC2 host adapter."""

from mat_dcml_tpu.envs.smac.maps import MapParams, get_map_params, map_param_registry
from mat_dcml_tpu.envs.smac.smaclite import SMACLiteConfig, SMACLiteEnv, SMACTimeStep
from mat_dcml_tpu.envs.smac.translation import (
    TARGET_ACTION_DIM,
    TARGET_NUM_AGENT,
    TASK_EMBEDDING_DIM,
    TranslatedSMACEnv,
    gen_task_embedding,
)

__all__ = [
    "MapParams",
    "get_map_params",
    "map_param_registry",
    "SMACLiteConfig",
    "SMACLiteEnv",
    "SMACTimeStep",
    "TranslatedSMACEnv",
    "gen_task_embedding",
    "TARGET_ACTION_DIM",
    "TARGET_NUM_AGENT",
    "TASK_EMBEDDING_DIM",
]
