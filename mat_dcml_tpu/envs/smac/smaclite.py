"""Pure-JAX SMAC combat stand-in ("SMACLite").

The reference vendors the full SC2-backed SMAC suite
(``starcraft2/StarCraft2_Env.py:1-2091``) — a process+RPC boundary around a
game binary that cannot be traced or vmapped.  The TPU-native counterpart is
this closed-form combat microsim with the SAME structural API (obs feature
layout, centralized state, availability mask, shaped reward, win/lose
bookkeeping), so every SMAC-facing component — runners, multi-map feature
translation, MAT/MAPPO policies — exercises the real interface while staying
jit/vmap-compatible.  The real game remains reachable through the gated host
adapter (:mod:`~mat_dcml_tpu.envs.smac.host`) over the process bridge
(:mod:`~mat_dcml_tpu.envs.vec_env`).

Faithful structural choices (citations into the reference):

- actions: 0 no-op (dead only), 1 stop, 2-5 move N/S/E/W, 6+e attack enemy e
  (``StarCraft2_Env.py:269-271`` ``n_actions = 6 + n_enemies``; avail rules
  ``:1846-1884``: move by pathability, attack iff alive + within shoot range).
- per-agent obs: move bits, then per-enemy (attackable, dist, rel_x, rel_y,
  health, [shield], [type]), per-ally (visible, dist, rel_x, rel_y, health,
  [shield], [type]), own (health, [shield], [type]) — all distances
  normalized by sight range, zeros when dead (``:1015-1110``).
- centralized state: per-ally (health, cooldown, rel-to-center x, y,
  [shield], [type]) + per-enemy (health, rel x, y, [shield], [type]) +
  last-action one-hots (``get_state``/``get_state_size`` ``:1189-1335``).
- shaped reward: positive-only damage + kill + win bonuses, normalized so the
  max episode return is ``reward_scale_rate`` (SMAC's reward_scale semantics).
- sight range 9, shoot range 6 (melee 2), one-hot unit types from the map
  roster (``maps.py``).

Deliberate simplifications (a microsim, not SC2): no terrain/pathing grid, no
shield regeneration, no medivac healing, enemy "AI" = attack nearest in range
else advance toward nearest ally — approximating the built-in attack-move bot
the real maps script.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.envs.smac.maps import MapParams, UNIT_STATS, get_map_params

SIGHT_RANGE = 9.0
SHOOT_RANGE = 6.0
MELEE_RANGE = 2.0
MOVE_AMOUNT = 2.0
N_ACTIONS_NO_ATTACK = 6
REWARD_DEATH_VALUE = 10.0
REWARD_WIN = 200.0
REWARD_SCALE_RATE = 20.0


class SMACLiteState(NamedTuple):
    rng: jax.Array
    ally_pos: jax.Array        # (A, 2)
    ally_hp: jax.Array         # (A,)  health + shield pooled? no: health only
    ally_shield: jax.Array     # (A,)
    ally_cd: jax.Array         # (A,) cooldown steps remaining
    enemy_pos: jax.Array       # (Ne, 2)
    enemy_hp: jax.Array        # (Ne,)
    enemy_shield: jax.Array    # (Ne,)
    enemy_cd: jax.Array        # (Ne,)
    last_actions: jax.Array    # (A,) int32
    t: jax.Array               # int32


class SMACTimeStep(NamedTuple):
    obs: jax.Array             # (A, obs_dim)
    share_obs: jax.Array       # (A, state_dim)
    available_actions: jax.Array  # (A, n_actions)
    reward: jax.Array          # (A, 1)
    done: jax.Array            # (A,) bool
    # info channels riding the generic scalar slots (Trajectory.delays /
    # payments): `delay` carries the battle-won flag on the terminal step —
    # per-episode sums of it ARE the win indicator the SMAC runner reports
    # (smac_runner.py:70-91) — and `payment` carries the ally dead ratio.
    delay: jax.Array           # scalar: 1.0 on the step a battle is won
    payment: jax.Array         # scalar: dead allies / A on this step


@dataclasses.dataclass(frozen=True)
class SMACLiteConfig:
    map_name: str = "3m"
    move_amount: float = MOVE_AMOUNT
    attack_own_team: bool = False          # reserved
    continuing_episode: bool = False
    # union obs-layout overrides (scenario-as-data map families,
    # envs/scenario.py): pin the one-hot type layout / shield columns to a
    # roster-wide union so same-shape maps observe through identical feature
    # widths.  () / False = this map's own layout.
    layout_types: Tuple[str, ...] = ()
    layout_shield: bool = False


def _roster_arrays(types: Tuple[str, ...], all_types: Tuple[str, ...]):
    hp = np.array([UNIT_STATS[t][0] for t in types], np.float32)
    sh = np.array([UNIT_STATS[t][1] for t in types], np.float32)
    dmg = np.array([UNIT_STATS[t][2] for t in types], np.float32)
    cd = np.array([UNIT_STATS[t][3] for t in types], np.float32)
    melee = np.array([UNIT_STATS[t][4] for t in types], bool)
    type_id = np.array([all_types.index(t) for t in types], np.int32)
    return hp, sh, dmg, cd, melee, type_id


class SMACLiteEnv:
    """TimeStep-protocol combat env; all methods jit/vmap-safe."""

    def __init__(self, cfg: SMACLiteConfig = SMACLiteConfig()):
        self.cfg = cfg
        mp: MapParams = get_map_params(cfg.map_name)
        self.map_params = mp
        self.n_agents = mp.n_agents
        self.n_enemies = mp.n_enemies
        self.n_actions = N_ACTIONS_NO_ATTACK + mp.n_enemies
        self.action_dim = self.n_actions
        self.episode_limit = mp.limit

        all_types = tuple(cfg.layout_types) if cfg.layout_types else mp.unit_types
        missing = sorted(set(mp.unit_types) - set(all_types))
        if missing:
            raise ValueError(
                f"map {mp.name!r} has unit types {missing} absent from "
                f"layout_types={all_types}"
            )
        # same rule as MapParams.unit_type_bits, applied to the union layout
        self.unit_type_bits = 0 if len(all_types) < 2 else len(all_types)
        a = _roster_arrays(mp.agents, all_types)
        e = _roster_arrays(mp.enemies, all_types)
        (self.a_hp0, self.a_sh0, self.a_dmg, self.a_cd0, a_melee, self.a_type) = (
            jnp.asarray(x) for x in a
        )
        (self.e_hp0, self.e_sh0, self.e_dmg, self.e_cd0, e_melee, self.e_type) = (
            jnp.asarray(x) for x in e
        )
        self.a_range = jnp.where(jnp.asarray(a_melee), MELEE_RANGE, SHOOT_RANGE)
        self.e_range = jnp.where(jnp.asarray(e_melee), MELEE_RANGE, SHOOT_RANGE)
        self.shield_bits = int((a[1].max() > 0) or (e[1].max() > 0)
                               or cfg.layout_shield)
        self.map_w, self.map_h = mp.map_size

        # obs layout widths (get_obs_*_size, StarCraft2_Env.py:1662-1686):
        # (attackable/visible, dist, relx, rely, health[, shield][, type])
        self.enemy_feat_dim = 4 + 1 + self.shield_bits + self.unit_type_bits
        self.ally_feat_dim = 4 + 1 + self.shield_bits + self.unit_type_bits
        self.own_feat_dim = 1 + self.shield_bits + self.unit_type_bits
        self.obs_dim = (
            4
            + self.n_enemies * self.enemy_feat_dim
            + (self.n_agents - 1) * self.ally_feat_dim
            + self.own_feat_dim
        )
        # state layout (get_state_size, :1688-1711): ally (health, cd, relx,
        # rely[, shield][, type]) + enemy (health, relx, rely[, shield][, type])
        # + last actions one-hot
        self.state_ally_dim = 4 + self.shield_bits + self.unit_type_bits
        self.state_enemy_dim = 3 + self.shield_bits + self.unit_type_bits
        self.share_obs_dim = (
            self.n_agents * self.state_ally_dim
            + self.n_enemies * self.state_enemy_dim
            + self.n_agents * self.n_actions
        )

        max_reward = float(e[0].sum() + e[1].sum()) + self.n_enemies * REWARD_DEATH_VALUE + REWARD_WIN
        self._reward_norm = max_reward / REWARD_SCALE_RATE

    # ------------------------------------------------------------- spawning

    def _spawn(self, key: jax.Array) -> SMACLiteState:
        k_a, k_e, key = jax.random.split(key, 3)
        cx, cy = self.map_w / 2.0, self.map_h / 2.0
        ally_y = cy + (jnp.arange(self.n_agents) - (self.n_agents - 1) / 2.0) * 1.5
        enemy_y = cy + (jnp.arange(self.n_enemies) - (self.n_enemies - 1) / 2.0) * 1.5
        jitter_a = jax.random.uniform(k_a, (self.n_agents, 2), minval=-0.5, maxval=0.5)
        jitter_e = jax.random.uniform(k_e, (self.n_enemies, 2), minval=-0.5, maxval=0.5)
        ally_pos = jnp.stack([jnp.full((self.n_agents,), cx - 6.0), ally_y], -1) + jitter_a
        enemy_pos = jnp.stack([jnp.full((self.n_enemies,), cx + 6.0), enemy_y], -1) + jitter_e
        return SMACLiteState(
            rng=key,
            ally_pos=ally_pos,
            ally_hp=self.a_hp0,
            ally_shield=self.a_sh0,
            ally_cd=jnp.zeros((self.n_agents,)),
            enemy_pos=enemy_pos,
            enemy_hp=self.e_hp0,
            enemy_shield=self.e_sh0,
            enemy_cd=jnp.zeros((self.n_enemies,)),
            last_actions=jnp.zeros((self.n_agents,), jnp.int32),
            t=jnp.zeros((), jnp.int32),
        )

    # ------------------------------------------------------------ observing

    def _avail(self, st: SMACLiteState) -> jax.Array:
        """(A, n_actions) availability (``get_avail_agent_actions :1846-1884``)."""
        alive = st.ally_hp > 0
        pos = st.ally_pos
        can_n = pos[:, 1] + self.cfg.move_amount <= self.map_h
        can_s = pos[:, 1] - self.cfg.move_amount >= 0.0
        can_e = pos[:, 0] + self.cfg.move_amount <= self.map_w
        can_w = pos[:, 0] - self.cfg.move_amount >= 0.0
        dist = jnp.linalg.norm(pos[:, None, :] - st.enemy_pos[None, :, :], axis=-1)
        att = (dist <= self.a_range[:, None]) & (st.enemy_hp > 0)[None, :]
        avail = jnp.concatenate(
            [
                (~alive)[:, None],                   # no-op iff dead
                alive[:, None],                      # stop
                jnp.stack([can_n, can_s, can_e, can_w], -1) & alive[:, None],
                att & alive[:, None],
            ],
            axis=-1,
        )
        return avail.astype(jnp.float32)

    def _unit_tail(self, hp_frac, sh_frac, type_id):
        cols = [hp_frac[..., None]]
        if self.shield_bits:
            cols.append(sh_frac[..., None])
        if self.unit_type_bits:
            cols.append(jax.nn.one_hot(type_id, self.unit_type_bits))
        return jnp.concatenate(cols, -1)

    def _observe(self, st: SMACLiteState) -> Tuple[jax.Array, jax.Array, jax.Array]:
        A, Ne = self.n_agents, self.n_enemies
        avail = self._avail(st)
        alive_a = st.ally_hp > 0
        alive_e = st.enemy_hp > 0
        rel_e = st.enemy_pos[None, :, :] - st.ally_pos[:, None, :]     # (A, Ne, 2)
        dist_e = jnp.linalg.norm(rel_e, axis=-1)
        vis_e = (dist_e < SIGHT_RANGE) & alive_e[None, :]
        e_hp_frac = st.enemy_hp / self.e_hp0
        e_sh_frac = st.enemy_shield / jnp.maximum(self.e_sh0, 1.0)
        e_tail = jnp.broadcast_to(
            self._unit_tail(e_hp_frac, e_sh_frac, self.e_type)[None],
            (A, Ne, 1 + self.shield_bits + self.unit_type_bits),
        )
        enemy_feats = jnp.concatenate(
            [
                avail[:, N_ACTIONS_NO_ATTACK:, None],
                (dist_e / SIGHT_RANGE)[..., None],
                rel_e / SIGHT_RANGE,
                e_tail,
            ],
            axis=-1,
        ) * vis_e[..., None]

        rel_a = st.ally_pos[None, :, :] - st.ally_pos[:, None, :]      # (A, A, 2)
        dist_a = jnp.linalg.norm(rel_a, axis=-1)
        vis_a = (dist_a < SIGHT_RANGE) & alive_a[None, :]
        a_hp_frac = st.ally_hp / self.a_hp0
        a_sh_frac = st.ally_shield / jnp.maximum(self.a_sh0, 1.0)
        a_tail = jnp.broadcast_to(
            self._unit_tail(a_hp_frac, a_sh_frac, self.a_type)[None],
            (A, A, 1 + self.shield_bits + self.unit_type_bits),
        )
        ally_feats_full = jnp.concatenate(
            [
                vis_a[..., None].astype(jnp.float32),
                (dist_a / SIGHT_RANGE)[..., None],
                rel_a / SIGHT_RANGE,
                a_tail,
            ],
            axis=-1,
        ) * vis_a[..., None]
        # drop self row i for each agent i (al_ids loop, :1101-1104);
        # numpy mask stays concrete under jit (a traced bool index errors)
        mask = ~np.eye(A, dtype=bool)
        ally_feats = ally_feats_full[mask].reshape(A, A - 1, self.ally_feat_dim)

        own = self._unit_tail(a_hp_frac, a_sh_frac, self.a_type)       # (A, own_feat)
        move_feats = avail[:, 2:N_ACTIONS_NO_ATTACK]
        obs = jnp.concatenate(
            [
                move_feats,
                enemy_feats.reshape(A, -1),
                ally_feats.reshape(A, -1),
                own,
            ],
            axis=-1,
        ) * alive_a[:, None]                                           # dead -> zeros

        # centralized state (get_state :1189-1240)
        cx, cy = self.map_w / 2.0, self.map_h / 2.0
        a_state = jnp.concatenate(
            [
                a_hp_frac[:, None],
                (st.ally_cd / jnp.maximum(self.a_cd0, 1.0))[:, None],
                (st.ally_pos[:, 0:1] - cx) / self.map_w,
                (st.ally_pos[:, 1:2] - cy) / self.map_h,
            ]
            + ([a_sh_frac[:, None]] if self.shield_bits else [])
            + ([jax.nn.one_hot(self.a_type, self.unit_type_bits)] if self.unit_type_bits else []),
            axis=-1,
        ) * alive_a[:, None]
        e_state = jnp.concatenate(
            [
                e_hp_frac[:, None],
                (st.enemy_pos[:, 0:1] - cx) / self.map_w,
                (st.enemy_pos[:, 1:2] - cy) / self.map_h,
            ]
            + ([e_sh_frac[:, None]] if self.shield_bits else [])
            + ([jax.nn.one_hot(self.e_type, self.unit_type_bits)] if self.unit_type_bits else []),
            axis=-1,
        ) * alive_e[:, None]
        last_act = jax.nn.one_hot(st.last_actions, self.n_actions)
        state = jnp.concatenate(
            [a_state.reshape(-1), e_state.reshape(-1), last_act.reshape(-1)]
        )
        share_obs = jnp.broadcast_to(state, (A, self.share_obs_dim))
        return obs, share_obs, avail

    # -------------------------------------------------------------- control

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[SMACLiteState, SMACTimeStep]:
        del episode_idx
        st = self._spawn(key)
        obs, share, avail = self._observe(st)
        zero = jnp.zeros(())
        return st, SMACTimeStep(
            obs, share, avail,
            jnp.zeros((self.n_agents, 1)),
            jnp.zeros((self.n_agents,), bool),
            zero, zero,
        )

    def step(self, st: SMACLiteState, action: jax.Array) -> Tuple[SMACLiteState, SMACTimeStep]:
        A, Ne = self.n_agents, self.n_enemies
        act = action.reshape(-1).astype(jnp.int32)
        alive_a = st.ally_hp > 0
        alive_e = st.enemy_hp > 0
        avail = self._avail(st) > 0.5
        # invalid submissions downgrade to stop (alive) / no-op (dead)
        valid = jnp.take_along_axis(avail, act[:, None], axis=1)[:, 0]
        act = jnp.where(valid, act, jnp.where(alive_a, 1, 0))

        # ally movement
        dirs = jnp.array([[0, 0], [0, 0], [0, 1], [0, -1], [1, 0], [-1, 0]], jnp.float32)
        move_vec = dirs[jnp.clip(act, 0, 5)] * self.cfg.move_amount
        moving = (act >= 2) & (act < N_ACTIONS_NO_ATTACK)
        new_pos = st.ally_pos + move_vec * moving[:, None]
        new_pos = jnp.clip(
            new_pos,
            jnp.zeros((2,)),
            jnp.array([self.map_w, self.map_h]),
        )

        # ally attacks: damage lands this step if cooldown ready
        attacking = act >= N_ACTIONS_NO_ATTACK
        target = jnp.clip(act - N_ACTIONS_NO_ATTACK, 0, Ne - 1)
        can_fire = attacking & (st.ally_cd <= 0) & alive_a
        dmg_to_enemy = jnp.zeros((Ne,)).at[target].add(
            jnp.where(can_fire, self.a_dmg, 0.0)
        )
        ally_cd = jnp.where(
            can_fire, self.a_cd0, jnp.maximum(st.ally_cd - 1.0, 0.0)
        )

        # enemy AI: attack nearest ally in range, else advance toward nearest
        dist_ea = jnp.linalg.norm(
            st.enemy_pos[:, None, :] - st.ally_pos[None, :, :], axis=-1
        )                                                           # (Ne, A)
        dist_masked = jnp.where(alive_a[None, :], dist_ea, jnp.inf)
        near = jnp.argmin(dist_masked, axis=1)                      # (Ne,)
        near_dist = jnp.take_along_axis(dist_masked, near[:, None], 1)[:, 0]
        any_ally = jnp.isfinite(near_dist)
        e_fire = alive_e & any_ally & (near_dist <= self.e_range) & (st.enemy_cd <= 0)
        dmg_to_ally = jnp.zeros((A,)).at[near].add(jnp.where(e_fire, self.e_dmg, 0.0))
        enemy_cd = jnp.where(e_fire, self.e_cd0, jnp.maximum(st.enemy_cd - 1.0, 0.0))
        # advance when not firing
        to_ally = jnp.take_along_axis(
            st.ally_pos[None].repeat(Ne, 0), near[:, None, None].repeat(2, 2), 1
        )[:, 0, :] - st.enemy_pos
        norm = jnp.maximum(jnp.linalg.norm(to_ally, axis=-1, keepdims=True), 1e-6)
        e_move = alive_e & any_ally & ~e_fire
        enemy_pos = st.enemy_pos + (to_ally / norm) * self.cfg.move_amount * e_move[:, None]

        # apply damage: shields absorb first (protoss semantics)
        e_sh_after = jnp.maximum(st.enemy_shield - dmg_to_enemy, 0.0)
        e_overflow = jnp.maximum(dmg_to_enemy - st.enemy_shield, 0.0)
        enemy_hp = jnp.clip(st.enemy_hp - e_overflow, 0.0, None)
        a_sh_after = jnp.maximum(st.ally_shield - dmg_to_ally, 0.0)
        a_overflow = jnp.maximum(dmg_to_ally - st.ally_shield, 0.0)
        ally_hp = jnp.clip(st.ally_hp - a_overflow, 0.0, None)

        # shaped reward (positive-only SMAC default): damage + kills + win
        enemy_killed = alive_e & (enemy_hp <= 0)
        damage_dealt = (st.enemy_hp - enemy_hp).sum() + (st.enemy_shield - e_sh_after).sum()
        won = ~(enemy_hp > 0).any()
        lost = ~(ally_hp > 0).any() & ~won
        t = st.t + 1
        timeout = t >= self.episode_limit
        done_now = won | lost | timeout
        raw = (
            damage_dealt
            + REWARD_DEATH_VALUE * enemy_killed.sum()
            + REWARD_WIN * won
        )
        reward = raw / self._reward_norm
        # emitted only on terminal steps so per-episode SUMS of the channel
        # (what the runner accounting computes) equal the episode's value
        dead_ratio = (1.0 - (ally_hp > 0).mean()) * done_now

        mid = SMACLiteState(
            rng=st.rng, ally_pos=new_pos, ally_hp=ally_hp, ally_shield=a_sh_after,
            ally_cd=ally_cd, enemy_pos=enemy_pos, enemy_hp=enemy_hp,
            enemy_shield=e_sh_after, enemy_cd=enemy_cd, last_actions=act, t=t,
        )
        # auto-reset inside step (pure-JAX convention): terminal steps return
        # the NEW episode's obs with the old step's reward
        key_next, k_spawn = jax.random.split(st.rng)
        fresh = self._spawn(k_spawn)._replace(rng=key_next)
        new_st = jax.tree.map(
            lambda a, b: jnp.where(done_now, a, b), fresh, mid
        )
        obs, share, avail_next = self._observe(new_st)
        return new_st, SMACTimeStep(
            obs=obs,
            share_obs=share,
            available_actions=avail_next,
            reward=jnp.full((A, 1), reward, jnp.float32),
            done=jnp.full((A,), done_now),
            delay=won.astype(jnp.float32),
            payment=dead_ratio,
        )
