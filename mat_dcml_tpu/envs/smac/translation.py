"""Multi-map SMAC feature translation (``starcraft2/feature_translation.py``).

Different maps have different agent counts, rosters, and action spaces; to
train ONE policy across maps (and evaluate few-shot on held-out maps —
``smac_multi_runner.py``), per-map obs/state/avail tensors are padded into a
universal layout:

- agents padded to ``TARGET_NUM_AGENT`` (27), enemies to ``TARGET_NUM_ENEMY``
  (30) — virtual units are dead: zero features, no-op-only availability
  (reference targets ``feature_translation.py:9-11``: 27 agents / 38 actions
  with SC2's wider rosters; ours derive from the stand-in registry).
- per-unit feature rows widened to a universal schema with a shield slot and
  a unified unit-type one-hot over every known type
  (``unified_unit_type_map``), so "marine" means the same feature column on
  every map.
- a task embedding (map one-hot + normalized team sizes/limit) appended to
  obs and state (``gen_task_embedding :283-293``).

Everything is static-shape jit/vmap-safe array surgery on top of
:class:`SMACLiteEnv`; :class:`TranslatedSMACEnv` exposes the padded env as a
normal TimeStep env so collectors/policies are map-agnostic.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.envs.smac.maps import UNIT_STATS, map_param_registry
from mat_dcml_tpu.envs.smac.smaclite import (
    N_ACTIONS_NO_ATTACK,
    SMACLiteConfig,
    SMACLiteEnv,
    SMACTimeStep,
)

TARGET_NUM_AGENT = 27
TARGET_NUM_ENEMY = 30
TARGET_ACTION_DIM = N_ACTIONS_NO_ATTACK + TARGET_NUM_ENEMY

UNIFIED_TYPES: Tuple[str, ...] = tuple(sorted(UNIT_STATS))
N_TYPES = len(UNIFIED_TYPES)

# universal per-row widths: (flag, dist, relx, rely, health, shield, type*)
UNIT_ROW_DIM = 5 + 1 + N_TYPES
OWN_ROW_DIM = 1 + 1 + N_TYPES
STATE_ALLY_DIM = 4 + 1 + N_TYPES          # health, cd, relx, rely, shield, type*
STATE_ENEMY_DIM = 3 + 1 + N_TYPES

_MAP_NAMES = tuple(sorted(map_param_registry))
TASK_EMBEDDING_DIM = len(_MAP_NAMES) + 3


def gen_task_embedding(map_name: str) -> np.ndarray:
    """Map one-hot + (n_agents, n_enemies, limit) normalized
    (``feature_translation.py:283-293``)."""
    mp = map_param_registry[map_name]
    one_hot = np.zeros(len(_MAP_NAMES), np.float32)
    one_hot[_MAP_NAMES.index(map_name)] = 1.0
    extras = np.array(
        [mp.n_agents / TARGET_NUM_AGENT, mp.n_enemies / TARGET_NUM_ENEMY, mp.limit / 200.0],
        np.float32,
    )
    return np.concatenate([one_hot, extras])


def _widen_rows(rows: jax.Array, env: SMACLiteEnv, flag_cols: int) -> jax.Array:
    """(..., k, env_row_dim) -> (..., k, flag_cols+4+1+1+N_TYPES): copy the
    first ``flag_cols + 4`` columns verbatim (flags/dist/rel/health — callers
    choose flag_cols so the copied prefix is exactly their non-shield,
    non-type columns), place shield into the universal shield slot, re-embed
    the unit type into the unified one-hot."""
    lead = rows[..., : flag_cols + 3]
    health = rows[..., flag_cols + 3 : flag_cols + 4]
    idx = flag_cols + 4
    if env.shield_bits:
        shield = rows[..., idx : idx + 1]
        idx += 1
    else:
        shield = jnp.zeros_like(health)
    # env-local type one-hot -> unified: scatter through the map's type list
    uni = jnp.zeros((*rows.shape[:-1], N_TYPES), rows.dtype)
    local_types = env.map_params.unit_types
    if env.unit_type_bits:
        local_oh = rows[..., idx : idx + env.unit_type_bits]
        for j, tname in enumerate(local_types):
            uni = uni.at[..., UNIFIED_TYPES.index(tname)].set(local_oh[..., j])
    else:
        # homogeneous map: the (single) roster type, gated on the row being
        # live (flag/health nonzero so padded rows stay all-zero)
        live = (jnp.abs(rows).sum(-1, keepdims=True) > 0).astype(rows.dtype)
        tname = local_types[0]
        uni = uni.at[..., UNIFIED_TYPES.index(tname)].set(live[..., 0])
    return jnp.concatenate([lead, health, shield, uni], axis=-1)


def _pad_axis(x: jax.Array, axis: int, target: int) -> jax.Array:
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - x.shape[axis])
    return jnp.pad(x, pad)


class TranslatedSMACEnv:
    """A SMACLite map padded to the universal multi-map layout."""

    def __init__(self, cfg: SMACLiteConfig = SMACLiteConfig()):
        self.env = SMACLiteEnv(cfg)
        e = self.env
        self.map_name = cfg.map_name
        self.n_agents = TARGET_NUM_AGENT
        self.action_dim = TARGET_ACTION_DIM
        self._task_emb = jnp.asarray(gen_task_embedding(cfg.map_name))
        self.obs_dim = (
            4
            + TARGET_NUM_ENEMY * UNIT_ROW_DIM
            + (TARGET_NUM_AGENT - 1) * UNIT_ROW_DIM
            + OWN_ROW_DIM
            + TASK_EMBEDDING_DIM
        )
        self.share_obs_dim = (
            TARGET_NUM_AGENT * STATE_ALLY_DIM
            + TARGET_NUM_ENEMY * STATE_ENEMY_DIM
            + TARGET_NUM_AGENT * TARGET_ACTION_DIM
            + TASK_EMBEDDING_DIM
        )

    # ------------------------------------------------------------ translate

    def _translate_obs(self, obs: jax.Array) -> jax.Array:
        e = self.env
        A, Ne = e.n_agents, e.n_enemies
        i = 4
        move = obs[:, :i]
        enemy = obs[:, i : i + Ne * e.enemy_feat_dim].reshape(A, Ne, e.enemy_feat_dim)
        i += Ne * e.enemy_feat_dim
        ally = obs[:, i : i + (A - 1) * e.ally_feat_dim].reshape(A, A - 1, e.ally_feat_dim)
        i += (A - 1) * e.ally_feat_dim
        own = obs[:, i:]

        enemy_u = _pad_axis(_widen_rows(enemy, e, flag_cols=1), 1, TARGET_NUM_ENEMY)
        ally_u = _pad_axis(_widen_rows(ally, e, flag_cols=1), 1, TARGET_NUM_AGENT - 1)
        own_u = _widen_rows(own[:, None, :], e, flag_cols=-3)[:, 0, :]
        flat = jnp.concatenate(
            [
                move,
                enemy_u.reshape(A, -1),
                ally_u.reshape(A, -1),
                own_u,
                jnp.broadcast_to(self._task_emb, (A, TASK_EMBEDDING_DIM)),
            ],
            axis=-1,
        )
        return _pad_axis(flat, 0, TARGET_NUM_AGENT)

    def _translate_state(self, share_obs: jax.Array) -> jax.Array:
        e = self.env
        A, Ne = e.n_agents, e.n_enemies
        row = share_obs[0]
        i = A * e.state_ally_dim
        a_state = row[:i].reshape(A, e.state_ally_dim)
        e_state = row[i : i + Ne * e.state_enemy_dim].reshape(Ne, e.state_enemy_dim)
        i += Ne * e.state_enemy_dim
        last = row[i:].reshape(A, e.n_actions)

        a_u = _pad_axis(_widen_rows(a_state[None], e, flag_cols=0)[0], 0, TARGET_NUM_AGENT)
        e_u = _pad_axis(_widen_rows(e_state[None], e, flag_cols=-1)[0], 0, TARGET_NUM_ENEMY)
        # split last-action one-hot: no-attack block + attack block padded apart
        last_u = jnp.concatenate(
            [
                last[:, :N_ACTIONS_NO_ATTACK],
                _pad_axis(last[:, N_ACTIONS_NO_ATTACK:], 1, TARGET_NUM_ENEMY),
            ],
            axis=-1,
        )
        last_u = _pad_axis(last_u, 0, TARGET_NUM_AGENT)
        state = jnp.concatenate(
            [a_u.reshape(-1), e_u.reshape(-1), last_u.reshape(-1), self._task_emb]
        )
        return jnp.broadcast_to(state, (TARGET_NUM_AGENT, self.share_obs_dim))

    def _translate_avail(self, avail: jax.Array) -> jax.Array:
        wide = jnp.concatenate(
            [
                avail[:, :N_ACTIONS_NO_ATTACK],
                _pad_axis(avail[:, N_ACTIONS_NO_ATTACK:], 1, TARGET_NUM_ENEMY),
            ],
            axis=-1,
        )
        pad_rows = jnp.zeros((TARGET_NUM_AGENT - avail.shape[0], TARGET_ACTION_DIM))
        pad_rows = pad_rows.at[:, 0].set(1.0)             # virtual agents: no-op only
        return jnp.concatenate([wide, pad_rows], axis=0)

    def _translate_ts(self, ts: SMACTimeStep) -> SMACTimeStep:
        A = self.env.n_agents
        reward = jnp.broadcast_to(ts.reward[:1], (TARGET_NUM_AGENT, 1))
        done = jnp.broadcast_to(ts.done[:1], (TARGET_NUM_AGENT,))
        return SMACTimeStep(
            obs=self._translate_obs(ts.obs),
            share_obs=self._translate_state(ts.share_obs),
            available_actions=self._translate_avail(ts.available_actions),
            reward=reward,
            done=done,
            delay=ts.delay,
            payment=ts.payment,
        )

    # --------------------------------------------------------------- control

    def reset(self, key: jax.Array, episode_idx=0):
        st, ts = self.env.reset(key, episode_idx)
        return st, self._translate_ts(ts)

    def step(self, st, action: jax.Array):
        # slice back to the real roster; padded agents' actions are ignored,
        # attack ids beyond the real enemy count downgrade inside the env
        real = action[: self.env.n_agents]
        st, ts = self.env.step(st, real)
        return st, self._translate_ts(ts)
