"""Real StarCraft II SMAC behind the host-process bridge (gated).

The reference vendors a full SMAC fork (``starcraft2/StarCraft2_Env.py``)
talking to the SC2 binary over pysc2 RPC.  A game binary cannot be vmapped or
traced, so here the real thing plugs in through the host vec-env layer
(:mod:`~mat_dcml_tpu.envs.vec_env`): one :class:`SMACHostEnv` per worker
process, stacked numpy to the device once per step.

Gated: requires the external ``smac`` package (oxwhirl/smac) and an SC2
install — neither ships in this image — and raises a clear error otherwise.
The pure-JAX stand-in (:mod:`~mat_dcml_tpu.envs.smac.smaclite`) covers
training/testing without the binary.
"""

from __future__ import annotations

import numpy as np


class SMACHostEnv:
    """Adapter: oxwhirl/smac ``StarCraft2Env`` -> host shared-obs contract
    (obs/state/avail layouts match ``StarCraft2_Env.py:1015-1335``)."""

    self_resetting = False                 # bridge auto-resets on done

    def __init__(self, map_name: str = "3m", seed: int = 0, backend_env=None,
                 **smac_kwargs):
        """``backend_env``: inject a pre-built StarCraft2Env-shaped object
        (fake-backend tests, tests/test_smac_host.py — the football pattern);
        default imports the real oxwhirl/smac."""
        if backend_env is None:
            try:
                from smac.env import StarCraft2Env  # type: ignore
            except ImportError as err:
                raise ImportError(
                    "SMACHostEnv needs the external 'smac' package and a StarCraft "
                    "II install (https://github.com/oxwhirl/smac). Neither is "
                    "bundled; use SMACLiteEnv (pure JAX) for binary-free training."
                ) from err
            backend_env = StarCraft2Env(map_name=map_name, seed=seed, **smac_kwargs)
        self._env = backend_env
        info = self._env.get_env_info()
        self.n_agents = info["n_agents"]
        self.obs_dim = info["obs_shape"]
        self.share_obs_dim = info["state_shape"]
        self.action_dim = info["n_actions"]
        self.episode_limit = info["episode_limit"]

    def _bundle(self):
        obs = np.stack(self._env.get_obs()).astype(np.float32)
        state = np.asarray(self._env.get_state(), np.float32)
        share = np.broadcast_to(state, (self.n_agents, state.shape[-1])).copy()
        avail = np.stack(
            [self._env.get_avail_agent_actions(i) for i in range(self.n_agents)]
        ).astype(np.float32)
        return obs, share, avail

    def reset(self):
        self._env.reset()
        return self._bundle()

    def step(self, actions):
        acts = np.asarray(actions).reshape(-1).astype(np.int64)
        reward, terminated, info = self._env.step(acts)
        obs, share, avail = self._bundle()
        rew = np.full((self.n_agents, 1), reward, np.float32)
        done = np.full((self.n_agents,), bool(terminated))
        info = dict(info or {})
        # ride the generic scalar info channels like SMACLite does
        info["delay"] = float(info.get("battle_won", False))
        info["payment"] = 0.0
        return obs, share, rew, done, info, avail

    def close(self):
        self._env.close()
