"""Pure-JAX multi-agent continuous-control stand-in ("MuJoCoLite").

The reference's multi-agent MuJoCo needs the MuJoCo binary (not bundled); the
real robots remain reachable through the gated gym adapter
(:mod:`~mat_dcml_tpu.envs.mamujoco.env`) over the host bridge.  This stand-in
exercises the identical factorization machinery — joint partitions, k-hop
obsk index building, per-agent continuous torque actions — on a closed-form
jointed-chain dynamics that is jit/vmap-compatible and quickly learnable:

    ω' = ω + dt (g·τ − d·ω − s·θ)          (damped torque integration)
    θ' = θ + dt ω'
    reward = −mean((θ − θ*)²) − c·mean(τ²)  (drive joints to a per-episode
                                             target posture, control cost)

i.e. a multi-joint "reacher" whose reward every agent shares (team objective,
like the reference's shared locomotion reward, ``mujoco_multi.py:129-136``).
Obs per agent = k-hop (θ, ω) slices via obsk indices + that joint-set's
targets; state = full (θ, ω, θ*).  Availability masks are all-ones
(continuous control has no masking, as upstream).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.envs.mamujoco.obsk import build_obs_indices, get_parts_and_edges


class MJLiteState(NamedTuple):
    rng: jax.Array
    theta: jax.Array          # (J,)
    omega: jax.Array          # (J,)
    target: jax.Array         # (J,)
    t: jax.Array


class MJLiteTimeStep(NamedTuple):
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    reward: jax.Array
    done: jax.Array
    delay: jax.Array          # protocol compat (zeros)
    payment: jax.Array


@dataclasses.dataclass(frozen=True)
class MJLiteConfig:
    scenario: str = "HalfCheetah-v2"
    agent_conf: str = "2x3"
    agent_obsk: int = 1
    episode_length: int = 50
    dt: float = 0.05
    gain: float = 4.0
    damping: float = 0.4
    stiffness: float = 0.5
    ctrl_cost: float = 0.05


class MJLiteEnv:
    """TimeStep-protocol env over the obsk factorization; jit/vmap-safe."""

    def __init__(self, cfg: MJLiteConfig = MJLiteConfig()):
        self.cfg = cfg
        parts, graph = get_parts_and_edges(cfg.scenario, cfg.agent_conf)
        self.partitions = parts
        self.graph = graph
        self.n_joints = len(graph.joints)
        self.n_agents = len(parts)
        # torques per agent (= the env's action_dim; reference uses the max
        # partition size, mujoco_multi.py:50)
        self.joints_per_agent = max(len(p) for p in parts)
        self.action_dim = self.joints_per_agent

        # per-agent obs gather indices over the JOINT axis, -1 padded; the
        # lite state has one θ/ω per joint so qpos ids ARE joint ids here
        idx_rows = []
        qpos_to_jid = {jt.qpos_id: j for j, jt in enumerate(graph.joints)}
        for p in parts:
            qpos_ids, _ = build_obs_indices(graph, p, cfg.agent_obsk)
            # map qpos ids back to joint ids, dropping root/global entries
            # (the lite state has one θ/ω per actuated joint only)
            jids = [qpos_to_jid[q] for q in qpos_ids if q in qpos_to_jid]
            idx_rows.append(jids)
        width = max(len(r) for r in idx_rows)
        self._obs_jids = jnp.asarray(
            np.array([r + [-1] * (width - len(r)) for r in idx_rows]), jnp.int32
        )
        self._obs_mask = jnp.asarray(
            np.array([[1.0] * len(r) + [0.0] * (width - len(r)) for r in idx_rows]),
            jnp.float32,
        )
        self._own_jids = jnp.asarray(
            np.array([list(p) + [-1] * (self.joints_per_agent - len(p)) for p in parts]),
            jnp.int32,
        )
        self.obs_dim = 3 * width                     # θ, ω, target per visible joint
        self.share_obs_dim = 3 * self.n_joints
        self.episode_limit = cfg.episode_length
        from mat_dcml_tpu.envs.spaces import Box

        self.action_space = Box(self.joints_per_agent)   # continuous torques

    # ----------------------------------------------------------------- obs

    def _gather(self, x: jax.Array) -> jax.Array:
        """(J,) -> (A, width) via the padded joint-index table."""
        safe = jnp.clip(self._obs_jids, 0, self.n_joints - 1)
        return x[safe] * self._obs_mask

    def _observe(self, st: MJLiteState):
        obs = jnp.concatenate(
            [self._gather(st.theta), self._gather(st.omega), self._gather(st.target)],
            axis=-1,
        )
        state = jnp.concatenate([st.theta, st.omega, st.target])
        share = jnp.broadcast_to(state, (self.n_agents, self.share_obs_dim))
        avail = jnp.ones((self.n_agents, 1), jnp.float32)
        return obs, share, avail

    # ------------------------------------------------------------- control

    def reset(self, key: jax.Array, episode_idx=0) -> Tuple[MJLiteState, MJLiteTimeStep]:
        del episode_idx
        key, k_th, k_tg = jax.random.split(key, 3)
        st = MJLiteState(
            rng=key,
            theta=jax.random.uniform(k_th, (self.n_joints,), minval=-0.1, maxval=0.1),
            omega=jnp.zeros((self.n_joints,)),
            target=jax.random.uniform(k_tg, (self.n_joints,), minval=-1.0, maxval=1.0),
            t=jnp.zeros((), jnp.int32),
        )
        obs, share, avail = self._observe(st)
        zero = jnp.zeros(())
        return st, MJLiteTimeStep(
            obs, share, avail,
            jnp.zeros((self.n_agents, 1)),
            jnp.zeros((self.n_agents,), bool),
            zero, zero,
        )

    def step(self, st: MJLiteState, action: jax.Array) -> Tuple[MJLiteState, MJLiteTimeStep]:
        c = self.cfg
        act = jnp.clip(action.reshape(self.n_agents, -1), -1.0, 1.0)
        # scatter per-agent torques back onto the joint axis
        tau = jnp.zeros((self.n_joints,))
        safe = jnp.clip(self._own_jids, 0, self.n_joints - 1)
        valid = (self._own_jids >= 0).astype(jnp.float32)
        tau = tau.at[safe.reshape(-1)].add((act * valid).reshape(-1))

        omega = st.omega + c.dt * (c.gain * tau - c.damping * st.omega - c.stiffness * st.theta)
        theta = st.theta + c.dt * omega
        err = theta - st.target
        reward = -(err**2).mean() - c.ctrl_cost * (tau**2).mean()
        t = st.t + 1
        done_now = t >= c.episode_length

        key_next, k_spawn = jax.random.split(st.rng)
        fresh_st, _ = self.reset(k_spawn)
        mid = MJLiteState(rng=key_next, theta=theta, omega=omega, target=st.target, t=t)
        new_st = jax.tree.map(lambda a, b: jnp.where(done_now, a, b), fresh_st._replace(rng=key_next), mid)
        obs, share, avail = self._observe(new_st)
        zero = jnp.zeros(())
        return new_st, MJLiteTimeStep(
            obs=obs, share_obs=share, available_actions=avail,
            reward=jnp.full((self.n_agents, 1), reward, jnp.float32),
            done=jnp.full((self.n_agents,), done_now),
            delay=zero, payment=zero,
        )
