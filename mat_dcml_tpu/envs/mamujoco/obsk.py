"""Joint-graph factorization for multi-agent MuJoCo (``obsk.py`` parity).

The reference factorizes a single MuJoCo robot into agents by partitioning
its actuated joints and builds per-agent observations from the k-hop
neighborhood of each agent's joints in the kinematic graph
(``ma_mujoco/multiagent_mujoco/obsk.py``: ``Node``/``HyperEdge`` +
``get_joints_at_kdist`` + ``build_obs``).  This module is the idiomatic
re-design: a plain joint graph with integer adjacency, robot definitions as
data, and the k-hop computation returning *index arrays* — ready to gather
``qpos``/``qvel`` slices as one vectorized take, both for the gated real-gym
adapter and the pure-JAX stand-in.

Supported (scenario, agent_conf) pairs mirror the reference registry
(``obsk.py:273-470``): HalfCheetah 2x3/6x1, Ant 2x4/2x4d/4x2/8x1, Hopper 3x1,
Walker2d 2x3/6x1, Swimmer 2x1, Reacher 2x1, Humanoid(Standup) 9|8 — plus the
scalable configs (``obsk.py:512-663``): manyagent_swimmer NxK (N agents x K
chained rotor segments each, asset auto-generated in the reference,
``manyagent_swimmer.py``), manyagent_ant NxK (K 4-joint leg segments per
agent, ``manyagent_ant.py``), coupled_half_cheetah 1p1 (two tendon-coupled
cheetahs, ``coupled_half_cheetah.py:1-43``).

Corrections vs the reference's registry: its manyagent_ant entry is marked
"TODO: FIX!" and computes non-negative "negative" qpos offsets for all but
the last segment, and its coupled_half_cheetah gives BOTH cheetahs the same
actuator ids 0-5; here every joint gets its true absolute qpos/qvel/actuator
index (second cheetah acts on 6-11).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Joint:
    """One actuated joint: indices into qpos/qvel/action vectors."""

    name: str
    qpos_id: int
    qvel_id: int
    act_id: int


@dataclasses.dataclass(frozen=True)
class RobotGraph:
    """Kinematic graph over actuated joints + free global coordinates."""

    name: str
    joints: Tuple[Joint, ...]
    edges: Tuple[Tuple[int, int], ...]      # joint-index pairs (kinematic links)
    # global (root) obs indices shared by all agents: (qpos ids, qvel ids)
    global_qpos: Tuple[int, ...]
    global_qvel: Tuple[int, ...]

    def neighbors(self, j: int) -> List[int]:
        out = []
        for a, b in self.edges:
            if a == j:
                out.append(b)
            elif b == j:
                out.append(a)
        return out


def _chain(names: Sequence[str], qpos0: int, qvel0: int,
           global_qpos: Sequence[int], global_qvel: Sequence[int],
           extra_edges: Sequence[Tuple[int, int]] = ()) -> RobotGraph:
    joints = tuple(
        Joint(n, qpos0 + i, qvel0 + i, i) for i, n in enumerate(names)
    )
    edges = tuple((i, i + 1) for i in range(len(names) - 1)) + tuple(extra_edges)
    return RobotGraph("chain", joints, edges, tuple(global_qpos), tuple(global_qvel))


def _legged(leg_names: Sequence[Sequence[str]], qpos0: int, qvel0: int,
            global_qpos: Sequence[int], global_qvel: Sequence[int]) -> RobotGraph:
    """Legs radiating from a torso: joints chained within a leg, first joints
    of all legs mutually connected through the torso."""
    joints: List[Joint] = []
    edges: List[Tuple[int, int]] = []
    firsts: List[int] = []
    i = 0
    for leg in leg_names:
        firsts.append(i)
        for k, n in enumerate(leg):
            joints.append(Joint(n, qpos0 + i, qvel0 + i, i))
            if k > 0:
                edges.append((i - 1, i))
            i += 1
    for a in range(len(firsts)):
        for b in range(a + 1, len(firsts)):
            edges.append((firsts[a], firsts[b]))
    return RobotGraph("legged", tuple(joints), tuple(edges),
                      tuple(global_qpos), tuple(global_qvel))


def _robot(scenario: str) -> RobotGraph:
    s = scenario.lower().split("-")[0]
    if s in ("halfcheetah", "half_cheetah"):
        # qpos: [rootx, rootz, rooty, bthigh, bshin, bfoot, fthigh, fshin, ffoot]
        return _chain(
            ["bthigh", "bshin", "bfoot", "fthigh", "fshin", "ffoot"],
            qpos0=3, qvel0=3, global_qpos=[1, 2], global_qvel=[0, 1, 2],
            extra_edges=[(0, 3)],           # back/front hips meet at the torso
        )
    if s == "walker2d":
        return _chain(
            ["thigh", "leg", "foot", "thigh_left", "leg_left", "foot_left"],
            qpos0=3, qvel0=3, global_qpos=[1, 2], global_qvel=[0, 1, 2],
            extra_edges=[(0, 3)],
        )
    if s == "hopper":
        return _chain(["thigh", "leg", "foot"], qpos0=3, qvel0=3,
                      global_qpos=[1, 2], global_qvel=[0, 1, 2])
    if s == "swimmer":
        return _chain(["rot2", "rot3"], qpos0=3, qvel0=3,
                      global_qpos=[2], global_qvel=[0, 1, 2])
    if s == "reacher":
        return _chain(["joint0", "joint1"], qpos0=0, qvel0=0,
                      global_qpos=[], global_qvel=[])
    if s == "ant":
        # qpos: 7 root dofs then 2 per leg (hip, ankle) x 4 legs
        return _legged(
            [["hip1", "ankle1"], ["hip2", "ankle2"],
             ["hip3", "ankle3"], ["hip4", "ankle4"]],
            qpos0=7, qvel0=6, global_qpos=[2, 3, 4, 5, 6], global_qvel=[0, 1, 2, 3, 4, 5],
        )
    if s in ("humanoid", "humanoidstandup"):
        return _legged(
            [["abdomen_z", "abdomen_y", "abdomen_x"],
             ["right_hip_x", "right_hip_z", "right_hip_y", "right_knee"],
             ["left_hip_x", "left_hip_z", "left_hip_y", "left_knee"],
             ["right_shoulder1", "right_shoulder2", "right_elbow"],
             ["left_shoulder1", "left_shoulder2", "left_elbow"]],
            qpos0=7, qvel0=6, global_qpos=[2, 3, 4, 5, 6], global_qvel=[0, 1, 2, 3, 4, 5],
        )
    raise KeyError(f"unknown scenario {scenario!r}")


def _manyagent_swimmer(n_segs: int) -> RobotGraph:
    """Chain of ``n_segs`` actuated rotors (one per body segment); the
    generated asset's qpos/qvel are [slide x, slide y, rot_0..rot_{n-1}]
    (``manyagent_swimmer.py:28-62``; registry ``obsk.py:568-586`` — its rot_i
    at qpos ``-n_segs+i`` == absolute ``2+i`` here).  The reference registry
    has empty globals for this robot, kept as-is."""
    joints = tuple(
        Joint(f"rot{i}", 2 + i, 2 + i, i) for i in range(n_segs)
    )
    edges = tuple((i, i + 1) for i in range(n_segs - 1))
    return RobotGraph("manyagent_swimmer", joints, edges, (), ())


def _manyagent_ant(n_segs: int) -> RobotGraph:
    """``n_segs`` torso segments, each with two 2-joint legs
    (hip1/ankle1/hip2/ankle2): qpos = 7 free-root dofs then 4 rotors per
    segment; actuator order per segment is (hip2, ankle2, hip1, ankle1) as in
    the reference's Node act ids (``obsk.py:588-656``).  Edges: ankle-hip
    within each leg, hips joined through the segment torso, and consecutive
    segments' hips linked (the reference's 4-ary HyperEdge, here as pairs)."""
    joints: List[Joint] = []
    edges: List[Tuple[int, int]] = []
    for si in range(n_segs):
        base = 4 * si
        # (name, qpos offset within segment, act id) — qpos order follows the
        # generated asset's body order, actuators the reference's Node ids
        joints.append(Joint(f"hip1_{si}", 7 + base, 6 + base, 2 + base))
        joints.append(Joint(f"ankle1_{si}", 7 + base + 1, 6 + base + 1, 3 + base))
        joints.append(Joint(f"hip2_{si}", 7 + base + 2, 6 + base + 2, 0 + base))
        joints.append(Joint(f"ankle2_{si}", 7 + base + 3, 6 + base + 3, 1 + base))
        h1, a1, h2, a2 = base, base + 1, base + 2, base + 3
        edges += [(a1, h1), (a2, h2), (h1, h2)]
        if si:
            prev_h1, prev_h2 = base - 4, base - 2
            edges += [(prev_h1, h1), (prev_h2, h2)]
    return RobotGraph(
        "manyagent_ant", tuple(joints), tuple(edges),
        global_qpos=(2, 3, 4, 5, 6), global_qvel=(0, 1, 2, 3, 4, 5),
    )


def _coupled_half_cheetah() -> RobotGraph:
    """Two half cheetahs coupled by a tendon between their back thighs
    (``coupled_half_cheetah.py:1-43``; registry ``obsk.py:512-566``).
    qpos = [root1 x/z/y, 6 joints, root2 x/z/y, 6 joints]; the tendon is an
    edge linking the two bthighs so k-hop obs can see across robots.
    Globals carry BOTH roots (the reference's registry exposes only cheetah
    1's root, leaving agent 2 blind to its own body height/velocity — kept
    corrected here alongside the actuator-id fix in the module docstring)."""
    names = ["bthigh", "bshin", "bfoot", "fthigh", "fshin", "ffoot"]
    joints = tuple(
        [Joint(n, 3 + i, 3 + i, i) for i, n in enumerate(names)]
        + [Joint(n + "2", 12 + i, 12 + i, 6 + i) for i, n in enumerate(names)]
    )
    chain = [(0, 1), (1, 2), (0, 3), (3, 4), (4, 5)]
    edges = tuple(chain + [(a + 6, b + 6) for a, b in chain] + [(0, 6)])
    return RobotGraph(
        "coupled_half_cheetah", joints, edges,
        global_qpos=(1, 2, 10, 11), global_qvel=(0, 1, 2, 9, 10, 11),
    )


def get_parts_and_edges(
    scenario: str, agent_conf: str
) -> Tuple[Tuple[Tuple[int, ...], ...], RobotGraph]:
    """(scenario, '2x3') -> (agent partitions as joint-index tuples, graph).

    ``agent_conf`` is "<n_agents>x<joints_per_agent>"; joints are dealt out in
    graph order except the Ant's special splits (``obsk.py:321-327``): "2x4"
    pairs neighbouring legs, "2x4d" pairs diagonal legs.  The scalable
    scenarios read it differently: manyagent_swimmer NxK = K rotor segments
    per agent, manyagent_ant NxK = K four-joint leg segments per agent,
    coupled_half_cheetah "1p1" = one agent per cheetah.
    """
    s = scenario.lower().split("-")[0]
    if s == "manyagent_swimmer":
        n_agents, per = _parse_conf(agent_conf)
        graph = _manyagent_swimmer(n_agents * per)
        parts = tuple(
            tuple(range(a * per, (a + 1) * per)) for a in range(n_agents)
        )
        return parts, graph
    if s == "manyagent_ant":
        n_agents, per = _parse_conf(agent_conf)
        graph = _manyagent_ant(n_agents * per)
        jper = 4 * per                       # 4 joints per leg segment
        parts = tuple(
            tuple(range(a * jper, (a + 1) * jper)) for a in range(n_agents)
        )
        return parts, graph
    if s == "coupled_half_cheetah":
        if agent_conf != "1p1":
            raise ValueError(
                f"coupled_half_cheetah supports agent_conf '1p1' only "
                f"(obsk.py:556-561), got {agent_conf!r}"
            )
        graph = _coupled_half_cheetah()
        return ((0, 1, 2, 3, 4, 5), (6, 7, 8, 9, 10, 11)), graph

    graph = _robot(scenario)
    n_joints = len(graph.joints)
    if scenario.lower().startswith("ant") and agent_conf == "2x4d":
        parts: Tuple[Tuple[int, ...], ...] = ((0, 1, 4, 5), (2, 3, 6, 7))
        return parts, graph
    n_agents, per = _parse_conf(agent_conf)
    if n_agents * per != n_joints:
        raise ValueError(
            f"{scenario}: {agent_conf} does not tile {n_joints} joints"
        )
    parts = tuple(
        tuple(range(a * per, (a + 1) * per)) for a in range(n_agents)
    )
    return parts, graph


def _parse_conf(agent_conf: str) -> Tuple[int, int]:
    try:
        n_agents, per = (int(x) for x in agent_conf.split("x"))
    except ValueError:
        raise ValueError(f"agent_conf {agent_conf!r} is not '<n>x<k>'") from None
    if n_agents < 1 or per < 1:
        raise ValueError(f"agent_conf {agent_conf!r}: both factors must be >= 1")
    return n_agents, per


def joints_at_kdist(graph: RobotGraph, partition: Sequence[int], k: int) -> List[List[int]]:
    """BFS shells: [joints at distance 0 (own), 1, ..., k] from the agent's
    joints (``get_joints_at_kdist``)."""
    seen = set(partition)
    shells = [sorted(partition)]
    frontier = list(partition)
    for _ in range(k):
        nxt = []
        for j in frontier:
            for nb in graph.neighbors(j):
                if nb not in seen:
                    seen.add(nb)
                    nxt.append(nb)
        shells.append(sorted(set(nxt)))
        frontier = nxt
    return shells


def build_obs_indices(
    graph: RobotGraph, partition: Sequence[int], k: int
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Gather indices (qpos_ids, qvel_ids) for one agent's k-hop obs:
    shell-ordered joint features then the shared globals (``build_obs``)."""
    qpos: List[int] = []
    qvel: List[int] = []
    for shell in joints_at_kdist(graph, partition, k):
        for j in shell:
            qpos.append(graph.joints[j].qpos_id)
            qvel.append(graph.joints[j].qvel_id)
    qpos.extend(graph.global_qpos)
    qvel.extend(graph.global_qvel)
    return tuple(qpos), tuple(qvel)
