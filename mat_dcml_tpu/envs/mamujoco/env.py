"""Real multi-agent MuJoCo behind the host-process bridge (gated).

Factorizes a gym MuJoCo robot into agents exactly as the reference
``MujocoMulti`` (``mujoco_multi.py:39-260``): actuated joints partitioned by
``agent_conf``, per-agent obs from the k-hop joint neighborhood (obsk index
tables), state = the wrapped env's full observation, availability all-ones,
shared reward.  Exposes the host shared-obs contract for
:mod:`~mat_dcml_tpu.envs.vec_env`.

Gated: requires ``gymnasium`` (or legacy ``gym``) with MuJoCo — not bundled;
:class:`~mat_dcml_tpu.envs.mamujoco.lite.MJLiteEnv` covers binary-free
training and tests.
"""

from __future__ import annotations

import numpy as np

from mat_dcml_tpu.envs.mamujoco.obsk import build_obs_indices, get_parts_and_edges
from mat_dcml_tpu.envs.spaces import Box


class MujocoMultiHostEnv:
    self_resetting = False

    def __init__(self, scenario: str = "HalfCheetah-v4", agent_conf: str = "2x3",
                 agent_obsk: int = 1, episode_limit: int = 1000, seed: int = 0,
                 backend_env=None):
        """``backend_env``: inject a pre-built gym(nasium)-shaped env object
        (fake-backend tests, tests/test_mamujoco_host.py); default gym.make."""
        if backend_env is None:
            try:
                import gymnasium as gym
            except ImportError:
                try:
                    import gym  # type: ignore
                except ImportError as err:
                    raise ImportError(
                        "MujocoMultiHostEnv needs gymnasium (or gym) with MuJoCo "
                        "installed; neither is bundled. Use MJLiteEnv for "
                        "binary-free multi-agent continuous control."
                    ) from err
            backend_env = gym.make(scenario)
        self._gym_env = backend_env
        self._seed = seed
        self.episode_limit = episode_limit
        parts, graph = get_parts_and_edges(scenario, agent_conf)
        self.partitions = parts
        self.n_agents = len(parts)
        self.joints_per_agent = max(len(p) for p in parts)
        self.action_dim = self.joints_per_agent
        self.action_space = Box(self.joints_per_agent)   # continuous torques
        self._act_ids = [
            [graph.joints[j].act_id for j in p] for p in parts
        ]
        rows = [build_obs_indices(graph, p, agent_obsk) for p in parts]
        width_p = max(len(q) for q, _ in rows)
        width_v = max(len(v) for _, v in rows)
        self._qpos_ids = np.array(
            [list(q) + [-1] * (width_p - len(q)) for q, _ in rows], np.int64
        )
        self._qvel_ids = np.array(
            [list(v) + [-1] * (width_v - len(v)) for _, v in rows], np.int64
        )
        self.obs_dim = width_p + width_v
        self._t = 0
        env = self._gym_env.unwrapped
        self.share_obs_dim = int(np.asarray(env.data.qpos).size + np.asarray(env.data.qvel).size)

    def _bundle(self):
        env = self._gym_env.unwrapped
        qpos = np.asarray(env.data.qpos).ravel()
        qvel = np.asarray(env.data.qvel).ravel()

        def gather(x, ids):
            out = x[np.clip(ids, 0, x.size - 1)]
            out[ids < 0] = 0.0
            return out

        obs = np.concatenate(
            [gather(qpos, self._qpos_ids), gather(qvel, self._qvel_ids)], axis=1
        ).astype(np.float32)
        state = np.concatenate([qpos, qvel]).astype(np.float32)
        share = np.broadcast_to(state, (self.n_agents, state.size)).copy()
        avail = np.ones((self.n_agents, 1), np.float32)
        return obs, share, avail

    def reset(self):
        self._gym_env.reset(seed=self._seed)
        self._seed += 1
        self._t = 0
        return self._bundle()

    def step(self, actions):
        acts = np.asarray(actions, np.float64).reshape(self.n_agents, -1)
        flat = np.zeros(sum(len(p) for p in self.partitions))
        for a, ids in enumerate(self._act_ids):
            for k, i in enumerate(ids):
                flat[i] = acts[a, k]
        out = self._gym_env.step(flat)
        if len(out) == 5:                       # gymnasium API
            _, reward, terminated, truncated, info = out
            done_flag = bool(terminated or truncated)
        else:                                   # legacy gym API
            _, reward, done_flag, info = out
        self._t += 1
        done_flag = done_flag or self._t >= self.episode_limit
        obs, share, avail = self._bundle()
        rew = np.full((self.n_agents, 1), reward, np.float32)
        done = np.full((self.n_agents,), done_flag)
        return obs, share, rew, done, dict(info or {}), avail

    def close(self):
        self._gym_env.close()
