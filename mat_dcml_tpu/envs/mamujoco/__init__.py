"""Multi-agent MuJoCo family: obsk joint-graph factorization, pure-JAX
stand-in dynamics, fault injection, and the gated real-gym host adapter."""

from mat_dcml_tpu.envs.mamujoco.fault import FaultyAgentWrapper
from mat_dcml_tpu.envs.mamujoco.lite import MJLiteConfig, MJLiteEnv
from mat_dcml_tpu.envs.mamujoco.obsk import (
    RobotGraph,
    build_obs_indices,
    get_parts_and_edges,
    joints_at_kdist,
)

__all__ = [
    "FaultyAgentWrapper",
    "MJLiteConfig",
    "MJLiteEnv",
    "RobotGraph",
    "build_obs_indices",
    "get_parts_and_edges",
    "joints_at_kdist",
]
