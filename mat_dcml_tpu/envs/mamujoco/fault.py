"""Agent fault injection for robustness studies.

The reference zeroes a chosen agent's torques during train/eval
(``mujoco_runner.py:13-20`` ``faulty_action``; swept over ``eval_faulty_node``
in ``train_mujoco.py:68-69``).  Here that is an env wrapper so the masking
happens INSIDE the jitted step — one compiled program per faulty node, no
host-side action surgery.
"""

from __future__ import annotations

import jax.numpy as jnp


class FaultyAgentWrapper:
    """Zeroes ``faulty_node``'s action before the wrapped step; -1 = no fault."""

    def __init__(self, env, faulty_node: int = -1):
        self.env = env
        self.faulty_node = faulty_node
        for attr in ("n_agents", "obs_dim", "share_obs_dim", "action_dim",
                     "episode_limit", "action_space"):
            if hasattr(env, attr):
                setattr(self, attr, getattr(env, attr))

    def reset(self, key, episode_idx=0):
        return self.env.reset(key, episode_idx)

    def step(self, state, action):
        if self.faulty_node >= 0:
            action = action.at[..., self.faulty_node, :].set(0.0)
        return self.env.step(state, action)
