"""AOT-compiled decode engine: fixed-shape programs, zero request-path compiles.

The TPU serving shape (PAPERS.md "Fine-Tuning and Serving Gemma on Cloud
TPU"): never let the compiler into the request path.  At startup the engine
lowers and compiles the deterministic decode forward — the *same* params-only
entry training rollouts use, :func:`mat_dcml_tpu.models.decode.serve_decode` —
once per batch bucket in a small ladder (default 1/8/32/128).  Steady-state
serving then only ever calls pre-compiled executables; the recompile detector
(:class:`telemetry.jit_instrument.InstrumentedJit`) is armed after warmup, so
any stray compile is counted loudly in ``steady_state_recompiles``.

A request is one joint observation: ``state (A, state_dim)``, ``obs (A,
obs_dim)``, optional ``available_actions (A, action_dim)``.  The engine
consumes host numpy stacked to a bucket's batch size and returns host numpy
actions/log-probs — device handles never leak to the batcher.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.models.decode import serve_decode
from mat_dcml_tpu.models.mat import MATConfig
from mat_dcml_tpu.telemetry import Telemetry, instrumented_jit


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine knobs.  ``buckets`` is the batch-size ladder, ascending; the
    batcher pads each dispatch up to the smallest bucket that fits."""

    buckets: Tuple[int, ...] = (1, 8, 32, 128)
    # "cached" (O(1)-per-step packed-KV decode, bit-exact to scan —
    # models/decode.py:cached_decode) | "scan" (exact sequential) | "spec"
    # (speculative draft-verify, bit-exact to scan — spec_decode) | "stride"
    # (block-commit approximation, benchmark-protocol parity only)
    decode_mode: str = "cached"
    stride: int = 2
    spec_block: int = 8           # speculative window K
    deterministic: bool = True
    # serving trunk precision: "f32" (exact — the training dtype) | "bf16"
    # (params cast at install time, trunk matmuls + KV cache in bfloat16;
    # heads/log_std/softmax stay f32).  A dtype flip is a *different
    # compiled program* — it must ride an engine (re)construction, never the
    # weight-swap path; the fleet gates a bf16 rollout behind the canary
    # controller with value-tolerance (not bit-parity) comparison.
    serve_dtype: str = "f32"

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("EngineConfig.buckets must be non-empty")
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be strictly ascending, got {self.buckets}")
        if self.decode_mode not in ("scan", "stride", "spec", "cached"):
            raise ValueError(
                "decode_mode must be 'cached', 'scan', 'stride' or 'spec', "
                f"got {self.decode_mode!r}"
            )
        if self.serve_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"serve_dtype must be 'f32' or 'bf16', got {self.serve_dtype!r}"
            )


class DecodeEngine:
    """Params + MATConfig in, pre-compiled fixed-shape decode programs out."""

    def __init__(
        self,
        params,
        cfg: MATConfig,
        engine_cfg: EngineConfig = EngineConfig(),
        telemetry: Optional[Telemetry] = None,
        log_fn=print,
        device=None,
    ):
        self.cfg = cfg
        self.engine_cfg = engine_cfg
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.log = log_fn
        # fleet mode pins each replica's engine to one device; every input
        # (params, key, request arrays) is placed there so the AOT executables
        # never see a cross-device argument
        self.device = device
        # the dtype the decode programs are compiled against: bf16 runs the
        # trunk (and KV cache) in bfloat16 while heads/log_std stay f32
        self._bf16 = engine_cfg.serve_dtype == "bf16"
        self._serve_cfg = (
            dataclasses.replace(cfg, dtype="bfloat16") if self._bf16 else cfg
        )
        self._zero_batches = {}            # bucket -> resident zero inputs
        self._params = self._prepare_params(params)  # resident once, all buckets
        ecfg = engine_cfg
        serve_cfg = self._serve_cfg

        self._spec = ecfg.decode_mode == "spec"
        self._cached = ecfg.decode_mode == "cached"

        def _decode(params, key, state, obs, avail):
            if ecfg.decode_mode == "spec":
                _, res, stats = serve_decode(
                    serve_cfg, params, key, state, obs, avail,
                    deterministic=ecfg.deterministic,
                    mode="spec", spec_block=ecfg.spec_block,
                    return_spec_stats=True,
                )
                return res.action, res.log_prob, stats
            _, res = serve_decode(
                serve_cfg, params, key, state, obs, avail,
                deterministic=ecfg.deterministic,
                mode=ecfg.decode_mode, stride=ecfg.stride,
            )
            return res.action, res.log_prob

        self._decode = instrumented_jit(
            _decode, "serve_decode", self.telemetry, log_fn
        )
        # deterministic serving still threads a key through the shared
        # signature (decode.serve_decode); one fixed resident key avoids a
        # fresh host->device transfer per dispatch
        self._key = self._put(jax.random.key(0))

    def _put(self, tree):
        if self.device is not None:
            return jax.device_put(tree, self.device)
        return jax.device_put(tree)

    def _prepare_params(self, params):
        """Device-place an artifact, casting the trunk to the serve dtype.

        Inbound params may arrive fsdp/tp-sharded from a training mesh (the
        live-push path); the AOT bucket programs run single-device, so such
        leaves gather to full values first — through the spec layer
        (``parallel.sharding.gather_replicated``), the inverse of
        ``place_params``, not an ad-hoc ``put_replicated``.  With
        ``serve_dtype="bf16"`` every float32 leaf is cast to bfloat16
        EXCEPT head and ``log_std`` leaves: logits/values feed distributions
        and the action std parameterization, which stay float32 by the Head
        contract (models/mat.py).  f32 serving is a pure device_put — training
        artifacts pass through bit-identically.
        """
        from mat_dcml_tpu.parallel.sharding import gather_replicated

        params = gather_replicated(params)
        if not self._bf16:
            return self._put(params)

        def cast(path, leaf):
            names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
            if "head" in names or "log_std" in names:
                return leaf
            if hasattr(leaf, "dtype") and leaf.dtype == jnp.float32:
                return leaf.astype(jnp.bfloat16)
            return leaf

        return self._put(jax.tree_util.tree_map_with_path(cast, params))

    # ------------------------------------------------------------- lifecycle

    @classmethod
    def from_export(
        cls,
        directory,
        engine_cfg: EngineConfig = EngineConfig(),
        telemetry: Optional[Telemetry] = None,
        log_fn=print,
    ) -> "DecodeEngine":
        """Build from a weights-only export (``checkpoint.export_policy``)."""
        from mat_dcml_tpu.training.checkpoint import load_policy

        params, cfg, space_meta = load_policy(directory)
        eng = cls(params, cfg, engine_cfg, telemetry, log_fn)
        eng.space_meta = space_meta
        return eng

    def warmup(self) -> None:
        """AOT-compile every bucket's program, then arm the recompile
        detector: from here on the request path must never compile."""
        import time

        for b in self.engine_cfg.buckets:
            t0 = time.perf_counter()
            out = self._decode(self._params, self._key, *self._zero_batch(b))
            jax.block_until_ready(out)
            self.log(
                f"[serving] bucket {b}: compiled in {time.perf_counter() - t0:.1f}s"
            )
        self._decode.mark_steady()
        tel = self.telemetry
        tel.gauge("serving_buckets", float(len(self.engine_cfg.buckets)))
        tel.gauge("serving_dtype_bits", 16.0 if self._bf16 else 32.0)
        if self._cached:
            # the packed-cache footprint is a static function of (bucket,
            # model shape, serve dtype) — publish the whole ladder's
            # arithmetic up front so capacity planning needs no live traffic
            from mat_dcml_tpu.models.modules import packed_cache_bytes

            cfg = self._serve_cfg
            for b in self.engine_cfg.buckets:
                tel.gauge(
                    f"decode_cache_bytes_b{b}",
                    float(packed_cache_bytes(
                        cfg.n_block, b, cfg.n_agent, cfg.n_embd, cfg.np_dtype
                    )),
                )

    def _zero_batch(self, b: int):
        # memoized per bucket: install_params warms the whole ladder on every
        # weight swap, and rebuilding the zero inputs each time paid a host
        # alloc + H2D transfer per bucket per swap for arrays that never change
        if b not in self._zero_batches:
            cfg = self.cfg
            self._zero_batches[b] = (
                self._put(jnp.zeros((b, cfg.n_agent, cfg.state_dim), jnp.float32)),
                self._put(jnp.zeros((b, cfg.n_agent, cfg.obs_dim), jnp.float32)),
                self._put(jnp.ones((b, cfg.n_agent, cfg.action_dim), jnp.float32)),
            )
        return self._zero_batches[b]

    # ---------------------------------------------------------- weight swap

    def install_params(self, params, warm: bool = True) -> int:
        """Hot weight-swap via atomic publish-then-swap.

        The new params are published to the device *next to* the live set,
        then (``warm=True``) every bucket program is run once against them
        while the old params keep serving — the shapes/dtypes of a healthy
        export hit the existing executables, so the warm pass compiles
        nothing.  Only after the ladder is warm does the resident reference
        flip, in one atomic attribute store; an in-flight :meth:`decode`
        captured its params reference at entry and never observes mixed
        weights.  Returns the number of compiles the warm pass triggered —
        0 in the healthy path; anything else means the artifact drifted
        (dtype/shape) and the caller should roll back before promoting.
        """
        before = self.compile_count()
        new_params = self._prepare_params(params)
        if warm:
            for b in self.engine_cfg.buckets:
                out = self._decode(new_params, self._key, *self._zero_batch(b))
                jax.block_until_ready(out)
        self._params = new_params   # atomic ref swap; old programs keep serving
        self.telemetry.count("serving_weight_swaps")
        return self.compile_count() - before

    # --------------------------------------------------------------- serving

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding ``n`` requests (largest bucket caps it)."""
        for b in self.engine_cfg.buckets:
            if n <= b:
                return b
        return self.engine_cfg.buckets[-1]

    @property
    def max_batch(self) -> int:
        return self.engine_cfg.buckets[-1]

    @property
    def min_bucket(self) -> int:
        return self.engine_cfg.buckets[0]

    def decode(
        self, state: np.ndarray, obs: np.ndarray, avail: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Run one pre-compiled bucket program.  Inputs must already be padded
        to a bucket size (the batcher's job); a non-bucket batch raises rather
        than silently compiling a new program."""
        import time

        b = state.shape[0]
        if b not in self.engine_cfg.buckets:
            raise ValueError(
                f"batch {b} is not a compiled bucket {self.engine_cfg.buckets}"
            )
        # chaos seam (after bucket validation — a malformed request is a
        # caller bug, never an injected fault): crash / hang / decode_error
        # faults targeted at this replica fire here
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_decode(getattr(self, "replica_id", None))
        t0 = time.perf_counter()
        # capture the resident params ONCE: install_params swaps the attribute
        # atomically, so one dispatch is entirely old or entirely new weights
        params = self._params
        # availability guards the discrete heads; the mask rows for padding
        # slots are all-ones so masked-softmax never sees a -inf-only row
        out = self._decode(
            params, self._key,
            self._put(jnp.asarray(state, jnp.float32)),
            self._put(jnp.asarray(obs, jnp.float32)),
            self._put(jnp.asarray(avail, jnp.float32)),
        )
        if self._spec:
            action, log_prob, stats = out
            # per-dispatch speculative health (padding rows included — they
            # run the same program and drag acceptance the same way)
            passes = np.asarray(stats.draft_passes)
            offered = float(np.asarray(stats.drafts_offered).sum())
            accepted = float(np.asarray(stats.drafts_accepted).sum())
            tel = self.telemetry
            tel.gauge("decode_spec_draft_passes", float(passes.mean()))
            tel.gauge("decode_spec_verify_passes",
                      float(np.asarray(stats.verify_passes).mean()))
            tel.gauge("decode_spec_accept_rate",
                      accepted / offered if offered > 0 else 1.0)
        else:
            action, log_prob = out
            if self._cached:
                # static per-program facts, re-asserted per dispatch so the
                # gauge family tracks the bucket actually serving: each step
                # attends i+1 positions of which i came from the cache, so
                # the cache serves sum(i)/sum(i+1) = (A-1)/(A+1) of positions
                A = self.cfg.n_agent
                tel = self.telemetry
                tel.gauge("decode_cache_steps", float(A))
                tel.gauge("decode_cache_hit_fraction", (A - 1) / (A + 1))
        result = (np.asarray(action), np.asarray(log_prob))
        # server-side decode latency sketch, host-materialized (the dispatch
        # itself is async): every decode path lands here — batcher dispatch,
        # health probe, canary shadow — but only once the recompile detector
        # is armed, so warmup compile seconds never poison the p99
        if self._decode._steady:
            self.telemetry.hist(
                "serving_decode_ms", (time.perf_counter() - t0) * 1e3)
        return result

    # ------------------------------------------------------------ accounting

    def compile_count(self) -> int:
        return self._decode.compile_count

    def steady_state_recompiles(self) -> float:
        return self.telemetry.counters.get("steady_state_recompiles", 0.0)
