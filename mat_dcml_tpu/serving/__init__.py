"""Policy serving: AOT decode engine + bucketed continuous batching.

The deployment half of the MAT-AS scheduler: ``engine.py`` holds a checkpoint
in an ahead-of-time-compiled decode program per batch bucket (zero compiles in
the request path), ``batcher.py`` packs concurrent requests into those
buckets, ``server.py`` fronts it with a stdlib JSON endpoint plus an
in-process client, and ``loadgen.py`` measures the whole stack (QPS,
latency percentiles, shed rate, bucket occupancy) through the telemetry
registry.  ``fleet.py`` replicates the engine+batcher pair behind a
load-aware router with fault tolerance and canary-gated hot weight pushes
(``rollout_ctl.py`` owns the gate and the export-watching pusher).  No
dependencies beyond the training stack itself.
"""

from mat_dcml_tpu.serving.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DeadlineExceededError,
    EngineFailureError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig
from mat_dcml_tpu.serving.fleet import EngineFleet, FleetConfig, FleetUnavailableError
from mat_dcml_tpu.serving.rollout_ctl import (
    RolloutConfig,
    RolloutController,
    WeightPusher,
)
from mat_dcml_tpu.serving.server import PolicyClient, PolicyServer

__all__ = [
    "BatcherConfig",
    "ContinuousBatcher",
    "DeadlineExceededError",
    "DecodeEngine",
    "EngineConfig",
    "EngineFailureError",
    "EngineFleet",
    "FleetConfig",
    "FleetUnavailableError",
    "PolicyClient",
    "PolicyServer",
    "QueueFullError",
    "RolloutConfig",
    "RolloutController",
    "ServingError",
    "WeightPusher",
]
