"""SLO-gated canary rollout control + weight pusher.

The decoupled actor/learner update flow (PAPERS.md "Podracer architectures"):
training exports weights-only artifacts on its own cadence; the serving fleet
pulls them in without ever dropping a request.  This module owns the *gate*
between those two worlds:

- :class:`RolloutController` is the state machine for one weight push
  (``IDLE -> CANARY -> ROLLING -> COMPLETE | ROLLED_BACK``).  During CANARY
  the first swapped replica serves **shadow traffic**: every compared request
  was answered by an incumbent replica (the client always gets the incumbent's
  bits) and replayed against the canary; the controller demands bit-parity on
  greedy actions (up to a configured mismatch fraction — successive PPO
  exports legitimately flip a few argmaxes) and tolerance-level agreement on
  the value/log-prob head, while :class:`telemetry.anomaly.CanaryTripwire`
  watches canary latency (vs the incumbent EMA baseline) and error count.
  Any trip produces a typed rollout anomaly record and a ``rollback``
  verdict; surviving ``canary_comparisons`` comparisons produces ``promote``.
- :class:`WeightPusher` watches an export root (``training/checkpoint.py``
  writes a monotonic ``generation`` into every policy manifest) and pushes
  each new generation into a live :class:`~mat_dcml_tpu.serving.fleet.
  EngineFleet`, one replica at a time, through the controller's gate.

Everything is stdlib + numpy; the fleet owns the actual weight swaps.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional

import numpy as np

from mat_dcml_tpu.telemetry.anomaly import Anomaly, CanaryTripwire, rollout_anomaly

IDLE = "idle"
CANARY = "canary"
ROLLING = "rolling"
COMPLETE = "complete"
ROLLED_BACK = "rolled_back"

PROMOTE = "promote"
ROLLBACK = "rollback"


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    canary_comparisons: int = 24    # shadow comparisons the gate demands
    max_mismatch_frac: float = 0.25  # tolerated greedy-action flips (PPO-sized
                                     # updates move a few argmaxes; a corrupt
                                     # or wrong-model artifact moves far more)
    value_rtol: float = 1e-4        # log-prob/value head tolerance vs incumbent
    value_atol: float = 1e-5
    # widened value tolerances for a bf16 serving trunk (EngineConfig.
    # serve_dtype="bf16"): bfloat16's 8-bit mantissa moves log-probs by
    # ~1e-2 relative on a healthy artifact, which the f32 tolerances would
    # read as a corrupt push.  The canary gate stays armed — a genuinely
    # wrong artifact overshoots these too — it just stops punishing the
    # precision the operator opted into.  Greedy-action comparison remains
    # exact either way (argmax flips are already budgeted by
    # max_mismatch_frac).
    bf16_value_rtol: float = 2e-2
    bf16_value_atol: float = 1e-3
    latency_factor: float = 4.0     # canary latency trip vs incumbent EMA
    latency_warmup: int = 8         # incumbent samples before the trip arms
    error_budget: int = 0           # canary request errors tolerated
    canary_timeout_s: float = 30.0  # give up (-> rollback) if comparisons stall
    synthetic_interval_s: float = 0.01  # pusher-driven shadow probe cadence

    def effective_for(self, serve_dtype: str) -> "RolloutConfig":
        """The config the gate should actually run with for an engine serving
        at ``serve_dtype`` — swaps the value tolerances to the bf16 pair when
        the trunk is lossy, identity otherwise."""
        if serve_dtype != "bf16":
            return self
        return dataclasses.replace(
            self, value_rtol=self.bf16_value_rtol,
            value_atol=self.bf16_value_atol,
        )


class RolloutController:
    """Gate for one push.  Thread-safe: live-traffic shadow comparisons arrive
    from replica dispatcher threads while the push thread polls the verdict."""

    def __init__(self, cfg: RolloutConfig, prior_generation: int,
                 new_generation: int, telemetry=None, log_fn=print):
        self.cfg = cfg
        self.prior_generation = prior_generation
        self.new_generation = new_generation
        self.telemetry = telemetry
        self.log = log_fn
        self.state = CANARY
        self.comparisons = 0
        self.parity_mismatches = 0
        self.value_mismatches = 0
        self.anomalies: List[Anomaly] = []
        self._tripwire = CanaryTripwire(
            latency_factor=cfg.latency_factor, warmup=cfg.latency_warmup,
            error_budget=cfg.error_budget, generation=new_generation,
            telemetry=telemetry,
        )
        self._lock = threading.Lock()
        self._verdict: Optional[str] = None
        self._decided = threading.Event()

    # ------------------------------------------------------------ observation

    def compare(self, incumbent_out, canary_out,
                incumbent_ms: float, canary_ms: float) -> None:
        """One shadow comparison: ``*_out`` are ``(action, log_prob)`` numpy
        pairs for the SAME request served by an incumbent and the canary."""
        inc_action, inc_logp = incumbent_out
        can_action, can_logp = canary_out
        with self._lock:
            if self._verdict is not None:
                return
            self.comparisons += 1
            if self.telemetry is not None:
                self.telemetry.count("rollout_canary_comparisons")
                # shadow-pair latency sketches: the gate's evidence becomes
                # scrapeable (/metrics) instead of living only in the verdict
                self.telemetry.hist("rollout_canary_ms", canary_ms)
                self.telemetry.hist("rollout_incumbent_ms", incumbent_ms)
            parity_ok = np.array_equal(
                np.asarray(inc_action), np.asarray(can_action))
            value_ok = bool(np.allclose(
                np.asarray(can_logp), np.asarray(inc_logp),
                rtol=self.cfg.value_rtol, atol=self.cfg.value_atol))
            if not parity_ok:
                self.parity_mismatches += 1
                self._count_mismatch("rollout_canary_parity",
                                     "greedy_action_mismatches",
                                     self.parity_mismatches)
            elif not value_ok:
                self.value_mismatches += 1
                self._count_mismatch("rollout_canary_value",
                                     "value_head_mismatches",
                                     self.value_mismatches)
            self._tripwire.observe_incumbent(incumbent_ms)
            trip = self._tripwire.observe_canary(canary_ms)
            if trip is not None:
                self.anomalies.append(trip)
                self._decide_locked(ROLLBACK, trip.kind)
                return
            self._maybe_decide_locked()

    def record_canary_error(self, exc: BaseException) -> None:
        with self._lock:
            if self._verdict is not None:
                return
            self.log(f"[rollout] canary request failed: {exc!r}")
            trip = self._tripwire.record_error()
            if trip is not None:
                self.anomalies.append(trip)
                self._decide_locked(ROLLBACK, trip.kind)

    def _count_mismatch(self, kind: str, signal: str, total: int) -> None:
        # every mismatch is recorded; the *budget* decides the verdict below
        if self.telemetry is not None:
            self.telemetry.count("rollout_canary_mismatches")
        self.anomalies.append(rollout_anomaly(
            kind, signal, float(total),
            float(self._mismatch_budget()), self.new_generation,
            self.telemetry,
        ))

    def _mismatch_budget(self) -> int:
        return int(self.cfg.max_mismatch_frac * self.cfg.canary_comparisons)

    def _maybe_decide_locked(self) -> None:
        budget = self._mismatch_budget()
        mismatches = self.parity_mismatches + self.value_mismatches
        if mismatches > budget:
            self._decide_locked(ROLLBACK, "mismatch budget exceeded "
                                f"({mismatches} > {budget})")
        elif self.comparisons >= self.cfg.canary_comparisons:
            self._decide_locked(PROMOTE, f"{self.comparisons} comparisons, "
                                f"{mismatches} mismatches <= budget {budget}")

    def _decide_locked(self, verdict: str, why: str) -> None:
        if self._verdict is None:
            self._verdict = verdict
            self.log(f"[rollout] gen {self.new_generation} canary verdict: "
                     f"{verdict} ({why})")
            self._decided.set()

    # ---------------------------------------------------------------- verdict

    def verdict(self) -> Optional[str]:
        return self._verdict

    def wait(self, timeout_s: Optional[float] = None) -> str:
        """Block until the gate decides; a timeout is a rollback (a canary
        that can't attract or survive its comparisons must not be promoted)."""
        timeout_s = timeout_s if timeout_s is not None else self.cfg.canary_timeout_s
        if not self._decided.wait(timeout=timeout_s):
            with self._lock:
                self._decide_locked(ROLLBACK, f"canary timed out after "
                                    f"{timeout_s:.1f}s with "
                                    f"{self.comparisons} comparisons")
        return self._verdict  # type: ignore[return-value]

    def summary(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "comparisons": self.comparisons,
                "parity_mismatches": self.parity_mismatches,
                "value_mismatches": self.value_mismatches,
                "verdict": self._verdict,
                "events": [a.to_record() for a in self.anomalies],
            }


class WeightPusher:
    """Polls an export root for new policy generations and pushes them.

    Training exports land under ``<watch_root>/<anything>/policy_manifest.json``
    with a monotonically increasing ``generation`` (``training/checkpoint.py``
    stamps it).  Each poll compares the newest on-disk generation against the
    fleet's installed one and, when newer, drives a full canary-gated push.
    ``poll_once`` is the synchronous unit (tests call it directly);
    ``start``/``stop`` wrap it in a daemon polling thread.
    """

    def __init__(self, fleet, watch_root, poll_interval_s: float = 2.0,
                 log_fn: Callable[[str], None] = print):
        self.fleet = fleet
        self.watch_root = watch_root
        self.poll_interval_s = poll_interval_s
        self.log = log_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pushes: List[dict] = []

    def poll_once(self) -> Optional[dict]:
        """One poll: returns the push report if a push happened, else None."""
        from mat_dcml_tpu.training.checkpoint import latest_export

        hit = latest_export(self.watch_root)
        if hit is None:
            return None
        path, generation = hit
        if generation <= self.fleet.current_generation:
            return None
        self.log(f"[rollout] pusher: found generation {generation} at {path} "
                 f"(fleet at {self.fleet.current_generation})")
        report = self.fleet.push_from_export(path)
        self.pushes.append(report)
        return report

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:   # a bad artifact must not kill the poller
                self.log(f"[rollout] pusher poll failed: {e!r}")
            self._stop.wait(timeout=self.poll_interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="weight-pusher", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
