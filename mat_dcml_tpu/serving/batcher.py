"""Host-side continuous batching over a bounded, thread-safe request queue.

The serving hot loop: request threads :meth:`ContinuousBatcher.submit` joint
observations and block on per-request futures; one dispatcher thread drains
the queue — waiting at most ``max_batch_wait_ms`` for stragglers once a first
request is in hand, or until the largest bucket fills — pads the batch to the
smallest fitting bucket, runs the pre-compiled engine program, and demuxes
per-request rows back into the futures.

Operational envelope:

- **admission control**: the queue is bounded (``max_queue``); an over-full
  submit sheds load immediately with a typed :class:`QueueFullError` instead
  of letting latency collapse for everyone already queued.
- **deadlines**: each request carries an absolute deadline; requests that
  expire while queued are failed with :class:`DeadlineExceededError` at
  dispatch time (never dispatched — a dead request must not occupy a bucket
  slot).
- **graceful degradation**: if a bucket dispatch raises, the batch is retried
  one request at a time at the smallest bucket; only requests that *still*
  fail get :class:`EngineFailureError`.  One poisoned request therefore can't
  take down its whole batch.

Everything is stdlib: ``threading`` + ``concurrent.futures.Future``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.serving.engine import DecodeEngine
from mat_dcml_tpu.telemetry import Telemetry
from mat_dcml_tpu.telemetry.tracing import TraceContext, Tracer


class ServingError(Exception):
    """Base class for typed serving rejections."""


class QueueFullError(ServingError):
    """Admission control: the bounded request queue is at capacity.

    Carries ``retry_after_s`` — the shed response's ``Retry-After`` hint,
    derived from the current queue depth and the EMA per-request service
    time at shed time."""

    def __init__(self, msg: str = "queue full", retry_after_s: int = 1):
        super().__init__(msg)
        self.retry_after_s = int(retry_after_s)


class DeadlineExceededError(ServingError):
    """The request's deadline elapsed before it could be dispatched."""


class EngineFailureError(ServingError):
    """The engine failed this request even at the degraded smallest bucket."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    max_queue: int = 256          # bounded admission; beyond this, shed
    max_batch_wait_ms: float = 2.0  # straggler window after the first request
    default_timeout_s: Optional[float] = None  # per-request deadline default


@dataclasses.dataclass
class _Request:
    state: np.ndarray             # (A, state_dim)
    obs: np.ndarray               # (A, obs_dim)
    avail: np.ndarray             # (A, action_dim)
    deadline: Optional[float]     # absolute time.monotonic() or None
    future: Future
    enqueued_at: float
    trace: Optional[TraceContext] = None  # sampled span tree (or None)
    owns_trace: bool = False      # minted by this batcher => finished here
    enqueued_pc: float = 0.0      # perf_counter twin of enqueued_at (spans
                                  # and trace offsets share one clock)


class ContinuousBatcher:
    def __init__(
        self,
        engine: DecodeEngine,
        cfg: BatcherConfig = BatcherConfig(),
        telemetry: Optional[Telemetry] = None,
        log_fn=print,
        tracer: Optional[Tracer] = None,
    ):
        self.engine = engine
        self.cfg = cfg
        self.telemetry = telemetry if telemetry is not None else engine.telemetry
        self.log = log_fn
        self.tracer = tracer
        self._queue: deque[_Request] = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._ema_ms_per_req: Optional[float] = None  # service-time estimate
        self._ema_queue_wait_ms: Optional[float] = None  # Retry-After source
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------- client side

    def submit(
        self,
        state: np.ndarray,
        obs: np.ndarray,
        avail: Optional[np.ndarray] = None,
        timeout_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> Future:
        """Enqueue one joint observation; returns a future resolving to
        ``(action, log_prob)`` numpy arrays (``(A, act_out)``/``(A,
        act_prob)``), or raising a typed :class:`ServingError`.

        ``trace`` carries a sampled span tree minted at ingress (server or
        fleet); when None and the batcher owns a tracer, one is minted here so
        a bare batcher still produces trees."""
        cfg = self.engine.cfg
        state = np.asarray(state, np.float32)
        obs = np.asarray(obs, np.float32)
        if state.shape != (cfg.n_agent, cfg.state_dim):
            raise ValueError(
                f"state shape {state.shape} != {(cfg.n_agent, cfg.state_dim)}"
            )
        if obs.shape != (cfg.n_agent, cfg.obs_dim):
            raise ValueError(f"obs shape {obs.shape} != {(cfg.n_agent, cfg.obs_dim)}")
        if avail is None:
            avail = np.ones((cfg.n_agent, cfg.action_dim), np.float32)
        else:
            avail = np.asarray(avail, np.float32)
            if avail.shape != (cfg.n_agent, cfg.action_dim):
                raise ValueError(
                    f"available_actions shape {avail.shape} != "
                    f"{(cfg.n_agent, cfg.action_dim)}"
                )
        timeout_s = timeout_s if timeout_s is not None else self.cfg.default_timeout_s
        # trace ownership: a trace minted HERE is finished here on every exit
        # path; a foreign trace (fleet/server ingress) is only finished on
        # success — its owner may retry a failed attempt on a sibling replica
        # under the same trace id.
        owns_trace = False
        if trace is None and self.tracer is not None:
            trace = self.tracer.start_trace("serving")
            owns_trace = trace is not None
        now = time.monotonic()
        req = _Request(
            state=state, obs=obs, avail=avail,
            deadline=(now + timeout_s) if timeout_s is not None else None,
            future=Future(), enqueued_at=now, trace=trace,
            owns_trace=owns_trace,
            enqueued_pc=(trace.t0 if owns_trace else time.perf_counter())
            if trace is not None else 0.0,
        )
        with self._not_empty:
            if self._closed:
                raise ServingError("batcher is closed")
            if len(self._queue) >= self.cfg.max_queue:
                self.telemetry.count("serving_shed")
                if owns_trace:
                    trace.finish(status="shed")
                raise QueueFullError(
                    f"queue at capacity ({self.cfg.max_queue}); shedding",
                    retry_after_s=self._retry_after_locked(),
                )
            self._queue.append(req)
            self.telemetry.count("serving_requests")
            self.telemetry.gauge("serving_queue_depth", float(len(self._queue)))
            self._not_empty.notify()
        return req.future

    def _retry_after_locked(self) -> int:
        """Seconds a shed client should back off, floored at 1s (callers hold
        ``_lock``).  Primary source: the EMA of *measured* server-side queue
        wait (what a just-admitted request actually waited before dispatch) —
        honest under bucket batching, where the old queue-depth x service-time
        product overestimates by up to the bucket width.  Before any request
        has been served, fall back to that coarse product."""
        if self._ema_queue_wait_ms is not None:
            return max(1, int(self._ema_queue_wait_ms / 1e3 + 0.999))
        ms = self._ema_ms_per_req if self._ema_ms_per_req is not None else 10.0
        est_s = len(self._queue) * ms / 1e3
        return max(1, int(est_s + 0.999))

    def retry_after_s(self) -> int:
        with self._lock:
            return self._retry_after_locked()

    def stats_snapshot(self) -> dict:
        """Counter/gauge snapshot taken under the batcher lock, so a reader
        racing the submit path can't observe torn values (e.g. a bumped
        ``serving_requests`` without its matching ``serving_queue_depth``)."""
        with self._lock:
            return {
                "counters": dict(self.telemetry.counters),
                "gauges": dict(self.telemetry._gauges),
                "queue_depth": len(self._queue),
            }

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the dispatcher; pending requests fail with ServingError."""
        with self._not_empty:
            if self._closed:
                return
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._not_empty.notify_all()
        for req in pending:
            req.future.set_exception(ServingError("batcher closed"))
        self._thread.join(timeout=timeout_s)

    # ------------------------------------------------------- dispatcher side

    def _collect_batch(self):
        """Block for the first request, then linger ``max_batch_wait_ms`` (or
        until the largest bucket fills) for stragglers."""
        with self._not_empty:
            while not self._queue and not self._closed:
                self._not_empty.wait(timeout=0.1)
            if self._closed:
                return None
            wait_s = self.cfg.max_batch_wait_ms / 1e3
            deadline = time.monotonic() + wait_s
            while len(self._queue) < self.engine.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._not_empty.wait(timeout=remaining)
                if self._closed:
                    return None
            n = min(len(self._queue), self.engine.max_batch)
            batch = [self._queue.popleft() for _ in range(n)]
            self.telemetry.gauge("serving_queue_depth", float(len(self._queue)))
            return batch

    def _dispatch_loop(self):
        while True:
            # chaos seam: a queue_stall fault sleeps HERE, outside the queue
            # lock, so arrivals keep queueing and shed/429 behavior under a
            # stalled dispatcher is exercised honestly
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.on_dequeue()
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except Exception as e:  # never kill the dispatcher thread
                self.log(f"[serving] dispatcher error: {e!r}")
                for req in batch:
                    if not req.future.done():
                        if req.trace is not None and req.owns_trace:
                            req.trace.finish(status="error")
                        req.future.set_exception(EngineFailureError(repr(e)))

    def _expire(self, batch):
        """Fail queued-past-deadline requests; return the live remainder."""
        now = time.monotonic()
        live = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                self.telemetry.count("serving_deadline_misses")
                if req.trace is not None and req.owns_trace:
                    req.trace.finish(status="deadline")
                req.future.set_exception(DeadlineExceededError(
                    f"deadline exceeded after {now - req.enqueued_at:.3f}s in queue"
                ))
            elif req.future.done():
                pass  # client gave up (cancelled) — don't waste a slot
            else:
                live.append(req)
        return live

    def _run_bucket(self, batch, degraded: bool = False):
        """Pad ``batch`` to its bucket, run the engine, demux into futures.

        ``degraded`` marks the single-request retry path: its successes count
        under ``serving_degraded_ok`` instead of the normal served counters,
        so fleet health scoring can distinguish a replica limping through
        one-by-one retries from one serving full buckets."""
        n = len(batch)
        b = self.engine.bucket_for(n)
        pad = b - n
        t_assemble = time.perf_counter()
        now_mono = time.monotonic()
        waits_ms = [(now_mono - r.enqueued_at) * 1e3 for r in batch]
        state = np.stack([r.state for r in batch] + [batch[-1].state] * pad)
        obs = np.stack([r.obs for r in batch] + [batch[-1].obs] * pad)
        avail = np.stack([r.avail for r in batch] + [batch[-1].avail] * pad)
        t0 = time.perf_counter()
        action, log_prob = self.engine.decode(state, obs, avail)
        t1 = time.perf_counter()
        dt = t1 - t0
        tel = self.telemetry
        with self._lock:   # EMAs feed Retry-After; read under the same lock
            per_req = dt * 1e3 / max(n, 1)
            self._ema_ms_per_req = per_req if self._ema_ms_per_req is None \
                else 0.8 * self._ema_ms_per_req + 0.2 * per_req
            for w in waits_ms:
                self._ema_queue_wait_ms = w if self._ema_queue_wait_ms is None \
                    else 0.8 * self._ema_queue_wait_ms + 0.2 * w
        if degraded:
            tel.count("serving_degraded_ok", float(n))
        else:
            tel.count("serving_batches")
            tel.count(f"serving_bucket_{b}")      # bucket-occupancy histogram
            tel.observe("serving_batch_fill", n / b)
            tel.observe("serving_engine_ms", dt * 1e3)
        for w in waits_ms:
            tel.hist("serving_queue_wait_ms", w)
        now = time.monotonic()
        # spans are recorded (and owned traces finished) BEFORE set_result:
        # done-callbacks run synchronously in set_result, so a fleet owner
        # finishing the trace must already see the demux span.
        t_done = time.perf_counter()
        for i, req in enumerate(batch):
            tel.observe("serving_latency_ms", (now - req.enqueued_at) * 1e3)
            tr = req.trace
            if tr is not None:
                # contiguous tiling of [trace start, t_done): the child spans
                # sum exactly to the root end-to-end (test-pinned invariant)
                tr.add_span("queue_wait", req.enqueued_pc, t_assemble)
                tr.add_span("pad", t_assemble, t0, bucket=b, batch=n, pad=pad)
                tr.add_span("device_decode", t0, t1, bucket=b,
                            degraded=degraded)
                tr.add_span("demux", t1, t_done)
                if req.owns_trace:
                    tr.finish(end=t_done, status="ok", bucket=b)
            if not req.future.done():
                req.future.set_result((action[i], log_prob[i]))

    def _dispatch(self, batch):
        batch = self._expire(batch)
        if not batch:
            return
        try:
            self._run_bucket(batch)
        except Exception as e:
            # graceful degradation: retry one-by-one at the smallest bucket —
            # a poisoned request fails alone instead of sinking its batch
            self.telemetry.count("serving_degraded_batches")
            self.log(f"[serving] bucket dispatch failed ({e!r}); degrading to "
                     f"bucket {self.engine.min_bucket} singles")
            for req in batch:
                if req.future.done():
                    continue
                try:
                    self._run_bucket([req], degraded=True)
                except Exception as e1:
                    self.telemetry.count("serving_degraded_failed")
                    self.telemetry.count("serving_engine_failures")
                    if req.trace is not None and req.owns_trace:
                        req.trace.finish(status="error")
                    req.future.set_exception(EngineFailureError(repr(e1)))
