"""Load generator + serving benchmark reporting.

Drives a :class:`PolicyClient` (in-process) with either an **open-loop**
arrival process (fixed target QPS, Poisson-ish via fixed inter-arrival
spacing; measures the latency the *system* imposes under an offered load,
sheds and all) or a **closed-loop** worker pool (``concurrency`` blocking
callers; measures max sustainable throughput).  Reports sustained QPS,
p50/p95/p99 latency, shed rate, deadline-miss rate, and bucket-occupancy
through the telemetry registry into ``metrics.jsonl`` — the same stream the
trainer writes, so BENCH tooling consumes serving records unchanged
(``scripts/check_metrics_schema.py`` knows the ``serving_*`` family).

HTTP mode additionally accepts MULTIPLE endpoints
(:class:`MultiTargetClient`; repeatable ``--target`` on the CLI): the same
loadgen then drives N host fleets directly or the federation router
(:mod:`mat_dcml_tpu.serving.router`) with one URL per host, round-robining
the offered load and attributing client overhead per endpoint
(``serving_target_<i>_client_overhead_ms`` next to the merged
``serving_client_overhead_ms``) — which is how the bench compares
router-fronted vs direct serving under a matched arrival process.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from mat_dcml_tpu.serving.batcher import ServingError
from mat_dcml_tpu.serving.server import PolicyClient
from mat_dcml_tpu.telemetry.registry import HistogramSketch, Telemetry


def synth_requests(cfg, n: int, seed: int = 0):
    """Synthetic joint observations shaped for ``cfg`` (MATConfig): the DCML
    serving payload without needing the env — availability keeps action 0
    legal so every request is valid."""
    rng = np.random.default_rng(seed)
    states = rng.normal(size=(n, cfg.n_agent, cfg.state_dim)).astype(np.float32)
    obs = rng.normal(size=(n, cfg.n_agent, cfg.obs_dim)).astype(np.float32)
    avail = np.ones((n, cfg.n_agent, cfg.action_dim), np.float32)
    if cfg.action_dim > 1:
        avail[:, :, 1:] = (
            rng.random((n, cfg.n_agent, cfg.action_dim - 1)) > 0.3
        ).astype(np.float32)
    return states, obs, avail


def percentiles(latencies_ms: List[float]) -> Dict[str, float]:
    if not latencies_ms:
        return {"serving_p50_ms": 0.0, "serving_p95_ms": 0.0, "serving_p99_ms": 0.0}
    arr = np.asarray(latencies_ms)
    return {
        "serving_p50_ms": float(np.percentile(arr, 50)),
        "serving_p95_ms": float(np.percentile(arr, 95)),
        "serving_p99_ms": float(np.percentile(arr, 99)),
    }


def _target_name(name: str, i: int) -> str:
    """``serving_client_overhead_ms`` -> ``serving_target_<i>_client_overhead_ms``
    (family prefix preserved so the schema checker keeps one vocabulary)."""
    bare = name[len("serving_"):] if name.startswith("serving_") else name
    return f"serving_target_{i}_{bare}"


class _MultiTargetTelemetry(Telemetry):
    """Facade registry over the per-target client registries.

    Each flush re-derives state from the targets: bare ``serving_client_*``
    names carry the merged view (so single-target consumers read the record
    unchanged — sketches merge exactly, per :class:`HistogramSketch`), and
    every name is re-emitted per endpoint under ``serving_target_<i>_*`` so
    one record shows which endpoint imposed what overhead."""

    def __init__(self, clients: Sequence) -> None:
        super().__init__()
        self._clients = list(clients)

    def _sync(self) -> None:
        self.counters = {}
        self.hists = {}
        for i, c in enumerate(self._clients):
            tel = c.telemetry
            for name, v in dict(tel.counters).items():
                self.counters[name] = self.counters.get(name, 0.0) + v
                self.counters[_target_name(name, i)] = v
            for name, sk in dict(tel.hists).items():
                merged = self.hists.get(name)
                if merged is None:
                    merged = self.hists[name] = HistogramSketch()
                merged.merge(sk)
                mine = self.hists[_target_name(name, i)] = HistogramSketch()
                mine.merge(sk)

    def flush(self) -> Dict[str, float]:
        self._sync()
        return super().flush()


class MultiTargetClient:
    """Round-robin fan-out over N ``/v1/act`` endpoints.

    Duck-types the slice of :class:`PolicyClient` that :func:`run_load`
    consumes (``act`` / ``cfg`` / ``telemetry``).  Every target gets its own
    :class:`~mat_dcml_tpu.serving.server.HttpPolicyClient` with a private
    registry, so per-endpoint client overhead stays attributable; the facade
    registry merges them on flush.  With one target this degenerates to a
    plain HTTP client (plus the ``serving_target_0_*`` echo), so the same
    loadgen invocation shape drives a single fleet, N fleets directly, or
    the federation router.
    """

    def __init__(self, targets: Sequence[str], cfg=None, tracer=None,
                 timeout_s: float = 60.0) -> None:
        from mat_dcml_tpu.serving.server import HttpPolicyClient

        urls = [str(t).rstrip("/") for t in targets if str(t).strip()]
        if not urls:
            raise ValueError("MultiTargetClient needs at least one target")
        self.targets = urls
        self.cfg = cfg
        self.clients = [HttpPolicyClient(url, cfg=cfg, tracer=tracer,
                                         timeout_s=timeout_s)
                        for url in urls]
        self.telemetry = _MultiTargetTelemetry(self.clients)
        self._next = itertools.count()   # next() is atomic under the GIL

    def act(self, state, obs, available_actions=None,
            timeout_s: Optional[float] = None):
        i = next(self._next) % len(self.clients)
        return self.clients[i].act(state, obs, available_actions,
                                   timeout_s=timeout_s)


def run_load(
    client: PolicyClient,
    n_requests: int,
    concurrency: int = 8,
    target_qps: Optional[float] = None,
    timeout_s: Optional[float] = None,
    seed: int = 0,
    slo_ms: Optional[float] = None,
    n_clients: int = 1,
) -> Dict[str, float]:
    """Fire ``n_requests`` at the stack and return a flat serving record.

    ``target_qps=None`` = closed loop (each of ``concurrency`` workers fires
    its next request as soon as the previous returns); a number = open loop
    (requests launched on schedule from a thread pool regardless of
    completions, so queueing/shedding behavior is exercised honestly).

    ``n_clients > 1`` (open loop only) splits the offered load across that
    many independent dispatcher threads, each keeping its own schedule — a
    single python thread can't launch fast enough to saturate a fleet, and
    real traffic is many clients, not one metronome.

    ``slo_ms`` adds goodput accounting: a request counts toward
    ``serving_goodput_slo`` (fraction of *offered* load) and
    ``serving_goodput_qps`` only if it succeeded AND finished inside the SLO
    — sheds, errors, and slow successes all count against goodput alike.

    ``client`` may also be an :class:`~mat_dcml_tpu.serving.server
    .HttpPolicyClient` (no batcher — the engine lives in another process):
    request shapes come from ``client.cfg`` and the flushed registry is the
    client's own, which carries the ``serving_client_overhead_ms`` histogram
    (client root span minus server-side ``request`` span).
    """
    batcher = getattr(client, "batcher", None)
    cfg = batcher.engine.cfg if batcher is not None else client.cfg
    states, obs, avail = synth_requests(cfg, n_requests, seed)
    latencies: List[float] = []
    outcomes = {"ok": 0, "shed": 0, "deadline": 0, "error": 0, "good": 0}
    lock = threading.Lock()

    def fire(i: int) -> None:
        t0 = time.perf_counter()
        try:
            client.act(states[i], obs[i], avail[i], timeout_s=timeout_s)
        except ServingError as e:
            kind = type(e).__name__
            with lock:
                if "QueueFull" in kind:
                    outcomes["shed"] += 1
                elif "Deadline" in kind:
                    outcomes["deadline"] += 1
                else:
                    outcomes["error"] += 1
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        with lock:
            outcomes["ok"] += 1
            if slo_ms is None or dt_ms <= slo_ms:
                outcomes["good"] += 1
            latencies.append(dt_ms)

    t_start = time.perf_counter()
    if target_qps is None:
        idx = iter(range(n_requests))
        idx_lock = threading.Lock()

        def worker():
            while True:
                with idx_lock:
                    i = next(idx, None)
                if i is None:
                    return
                fire(i)

        threads = [threading.Thread(target=worker) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:
        n_clients = max(1, int(n_clients))
        period = n_clients / target_qps   # per-client inter-arrival spacing
        threads: List[threading.Thread] = []
        threads_lock = threading.Lock()

        def dispatcher(c: int) -> None:
            # client c owns requests c, c+n_clients, ...; staggered start so
            # the aggregate arrival process interleaves instead of bursting
            for k, i in enumerate(range(c, n_requests, n_clients)):
                due = t_start + (c / n_clients) * period + k * period
                lag = due - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                t = threading.Thread(target=fire, args=(i,))
                t.start()
                with threads_lock:
                    threads.append(t)

        dispatchers = [threading.Thread(target=dispatcher, args=(c,))
                       for c in range(n_clients)]
        for d in dispatchers:
            d.start()
        for d in dispatchers:
            d.join()
        for t in threads:
            t.join()
    elapsed = time.perf_counter() - t_start

    record: Dict[str, float] = {
        "serving_qps": outcomes["ok"] / max(elapsed, 1e-9),
        "serving_offered_qps": n_requests / max(elapsed, 1e-9),
        "serving_ok": float(outcomes["ok"]),
        "serving_shed_rate": outcomes["shed"] / max(n_requests, 1),
        "serving_deadline_miss_rate": outcomes["deadline"] / max(n_requests, 1),
        "serving_error_rate": outcomes["error"] / max(n_requests, 1),
        "serving_wall_s": elapsed,
    }
    if slo_ms is not None:
        record["serving_slo_ms"] = float(slo_ms)
        record["serving_goodput_slo"] = outcomes["good"] / max(n_requests, 1)
        record["serving_goodput_qps"] = outcomes["good"] / max(elapsed, 1e-9)
    record.update(percentiles(latencies))
    tel = batcher.telemetry if batcher is not None else client.telemetry
    # bucket-occupancy histogram + engine-side aggregates ride along —
    # including the server-side serving_queue_wait_ms/serving_decode_ms
    # latency sketches, which complement the client-side percentiles above
    # (HTTP mode flushes the client registry instead: the client-overhead
    # histogram and client-side error counters)
    record.update(tel.flush())
    # fleet mode: merged per-replica sketches (honest fleet-wide p50/p95/p99)
    # plus live SLO burn gauges ride along through fleet_record
    fleet_rec = getattr(batcher, "fleet_record", None)
    if fleet_rec is not None:
        record.update(fleet_rec())
    return record


def write_serving_record(run_dir, record: Dict[str, float]) -> None:
    """Append the serving record to ``<run_dir>/metrics.jsonl`` via the
    training stack's writer (same schema pipeline)."""
    from mat_dcml_tpu.utils.metrics import MetricsWriter

    writer = MetricsWriter(run_dir)
    writer.write(record)
    writer.close()


class _ShapeCfg:
    """Request-shape stub for HTTP mode (``synth_requests`` needs only the
    four dims; the model itself lives in the server process)."""

    def __init__(self, n_agent, obs_dim, state_dim, action_dim):
        self.n_agent, self.obs_dim = n_agent, obs_dim
        self.state_dim, self.action_dim = state_dim, action_dim


def main(argv=None) -> None:
    """CLI: load-test a policy export end to end — engine in-process, or a
    remote :class:`PolicyServer` over HTTP with trace propagation.

    Usage: python -m mat_dcml_tpu.serving.loadgen --policy_dir <export>
           [--requests 2000] [--concurrency 16] [--qps 0 = closed-loop]
           [--buckets 1,8,32,128] [--run_dir results/serving]

    HTTP mode (no local engine; ``--policy_dir`` not needed):
           --server_url http://host:port --shape N_AGENT,OBS,STATE,ACT
           [--obs_port 9100]   # join the scrape plane (telemetry/remote.py)

    Federated HTTP mode — repeat ``--target`` for each endpoint (host fleets
    driven directly, or the one router URL; ``--server_url`` is the
    single-target alias).  Load round-robins across targets and the record
    carries per-target ``serving_target_<i>_client_overhead_ms`` histograms
    next to the merged client-overhead sketch:
           --target http://h0:8420 --target http://h1:8420 --shape ...
    """
    import argparse

    from mat_dcml_tpu.serving.batcher import BatcherConfig, ContinuousBatcher
    from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig

    p = argparse.ArgumentParser(description="MAT serving load generator")
    p.add_argument("--policy_dir", default=None)
    p.add_argument("--server_url", default=None,
                   help="drive a remote PolicyServer over HTTP instead of an "
                        "in-process engine (traceparent propagation on); "
                        "alias for a single --target")
    p.add_argument("--target", action="append", default=None, dest="targets",
                   metavar="URL",
                   help="repeatable: a /v1/act base URL (a host fleet, or "
                        "the federation router).  Two or more targets "
                        "round-robin the offered load and emit per-target "
                        "client-overhead histograms")
    p.add_argument("--shape", default=None,
                   help="HTTP mode request shape: n_agent,obs_dim,state_dim,"
                        "action_dim")
    p.add_argument("--obs_port", type=int, default=0,
                   help="serve this process's telemetry at "
                        "http://127.0.0.1:<port>/telemetry.json "
                        "(0 = off, -1 = ephemeral; bound port printed as "
                        "'OBS_PORT <n>')")
    p.add_argument("--linger_s", type=float, default=0.0,
                   help="keep the obs sidecar up this long after the load "
                        "finishes (lets a collector take a final scrape)")
    p.add_argument("--requests", type=int, default=2000)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--qps", type=float, default=0.0, help="0 = closed loop")
    p.add_argument("--clients", type=int, default=1,
                   help="open-loop dispatcher threads sharing the offered load")
    p.add_argument("--slo_ms", type=float, default=0.0,
                   help="goodput SLO in ms; 0 disables goodput accounting")
    p.add_argument("--timeout_s", type=float, default=0.0, help="0 = none")
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch_wait_ms", type=float, default=2.0)
    p.add_argument("--run_dir", default=None,
                   help="append the record to <run_dir>/metrics.jsonl")
    p.add_argument("--trace_sample", type=float, default=0.0,
                   help="trace this fraction of requests to "
                        "<run_dir>/trace.jsonl (0 disables)")
    p.add_argument("--trace_max_mb", type=float, default=64.0)
    args = p.parse_args(argv)

    tracer = None
    if args.trace_sample > 0 and args.run_dir:
        from mat_dcml_tpu.telemetry.tracing import Tracer

        tracer = Tracer(args.run_dir, sample=args.trace_sample,
                        max_mb=args.trace_max_mb)
    engine = batcher = None
    urls = ([args.server_url] if args.server_url else []) \
        + list(args.targets or [])
    if urls:
        # HTTP mode: the engine lives in the server process(es); this process
        # is a pure client minting root spans + injecting traceparent headers
        from mat_dcml_tpu.serving.server import HttpPolicyClient

        if not args.shape:
            p.error("--server_url/--target needs "
                    "--shape n_agent,obs,state,action")
        dims = [int(x) for x in args.shape.split(",")]
        if len(dims) != 4:
            p.error("--shape takes exactly four comma-separated ints")
        if len(urls) == 1:
            client = HttpPolicyClient(urls[0], cfg=_ShapeCfg(*dims),
                                      tracer=tracer)
        else:
            client = MultiTargetClient(urls, cfg=_ShapeCfg(*dims),
                                       tracer=tracer)
    else:
        if not args.policy_dir:
            p.error("--policy_dir is required without --server_url")
        engine = DecodeEngine.from_export(
            args.policy_dir,
            EngineConfig(buckets=tuple(int(b) for b in args.buckets.split(","))),
        )
        engine.warmup()
        batcher = ContinuousBatcher(
            engine, BatcherConfig(max_batch_wait_ms=args.max_batch_wait_ms),
            tracer=tracer,
        )
        client = PolicyClient(batcher)
    sidecar = None
    if args.obs_port:
        from mat_dcml_tpu.telemetry.remote import TelemetrySidecar

        if batcher is not None:
            tel = batcher.telemetry
        elif isinstance(client, MultiTargetClient):
            # one labelled registry per endpoint joins the scrape plane (the
            # merged facade only materializes its state on flush)
            tel = {f"target{i}": c.telemetry
                   for i, c in enumerate(client.clients)}
        else:
            tel = client.telemetry
        sidecar = TelemetrySidecar(tel, port=max(0, args.obs_port),
                                   label="loadgen")
        sidecar.start()
        print(f"OBS_PORT {sidecar.port}", flush=True)
    record = run_load(
        client,
        n_requests=args.requests,
        concurrency=args.concurrency,
        target_qps=args.qps or None,
        timeout_s=args.timeout_s or None,
        slo_ms=args.slo_ms or None,
        n_clients=args.clients,
    )
    if engine is not None:
        record["steady_state_recompiles"] = engine.steady_state_recompiles()
    import json as _json

    print(_json.dumps(record))
    if args.run_dir:
        write_serving_record(args.run_dir, record)
    if sidecar is not None:
        if args.linger_s > 0:
            time.sleep(args.linger_s)
        sidecar.stop()
    if batcher is not None:
        batcher.close()


if __name__ == "__main__":
    main()
