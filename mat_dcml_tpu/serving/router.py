"""Cross-host serving federation: a router tier that turns N fleets into one
service.

One :class:`~mat_dcml_tpu.serving.fleet.EngineFleet` is a single process — N
replicas on one host's devices.  This module adds the tier above it (the
Gemma-on-TPU topology from PAPERS.md: replica-per-chip, router-per-host,
federation above): a stdlib-HTTP router that fronts N *host* endpoints, each
a ``PolicyServer`` running a fleet (``scripts/serve_fleet.py``), and speaks
the same JSON ``/v1/act`` protocol on both sides — so every existing client
(``HttpPolicyClient``, the loadgen, the soak harness) drives a federation
exactly like a single host.

**Routing** — least-outstanding-requests over the healthy host pool with a
health-penalty score (a host that has been failing requests ranks behind a
clean sibling at equal depth) and a rotating tie-break, mirroring the fleet's
replica router one level up.

**Fault tolerance** — a host that refuses a connection, times out, or
returns a 5xx is marked UNHEALTHY and the in-flight request is retried on a
sibling host with bounded jittered exponential backoff (safe because decode
is pure: a duplicate attempt returns identical bits).  A background prober
re-polls ``GET /healthz`` on every host; ``probe_successes`` consecutive
passes readmit an unhealthy host — the fleet's UNHEALTHY→probe→readmit state
machine at host granularity.  An upstream 429 is *saturation*, not sickness:
the host stays healthy, the router tries a sibling, and only when every host
has shed does the client see an honest 429 whose ``Retry-After`` is the
largest upstream hint (the earliest instant at which the WHOLE service could
plausibly have capacity again — any smaller hint would bounce the client off
the still-saturated slowest host).  Zero healthy hosts is a brownout 429
derived from one probe-readmission cycle, exactly like the fleet's.

**Tracing** — the router continues an inbound ``traceparent`` (or mints its
own sampled root) and injects the SAME id upstream, so one trace id spans
client → router → host fleet → replica; each upstream try is a ``route``
span with the host id attached, and ``obs_report.py --source`` stitches the
three tiers.

**Generation-consistent push** — :meth:`ServiceRouter.push` rolls a new
weight generation across hosts one at a time.  Each host runs its own
canary gate (``RolloutController``); before the roll starts, the router
scrapes every host's ``/telemetry.json`` and vetoes on any burning
``slo_*_burn`` gauge (never widen a rollout into a burning service).  Any
host failing mid-roll — gate verdict, HTTP error, or death — aborts to a
full-service rollback of every already-promoted host, so no two hosts ever
serve different generations steady-state.  ``router_generation_split`` is
the flagged invariant.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mat_dcml_tpu.serving.batcher import (
    DeadlineExceededError,
    EngineFailureError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
from mat_dcml_tpu.telemetry.anomaly import AnomalyConfig, AnomalyDetector
from mat_dcml_tpu.telemetry.propagate import TRACEPARENT_HEADER
from mat_dcml_tpu.telemetry.propagate import extract as extract_traceparent
from mat_dcml_tpu.telemetry.propagate import inject as inject_traceparent
from mat_dcml_tpu.telemetry.registry import Telemetry
from mat_dcml_tpu.telemetry.remote import (
    SNAPSHOT_PATH,
    build_snapshot,
    run_identity,
)
from mat_dcml_tpu.telemetry.slo import SLOMonitor
from mat_dcml_tpu.telemetry.timeseries import TIMESERIES_PATH, RollupStore
from mat_dcml_tpu.telemetry.tracing import Tracer

# host health states: the fleet's replica-level vocabulary, one level up
# (no canary state — canarying happens inside each host's fleet)
HEALTHY = "healthy"
UNHEALTHY = "unhealthy"

_STATE_CODE = {UNHEALTHY: 0.0, HEALTHY: 1.0}

# network-level failures that mean "this HOST is gone", not "this request is
# bad" — connection refused/reset, DNS, socket timeout, torn HTTP framing
_HOST_ERRORS = (urllib.error.URLError, http.client.HTTPException,
                ConnectionError, OSError, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    max_retries: int = 2              # sibling-host retries per request
    backoff_base_ms: float = 5.0      # jittered exponential backoff base
    attempt_timeout_s: float = 60.0   # per-attempt HTTP budget (no deadline)
    probe_interval_s: float = 0.25    # host /healthz probe cadence
    probe_successes: int = 2          # consecutive passes before readmission
    probe_timeout_s: float = 2.0      # per-probe HTTP budget
    scrape_timeout_s: float = 2.0     # /telemetry.json fetch budget (push gate)
    push_timeout_s: float = 600.0     # per-host /v1/push budget (canary waits)
    push_burn_threshold: float = 1.0  # federated slo_*_burn veto level

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError("RouterConfig.max_retries must be >= 0")


class Host:
    """One upstream fleet endpoint + health record.  Mutable health fields
    are guarded by the router lock."""

    def __init__(self, hid: int, base_url: str):
        self.hid = hid
        self.base_url = base_url.rstrip("/")
        self.state = HEALTHY
        self.outstanding = 0
        self.generation = 0
        self.probe_ok = 0
        self.requests = 0.0
        self.failures = 0.0          # unhealthy marks + failed probes
        self.sheds = 0.0             # upstream 429s (saturation, not sickness)
        self.unhealthy_since: Optional[float] = None

    def health_penalty(self) -> float:
        """Degraded-path history as a routing tie-break: a host that has been
        failing requests (or shedding) is a worse bet than a clean sibling at
        equal outstanding depth."""
        return self.failures * 1.0 + self.sheds * 0.25


class ServiceRouter:
    """N host fleets behind a load-aware router; the service-level twin of
    :class:`~mat_dcml_tpu.serving.fleet.EngineFleet`'s replica router."""

    def __init__(
        self,
        endpoints: List[str],
        cfg: RouterConfig = RouterConfig(),
        telemetry: Optional[Telemetry] = None,
        tracer: Optional[Tracer] = None,
        slo_monitor: Optional[SLOMonitor] = None,
        log_fn=print,
    ):
        if not endpoints:
            raise ValueError("ServiceRouter needs at least one host endpoint")
        self.cfg = cfg
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.tracer = tracer
        self.slo = slo_monitor
        self.log = log_fn
        self.hosts = [Host(i, url) for i, url in enumerate(endpoints)]
        self.current_generation = 0
        self._lock = threading.Lock()
        self._push_lock = threading.Lock()
        self._rr = 0
        self._closed = False
        self.telemetry.gauge("router_hosts", float(len(self.hosts)))
        self._prober = threading.Thread(
            target=self._probe_loop, name="service-prober", daemon=True)
        self._prober.start()

    def close(self) -> None:
        self._closed = True

    # --------------------------------------------------------------- routing

    def _pick(self, tried: set) -> Optional[Host]:
        """Least-outstanding healthy host, health-penalty then rotating
        tie-break — the fleet's ``_pick`` at host granularity."""
        with self._lock:
            self._rr += 1
            pool = [h for h in self.hosts
                    if h.state == HEALTHY and h.hid not in tried]
            if not pool:
                return None
            n = len(self.hosts)
            pool.sort(key=lambda h: (
                h.outstanding,
                h.health_penalty(),
                (h.hid - self._rr) % n,
            ))
            choice = pool[0]
            choice.outstanding += 1
            choice.requests += 1
            return choice

    def route(self, body: bytes, timeout_s: Optional[float] = None,
              trace=None, traceparent: Optional[str] = None) -> dict:
        """Forward one ``/v1/act`` request body to the best host; retries on
        sibling hosts when a host dies mid-request.  Returns the winning
        host's reply payload (with ``router_host`` stamped on) or raises the
        batcher's typed :class:`ServingError` family — so the router's HTTP
        frontend and every existing client keep their error mapping."""
        if self._closed:
            raise ServingError("service router is closed")
        self.telemetry.count("router_requests")
        tried: set = set()
        attempts = 0
        sheds: List[float] = []
        wait = (self.cfg.attempt_timeout_s if timeout_s is None
                else float(timeout_s) + 5.0)
        while True:
            host = self._pick(tried)
            if host is None:
                if sheds:
                    # every live host refused admission — service-level shed
                    # with the LARGEST upstream hint: the whole service has
                    # capacity only once its slowest host does
                    self.telemetry.count("router_shed")
                    raise QueueFullError(
                        "all hosts at capacity",
                        retry_after_s=max(sheds))
                # total outage: honest brownout, hint = one probe-readmission
                # cycle (same derivation as the fleet's)
                self.telemetry.count("router_no_healthy")
                self.telemetry.count("router_brownout")
                retry_after = max(1, math.ceil(
                    self.cfg.probe_interval_s
                    * max(1, self.cfg.probe_successes)))
                raise QueueFullError(
                    "service brownout: no healthy hosts (probes will "
                    "readmit)", retry_after_s=retry_after)
            t0 = time.perf_counter()
            try:
                payload = self._post_act(host, body, wait, trace, traceparent)
            except urllib.error.HTTPError as e:
                with self._lock:
                    host.outstanding -= 1
                try:
                    err = json.loads(e.read() or b"{}")
                except (ValueError, json.JSONDecodeError):
                    err = {}
                if trace is not None:
                    trace.add_span("route", t0, time.perf_counter(),
                                   host=host.hid, retry=attempts, ok=False,
                                   status=f"http_{e.code}")
                if e.code == 429:
                    # saturation, not sickness: the host is alive and honest
                    # about its queue — try a sibling, remember the hint
                    with self._lock:
                        host.sheds += 1
                    tried.add(host.hid)
                    sheds.append(float(err.get("retry_after_s", 1)))
                    continue
                if e.code == 400:
                    # caller bug, not host health — propagate verbatim
                    raise ValueError(
                        err.get("error", "bad request")) from None
                if e.code == 504:
                    # the request's own budget elapsed — retrying can't help
                    raise DeadlineExceededError(
                        err.get("error", "deadline exceeded")) from None
                # 5xx: the host's engine is failing — fail over
                self._mark_unhealthy(host, f"HTTP {e.code}: "
                                     f"{err.get('error', '')!r}")
                attempts = self._retry_or_raise(attempts, tried, host)
                continue
            except _HOST_ERRORS as e:
                with self._lock:
                    host.outstanding -= 1
                if trace is not None:
                    trace.add_span("route", t0, time.perf_counter(),
                                   host=host.hid, retry=attempts, ok=False,
                                   status=e.__class__.__name__)
                self._mark_unhealthy(host, repr(e))
                attempts = self._retry_or_raise(attempts, tried, host)
                continue
            with self._lock:
                host.outstanding -= 1
            self.telemetry.hist("router_upstream_ms",
                                (time.perf_counter() - t0) * 1e3)
            if trace is not None:
                trace.add_span("route", t0, time.perf_counter(),
                               host=host.hid, retry=attempts, ok=True)
            if attempts:
                self.telemetry.count("router_failovers")
            payload["router_host"] = host.hid
            return payload

    def _post_act(self, host: Host, body: bytes, wait: float, trace,
                  traceparent: Optional[str]) -> dict:
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            # the SAME trace id rides upstream: client → router → host fleet
            inject_traceparent(headers, trace)
        elif traceparent:
            # not sampled at this tier, but the client's header still flows
            # through so the host can continue the client's id
            headers[TRACEPARENT_HEADER] = traceparent
        req = urllib.request.Request(host.base_url + "/v1/act", data=body,
                                     headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=wait) as resp:
            return json.loads(resp.read())

    def _retry_or_raise(self, attempts: int, tried: set, failed: Host) -> int:
        """Bounded jittered-backoff failover bookkeeping; returns the new
        attempt count or raises once the retry budget is spent."""
        tried.add(failed.hid)
        if attempts >= self.cfg.max_retries:
            self.telemetry.count("router_retries_exhausted")
            raise EngineFailureError(
                f"request failed on {attempts + 1} hosts")
        attempts += 1
        self.telemetry.count("router_retries")
        base = self.cfg.backoff_base_ms / 1e3
        time.sleep(base * (2 ** (attempts - 1)) * (0.5 + random.random()))
        return attempts

    # ---------------------------------------------------------------- health

    def _mark_unhealthy(self, host: Host, why: str) -> None:
        with self._lock:
            host.failures += 1
            if host.state == UNHEALTHY:
                return
            host.state = UNHEALTHY
            host.probe_ok = 0
            host.unhealthy_since = time.monotonic()
        self.telemetry.count("router_unhealthy_marks")
        self.log(f"[service] host {host.hid} ({host.base_url}) marked "
                 f"UNHEALTHY: {why}")

    def _probe_host(self, host: Host) -> Optional[dict]:
        """One ``GET /healthz`` against the host; payload dict or None."""
        try:
            with urllib.request.urlopen(
                    host.base_url + "/healthz",
                    timeout=self.cfg.probe_timeout_s) as resp:
                return json.loads(resp.read())
        except (*_HOST_ERRORS, ValueError, json.JSONDecodeError):
            return None

    def _probe_loop(self) -> None:
        """Probe every host each cycle: a live ``/healthz`` refreshes the
        host's advertised weight generation; ``probe_successes`` consecutive
        passes readmit an UNHEALTHY host; a failed probe of a healthy host
        marks it (so an idle router still notices a dead host)."""
        while not self._closed:
            time.sleep(self.cfg.probe_interval_s)
            if self._closed:
                return
            for host in self.hosts:
                self.telemetry.count("router_probes")
                payload = self._probe_host(host)
                if payload is None:
                    self.telemetry.count("router_probe_failures")
                    if host.state == UNHEALTHY:
                        host.probe_ok = 0
                    else:
                        self._mark_unhealthy(host, "healthz probe failed")
                    continue
                gen = (payload.get("fleet") or {}).get("generation")
                if gen is not None:
                    with self._lock:
                        host.generation = int(gen)
                if host.state != UNHEALTHY:
                    continue
                host.probe_ok += 1
                if host.probe_ok >= self.cfg.probe_successes:
                    with self._lock:
                        host.state = HEALTHY
                        host.unhealthy_since = None
                    self.telemetry.count("router_readmissions")
                    self.log(f"[service] host {host.hid} readmitted after "
                             f"{host.probe_ok} clean probes")

    # ------------------------------------------------------------ weight push

    def _host_burns(self, host: Host) -> Dict[str, float]:
        """The host's live ``slo_*_burn`` gauges from its federated
        ``/telemetry.json`` snapshot (``extra_gauges`` rider)."""
        try:
            with urllib.request.urlopen(
                    host.base_url + SNAPSHOT_PATH,
                    timeout=self.cfg.scrape_timeout_s) as resp:
                snap = json.loads(resp.read())
        except (*_HOST_ERRORS, ValueError, json.JSONDecodeError):
            return {}
        return {k: float(v)
                for k, v in (snap.get("extra_gauges") or {}).items()
                if k.endswith("_burn")}

    def _post_json(self, host: Host, path: str, payload: dict,
                   timeout_s: float) -> Tuple[int, dict]:
        req = urllib.request.Request(
            host.base_url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except (ValueError, json.JSONDecodeError):
                return e.code, {}

    def push(self, policy_dir: str) -> dict:
        """Generation-consistent weight push across every host.

        Gate order: (1) federated SLO burn — every host's scraped
        ``slo_*_burn`` must be under ``push_burn_threshold``; (2) each host's
        own canary gate (``POST /v1/push`` blocks on its
        ``RolloutController``), rolled one host at a time.  ANY host failing
        — gate verdict, HTTP error, or mid-roll death — aborts to a
        full-service rollback of every already-promoted host.  Steady state
        therefore never has two hosts on different generations."""
        if not self._push_lock.acquire(blocking=False):
            raise RuntimeError("a service push is already in progress")
        try:
            return self._push_locked(policy_dir)
        finally:
            self._push_lock.release()

    def _push_locked(self, policy_dir: str) -> dict:
        t_start = time.perf_counter()
        report: dict = {"status": "", "policy_dir": str(policy_dir),
                        "prior_generation": self.current_generation,
                        "hosts": {}, "events": []}

        # (1) never widen a rollout into a burning service: any host's live
        # burn at/past threshold vetoes before the first host swaps
        for host in self.hosts:
            hot = {k: v for k, v in self._host_burns(host).items()
                   if v >= self.cfg.push_burn_threshold}
            if hot:
                self.telemetry.count("router_slo_gated")
                report["status"] = "rejected"
                report["events"].append(
                    {"host": host.hid, "slo_gated": hot})
                self.log(f"[service] push REJECTED: host {host.hid} SLO "
                         f"budget burning ({hot})")
                return report

        promoted: List[Host] = []
        generation = None
        for host in self.hosts:
            try:
                code, host_report = self._post_json(
                    host, "/v1/push", {"policy_dir": str(policy_dir)},
                    self.cfg.push_timeout_s)
            except _HOST_ERRORS as e:
                code, host_report = 0, {"status": "unreachable",
                                        "error": repr(e)}
            report["hosts"][host.hid] = host_report
            status = host_report.get("status", "")
            if code == 200 and status == "promoted":
                promoted.append(host)
                generation = int(host_report.get(
                    "generation", self.current_generation + 1))
                with self._lock:
                    host.generation = generation
                continue
            # host gate tripped / host died mid-roll: full-service rollback
            self.telemetry.count("router_push_failures")
            self._mark_unhealthy(host, f"push failed ({status or code})")
            self._rollback_hosts(promoted, report)
            report["status"] = "rolled_back"
            report["failed_host"] = host.hid
            report["wall_s"] = time.perf_counter() - t_start
            self.telemetry.count("router_rollbacks")
            self.log(f"[service] push ROLLED BACK: host {host.hid} "
                     f"{status or f'HTTP {code}'} — {len(promoted)} host(s) "
                     f"reverted")
            return report

        self.current_generation = (generation if generation is not None
                                   else self.current_generation)
        self.telemetry.count("router_pushes")
        report["status"] = "promoted"
        report["generation"] = self.current_generation
        report["wall_s"] = time.perf_counter() - t_start
        self.log(f"[service] push PROMOTED to generation "
                 f"{self.current_generation} across {len(self.hosts)} hosts")
        return report

    def _rollback_hosts(self, hosts: List[Host], report: dict) -> None:
        for host in hosts:
            try:
                code, rb = self._post_json(host, "/v1/rollback", {},
                                           self.cfg.push_timeout_s)
            except _HOST_ERRORS as e:
                code, rb = 0, {"error": repr(e)}
            report["events"].append(
                {"host": host.hid, "rollback": rb, "code": code})
            if code == 200:
                with self._lock:
                    host.generation = int(
                        rb.get("generation", host.generation))

    def rollback(self) -> dict:
        """Manual full-service rollback: every host reverts to its prior
        promoted manifest."""
        report: dict = {"status": "rolled_back", "hosts": {}}
        failed = 0
        for host in self.hosts:
            try:
                code, rb = self._post_json(host, "/v1/rollback", {},
                                           self.cfg.push_timeout_s)
            except _HOST_ERRORS as e:
                code, rb = 0, {"error": repr(e)}
            report["hosts"][host.hid] = rb
            if code == 200:
                with self._lock:
                    host.generation = int(
                        rb.get("generation", host.generation))
            else:
                failed += 1
        self.telemetry.count("router_rollbacks")
        if failed == len(self.hosts):
            raise RuntimeError("rollback failed on every host")
        gens = {h.generation for h in self.hosts}
        if len(gens) == 1:
            self.current_generation = gens.pop()
        report["generation"] = self.current_generation
        return report

    # ------------------------------------------------------------ accounting

    def status(self) -> dict:
        """Human/HTTP-facing service state (the ``/service`` endpoint)."""
        with self._lock:
            hosts = [{
                "hid": h.hid,
                "url": h.base_url,
                "state": h.state,
                "outstanding": h.outstanding,
                "generation": h.generation,
                "requests": h.requests,
                "failures": h.failures,
            } for h in self.hosts]
        gens = {h["generation"] for h in hosts}
        return {
            "hosts": hosts,
            "healthy": sum(1 for h in hosts if h["state"] == HEALTHY),
            "generation": self.current_generation,
            "generation_split": len(gens) > 1,
            "push_in_progress": self._push_lock.locked(),
        }

    def sync_gauges(self) -> None:
        """Refresh the point-in-time service gauges on the registry.
        Counters and the upstream latency sketch accrue live, but
        health/generation are derived state — materialized scrape-driven
        (each ``/metrics`` / ``/telemetry.json`` hit), the same cadence
        trick the telemetry sidecar uses for rollup sampling."""
        with self._lock:
            hosts = list(self.hosts)
            healthy = sum(1 for h in hosts if h.state == HEALTHY)
            gens = {h.generation for h in hosts}
        self.telemetry.gauge("router_hosts", float(len(hosts)))
        self.telemetry.gauge("router_healthy", float(healthy))
        self.telemetry.gauge("router_generation",
                             float(self.current_generation))
        self.telemetry.gauge("router_generation_split",
                             1.0 if len(gens) > 1 else 0.0)
        for h in hosts:
            prefix = f"host_{h.hid}"
            self.telemetry.gauge(f"{prefix}_state", _STATE_CODE[h.state])
            self.telemetry.gauge(f"{prefix}_outstanding",
                                 float(h.outstanding))
            self.telemetry.gauge(f"{prefix}_generation",
                                 float(h.generation))
            self.telemetry.gauge(f"{prefix}_requests", h.requests)
            self.telemetry.gauge(f"{prefix}_failures", h.failures)

    def service_record(self) -> Dict[str, float]:
        """Flat metrics.jsonl fragment: the ``router_``/``host_`` families
        (`scripts/check_metrics_schema.py` REQUIRED_ROUTER contract) plus the
        upstream latency sketch and live SLO gauges."""
        c = self.telemetry.counters
        with self._lock:
            hosts = list(self.hosts)
            healthy = sum(1 for h in hosts if h.state == HEALTHY)
            gens = {h.generation for h in hosts}
        record: Dict[str, float] = {
            "router_hosts": float(len(hosts)),
            "router_healthy": float(healthy),
            "router_requests": c.get("router_requests", 0.0),
            "router_retries": c.get("router_retries", 0.0),
            "router_retries_exhausted": c.get("router_retries_exhausted", 0.0),
            "router_failovers": c.get("router_failovers", 0.0),
            "router_shed": c.get("router_shed", 0.0),
            "router_no_healthy": c.get("router_no_healthy", 0.0),
            "router_brownout": c.get("router_brownout", 0.0),
            "router_unhealthy_marks": c.get("router_unhealthy_marks", 0.0),
            "router_readmissions": c.get("router_readmissions", 0.0),
            "router_probes": c.get("router_probes", 0.0),
            "router_probe_failures": c.get("router_probe_failures", 0.0),
            "router_pushes": c.get("router_pushes", 0.0),
            "router_rollbacks": c.get("router_rollbacks", 0.0),
            "router_push_failures": c.get("router_push_failures", 0.0),
            "router_slo_gated": c.get("router_slo_gated", 0.0),
            "router_generation": float(self.current_generation),
            "router_generation_split": 1.0 if len(gens) > 1 else 0.0,
        }
        # per-host labels: one flat field per (host, signal)
        for h in hosts:
            prefix = f"host_{h.hid}"
            record[f"{prefix}_state"] = _STATE_CODE[h.state]
            record[f"{prefix}_outstanding"] = float(h.outstanding)
            record[f"{prefix}_generation"] = float(h.generation)
            record[f"{prefix}_requests"] = h.requests
            record[f"{prefix}_failures"] = h.failures
        sk = self.telemetry.hists.get("router_upstream_ms")
        if sk is not None and sk.count:
            record.update(sk.snapshot("router_upstream_ms"))
        if self.slo is not None:
            record.update(self.slo.gauges())
        return record


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "mat-dcml-service/1"

    def log_message(self, fmt, *args):   # route through the server's logger
        self.server.log_fn("[service] " + fmt % args)

    def _reply(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: "RouterServer" = self.server.router_server
        if self.path == "/metrics":
            self._reply_text(200, srv.metrics_text(),
                             "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == SNAPSHOT_PATH:
            self._reply(200, srv.telemetry_snapshot())
        elif self.path == TIMESERIES_PATH:
            self._reply(200, srv.timeseries_snapshot())
        elif self.path == "/healthz":
            status = srv.router.status()
            self._reply(200, {
                "ok": True,
                "service": {"hosts": len(status["hosts"]),
                            "healthy": status["healthy"],
                            "generation": status["generation"]}})
        elif self.path == "/service":
            self._reply(200, srv.router.status())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv: "RouterServer" = self.server.router_server
        if self.path == "/v1/push":
            self._do_push(srv)
            return
        if self.path == "/v1/rollback":
            self._do_rollback(srv)
            return
        if self.path != "/v1/act":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        try:
            # the body is forwarded verbatim; only timeout_s is peeked (the
            # host enforces the deadline — the router just sizes its wait)
            timeout_s = json.loads(body).get("timeout_s")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        # ingress: continue the client-minted trace id (the client made the
        # sampling decision) or mint a sampled root — either way the SAME id
        # is injected upstream, so one trace spans all three tiers
        traceparent = self.headers.get(TRACEPARENT_HEADER)
        trace = None
        if srv.tracer is not None:
            remote_id = extract_traceparent(self.headers)
            trace = (srv.tracer.continue_trace(remote_id, "router")
                     if remote_id else srv.tracer.start_trace("router"))
        t0 = time.monotonic()
        try:
            payload = srv.router.route(body, timeout_s, trace=trace,
                                       traceparent=traceparent)
        except QueueFullError as e:
            srv.observe_request(t0, ok=False, trace=trace, status="shed")
            self._reply(429, {"error": str(e), "kind": "queue_full",
                              "retry_after_s": getattr(e, "retry_after_s", 1)},
                        headers={"Retry-After":
                                 str(getattr(e, "retry_after_s", 1))})
        except DeadlineExceededError as e:
            srv.observe_request(t0, ok=False, trace=trace, status="deadline")
            self._reply(504, {"error": str(e), "kind": "deadline_exceeded"})
        except ValueError as e:
            # caller bug, not service health: finish the trace, spare the SLO
            if trace is not None:
                trace.finish(status="bad_shape")
            self._reply(400, {"error": str(e), "kind": "bad_shape"})
        except Exception as e:  # retries exhausted / unexpected
            srv.observe_request(t0, ok=False, trace=trace, status="error")
            self._reply(500, {"error": repr(e), "kind": "engine_failure"})
        else:
            payload["router_ms"] = (time.monotonic() - t0) * 1e3
            srv.observe_request(t0, ok=True, trace=trace, status="ok")
            self._reply(200, payload)

    def _do_push(self, srv: "RouterServer") -> None:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            policy_dir = req["policy_dir"]
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        try:
            report = srv.router.push(policy_dir)
        except RuntimeError as e:       # push already in progress
            self._reply(409, {"error": str(e), "kind": "push_in_progress"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "push_failure"})
        else:
            self._reply(200, report)

    def _do_rollback(self, srv: "RouterServer") -> None:
        try:
            report = srv.router.rollback()
        except RuntimeError as e:       # nothing to roll back to anywhere
            self._reply(409, {"error": str(e), "kind": "no_prior"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "rollback_failure"})
        else:
            self._reply(200, report)


class RouterServer:
    """HTTP frontend over a :class:`ServiceRouter` — the service twin of
    :class:`~mat_dcml_tpu.serving.server.PolicyServer`.  Same routes, same
    typed-rejection mapping, so ``HttpPolicyClient`` and the loadgen drive
    the federation URL exactly like a single host.  ``start()`` binds and
    serves on a background thread; ``port=0`` picks a free port (tests)."""

    def __init__(
        self,
        router: ServiceRouter,
        host: str = "127.0.0.1",
        port: int = 8520,
        log_fn=print,
        tracer: Optional[Tracer] = None,
        slo_monitor: Optional[SLOMonitor] = None,
        anomaly_cfg: AnomalyConfig = AnomalyConfig(),
    ):
        self.router = router
        self.tracer = tracer if tracer is not None else router.tracer
        self.slo = slo_monitor if slo_monitor is not None else router.slo
        self._slo_detector = (
            AnomalyDetector(
                anomaly_cfg,
                exemplar_fn=lambda: (self.tracer.last_trace_id
                                     if self.tracer is not None else None))
            if self.slo is not None else None)
        self.anomalies: list = []
        self._slo_seen = 0
        self._snapshot_seq = 0
        self._ts_seq = 0
        self._snapshot_lock = threading.Lock()
        self.rollup = RollupStore()
        self.log_fn = log_fn
        self._httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self._httpd.router_server = self
        self._httpd.log_fn = log_fn
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # --------------------------------------------------------- observability

    def metrics_text(self) -> str:
        self.router.sync_gauges()
        agg = TelemetryAggregator([("router", self.router.telemetry)])
        extra = self.slo.gauges() if self.slo is not None else None
        return agg.prometheus_text(extra_gauges=extra)

    def telemetry_snapshot(self) -> dict:
        """``GET /telemetry.json`` payload (telemetry/remote.py wire format)
        for the router's OWN registry — host fleets expose their own
        endpoints; a collector scrapes all N+1 and merges."""
        with self._snapshot_lock:
            self._snapshot_seq += 1
            seq = self._snapshot_seq
        self.router.sync_gauges()
        self.router.telemetry.count("obs_snapshot_requests")
        extra = self.slo.gauges() if self.slo is not None else None
        return build_snapshot(f"router:{self.port}",
                              [("router", self.router.telemetry)], seq,
                              extra_gauges=extra)

    def timeseries_snapshot(self) -> dict:
        """``GET /timeseries.json`` payload: scrape-driven sampling into the
        rollup store (PolicyServer's contract, router families)."""
        self.router.sync_gauges()
        with self._snapshot_lock:
            self._ts_seq += 1
            seq = self._ts_seq
            t = time.time()
            self.rollup.observe_telemetry(self.router.telemetry, t=t,
                                          source="router")
            if self.slo is not None:
                self.rollup.observe_record(self.slo.gauges(), t=t)
            wire = self.rollup.to_wire()
        snap = {
            "source": f"router:{self.port}",
            "seq": seq,
            "time_s": t,
            "rollup": wire,
        }
        snap.update(run_identity())
        return snap

    def observe_request(self, t0: float, ok: bool, trace=None,
                        status: str = "ok") -> None:
        """Terminal accounting for one routed request: finish the ingress
        trace and feed the service-level SLO monitor (amortized burn-rate
        tripwire checks, same cadence as the fleet's)."""
        if trace is not None:
            trace.finish(status=status)
        if self.slo is None:
            return
        self.slo.observe_request((time.monotonic() - t0) * 1e3, ok=ok)
        self._slo_seen += 1
        if self._slo_detector is not None and self._slo_seen % 16 == 0:
            from mat_dcml_tpu.chaos import inject as _chaos
            trips = self._slo_detector.observe(
                self.slo.burn_signals(), episode=0,
                total_steps=int(self.slo.total_requests))
            for a in trips:
                if _chaos.ACTIVE is not None:
                    event_id = _chaos.ACTIVE.suppression_for(a.kind)
                    if event_id is not None:
                        self.log_fn(f"[service] SLO anomaly {a.kind} "
                                    f"suppressed — expected under chaos "
                                    f"event {event_id}")
                        continue
                self.anomalies.append(a.to_record())
                self.log_fn(f"[service] SLO budget anomaly: {a.kind}")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="service-http",
            daemon=True)
        self._thread.start()
        self.log_fn(
            f"[service] router listening on "
            f"http://{self._httpd.server_address[0]}:{self.port} "
            f"({len(self.router.hosts)} hosts)")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.router.close()
