"""Minimal serving frontend: in-process client + stdlib JSON-over-HTTP.

``PolicyClient`` is the canonical interface (tests, loadgen, and co-located
schedulers use it directly — no serialization, no sockets).  ``PolicyServer``
wraps the same batcher in a ``ThreadingHTTPServer`` JSON endpoint for
out-of-process callers; intentionally stdlib-only (no new dependencies):

- ``POST /v1/act``   {"state": [[..]], "obs": [[..]], "available_actions":
  [[..]]?, "timeout_s": float?} -> {"action": [[..]], "log_prob": [[..]]}
- ``GET /healthz``   liveness + warmup state
- ``GET /stats``     telemetry counter/gauge snapshot taken under the batcher
  lock (queue depth, shed counts, bucket occupancy, recompiles)
- ``GET /metrics``   Prometheus text exposition (counters, gauges, and the
  server-side latency summaries — fleet-wide merged across replicas in fleet
  mode) so a live soak run is scrapeable
- ``GET /telemetry.json``  the structured federation snapshot
  (telemetry/remote.py): per-source counters/gauges plus EXACT histogram
  sketch state under a monotonic ``seq``, so a remote scraper merges
  fleet-wide percentiles bit-for-bit instead of re-parsing rounded
  Prometheus text

Typed rejections map onto HTTP: queue-full -> 429 with a ``Retry-After``
header derived from queue depth x EMA service time, deadline -> 504, engine
failure -> 500, malformed request -> 400.

Cross-process tracing: a ``traceparent`` request header on ``POST /v1/act``
(telemetry/propagate.py; injected by :class:`HttpPolicyClient` / loadgen)
continues the client-minted trace id through routing → queue → replica, so
one trace spans client → network → server; successful replies carry
``server_ms`` (the server-side end-to-end) so the client can histogram the
network+client-queue gap as ``serving_client_overhead_ms``.

Fleet mode (``PolicyServer(fleet=...)`` or ``scripts/serve_fleet.py``) serves
the same ``/v1/act`` through the fleet router and adds:

- ``GET /fleet``          per-replica health/generation/outstanding
- ``POST /v1/push``       {"policy_dir": ...} -> canary-gated weight push
- ``POST /v1/rollback``   roll every replica back to the prior manifest
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from mat_dcml_tpu.serving.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DeadlineExceededError,
    EngineFailureError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig
from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
from mat_dcml_tpu.telemetry.anomaly import AnomalyConfig, AnomalyDetector
from mat_dcml_tpu.telemetry.propagate import extract as extract_traceparent
from mat_dcml_tpu.telemetry.propagate import inject as inject_traceparent
from mat_dcml_tpu.telemetry.registry import Telemetry
from mat_dcml_tpu.telemetry.remote import SNAPSHOT_PATH, build_snapshot
from mat_dcml_tpu.telemetry.remote import run_identity
from mat_dcml_tpu.telemetry.timeseries import TIMESERIES_PATH, RollupStore
from mat_dcml_tpu.telemetry.slo import SLOConfig, SLOMonitor
from mat_dcml_tpu.telemetry.tracing import TraceContext, Tracer


class PolicyClient:
    """In-process client: one joint observation in, one joint action out.

    ``tracer`` makes this an ingress point: each ``act`` mints a sampled
    trace that rides through routing/queueing/decode and is finished when the
    result lands back in the caller's thread."""

    def __init__(self, batcher: ContinuousBatcher,
                 tracer: Optional[Tracer] = None):
        self.batcher = batcher
        self.tracer = tracer

    def act(
        self,
        state,
        obs,
        available_actions=None,
        timeout_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking request -> ``(action, log_prob)``; raises the batcher's
        typed :class:`ServingError` subclasses on shed/deadline/failure."""
        owns = False
        if trace is None and self.tracer is not None:
            trace = self.tracer.start_trace("serving")
            owns = trace is not None
        try:
            fut = self.batcher.submit(state, obs, available_actions, timeout_s,
                                      trace=trace)
            # the batcher enforces the deadline; the client-side wait gets
            # slack on top so the typed DeadlineExceededError (not a bare
            # concurrent.futures timeout) is what surfaces
            wait = None if timeout_s is None else timeout_s + 5.0
            result = fut.result(timeout=wait)
        except BaseException:
            if owns:
                trace.finish(status="error")
            raise
        if owns:
            trace.finish(status="ok")
        return result


class HttpPolicyClient:
    """``PolicyClient`` twin that crosses the process boundary: POSTs
    ``/v1/act`` to a remote :class:`PolicyServer`, mints a client-side root
    span per request, and injects the ``traceparent`` header so the server
    continues the SAME trace id (telemetry/propagate.py).

    Duck-types what ``loadgen.run_load`` needs — ``act`` with the typed
    :class:`ServingError` mapping (429 -> queue-full, 504 -> deadline,
    others -> engine failure) plus a local ``telemetry``/``cfg`` instead of a
    batcher.  Successful replies carry ``server_ms`` (the server-side
    end-to-end span); the difference against the client root span lands in
    the ``serving_client_overhead_ms`` histogram — the measurable
    network + client-queue gap."""

    def __init__(self, base_url: str, cfg=None,
                 tracer: Optional[Tracer] = None,
                 telemetry: Optional[Telemetry] = None,
                 timeout_s: float = 60.0):
        import urllib.request

        self._urllib = urllib.request
        self.base_url = base_url.rstrip("/")
        self.cfg = cfg
        self.tracer = tracer
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.timeout_s = float(timeout_s)

    def act(
        self,
        state,
        obs,
        available_actions=None,
        timeout_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        import urllib.error

        owns = False
        if trace is None and self.tracer is not None:
            trace = self.tracer.start_trace("client", root="client_request")
            owns = trace is not None
        payload = {"state": np.asarray(state).tolist(),
                   "obs": np.asarray(obs).tolist()}
        if available_actions is not None:
            payload["available_actions"] = np.asarray(available_actions).tolist()
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        headers = {"Content-Type": "application/json"}
        inject_traceparent(headers, trace)
        req = self._urllib.Request(self.base_url + "/v1/act",
                                   data=json.dumps(payload).encode(),
                                   headers=headers, method="POST")
        t0 = time.perf_counter()
        wait = self.timeout_s if timeout_s is None else timeout_s + 5.0
        try:
            with self._urllib.urlopen(req, timeout=wait) as resp:
                body = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read() or b"{}")
            except (ValueError, json.JSONDecodeError):
                err = {}
            detail = err.get("error", f"HTTP {e.code}")
            self.telemetry.count("serving_client_errors")
            if owns:
                trace.finish(status=err.get("kind", "error"))
            if e.code == 429:
                exc = QueueFullError(detail)
                exc.retry_after_s = err.get("retry_after_s", 1)
                raise exc from None
            if e.code == 504:
                raise DeadlineExceededError(detail) from None
            if e.code == 400:
                raise ValueError(detail) from None
            raise EngineFailureError(detail) from None
        except BaseException:
            self.telemetry.count("serving_client_errors")
            if owns:
                trace.finish(status="error")
            raise
        client_ms = (time.perf_counter() - t0) * 1e3
        server_ms = body.get("server_ms")
        if server_ms is not None:
            self.telemetry.hist("serving_client_overhead_ms",
                                max(0.0, client_ms - float(server_ms)))
        if owns:
            trace.finish(status="ok",
                         server_ms=0.0 if server_ms is None else server_ms)
        return (np.asarray(body["action"], np.float32),
                np.asarray(body["log_prob"], np.float32))


class _Handler(BaseHTTPRequestHandler):
    server_version = "mat-dcml-serving/1"

    def log_message(self, fmt, *args):   # route through the server's logger
        self.server.log_fn("[serving] " + fmt % args)

    def _reply(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/metrics":
            self._reply_text(200, srv.metrics_text(),
                             "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == SNAPSHOT_PATH:
            self._reply(200, srv.telemetry_snapshot())
        elif self.path == TIMESERIES_PATH:
            self._reply(200, srv.timeseries_snapshot())
        elif self.path == "/healthz":
            payload = {"ok": True, "warm": srv.warm,
                       "buckets": list(srv.engine.engine_cfg.buckets)}
            if srv.fleet is not None:
                status = srv.fleet.status()
                payload["fleet"] = {"replicas": len(status["replicas"]),
                                    "healthy": status["healthy"],
                                    "generation": status["generation"]}
            self._reply(200, payload)
        elif self.path == "/stats":
            # snapshot under the batcher lock: no torn counter/gauge pairs
            self._reply(200, srv.batcher.stats_snapshot())
        elif self.path == "/fleet":
            if srv.fleet is None:
                self._reply(404, {"error": "not running in fleet mode"})
            else:
                self._reply(200, srv.fleet.status())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/v1/push":
            self._do_push(srv)
            return
        if self.path == "/v1/rollback":
            self._do_rollback(srv)
            return
        if self.path != "/v1/act":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            state = np.asarray(req["state"], np.float32)
            obs = np.asarray(req["obs"], np.float32)
            avail = req.get("available_actions")
            avail = None if avail is None else np.asarray(avail, np.float32)
            timeout_s = req.get("timeout_s")
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        # ingress: mint the (sampled) trace and the SLO latency clock here so
        # the root span covers parse-to-reply — the server-side end-to-end.
        # A traceparent header continues the client-minted trace id instead
        # (the client already made the sampling decision), so one trace spans
        # client -> network -> queue -> replica across the process boundary.
        trace = None
        if srv.tracer is not None:
            remote_id = extract_traceparent(self.headers)
            trace = (srv.tracer.continue_trace(remote_id, "serving")
                     if remote_id else srv.tracer.start_trace("serving"))
        t0 = time.monotonic()
        try:
            action, log_prob = srv.client.act(state, obs, avail, timeout_s,
                                              trace=trace)
        except QueueFullError as e:
            # a shed client that retries immediately just gets shed again;
            # the hint is the server-side queue-wait EMA at shed instant
            srv.observe_request(t0, ok=False, trace=trace, status="shed")
            self._reply(429, {"error": str(e), "kind": "queue_full",
                              "retry_after_s": getattr(e, "retry_after_s", 1)},
                        headers={"Retry-After":
                                 str(getattr(e, "retry_after_s", 1))})
        except DeadlineExceededError as e:
            srv.observe_request(t0, ok=False, trace=trace, status="deadline")
            self._reply(504, {"error": str(e), "kind": "deadline_exceeded"})
        except ValueError as e:
            # caller bug, not service health: finish the trace, spare the SLO
            if trace is not None:
                trace.finish(status="bad_shape")
            self._reply(400, {"error": str(e), "kind": "bad_shape"})
        except Exception as e:  # ServingError + engine failures
            srv.observe_request(t0, ok=False, trace=trace, status="error")
            self._reply(500, {"error": repr(e), "kind": "engine_failure"})
        else:
            server_ms = (time.monotonic() - t0) * 1e3
            srv.observe_request(t0, ok=True, trace=trace, status="ok")
            # server_ms = the server-side end-to-end; the client subtracts it
            # from its own root span to histogram the network/client gap
            self._reply(200, {"action": action.tolist(),
                              "log_prob": log_prob.tolist(),
                              "server_ms": server_ms})

    def _do_push(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            policy_dir = req["policy_dir"]
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        try:
            report = srv.fleet.push_from_export(policy_dir)
        except RuntimeError as e:       # push already in progress
            self._reply(409, {"error": str(e), "kind": "push_in_progress"})
        except FileNotFoundError as e:
            self._reply(400, {"error": str(e), "kind": "bad_artifact"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "push_failure"})
        else:
            self._reply(200, report)

    def _do_rollback(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            report = srv.fleet.rollback()
        except RuntimeError as e:       # nothing to roll back to
            self._reply(409, {"error": str(e), "kind": "no_prior"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "rollback_failure"})
        else:
            self._reply(200, report)


class PolicyServer:
    """HTTP frontend over (engine, batcher) — or over an
    :class:`~mat_dcml_tpu.serving.fleet.EngineFleet`, which duck-types the
    batcher interface, in which case ``/fleet`` + ``/v1/push`` +
    ``/v1/rollback`` come alive.  ``start()`` binds and serves on a
    background thread; ``port=0`` picks a free port (tests)."""

    def __init__(
        self,
        engine: Optional[DecodeEngine] = None,
        batcher_cfg: BatcherConfig = BatcherConfig(),
        host: str = "127.0.0.1",
        port: int = 8420,
        log_fn=print,
        fleet=None,
        tracer: Optional[Tracer] = None,
        slo_monitor: Optional[SLOMonitor] = None,
        anomaly_cfg: AnomalyConfig = AnomalyConfig(),
    ):
        if (engine is None) == (fleet is None):
            raise ValueError("pass exactly one of engine= or fleet=")
        self.fleet = fleet
        if fleet is not None:
            self.engine = fleet.engine     # bucket/config introspection
            self.batcher = fleet           # router IS the batcher interface
            # the fleet owns tracing/SLO accounting on its own ingress; the
            # HTTP layer defers to it rather than double-counting
            self.tracer = tracer if tracer is not None else fleet.tracer
            self.slo = slo_monitor if slo_monitor is not None else fleet.slo
            self._slo_detector = fleet.anomaly_detector
        else:
            self.engine = engine
            self.batcher = ContinuousBatcher(engine, batcher_cfg, log_fn=log_fn)
            self.tracer = tracer
            self.slo = slo_monitor
            self._slo_detector = (
                AnomalyDetector(
                    anomaly_cfg,
                    exemplar_fn=lambda: (self.tracer.last_trace_id
                                         if self.tracer is not None else None))
                if slo_monitor is not None else None)
        self.anomalies: list = []
        self._slo_seen = 0
        self._snapshot_seq = 0
        self._ts_seq = 0
        self.rollup = RollupStore()
        self._snapshot_lock = threading.Lock()
        self.client = PolicyClient(self.batcher)
        self.log_fn = log_fn
        self.warm = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.policy_server = self
        self._httpd.log_fn = log_fn
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # --------------------------------------------------------- observability

    def _obs_sources(self):
        """The labelled registries this process exposes: fleet router +
        per-replica engines in fleet mode, the lone batcher otherwise."""
        if self.fleet is not None:
            sources = [("fleet", self.fleet.telemetry)]
            sources += [(str(r.rid), r.engine.telemetry)
                        for r in self.fleet.replicas]
            return sources
        return [("0", self.batcher.telemetry)]

    def metrics_text(self) -> str:
        """Prometheus text for ``GET /metrics``: merged counters/gauges and
        fleet-wide latency summaries, plus live SLO burn gauges."""
        agg = TelemetryAggregator(self._obs_sources())
        extra = self.slo.gauges() if self.slo is not None else None
        return agg.prometheus_text(extra_gauges=extra)

    def telemetry_snapshot(self) -> dict:
        """``GET /telemetry.json`` payload (telemetry/remote.py wire format):
        exact per-source sketch state under a process-monotonic ``seq``, so a
        remote scraper's merge is bit-identical to an in-process merge."""
        with self._snapshot_lock:
            self._snapshot_seq += 1
            seq = self._snapshot_seq
        sources = self._obs_sources()
        sources[0][1].count("obs_snapshot_requests")
        extra = self.slo.gauges() if self.slo is not None else None
        return build_snapshot(f"serving:{self.port}", sources, seq,
                              extra_gauges=extra)

    def timeseries_snapshot(self) -> dict:
        """``GET /timeseries.json`` payload: scrape-driven sampling — each
        request diffs every labelled registry (and the live SLO burn gauges)
        into the rollup store, then serves its canonical wire under a
        monotonic ``seq``."""
        with self._snapshot_lock:
            self._ts_seq += 1
            seq = self._ts_seq
            t = time.time()
            for label, tel in self._obs_sources():
                self.rollup.observe_telemetry(tel, t=t, source=label)
            if self.slo is not None:
                self.rollup.observe_record(self.slo.gauges(), t=t)
            wire = self.rollup.to_wire()
        snap = {
            "source": f"serving:{self.port}",
            "seq": seq,
            "time_s": t,
            "rollup": wire,
        }
        snap.update(run_identity())
        return snap

    def observe_request(self, t0: float, ok: bool, trace=None,
                        status: str = "ok") -> None:
        """Terminal HTTP-path accounting: finish the ingress trace (idempotent
        — the fleet may have finished it first) and feed the SLO monitor,
        unless the fleet already fed this request at its own ingress."""
        if trace is not None:
            trace.finish(status=status)
        if self.slo is None:
            return
        if self.fleet is not None and self.fleet.slo is self.slo:
            return
        self.slo.observe_request((time.monotonic() - t0) * 1e3, ok=ok)
        self._slo_seen += 1
        if self._slo_detector is not None and self._slo_seen % 16 == 0:
            trips = self._slo_detector.observe(
                self.slo.burn_signals(), episode=0,
                total_steps=int(self.slo.total_requests))
            for a in trips:
                self.anomalies.append(a.to_record())
                self.log_fn(f"[serving] SLO budget anomaly: {a.kind}")

    def warmup(self) -> None:
        if self.fleet is not None:
            self.fleet.warmup()
        else:
            self.engine.warmup()
        self.warm = True

    def start(self) -> None:
        if not self.warm:
            self.warmup()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http", daemon=True
        )
        self._thread.start()
        self.log_fn(f"[serving] listening on http://{self._httpd.server_address[0]}"
                    f":{self.port} (buckets {self.engine.engine_cfg.buckets})")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.batcher.close()


def main(argv=None) -> None:
    """CLI: serve a weights-only export.

    Usage: python -m mat_dcml_tpu.serving.server --policy_dir <export>
           [--port 8420] [--buckets 1,8,32,128] [--max_batch_wait_ms 2.0]
           [--max_queue 256] [--decode_mode cached|scan|stride|spec]
           [--spec_block 8] [--serve_dtype f32|bf16]
    """
    import argparse

    p = argparse.ArgumentParser(description="MAT policy server")
    p.add_argument("--policy_dir", required=True,
                   help="export dir from scripts/export_policy.py")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8420)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch_wait_ms", type=float, default=2.0)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--decode_mode", default="cached",
                   choices=("cached", "scan", "stride", "spec"))
    p.add_argument("--spec_block", type=int, default=8)
    p.add_argument("--serve_dtype", default="f32", choices=("f32", "bf16"),
                   help="serving trunk precision; bf16 casts params at "
                        "install time and is gated by value-tolerance (not "
                        "bit-parity) canary comparison in fleet mode")
    p.add_argument("--run_dir", default=None,
                   help="observability output dir (enables trace.jsonl)")
    p.add_argument("--trace_sample", type=float, default=0.01,
                   help="fraction of requests traced (0 disables)")
    p.add_argument("--trace_max_mb", type=float, default=64.0)
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="latency SLO target for burn-rate tracking; 0 off")
    args = p.parse_args(argv)

    tracer = (Tracer(args.run_dir, sample=args.trace_sample,
                     max_mb=args.trace_max_mb)
              if args.run_dir else None)
    slo = (SLOMonitor(SLOConfig(latency_p99_ms=args.slo_p99_ms))
           if args.slo_p99_ms > 0 else None)

    engine = DecodeEngine.from_export(
        args.policy_dir,
        EngineConfig(
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            decode_mode=args.decode_mode,
            spec_block=args.spec_block,
            serve_dtype=args.serve_dtype,
        ),
    )
    server = PolicyServer(
        engine,
        BatcherConfig(max_queue=args.max_queue,
                      max_batch_wait_ms=args.max_batch_wait_ms),
        host=args.host, port=args.port,
        tracer=tracer, slo_monitor=slo,
    )
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
