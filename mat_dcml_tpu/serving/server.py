"""Minimal serving frontend: in-process client + stdlib JSON-over-HTTP.

``PolicyClient`` is the canonical interface (tests, loadgen, and co-located
schedulers use it directly — no serialization, no sockets).  ``PolicyServer``
wraps the same batcher in a ``ThreadingHTTPServer`` JSON endpoint for
out-of-process callers; intentionally stdlib-only (no new dependencies):

- ``POST /v1/act``   {"state": [[..]], "obs": [[..]], "available_actions":
  [[..]]?, "timeout_s": float?} -> {"action": [[..]], "log_prob": [[..]]}
- ``GET /healthz``   liveness + warmup state
- ``GET /stats``     telemetry counter/gauge snapshot taken under the batcher
  lock (queue depth, shed counts, bucket occupancy, recompiles)

Typed rejections map onto HTTP: queue-full -> 429 with a ``Retry-After``
header derived from queue depth x EMA service time, deadline -> 504, engine
failure -> 500, malformed request -> 400.

Fleet mode (``PolicyServer(fleet=...)`` or ``scripts/serve_fleet.py``) serves
the same ``/v1/act`` through the fleet router and adds:

- ``GET /fleet``          per-replica health/generation/outstanding
- ``POST /v1/push``       {"policy_dir": ...} -> canary-gated weight push
- ``POST /v1/rollback``   roll every replica back to the prior manifest
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from mat_dcml_tpu.serving.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DeadlineExceededError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig


class PolicyClient:
    """In-process client: one joint observation in, one joint action out."""

    def __init__(self, batcher: ContinuousBatcher):
        self.batcher = batcher

    def act(
        self,
        state,
        obs,
        available_actions=None,
        timeout_s: Optional[float] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking request -> ``(action, log_prob)``; raises the batcher's
        typed :class:`ServingError` subclasses on shed/deadline/failure."""
        fut = self.batcher.submit(state, obs, available_actions, timeout_s)
        # the batcher enforces the deadline; the client-side wait gets slack
        # on top so the typed DeadlineExceededError (not a bare concurrent
        # .futures timeout) is what surfaces
        wait = None if timeout_s is None else timeout_s + 5.0
        return fut.result(timeout=wait)


class _Handler(BaseHTTPRequestHandler):
    server_version = "mat-dcml-serving/1"

    def log_message(self, fmt, *args):   # route through the server's logger
        self.server.log_fn("[serving] " + fmt % args)

    def _reply(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/healthz":
            payload = {"ok": True, "warm": srv.warm,
                       "buckets": list(srv.engine.engine_cfg.buckets)}
            if srv.fleet is not None:
                status = srv.fleet.status()
                payload["fleet"] = {"replicas": len(status["replicas"]),
                                    "healthy": status["healthy"],
                                    "generation": status["generation"]}
            self._reply(200, payload)
        elif self.path == "/stats":
            # snapshot under the batcher lock: no torn counter/gauge pairs
            self._reply(200, srv.batcher.stats_snapshot())
        elif self.path == "/fleet":
            if srv.fleet is None:
                self._reply(404, {"error": "not running in fleet mode"})
            else:
                self._reply(200, srv.fleet.status())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/v1/push":
            self._do_push(srv)
            return
        if self.path == "/v1/rollback":
            self._do_rollback(srv)
            return
        if self.path != "/v1/act":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            state = np.asarray(req["state"], np.float32)
            obs = np.asarray(req["obs"], np.float32)
            avail = req.get("available_actions")
            avail = None if avail is None else np.asarray(avail, np.float32)
            timeout_s = req.get("timeout_s")
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        try:
            action, log_prob = srv.client.act(state, obs, avail, timeout_s)
        except QueueFullError as e:
            # a shed client that retries immediately just gets shed again;
            # the hint is queue depth x EMA service time at shed instant
            self._reply(429, {"error": str(e), "kind": "queue_full",
                              "retry_after_s": getattr(e, "retry_after_s", 1)},
                        headers={"Retry-After":
                                 str(getattr(e, "retry_after_s", 1))})
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e), "kind": "deadline_exceeded"})
        except ValueError as e:
            self._reply(400, {"error": str(e), "kind": "bad_shape"})
        except Exception as e:  # ServingError + engine failures
            self._reply(500, {"error": repr(e), "kind": "engine_failure"})
        else:
            self._reply(200, {"action": action.tolist(),
                              "log_prob": log_prob.tolist()})

    def _do_push(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            policy_dir = req["policy_dir"]
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        try:
            report = srv.fleet.push_from_export(policy_dir)
        except RuntimeError as e:       # push already in progress
            self._reply(409, {"error": str(e), "kind": "push_in_progress"})
        except FileNotFoundError as e:
            self._reply(400, {"error": str(e), "kind": "bad_artifact"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "push_failure"})
        else:
            self._reply(200, report)

    def _do_rollback(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            report = srv.fleet.rollback()
        except RuntimeError as e:       # nothing to roll back to
            self._reply(409, {"error": str(e), "kind": "no_prior"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "rollback_failure"})
        else:
            self._reply(200, report)


class PolicyServer:
    """HTTP frontend over (engine, batcher) — or over an
    :class:`~mat_dcml_tpu.serving.fleet.EngineFleet`, which duck-types the
    batcher interface, in which case ``/fleet`` + ``/v1/push`` +
    ``/v1/rollback`` come alive.  ``start()`` binds and serves on a
    background thread; ``port=0`` picks a free port (tests)."""

    def __init__(
        self,
        engine: Optional[DecodeEngine] = None,
        batcher_cfg: BatcherConfig = BatcherConfig(),
        host: str = "127.0.0.1",
        port: int = 8420,
        log_fn=print,
        fleet=None,
    ):
        if (engine is None) == (fleet is None):
            raise ValueError("pass exactly one of engine= or fleet=")
        self.fleet = fleet
        if fleet is not None:
            self.engine = fleet.engine     # bucket/config introspection
            self.batcher = fleet           # router IS the batcher interface
        else:
            self.engine = engine
            self.batcher = ContinuousBatcher(engine, batcher_cfg, log_fn=log_fn)
        self.client = PolicyClient(self.batcher)
        self.log_fn = log_fn
        self.warm = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.policy_server = self
        self._httpd.log_fn = log_fn
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def warmup(self) -> None:
        if self.fleet is not None:
            self.fleet.warmup()
        else:
            self.engine.warmup()
        self.warm = True

    def start(self) -> None:
        if not self.warm:
            self.warmup()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http", daemon=True
        )
        self._thread.start()
        self.log_fn(f"[serving] listening on http://{self._httpd.server_address[0]}"
                    f":{self.port} (buckets {self.engine.engine_cfg.buckets})")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.batcher.close()


def main(argv=None) -> None:
    """CLI: serve a weights-only export.

    Usage: python -m mat_dcml_tpu.serving.server --policy_dir <export>
           [--port 8420] [--buckets 1,8,32,128] [--max_batch_wait_ms 2.0]
           [--max_queue 256] [--decode_mode scan|stride|spec] [--spec_block 8]
    """
    import argparse

    p = argparse.ArgumentParser(description="MAT policy server")
    p.add_argument("--policy_dir", required=True,
                   help="export dir from scripts/export_policy.py")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8420)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch_wait_ms", type=float, default=2.0)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--decode_mode", default="scan", choices=("scan", "stride", "spec"))
    p.add_argument("--spec_block", type=int, default=8)
    args = p.parse_args(argv)

    engine = DecodeEngine.from_export(
        args.policy_dir,
        EngineConfig(
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            decode_mode=args.decode_mode,
            spec_block=args.spec_block,
        ),
    )
    server = PolicyServer(
        engine,
        BatcherConfig(max_queue=args.max_queue,
                      max_batch_wait_ms=args.max_batch_wait_ms),
        host=args.host, port=args.port,
    )
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
