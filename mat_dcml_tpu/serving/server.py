"""Minimal serving frontend: in-process client + stdlib JSON-over-HTTP.

``PolicyClient`` is the canonical interface (tests, loadgen, and co-located
schedulers use it directly — no serialization, no sockets).  ``PolicyServer``
wraps the same batcher in a ``ThreadingHTTPServer`` JSON endpoint for
out-of-process callers; intentionally stdlib-only (no new dependencies):

- ``POST /v1/act``   {"state": [[..]], "obs": [[..]], "available_actions":
  [[..]]?, "timeout_s": float?} -> {"action": [[..]], "log_prob": [[..]]}
- ``GET /healthz``   liveness + warmup state
- ``GET /stats``     telemetry counter/gauge snapshot taken under the batcher
  lock (queue depth, shed counts, bucket occupancy, recompiles)
- ``GET /metrics``   Prometheus text exposition (counters, gauges, and the
  server-side latency summaries — fleet-wide merged across replicas in fleet
  mode) so a live soak run is scrapeable

Typed rejections map onto HTTP: queue-full -> 429 with a ``Retry-After``
header derived from queue depth x EMA service time, deadline -> 504, engine
failure -> 500, malformed request -> 400.

Fleet mode (``PolicyServer(fleet=...)`` or ``scripts/serve_fleet.py``) serves
the same ``/v1/act`` through the fleet router and adds:

- ``GET /fleet``          per-replica health/generation/outstanding
- ``POST /v1/push``       {"policy_dir": ...} -> canary-gated weight push
- ``POST /v1/rollback``   roll every replica back to the prior manifest
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from mat_dcml_tpu.serving.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DeadlineExceededError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig
from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
from mat_dcml_tpu.telemetry.anomaly import AnomalyConfig, AnomalyDetector
from mat_dcml_tpu.telemetry.slo import SLOConfig, SLOMonitor
from mat_dcml_tpu.telemetry.tracing import TraceContext, Tracer


class PolicyClient:
    """In-process client: one joint observation in, one joint action out.

    ``tracer`` makes this an ingress point: each ``act`` mints a sampled
    trace that rides through routing/queueing/decode and is finished when the
    result lands back in the caller's thread."""

    def __init__(self, batcher: ContinuousBatcher,
                 tracer: Optional[Tracer] = None):
        self.batcher = batcher
        self.tracer = tracer

    def act(
        self,
        state,
        obs,
        available_actions=None,
        timeout_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking request -> ``(action, log_prob)``; raises the batcher's
        typed :class:`ServingError` subclasses on shed/deadline/failure."""
        owns = False
        if trace is None and self.tracer is not None:
            trace = self.tracer.start_trace("serving")
            owns = trace is not None
        try:
            fut = self.batcher.submit(state, obs, available_actions, timeout_s,
                                      trace=trace)
            # the batcher enforces the deadline; the client-side wait gets
            # slack on top so the typed DeadlineExceededError (not a bare
            # concurrent.futures timeout) is what surfaces
            wait = None if timeout_s is None else timeout_s + 5.0
            result = fut.result(timeout=wait)
        except BaseException:
            if owns:
                trace.finish(status="error")
            raise
        if owns:
            trace.finish(status="ok")
        return result


class _Handler(BaseHTTPRequestHandler):
    server_version = "mat-dcml-serving/1"

    def log_message(self, fmt, *args):   # route through the server's logger
        self.server.log_fn("[serving] " + fmt % args)

    def _reply(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/metrics":
            self._reply_text(200, srv.metrics_text(),
                             "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/healthz":
            payload = {"ok": True, "warm": srv.warm,
                       "buckets": list(srv.engine.engine_cfg.buckets)}
            if srv.fleet is not None:
                status = srv.fleet.status()
                payload["fleet"] = {"replicas": len(status["replicas"]),
                                    "healthy": status["healthy"],
                                    "generation": status["generation"]}
            self._reply(200, payload)
        elif self.path == "/stats":
            # snapshot under the batcher lock: no torn counter/gauge pairs
            self._reply(200, srv.batcher.stats_snapshot())
        elif self.path == "/fleet":
            if srv.fleet is None:
                self._reply(404, {"error": "not running in fleet mode"})
            else:
                self._reply(200, srv.fleet.status())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv: "PolicyServer" = self.server.policy_server
        if self.path == "/v1/push":
            self._do_push(srv)
            return
        if self.path == "/v1/rollback":
            self._do_rollback(srv)
            return
        if self.path != "/v1/act":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            state = np.asarray(req["state"], np.float32)
            obs = np.asarray(req["obs"], np.float32)
            avail = req.get("available_actions")
            avail = None if avail is None else np.asarray(avail, np.float32)
            timeout_s = req.get("timeout_s")
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        # ingress: mint the (sampled) trace and the SLO latency clock here so
        # the root span covers parse-to-reply — the server-side end-to-end
        trace = srv.tracer.start_trace("serving") if srv.tracer else None
        t0 = time.monotonic()
        try:
            action, log_prob = srv.client.act(state, obs, avail, timeout_s,
                                              trace=trace)
        except QueueFullError as e:
            # a shed client that retries immediately just gets shed again;
            # the hint is the server-side queue-wait EMA at shed instant
            srv.observe_request(t0, ok=False, trace=trace, status="shed")
            self._reply(429, {"error": str(e), "kind": "queue_full",
                              "retry_after_s": getattr(e, "retry_after_s", 1)},
                        headers={"Retry-After":
                                 str(getattr(e, "retry_after_s", 1))})
        except DeadlineExceededError as e:
            srv.observe_request(t0, ok=False, trace=trace, status="deadline")
            self._reply(504, {"error": str(e), "kind": "deadline_exceeded"})
        except ValueError as e:
            # caller bug, not service health: finish the trace, spare the SLO
            if trace is not None:
                trace.finish(status="bad_shape")
            self._reply(400, {"error": str(e), "kind": "bad_shape"})
        except Exception as e:  # ServingError + engine failures
            srv.observe_request(t0, ok=False, trace=trace, status="error")
            self._reply(500, {"error": repr(e), "kind": "engine_failure"})
        else:
            srv.observe_request(t0, ok=True, trace=trace, status="ok")
            self._reply(200, {"action": action.tolist(),
                              "log_prob": log_prob.tolist()})

    def _do_push(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            policy_dir = req["policy_dir"]
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"malformed request: {e!r}"})
            return
        try:
            report = srv.fleet.push_from_export(policy_dir)
        except RuntimeError as e:       # push already in progress
            self._reply(409, {"error": str(e), "kind": "push_in_progress"})
        except FileNotFoundError as e:
            self._reply(400, {"error": str(e), "kind": "bad_artifact"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "push_failure"})
        else:
            self._reply(200, report)

    def _do_rollback(self, srv: "PolicyServer") -> None:
        if srv.fleet is None:
            self._reply(404, {"error": "not running in fleet mode"})
            return
        try:
            report = srv.fleet.rollback()
        except RuntimeError as e:       # nothing to roll back to
            self._reply(409, {"error": str(e), "kind": "no_prior"})
        except Exception as e:
            self._reply(500, {"error": repr(e), "kind": "rollback_failure"})
        else:
            self._reply(200, report)


class PolicyServer:
    """HTTP frontend over (engine, batcher) — or over an
    :class:`~mat_dcml_tpu.serving.fleet.EngineFleet`, which duck-types the
    batcher interface, in which case ``/fleet`` + ``/v1/push`` +
    ``/v1/rollback`` come alive.  ``start()`` binds and serves on a
    background thread; ``port=0`` picks a free port (tests)."""

    def __init__(
        self,
        engine: Optional[DecodeEngine] = None,
        batcher_cfg: BatcherConfig = BatcherConfig(),
        host: str = "127.0.0.1",
        port: int = 8420,
        log_fn=print,
        fleet=None,
        tracer: Optional[Tracer] = None,
        slo_monitor: Optional[SLOMonitor] = None,
        anomaly_cfg: AnomalyConfig = AnomalyConfig(),
    ):
        if (engine is None) == (fleet is None):
            raise ValueError("pass exactly one of engine= or fleet=")
        self.fleet = fleet
        if fleet is not None:
            self.engine = fleet.engine     # bucket/config introspection
            self.batcher = fleet           # router IS the batcher interface
            # the fleet owns tracing/SLO accounting on its own ingress; the
            # HTTP layer defers to it rather than double-counting
            self.tracer = tracer if tracer is not None else fleet.tracer
            self.slo = slo_monitor if slo_monitor is not None else fleet.slo
            self._slo_detector = fleet.anomaly_detector
        else:
            self.engine = engine
            self.batcher = ContinuousBatcher(engine, batcher_cfg, log_fn=log_fn)
            self.tracer = tracer
            self.slo = slo_monitor
            self._slo_detector = (
                AnomalyDetector(anomaly_cfg) if slo_monitor is not None else None)
        self.anomalies: list = []
        self._slo_seen = 0
        self.client = PolicyClient(self.batcher)
        self.log_fn = log_fn
        self.warm = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.policy_server = self
        self._httpd.log_fn = log_fn
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # --------------------------------------------------------- observability

    def metrics_text(self) -> str:
        """Prometheus text for ``GET /metrics``: merged counters/gauges and
        fleet-wide latency summaries, plus live SLO burn gauges."""
        agg = TelemetryAggregator()
        if self.fleet is not None:
            agg.add_source("fleet", self.fleet.telemetry)
            for r in self.fleet.replicas:
                agg.add_source(str(r.rid), r.engine.telemetry)
        else:
            agg.add_source("0", self.batcher.telemetry)
        extra = self.slo.gauges() if self.slo is not None else None
        return agg.prometheus_text(extra_gauges=extra)

    def observe_request(self, t0: float, ok: bool, trace=None,
                        status: str = "ok") -> None:
        """Terminal HTTP-path accounting: finish the ingress trace (idempotent
        — the fleet may have finished it first) and feed the SLO monitor,
        unless the fleet already fed this request at its own ingress."""
        if trace is not None:
            trace.finish(status=status)
        if self.slo is None:
            return
        if self.fleet is not None and self.fleet.slo is self.slo:
            return
        self.slo.observe_request((time.monotonic() - t0) * 1e3, ok=ok)
        self._slo_seen += 1
        if self._slo_detector is not None and self._slo_seen % 16 == 0:
            trips = self._slo_detector.observe(
                self.slo.burn_signals(), episode=0,
                total_steps=int(self.slo.total_requests))
            for a in trips:
                self.anomalies.append(a.to_record())
                self.log_fn(f"[serving] SLO budget anomaly: {a.kind}")

    def warmup(self) -> None:
        if self.fleet is not None:
            self.fleet.warmup()
        else:
            self.engine.warmup()
        self.warm = True

    def start(self) -> None:
        if not self.warm:
            self.warmup()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http", daemon=True
        )
        self._thread.start()
        self.log_fn(f"[serving] listening on http://{self._httpd.server_address[0]}"
                    f":{self.port} (buckets {self.engine.engine_cfg.buckets})")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.batcher.close()


def main(argv=None) -> None:
    """CLI: serve a weights-only export.

    Usage: python -m mat_dcml_tpu.serving.server --policy_dir <export>
           [--port 8420] [--buckets 1,8,32,128] [--max_batch_wait_ms 2.0]
           [--max_queue 256] [--decode_mode cached|scan|stride|spec]
           [--spec_block 8] [--serve_dtype f32|bf16]
    """
    import argparse

    p = argparse.ArgumentParser(description="MAT policy server")
    p.add_argument("--policy_dir", required=True,
                   help="export dir from scripts/export_policy.py")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8420)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch_wait_ms", type=float, default=2.0)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--decode_mode", default="cached",
                   choices=("cached", "scan", "stride", "spec"))
    p.add_argument("--spec_block", type=int, default=8)
    p.add_argument("--serve_dtype", default="f32", choices=("f32", "bf16"),
                   help="serving trunk precision; bf16 casts params at "
                        "install time and is gated by value-tolerance (not "
                        "bit-parity) canary comparison in fleet mode")
    p.add_argument("--run_dir", default=None,
                   help="observability output dir (enables trace.jsonl)")
    p.add_argument("--trace_sample", type=float, default=0.01,
                   help="fraction of requests traced (0 disables)")
    p.add_argument("--trace_max_mb", type=float, default=64.0)
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="latency SLO target for burn-rate tracking; 0 off")
    args = p.parse_args(argv)

    tracer = (Tracer(args.run_dir, sample=args.trace_sample,
                     max_mb=args.trace_max_mb)
              if args.run_dir else None)
    slo = (SLOMonitor(SLOConfig(latency_p99_ms=args.slo_p99_ms))
           if args.slo_p99_ms > 0 else None)

    engine = DecodeEngine.from_export(
        args.policy_dir,
        EngineConfig(
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            decode_mode=args.decode_mode,
            spec_block=args.spec_block,
            serve_dtype=args.serve_dtype,
        ),
    )
    server = PolicyServer(
        engine,
        BatcherConfig(max_queue=args.max_queue,
                      max_batch_wait_ms=args.max_batch_wait_ms),
        host=args.host, port=args.port,
        tracer=tracer, slo_monitor=slo,
    )
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
