"""Replicated serving fleet: load-aware router, fault tolerance, hot push.

The Gemma-on-TPU serving shape (PAPERS.md): N :class:`DecodeEngine` replicas
— one per local device when the host has several, N engines on one device on
a CPU dev box — each behind its own :class:`ContinuousBatcher`, fronted by a
load-aware router.  The fleet is the unit the HTTP server and the loadgen
talk to; it duck-types the batcher interface (``submit``/``close``/
``telemetry``/``engine``) so every existing client works unchanged.

**Routing** — least-outstanding-requests over the healthy pool, with a
health-score tie-break (a replica that has been limping through degraded
single-request retries scores worse than a clean sibling) and a rotating
round-robin tie-break so an idle fleet still spreads load.

**Fault tolerance** — a replica that throws, times out an attempt, or trips
its recompile detector is marked UNHEALTHY: its in-flight requests are
retried on a sibling (bounded ``max_retries`` with jittered exponential
backoff; safe because decode is pure — a duplicate attempt returns identical
bits and the first resolution wins), and a background prober replays a
synthetic bucket through the sick engine until ``probe_successes``
consecutive passes readmit it.

**Hot weight-swap** — :meth:`EngineFleet.push` installs a new params set one
replica at a time via the engine's atomic publish-then-swap (the old program
serves until the new bucket ladder is warm; a warm pass that re-enters XLA
rejects the artifact before any client sees it).  The first swapped replica
is the **canary**: it leaves the live pool and serves shadow traffic —
duplicates of live incumbent-served requests, plus pusher-driven synthetic
probes so a quiet fleet still gates — through the
:class:`~mat_dcml_tpu.serving.rollout_ctl.RolloutController`'s parity/
latency/error gate.  Promotion rolls the remaining replicas; any trip rolls
every swapped replica back to the prior weights and records a typed
``rollout_rollback`` anomaly.  Zero requests are shed by the push itself:
the report carries the measured ``push_dropped`` delta.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.models.mat import MATConfig
from mat_dcml_tpu.serving.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DeadlineExceededError,
    QueueFullError,
    ServingError,
)
from mat_dcml_tpu.serving.engine import DecodeEngine, EngineConfig
from mat_dcml_tpu.serving.rollout_ctl import (
    COMPLETE,
    PROMOTE,
    ROLLED_BACK,
    ROLLING,
    RolloutConfig,
    RolloutController,
)
from mat_dcml_tpu.telemetry import Telemetry
from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
from mat_dcml_tpu.telemetry.anomaly import AnomalyConfig, AnomalyDetector, rollout_anomaly
from mat_dcml_tpu.telemetry.slo import SLOMonitor
from mat_dcml_tpu.telemetry.tracing import Tracer

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"
CANARY_STATE = "canary"

_STATE_CODE = {UNHEALTHY: 0.0, HEALTHY: 1.0, CANARY_STATE: 2.0}


class FleetUnavailableError(ServingError):
    """Every replica is unhealthy; the request cannot be placed."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    n_replicas: int = 2
    max_retries: int = 2              # sibling retries per request
    backoff_base_ms: float = 5.0      # jittered exponential backoff base
    request_timeout_s: Optional[float] = None  # per-ATTEMPT watchdog; a late
                                      # attempt fails over to a sibling while
                                      # the original keeps running (decode is
                                      # pure, first resolution wins)
    probe_interval_s: float = 0.25    # unhealthy-replica probe cadence
    probe_successes: int = 2          # consecutive passes before readmission

    def __post_init__(self):
        if self.n_replicas < 1:
            raise ValueError("FleetConfig.n_replicas must be >= 1")


class Replica:
    """One engine + batcher + health record.  Mutable health fields are
    guarded by the fleet lock."""

    def __init__(self, rid: int, engine: DecodeEngine,
                 batcher_cfg: BatcherConfig, log_fn):
        self.rid = rid
        self.engine = engine
        # replica identity on the engine itself: the chaos injector's decode
        # seam targets faults at specific replicas through this attribute
        engine.replica_id = rid
        self.batcher = ContinuousBatcher(
            engine, batcher_cfg, telemetry=engine.telemetry, log_fn=log_fn)
        self.state = HEALTHY
        self.outstanding = 0
        self.generation = 0
        self.probe_ok = 0
        self.unhealthy_since: Optional[float] = None

    def health_penalty(self) -> float:
        """Degraded-path history as a routing tie-break: a replica that has
        been retrying requests one-by-one (or failing them) is a worse bet
        than a clean sibling at equal queue depth."""
        c = self.engine.telemetry.counters
        return (c.get("serving_degraded_failed", 0.0) * 1.0
                + c.get("serving_degraded_ok", 0.0) * 0.25)

    def install(self, params, generation: int) -> int:
        """Warm-then-swap; returns warm-pass compile count (0 = healthy)."""
        recompiles = self.engine.install_params(params, warm=True)
        self.generation = generation
        return recompiles


class _RequestCtx:
    __slots__ = ("state", "obs", "avail", "timeout_s", "attempts", "tried",
                 "trace", "t_ingress")

    def __init__(self, state, obs, avail, timeout_s, trace=None):
        self.state = state
        self.obs = obs
        self.avail = avail
        self.timeout_s = timeout_s
        self.attempts = 0
        self.tried: set = set()
        self.trace = trace            # sampled span tree; one id across hops
        self.t_ingress = time.monotonic()


def _resolve(fut: Future, result=None, exc: Optional[BaseException] = None) -> None:
    """Set a future exactly once; duplicate resolutions (timeout failover
    racing the original attempt) are expected and dropped."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass


class EngineFleet:
    """N replicas behind a load-aware router.  Duck-types the batcher
    interface (``submit``/``close``/``telemetry``) plus ``engine``/``cfg`` so
    :class:`~mat_dcml_tpu.serving.server.PolicyClient` and the loadgen drive
    a fleet exactly like a single batcher."""

    def __init__(
        self,
        params,
        cfg: MATConfig,
        fleet_cfg: FleetConfig = FleetConfig(),
        engine_cfg: EngineConfig = EngineConfig(),
        batcher_cfg: BatcherConfig = BatcherConfig(),
        rollout_cfg: RolloutConfig = RolloutConfig(),
        telemetry: Optional[Telemetry] = None,
        log_fn=print,
        generation: int = 0,
        tracer: Optional[Tracer] = None,
        slo_monitor: Optional[SLOMonitor] = None,
        anomaly_cfg: AnomalyConfig = AnomalyConfig(),
    ):
        self.cfg = cfg
        self.fleet_cfg = fleet_cfg
        self.engine_cfg = engine_cfg
        self.rollout_cfg = rollout_cfg
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.log = log_fn
        self.tracer = tracer
        self.slo = slo_monitor
        # SLO burns thread through the same detector the trainer uses: budget
        # exhaustion becomes a typed slo_*_budget anomaly with cooldown, and
        # a tripped budget gates weight-push promotion.
        self.anomaly_detector = (
            AnomalyDetector(anomaly_cfg, telemetry=self.telemetry,
                            exemplar_fn=self._trace_exemplar)
            if slo_monitor is not None else None)
        self.anomalies: List[dict] = []
        self._slo_seen = 0
        self._slo_check_every = 16    # burn math is O(window); amortize it
        self.current_generation = generation
        self._params_current = params
        self._prior: Optional[Tuple[object, int]] = None
        self._lock = threading.Lock()
        self._rr = 0
        self._closed = False
        self._warm = False
        self._push_lock = threading.Lock()
        self._canary_rid: Optional[int] = None
        self._controller: Optional[RolloutController] = None
        self.rollout_events: List[dict] = []

        devices = jax.local_devices()
        self.replicas: List[Replica] = []
        for rid in range(fleet_cfg.n_replicas):
            device = devices[rid % len(devices)] if len(devices) > 1 else None
            engine = DecodeEngine(
                params, cfg, engine_cfg,
                telemetry=Telemetry(),      # per-replica metric isolation
                log_fn=self._replica_log(rid), device=device,
            )
            replica = Replica(rid, engine, batcher_cfg, self._replica_log(rid))
            replica.generation = generation
            self.replicas.append(replica)

        self._prober = threading.Thread(
            target=self._probe_loop, name="fleet-prober", daemon=True)
        self._prober.start()

    def _replica_log(self, rid: int):
        return lambda msg: self.log(f"[fleet r{rid}] {msg}")

    # ------------------------------------------------------------- lifecycle

    @classmethod
    def from_export(cls, directory, **kwargs) -> "EngineFleet":
        """Build a fleet from a weights-only export; the manifest's
        ``generation`` seeds the fleet's ordering counter."""
        from mat_dcml_tpu.training.checkpoint import load_policy, read_manifest

        params, cfg, space_meta = load_policy(directory)
        generation = int(read_manifest(directory).get("generation", 0))
        fleet = cls(params, cfg, generation=generation, **kwargs)
        fleet.space_meta = space_meta
        return fleet

    def warmup(self) -> None:
        for replica in self.replicas:
            t0 = time.perf_counter()
            replica.engine.warmup()
            self.log(f"[fleet] replica {replica.rid} warm "
                     f"({time.perf_counter() - t0:.1f}s, device "
                     f"{replica.engine.device})")
        self._warm = True
        self.telemetry.gauge("fleet_replicas", float(len(self.replicas)))

    @property
    def engine(self) -> DecodeEngine:
        """Primary replica's engine — config/bucket introspection only."""
        return self.replicas[0].engine

    def close(self, timeout_s: float = 5.0) -> None:
        self._closed = True
        for replica in self.replicas:
            replica.batcher.close(timeout_s=timeout_s)

    # --------------------------------------------------------------- routing

    def _pick(self, tried: set) -> Optional[Replica]:
        """Least-outstanding healthy replica, health-penalty then rotating
        tie-break.  The canary is excluded from live traffic unless it is the
        only survivor."""
        with self._lock:
            self._rr += 1
            pool = [r for r in self.replicas
                    if r.state == HEALTHY and r.rid not in tried]
            if not pool:
                pool = [r for r in self.replicas
                        if r.state == CANARY_STATE and r.rid not in tried]
            if not pool:
                return None
            n = len(self.replicas)
            pool.sort(key=lambda r: (
                r.outstanding,
                r.health_penalty(),
                (r.rid - self._rr) % n,
            ))
            choice = pool[0]
            choice.outstanding += 1
            return choice

    def submit(
        self,
        state: np.ndarray,
        obs: np.ndarray,
        avail: Optional[np.ndarray] = None,
        timeout_s: Optional[float] = None,
        trace=None,
    ) -> Future:
        """Route one joint observation; same contract as
        :meth:`ContinuousBatcher.submit` with fleet semantics on top:
        replica failures retry on siblings, total shed only when every
        replica's queue is full."""
        if self._closed:
            raise ServingError("fleet is closed")
        if trace is None and self.tracer is not None:
            trace = self.tracer.start_trace("serving")
        outer: Future = Future()
        ctx = _RequestCtx(state, obs, avail, timeout_s, trace=trace)
        self.telemetry.count("fleet_requests")
        try:
            self._attempt(ctx, outer, first=True)
        except ServingError:
            self._observe_outcome(ctx, ok=False, status="shed")
            raise
        return outer

    def _attempt(self, ctx: _RequestCtx, outer: Future, first: bool = False) -> None:
        sheds: List[int] = []
        while True:
            if outer.done():
                return
            replica = self._pick(ctx.tried)
            if replica is None:
                if sheds:
                    # every live replica refused admission — fleet-level shed
                    self.telemetry.count("fleet_shed")
                    exc: ServingError = QueueFullError(
                        "all replica queues at capacity",
                        retry_after_s=min(sheds))
                else:
                    # total outage: brownout with an honest Retry-After
                    # (one full probe-readmission cycle) instead of an
                    # EngineFailureError/FleetUnavailableError storm — clients
                    # back off and retry; requests arriving after the outage
                    # clears succeed normally
                    self.telemetry.count("fleet_no_healthy")
                    self.telemetry.count("fleet_brownout")
                    retry_after = max(1, math.ceil(
                        self.fleet_cfg.probe_interval_s
                        * max(1, self.fleet_cfg.probe_successes)))
                    exc = QueueFullError(
                        "fleet brownout: no healthy replicas (probes will "
                        "readmit)", retry_after_s=retry_after)
                if first:
                    raise exc    # keep the batcher's synchronous-shed contract
                self._observe_outcome(ctx, ok=False, status="unplaceable")
                _resolve(outer, exc=exc)
                return
            try:
                inner = replica.batcher.submit(
                    ctx.state, ctx.obs, ctx.avail, ctx.timeout_s,
                    trace=ctx.trace)
            except QueueFullError as e:
                with self._lock:
                    replica.outstanding -= 1
                ctx.tried.add(replica.rid)
                sheds.append(e.retry_after_s)
                continue
            except ValueError:
                with self._lock:
                    replica.outstanding -= 1
                raise    # malformed request: caller bug, not replica health
            except ServingError as e:
                with self._lock:
                    replica.outstanding -= 1
                self._mark_unhealthy(replica, f"submit refused: {e!r}")
                ctx.tried.add(replica.rid)
                continue
            break

        t0 = time.monotonic()
        t0_pc = time.perf_counter()   # span clock twin of t0
        timer: Optional[threading.Timer] = None
        if self.fleet_cfg.request_timeout_s is not None:
            timer = threading.Timer(
                self.fleet_cfg.request_timeout_s,
                self._attempt_timed_out, args=(ctx, outer, replica, inner))
            timer.daemon = True
            timer.start()
        inner.add_done_callback(
            lambda fut: self._on_done(ctx, outer, replica, fut, t0, t0_pc, timer))
        if first:
            self._maybe_shadow(ctx, inner, t0)

    def _on_done(self, ctx, outer, replica: Replica, inner: Future,
                 t0: float, t0_pc: float, timer: Optional[threading.Timer]) -> None:
        if timer is not None:
            timer.cancel()
        with self._lock:
            replica.outstanding -= 1
        exc = inner.exception()
        latency_ms = (time.monotonic() - t0) * 1e3
        ok = exc is None
        if ctx.trace is not None:
            # one hop of the tree: failover retries add further attempt spans
            # under the same trace id
            ctx.trace.add_span("attempt", t0_pc, time.perf_counter(),
                               replica=replica.rid, retry=ctx.attempts,
                               ok=ok)
        if ok:
            if (self._controller is not None
                    and replica.rid != self._canary_rid):
                self._controller._tripwire.observe_incumbent(latency_ms)
            if not outer.done():   # a raced failover sibling already counted
                self._observe_outcome(ctx, ok=True, status="ok",
                                      replica=replica)
            _resolve(outer, result=inner.result())
            return
        if isinstance(exc, DeadlineExceededError):
            # the request's own budget elapsed — retrying can't help
            self._observe_outcome(ctx, ok=False, status="deadline",
                                  replica=replica)
            _resolve(outer, exc=exc)
            return
        self._mark_unhealthy(replica, repr(exc))
        self._retry(ctx, outer, replica)

    def _attempt_timed_out(self, ctx, outer, replica: Replica,
                           inner: Future) -> None:
        if inner.done() or outer.done():
            return
        self.telemetry.count("fleet_attempt_timeouts")
        self._mark_unhealthy(
            replica, f"attempt exceeded {self.fleet_cfg.request_timeout_s}s")
        # the original attempt keeps running; decode is pure, so if it lands
        # first its bits are identical to the sibling's — first resolve wins
        self._retry(ctx, outer, replica)

    def _retry(self, ctx, outer, failed: Replica) -> None:
        if outer.done():
            return
        ctx.tried.add(failed.rid)
        if ctx.attempts >= self.fleet_cfg.max_retries:
            self.telemetry.count("fleet_retries_exhausted")
            self._observe_outcome(ctx, ok=False, status="retries_exhausted")
            _resolve(outer, exc=ServingError(
                f"request failed on {ctx.attempts + 1} replicas"))
            return
        ctx.attempts += 1
        self.telemetry.count("fleet_retries")
        base = self.fleet_cfg.backoff_base_ms / 1e3
        delay = base * (2 ** (ctx.attempts - 1)) * (0.5 + random.random())
        timer = threading.Timer(delay, self._attempt, args=(ctx, outer))
        timer.daemon = True
        timer.start()

    # ------------------------------------------------------------ observe/SLO

    def _observe_outcome(self, ctx: _RequestCtx, ok: bool, status: str,
                         replica: Optional[Replica] = None) -> None:
        """Terminal accounting for one request: finish its trace, feed the
        SLO monitor, and (amortized) run the burn-rate tripwires."""
        if ctx.trace is not None:
            attrs = {"status": status}
            if replica is not None:
                attrs["replica"] = replica.rid
            ctx.trace.finish(**attrs)
        if self.slo is None:
            return
        latency_ms = (time.monotonic() - ctx.t_ingress) * 1e3
        self.slo.observe_request(latency_ms, ok=ok)
        self._slo_seen += 1
        if self._slo_seen % self._slo_check_every == 0:
            self.check_slo()

    def _trace_exemplar(self) -> Optional[str]:
        """Most recent sampled trace id — pinned on anomaly trips so an
        incident links to one concrete request tree."""
        return self.tracer.last_trace_id if self.tracer is not None else None

    def check_slo(self) -> List[dict]:
        """Run the SLO burn gauges through the anomaly detector; returns (and
        remembers) any typed ``slo_*_budget`` trips.  Also callable by the
        server's stats path so a quiet fleet still evaluates its windows."""
        det = self.anomaly_detector
        if det is None or self.slo is None:
            return []
        signals = self.slo.export_into(self.telemetry)
        trips = det.observe(
            {k: v for k, v in signals.items() if k.endswith("_burn")},
            episode=int(self.current_generation),
            total_steps=int(self.slo.total_requests))
        out = []
        for a in trips:
            if _chaos.ACTIVE is not None:
                event_id = _chaos.ACTIVE.suppression_for(a.kind)
                if event_id is not None:
                    # expected under the armed fault plan: correlated +
                    # counted by the injector, but it doesn't page
                    self.log(f"[fleet] SLO anomaly {a.kind} suppressed — "
                             f"expected under chaos event {event_id}")
                    continue
            rec = a.to_record()
            self.anomalies.append(rec)
            self.log(f"[fleet] SLO budget anomaly: {rec['anomaly']} "
                     f"(burn {rec['value']:.2f})")
            out.append(rec)
        return out

    def _slo_exhausted(self) -> bool:
        """Promotion gate: is any combined (multi-window) burn at or past the
        tripwire threshold right now?"""
        if self.slo is None or self.anomaly_detector is None:
            return False
        thr = self.anomaly_detector.cfg.slo_burn_threshold
        return any(v >= thr for v in self.slo.burn_signals().values())

    # ---------------------------------------------------------------- health

    def _mark_unhealthy(self, replica: Replica, why: str) -> None:
        with self._lock:
            if replica.state == UNHEALTHY:
                return
            was_canary = replica.state == CANARY_STATE
            replica.state = UNHEALTHY
            replica.probe_ok = 0
            replica.unhealthy_since = time.monotonic()
        self.telemetry.count("fleet_unhealthy_marks")
        self.log(f"[fleet] replica {replica.rid} marked UNHEALTHY: {why}")
        if was_canary and self._controller is not None:
            self._controller.record_canary_error(ServingError(why))

    def _probe_loop(self) -> None:
        while not self._closed:
            time.sleep(self.fleet_cfg.probe_interval_s)
            if not self._warm:
                continue
            for replica in self.replicas:
                if replica.state != UNHEALTHY:
                    continue
                try:
                    b = replica.engine.min_bucket
                    cfg = self.cfg
                    replica.engine.decode(
                        np.zeros((b, cfg.n_agent, cfg.state_dim), np.float32),
                        np.zeros((b, cfg.n_agent, cfg.obs_dim), np.float32),
                        np.ones((b, cfg.n_agent, cfg.action_dim), np.float32),
                    )
                except Exception as e:
                    replica.probe_ok = 0
                    self.telemetry.count("fleet_probe_failures")
                    self.log(f"[fleet] probe of replica {replica.rid} "
                             f"failed: {e!r}")
                    continue
                replica.probe_ok += 1
                if replica.probe_ok >= self.fleet_cfg.probe_successes:
                    with self._lock:
                        replica.state = HEALTHY
                        replica.unhealthy_since = None
                    self.telemetry.count("fleet_readmissions")
                    self.log(f"[fleet] replica {replica.rid} readmitted "
                             f"after {replica.probe_ok} clean probes")

    # ------------------------------------------------------------ shadowing

    def _maybe_shadow(self, ctx, primary: Future, p_t0: float) -> None:
        """During CANARY, duplicate a live incumbent-served request onto the
        canary and feed the pair to the controller.  The client only ever
        sees the incumbent's answer."""
        controller = self._controller
        canary_rid = self._canary_rid
        if controller is None or canary_rid is None:
            return
        canary = self.replicas[canary_rid]
        if canary.state != CANARY_STATE:
            return
        try:
            shadow = canary.batcher.submit(
                ctx.state, ctx.obs, ctx.avail, ctx.timeout_s)
        except Exception as e:
            controller.record_canary_error(e)
            return
        s_t0 = time.monotonic()
        pair: Dict[str, Optional[Future]] = {"primary": None, "shadow": None}
        pair_lock = threading.Lock()

        def arm(slot):
            def cb(fut):
                with pair_lock:
                    pair[slot] = fut
                    ready = pair["primary"] is not None and pair["shadow"] is not None
                if ready:
                    self._compare_pair(controller, pair["primary"],
                                       pair["shadow"], p_t0, s_t0)
            return cb

        primary.add_done_callback(arm("primary"))
        shadow.add_done_callback(arm("shadow"))

    def _compare_pair(self, controller, primary: Future, shadow: Future,
                      p_t0: float, s_t0: float) -> None:
        if primary.exception() is not None:
            return    # nothing to compare against; incumbent health is
                      # handled by the normal retry path
        if shadow.exception() is not None:
            controller.record_canary_error(shadow.exception())
            return
        now = time.monotonic()
        controller.compare(
            primary.result(), shadow.result(),
            (now - p_t0) * 1e3, (now - s_t0) * 1e3,
        )

    def _synthetic_shadow(self, controller, incumbent: Replica,
                          canary: Replica, seed: int) -> None:
        """Pusher-driven shadow probe: one synthetic request decoded by both
        an incumbent and the canary directly at the engine, so a fleet with
        no live traffic still accumulates gated comparisons."""
        from mat_dcml_tpu.serving.loadgen import synth_requests

        states, obs, avail = synth_requests(self.cfg, 1, seed=seed)
        b = incumbent.engine.min_bucket
        s = np.repeat(states, b, axis=0)
        o = np.repeat(obs, b, axis=0)
        a = np.repeat(avail, b, axis=0)
        t0 = time.monotonic()
        try:
            inc_act, inc_logp = incumbent.engine.decode(s, o, a)
        except Exception:
            return   # incumbent trouble is the router's problem, not the gate's
        t1 = time.monotonic()
        try:
            can_act, can_logp = canary.engine.decode(s, o, a)
        except Exception as e:
            controller.record_canary_error(e)
            return
        t2 = time.monotonic()
        controller.compare(
            (inc_act[0], inc_logp[0]), (can_act[0], can_logp[0]),
            (t1 - t0) * 1e3, (t2 - t1) * 1e3,
        )

    # ------------------------------------------------------------ weight push

    def push_from_export(self, directory) -> dict:
        from mat_dcml_tpu.training.checkpoint import load_policy, read_manifest

        params, cfg, _ = load_policy(directory)
        generation = int(read_manifest(directory).get("generation",
                                                      self.current_generation + 1))
        return self.push(params, generation=generation)

    def push(self, params, generation: Optional[int] = None) -> dict:
        """Canary-gated hot weight push.  Blocks until the rollout resolves;
        returns a report dict (``status`` promoted | rolled_back | rejected).
        Raises RuntimeError if a push is already in flight."""
        if not self._push_lock.acquire(blocking=False):
            raise RuntimeError("a weight push is already in progress")
        try:
            return self._push_locked(params, generation)
        finally:
            self._canary_rid = None
            self._controller = None
            self._push_lock.release()

    def _push_locked(self, params, generation: Optional[int]) -> dict:
        if generation is None:
            generation = self.current_generation + 1
        prior_params = self._params_current
        prior_generation = self.current_generation
        dropped_before = self._client_drop_count()
        t_start = time.perf_counter()
        report = {
            "status": "", "generation": generation,
            "prior_generation": prior_generation,
            "comparisons": 0, "mismatches": 0,
            "warm_recompiles": 0, "push_dropped": 0, "events": [],
        }

        with self._lock:
            healthy = [r for r in self.replicas if r.state == HEALTHY]
        if not healthy:
            raise ServingError("no healthy replica to canary")
        canary = healthy[0]

        # --- canary swap: warm the new ladder while the old params serve
        recompiles = canary.install(params, generation)
        report["warm_recompiles"] = recompiles
        if recompiles > 0:
            # artifact drift re-entered XLA during warm: reject before any
            # client request can see the new weights
            canary.install(prior_params, prior_generation)
            self._record_rollout_event(rollout_anomaly(
                "rollout_warm_recompile", "warm_pass_compiles",
                float(recompiles), 0.0, generation, self.telemetry))
            report["status"] = "rejected"
            report["push_dropped"] = self._client_drop_count() - dropped_before
            self.log(f"[fleet] push gen {generation} REJECTED: warm pass "
                     f"compiled {recompiles} program(s)")
            return report

        if len(self.replicas) == 1:
            # nothing to shadow against — swap is already done, promote
            self.log("[fleet] single-replica fleet: skipping canary gate")
            self._promote(params, generation)
            report["status"] = "promoted"
            report["wall_s"] = time.perf_counter() - t_start
            return report

        # a bf16 trunk is a healthy ~1e-2 relative off the f32 incumbent:
        # the gate swaps to the widened value tolerances instead of reading
        # opted-into precision as a corrupt artifact (bit-parity on greedy
        # actions stays, budgeted by max_mismatch_frac as always)
        controller = RolloutController(
            self.rollout_cfg.effective_for(self.engine_cfg.serve_dtype),
            prior_generation, generation,
            telemetry=self.telemetry, log_fn=self.log)
        with self._lock:
            canary.state = CANARY_STATE
            self._canary_rid = canary.rid
            self._controller = controller

        # drive synthetic shadow probes until the gate decides (live traffic
        # contributes concurrently through _maybe_shadow)
        deadline = time.monotonic() + self.rollout_cfg.canary_timeout_s
        seed = 0
        while controller.verdict() is None and time.monotonic() < deadline:
            with self._lock:
                incumbents = [r for r in self.replicas
                              if r.state == HEALTHY and r.rid != canary.rid]
            if incumbents:
                self._synthetic_shadow(controller, incumbents[seed % len(incumbents)],
                                       canary, seed)
                seed += 1
            time.sleep(self.rollout_cfg.synthetic_interval_s)
        verdict = controller.wait(timeout_s=0.0)

        if verdict == PROMOTE and self._slo_exhausted():
            # an exhausted error budget vetoes promotion even when the canary
            # itself gated clean: never widen a rollout into a burning fleet
            self.telemetry.count("rollout_slo_gated")
            self.log(f"[fleet] push gen {generation}: SLO error budget "
                     "exhausted — promotion vetoed")
            verdict = None

        summary = controller.summary()
        report["comparisons"] = summary["comparisons"]
        report["mismatches"] = (summary["parity_mismatches"]
                                + summary["value_mismatches"])
        for event in summary["events"]:
            self._record_rollout_event_dict(event)
        report["events"] = list(summary["events"])

        if verdict != PROMOTE:
            controller.state = ROLLED_BACK
            canary.install(prior_params, prior_generation)
            with self._lock:
                if canary.state == CANARY_STATE:
                    canary.state = HEALTHY
            rollback = rollout_anomaly(
                "rollout_rollback", "canary_verdict",
                float(report["mismatches"]), float(report["comparisons"]),
                generation, self.telemetry)
            self._record_rollout_event(rollback)
            report["events"].append(rollback.to_record())
            self.telemetry.count("rollout_rollbacks")
            report["status"] = "rolled_back"
            report["push_dropped"] = self._client_drop_count() - dropped_before
            report["wall_s"] = time.perf_counter() - t_start
            self.log(f"[fleet] push gen {generation} ROLLED BACK "
                     f"({report['mismatches']}/{report['comparisons']} "
                     f"mismatches)")
            return report

        # --- promote: roll the remaining replicas one at a time
        controller.state = ROLLING
        with self._lock:
            if canary.state == CANARY_STATE:
                canary.state = HEALTHY
            self._canary_rid = None
            self._controller = None
        swapped = [canary]
        for replica in self.replicas:
            if replica is canary:
                continue
            recompiles = replica.install(params, generation)
            if recompiles > 0:
                # mid-roll drift: put EVERY swapped replica back
                for r in swapped + [replica]:
                    r.install(prior_params, prior_generation)
                self._record_rollout_event(rollout_anomaly(
                    "rollout_warm_recompile", "warm_pass_compiles",
                    float(recompiles), 0.0, generation, self.telemetry))
                self.telemetry.count("rollout_rollbacks")
                report["status"] = "rolled_back"
                report["push_dropped"] = self._client_drop_count() - dropped_before
                report["wall_s"] = time.perf_counter() - t_start
                return report
            swapped.append(replica)

        controller.state = COMPLETE
        self._promote(params, generation)
        report["status"] = "promoted"
        report["push_dropped"] = self._client_drop_count() - dropped_before
        report["wall_s"] = time.perf_counter() - t_start
        self.log(f"[fleet] push gen {generation} PROMOTED "
                 f"({report['comparisons']} comparisons, "
                 f"{report['mismatches']} mismatches, "
                 f"{report['push_dropped']} dropped)")
        return report

    def _promote(self, params, generation: int) -> None:
        self._prior = (self._params_current, self.current_generation)
        self._params_current = params
        self.current_generation = generation
        self.telemetry.count("rollout_pushes")

    def rollback(self) -> dict:
        """Manual rollback to the prior promoted manifest."""
        if self._prior is None:
            raise RuntimeError("no prior generation to roll back to")
        prior_params, prior_generation = self._prior
        for replica in self.replicas:
            replica.install(prior_params, prior_generation)
        rollback = rollout_anomaly(
            "rollout_rollback", "manual",
            float(self.current_generation), float(prior_generation),
            self.current_generation, self.telemetry)
        self._record_rollout_event(rollback)
        self.telemetry.count("rollout_rollbacks")
        self._params_current = prior_params
        self.current_generation = prior_generation
        self._prior = None
        return {"status": "rolled_back", "generation": prior_generation}

    def _client_drop_count(self) -> float:
        """Client-visible request drops: fleet-level sheds, exhausted
        retries, unplaceable requests, plus per-replica deadline misses.
        Replica failures that were retried successfully are NOT drops."""
        c = self.telemetry.counters
        total = (c.get("fleet_shed", 0.0)
                 + c.get("fleet_retries_exhausted", 0.0)
                 + c.get("fleet_no_healthy", 0.0))
        for replica in self.replicas:
            total += replica.engine.telemetry.counters.get(
                "serving_deadline_misses", 0.0)
        return total

    def _record_rollout_event(self, anomaly) -> None:
        self._record_rollout_event_dict(anomaly.to_record())

    def _record_rollout_event_dict(self, record: dict) -> None:
        self.rollout_events.append(record)

    # ------------------------------------------------------------ accounting

    def status(self) -> dict:
        """Human/HTTP-facing fleet state (the ``/fleet`` endpoint)."""
        with self._lock:
            replicas = [{
                "rid": r.rid,
                "state": r.state,
                "outstanding": r.outstanding,
                "generation": r.generation,
                "compile_count": r.engine.compile_count(),
                "steady_state_recompiles": r.engine.steady_state_recompiles(),
            } for r in self.replicas]
        return {
            "replicas": replicas,
            "generation": self.current_generation,
            "healthy": sum(1 for r in replicas if r["state"] == HEALTHY),
            "push_in_progress": self._push_lock.locked(),
            "rollout_events": list(self.rollout_events[-16:]),
        }

    def stats_snapshot(self) -> dict:
        """Aggregated counter snapshot: fleet counters plus each replica's
        batcher snapshot (each taken under its own lock)."""
        return {
            "counters": dict(self.telemetry.counters),
            "gauges": dict(self.telemetry._gauges),
            "replicas": {r.rid: r.batcher.stats_snapshot()
                         for r in self.replicas},
        }

    def aggregator(self) -> TelemetryAggregator:
        """Read-side merge over the per-replica registries (plus the fleet's
        own counters) — the source for ``/metrics`` and fleet-wide
        percentiles."""
        agg = TelemetryAggregator()
        for r in self.replicas:
            agg.add_source(str(r.rid), r.engine.telemetry)
        return agg

    def fleet_record(self) -> Dict[str, float]:
        """Flat metrics.jsonl fragment: the ``fleet_``/``rollout_`` families
        (`scripts/check_metrics_schema.py` REQUIRED_FLEET contract) plus
        per-replica labeled gauges."""
        c = self.telemetry.counters
        with self._lock:
            replicas = list(self.replicas)
            healthy = sum(1 for r in replicas if r.state == HEALTHY)
        record: Dict[str, float] = {
            "fleet_replicas": float(len(replicas)),
            "fleet_healthy": float(healthy),
            "fleet_requests": c.get("fleet_requests", 0.0),
            "fleet_retries": c.get("fleet_retries", 0.0),
            "fleet_retries_exhausted": c.get("fleet_retries_exhausted", 0.0),
            "fleet_attempt_timeouts": c.get("fleet_attempt_timeouts", 0.0),
            "fleet_shed": c.get("fleet_shed", 0.0),
            "fleet_no_healthy": c.get("fleet_no_healthy", 0.0),
            "fleet_brownout": c.get("fleet_brownout", 0.0),
            "fleet_unhealthy_marks": c.get("fleet_unhealthy_marks", 0.0),
            "fleet_readmissions": c.get("fleet_readmissions", 0.0),
            "fleet_probe_failures": c.get("fleet_probe_failures", 0.0),
            "fleet_generation": float(self.current_generation),
            "rollout_pushes": c.get("rollout_pushes", 0.0),
            "rollout_rollbacks": c.get("rollout_rollbacks", 0.0),
            "rollout_canary_comparisons": c.get("rollout_canary_comparisons", 0.0),
            "rollout_canary_mismatches": c.get("rollout_canary_mismatches", 0.0),
        }
        # per-replica labels: one flat field per (replica, signal)
        for r in replicas:
            rc = r.engine.telemetry.counters
            prefix = f"fleet_replica_{r.rid}"
            record[f"{prefix}_state"] = _STATE_CODE[r.state]
            record[f"{prefix}_outstanding"] = float(r.outstanding)
            record[f"{prefix}_generation"] = float(r.generation)
            record[f"{prefix}_recompiles"] = r.engine.steady_state_recompiles()
            record[f"{prefix}_served"] = rc.get("serving_batches", 0.0)
            record[f"{prefix}_degraded_ok"] = rc.get("serving_degraded_ok", 0.0)
            record[f"{prefix}_degraded_failed"] = rc.get(
                "serving_degraded_failed", 0.0)
        # honest fleet-wide percentiles: merged per-replica sketches, never
        # averaged per-replica quantiles
        for name, sk in self.aggregator().merged_hists().items():
            if sk.count:
                record.update(sk.snapshot(name))
        if self.slo is not None:
            record.update(self.slo.gauges())
        return record

    def steady_state_recompiles(self) -> float:
        return sum(r.engine.steady_state_recompiles() for r in self.replicas)
