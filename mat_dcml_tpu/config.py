"""Experiment configuration: typed dataclasses + strict CLI.

Replaces the reference's single global argparse namespace threaded through
every layer (``mat/config.py:156-315``).  Unknown flags are an error — the
reference's ``parse_known_args`` silently dropped them, which demonstrably ate
a hyperparameter (``DCML_MAT_Train.py:193`` passes ``"value_loss_coef"``
without ``--`` and it vanishes; SURVEY.md §7 known defects).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from mat_dcml_tpu.training.ppo import PPOConfig


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Run-level settings (env/episode/bookkeeping)."""

    algorithm_name: str = "mat"       # mat | mat_dec | mat_encoder | mat_decoder | mat_gru | ...
    env_name: str = "DCML"
    scenario: str = "AS"
    experiment_name: str = "check"
    seed: int = 1
    n_rollout_threads: int = 8        # env-batch size E (vmapped, not OS threads)
    num_env_steps: int = 1_000_000
    episode_length: int = 50
    log_interval: int = 5
    save_interval: int = 50
    eval_interval: int = 25
    use_eval: bool = False
    eval_episodes: int = 32
    run_dir: str = "results"
    model_dir: Optional[str] = None
    # scalar-stream mirrors behind the jsonl metrics (base_runner.py:54-66)
    use_tensorboard: bool = False
    use_wandb: bool = False
    wandb_project: str = "mat_dcml_tpu"
    # capture a jax.profiler trace of one post-warmup training iteration
    # (collect + train) into this directory; TensorBoard-viewable
    profile_dir: Optional[str] = None
    # telemetry (telemetry/): sample the blocking step timers, NaN-guard
    # fetch, and device/host gauges every N iterations (0 disables sampling;
    # counters and the recompile detector stay on).  The registry flushes
    # into the jsonl record at every log_interval.
    telemetry_interval: int = 1
    # rotate metrics.jsonl to metrics.jsonl.1 when it exceeds this size
    # (MB; 0 = unbounded, the classic behavior)
    metrics_max_mb: float = 0.0
    # request-scoped tracing (telemetry/tracing.py): sample this fraction of
    # training dispatches into <run_dir>/trace.jsonl as span trees (root
    # "dispatch" with collect/train/fetch/checkpoint children).  0 disables.
    trace_sample: float = 0.0
    # rotate trace.jsonl at this size (MB), same scheme as metrics_max_mb
    trace_max_mb: float = 64.0
    # observability federation (telemetry/remote.py): serve this process's
    # telemetry registry at http://127.0.0.1:<port>/telemetry.json on a
    # stdlib sidecar thread so training joins the same scrape plane as the
    # serving fleet (scripts/obs_collector.py).  0 disables (default);
    # -1 binds an ephemeral port (announced on the OBS_PORT log line).
    obs_port: int = 0
    # bounded trend rollups (telemetry/timeseries.py): diff the registry into
    # tiered time windows at every metrics flush and stream closed raw
    # windows as typed ts_ records into <run_dir>/timeseries.jsonl (rotating;
    # hard memory cap independent of run length).  Served at /timeseries.json
    # when --obs_port is set.
    timeseries: bool = True
    # fused multi-episode dispatch: lax.scan K collect+train iterations inside
    # ONE jitted call with donated train/rollout state, so the host re-enters
    # once per K episodes instead of twice per episode (Podracer-style).  1 =
    # the classic two-dispatch loop.  Log/save/eval cadences snap UP to
    # dispatch boundaries; see README "Observability" for when not to raise it.
    iters_per_dispatch: int = 1
    # tuned-config artifact from scripts/autotune.py: fills every perf knob
    # the command line left at its default (explicit CLI flags always win;
    # tuning/__init__.py:apply_tuned_cli).  A fingerprint mismatch — wrong
    # backend/device count/model shape — warns and continues on defaults.
    tuned_config: Optional[str] = None
    # annotate model/trainer phases with jax.named_scope so xplane traces and
    # scripts/trace_report.py group op time semantically; trace-time only
    trace_named_scopes: bool = True
    # anomaly tripwires (telemetry/anomaly.py): EMA-baselined detection over
    # nonfinite grads, grad/param-norm and update-ratio spikes, step-time
    # regressions, and steady-state recompiles; trips emit typed "anomaly"
    # records into metrics.jsonl and drive the flight recorder / profiler
    # window below
    anomaly_tripwires: bool = True
    # where tripped runs dump repro bundles (and tripwire profiler traces)
    anomaly_dir: str = "artifacts"
    # flight recorder (telemetry/flight_recorder.py): keep host snapshots of
    # the last N dispatch inputs, taken BEFORE each launch (the donated
    # buffers are gone afterwards).  0 disables (default — snapshots are a
    # blocking device->host copy).  Under --iters_per_dispatch K>1, detection
    # lags launch by one dispatch, so use a depth of at least 2.
    flight_recorder_depth: int = 0
    # snapshot every N-th episode/dispatch (amortizes the blocking copy)
    flight_recorder_interval: int = 1
    # on a tripwire, capture a bounded jax.profiler trace window spanning this
    # many subsequent dispatches into anomaly_dir (0 disables); at most one
    # window per run
    anomaly_profile_dispatches: int = 0
    # model
    n_block: int = 2
    n_embd: int = 64
    n_head: int = 2
    # transformer trunk compute dtype ("float32" | "bfloat16"); heads,
    # softmax, distributions, and params always float32 (models/mat.py)
    model_dtype: str = "float32"
    # rematerialize transformer blocks in the PPO backward pass
    # (jax.checkpoint): big-batch updates fit in HBM at ~1/3 extra forward
    # FLOPs; numerically exact (tests/test_ppo_accum.py)
    remat: bool = False
    encode_state: bool = False
    dec_actor: bool = False
    share_actor: bool = False
    n_objective: int = 1
    # context parallelism: ring-shard the agent axis of the teacher-forced
    # training forward over this many devices (parallel/seq_parallel.py);
    # 1 = replicated. Indivisible agent counts (DCML's 101) zero-pad with
    # masked keys — numerics identical.
    seq_shards: int = 1
    # data parallelism: shard the env-batch axis (n_rollout_threads) of the
    # whole collect+train program over this many devices of a (data, seq)
    # mesh (parallel/mesh.build_run_mesh).  Params/optimizer stay replicated;
    # grad psums and the batch statistics fall out of jit.  0 = auto (all
    # devices not consumed by --seq_shards); 1 = no data sharding.
    # n_rollout_threads must be divisible by the resulting shard count.
    data_shards: int = 1
    # parameter sharding (parallel/sharding.py): shard every rule-matched
    # param (and its optimizer moments) over the mesh's fsdp/tp axes so the
    # trunk is no longer capped by one device's HBM.  Specs come from regex
    # rules over flattened param names (first match wins; unmatched params
    # are a typed error, never silent replication).  1/1 = replicated, the
    # classic path, bit-exact.  n_embd must divide fsdp_shards*tp_shards.
    fsdp_shards: int = 1
    tp_shards: int = 1
    # optional JSON rules file overriding the built-in MAT rule set; format
    # in README "Scaling" (list of [regex, spec-list] pairs)
    sharding_rules: Optional[str] = None
    # rollout decode: "cached" (default) = O(1)-per-step decode against the
    # packed head-split KV buffer (models/decode.py:cached_decode), bit-exact
    # to "scan"; "scan" = sequential AR decode re-deriving per-step state;
    # "spec" = speculative draft-verify decode (spec_decode) — also bit-exact
    # (actions AND log-probs, via gumbel/noise replay), ~n_agent/K̄ block
    # passes instead of n_agent sequential steps.  "stride" is reserved for
    # the deterministic benchmark-protocol path and is not valid here.
    decode_mode: str = "cached"
    # speculative window K: draft positions verified per block pass
    spec_block: int = 8
    # resume policy when a checkpoint source is configured (training/
    # resilience.py): "strict" = --model_dir must hold a checkpoint (missing
    # -> FileNotFoundError, the pre-PR-9 behavior); "auto" = resume from
    # --model_dir OR this run's own <run_dir>/models when either holds a
    # valid (or emergency) checkpoint, start fresh otherwise — one command
    # line serves first launch and supervisor relaunch
    resume: str = "strict"
    # SIGTERM/SIGINT -> stop at the next dispatch boundary with a blocking
    # emergency checkpoint of the full carry (exit code 75 = preempted)
    graceful_stop: bool = True
    # watchdog wall-clock bound on one fused dispatch, in seconds; >0 blocks
    # on the dispatch outputs to enforce it (costs the async overlap), 0
    # keeps launches async and only traps device errors
    dispatch_deadline_s: float = 0.0
    # retries per failed dispatch (re-placed from the last pre-launch
    # snapshot, fleet.py-style jittered backoff) before the run emergency-
    # saves and exits 76
    dispatch_retries: int = 2
    dispatch_backoff_ms: float = 100.0
    # pre-launch full-carry snapshot cadence (dispatches) feeding watchdog
    # retries and crash-path emergency checkpoints; each snapshot is a
    # blocking device->host deep copy.  0 disables (graceful stop still
    # works — it packs boundary state directly); raise to amortize
    emergency_snapshot_interval: int = 1
    # Podracer-style async actor-learner overlap (training/async_loop.py):
    # split the devices into disjoint actor/learner submeshes and run the
    # jitted collector continuously in an actor thread while the learner
    # consumes trajectory blocks from a bounded queue (1-step-lagged PPO;
    # see README "Async actor-learner").  Single-process, >= 2 devices,
    # incompatible with --iters_per_dispatch > 1 and --data_shards/
    # --seq_shards > 1 (the submeshes replace the run mesh).
    async_actors: bool = False
    # device split for --async_actors; 0 = auto (half/half, actors take the
    # extra device on odd counts)
    actor_devices: int = 0
    learner_devices: int = 0
    # bounded trajectory-queue capacity (device-buffer ring slots).  Deeper
    # queues buy transient actor/learner jitter tolerance at the cost of
    # learner HBM; consumed param staleness stays <= --staleness_budget
    # regardless (the store's admission control gates collects, not the
    # ring depth — async_loop.TrajectoryStore).  The effective capacity is
    # max(async_queue_depth, staleness_budget) so a raised budget is never
    # throttled by the default ring.
    async_queue_depth: int = 2
    # learner-side liveness budget: how many times a silently-dead actor
    # thread (no recorded error, queue left open) is restarted from the last
    # published params before the run raises ActorDeadError (per worker)
    async_actor_max_restarts: int = 2
    # number of concurrent ActorWorker threads; the actor submesh is carved
    # into this many equal contiguous (data, seq=1) slices
    # (parallel.mesh.carve_actor_worker_meshes), each worker running its own
    # compiled collect program.  Near-linear actor-side scaling needs
    # --staleness_budget >= workers (admission serializes collects beyond
    # the budget); 1 = PR 13 single-worker behavior
    async_actor_workers: int = 1
    # staleness budget B: max param-version lag any consumed trajectory
    # block may carry (admission control: a collect starts only while
    # in-flight + queued + consuming <= B).  1 reproduces the conservative
    # double-buffered overlap; > 1 admits off-policy blocks and (with
    # --off_policy_correction auto) turns on V-trace-style truncated-IS
    # weighting in the PPO update
    staleness_budget: int = 1
    # off-policy correction for stale blocks (training/off_policy.py):
    # "auto" = V-trace truncated IS iff staleness_budget > 1 (so B=1 runs
    # stay bit-exact with PR 13), "vtrace" / "none" force it on / off.
    # Clipping thresholds live in PPOConfig (vtrace_rho_bar / vtrace_c_bar)
    off_policy_correction: str = "auto"

    @property
    def episodes(self) -> int:
        return int(self.num_env_steps) // self.episode_length // self.n_rollout_threads


def dcml_default_configs() -> tuple[RunConfig, PPOConfig]:
    """The DCML-AS training recipe (``DCML_MAT_Train.py:193``), including the
    ``value_loss_coef=1.0`` that the reference *actually* trained with (its
    intended 1.5 was silently dropped by argparse)."""
    return RunConfig(), PPOConfig()


def _parse_bool(s: str) -> bool:
    low = s.lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {s!r}")


def _add_dataclass_args(parser: argparse.ArgumentParser, dc) -> None:
    for f in dataclasses.fields(dc):
        name = "--" + f.name
        default = getattr(dc, f.name)
        if f.type == "bool" or isinstance(default, bool):
            parser.add_argument(name, type=_parse_bool, default=default)
        elif default is None:
            parser.add_argument(name, default=None)
        else:
            parser.add_argument(name, type=type(default), default=default)


def parse_cli(argv=None) -> tuple[RunConfig, PPOConfig]:
    run, ppo, _ = parse_cli_with_extras(argv)
    return run, ppo


def parse_cli_with_extras(
    argv=None,
    extras: Optional[argparse.ArgumentParser] = None,
    overrides: Optional[dict] = None,
) -> tuple[RunConfig, PPOConfig, argparse.Namespace]:
    """Strict CLI with optional entry-point-specific flags.

    ``extras``: a parent parser contributing additional arguments (returned via
    the namespace).  ``overrides``: per-entry-point defaults (e.g. MPE's
    ``episode_length=25``), replacing the reference's per-script ``parse_args``
    shims (``train_mpe.py:21-40``).
    """
    rc_fields = {f.name for f in dataclasses.fields(RunConfig)}
    run = RunConfig(**{k: v for k, v in (overrides or {}).items() if k in rc_fields})
    ppo = PPOConfig()
    parents = [extras] if extras is not None else []
    parser = argparse.ArgumentParser(
        description="mat_dcml_tpu trainer", allow_abbrev=False, parents=parents
    )
    _add_dataclass_args(parser, run)
    _add_dataclass_args(parser, ppo)
    ns = parser.parse_args(argv)  # strict: unknown flags raise
    run_kwargs = {f.name: getattr(ns, f.name) for f in dataclasses.fields(RunConfig)}
    ppo_kwargs = {f.name: getattr(ns, f.name) for f in dataclasses.fields(PPOConfig)}
    run, ppo = RunConfig(**run_kwargs), PPOConfig(**ppo_kwargs)
    if ns.tuned_config:
        from mat_dcml_tpu.tuning import apply_tuned_cli

        run, ppo = apply_tuned_cli(ns.tuned_config, run, ppo, argv=argv)
    return run, ppo, ns
