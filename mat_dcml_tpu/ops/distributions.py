"""Action distributions as pure functions.

Replaces ``torch.distributions`` usage in the reference
(``transformer_act.py``, ``distributions.py``).  Availability masking uses the
same convention as the reference: unavailable logits forced to -1e10
(``transformer_act.py:163``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

MASK_VALUE = -1e10
# math.log, NOT jnp.log: a module-level jnp op initializes the JAX backend at
# import time, which crashes the whole import chain when the TPU is
# unavailable/contended (round-1 bench failure).
LOG_2PI = math.log(2.0 * math.pi)


def mask_logits(logits: jax.Array, available: jax.Array | None) -> jax.Array:
    """Force logits of unavailable actions to -1e10 (``transformer_act.py:14,163``)."""
    if available is None:
        return logits
    return jnp.where(available == 0, MASK_VALUE, logits)


def categorical_sample(key: jax.Array, logits: jax.Array) -> jax.Array:
    return jax.random.categorical(key, logits, axis=-1)


def categorical_mode(logits: jax.Array) -> jax.Array:
    # torch Categorical.probs.argmax == logits argmax (softmax is monotone).
    return jnp.argmax(logits, axis=-1)


def categorical_log_prob(logits: jax.Array, action: jax.Array) -> jax.Array:
    """Log prob of integer ``action`` under ``Categorical(logits)``."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, action[..., None].astype(jnp.int32), axis=-1)[..., 0]


def categorical_entropy(logits: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    # Match torch.distributions.Categorical.entropy: -(p * logp).sum over support.
    # With -1e10 masked logits p ~ 0 for masked entries; p*logp -> 0 * -1e10 is
    # a large negative times ~0 which torch evaluates as p_min*logp; guard NaNs.
    plogp = jnp.where(p > 0, p * logp, 0.0)
    return -plogp.sum(axis=-1)


def normal_sample(key: jax.Array, mean: jax.Array, std: jax.Array) -> jax.Array:
    return mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)


def normal_sample_from_noise(mean: jax.Array, std: jax.Array, noise: jax.Array) -> jax.Array:
    """``mean + std * noise`` with the product pinned behind an optimization
    barrier, so the expression rounds identically in every compilation context
    (scan body, while body, eager).  Without the barrier XLA may contract the
    multiply-add into an FMA inside one loop body but not another — a 1-ulp
    drift that breaks the speculative decode's bit-exactness contract."""
    return mean + jax.lax.optimization_barrier(std * noise)


def normal_log_prob(mean: jax.Array, std: jax.Array, action: jax.Array) -> jax.Array:
    var = std * std
    return -((action - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * LOG_2PI


def normal_entropy(mean: jax.Array, std: jax.Array) -> jax.Array:
    del mean
    return 0.5 + 0.5 * LOG_2PI + jnp.log(std)


def huber_loss(e: jax.Array, delta: float) -> jax.Array:
    """Matches ``mat/utils/util.py`` huber: 0.5 e^2 if |e|<=d else d(|e| - 0.5 d)."""
    a = jnp.abs(e)
    return jnp.where(a <= delta, 0.5 * e * e, delta * (a - 0.5 * delta))
