"""Pallas TPU kernel: fused masked multi-head attention over the agent axis.

The reference's hot op is QKV attention over the (≤101-token) agent sequence
(``ma_transformer.py:45-69``): on GPU it runs as 4+ separate CUDA kernels
(matmul, mask-add, softmax, matmul) with HBM round-trips between them.  Here
the whole ``softmax(mask(qk^T)) v`` chain is one VMEM-resident fused kernel.

Because the sequence is tiny (no flash tiling needed) but the batch is huge
(thousands of vmapped envs), the grid runs over GROUPS of flattened
(batch*head) rows — ``_GROUP`` rows of the whole attention problem per grid
cell as one batched ``dot_general`` — rather than one cell per (batch, head),
which drowned in per-cell overhead (measured 2x slower than XLA at B=256;
grouping amortizes it).

A custom VJP keeps the op differentiable: the backward pass is a second fused
kernel that recomputes the (cheap) probability matrix instead of storing it —
the flash-attention trade, profitable here because L² at L≤128 is smaller
than the HBM round-trip it avoids.

Interpret mode (``interpret=True``) runs the same kernels on CPU for the unit
tests (SURVEY.md §7.1: "drop-in vs jnp reference, unit-tested for equality").
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os

from mat_dcml_tpu.ops.attention import NEG_INF


def _group_size() -> int:
    try:
        g = int(_os.environ.get("MAT_DCML_TPU_ATTN_GROUP", "16"))
    except ValueError as e:
        raise ValueError("MAT_DCML_TPU_ATTN_GROUP must be a positive integer") from e
    if g < 1:
        raise ValueError(f"MAT_DCML_TPU_ATTN_GROUP must be >= 1, got {g}")
    return g


def _apply_masks(s: jax.Array, m: jax.Array | None, causal: bool) -> jax.Array:
    """Mask a (G, Lq, Lk) score block with a (G, Lk) kv mask + causal tril."""
    g, lq, lk = s.shape
    if m is not None:
        s = jnp.where(m[:, None, :] > 0, s, NEG_INF)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((col <= row)[None], s, NEG_INF)
    return s


_BATCH_QKT = (((2,), (2,)), ((0,), (0,)))   # (G,Lq,D) x (G,Lk,D) -> (G,Lq,Lk)
_BATCH_PV = (((2,), (1,)), ((0,), (0,)))    # (G,Lq,Lk) x (G,Lk,D) -> (G,Lq,D)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal: bool, scale: float, has_mask: bool):
    m_ref, o_ref = rest if has_mask else (None, *rest)
    q = q_ref[...].astype(jnp.float32)           # (G, Lq, Dh)
    k = k_ref[...].astype(jnp.float32)           # (G, Lk, Dh)
    v = v_ref[...].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, _BATCH_QKT, preferred_element_type=jnp.float32) * scale
    s = _apply_masks(s, m_ref[...] if has_mask else None, causal)
    p = jax.nn.softmax(s, axis=-1)
    o_ref[...] = jax.lax.dot_general(p, v, _BATCH_PV, preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, *rest, causal: bool, scale: float, has_mask: bool):
    m_ref, do_ref, dq_ref, dk_ref, dv_ref = rest if has_mask else (None, *rest)
    q = q_ref[...].astype(jnp.float32)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    # recompute probabilities (flash-style) instead of saving them
    s = jax.lax.dot_general(q, k, _BATCH_QKT, preferred_element_type=jnp.float32) * scale
    s = _apply_masks(s, m_ref[...] if has_mask else None, causal)
    p = jax.nn.softmax(s, axis=-1)
    # dv = p^T do ; dp = do v^T ; ds = p*(dp - rowsum(dp*p)) ; dq = ds k ; dk = ds^T q
    dv = jax.lax.dot_general(p, do, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jax.lax.dot_general(ds, k, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32) * scale
    dk = jax.lax.dot_general(ds, q, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32) * scale
    dq_ref[...] = dq.astype(dq_ref.dtype)
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _row_spec(g: int, l: int, dh: int) -> pl.BlockSpec:
    return pl.BlockSpec((g, l, dh), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)


def _mask_spec(g: int, lk: int) -> pl.BlockSpec:
    return pl.BlockSpec((g, lk), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _specs_and_inputs(g: int, q, k, v, mask):
    """The (in_specs, inputs) pair shared by the fwd and bwd pallas_calls.

    A shared mask is a single (1, Lk) row every grid cell reads (index map
    pinned to block 0); a per-row mask is blocked like q/k/v.
    """
    N, Lq, Dh = q.shape
    Lk = k.shape[1]
    assert N % g == 0, f"row count {N} not divisible by group {g}"
    in_specs = [_row_spec(g, Lq, Dh), _row_spec(g, Lk, Dh), _row_spec(g, Lk, Dh)]
    inputs = [q, k, v]
    if mask is not None:
        if mask.shape[0] == 1:
            in_specs.append(pl.BlockSpec((1, Lk), lambda i: (0, 0), memory_space=pltpu.VMEM))
        else:
            in_specs.append(_mask_spec(g, Lk))
        inputs.append(mask)
    return in_specs, inputs


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_attention(q, k, v, mask, causal: bool, interpret: bool, g: int):
    return _fused_attention_fwd(q, k, v, mask, causal, interpret, g)[0]


def _fused_attention_fwd(q, k, v, mask, causal: bool, interpret: bool, g: int):
    N, Lq, Dh = q.shape
    in_specs, inputs = _specs_and_inputs(g, q, k, v, mask)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, scale=1.0 / math.sqrt(Dh), has_mask=mask is not None
        ),
        out_shape=jax.ShapeDtypeStruct((N, Lq, Dh), q.dtype),
        grid=(N // g,),
        in_specs=in_specs,
        out_specs=_row_spec(g, Lq, Dh),
        interpret=interpret,
    )(*inputs)
    return out, (q, k, v, mask)


def _fused_attention_bwd(causal: bool, interpret: bool, g: int, res, do):
    q, k, v, mask = res
    N, Lq, Dh = q.shape
    Lk = k.shape[1]
    in_specs, inputs = _specs_and_inputs(g, q, k, v, mask)
    in_specs.append(_row_spec(g, Lq, Dh))
    inputs.append(do)
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kernel, causal=causal, scale=1.0 / math.sqrt(Dh), has_mask=mask is not None
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        grid=(N // g,),
        in_specs=in_specs,
        out_specs=(_row_spec(g, Lq, Dh), _row_spec(g, Lk, Dh), _row_spec(g, Lk, Dh)),
        interpret=interpret,
    )(*inputs)
    dmask = jnp.zeros_like(mask) if mask is not None else None
    return dq, dk, dv, dmask


_fused_attention.defvjp(_fused_attention_fwd, _fused_attention_bwd)


def fused_masked_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Pallas drop-in for ``ops.attention.multi_head_attention``.

    Same contract: ``q (B,H,Lq,Dh)``, ``k/v (B,H,Lk,Dh)``, optional causal
    mask (requires Lq == Lk) and ``(Lk,)`` / ``(B, Lk)`` kv validity mask.
    """
    B, H, Lq, Dh = q.shape
    Lk = k.shape[2]
    if causal:
        assert Lq == Lk, "causal attention requires Lq == Lk"
    # flatten (B, H) -> rows; a per-batch mask is repeated per head, a shared
    # 1D mask stays a single (1, Lk) row all grid cells read; the no-mask
    # (encoder) hot path skips the mask input entirely (static flag)
    if kv_mask is None:
        mask_rows = None
    elif kv_mask.ndim == 1:
        mask_rows = kv_mask.astype(jnp.float32)[None, :]
    else:
        mask_rows = jnp.repeat(kv_mask.astype(jnp.float32), H, axis=0)

    qf = q.reshape(B * H, Lq, Dh)
    kf = k.reshape(B * H, Lk, Dh)
    vf = v.reshape(B * H, Lk, Dh)

    # Mosaic computes (rows < 8)-sublane matmul tiles at reduced precision
    # (observed ~1e-3 drift at Lq=1 on hardware, exact at Lq >= 8); pad the
    # query rows to a full sublane tile and slice back.  Zero do-rows
    # contribute zero to dk/dv, so the custom VJP is unaffected.
    lq_pad = max(Lq, 8)
    if lq_pad != Lq:
        qf = jnp.pad(qf, ((0, 0), (0, lq_pad - Lq), (0, 0)))

    # pad the flattened row count to a multiple of the group size (padded mask
    # rows are all-ones; their outputs are sliced away)
    n = B * H
    g = min(_group_size(), n)
    n_pad = -n % g
    if n_pad:
        qf = jnp.pad(qf, ((0, n_pad), (0, 0), (0, 0)))
        kf = jnp.pad(kf, ((0, n_pad), (0, 0), (0, 0)))
        vf = jnp.pad(vf, ((0, n_pad), (0, 0), (0, 0)))
        if mask_rows is not None and mask_rows.shape[0] != 1:
            mask_rows = jnp.pad(mask_rows, ((0, n_pad), (0, 0)), constant_values=1.0)

    out = _fused_attention(qf, kf, vf, mask_rows, causal, interpret, g)
    return out[:n, :Lq].reshape(B, H, Lq, Dh)
