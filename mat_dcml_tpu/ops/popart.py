"""PopArt: preserve-outputs-precisely value-head rescaling.

The reference has two PopArts: a statistics-only one (``mat/utils/popart.py``,
identical math to ValueNorm — covered by ``ops/normalize.py``) and the
output-layer variant (``mat/algorithms/utils/popart.py``) whose ``update``
both advances the running moments AND rescales the value head's weight/bias so
denormalized predictions are unchanged (``popart.py:48-70``):

    w' = w * old_std / new_std
    b' = (old_std * b + old_mean - new_mean) / new_std

Here the head weights live in the critic's params pytree; ``popart_update``
returns the new statistics plus a function of the head params, applied by the
trainer — the functional equivalent of the in-place ``nn.Parameter`` mutation.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.ops.normalize import (
    ValueNormState,
    _debiased_mean_var,
    value_norm_init,
    value_norm_update,
)

PopArtState = ValueNormState  # same running-moment pytree

popart_init = value_norm_init


def popart_std_mean(state: PopArtState) -> Tuple[jax.Array, jax.Array]:
    mean, var = _debiased_mean_var(state)
    return jnp.sqrt(var), mean


def popart_update(
    state: PopArtState, batch: jax.Array, head_params: dict, beta: float = 0.99999
) -> Tuple[PopArtState, dict]:
    """Advance moments from ``batch`` and rescale the Dense head params.

    ``head_params`` is the flax param dict of the critic's ``v_out`` Dense:
    ``{"kernel": (in, out), "bias": (out,)}``.
    """
    old_std, old_mean = popart_std_mean(state)
    new_state = value_norm_update(state, batch, beta=beta)
    new_std, new_mean = popart_std_mean(new_state)
    kernel = head_params["kernel"] * (old_std / new_std)[None, :]
    bias = (old_std * head_params["bias"] + old_mean - new_mean) / new_std
    return new_state, {"kernel": kernel, "bias": bias}


def popart_normalize(state: PopArtState, x: jax.Array) -> jax.Array:
    mean, var = _debiased_mean_var(state)
    return (x - mean) / jnp.sqrt(var)


def popart_denormalize(state: PopArtState, x: jax.Array) -> jax.Array:
    mean, var = _debiased_mean_var(state)
    return x * jnp.sqrt(var) + mean
