"""Low-level numerical ops: attention, distributions, GAE, normalizers."""

from mat_dcml_tpu.ops.attention import multi_head_attention
from mat_dcml_tpu.ops.gae import compute_gae
from mat_dcml_tpu.ops.normalize import ValueNormState, value_norm_init, value_norm_update, value_norm_normalize, value_norm_denormalize
