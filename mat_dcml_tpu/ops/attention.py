"""Multi-head attention over the agent axis.

The reference computes plain QKV attention with an optional causal
(lower-triangular) mask over agents (``ma_transformer.py:24-69``).  Here the
math is a single fused function over already-projected q/k/v dispatched to
the XLA einsum path below by default; the Pallas fused kernel
(``ops/pallas_attention.py``) is an env-var opt-in portability artifact
(same numerics, unit-tested equal — see the dispatch note at
``_VALID_IMPLS``).

Shapes follow TPU conventions: ``(batch, heads, length, head_dim)``.
"""

from __future__ import annotations

import contextlib
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e9

# "auto", "xla", "pallas", "pallas_interpret" (CPU debugging), "ring"
# (context-parallel; only valid inside shard_map with the length axis
# sharded — see parallel/seq_parallel.py)
_IMPL_ENV = "MAT_DCML_TPU_ATTN_IMPL"
_RING_AXIS_ENV = "MAT_DCML_TPU_ATTN_RING_AXIS"
# global (pre-pad) sequence length when the caller padded L to divide the
# ring; read at trace time by the "ring" dispatch below ("0" = no padding)
_RING_VALID_ENV = "MAT_DCML_TPU_ATTN_RING_VALID"

# "auto" always resolves to XLA.  Measured twice, both against the kernel:
# r1 on a v4 chip (bench.py, E=256, T=50, full train loop) XLA 683 env-steps/s
# vs fused kernel 543 (grouped grid) / 318 (per-(b,h) grid); r5 on the v5-lite
# driver chip XLA 2409 env-steps/s vs 1654 with the kernel in dispatch, the
# collect phase regressing ~4x (the kernel re-enters per decode position,
# where XLA keeps the tiny L=101 score matrix fused and VMEM-resident).  The
# kernel is a portability artifact like ops/pallas_decode.py: opt in via
# MAT_DCML_TPU_ATTN_IMPL=pallas (or impl=), parity held by
# tests/test_pallas_attention.py + tests/test_update_attn_parity.py in
# interpret mode.  See BENCHLOG.md (pallas-attention close-out).
_VALID_IMPLS = ("auto", "xla", "pallas", "pallas_interpret", "ring")

# process-local trace-time override installed by parallel/seq_parallel.py's
# context manager: (impl, ring_axis, valid_len).  Scoped to this module —
# unlike an env var it is invisible to subprocesses (vec-env bridge workers,
# multihost launchers) and does not shadow the user-facing _IMPL_ENV knob.
_OVERRIDE: tuple | None = None


@contextlib.contextmanager
def impl_override(impl: str, axis: str = "seq", valid_len: int = 0):
    """Pin attention dispatch while tracing a sharded forward.

    Single-trace assumption: the override is process-global state consulted at
    trace time, so exactly one sharded forward may be traced inside the
    context (which is how ``parallel/seq_parallel.py`` uses it — one
    ``seq_sharded_call`` trace per context).  An explicitly passed ``impl=``
    at a call site still wins over the override (ADVICE r2): call sites that
    pin an implementation know something the blanket override does not.
    """
    global _OVERRIDE
    old = _OVERRIDE
    _OVERRIDE = (impl, axis, valid_len)
    try:
        yield
    finally:
        _OVERRIDE = old


def _resolve_impl(impl: str | None, lk: int) -> str:
    if _OVERRIDE is not None and impl is None:
        return _OVERRIDE[0]
    impl = impl or os.environ.get(_IMPL_ENV, "auto")
    if impl not in _VALID_IMPLS:
        raise ValueError(f"attention impl must be one of {_VALID_IMPLS}, got {impl!r}")
    if impl == "auto":
        return "xla"
    return impl


def multi_head_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    qk_mask: jax.Array | None = None,
    impl: str | None = None,
) -> jax.Array:
    """Scaled dot-product attention.

    Args:
      q: ``(B, H, Lq, Dh)`` queries.
      k: ``(B, H, Lk, Dh)`` keys.
      v: ``(B, H, Lk, Dh)`` values.
      causal: if True, query position i attends only to key positions <= i
        (requires Lq == Lk), matching the registered ``tril`` buffer of the
        reference (``ma_transformer.py:40-41,60-61``).
      kv_mask: optional ``(Lk,)`` or ``(B, Lk)`` boolean mask of valid key
        positions (used by the KV-cached decode where the cache has static
        length but only a prefix is populated).
      qk_mask: optional ``(Lq, Lk)`` or ``(B, Lq, Lk)`` boolean per-query
        validity mask — the block-windowed cached decode (``spec_decode``)
        attends a window of Lq queries against the full cache, each with its
        own causal frontier (per batch row when the window start differs per
        row).  XLA path only.

    Returns:
      ``(B, H, Lq, Dh)`` attention output (before the output projection).
    """
    chosen = _resolve_impl(impl, k.shape[-2])
    if qk_mask is not None and chosen != "xla":
        raise ValueError(
            f"qk_mask is only supported by the XLA attention path, got impl={chosen!r}"
        )
    if chosen == "ring":
        # context parallelism: this call site is inside shard_map with the
        # length axis sharded over the ring axis; K/V shards rotate with
        # ppermute (ops/ring_attention.py).  The decode path's kv_mask never
        # reaches here — decode is sequential and stays on one device.
        if kv_mask is not None:
            raise ValueError("ring attention does not support kv_mask")
        from mat_dcml_tpu.ops.ring_attention import ring_attention

        if _OVERRIDE is not None:
            axis, valid = _OVERRIDE[1], _OVERRIDE[2] or None
        else:  # manual env-var selection
            axis = os.environ.get(_RING_AXIS_ENV, "seq")
            valid = int(os.environ.get(_RING_VALID_ENV, "0")) or None
        return ring_attention(
            q, k, v, axis_name=axis, causal=causal, valid_len=valid
        )
    if chosen.startswith("pallas"):
        from mat_dcml_tpu.ops.pallas_attention import fused_masked_attention

        return fused_masked_attention(
            q, k, v, causal=causal, kv_mask=kv_mask,
            interpret=chosen == "pallas_interpret",
        )
    dh = q.shape[-1]
    # scores + softmax in float32 even under a bfloat16 trunk: attention
    # weights are the numerically delicate part; the matmuls stay low-precision
    att = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32)))
    if causal:
        lq, lk = q.shape[-2], k.shape[-2]
        tri = jnp.tril(jnp.ones((lq, lk), dtype=bool))
        att = jnp.where(tri[None, None], att, NEG_INF)
    if kv_mask is not None:
        if kv_mask.ndim == 1:
            m = kv_mask[None, None, None, :]
        else:
            m = kv_mask[:, None, None, :]
        att = jnp.where(m, att, NEG_INF)
    if qk_mask is not None:
        m = qk_mask[None, None] if qk_mask.ndim == 2 else qk_mask[:, None]
        att = jnp.where(m, att, NEG_INF)
    att = jax.nn.softmax(att, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", att, v)


def split_heads(x: jax.Array, n_head: int) -> jax.Array:
    """``(B, L, D) -> (B, H, L, D//H)``."""
    b, l, d = x.shape
    return x.reshape(b, l, n_head, d // n_head).transpose(0, 2, 1, 3)


def merge_heads(x: jax.Array) -> jax.Array:
    """``(B, H, L, Dh) -> (B, L, H*Dh)``."""
    b, h, l, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, l, h * dh)
