"""Ring attention: sequence/context parallelism over the agent axis.

The reference's only length-scaling device is stride-batched decoding over a
≤101-token agent axis (SURVEY.md §5 long-context) — nothing distributes the
sequence.  Here the attention interface is context-shardable: shards of the
(agent) sequence live on different devices along a ``seq`` mesh axis, K/V
shards rotate around the ring with ``jax.lax.ppermute`` while each device's
Q shard accumulates output with an online (flash-style) softmax — compute
overlaps communication, memory per device is O(L/n), and the result is exact
(tested against dense attention on a virtual CPU mesh).

This is headroom, not parity: DCML's 101 agents fit one chip trivially, but
the MAT design treats agents AS the sequence, so a 100x agent count rides
the same op over ICI.  Usage is via ``shard_map`` with the length axis
sharded on ``seq``:

    out = shard_map(
        partial(ring_attention, axis_name="seq", causal=True),
        mesh=mesh,
        in_specs=P(None, None, "seq", None),
        out_specs=P(None, None, "seq", None),
    )(q, k, v)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = False,
    valid_len: Optional[int] = None,
) -> jax.Array:
    """Exact attention over a ring-sharded sequence (call inside shard_map).

    Args:
      q, k, v: ``(B, H, L_local, Dh)`` — this device's shard of the global
        length axis, sharded over ``axis_name``.
      causal: apply the global lower-triangular mask (query position attends
        to key positions <= its own GLOBAL index).
      valid_len: when the global length was zero-padded to divide the ring
        (e.g. DCML's 101 agents on 2 shards -> 102), the number of REAL
        positions; keys at global index >= valid_len are masked out.  Query
        rows >= valid_len produce garbage the caller slices away.

    Returns:
      ``(B, H, L_local, Dh)`` — this device's shard of the attention output.
    """
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, Ll, Dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))

    q32 = q.astype(jnp.float32)
    q_pos = my_idx * Ll + jnp.arange(Ll)                    # global q positions

    def scores_for(k_blk, kv_idx):
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        k_pos = kv_idx * Ll + jnp.arange(Ll)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]          # (Ll, Ll)
            s = jnp.where(mask[None, None], s, NEG_INF)
        if valid_len is not None:
            s = jnp.where((k_pos < valid_len)[None, None, None, :], s, NEG_INF)
        return s

    # online softmax accumulators, derived from q so they carry the same
    # device-varying type under shard_map (fresh constants would be
    # "replicated" and mismatch the loop carry)
    o = jnp.zeros_like(q32)
    m = jnp.full_like(q32[..., :1], NEG_INF)
    l = jnp.zeros_like(q32[..., :1])

    def body(step, carry):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (my_idx - step) % n_shards                  # whose shard we hold
        s = scores_for(k_blk, kv_idx)                        # (B, H, Ll, Ll)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # guard fully-masked rows (m_new == NEG_INF): exp(0)=1 but l stays 0
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        o = o * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        # rotate K/V shards around the ring (next step sees neighbor's shard)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m_new, l, k_blk, v_blk

    o, m, l, _, _ = jax.lax.fori_loop(0, n_shards, body, (o, m, l, k, v))
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    axis_name: str = "seq",
    causal: bool = False,
):
    """Convenience wrapper: shard_map ``ring_attention`` with the length axis
    of global ``(B, H, L, Dh)`` inputs sharded over ``axis_name``."""
    from jax.sharding import PartitionSpec as P

    try:                                    # top-level API (jax >= 0.6)
        from jax import shard_map
    except ImportError:                     # jax 0.4.x
        from jax.experimental.shard_map import shard_map

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
