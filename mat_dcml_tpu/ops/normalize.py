"""Running-statistics value normalizers as pytree state.

``ValueNorm`` reproduces ``mat/utils/valuenorm.py``: debiased EMA of mean and
mean-square with ``beta=0.99999``, variance clamped to ``>= 1e-2``, debiasing
term clamped to ``>= 1e-5``.  PopArt statistics (``mat/utils/popart.py``) share
the same running-moment math; the output-layer-rescaling PopArt variant lives
with the MLP critics.

All functions are pure; on a device mesh the batch moments should be averaged
with ``jax.lax.pmean`` before ``value_norm_update`` so every replica holds
bit-identical statistics (see SURVEY.md §5 "Distributed communication
backend").
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class ValueNormState(NamedTuple):
    running_mean: jax.Array      # (shape,)
    running_mean_sq: jax.Array   # (shape,)
    debiasing_term: jax.Array    # scalar


def value_norm_init(shape: int = 1, dtype=jnp.float32) -> ValueNormState:
    return ValueNormState(
        running_mean=jnp.zeros((shape,), dtype),
        running_mean_sq=jnp.zeros((shape,), dtype),
        debiasing_term=jnp.zeros((), dtype),
    )


def _debiased_mean_var(state: ValueNormState, epsilon: float = 1e-5) -> Tuple[jax.Array, jax.Array]:
    term = jnp.clip(state.debiasing_term, min=epsilon)
    mean = state.running_mean / term
    mean_sq = state.running_mean_sq / term
    var = jnp.clip(mean_sq - mean**2, min=1e-2)
    return mean, var


def value_norm_update(
    state: ValueNormState,
    batch: jax.Array,
    beta: float = 0.99999,
    axis_mean=None,
) -> ValueNormState:
    """EMA update from a batch; ``batch`` has trailing dim == state shape.

    ``axis_mean`` optionally supplies pre-reduced (mean, sq_mean) computed with
    cross-device ``pmean`` — pass None to reduce locally (single host).
    """
    if axis_mean is None:
        reduce_axes = tuple(range(batch.ndim - 1))
        batch_mean = batch.mean(axis=reduce_axes)
        batch_sq_mean = (batch**2).mean(axis=reduce_axes)
    else:
        batch_mean, batch_sq_mean = axis_mean
    w = beta
    return ValueNormState(
        running_mean=state.running_mean * w + batch_mean * (1.0 - w),
        running_mean_sq=state.running_mean_sq * w + batch_sq_mean * (1.0 - w),
        debiasing_term=state.debiasing_term * w + (1.0 - w),
    )


def value_norm_normalize(state: ValueNormState, x: jax.Array) -> jax.Array:
    mean, var = _debiased_mean_var(state)
    return (x - mean) / jnp.sqrt(var)


def value_norm_denormalize(state: ValueNormState, x: jax.Array) -> jax.Array:
    mean, var = _debiased_mean_var(state)
    return x * jnp.sqrt(var) + mean
