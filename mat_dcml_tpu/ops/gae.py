"""Generalized Advantage Estimation as a reverse scan.

Reproduces the masked-GAE semantics of ``shared_buffer.py:207-238``:

  delta_t = r_t + gamma * V'_{t+1} * mask_{t+1} - V'_t
  gae_t   = delta_t + gamma * lambda * mask_{t+1} * gae_{t+1}
  ret_t   = gae_t + V'_t

where ``V'`` is the (optionally value-norm denormalized) value prediction and
``mask_{t+1}`` is 0 when the episode ended at step t.  The DCML convention is
that ``done`` fires with ``CONTINUE_PROBABILITY`` per step
(``DCML_..._SingleProcess.py:141-142``) and ``dcml_runner.py:267-269`` turns it
into ``mask = 1 - done``; we replicate that exactly.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.telemetry.scopes import named_scope, probe


def compute_gae(
    rewards: jax.Array,
    values: jax.Array,
    masks: jax.Array,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Masked GAE over the leading time axis.

    Args:
      rewards: ``(T, ...)`` per-step rewards.
      values: ``(T+1, ...)`` (denormalized) value predictions, incl. bootstrap.
      masks: ``(T+1, ...)`` continuation masks; ``masks[t+1] == 0`` means the
        env terminated at step t. ``masks[0]`` is unused (kept for buffer-shape
        parity with the reference).

    Returns:
      ``(advantages, returns)`` each ``(T, ...)``.
    """

    def step(gae, inp):
        r, v, v_next, m_next = inp
        delta = r + gamma * v_next * m_next - v
        gae = delta + gamma * gae_lambda * m_next * gae
        return gae, gae

    with named_scope("ops/gae"):
        inputs = (rewards, values[:-1], values[1:], masks[1:])
        init = jnp.zeros_like(rewards[0])
        _, adv = jax.lax.scan(step, init, inputs, reverse=True)
        returns = adv + values[:-1]
        probe("ops/gae", {"advantages": adv, "returns": returns})
        return adv, returns


def compute_gae_chunked(
    rewards: jax.Array,
    values: jax.Array,
    masks: jax.Array,
    gamma: float,
    gae_lambda: float,
    chunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Bit-exact ``compute_gae`` as a reverse scan over time CHUNKS.

    Identical per-step arithmetic in identical order (the outer reverse scan
    carries the GAE boundary between chunks, the inner reverse scan runs the
    same ``step`` over each chunk), so advantages and returns are bitwise
    equal to the monolithic path (pinned by tests/test_stream_equivalence.py).
    What changes is the *counted* data motion: the per-step elementwise chain
    lives in a chunk-shaped scan body that XLA's ``cost_analysis`` counts
    once, instead of full-(T,...) slice/concat intermediates materializing in
    the caller's (per-epoch) scope — the streamed-recompute half of the
    byte-lean update.

    ``chunk`` must divide ``T`` (callers round with
    ``minibatch.largest_divisor_leq``); ``chunk == T`` degenerates to one
    outer step.
    """
    T = rewards.shape[0]
    assert T % chunk == 0, f"chunk ({chunk}) must divide T ({T})"
    n_chunks = T // chunk

    def step(gae, inp):
        r, v, v_next, m_next = inp
        delta = r + gamma * v_next * m_next - v
        gae = delta + gamma * gae_lambda * m_next * gae
        return gae, gae

    def chunk_step(gae, inp):
        r_c, v_c, v_next_c, m_next_c = inp
        gae, adv_c = jax.lax.scan(step, gae, (r_c, v_c, v_next_c, m_next_c), reverse=True)
        # returns for this chunk while its inputs are still live
        return gae, (adv_c, adv_c + v_c)

    def split(x):
        return x.reshape(n_chunks, chunk, *x.shape[1:])

    with named_scope("ops/gae_chunked"):
        inputs = (split(rewards), split(values[:-1]), split(values[1:]), split(masks[1:]))
        init = jnp.zeros_like(rewards[0])
        _, (adv, returns) = jax.lax.scan(chunk_step, init, inputs, reverse=True)
        adv = adv.reshape(T, *adv.shape[2:])
        returns = returns.reshape(T, *returns.shape[2:])
        probe("ops/gae", {"advantages": adv, "returns": returns})
        return adv, returns
