"""Fused MAT decode-step kernel (Pallas, TPU).

One autoregressive decode position is ~30 small XLA ops (embed, LayerNorms,
cache updates, two cached attentions, MLP, head) executed 101 times per env
step inside the collect scan — per-op dispatch dominates at DCML batch sizes
(collect profile, VERDICT r1 item 8).  This kernel fuses the ENTIRE decode
step — action embed -> n_block x (cached causal self-attn + cached causal
cross-attn + MLP) -> f32 logits head — into one ``pallas_call`` per position:

- grid over batch tiles; per-block KV caches are aliased in/out and updated
  at position ``i`` in place (``input_output_aliases``);
- the position index arrives via scalar prefetch;
- attention scores/softmax compute in f32 regardless of trunk dtype,
  matching ``ops/attention.py``; the head always runs f32 (models/mat.py);
- forward-only by design: sampling happens outside, and training gradients
  flow through the teacher-forced parallel pass, never through decode.

Weights are packed per block ([q|k|v|proj] concatenations, stacked
LayerNorms) by :func:`pack_decode_weights` so the kernel takes a dozen refs
instead of seventy.  Numerics are pinned to the unfused path by
``tests/test_pallas_decode.py``.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


class DecodeStepWeights(NamedTuple):
    """Packed decoder weights (see ``pack_decode_weights``)."""

    embed_w: jax.Array       # (in_dim_pad, D)
    embed_b: jax.Array       # (D,)
    ln0: jax.Array           # (2, D) scale;bias of the post-embed LN
    block_qkvp1_w: jax.Array  # (n_block, D, 4D) [q|k|v|proj] self-attn
    block_qkvp1_b: jax.Array  # (n_block, 4D)
    block_qkvp2_w: jax.Array  # (n_block, D, 4D) cross-attn
    block_qkvp2_b: jax.Array  # (n_block, 4D)
    block_mlp_w1: jax.Array  # (n_block, D, D)
    block_mlp_b1: jax.Array  # (n_block, D)
    block_mlp_w2: jax.Array  # (n_block, D, D)
    block_mlp_b2: jax.Array  # (n_block, D)
    block_lns: jax.Array     # (n_block, 6, D) ln1 s,b, ln2 s,b, ln3 s,b
    head_w1: jax.Array       # (D, D)
    head_b1: jax.Array       # (D,)
    head_ln: jax.Array       # (2, D)
    head_w2: jax.Array       # (D, adim_pad)
    head_b2: jax.Array       # (adim_pad,)


def _dense_params(p):
    return p["kernel"], p.get("bias")


def pack_decode_weights(params, cfg) -> Tuple[DecodeStepWeights, int]:
    """Flax MAT params -> packed kernel weights.  Returns (weights, adim)."""
    dec = params["params"]["decoder"]
    D = cfg.n_embd
    from mat_dcml_tpu.models.mat import DISCRETE, SEMI_DISCRETE

    if cfg.action_type in (DISCRETE, SEMI_DISCRETE):
        emb_w, emb_b = dec["action_encoder_nobias"]["kernel"], None
    else:
        emb_w = dec["action_encoder_bias"]["kernel"]
        emb_b = dec["action_encoder_bias"]["bias"]
    in_dim = emb_w.shape[0]
    in_dim_pad = max(8, in_dim)
    embed_w = jnp.zeros((in_dim_pad, D), emb_w.dtype).at[:in_dim].set(emb_w)
    embed_b = emb_b if emb_b is not None else jnp.zeros((D,), emb_w.dtype)
    ln0 = jnp.stack([dec["ln"]["scale"], dec["ln"]["bias"]])

    def pack_attn(a):
        w = jnp.concatenate(
            [a["query_p"]["kernel"], a["key_p"]["kernel"], a["value_p"]["kernel"], a["proj"]["kernel"]],
            axis=1,
        )
        b = jnp.concatenate(
            [a["query_p"]["bias"], a["key_p"]["bias"], a["value_p"]["bias"], a["proj"]["bias"]]
        )
        return w, b

    qkvp1_w, qkvp1_b, qkvp2_w, qkvp2_b = [], [], [], []
    mlp_w1, mlp_b1, mlp_w2, mlp_b2, lns = [], [], [], [], []
    for bi in range(cfg.n_block):
        blk = dec[f"blocks_{bi}"]
        w1, b1 = pack_attn(blk["attn1"])
        w2, b2 = pack_attn(blk["attn2"])
        qkvp1_w.append(w1); qkvp1_b.append(b1)
        qkvp2_w.append(w2); qkvp2_b.append(b2)
        mlp_w1.append(blk["mlp"]["Dense_0"]["kernel"])
        mlp_b1.append(blk["mlp"]["Dense_0"]["bias"])
        mlp_w2.append(blk["mlp"]["Dense_1"]["kernel"])
        mlp_b2.append(blk["mlp"]["Dense_1"]["bias"])
        lns.append(jnp.stack([
            blk["ln1"]["scale"], blk["ln1"]["bias"],
            blk["ln2"]["scale"], blk["ln2"]["bias"],
            blk["ln3"]["scale"], blk["ln3"]["bias"],
        ]))

    head = dec["head"]
    adim = head["Dense_1"]["kernel"].shape[1]
    adim_pad = max(128, adim)
    head_w2 = jnp.zeros((D, adim_pad), jnp.float32).at[:, :adim].set(head["Dense_1"]["kernel"])
    head_b2 = jnp.zeros((adim_pad,), jnp.float32).at[:adim].set(head["Dense_1"]["bias"])

    return DecodeStepWeights(
        embed_w=embed_w,
        embed_b=embed_b,
        ln0=ln0,
        block_qkvp1_w=jnp.stack(qkvp1_w),
        block_qkvp1_b=jnp.stack(qkvp1_b),
        block_qkvp2_w=jnp.stack(qkvp2_w),
        block_qkvp2_b=jnp.stack(qkvp2_b),
        block_mlp_w1=jnp.stack(mlp_w1),
        block_mlp_b1=jnp.stack(mlp_b1),
        block_mlp_w2=jnp.stack(mlp_w2),
        block_mlp_b2=jnp.stack(mlp_b2),
        block_lns=jnp.stack(lns),
        head_w1=head["Dense_0"]["kernel"],
        head_b1=head["Dense_0"]["bias"],
        head_ln=jnp.stack([head["LayerNorm_0"]["scale"], head["LayerNorm_0"]["bias"]]),
        head_w2=head_w2,
        head_b2=head_b2,
    ), adim


def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _cached_attention(q, k_cache, v_cache, i, n_head):
    """Single-position attention over a cache; f32 scores + softmax.

    q: (TB, D); k_cache/v_cache: (TB, L, D); mask positions > i.
    """
    TB, L, D = k_cache.shape
    dh = D // n_head
    scale = 1.0 / math.sqrt(dh)
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
    valid = pos <= i                                       # (1, L)
    outs = []
    for h in range(n_head):
        qh = q[:, h * dh : (h + 1) * dh].astype(jnp.float32)          # (TB, dh)
        kh = k_cache[:, :, h * dh : (h + 1) * dh].astype(jnp.float32)  # (TB, L, dh)
        vh = v_cache[:, :, h * dh : (h + 1) * dh]
        scores = jnp.einsum("bd,bld->bl", qh, kh) * scale              # (TB, L)
        scores = jnp.where(valid, scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        outs.append(jnp.einsum("bl,bld->bd", w, vh.astype(jnp.float32)))
    return jnp.concatenate(outs, axis=-1)                  # (TB, D) f32


def _decode_step_kernel(
    # scalar prefetch
    i_ref,
    # inputs
    x_ref, rep_ref,
    embed_w_ref, embed_b_ref, ln0_ref,
    qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
    mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
    head_w1_ref, head_b1_ref, head_ln_ref, head_w2_ref, head_b2_ref,
    *cache_and_out_refs,
    n_block: int,
    n_head: int,
):
    n_caches = 4 * n_block
    cache_in = cache_and_out_refs[:n_caches]
    logits_ref = cache_and_out_refs[n_caches]
    cache_out = cache_and_out_refs[n_caches + 1 :]

    i = i_ref[0]
    dtype = cache_in[0].dtype
    D = embed_w_ref.shape[1]

    # action embed + gelu + LN (Decoder._embed_action + ln)
    x = x_ref[:].astype(dtype) @ embed_w_ref[:].astype(dtype) + embed_b_ref[:].astype(dtype)
    x = jax.nn.gelu(x, approximate=False)
    x = _layer_norm(x, ln0_ref[0], ln0_ref[1])
    rep = rep_ref[:].astype(dtype)                        # (TB, D)

    for b in range(n_block):
        lns = lns_ref[b]
        # ---- causal self-attn over the action cache (DecodeBlock.decode_step)
        w1 = qkvp1_w_ref[b].astype(dtype)
        b1 = qkvp1_b_ref[b].astype(dtype)
        q1 = x @ w1[:, :D] + b1[:D]
        k1 = x @ w1[:, D : 2 * D] + b1[D : 2 * D]
        v1 = x @ w1[:, 2 * D : 3 * D] + b1[2 * D : 3 * D]
        k1_ref, v1_ref = cache_out[4 * b], cache_out[4 * b + 1]
        k1_ref[:] = cache_in[4 * b][:]
        v1_ref[:] = cache_in[4 * b + 1][:]
        k1_ref[:, pl.ds(i, 1), :] = k1[:, None, :]
        v1_ref[:, pl.ds(i, 1), :] = v1[:, None, :]
        att1 = _cached_attention(q1, k1_ref[:], v1_ref[:], i, n_head).astype(dtype)
        y1 = att1 @ w1[:, 3 * D :] + b1[3 * D :]
        h = _layer_norm(x + y1, lns[0], lns[1])

        # ---- causal cross-attn: keys/values from h-cache, query = rep
        w2 = qkvp2_w_ref[b].astype(dtype)
        b2 = qkvp2_b_ref[b].astype(dtype)
        q2 = rep @ w2[:, :D] + b2[:D]
        k2 = h @ w2[:, D : 2 * D] + b2[D : 2 * D]
        v2 = h @ w2[:, 2 * D : 3 * D] + b2[2 * D : 3 * D]
        k2_ref, v2_ref = cache_out[4 * b + 2], cache_out[4 * b + 3]
        k2_ref[:] = cache_in[4 * b + 2][:]
        v2_ref[:] = cache_in[4 * b + 3][:]
        k2_ref[:, pl.ds(i, 1), :] = k2[:, None, :]
        v2_ref[:, pl.ds(i, 1), :] = v2[:, None, :]
        att2 = _cached_attention(q2, k2_ref[:], v2_ref[:], i, n_head).astype(dtype)
        y2 = att2 @ w2[:, 3 * D :] + b2[3 * D :]
        h2 = _layer_norm(rep + y2, lns[2], lns[3])

        # ---- MLP + residual
        m = jax.nn.gelu(h2 @ mlp_w1_ref[b].astype(dtype) + mlp_b1_ref[b].astype(dtype), approximate=False)
        m = m @ mlp_w2_ref[b].astype(dtype) + mlp_b2_ref[b].astype(dtype)
        # block output becomes the next block's self-attn stream; `rep` stays
        # the ENCODER representation for every block (Decoder.decode_step)
        x = _layer_norm(h2 + m, lns[4], lns[5])

    # ---- f32 head (models/mat.py Head)
    t = x.astype(jnp.float32) @ head_w1_ref[:].astype(jnp.float32) + head_b1_ref[:].astype(jnp.float32)
    t = jax.nn.gelu(t, approximate=False)
    t = _layer_norm(t, head_ln_ref[0], head_ln_ref[1])
    logits_ref[:] = t @ head_w2_ref[:] + head_b2_ref[:]


def fused_decode_step(
    weights: DecodeStepWeights,
    x_in: jax.Array,            # (B, in_dim) current position's input
    rep_i: jax.Array,           # (B, D) encoder rep at position i
    caches: Sequence[jax.Array],  # 4*n_block arrays (B, L, D)
    i: jax.Array,               # scalar int32 position
    *,
    n_head: int,
    adim: int,
    interpret: bool = False,
    block_b: int | None = None,
):
    """Returns (logits (B, adim) f32, new_caches)."""
    B, D = rep_i.shape
    n_block = weights.block_qkvp1_w.shape[0]
    L = caches[0].shape[1]
    in_dim_pad = weights.embed_w.shape[0]
    adim_pad = weights.head_w2.shape[1]

    if block_b is None:
        # VMEM budget: in+out cache tiles dominate (4*n_block * 2 * TB*L*D)
        bytes_per = 2 if caches[0].dtype == jnp.bfloat16 else 4
        budget = 10 * 2**20
        tb = budget // max(1, (4 * n_block * 2 * L * D * bytes_per))
        block_b = max(8, min(256, 1 << (tb.bit_length() - 1) if tb > 0 else 8))
    TB = min(block_b, B)

    pad_b = (-B) % TB
    if pad_b:
        x_in = jnp.pad(x_in, ((0, pad_b), (0, 0)))
        rep_i = jnp.pad(rep_i, ((0, pad_b), (0, 0)))
        caches = [jnp.pad(c, ((0, pad_b), (0, 0), (0, 0))) for c in caches]
    Bp = B + pad_b
    if x_in.shape[1] < in_dim_pad:
        x_in = jnp.pad(x_in, ((0, 0), (0, in_dim_pad - x_in.shape[1])))

    grid = (Bp // TB,)
    tile = lambda *shape: pl.BlockSpec(shape, lambda g, i_s: tuple([g] + [0] * (len(shape) - 1)))
    full = lambda a: pl.BlockSpec(a.shape, lambda g, i_s: (0,) * a.ndim)

    w = weights
    weight_specs = [full(x) for x in (
        w.embed_w, w.embed_b, w.ln0,
        w.block_qkvp1_w, w.block_qkvp1_b, w.block_qkvp2_w, w.block_qkvp2_b,
        w.block_mlp_w1, w.block_mlp_b1, w.block_mlp_w2, w.block_mlp_b2,
        w.block_lns, w.head_w1, w.head_b1, w.head_ln, w.head_w2, w.head_b2,
    )]
    cache_spec = pl.BlockSpec((TB, L, D), lambda g, i_s: (g, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[tile(TB, in_dim_pad), tile(TB, D)] + weight_specs
        + [cache_spec] * (4 * n_block),
        out_specs=[tile(TB, adim_pad)] + [cache_spec] * (4 * n_block),
    )

    n_weight_args = len(weight_specs)
    # inputs: [i(prefetch), x, rep, weights..., caches...]; alias cache k ->
    # output k+1 (output 0 is logits).  +1 for the scalar-prefetch operand.
    first_cache_arg = 1 + 2 + n_weight_args
    aliases = {first_cache_arg + k: 1 + k for k in range(4 * n_block)}

    out_shapes = [jax.ShapeDtypeStruct((Bp, adim_pad), jnp.float32)] + [
        jax.ShapeDtypeStruct((Bp, L, D), caches[0].dtype) for _ in range(4 * n_block)
    ]

    kernel = functools.partial(_decode_step_kernel, n_block=n_block, n_head=n_head)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        input_output_aliases=aliases,
        interpret=interpret,
    )(jnp.atleast_1d(i).astype(jnp.int32), x_in, rep_i,
      w.embed_w, w.embed_b, w.ln0,
      w.block_qkvp1_w, w.block_qkvp1_b, w.block_qkvp2_w, w.block_qkvp2_b,
      w.block_mlp_w1, w.block_mlp_b1, w.block_mlp_w2, w.block_mlp_b2,
      w.block_lns, w.head_w1, w.head_b1, w.head_ln, w.head_w2, w.head_b2,
      *caches)

    logits = outs[0][:B, :adim]
    new_caches = [c[:B] for c in outs[1:]]
    return logits, new_caches
