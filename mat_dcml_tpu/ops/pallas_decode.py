"""Fused MAT decode-step kernel (Pallas, TPU).

One autoregressive decode position is ~30 small XLA ops (embed, LayerNorms,
cache updates, two cached attentions, MLP, head) executed 101 times per env
step inside the collect scan — per-op dispatch dominates at DCML batch sizes
(collect profile, VERDICT r1 item 8).  This kernel fuses the ENTIRE decode
step — action embed -> n_block x (cached causal self-attn + cached causal
cross-attn + MLP) -> f32 logits head — into one ``pallas_call`` per position:

- grid over batch tiles; per-block KV caches are aliased in/out and updated
  at position ``i`` in place (``input_output_aliases``);
- the position index arrives via scalar prefetch;
- attention scores/softmax compute in f32 regardless of trunk dtype,
  matching ``ops/attention.py``; the head always runs f32 (models/mat.py);
- forward-only by design: sampling happens outside, and training gradients
  flow through the teacher-forced parallel pass, never through decode.

Weights are packed per block ([q|k|v|proj] concatenations, stacked
LayerNorms) by :func:`pack_decode_weights` so the kernel takes a dozen refs
instead of seventy.  Numerics are pinned to the unfused path by
``tests/test_pallas_decode.py``.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


class DecodeStepWeights(NamedTuple):
    """Packed decoder weights (see ``pack_decode_weights``)."""

    embed_w: jax.Array       # (in_dim_pad, D)
    embed_b: jax.Array       # (D,)
    ln0: jax.Array           # (2, D) scale;bias of the post-embed LN
    block_qkvp1_w: jax.Array  # (n_block, D, 4D) [q|k|v|proj] self-attn
    block_qkvp1_b: jax.Array  # (n_block, 4D)
    block_qkvp2_w: jax.Array  # (n_block, D, 4D) cross-attn
    block_qkvp2_b: jax.Array  # (n_block, 4D)
    block_mlp_w1: jax.Array  # (n_block, D, D)
    block_mlp_b1: jax.Array  # (n_block, D)
    block_mlp_w2: jax.Array  # (n_block, D, D)
    block_mlp_b2: jax.Array  # (n_block, D)
    block_lns: jax.Array     # (n_block, 6, D) ln1 s,b, ln2 s,b, ln3 s,b
    head_w1: jax.Array       # (D, D)
    head_b1: jax.Array       # (D,)
    head_ln: jax.Array       # (2, D)
    head_w2: jax.Array       # (D, adim_pad)
    head_b2: jax.Array       # (adim_pad,)


def _dense_params(p):
    return p["kernel"], p.get("bias")


def pack_decode_weights(params, cfg) -> Tuple[DecodeStepWeights, int]:
    """Flax MAT params -> packed kernel weights.  Returns (weights, adim)."""
    dec = params["params"]["decoder"]
    D = cfg.n_embd
    from mat_dcml_tpu.models.mat import DISCRETE, SEMI_DISCRETE

    if cfg.action_type in (DISCRETE, SEMI_DISCRETE):
        emb_w, emb_b = dec["action_encoder_nobias"]["kernel"], None
    else:
        emb_w = dec["action_encoder_bias"]["kernel"]
        emb_b = dec["action_encoder_bias"]["bias"]
    in_dim = emb_w.shape[0]
    in_dim_pad = max(8, in_dim)
    embed_w = jnp.zeros((in_dim_pad, D), emb_w.dtype).at[:in_dim].set(emb_w)
    embed_b = emb_b if emb_b is not None else jnp.zeros((D,), emb_w.dtype)
    ln0 = jnp.stack([dec["ln"]["scale"], dec["ln"]["bias"]])

    def pack_attn(a):
        w = jnp.concatenate(
            [a["query_p"]["kernel"], a["key_p"]["kernel"], a["value_p"]["kernel"], a["proj"]["kernel"]],
            axis=1,
        )
        b = jnp.concatenate(
            [a["query_p"]["bias"], a["key_p"]["bias"], a["value_p"]["bias"], a["proj"]["bias"]]
        )
        return w, b

    qkvp1_w, qkvp1_b, qkvp2_w, qkvp2_b = [], [], [], []
    mlp_w1, mlp_b1, mlp_w2, mlp_b2, lns = [], [], [], [], []
    for bi in range(cfg.n_block):
        blk = dec[f"blocks_{bi}"]
        w1, b1 = pack_attn(blk["attn1"])
        w2, b2 = pack_attn(blk["attn2"])
        qkvp1_w.append(w1); qkvp1_b.append(b1)
        qkvp2_w.append(w2); qkvp2_b.append(b2)
        mlp_w1.append(blk["mlp"]["Dense_0"]["kernel"])
        mlp_b1.append(blk["mlp"]["Dense_0"]["bias"])
        mlp_w2.append(blk["mlp"]["Dense_1"]["kernel"])
        mlp_b2.append(blk["mlp"]["Dense_1"]["bias"])
        lns.append(jnp.stack([
            blk["ln1"]["scale"], blk["ln1"]["bias"],
            blk["ln2"]["scale"], blk["ln2"]["bias"],
            blk["ln3"]["scale"], blk["ln3"]["bias"],
        ]))

    head = dec["head"]
    adim = head["Dense_1"]["kernel"].shape[1]
    adim_pad = max(128, adim)
    head_w2 = jnp.zeros((D, adim_pad), jnp.float32).at[:, :adim].set(head["Dense_1"]["kernel"])
    head_b2 = jnp.zeros((adim_pad,), jnp.float32).at[:adim].set(head["Dense_1"]["bias"])

    return DecodeStepWeights(
        embed_w=embed_w,
        embed_b=embed_b,
        ln0=ln0,
        block_qkvp1_w=jnp.stack(qkvp1_w),
        block_qkvp1_b=jnp.stack(qkvp1_b),
        block_qkvp2_w=jnp.stack(qkvp2_w),
        block_qkvp2_b=jnp.stack(qkvp2_b),
        block_mlp_w1=jnp.stack(mlp_w1),
        block_mlp_b1=jnp.stack(mlp_b1),
        block_mlp_w2=jnp.stack(mlp_w2),
        block_mlp_b2=jnp.stack(mlp_b2),
        block_lns=jnp.stack(lns),
        head_w1=head["Dense_0"]["kernel"],
        head_b1=head["Dense_0"]["bias"],
        head_ln=jnp.stack([head["LayerNorm_0"]["scale"], head["LayerNorm_0"]["bias"]]),
        head_w2=head_w2,
        head_b2=head_b2,
    ), adim


def _gelu(x):
    """Exact-erf GELU with an in-kernel polynomial erf.

    Mosaic has no ``erf``/``erfc`` primitive (``jax.nn.gelu(approximate=False)``
    lowers via ``lax.erfc`` and fails to compile for TPU kernels), so compute
    erf with the Abramowitz–Stegun 7.1.26 rational approximation in f32
    (max abs error 1.5e-7 ≈ one f32 ulp of erf's range).  Decode is
    forward-only — no gradients ever flow through this — and the parity
    suite pins the resulting logits to the XLA path at 1e-4.
    """
    x32 = x.astype(jnp.float32)
    y = x32 * 0.7071067811865476          # x / sqrt(2)
    a = jnp.abs(y)
    t = 1.0 / (1.0 + 0.3275911 * a)
    poly = t * (
        0.254829592
        + t * (-0.284496736 + t * (1.421413741 + t * (-1.453152027 + t * 1.061405429)))
    )
    erf_y = jnp.sign(y) * (1.0 - poly * jnp.exp(-a * a))
    return (0.5 * x32 * (1.0 + erf_y)).astype(x.dtype)


def _mm(a, b):
    """Matmul with an f32 accumulator, rounded back to the input dtype.

    Mosaic requires 32-bit matmul accumulation (a bf16 ``@`` traces as a
    bf16-acc dot and fails verification); f32-accumulate-then-round is also
    exactly what XLA emits for bf16 operands on the MXU, so this keeps the
    kernel's numerics aligned with the unfused path.
    """
    out = jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(a.dtype)


def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _cached_attention(q, k_cache, v_cache, i, n_head):
    """Single-position attention over a cache; f32 scores + softmax.

    q: (TB, D); k_cache/v_cache: (L, TB, D); mask positions > i.

    Caches are laid out position-MAJOR: the per-position write then only
    needs a leading-unit-dim expand of the (TB, D) value, which Mosaic
    lowers (the (TB, L, D) layout's write needs a sublane->major relayout
    — ``tpu.reshape vector<TBxD> -> vector<TBx1xD>`` — that
    infer-vector-layout rejects; every pattern below is validated by
    ``scripts/mosaic_probe.py`` via chipless AOT compilation).
    """
    L, TB, D = k_cache.shape
    dh = D // n_head
    scale = 1.0 / math.sqrt(dh)
    pos = jax.lax.broadcasted_iota(jnp.int32, (L, 1), 0)
    valid = pos <= i                                       # (L, 1)
    outs = []
    for h in range(n_head):
        qh = q[:, h * dh : (h + 1) * dh].astype(jnp.float32)          # (TB, dh)
        kh = k_cache[:, :, h * dh : (h + 1) * dh].astype(jnp.float32)  # (L, TB, dh)
        vh = v_cache[:, :, h * dh : (h + 1) * dh]
        # broadcast-multiply-reduce instead of batched dot_general: the
        # contractions are tiny (dh<=64) and this form always lowers on
        # Mosaic (lane reduce for scores, major reduce for the output)
        scores = jnp.sum(qh[None] * kh, axis=-1) * scale               # (L, TB)
        scores = jnp.where(valid, scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=0)
        outs.append(jnp.sum(w[:, :, None] * vh.astype(jnp.float32), axis=0))
    return jnp.concatenate(outs, axis=-1)                  # (TB, D) f32


def _decoder_block_body(
    x, rep, i, b, dtype, n_head, D,
    qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
    mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
    k1_ref, v1_ref, k2_ref, v2_ref,
):
    """One DecodeBlock position: write K/V at ``i`` into the given cache refs
    (position-major (L, TB, D) layout — see ``_cached_attention``), attend
    over them, LN/MLP — shared by the per-position and whole-decode
    kernels so their numerics cannot drift apart (models/modules.py
    ``DecodeBlock.decode_step`` is the XLA twin both are pinned to)."""
    lns = lns_ref[b]
    # ---- causal self-attn over the action cache
    w1 = qkvp1_w_ref[b].astype(dtype)
    b1 = qkvp1_b_ref[b].astype(dtype)
    q1 = _mm(x, w1[:, :D]) + b1[:D]
    k1 = _mm(x, w1[:, D : 2 * D]) + b1[D : 2 * D]
    v1 = _mm(x, w1[:, 2 * D : 3 * D]) + b1[2 * D : 3 * D]
    k1_ref[pl.ds(i, 1)] = k1[None]
    v1_ref[pl.ds(i, 1)] = v1[None]
    att1 = _cached_attention(q1, k1_ref[:], v1_ref[:], i, n_head).astype(dtype)
    y1 = _mm(att1, w1[:, 3 * D :]) + b1[3 * D :]
    h = _layer_norm(x + y1, lns[0], lns[1])

    # ---- causal cross-attn: keys/values from the h-cache, query = rep
    w2 = qkvp2_w_ref[b].astype(dtype)
    b2 = qkvp2_b_ref[b].astype(dtype)
    q2 = _mm(rep, w2[:, :D]) + b2[:D]
    k2 = _mm(h, w2[:, D : 2 * D]) + b2[D : 2 * D]
    v2 = _mm(h, w2[:, 2 * D : 3 * D]) + b2[2 * D : 3 * D]
    k2_ref[pl.ds(i, 1)] = k2[None]
    v2_ref[pl.ds(i, 1)] = v2[None]
    att2 = _cached_attention(q2, k2_ref[:], v2_ref[:], i, n_head).astype(dtype)
    y2 = _mm(att2, w2[:, 3 * D :]) + b2[3 * D :]
    h2 = _layer_norm(rep + y2, lns[2], lns[3])

    # ---- MLP + residual; block output feeds the next block's self-attn
    # stream while `rep` stays the ENCODER representation for every block
    m = _gelu(_mm(h2, mlp_w1_ref[b].astype(dtype)) + mlp_b1_ref[b].astype(dtype))
    m = _mm(m, mlp_w2_ref[b].astype(dtype)) + mlp_b2_ref[b].astype(dtype)
    return _layer_norm(h2 + m, lns[4], lns[5])


def _decode_step_kernel(
    # scalar prefetch
    i_ref,
    # inputs
    x_ref, rep_ref,
    embed_w_ref, embed_b_ref, ln0_ref,
    qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
    mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
    head_w1_ref, head_b1_ref, head_ln_ref, head_w2_ref, head_b2_ref,
    *cache_and_out_refs,
    n_block: int,
    n_head: int,
):
    n_caches = 4 * n_block
    cache_in = cache_and_out_refs[:n_caches]
    logits_ref = cache_and_out_refs[n_caches]
    cache_out = cache_and_out_refs[n_caches + 1 :]

    i = i_ref[0]
    dtype = cache_in[0].dtype
    D = embed_w_ref.shape[1]

    # action embed + gelu + LN (Decoder._embed_action + ln)
    x = _mm(x_ref[:].astype(dtype), embed_w_ref[:].astype(dtype)) + embed_b_ref[:].astype(dtype)
    x = _gelu(x)
    x = _layer_norm(x, ln0_ref[0], ln0_ref[1])
    rep = rep_ref[:].astype(dtype)                        # (TB, D)

    for b in range(n_block):
        # cache tiles round-trip HBM here (aliased in/out); copy forward
        # before the in-place position-i update
        for c in range(4):
            cache_out[4 * b + c][:] = cache_in[4 * b + c][:]
        x = _decoder_block_body(
            x, rep, i, b, dtype, n_head, D,
            qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
            mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
            cache_out[4 * b], cache_out[4 * b + 1],
            cache_out[4 * b + 2], cache_out[4 * b + 3],
        )

    # ---- f32 head (models/mat.py Head)
    t = _mm(x.astype(jnp.float32), head_w1_ref[:].astype(jnp.float32)) + head_b1_ref[:].astype(jnp.float32)
    t = _gelu(t)
    t = _layer_norm(t, head_ln_ref[0], head_ln_ref[1])
    logits_ref[:] = _mm(t, head_w2_ref[:]) + head_b2_ref[:]


# ---------------------------------------------------------------------------
# Whole-decode fused kernel (round 3)
# ---------------------------------------------------------------------------
#
# The per-position kernel above still pays one HBM round-trip of every KV
# cache per position (8 caches x TB x L x D, in AND out, L times) plus one
# kernel dispatch per scan step.  This kernel runs the ENTIRE autoregressive
# decode — all L positions, sampling included — in ONE ``pallas_call``:
#
# - grid = (batch tiles, position chunks), position minor: noise/avail/rep
#   stream through VMEM in 8-position chunks (whole-sequence f32 tiles don't
#   fit VMEM at the production shape), while per-position state never leaves
#   the core;
# - KV caches and the previous-action carry live in VMEM *scratch*, which
#   persists across the sequential position-chunk grid steps (never written
#   to HBM at all — decode outputs are just actions and log-probs);
# - sampling is fused: categorical draws use precomputed Gumbel noise
#   (``jax.random.categorical`` IS argmax(logits + gumbel), so feeding the
#   same per-position Gumbel tensor reproduces the XLA path's draws — up to
#   the in-kernel polynomial-erf gelu's ~1e-4 logit tolerance, i.e. a draw
#   can flip only when two gumbel-perturbed logits tie within that margin),
#   the semi-discrete Gaussian tail uses precomputed normal noise
#   (``transformer_act.py:77-98`` sampling semantics);
# - the sampled action is one-hot re-embedded as the next position's input
#   inside the loop (the loop-carried value), replicating
#   ``transformer_act.py:90`` without leaving the kernel.

MASK_VALUE = -1e10   # ops/distributions.mask_logits (transformer_act.py:14,163)
PAD_KILL = -3e38     # below MASK_VALUE + any Gumbel draw: padding lanes never win


class ARDecodeWeights(NamedTuple):
    """Packed weights for the whole-decode kernel."""

    embed_start: jax.Array   # (1, D) pre-activation embedding of the start token
    embed_act: jax.Array     # (adim_pad, D) rows = one-hot action embeddings
    ln0: jax.Array           # (2, D)
    block_qkvp1_w: jax.Array
    block_qkvp1_b: jax.Array
    block_qkvp2_w: jax.Array
    block_qkvp2_b: jax.Array
    block_mlp_w1: jax.Array
    block_mlp_b1: jax.Array
    block_mlp_w2: jax.Array
    block_mlp_b2: jax.Array
    block_lns: jax.Array
    head_w1: jax.Array
    head_b1: jax.Array
    head_ln: jax.Array
    head_w2: jax.Array       # (D, adim_pad)
    head_b2: jax.Array
    std_row: jax.Array       # (1, adim_pad) f32 action std (ones when discrete)


def pack_ar_decode_weights(params, cfg, std=None) -> Tuple[ARDecodeWeights, int]:
    """Flax MAT params -> whole-decode kernel weights.

    The discrete-family action embedding is a no-bias dense over
    ``[start | one-hot]`` (``ma_transformer.py:163-166``); split it into the
    start row and the action rows so the kernel never materializes the
    shifted-action vector.
    """
    w, adim = pack_decode_weights(params, cfg)
    D = w.embed_w.shape[1]
    adim_pad = w.head_w2.shape[1]
    embed_act = jnp.zeros((adim_pad, D), w.embed_w.dtype).at[:adim].set(
        w.embed_w[1 : 1 + adim]
    )
    std_row = jnp.ones((1, adim_pad), jnp.float32)
    if std is not None:
        std_row = std_row.at[0, :adim].set(std.astype(jnp.float32))
    return ARDecodeWeights(
        embed_start=w.embed_w[0:1],
        embed_act=embed_act,
        ln0=w.ln0,
        block_qkvp1_w=w.block_qkvp1_w,
        block_qkvp1_b=w.block_qkvp1_b,
        block_qkvp2_w=w.block_qkvp2_w,
        block_qkvp2_b=w.block_qkvp2_b,
        block_mlp_w1=w.block_mlp_w1,
        block_mlp_b1=w.block_mlp_b1,
        block_mlp_w2=w.block_mlp_w2,
        block_mlp_b2=w.block_mlp_b2,
        block_lns=w.block_lns,
        head_w1=w.head_w1,
        head_b1=w.head_b1,
        head_ln=w.head_ln,
        head_w2=w.head_w2,
        head_b2=w.head_b2,
        std_row=std_row,
    ), adim


def _ar_decode_kernel(
    *refs,
    n_block: int,
    n_head: int,
    adim: int,
    nd: int,
    has_avail: bool,
    pos_chunk: int,
):
    """Grid = (batch tiles, position chunks).  The position axis is walked in
    ``pos_chunk``-sized grid steps (minor dimension, so steps for one batch
    tile are consecutive): per-chunk noise/avail/rep tiles stream through
    VMEM instead of whole-sequence tiles (which blow VMEM at the production
    shape A=101, adim_pad=128), while the KV caches, the previous-action
    carry, and the (TB, Ap) output blocks stay VMEM-resident across chunks —
    caches/carry as scratch, outputs by revisiting the same block index."""
    k = 4 if has_avail else 3
    rep_ref, gumbel_ref, normal_ref = refs[0], refs[1], refs[2]
    avail_ref = refs[3] if has_avail else None
    (embed_start_ref, embed_act_ref, ln0_ref,
     qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
     mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
     head_w1_ref, head_b1_ref, head_ln_ref, head_w2_ref, head_b2_ref,
     std_ref) = refs[k : k + 18]
    act_ref, logp_ref = refs[k + 18], refs[k + 19]
    carry_ref = refs[k + 20]
    cache_refs = refs[k + 21 :]

    TB, _, D = rep_ref.shape
    adim_pad = gumbel_ref.shape[2]
    n_rows = normal_ref.shape[1]
    Ap = cache_refs[0].shape[0]
    dtype = cache_refs[0].dtype
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        # Zero the caches: attention weights at not-yet-written positions are
        # exactly 0 after softmax underflow, but 0 * uninitialized-VMEM can
        # be 0 * NaN.  (K garbage is masked before softmax; zero it too.)
        for c in cache_refs:
            c[:] = jnp.zeros_like(c)
        carry_ref[:] = jnp.zeros_like(carry_ref)
        act_ref[:] = jnp.zeros_like(act_ref)
        logp_ref[:] = jnp.zeros_like(logp_ref)

    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, adim_pad), 1)
    lanes_a = jax.lax.broadcasted_iota(jnp.int32, (1, Ap), 1)
    lane_valid = lanes < adim                       # (1, adim_pad)
    last_col = (lanes == adim - 1).astype(jnp.float32)
    std_f = std_ref[:]                              # (1, adim_pad) f32
    c_std = jnp.sum(std_f * last_col)               # scalar: std of the tail dim

    prev_onehot = carry_ref[:]
    for jj in range(pos_chunk):
        i = j * pos_chunk + jj                       # global position (traced)
        # ---- action embed (start token at i=0) + gelu + LN
        x = _mm(prev_onehot.astype(dtype), embed_act_ref[:].astype(dtype))
        start = jnp.where(i == 0, 1.0, 0.0).astype(dtype)
        x = x + start * embed_start_ref[:].astype(dtype)
        x = _gelu(x)
        x = _layer_norm(x, ln0_ref[0], ln0_ref[1])
        rep = rep_ref[:, jj, :].astype(dtype)

        for b in range(n_block):
            x = _decoder_block_body(
                x, rep, i, b, dtype, n_head, D,
                qkvp1_w_ref, qkvp1_b_ref, qkvp2_w_ref, qkvp2_b_ref,
                mlp_w1_ref, mlp_b1_ref, mlp_w2_ref, mlp_b2_ref, lns_ref,
                cache_refs[4 * b], cache_refs[4 * b + 1],
                cache_refs[4 * b + 2], cache_refs[4 * b + 3],
            )

        # ---- f32 head -> logits (TB, adim_pad)
        t = _mm(x.astype(jnp.float32), head_w1_ref[:].astype(jnp.float32)) + head_b1_ref[:].astype(jnp.float32)
        t = _gelu(t)
        t = _layer_norm(t, head_ln_ref[0], head_ln_ref[1])
        logits = _mm(t, head_w2_ref[:]) + head_b2_ref[:]

        # ---- fused sampling
        if has_avail:
            ava = avail_ref[:, jj, :]
            masked = jnp.where(ava == 0, MASK_VALUE, logits)
        else:
            masked = logits
        masked = jnp.where(lane_valid, masked, PAD_KILL)

        g = gumbel_ref[:, jj, :]
        idx = jnp.argmax(masked + g, axis=-1)                       # (TB,)
        onehot = (lanes == idx[:, None]).astype(jnp.float32)        # (TB, adim_pad)
        mm = masked - jnp.max(masked, axis=-1, keepdims=True)
        log_z = jnp.log(jnp.sum(jnp.exp(mm), axis=-1, keepdims=True))
        logp_d = jnp.sum((mm - log_z) * onehot, axis=-1)            # (TB,)

        nrow = jnp.clip(i - nd, 0, n_rows - 1)
        nz = normal_ref[:, pl.ds(nrow, 1), :][:, 0, :]
        c_sample = logits + std_f * nz
        c_act = jnp.sum(c_sample * last_col, axis=-1)               # (TB,)
        c_mean = jnp.sum(logits * last_col, axis=-1)
        logp_c = (
            -jnp.square(c_act - c_mean) / (2.0 * c_std * c_std)
            - jnp.log(c_std)
            - 0.5 * math.log(2.0 * math.pi)
        )

        is_cont = i >= nd
        act_i = jnp.where(is_cont, c_act, idx.astype(jnp.float32))
        logp_i = jnp.where(is_cont, logp_c, logp_d)
        # masked read-modify-write of the resident (TB, Ap) output blocks:
        # no dynamic lane indexing (unsupported on Mosaic), just a select
        col = lanes_a == i                                          # (1, Ap)
        act_ref[:] = jnp.where(col, act_i[:, None], act_ref[:])
        logp_ref[:] = jnp.where(col, logp_i[:, None], logp_ref[:])
        prev_onehot = onehot
    carry_ref[:] = prev_onehot


def fused_ar_decode(
    weights: ARDecodeWeights,
    obs_rep: jax.Array,           # (B, A, D) trunk dtype
    gumbel: jax.Array,            # (B, A, adim_pad) f32; zeros when deterministic
    normal_rows: jax.Array,       # (B, max(1, A-nd), adim_pad) f32 tail noise
    avail: jax.Array | None,      # (B, A, adim_pad) f32 or None (= all available)
    *,
    n_head: int,
    adim: int,
    nd: int,
    interpret: bool = False,
    block_b: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Whole-decode fused kernel.  Returns (action (B, A), log_prob (B, A))."""
    B, A, D = obs_rep.shape
    n_block = weights.block_qkvp1_w.shape[0]
    adim_pad = weights.embed_act.shape[0]
    n_rows = normal_rows.shape[1]

    # Position axis walked in chunks (grid minor dim); Mosaic wants the
    # second-to-last block dim sublane-aligned, and 8 positions per chunk
    # keeps the streamed noise tiles small.
    P = 8
    pad_a = (-A) % P
    Ap = A + pad_a

    if block_b is None:
        # VMEM: the persistent per-tile KV caches dominate (streamed chunk
        # tiles are ~0.5 MB at P=8); leave headroom for double-buffering.
        bytes_c = 2 if obs_rep.dtype == jnp.bfloat16 else 4
        per_b = 4 * n_block * Ap * D * bytes_c
        budget = 9 * 2**20
        tb = budget // max(1, per_b)
        block_b = max(8, min(256, 1 << (tb.bit_length() - 1) if tb > 0 else 8))
    if not interpret:
        # sublane-aligned batch tiles: both the chosen tile AND the B-clamp
        # must be rounded up to 8, else 8 < B < block_b with B % 8 != 0
        # produces a Mosaic-illegal tile (review r3)
        block_b = max(8, (block_b + 7) // 8 * 8)
        TB = min(block_b, (max(B, 8) + 7) // 8 * 8)
    else:
        TB = min(block_b, B)

    pad_b = (-B) % TB
    if pad_b or pad_a:
        pad3 = lambda x: jnp.pad(x, ((0, pad_b), (0, pad_a), (0, 0)))
        obs_rep, gumbel = pad3(obs_rep), pad3(gumbel)
        normal_rows = jnp.pad(normal_rows, ((0, pad_b), (0, 0), (0, 0)))
        if avail is not None:
            avail = pad3(avail)
    Bp = B + pad_b

    grid = (Bp // TB, Ap // P)
    chunk = lambda s2: pl.BlockSpec((TB, P, s2), lambda g, j: (g, j, 0))
    full = lambda a: pl.BlockSpec(a.shape, lambda g, j: (0,) * a.ndim)

    ops = [obs_rep, gumbel, normal_rows]
    in_specs = [
        chunk(D),
        chunk(adim_pad),
        pl.BlockSpec((TB, n_rows, adim_pad), lambda g, j: (g, 0, 0)),
    ]
    if avail is not None:
        ops.append(avail)
        in_specs.append(chunk(adim_pad))
    w = weights
    wlist = [
        w.embed_start, w.embed_act, w.ln0,
        w.block_qkvp1_w, w.block_qkvp1_b, w.block_qkvp2_w, w.block_qkvp2_b,
        w.block_mlp_w1, w.block_mlp_b1, w.block_mlp_w2, w.block_mlp_b2,
        w.block_lns, w.head_w1, w.head_b1, w.head_ln, w.head_w2, w.head_b2,
        w.std_row,
    ]
    ops += wlist
    in_specs += [full(x) for x in wlist]

    kernel = functools.partial(
        _ar_decode_kernel,
        n_block=n_block, n_head=n_head, adim=adim, nd=nd,
        has_avail=avail is not None, pos_chunk=P,
    )
    act, logp = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        # same (g, 0) block revisited across all position chunks: the output
        # stays VMEM-resident per batch tile and flushes once at tile change
        out_specs=[pl.BlockSpec((TB, Ap), lambda g, j: (g, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((Bp, Ap), jnp.float32)] * 2,
        scratch_shapes=[pltpu.VMEM((TB, adim_pad), jnp.float32)]
        + [pltpu.VMEM((Ap, TB, D), obs_rep.dtype)] * (4 * n_block),
        interpret=interpret,
    )(*ops)
    return act[:B, :A], logp[:B, :A]


def fused_decode_step(
    weights: DecodeStepWeights,
    x_in: jax.Array,            # (B, in_dim) current position's input
    rep_i: jax.Array,           # (B, D) encoder rep at position i
    caches: Sequence[jax.Array],  # 4*n_block arrays (L, B, D) position-major
    i: jax.Array,               # scalar int32 position
    *,
    n_head: int,
    adim: int,
    interpret: bool = False,
    block_b: int | None = None,
):
    """Returns (logits (B, adim) f32, new_caches)."""
    B, D = rep_i.shape
    n_block = weights.block_qkvp1_w.shape[0]
    L = caches[0].shape[0]
    in_dim_pad = weights.embed_w.shape[0]
    adim_pad = weights.head_w2.shape[1]

    if block_b is None:
        # VMEM budget: in+out cache tiles dominate (4*n_block * 2 * TB*L*D)
        bytes_per = 2 if caches[0].dtype == jnp.bfloat16 else 4
        budget = 10 * 2**20
        tb = budget // max(1, (4 * n_block * 2 * L * D * bytes_per))
        block_b = max(8, min(256, 1 << (tb.bit_length() - 1) if tb > 0 else 8))
    TB = min(block_b, B)

    pad_b = (-B) % TB
    if pad_b:
        x_in = jnp.pad(x_in, ((0, pad_b), (0, 0)))
        rep_i = jnp.pad(rep_i, ((0, pad_b), (0, 0)))
        caches = [jnp.pad(c, ((0, 0), (0, pad_b), (0, 0))) for c in caches]
    Bp = B + pad_b
    if x_in.shape[1] < in_dim_pad:
        x_in = jnp.pad(x_in, ((0, 0), (0, in_dim_pad - x_in.shape[1])))

    grid = (Bp // TB,)
    tile = lambda *shape: pl.BlockSpec(shape, lambda g, i_s: tuple([g] + [0] * (len(shape) - 1)))
    full = lambda a: pl.BlockSpec(a.shape, lambda g, i_s: (0,) * a.ndim)

    w = weights
    weight_specs = [full(x) for x in (
        w.embed_w, w.embed_b, w.ln0,
        w.block_qkvp1_w, w.block_qkvp1_b, w.block_qkvp2_w, w.block_qkvp2_b,
        w.block_mlp_w1, w.block_mlp_b1, w.block_mlp_w2, w.block_mlp_b2,
        w.block_lns, w.head_w1, w.head_b1, w.head_ln, w.head_w2, w.head_b2,
    )]
    cache_spec = pl.BlockSpec((L, TB, D), lambda g, i_s: (0, g, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[tile(TB, in_dim_pad), tile(TB, D)] + weight_specs
        + [cache_spec] * (4 * n_block),
        out_specs=[tile(TB, adim_pad)] + [cache_spec] * (4 * n_block),
    )

    n_weight_args = len(weight_specs)
    # inputs: [i(prefetch), x, rep, weights..., caches...]; alias cache k ->
    # output k+1 (output 0 is logits).  +1 for the scalar-prefetch operand.
    first_cache_arg = 1 + 2 + n_weight_args
    aliases = {first_cache_arg + k: 1 + k for k in range(4 * n_block)}

    out_shapes = [jax.ShapeDtypeStruct((Bp, adim_pad), jnp.float32)] + [
        jax.ShapeDtypeStruct((L, Bp, D), caches[0].dtype) for _ in range(4 * n_block)
    ]

    kernel = functools.partial(_decode_step_kernel, n_block=n_block, n_head=n_head)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        input_output_aliases=aliases,
        interpret=interpret,
    )(jnp.atleast_1d(i).astype(jnp.int32), x_in, rep_i,
      w.embed_w, w.embed_b, w.ln0,
      w.block_qkvp1_w, w.block_qkvp1_b, w.block_qkvp2_w, w.block_qkvp2_b,
      w.block_mlp_w1, w.block_mlp_b1, w.block_mlp_w2, w.block_mlp_b2,
      w.block_lns, w.head_w1, w.head_b1, w.head_ln, w.head_w2, w.head_b2,
      *caches)

    logits = outs[0][:B, :adim]
    new_caches = [c[:, :B] for c in outs[1:]]
    return logits, new_caches
