"""PPO trainer for the actor-critic families (MAPPO / IPPO / centralized PPO).

Reference: ``r_mappo/r_mappo.py`` (shared recurrent MAPPO), ``ppo/ppo_trainer.py``
(centralized joint PPO), ``ippo/ippo_trainer.py`` (independent PPO).  All three
share one update shape; the differences are flags here:

- ``importance_prod``: r_mappo uses elementwise ``exp(logp - old)`` summed
  after the clip (``r_mappo.py:124-134``); ppo/happo take the *product* over
  action dims first (``ppo_trainer.py:128``).
- ``use_popart``: value targets normalized by the output-layer PopArt, whose
  ``update`` also rescales the critic head weights (``algorithms/utils/
  popart.py:48-70``) — here applied functionally to the params pytree.
- separate actor/critic optimizers with ``lr`` / ``critic_lr``
  (``ppo_policy.py``, ``rMAPPOPolicy.py``).
- recurrent training re-runs GRU sequences from stored chunk-start hidden
  states (``separated_buffer.py:236-430`` recurrent generator, chunk length
  ``data_chunk_length``).

Unlike the MAT trainer (which reproduces the reference's per-epoch return
recomputation), the AC families compute returns ONCE per update — matching
``base_runner.train:329-435``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from mat_dcml_tpu.models.actor_critic import ActorCriticPolicy
from mat_dcml_tpu.ops.distributions import huber_loss
from mat_dcml_tpu.ops.gae import compute_gae
from mat_dcml_tpu.ops.normalize import (
    ValueNormState,
    value_norm_denormalize,
    value_norm_init,
    value_norm_normalize,
    value_norm_update,
)
from mat_dcml_tpu.ops.popart import (
    popart_denormalize,
    popart_normalize,
    popart_update,
)
from mat_dcml_tpu.telemetry.scopes import named_scope, probe
from mat_dcml_tpu.training.ac_rollout import ACTrajectory
from mat_dcml_tpu.training.minibatch import check_layout, permute_rows, slice_rows


def chunk_windows(x: jax.Array, L: int, n_batch: int) -> jax.Array:
    """``(T, *batch, ...) -> (nC*prod(batch), L, ...)`` data-chunk windows.

    The reference's recurrent generator layout (``separated_buffer.py:320-430``):
    time splits into ``nC = T//L`` windows, each (window, batch-element) pair
    becomes one minibatch item.  ``n_batch`` = number of leading batch axes
    after time (shared buffers: 2 = (E, A); separated/HAPPO slices: 1 = (E,)).
    """
    nC = x.shape[0] // L
    y = x.reshape(nC, L, *x.shape[1:])
    y = jnp.moveaxis(y, 1, 1 + n_batch)         # (nC, *batch, L, ...)
    return y.reshape(-1, L, *x.shape[1 + n_batch:])


def chunk_start_states(x: jax.Array, L: int, n_batch: int) -> jax.Array:
    """Hidden state entering each window (``x[c*L]`` per batch element) ->
    ``(nC*prod(batch), ...)``; item order matches :func:`chunk_windows`."""
    return x[::L].reshape(-1, *x.shape[1 + n_batch:])


@dataclasses.dataclass(frozen=True)
class MAPPOConfig:
    """Defaults follow ``config.py`` (lr 5e-4 group, ppo group)."""

    lr: float = 5e-4
    critic_lr: float = 5e-4
    opti_eps: float = 1e-5
    weight_decay: float = 0.0
    clip_param: float = 0.2
    ppo_epoch: int = 15
    num_mini_batch: int = 1
    entropy_coef: float = 0.01
    value_loss_coef: float = 1.0
    max_grad_norm: float = 10.0
    gamma: float = 0.99
    gae_lambda: float = 0.95
    huber_delta: float = 10.0
    use_clipped_value_loss: bool = True
    use_huber_loss: bool = True
    use_popart: bool = False
    use_valuenorm: bool = True
    use_value_active_masks: bool = True
    use_policy_active_masks: bool = True
    use_max_grad_norm: bool = True
    importance_prod: bool = False
    use_recurrent_policy: bool = False
    data_chunk_length: int = 10
    # Minibatch assembly recipe (see ppo.PPOConfig.minibatch_layout): "gather"
    # (default, per-minibatch gathers) or "contiguous" (one permutation gather
    # per epoch + dynamic_slice minibatches; byte-identical minibatch content
    # under the same permutation — tests/test_stream_equivalence.py).
    minibatch_layout: str = "gather"
    # Truncated-IS clip thresholds for async off-policy blocks carrying
    # ``is_weights`` (see ppo.PPOConfig.vtrace_rho_bar / vtrace_c_bar).
    vtrace_rho_bar: float = 1.0
    vtrace_c_bar: float = 1.0


class Bootstrap(NamedTuple):
    """Inputs for the next-value bootstrap (the tail of the rollout)."""

    cent_obs: jax.Array      # (E, A, d)
    critic_h: jax.Array      # (E, A, N, h)
    mask: jax.Array          # (E, A, 1)


class MAPPOTrainState(NamedTuple):
    params: dict
    actor_opt: optax.OptState
    critic_opt: optax.OptState
    value_norm: ValueNormState
    update_step: jax.Array


class MAPPOMetrics(NamedTuple):
    value_loss: jax.Array
    policy_loss: jax.Array
    dist_entropy: jax.Array
    actor_grad_norm: jax.Array
    critic_grad_norm: jax.Array
    ratio: jax.Array
    # training-health telemetry (see ppo.TrainMetrics): combined actor+critic
    # grad/param norms, |update|/|params|, non-finite-gradient step count
    grad_norm: jax.Array = 0.0
    param_norm: jax.Array = 0.0
    update_ratio: jax.Array = 0.0
    nonfinite_grads: jax.Array = 0.0


def _rows(x):
    return x.reshape(-1, *x.shape[2:])


def ac_train_iteration(trainer, collector, state, rollout_state, key):
    """One fused collect+train iteration for the actor-critic family — the
    unit ``base_runner``'s ``--iters_per_dispatch`` scans over.  Builds the
    :class:`Bootstrap` from the post-collect rollout state exactly the way
    ``BaseRunner._bootstrap`` does on the host (IPPO's decentralized-V reads
    local obs via ``collector.use_local_value``).  Shared by MAPPO / IPPO /
    HAPPO / HATRPO trainers, whose ``train`` signatures are identical.
    Returns ``(state, rollout_state, metrics, chunk_stats)``."""
    rollout_state, traj = collector.collect(state.params, rollout_state)
    use_local = getattr(collector, "use_local_value", False)
    cent = rollout_state.obs if use_local else rollout_state.share_obs
    boot = Bootstrap(cent_obs=cent, critic_h=rollout_state.critic_h,
                     mask=rollout_state.mask)
    state, metrics = trainer.train(state, traj, boot, key)
    return state, rollout_state, metrics, traj.chunk_stats


class MAPPOTrainer:
    def __init__(self, policy: ActorCriticPolicy, cfg: MAPPOConfig):
        self.policy = policy
        self.cfg = cfg
        check_layout(cfg.minibatch_layout)

        def make_tx(lr):
            tx = optax.adam(lr, eps=cfg.opti_eps)
            if cfg.weight_decay:
                tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
            if cfg.use_max_grad_norm:
                tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), tx)
            return tx

        self.actor_tx = make_tx(cfg.lr)
        self.critic_tx = make_tx(cfg.critic_lr)

    def init_state(self, params) -> MAPPOTrainState:
        return MAPPOTrainState(
            params=params,
            actor_opt=self.actor_tx.init(params["actor"]),
            critic_opt=self.critic_tx.init(params["critic"]),
            value_norm=value_norm_init(1),
            update_step=jnp.zeros((), jnp.int32),
        )

    # ----------------------------------------------------------------- helpers

    def _denorm(self, vn: ValueNormState, x):
        if self.cfg.use_popart:
            return popart_denormalize(vn, x)
        if self.cfg.use_valuenorm:
            return value_norm_denormalize(vn, x)
        return x

    def _value_loss(self, values, old_values, ret_norm, active, is_w=None):
        cfg = self.cfg
        v_clipped = old_values + jnp.clip(values - old_values, -cfg.clip_param, cfg.clip_param)
        err_clipped = ret_norm - v_clipped
        err_orig = ret_norm - values
        if cfg.use_huber_loss:
            vl_c, vl_o = huber_loss(err_clipped, cfg.huber_delta), huber_loss(err_orig, cfg.huber_delta)
        else:
            vl_c, vl_o = 0.5 * err_clipped**2, 0.5 * err_orig**2
        vl = jnp.maximum(vl_o, vl_c) if cfg.use_clipped_value_loss else vl_o
        if is_w is not None:
            # async off-policy correction: c-bar-truncated IS weight
            vl = vl * jnp.minimum(is_w, cfg.vtrace_c_bar)
        if cfg.use_value_active_masks:
            return (vl * active).sum() / active.sum()
        return vl.mean()

    def _policy_loss(self, logp, old_logp, adv, active, is_w=None):
        cfg = self.cfg
        delta = logp - old_logp
        if cfg.importance_prod:
            ratio = jnp.exp(delta.sum(-1, keepdims=True))  # prod(exp) == exp(sum)
        else:
            ratio = jnp.exp(delta)
        surr1 = ratio * adv
        surr2 = jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param) * adv
        surr = jnp.minimum(surr1, surr2).sum(-1, keepdims=True)
        if is_w is not None:
            # async off-policy correction: rho-bar-truncated IS weight
            surr = surr * jnp.minimum(is_w, cfg.vtrace_rho_bar)
        if cfg.use_policy_active_masks:
            return -(surr * active).sum() / active.sum(), ratio
        return -surr.mean(), ratio

    def _compute_targets(self, state: MAPPOTrainState, traj: ACTrajectory, boot: Bootstrap):
        with named_scope("train/compute_targets"):
            next_v = self.policy.get_values(
                state.params, _rows(boot.cent_obs), _rows(boot.critic_h), _rows(boot.mask)
            ).reshape(1, *traj.values.shape[1:])
            values_all = self._denorm(state.value_norm, jnp.concatenate([traj.values, next_v], 0))
            adv, returns = compute_gae(
                traj.rewards, values_all, traj.masks, self.cfg.gamma, self.cfg.gae_lambda
            )
            active = traj.active_masks[:-1]
            denom = active.sum()
            mean = (adv * active).sum() / denom
            var = (((adv - mean) ** 2) * active).sum() / denom
            adv_norm = (adv - mean) / (jnp.sqrt(var) + 1e-5)
            probe("train/compute_targets",
                  {"advantages": adv_norm, "returns": returns})
            return adv_norm, returns

    def _normalize_targets(self, value_norm, params, ret_b):
        """ValueNorm/PopArt update-then-normalize; PopArt also rescales the
        critic head in params (``r_mappo.py:52-89`` + ``popart.py:48-70``)."""
        cfg = self.cfg
        flat_ret = ret_b.reshape(-1, ret_b.shape[-1])
        if cfg.use_popart:
            head = params["critic"]["params"]["v_out"]
            value_norm, new_head = popart_update(value_norm, flat_ret, head)
            critic = dict(params["critic"])
            inner = dict(critic["params"])
            inner["v_out"] = new_head
            critic["params"] = inner
            params = {**params, "critic": critic}
            return value_norm, params, popart_normalize(value_norm, ret_b)
        if cfg.use_valuenorm:
            value_norm = value_norm_update(value_norm, flat_ret)
            return value_norm, params, value_norm_normalize(value_norm, ret_b)
        return value_norm, params, ret_b

    # ------------------------------------------------------------------- train

    def train_iteration(self, collector, state: MAPPOTrainState, rollout_state,
                        key: jax.Array):
        """Fused collect+train unit for ``--iters_per_dispatch`` (see
        :func:`ac_train_iteration`)."""
        return ac_train_iteration(self, collector, state, rollout_state, key)

    def train(self, state: MAPPOTrainState, traj: ACTrajectory, boot: Bootstrap,
              key: jax.Array) -> Tuple[MAPPOTrainState, MAPPOMetrics]:
        adv, returns = self._compute_targets(state, traj, boot)
        if self.cfg.use_recurrent_policy:
            return self._train_recurrent(state, traj, adv, returns, key)
        return self._train_ff(state, traj, adv, returns, key)

    def _apply_updates(self, params, grads, actor_opt, critic_opt):
        a_up, actor_opt = self.actor_tx.update(grads["actor"], actor_opt, params["actor"])
        c_up, critic_opt = self.critic_tx.update(grads["critic"], critic_opt, params["critic"])
        params = {
            "actor": optax.apply_updates(params["actor"], a_up),
            "critic": optax.apply_updates(params["critic"], c_up),
        }
        gnorm = optax.global_norm(grads)
        probe("train/mappo_update", {"grad_norm": gnorm})
        pnorm = optax.global_norm(params)
        unorm = optax.global_norm({"actor": a_up, "critic": c_up})
        health = (
            gnorm,
            pnorm,
            unorm / (pnorm + 1e-12),
            (~jnp.isfinite(gnorm)).astype(jnp.float32),
        )
        return (
            params,
            actor_opt,
            critic_opt,
            optax.global_norm(grads["actor"]),
            optax.global_norm(grads["critic"]),
            health,
        )

    def _train_ff(self, state, traj, adv, returns, key):
        cfg = self.cfg
        T, E, A = traj.rewards.shape[:3]
        n_rows = T * E * A
        mb_size = n_rows // cfg.num_mini_batch
        flat = {
            "cent_obs": traj.share_obs.reshape(n_rows, -1),
            "obs": traj.obs.reshape(n_rows, -1),
            "avail": traj.available_actions.reshape(n_rows, *traj.available_actions.shape[3:]),
            "actions": traj.actions.reshape(n_rows, -1),
            "log_probs": traj.log_probs.reshape(n_rows, -1),
            "values": traj.values.reshape(n_rows, -1),
            "active": traj.active_masks[:-1].reshape(n_rows, -1),
            "masks": traj.masks[:-1].reshape(n_rows, -1),
            "actor_h": traj.actor_h.reshape(n_rows, *traj.actor_h.shape[3:]),
            "critic_h": traj.critic_h.reshape(n_rows, *traj.critic_h.shape[3:]),
            "adv": adv.reshape(n_rows, -1),
            "returns": returns.reshape(n_rows, -1),
        }
        if traj.is_weights is not None:
            flat["is_w"] = traj.is_weights.reshape(n_rows, -1)

        def ppo_update(carry, b):
            params, actor_opt, critic_opt, value_norm = carry
            value_norm, params, ret_norm = self._normalize_targets(value_norm, params, b["returns"])

            def loss_fn(p):
                values, logp, ent = self.policy.evaluate_actions(
                    p, b["cent_obs"], b["obs"], b["actor_h"], b["critic_h"],
                    b["actions"], b["masks"], b["avail"], b["active"],
                )
                policy_loss, ratio = self._policy_loss(
                    logp, b["log_probs"], b["adv"], b["active"],
                    is_w=b.get("is_w"),
                )
                value_loss = self._value_loss(
                    values, b["values"], ret_norm, b["active"],
                    is_w=b.get("is_w"),
                )
                total = policy_loss - ent * cfg.entropy_coef + value_loss * cfg.value_loss_coef
                return total, (value_loss, policy_loss, ent, ratio)

            (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            params, actor_opt, critic_opt, a_gn, c_gn, health = self._apply_updates(
                params, grads, actor_opt, critic_opt
            )
            vl, pl, ent, ratio = aux
            gn, pn, ur, nf = health
            return (params, actor_opt, critic_opt, value_norm), MAPPOMetrics(
                vl, pl, ent, a_gn, c_gn, ratio.mean(),
                grad_norm=gn, param_norm=pn, update_ratio=ur, nonfinite_grads=nf,
            )

        def epoch(carry, key_e):
            perm = jax.random.permutation(key_e, n_rows)
            keep = mb_size * cfg.num_mini_batch
            if cfg.minibatch_layout == "contiguous":
                data_p = permute_rows(flat, perm[:keep])
                step = lambda c, start: ppo_update(c, slice_rows(data_p, start, mb_size))
                xs = jnp.arange(cfg.num_mini_batch) * mb_size
            else:
                step = lambda c, mb_idx: ppo_update(c, jax.tree.map(lambda x: x[mb_idx], flat))
                xs = perm[:keep].reshape(cfg.num_mini_batch, mb_size)
            return jax.lax.scan(step, carry, xs)

        keys = jax.random.split(key, cfg.ppo_epoch)
        carry = (state.params, state.actor_opt, state.critic_opt, state.value_norm)
        with named_scope("train/mappo_update"):
            (params, actor_opt, critic_opt, value_norm), metrics = jax.lax.scan(epoch, carry, keys)
        new_state = MAPPOTrainState(params, actor_opt, critic_opt, value_norm, state.update_step + 1)
        return new_state, jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )

    def _train_recurrent(self, state, traj, adv, returns, key):
        """Chunked-sequence training (``separated_buffer.py:320-430``)."""
        cfg = self.cfg
        T, E, A = traj.rewards.shape[:3]
        L = cfg.data_chunk_length
        assert T % L == 0, f"episode_length {T} must be divisible by data_chunk_length {L}"
        nC = T // L
        n_items = nC * E * A
        mb_size = n_items // cfg.num_mini_batch
        to_chunks = lambda x: chunk_windows(x, L, n_batch=2)
        chunk_starts = lambda x: chunk_start_states(x, L, n_batch=2)

        data = {
            "cent_obs": to_chunks(traj.share_obs),
            "obs": to_chunks(traj.obs),
            "avail": to_chunks(traj.available_actions),
            "actions": to_chunks(traj.actions),
            "log_probs": to_chunks(traj.log_probs),
            "values": to_chunks(traj.values),
            "active": to_chunks(traj.active_masks[:-1]),
            "masks": to_chunks(traj.masks[:-1]),
            "adv": to_chunks(adv),
            "returns": to_chunks(returns),
            "actor_h0": chunk_starts(traj.actor_h),
            "critic_h0": chunk_starts(traj.critic_h),
        }
        if traj.is_weights is not None:
            data["is_w"] = to_chunks(traj.is_weights)

        def seq(x):
            # (mb, L, ...) -> (L, mb, ...)
            return jnp.swapaxes(x, 0, 1)

        def ppo_update(carry, b):
            params, actor_opt, critic_opt, value_norm = carry
            value_norm, params, ret_norm = self._normalize_targets(value_norm, params, b["returns"])

            def loss_fn(p):
                values, logp, ent = self.policy.evaluate_actions_seq(
                    p, seq(b["cent_obs"]), seq(b["obs"]), b["actor_h0"], b["critic_h0"],
                    seq(b["actions"]), seq(b["masks"]), seq(b["avail"]), seq(b["active"]),
                )
                is_w = b.get("is_w")
                policy_loss, ratio = self._policy_loss(
                    logp, seq(b["log_probs"]), seq(b["adv"]), seq(b["active"]),
                    is_w=None if is_w is None else seq(is_w),
                )
                value_loss = self._value_loss(
                    values, seq(b["values"]), seq(ret_norm), seq(b["active"]),
                    is_w=None if is_w is None else seq(is_w),
                )
                total = policy_loss - ent * cfg.entropy_coef + value_loss * cfg.value_loss_coef
                return total, (value_loss, policy_loss, ent, ratio)

            (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            params, actor_opt, critic_opt, a_gn, c_gn, health = self._apply_updates(
                params, grads, actor_opt, critic_opt
            )
            vl, pl, ent, ratio = aux
            gn, pn, ur, nf = health
            return (params, actor_opt, critic_opt, value_norm), MAPPOMetrics(
                vl, pl, ent, a_gn, c_gn, ratio.mean(),
                grad_norm=gn, param_norm=pn, update_ratio=ur, nonfinite_grads=nf,
            )

        def epoch(carry, key_e):
            perm = jax.random.permutation(key_e, n_items)
            keep = mb_size * cfg.num_mini_batch
            if cfg.minibatch_layout == "contiguous":
                data_p = permute_rows(data, perm[:keep])
                step = lambda c, start: ppo_update(c, slice_rows(data_p, start, mb_size))
                xs = jnp.arange(cfg.num_mini_batch) * mb_size
            else:
                step = lambda c, mb_idx: ppo_update(c, jax.tree.map(lambda x: x[mb_idx], data))
                xs = perm[:keep].reshape(cfg.num_mini_batch, mb_size)
            return jax.lax.scan(step, carry, xs)

        keys = jax.random.split(key, cfg.ppo_epoch)
        carry = (state.params, state.actor_opt, state.critic_opt, state.value_norm)
        with named_scope("train/mappo_update"):
            (params, actor_opt, critic_opt, value_norm), metrics = jax.lax.scan(epoch, carry, keys)
        new_state = MAPPOTrainState(params, actor_opt, critic_opt, value_norm, state.update_step + 1)
        return new_state, jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )
