"""Generalist multi-scenario DCML training (the ROADMAP's generalist item).

Builds a :class:`~mat_dcml_tpu.envs.scenario.ScenarioEnv` over a roster of
DCML fault presets (``envs/dcml/fault.py`` array-ized through
``DCMLScenarioFamily``) and runs the standard ``DCMLRunner`` machinery over
it — the scenario id is data in the rollout carry, so the donated
``--iters_per_dispatch`` scan, ``--data_shards`` sharding, anomaly
tripwires, and emergency-checkpoint resume apply unchanged.

What this module adds on top of the wrapper is the **per-scenario eval
matrix**: every eval cadence, each scenario is rolled out separately with
the deterministic policy (scenario id *pinned*, resampling frozen) and
reported as a ``scenario_`` gauge family — per-scenario return/delay/
payment, the min/max/spread across the family, and the generalist-vs-
specialist gap when specialist baselines are supplied.  One jitted rollout
(scenario id a traced argument) covers the whole matrix: N scenarios =
N calls into ONE compiled program.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from pathlib import Path
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.dcml import DCMLEnv
from mat_dcml_tpu.envs.dcml.fault import DCMLFaultConfig, fleet_stress_preset
from mat_dcml_tpu.envs.scenario import (
    DCMLScenarioFamily,
    ScenarioEnv,
    ScenarioSet,
)
from mat_dcml_tpu.training.ppo import PPOConfig
from mat_dcml_tpu.training.runner import MAT_DCML_ALGOS, DCMLRunner

DEFAULT_SCENARIOS = ("nominal", "fleet_stress", "heavy_stragglers", "busy_fleet")


def dcml_fault_presets(W: int) -> "OrderedDict[str, DCMLFaultConfig]":
    """Named fault presets scaled to a ``W``-worker fleet (``q`` = one
    "rack" of roughly W/8 workers).  ``nominal`` is the identity scenario;
    ``fleet_stress`` is PR 9's canonical preset verbatim."""
    q = max(1, W // 8)
    return OrderedDict([
        ("nominal", DCMLFaultConfig()),
        ("fleet_stress", fleet_stress_preset()),
        ("heavy_stragglers", DCMLFaultConfig(
            straggler_nodes=tuple(range(2 * q)),
            straggler_pr_floor=0.8, straggler_load=0.3)),
        ("busy_fleet", DCMLFaultConfig(
            straggler_nodes=tuple(range(3 * q)), straggler_load=0.6)),
        ("lossy_links", DCMLFaultConfig(
            straggler_nodes=tuple(range(2 * q)), straggler_pr_floor=0.9)),
        ("dead_rack", DCMLFaultConfig(dead_nodes=tuple(range(q)))),
    ])


def build_dcml_scenario_env(
    env: DCMLEnv,
    scenario_names: Sequence[str] = DEFAULT_SCENARIOS,
    weights: Optional[Sequence[float]] = None,
) -> ScenarioEnv:
    """Wrap ``env`` in a scenario distribution over named fault presets."""
    W = env.cfg.consts.worker_number_max
    presets = dcml_fault_presets(W)
    unknown = [n for n in scenario_names if n not in presets]
    if unknown:
        raise ValueError(
            f"unknown DCML scenario(s) {unknown}; known: {list(presets)}"
        )
    params = [DCMLScenarioFamily.from_fault(presets[n], W)
              for n in scenario_names]
    sset = ScenarioSet.stack(tuple(scenario_names), params, weights)
    return ScenarioEnv(env, sset, DCMLScenarioFamily)


def load_specialist_baselines(path: str | Path) -> Dict[str, float]:
    """``{scenario_name: specialist eval reward}`` from a JSON file —
    typically produced by per-scenario specialist runs of the same budget."""
    with open(path) as f:
        data = json.load(f)
    return {str(k): float(v) for k, v in data.items()}


class MultiScenarioDCMLRunner(DCMLRunner):
    """DCMLRunner over a :class:`ScenarioEnv` with a per-scenario eval
    matrix.  MAT-family only: the eval matrix drives ``policy.get_actions``
    directly (``dmomat`` is excluded — its preference-conditioning collector
    already widens obs and would double-condition)."""

    def __init__(
        self,
        run: RunConfig,
        ppo: PPOConfig,
        scenario_env: ScenarioEnv,
        log_fn=print,
        specialist_baselines: Optional[Dict[str, float]] = None,
    ):
        if run.algorithm_name not in MAT_DCML_ALGOS or \
                run.algorithm_name == "dmomat":
            raise NotImplementedError(
                f"MultiScenarioDCMLRunner supports the MAT family minus "
                f"dmomat, not {run.algorithm_name!r}"
            )
        if not isinstance(scenario_env, ScenarioEnv):
            raise TypeError("scenario_env must be a ScenarioEnv")
        self.specialist_baselines = dict(specialist_baselines or {})
        self._eval_roll = None
        super().__init__(run, ppo, env=scenario_env, log_fn=log_fn)

    # ----------------------------------------------------------------- eval

    def _build_eval_roll(self, n_steps: int, seed: int):
        """ONE jitted deterministic rollout parameterized by the (traced)
        scenario id — the whole eval matrix is N calls into one compile."""
        senv = self.env.frozen_view()
        E = self.run_cfg.n_rollout_threads
        policy = self.policy

        def roll(params, sid):
            keys = jax.random.split(jax.random.key(seed + 13), E)
            states, ts = jax.vmap(senv.reset_pinned, in_axes=(0, None))(keys, sid)

            def body(carry, _):
                states, obs, share_obs, avail = carry
                out = policy.get_actions(
                    params, jax.random.key(0), share_obs, obs, avail,
                    deterministic=True,
                )
                states, ts = jax.vmap(senv.step)(states, out.action)
                per_step = (
                    ts.reward.sum(-1).mean(),     # mean over (E, A)
                    ts.delay.mean(),
                    ts.payment.mean(),
                )
                return (states, ts.obs, ts.share_obs,
                        ts.available_actions), per_step

            carry = (states, ts.obs, ts.share_obs, ts.available_actions)
            _, (rew, delay, pay) = jax.lax.scan(
                body, carry, None, length=n_steps
            )
            return rew.mean(), delay.mean(), pay.mean()

        return jax.jit(roll)

    def evaluate(self, train_state, n_steps: int = 64, seed: int = 0):
        """Deterministic per-scenario eval matrix.

        Emits one ``scenario_{name}_*`` gauge triple per scenario plus the
        family aggregates; ``eval_average_step_rewards`` (the macro-average
        over scenarios) keeps the base eval contract so existing dashboards
        and the schema checker's eval branch stay valid."""
        if self._eval_roll is None:
            self._eval_roll = self._build_eval_roll(n_steps, seed)
        names = self.env.scenarios.names
        info = {}
        rewards = {}
        delays, payments = [], []
        for i, name in enumerate(names):
            r, d, p = self._eval_roll(train_state.params,
                                      jnp.asarray(i, jnp.int32))
            rewards[name] = float(r)
            delays.append(float(d))
            payments.append(float(p))
            info[f"scenario_{name}_reward"] = float(r)
            info[f"scenario_{name}_delay"] = float(d)
            info[f"scenario_{name}_payment"] = float(p)
        vals = np.array(list(rewards.values()))
        info["scenario_count"] = float(len(names))
        info["scenario_reward_min"] = float(vals.min())
        info["scenario_reward_max"] = float(vals.max())
        info["scenario_spread"] = float(vals.max() - vals.min())
        # generalist-vs-specialist gap: positive = specialists still ahead.
        # specialist_count == 0 flags "no baselines supplied" honestly
        # instead of a silently meaningless 0 gap.
        common = [n for n in names if n in self.specialist_baselines]
        info["scenario_specialist_count"] = float(len(common))
        info["scenario_generalist_gap"] = (
            float(np.mean([self.specialist_baselines[n] - rewards[n]
                           for n in common])) if common else 0.0
        )
        info["eval_average_step_rewards"] = float(vals.mean())
        info["eval_average_delays"] = float(np.mean(delays))
        info["eval_average_payments"] = float(np.mean(payments))
        return info
