"""Bi-DexHands runner (gated — the reference's own env module is absent).

The reference ships ``runner/shared/hands_runner.py`` + ``train_hands.py``
but the env package they import (``mat.envs.dexteroushandenvs``) does not
exist in its tree (SURVEY.md §2.4 missing modules), so the capability was
already broken upstream.  Here the runner exists as a thin specialization of
the host-bridge pattern: Isaac-Gym-style hands envs are host simulators, so
they plug in exactly like football — a host env exposing the shared-obs
contract, driven through ``ShareSubprocVecEnv`` + ``HostRolloutCollector``.

The one hands-specific behavior worth preserving from ``hands_runner.py:178``
(actions arrive agent-major and are transposed per-agent before the env) is
host-side layout, which the vec-env contract already fixes as ``(E, A, d)``.
"""

from __future__ import annotations

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.vec_env import ShareVecEnv
from mat_dcml_tpu.training.football_runner import FootballRunner
from mat_dcml_tpu.training.ppo import PPOConfig


class HandsRunner(FootballRunner):
    """Host-bridge MAT runner for dexterous-hands simulators.

    Construct with a vec env of host hands envs (obs/share_obs/avail per
    agent, shared reward).  Requires an external Isaac Gym / Bi-DexHands
    install to supply the envs — not bundled."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, vec_env: ShareVecEnv,
                 log_fn=print):
        super().__init__(run, ppo, vec_env, log_fn=log_fn)

    def _extra_metrics(self, record: dict) -> None:
        # hands envs report no score channels; keep raw episode rewards
        record.pop("aver_episode_delays", None)
        record.pop("aver_episode_payments", None)
