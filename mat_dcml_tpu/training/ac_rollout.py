"""On-device rollout collection for actor-critic (non-MAT) policies.

Counterpart of ``training/rollout.py`` for the MAPPO/IPPO/PPO/HAPPO families:
additionally threads and stores per-step actor/critic GRU hidden states the
way the reference buffers do (``shared_buffer.py:60-66``,
``separated_buffer.py:56-62``), so recurrent training can re-run sequences
from stored chunk-start states (``separated_buffer.py:236-430``).

Works with any env exposing the DCML TimeStep protocol:
``reset(key, episode_idx) -> (state, ts)``, ``step(state, action) ->
(state, ts)`` with ``ts = (obs, share_obs, available_actions, reward, done,
...)``.  Policies see flattened ``(E * A, d)`` rows — the reference's
(threads x agents) layout (``rMAPPOPolicy.py`` call sites in
``base_runner.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.actor_critic import ActorCriticPolicy


class ACTrajectory(NamedTuple):
    """Time-major rollout chunk ``(T, E, A, d)`` (+ hidden states)."""

    share_obs: jax.Array
    obs: jax.Array
    available_actions: jax.Array
    actions: jax.Array
    log_probs: jax.Array
    values: jax.Array
    rewards: jax.Array
    masks: jax.Array             # (T+1, E, A, 1)
    active_masks: jax.Array      # (T+1, E, A, 1)
    actor_h: jax.Array           # (T, E, A, N, h) hidden entering each step
    critic_h: jax.Array
    dones: jax.Array             # (T, E)
    delays: Optional[jax.Array] = None    # (T, E) DCML per-step info, else None
    payments: Optional[jax.Array] = None
    # On-device episode accounting over this chunk (see rollout.Trajectory):
    # n_done, done_reward_sum, step_reward_mean always; done_delay_sum /
    # done_payment_sum only for envs whose TimeStep carries the info channels.
    chunk_stats: Optional[dict] = None
    # Raw truncated-IS ratios (T, E, A, 1) from the async off-policy
    # correction (training/off_policy.py); None outside stale async blocks.
    is_weights: Optional[jax.Array] = None


class ACRolloutState(NamedTuple):
    env_states: NamedTuple
    obs: jax.Array
    share_obs: jax.Array
    available_actions: jax.Array
    mask: jax.Array              # (E, A, 1)
    actor_h: jax.Array           # (E, A, N, h)
    critic_h: jax.Array
    rng: jax.Array
    # per-env running (reward, delay, payment) episode sums carried across
    # chunks (rollout.RolloutState.episode_acc); zeros stand in for the info
    # channels on envs without them
    episode_acc: Optional[jax.Array] = None             # (E, 3)


def _rows(x: jax.Array) -> jax.Array:
    """(E, A, ...) -> (E*A, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def _unrows(x: jax.Array, E: int, A: int) -> jax.Array:
    return x.reshape(E, A, *x.shape[1:])


class ACRolloutCollector:
    # explicit fused-dispatch eligibility (base_runner gates on this;
    # host-driven collectors declare False, host_rollout.py:45)
    jittable = True

    def __init__(self, env, policy: ActorCriticPolicy, episode_length: int,
                 use_local_value: bool = False):
        """``use_local_value=True`` feeds the critic local obs instead of the
        shared state — the IPPO decentralized-V configuration
        (``ippo_policy.py:13-29``)."""
        self.env = env
        self.policy = policy
        self.T = episode_length
        self.use_local_value = use_local_value

    def _cent(self, st: ACRolloutState) -> jax.Array:
        return st.obs if self.use_local_value else st.share_obs

    def apply(self, params, key, st: ACRolloutState, deterministic: bool = False):
        """Public policy application for eval loops and external drivers:
        actions + values + next hidden states at the (E, A, ...) level.
        Subclass dispatch (IPPO/HAPPO per-agent stacking) happens in
        ``_apply``, so callers never reach into collector internals."""
        return self._apply(params, key, st, deterministic)

    def _apply(self, params, key, st: ACRolloutState, deterministic: bool = False):
        """One policy application at the (E, A, ...) level.  The base class
        flattens to (E*A) rows for shared params; stacked-per-agent collectors
        (IPPO/HAPPO) override this with a vmap over the agent axis."""
        E, A = st.obs.shape[:2]
        out = self.policy.get_actions(
            params, key, _rows(self._cent(st)), _rows(st.obs),
            _rows(st.actor_h), _rows(st.critic_h), _rows(st.mask),
            _rows(st.available_actions), deterministic,
        )
        return jax.tree.map(lambda x: _unrows(x, E, A), out)

    def init_state(self, key: jax.Array, n_envs: int) -> ACRolloutState:
        key, k_reset = jax.random.split(key)
        keys = jax.random.split(k_reset, n_envs)
        env_states, ts = jax.vmap(self.env.reset)(keys, jnp.zeros(n_envs, jnp.int32))
        E, A = ts.obs.shape[0], ts.obs.shape[1]
        ah, ch = self.policy.init_hidden(E * A)
        return ACRolloutState(
            env_states=env_states,
            obs=ts.obs,
            share_obs=ts.share_obs,
            available_actions=ts.available_actions,
            mask=jnp.ones((E, A, 1), jnp.float32),
            actor_h=_unrows(ah, E, A),
            critic_h=_unrows(ch, E, A),
            rng=key,
            episode_acc=jnp.zeros((E, 3), jnp.float32),
        )

    def collect(self, params, rollout_state: ACRolloutState) -> Tuple[ACRolloutState, ACTrajectory]:
        E, A = rollout_state.obs.shape[:2]

        def body(st: ACRolloutState, _):
            key, k_act = jax.random.split(st.rng)
            out = self._apply(params, k_act, st)
            env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
            done_env = ts.done.all(axis=1)
            # strongly-typed float32 (see rollout.py): weak-typed masks in the
            # scan carry force one steady-state recompile per run
            next_mask = jnp.broadcast_to(
                jnp.where(done_env[:, None, None], jnp.float32(0.0), jnp.float32(1.0)),
                st.mask.shape,
            )
            has_info = hasattr(ts, "delay")   # DCML info channels (env TimeStep)
            # on-device episode accounting (rollout.py): accumulate per-env
            # sums, flush finished episodes' totals into the chunk aggregates
            step_vals = jnp.stack([
                ts.reward.sum(-1).mean(-1),
                ts.delay if has_info else jnp.zeros_like(done_env, jnp.float32),
                ts.payment if has_info else jnp.zeros_like(done_env, jnp.float32),
            ], axis=-1)                                          # (E, 3)
            acc = st.episode_acc + step_vals
            flushed = jnp.where(done_env[:, None], acc, 0.0).sum(axis=0)   # (3,)
            n_done = done_env.sum().astype(jnp.float32)
            acc = jnp.where(done_env[:, None], 0.0, acc)

            transition = dict(
                share_obs=self._cent(st),
                obs=st.obs,
                available_actions=st.available_actions,
                actions=out.action,
                log_probs=out.log_prob,
                values=out.value,
                rewards=ts.reward,
                next_mask=next_mask,
                actor_h=st.actor_h,
                critic_h=st.critic_h,
                done=done_env,
                _flushed=flushed,
                _n_done=n_done,
            )
            if has_info:
                transition["delay"] = ts.delay
                transition["payment"] = ts.payment
            # Hidden states reset via the mask multiply inside the GRU on the
            # *next* step (rnn.py:27-28); store post-step states as-is.
            new_st = ACRolloutState(
                env_states=env_states,
                obs=ts.obs,
                share_obs=ts.share_obs,
                available_actions=ts.available_actions,
                mask=next_mask,
                actor_h=out.actor_h,
                critic_h=out.critic_h,
                rng=key,
                episode_acc=acc,
            )
            return new_st, transition

        if rollout_state.episode_acc is None:      # hand-built legacy state
            rollout_state = rollout_state._replace(
                episode_acc=jnp.zeros((E, 3), jnp.float32)
            )
        final_state, tr = jax.lax.scan(body, rollout_state, None, length=self.T)

        flushed = tr.pop("_flushed").sum(axis=0)            # (3,)
        n_done = tr.pop("_n_done").sum()
        chunk_stats = {
            "n_done": n_done,
            "done_reward_sum": flushed[0],
            "step_reward_mean": tr["rewards"].sum(-1).mean(),
        }
        if "delay" in tr:
            chunk_stats["done_delay_sum"] = flushed[1]
            chunk_stats["done_payment_sum"] = flushed[2]
        if tr["rewards"].shape[-1] > 1:            # per-objective channel means
            for i in range(tr["rewards"].shape[-1]):
                chunk_stats[f"step_objective_{i}_mean"] = tr["rewards"][..., i].mean()

        masks = jnp.concatenate([rollout_state.mask[None], tr["next_mask"]], axis=0)
        active = jnp.ones_like(masks)
        traj = ACTrajectory(
            share_obs=tr["share_obs"],
            obs=tr["obs"],
            available_actions=tr["available_actions"],
            actions=tr["actions"],
            log_probs=tr["log_probs"],
            values=tr["values"],
            rewards=tr["rewards"],
            masks=masks,
            active_masks=active,
            actor_h=tr["actor_h"],
            critic_h=tr["critic_h"],
            dones=tr["done"],
            delays=tr.get("delay"),
            payments=tr.get("payment"),
            chunk_stats=chunk_stats,
        )
        return final_state, traj
