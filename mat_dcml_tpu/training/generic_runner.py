"""Generic rollout-train runner for TimeStep-protocol envs (MPE, toy, ...).

The JAX analogue of the reference's per-benchmark runners
(``mpe_runner.py:20-130``, ``base_runner.py:17-265`` algorithm dispatch):
policy/trainer/collector construction for discrete-action envs; the
collect/train loop, checkpoint restore/resume, and metric accounting live in
:class:`~mat_dcml_tpu.training.base_runner.BaseRunner`.  Algorithm dispatch
covers the full MAT family — vanilla MAT, MAT-Dec (``dec_actor``), and the
encoder/decoder/GRU ablations (``mat_encoder.py``, ``mat_decoder.py``,
``mat_gru.py``) — plus the MLP actor-critic family (MAPPO / rMAPPO / IPPO).
"""

from __future__ import annotations

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.spaces import Box, Discrete, MultiDiscrete
from mat_dcml_tpu.models.actor_critic import ACConfig, ActorCriticPolicy
from mat_dcml_tpu.models.mat import CONTINUOUS, DISCRETE, MATConfig
from mat_dcml_tpu.models.mat_variants import DecoderPolicy, EncoderPolicy, GRUPolicy
from mat_dcml_tpu.models.policy import TransformerPolicy
from mat_dcml_tpu.training.ac_rollout import ACRolloutCollector
from mat_dcml_tpu.training.base_runner import BaseRunner, ac_config_kwargs, apply_mesh
from mat_dcml_tpu.training.ippo import IPPORolloutCollector, IPPOTrainer
from mat_dcml_tpu.training.mappo import MAPPOConfig, MAPPOTrainer
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig
from mat_dcml_tpu.training.rollout import RolloutCollector

MAT_FAMILY = ("mat", "mat_dec", "mat_encoder", "mat_decoder", "mat_gru")
AC_FAMILY = ("mappo", "rmappo", "ippo", "happo", "hatrpo", "rhappo", "rhatrpo")
SUPPORTED_ALGOS = MAT_FAMILY + AC_FAMILY


def _env_space(env):
    """Envs declare a continuous space via ``env.action_space = Box(dim)``
    (multi-agent MuJoCo) or a factored one via ``MultiDiscrete(nvec)`` (MPE
    move+comm scenarios); everything else is Discrete(action_dim)."""
    space = getattr(env, "action_space", None)
    return space if isinstance(space, (Box, MultiDiscrete)) else Discrete(env.action_dim)


def build_discrete_policy(run: RunConfig, env):
    """Algorithm -> policy for a discrete- or continuous-action TimeStep env
    (``transformer_policy.py:28-39`` action-type inference + ``:66-79``
    model-class dispatch)."""
    space = _env_space(env)
    if isinstance(space, MultiDiscrete):
        # faithful scope: the reference's transformer act machinery has no
        # MultiDiscrete family either (transformer_act.py's four families);
        # use the actor-critic algorithms for move+comm scenarios
        raise NotImplementedError(
            "MAT family has no MultiDiscrete act path (use mappo/rmappo/ippo)"
        )
    continuous = isinstance(space, Box)
    cfg = MATConfig(
        n_agent=env.n_agents,
        obs_dim=env.obs_dim,
        state_dim=env.share_obs_dim,
        action_dim=env.action_dim,
        n_block=run.n_block,
        n_embd=run.n_embd,
        n_head=run.n_head,
        dtype=run.model_dtype,
        remat=run.remat,
        action_type=CONTINUOUS if continuous else DISCRETE,
        encode_state=run.encode_state,
        dec_actor=run.dec_actor or run.algorithm_name == "mat_dec",
        share_actor=run.share_actor or run.algorithm_name == "mat_dec",
        n_objective=run.n_objective,
    )
    if run.algorithm_name in ("mat", "mat_dec"):
        return TransformerPolicy(cfg)
    if run.algorithm_name == "mat_encoder":
        return EncoderPolicy(cfg)
    if run.algorithm_name == "mat_decoder":
        return DecoderPolicy(cfg)
    if run.algorithm_name == "mat_gru":
        return GRUPolicy(cfg)
    raise NotImplementedError(
        f"algorithm_name={run.algorithm_name!r}; MAT family: {MAT_FAMILY}"
    )


class GenericRunner(BaseRunner):
    """Collect/train loop with episode-reward accounting for any TimeStep env."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, env, log_fn=print):
        if run.algorithm_name not in SUPPORTED_ALGOS:
            raise NotImplementedError(
                f"algorithm_name={run.algorithm_name!r}; supported: {SUPPORTED_ALGOS}"
            )
        self.env = env
        self.is_mat = run.algorithm_name in MAT_FAMILY

        if self.is_mat:
            self.policy = build_discrete_policy(run, env)
            self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
            self.collector = RolloutCollector(env, self.policy, run.episode_length)
        else:
            use_rec = run.algorithm_name in ("rmappo", "rhappo", "rhatrpo")
            ac = ACConfig(
                hidden_size=run.n_embd,
                use_recurrent_policy=use_rec,
            )
            self.policy = ActorCriticPolicy(
                ac,
                obs_dim=env.obs_dim,
                cent_obs_dim=env.obs_dim if run.algorithm_name == "ippo" else env.share_obs_dim,
                space=_env_space(env),
            )
            mcfg = MAPPOConfig(
                use_recurrent_policy=use_rec,
                **ac_config_kwargs(ppo),
            )
            if run.algorithm_name == "ippo":
                self.trainer = IPPOTrainer(self.policy, mcfg, n_agents=env.n_agents)
                self.collector = IPPORolloutCollector(
                    env, self.policy, run.episode_length, use_local_value=True
                )
            elif run.algorithm_name in ("happo", "hatrpo", "rhappo", "rhatrpo"):
                from mat_dcml_tpu.training.happo import (
                    HAPPOConfig,
                    HAPPORolloutCollector,
                    HAPPOTrainer,
                    HATRPOTrainer,
                )

                hcfg = HAPPOConfig(use_recurrent_policy=use_rec,
                                   **ac_config_kwargs(ppo))
                cls = (HATRPOTrainer if run.algorithm_name.endswith("hatrpo")
                       else HAPPOTrainer)
                self.trainer = cls(self.policy, hcfg, n_agents=env.n_agents)
                self.collector = HAPPORolloutCollector(env, self.policy, run.episode_length)
            else:
                self.trainer = MAPPOTrainer(self.policy, mcfg)
                self.collector = ACRolloutCollector(env, self.policy, run.episode_length)

        self.mesh = apply_mesh(run, self.policy)
        self.finalize(run, log_fn)

    # ----------------------------------------------------------------- eval

    def evaluate(self, train_state, n_steps: int = 100, seed: int = 0):
        """Deterministic-policy mean step reward on fresh envs — the generic
        in-loop eval every reference runner carries (``base_runner``/
        ``mpe_runner`` eval loops)."""
        import jax
        import numpy as np

        E = self.run_cfg.n_rollout_threads
        rs = self.collector.init_state(jax.random.key(seed + 29), E)

        if self.is_mat:
            @jax.jit
            def eval_step(params, st):
                out = self.policy.get_actions(
                    params, jax.random.key(0), st.share_obs, st.obs,
                    st.available_actions, deterministic=True,
                )
                env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
                new_st = st._replace(
                    env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                    available_actions=ts.available_actions,
                )
                return new_st, ts.reward.mean()
        else:
            @jax.jit
            def eval_step(params, st):
                out = self.collector.apply(params, jax.random.key(0), st, deterministic=True)
                env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
                new_st = st._replace(
                    env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                    available_actions=ts.available_actions,
                    actor_h=out.actor_h, critic_h=out.critic_h,
                )
                return new_st, ts.reward.mean()

        rewards = []
        for _ in range(n_steps):
            rs, r = eval_step(train_state.params, rs)
            rewards.append(float(r))
        return {"eval_average_step_rewards": float(np.mean(rewards))}
